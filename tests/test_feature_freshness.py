"""The train-to-serve freshness loop, live (ISSUE 18 acceptance).

An unbounded hashed click stream trains a :class:`StreamingHashedFMTrainer`
whose row deltas reach a 2-replica pool through the registry:

- **delta-only hot path** — after the base version, every publish is a
  :class:`ModelDelta` and every replica swap is an in-place row patch
  (``delta_swaps``); ``full_loads`` stays at exactly the one start-up
  install per replica.
- **bounded staleness, deterministically** — watermarks are batch
  counts, so every lag assertion is an exact integer; no wall-clock
  sleeps anywhere in the accounting tests.
- **chaos** — a ReplicaDown mid-patch loses zero requests (failover),
  and the revived replica converges to the current version.
- **bitwise parity** — predictions served off the delta chain equal a
  full-snapshot publish of the same trainer state, bit for bit.
"""

import threading
import time

import numpy as np

import flinkml_tpu.faults as faults
from flinkml_tpu.features import (
    DeltaPublisher,
    StreamingHashedFMTrainer,
    hash_buckets,
)
from flinkml_tpu.serving.engine import ServingConfig
from flinkml_tpu.serving.pool import ReplicaPool
from flinkml_tpu.serving.registry import ModelRegistry
from flinkml_tpu.table import Table
from flinkml_tpu.utils.metrics import metrics

_B, _L = 128, 3          # hash space / ids per row
_SEED = 11


def _stream(rng, n=16):
    """One synthetic click batch: raw int keys → hashed id rows."""
    keys = rng.integers(0, 5000, size=(n, _L))
    ids = hash_buckets(keys.reshape(-1), seed=_SEED,
                       num_buckets=_B).reshape(n, _L)
    labels = (keys.sum(axis=1) % 2).astype(np.float32)
    return ids, labels


def _loop(tmp_path, name, n_replicas=2, every_n=1, max_depth=32):
    rng = np.random.default_rng(3)
    trainer = StreamingHashedFMTrainer(
        num_buckets=_B, factor_size=4, hash_seed=_SEED, learning_rate=0.1)
    registry = ModelRegistry(str(tmp_path / "reg"))
    publisher = DeltaPublisher(registry, trainer, every_n_batches=every_n,
                               max_depth=max_depth)
    ids, labels = _stream(rng)
    trainer.fit_batch(ids, labels)
    publisher.publish_now()              # the base snapshot
    example = Table({"hashed_ids": np.zeros((2, _L), np.int32)})
    pool = ReplicaPool(
        registry, example, config=ServingConfig(max_batch_rows=64,
                                                max_wait_ms=1.0),
        n_replicas=n_replicas, name=name,
    ).start().follow_registry()
    return rng, trainer, registry, publisher, pool


def test_live_freshness_scenario_delta_only_hot_path(tmp_path):
    rng, tr, reg, pub, pool = _loop(tmp_path, "fresh_pool")
    try:
        # serving.registry is one process-global metrics group — count
        # from here (base snapshot already published) so the assertions
        # hold in any suite order.
        reg_base = dict(reg._metrics.snapshot()["counters"])
        n_publishes = 8
        for _ in range(n_publishes):
            ids, labels = _stream(rng)
            tr.fit_batch(ids, labels)
            assert pub.maybe_publish() is not None
        full = tr.make_model()           # the same state, as a snapshot
        current = reg.current_version()

        # Every replica rolled to current through row patches alone.
        assert pool.versions() == {"r0": current, "r1": current}
        for r in pool.replicas:
            counters = r.engine._metrics.snapshot()["counters"]
            assert counters["full_loads"] == 1, (r.name, counters)
            assert counters["delta_swaps"] == n_publishes, (r.name, counters)
        reg_counters = reg._metrics.snapshot()["counters"]
        assert (reg_counters.get("delta_publishes", 0)
                - reg_base.get("delta_publishes", 0)) == n_publishes
        # Zero full republishes after the base version.
        assert (reg_counters.get("full_publishes", 0)
                - reg_base.get("full_publishes", 0)) == 0

        # Freshness: fully caught up, exactly.
        assert pool.freshness_lag(tr.watermark) == 0

        # Bitwise parity: pool predictions (served off the patched
        # clones) == the full snapshot's transform of the same state.
        ids, _ = _stream(rng, n=8)
        resp = pool.predict({"hashed_ids": ids})
        assert resp.version == current
        (want,) = full.transform(Table({"hashed_ids": ids}))
        np.testing.assert_array_equal(
            resp.column("prediction"),
            np.asarray(want.column("prediction")))
        np.testing.assert_array_equal(
            resp.column("rawPrediction"),
            np.asarray(want.column("rawPrediction")))
    finally:
        pool.stop()


def test_staleness_accounting_is_deterministic(tmp_path):
    """The lag gauge is exact integer batch math — pinned without a
    single sleep. Bound contract: with publish cadence ``every_n`` and a
    synchronous roll, lag right after ``maybe_publish`` is always 0 and
    never exceeds ``every_n - 1`` between publishes."""
    every_n = 3
    rng, tr, reg, pub, pool = _loop(tmp_path, "stale_pool",
                                    every_n=every_n)
    try:
        for step in range(1, 8):
            ids, labels = _stream(rng)
            tr.fit_batch(ids, labels)
            published = pub.maybe_publish()
            lag = pool.freshness_lag(tr.watermark)
            if published is not None:
                assert lag == 0, step
            else:
                assert 0 < lag <= every_n - 1, (step, lag)
        snap = metrics.group("serving.stale_pool.freshness").snapshot()
        assert snap["gauges"]["lag_batches"] == lag
        assert snap["gauges"]["latest_watermark"] == tr.watermark
        # The registry-side edge (no live trainer handle) is the newest
        # stamped publish.
        assert pool.freshness_lag() == 0
    finally:
        pool.stop()


def test_chaos_kill_mid_patch_loses_zero_requests(tmp_path):
    """A replica dies while deltas roll across the pool: every client
    request still succeeds (failover), the survivor keeps taking row
    patches, and the revived replica converges to the current version."""
    rng, tr, reg, pub, pool = _loop(tmp_path, "chaos_fresh")
    errors, served = [], [0]
    stop = threading.Event()

    def client(tid):
        crng = np.random.default_rng(100 + tid)
        try:
            while not stop.is_set():
                n = int(crng.integers(1, 6))
                keys = crng.integers(0, 5000, size=(n, _L))
                ids = hash_buckets(keys.reshape(-1), seed=_SEED,
                                   num_buckets=_B).reshape(n, _L)
                resp = pool.predict({"hashed_ids": ids})
                assert resp.columns["prediction"].shape == (n,)
                served[0] += 1
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    try:
        with faults.armed(faults.FaultPlan(
                faults.ReplicaDown("r0", at_batch=2))) as plan:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            # Keep publishing deltas while traffic flows and r0 dies.
            for _ in range(6):
                ids, labels = _stream(rng)
                tr.fit_batch(ids, labels)
                pub.maybe_publish()
            # Drive requests until the kill has landed, then stop.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (pool.stats()["per_replica"]["r0"]["state"]
                        == "unhealthy"):
                    break
                time.sleep(0.05)
            served_at_kill = served[0]
            time.sleep(0.3)  # pool must keep serving after the kill
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        assert not errors, errors[:3]
        assert served[0] > served_at_kill, "pool stopped serving after kill"
        st = pool.stats()
        assert st["per_replica"]["r0"]["state"] == "unhealthy"
        assert any(site == "serving.replica" for site, _, _ in plan.log)
        current = reg.current_version()
        assert pool.versions()["r1"] == current

        # More deltas while degraded: the survivor keeps patching.
        ids, labels = _stream(rng)
        tr.fit_batch(ids, labels)
        pub.maybe_publish()
        current = reg.current_version()
        assert pool.versions()["r1"] == current

        # The revived replica converges to the current version.
        pool.revive("r0")
        assert pool.versions() == {"r0": current, "r1": current}
        assert pool.freshness_lag(tr.watermark) == 0
    finally:
        stop.set()
        pool.stop()


def test_engine_falls_back_to_full_load_off_chain(tmp_path):
    """A replica that cannot be reached by the delta chain (its active
    version was compacted over) falls back to a verified full load —
    correctness never depends on the fast path being available."""
    rng, tr, reg, pub, pool = _loop(tmp_path, "fallback_pool",
                                    n_replicas=1, max_depth=2)
    try:
        # depth cap 2: publishes go d1, d2, FULL, ... — the full
        # snapshot at depth cap breaks the patch chain on purpose.
        for _ in range(3):
            ids, labels = _stream(rng)
            tr.fit_batch(ids, labels)
            pub.publish_now()
        (replica,) = pool.replicas
        counters = replica.engine._metrics.snapshot()["counters"]
        assert counters["delta_swaps"] == 2
        assert counters["full_loads"] == 2  # start + the compacted swap
        assert replica.engine.active_version == reg.current_version()
    finally:
        pool.stop()
