"""Tight convergence parity: pin the optimizer to the true optimum.

The reference asserts trained coefficients against a known fixture
(``flink-ml-lib/src/test/java/.../LogisticRegressionTest.java:91-94,253``:
``expectedCoefficient = [0.528, -0.286, -0.429, -0.572]`` at tolerance
0.1 on the weighted 10-row dataset). These tests reproduce that fixture
check exactly, and go further: a full-batch GD configuration (global
batch ≥ n makes the SGD window the whole dataset, so the trajectory is
deterministic GD on the exact objective) is pinned against sklearn's
optimum to ≤1e-4, for both the unregularized and L2 objectives.

Objective mapping (``_linear_sgd.make_dense_step``): the update is
``coef -= lr/weightSum · (Σᵢ wᵢ ∂lossᵢ + 2·reg·coef)``, whose fixed
point minimizes ``Σᵢ wᵢ·log(1+exp(-ysᵢ·xᵢ·β)) + reg·‖β‖²``. sklearn's
``LogisticRegression(C, fit_intercept=False)`` minimizes
``C·Σᵢ wᵢ·loss + ½‖β‖²``, so ``C = 1/(2·reg)``.
"""

import numpy as np
import pytest

from flinkml_tpu.models import LogisticRegression
from flinkml_tpu.table import Table

from .test_logistic_regression import reference_train_table

REFERENCE_COEF = np.array([0.528, -0.286, -0.429, -0.572])


def _full_batch_lr(n, **overrides):
    """Full-batch deterministic GD: batch covers the dataset, tol=0."""
    lr = (
        LogisticRegression()
        .set_seed(0)
        .set_tol(0.0)
        .set_global_batch_size(max(n, 32))
    )
    for name, value in overrides.items():
        getattr(lr, f"set_{name}")(value)
    return lr


def test_reference_fixture_coefficients():
    """Exact reference parity: same data, same config, same fixture.

    The reference's dataset is linearly separable, so the coefficients
    grow without bound as epochs increase — the fixture is where its
    default config (maxIter=20, learningRate=0.1) stops. Full-batch GD
    with the same epoch count and step rule reproduces it: the
    reference's per-epoch update is ``coef -= lr/weightSumₛ · gradₛ``
    over a sampled batch whose expectation is the full weighted
    gradient, and at batch ≥ n the two coincide. Our 20-epoch point is
    [0.5258, -0.284, -0.4259, -0.5679] — inside 3e-3 of the fixture,
    far inside the reference's own 0.1 assertion tolerance.
    """
    table = reference_train_table()
    model = (
        _full_batch_lr(10, max_iter=20, learning_rate=0.1)
        .set_weight_col("weight")
        .fit(table)
    )
    np.testing.assert_allclose(model.coefficient, REFERENCE_COEF, atol=0.1)
    np.testing.assert_allclose(model.coefficient, REFERENCE_COEF, atol=5e-3)


def test_degenerate_margins_match_sklearn():
    """Constant features (like the reference fixture's 2/3/4 columns)
    make the minimizing β non-unique, but the margins X·β at the optimum
    are unique — compare ours against sklearn's on a non-separable
    variant of the reference's dataset shape."""
    from sklearn.linear_model import LogisticRegression as SkLR

    rng = np.random.default_rng(11)
    n = 80
    x0 = rng.normal(size=n)
    # Overlapping classes → finite optimum; constant cols 2,3,4 → rank-2 X.
    y = (x0 + rng.normal(scale=1.5, size=n) > 0).astype(np.float64)
    x = np.column_stack([x0, np.full(n, 2.0), np.full(n, 3.0), np.full(n, 4.0)])
    # The constant columns dominate the curvature (row norm² ≈ 29, mean
    # Hessian eigenvalue ≈ 29/4), so GD stability needs lr < 2/7.25.
    model = _full_batch_lr(n, max_iter=40_000, learning_rate=0.2).fit(
        Table({"features": x, "label": y})
    )
    sk = SkLR(
        C=np.inf, fit_intercept=False, tol=1e-12, max_iter=50_000
    ).fit(x, y)
    np.testing.assert_allclose(
        x @ model.coefficient, x @ sk.coef_[0], atol=1e-3
    )


def _noisy_logistic_data(rng, n, d):
    """Non-separable, non-degenerate data: finite, unique optimum."""
    x = rng.normal(size=(n, d))
    beta = rng.normal(size=d)
    p = 1.0 / (1.0 + np.exp(-(x @ beta)))
    y = (rng.uniform(size=n) < p).astype(np.float64)
    return x, y


def test_full_batch_gd_matches_sklearn_optimum(rng):
    from sklearn.linear_model import LogisticRegression as SkLR

    n, d = 256, 5
    x, y = _noisy_logistic_data(rng, n, d)
    model = _full_batch_lr(
        n, max_iter=20_000, learning_rate=2.0
    ).fit(Table({"features": x, "label": y}))
    sk = SkLR(
        C=np.inf, fit_intercept=False, tol=1e-12, max_iter=50_000
    ).fit(x, y)
    np.testing.assert_allclose(model.coefficient, sk.coef_[0], atol=1e-4)


def test_full_batch_gd_matches_sklearn_l2_optimum(rng):
    from sklearn.linear_model import LogisticRegression as SkLR

    n, d = 256, 5
    x, y = _noisy_logistic_data(rng, n, d)
    reg = 0.05
    model = _full_batch_lr(
        n, max_iter=20_000, learning_rate=2.0, reg=reg
    ).fit(Table({"features": x, "label": y}))
    # C = 1/(2·reg): see the objective mapping in the module docstring.
    sk = SkLR(
        C=1.0 / (2.0 * reg), fit_intercept=False, tol=1e-12, max_iter=50_000
    ).fit(x, y)
    np.testing.assert_allclose(model.coefficient, sk.coef_[0], atol=1e-4)


def test_bf16_training_accumulates_in_f32(rng):
    """bf16-resident training must reduce loss/weight sums in f32: a
    stepwise bf16 sum of 4096 unit weights saturates at 256, which would
    scale step_size 16x too large and diverge. Regression for the
    _acc_dt fix."""
    import jax.numpy as jnp

    from flinkml_tpu.models._linear_sgd import train_linear_model
    from flinkml_tpu.parallel import DeviceMesh

    n, d = 4096, 8
    x, y = _noisy_logistic_data(rng, n, d)
    hyper = dict(
        loss="logistic", mesh=DeviceMesh(), max_iter=150,
        learning_rate=1.0, global_batch_size=n,
        reg=0.0, elastic_net=0.0, tol=0.0, seed=0,
    )
    coef16 = train_linear_model(
        x, y, np.ones(n), dtype=jnp.bfloat16, **hyper
    ).astype(np.float64)
    coef32 = train_linear_model(
        x, y, np.ones(n), dtype=np.float32, **hyper
    ).astype(np.float64)
    assert np.isfinite(coef16).all()
    acc16 = np.mean((x @ coef16 > 0) == (y > 0.5))
    acc32 = np.mean((x @ coef32 > 0) == (y > 0.5))
    # A saturated wsum scales step_size 16x and diverges; with the f32
    # accumulators the bf16 run tracks the f32 one.
    assert acc16 > acc32 - 0.05, (acc16, acc32)
    cos = coef16 @ coef32 / (np.linalg.norm(coef16) * np.linalg.norm(coef32))
    assert cos > 0.98, cos


def test_full_batch_is_deterministic_across_seeds():
    """With the batch window covering the dataset the sampling seed is
    irrelevant — the trajectory is plain GD."""
    rng = np.random.default_rng(17)
    x, y = _noisy_logistic_data(rng, 64, 3)
    t = Table({"features": x, "label": y})
    c1 = _full_batch_lr(64, max_iter=200, learning_rate=1.0, seed=1).fit(t)
    c2 = _full_batch_lr(64, max_iter=200, learning_rate=1.0, seed=99).fit(t)
    # The seed still permutes rows across device shards, so per-device
    # partial sums accumulate in a different order — identical up to
    # float rounding, not bit-identical.
    np.testing.assert_allclose(c1.coefficient, c2.coefficient, atol=1e-9)
