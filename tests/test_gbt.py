"""GBTClassifier / GBTRegressor: quality vs sklearn, semantics,
persistence, determinism."""

import numpy as np
import pytest
from sklearn.ensemble import HistGradientBoostingClassifier
from sklearn.metrics import r2_score, roc_auc_score

from flinkml_tpu.models import (
    GBTClassifier,
    GBTClassifierModel,
    GBTRegressor,
    GBTRegressorModel,
)
from flinkml_tpu.table import Table


def _nonlinear_classification(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-2, 2, size=(n, 6))
    # XOR-ish + interaction: linear models can't fit this.
    logits = 3 * (x[:, 0] * x[:, 1] > 0) - 1.5 + 0.8 * np.sin(3 * x[:, 2])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return x, y


def _nonlinear_regression(n=2000, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-2, 2, size=(n, 5))
    y = (
        np.where(x[:, 0] > 0, 3.0, -1.0) + x[:, 1] ** 2
        + 0.5 * x[:, 2] * x[:, 3] + 0.1 * rng.normal(size=n)
    )
    return x, y


def _clf(**kw):
    c = (
        GBTClassifier().set_num_trees(40).set_max_depth(4)
        .set_learning_rate(0.2).set_seed(0)
    )
    for name, v in kw.items():
        getattr(c, f"set_{name}")(v)
    return c


def test_classifier_beats_linear_on_nonlinear_data():
    x, y = _nonlinear_classification()
    t = Table({"features": x, "label": y})
    model = _clf().fit(t)
    (out,) = model.transform(t)
    auc = roc_auc_score(y, out["rawPrediction"][:, 1])
    ref = HistGradientBoostingClassifier(
        max_iter=40, max_depth=4, learning_rate=0.2
    ).fit(x, y)
    ref_auc = roc_auc_score(y, ref.predict_proba(x)[:, 1])
    assert auc > 0.92, auc
    assert auc > ref_auc - 0.03, (auc, ref_auc)   # within 3pts of sklearn
    # Labels are sampled through a sigmoid: Bayes accuracy on this
    # task is ~0.79-0.83 depending on the seed; in-sample boosting
    # should land above it.
    acc = (out["prediction"] == y).mean()
    assert acc > 0.82, acc


def test_classifier_holdout_generalizes():
    x, y = _nonlinear_classification(seed=2)
    t = Table({"features": x[:1500], "label": y[:1500]})
    model = _clf().fit(t)
    (out,) = model.transform(Table({"features": x[1500:]}))
    margin = out["rawPrediction"][:, 1]
    auc = roc_auc_score(y[1500:], margin)
    ref = HistGradientBoostingClassifier(
        max_iter=40, max_depth=4, learning_rate=0.2
    ).fit(x[:1500], y[:1500])
    ref_auc = roc_auc_score(y[1500:], ref.predict_proba(x[1500:])[:, 1])
    # Label noise caps holdout AUC near 0.81 on this task; require
    # parity with sklearn's histogram GBT rather than an absolute bar.
    assert auc > ref_auc - 0.02, (auc, ref_auc)
    assert auc > 0.78, auc


def test_regressor_fits_nonlinear_function():
    x, y = _nonlinear_regression()
    t = Table({"features": x, "label": y})
    model = (
        GBTRegressor().set_num_trees(60).set_max_depth(4)
        .set_learning_rate(0.2).set_seed(0).fit(t)
    )
    (out,) = model.transform(t)
    assert r2_score(y, out["prediction"]) > 0.93


def test_weighted_rows_shift_the_model():
    rng = np.random.default_rng(3)
    x = rng.uniform(-1, 1, size=(500, 2))
    y = (x[:, 0] > 0).astype(np.float64)
    w = np.where(y == 1, 100.0, 0.01)   # positives dominate
    t = Table({"features": x, "label": y, "w": w})
    model = _clf(num_trees=10, weight_col="w").fit(t)
    (out,) = model.transform(t)
    # With overwhelming positive weight, nearly everything predicts 1.
    assert out["prediction"].mean() > 0.9


def test_deterministic_and_subsample_varies():
    x, y = _nonlinear_classification(n=600, seed=4)
    t = Table({"features": x, "label": y})
    m1 = _clf(num_trees=5).fit(t)
    m2 = _clf(num_trees=5).fit(t)
    np.testing.assert_array_equal(m1._leaves, m2._leaves)
    m3 = _clf(num_trees=5, subsample=0.5).fit(t)
    assert not np.array_equal(m3._leaves, m1._leaves)
    (out,) = m3.transform(t)
    # 5 trees at 50% subsample on a noisy 600-row task: well above
    # chance is all that is guaranteed.
    assert (out["prediction"] == y).mean() > 0.6


def test_save_load_and_model_data(tmp_path):
    x, y = _nonlinear_classification(n=500, seed=5)
    t = Table({"features": x, "label": y})
    model = _clf(num_trees=8).fit(t)
    model.save(str(tmp_path / "gbt"))
    loaded = GBTClassifierModel.load(str(tmp_path / "gbt"))
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_allclose(p2["rawPrediction"], p1["rawPrediction"])
    clone = GBTClassifierModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    (p3,) = clone.transform(t)
    np.testing.assert_allclose(p3["prediction"], p1["prediction"])


def test_regressor_save_load(tmp_path):
    x, y = _nonlinear_regression(n=400, seed=6)
    t = Table({"features": x, "label": y})
    model = (
        GBTRegressor().set_num_trees(10).set_max_depth(3).set_seed(1).fit(t)
    )
    model.save(str(tmp_path / "gbtr"))
    loaded = GBTRegressorModel.load(str(tmp_path / "gbtr"))
    np.testing.assert_allclose(
        loaded.transform(t)[0]["prediction"],
        model.transform(t)[0]["prediction"],
    )


def test_classifier_rejects_nonbinary_labels():
    t = Table({"features": np.zeros((4, 2)),
               "label": np.asarray([0.0, 1.0, 2.0, 1.0])})
    with pytest.raises(ValueError, match="0, 1"):
        _clf().fit(t)


def test_depth1_is_a_stump_ensemble():
    rng = np.random.default_rng(7)
    x = rng.uniform(-1, 1, size=(800, 3))
    y = (x[:, 1] > 0.3).astype(np.float64)
    t = Table({"features": x, "label": y})
    model = _clf(max_depth=1, num_trees=20).fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.97
    # Stumps overwhelmingly split on the informative feature.
    assert (model._feats[:, 0] == 1).mean() > 0.8


def test_reg_lambda_zero_still_learns():
    # lambda=0 used to produce NaN gains on empty histogram cells, which
    # argmax treated as maximal — silently training a useless forest.
    x, y = _nonlinear_classification(n=800, seed=8)
    t = Table({"features": x, "label": y})
    model = _clf(num_trees=20, reg_lambda=0.0).fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.8
    assert np.isfinite(model._leaves).all()


def test_feature_importances_rank_informative_features():
    rng = np.random.default_rng(9)
    x = rng.uniform(-1, 1, size=(800, 5))
    y = ((x[:, 1] > 0) ^ (x[:, 3] > 0)).astype(np.float64)  # 1 and 3 matter
    t = Table({"features": x, "label": y})
    model = _clf(num_trees=20, max_depth=3).fit(t)
    imp = model.feature_importances()
    assert imp.shape == (5,)    # training feature count, persisted
    np.testing.assert_allclose(imp.sum(), 1.0, rtol=1e-9)
    assert set(np.argsort(-imp)[:2]) == {1, 3}
    # Gain-weighted: the noise features carry almost nothing.
    assert imp[[1, 3]].sum() > 0.9
    # num_features pads unseen trailing features with zero, and rejects
    # counts smaller than features actually split on.
    imp8 = model.feature_importances(num_features=8)
    np.testing.assert_allclose(imp8[:5], imp)
    np.testing.assert_allclose(imp8[5:], 0.0)
    with pytest.raises(ValueError, match="splits on feature"):
        model.feature_importances(num_features=1)
    # Deep trees on one-split data: degenerate nodes must not inflate
    # feature 0 (the zero-gain argmax default).
    rng2 = np.random.default_rng(10)
    x2 = rng2.uniform(-1, 1, size=(600, 3))
    y2 = (x2[:, 2] > 0).astype(np.float64)
    deep = _clf(num_trees=10, max_depth=5).fit(
        Table({"features": x2, "label": y2})
    )
    imp_deep = deep.feature_importances()
    assert np.argmax(imp_deep) == 2 and imp_deep[2] > 0.9, imp_deep


def test_random_forest_classifier_quality_and_diversity():
    from flinkml_tpu.models import RandomForestClassifier

    x, y = _nonlinear_classification(n=1500, seed=11)
    t = Table({"features": x, "label": y})
    rf = (
        RandomForestClassifier().set_num_trees(40).set_max_depth(5)
        .set_subsample(0.7).set_seed(0)
    )
    model = rf.fit(t)
    (out,) = model.transform(t)
    auc = roc_auc_score(y, out["rawPrediction"][:, 1])
    # Poisson(0.7) bootstrap rows + sqrt feature subsets on a noisy task.
    assert auc > 0.8, auc
    # Feature subsets differ across trees (sqrt(6)/6 fraction).
    assert len({tuple(np.unique(model._feats[i])) for i in range(10)}) > 1
    # Prediction scale is the MEAN of tree outputs, not a sum.
    assert model._lr == pytest.approx(1.0 / 40)


def test_random_forest_regressor_and_persistence(tmp_path):
    from flinkml_tpu.models import (
        RandomForestRegressor,
        RandomForestRegressorModel,
    )

    x, y = _nonlinear_regression(n=1200, seed=12)
    t = Table({"features": x, "label": y})
    model = (
        RandomForestRegressor().set_num_trees(40).set_max_depth(6)
        .set_subsample(0.7).set_seed(0).fit(t)
    )
    (out,) = model.transform(t)
    assert r2_score(y, out["prediction"]) > 0.7
    model.save(str(tmp_path / "rf"))
    loaded = RandomForestRegressorModel.load(str(tmp_path / "rf"))
    np.testing.assert_allclose(
        loaded.transform(t)[0]["prediction"], out["prediction"]
    )


def test_random_forest_feature_fraction_param():
    from flinkml_tpu.models import RandomForestClassifier

    rng = np.random.default_rng(13)
    x = rng.uniform(-1, 1, size=(400, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    t = Table({"features": x, "label": y})
    full = (
        RandomForestClassifier().set_num_trees(8).set_max_depth(2)
        .set_feature_subset_fraction(1.0).set_seed(0).fit(t)
    )
    # With all features available, every tree roots on the true one.
    assert np.all(full._feats[:, 0] == 0)


def test_random_forest_trees_are_diverse_at_default_params():
    from flinkml_tpu.models import RandomForestRegressor

    x, y = _nonlinear_regression(n=600, seed=14)
    t = Table({"features": x, "label": y})
    model = (
        RandomForestRegressor().set_num_trees(6).set_max_depth(3)
        .set_seed(0).fit(t)    # defaults: subsample 1.0, all features
    )
    # Poisson bootstrap must make default-param trees differ.
    leaves = [tuple(np.round(model._leaves[i], 6)) for i in range(6)]
    assert len(set(leaves)) > 1


def test_random_forest_subset_contract_is_strict():
    from flinkml_tpu.models import RandomForestClassifier

    rng = np.random.default_rng(15)
    x = rng.uniform(-1, 1, size=(500, 6))
    y = (x[:, 2] > 0).astype(np.float64)
    t = Table({"features": x, "label": y})
    model = (
        RandomForestClassifier().set_num_trees(50).set_max_depth(3)
        .set_feature_subset_fraction(0.34).set_seed(0).fit(t)
    )
    # Every tree's POSITIVE-gain splits use at most 2 distinct features
    # (round(0.34 * 6) = 2) — zero-gain degenerate nodes are excluded.
    for i in range(50):
        used = {
            int(f) for f, g in zip(model._feats[i], model._gains[i]) if g > 0
        }
        assert len(used) <= 2, (i, used)
    # The param survives into the fitted model's map.
    assert "featureSubsetFraction" in model.get_param_map_json()


def test_early_stopping_truncates_overfitting_forest():
    # Tiny noisy data + many deep trees: holdout-best prefix must be
    # shorter than the full forest and generalize at least as well.
    rng = np.random.default_rng(16)
    x = rng.uniform(-2, 2, size=(400, 4))
    logits = 1.5 * x[:, 0]
    y = (rng.uniform(size=400) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    t = Table({"features": x[:300], "label": y[:300]})
    full = _clf(num_trees=80, max_depth=5, learning_rate=0.4).fit(t)
    stopped = _clf(
        num_trees=80, max_depth=5, learning_rate=0.4,
        validation_fraction=0.25,
    ).fit(t)
    assert stopped._feats.shape[0] < 80
    probe = Table({"features": x[300:]})
    (pf,) = full.transform(probe)
    (ps,) = stopped.transform(probe)
    full_auc = roc_auc_score(y[300:], pf["rawPrediction"][:, 1])
    stop_auc = roc_auc_score(y[300:], ps["rawPrediction"][:, 1])
    assert stop_auc >= full_auc - 0.02


def test_early_stopping_rejected_for_bagging():
    from flinkml_tpu.models import RandomForestClassifier

    t = Table({"features": np.zeros((10, 2)),
               "label": np.asarray([0.0, 1.0] * 5)})
    with pytest.raises(ValueError, match="boosted"):
        (
            RandomForestClassifier().set_validation_fraction(0.2)
            .set_num_trees(2).fit(t)
        )


def test_labels_validated_before_holdout_split():
    rng = np.random.default_rng(17)
    x = rng.uniform(-1, 1, size=(40, 2))
    y = np.zeros(40)
    y[::2] = 1.0
    y[7] = 2.0   # invalid label that a split could hide in the holdout
    t = Table({"features": x, "label": y})
    for vf in (0.0, 0.25):
        with pytest.raises(ValueError, match="0, 1"):
            _clf(num_trees=2, validation_fraction=vf).fit(t)


def test_early_stopping_regressor_path():
    rng = np.random.default_rng(18)
    x = rng.uniform(-2, 2, size=(300, 3))
    y = x[:, 0] + 0.5 * rng.normal(size=300)   # noisy linear target
    t = Table({"features": x[:220], "label": y[:220]})
    stopped = (
        GBTRegressor().set_num_trees(60).set_max_depth(5)
        .set_learning_rate(0.4).set_validation_fraction(0.25)
        .set_seed(0).fit(t)
    )
    assert stopped._feats.shape[0] < 60
    (out,) = stopped.transform(Table({"features": x[220:]}))
    assert r2_score(y[220:], out["prediction"]) > 0.5


# -- sparse (hash-bundled) inputs (round-3: VERDICT r2 item 8) ---------------

def _sparse_cat_table(n=600, cardinality=1_000_000, seed=0):
    """SparseVector features at a cardinality where densifying would need
    n x 1e6 floats (the pipeline shape sparse LR consumes): a handful of
    informative real-valued columns + high-cardinality one-hot noise.
    The hash bundling must land each informative column in a stable
    bucket whose value the trees can split on."""
    from flinkml_tpu.linalg import SparseVector

    rng = np.random.default_rng(seed)
    info_cols = [12_345, 777_777, 424_242]
    col = np.empty(n, dtype=object)
    y = np.empty(n, np.float64)
    for i in range(n):
        v = rng.normal(size=len(info_cols))
        noise_ids = rng.choice(cardinality, size=4, replace=False)
        ids = np.concatenate([np.asarray(info_cols), noise_ids])
        vals = np.concatenate([v, np.full(4, 0.01)])
        uniq, first = np.unique(ids, return_index=True)
        col[i] = SparseVector(cardinality, uniq, vals[first])
        y[i] = float(v[0] + 0.5 * v[1] - 0.5 * v[2] > 0)
    return Table({"features": col, "label": y}), y


def test_gbt_trains_on_sparse_without_densifying():
    from flinkml_tpu.models import GBTClassifier

    table, y = _sparse_cat_table()
    model = (
        GBTClassifier().set_num_trees(15).set_max_depth(4)
        .set_max_bins(32).set_num_hash_features(512)
        .set_learning_rate(0.5).set_seed(0)
        .fit(table)
    )
    # The forest was trained on the bundled space, not the 1e6-dim one.
    assert model._hash_features == 512
    assert model._n_features == 512
    (out,) = model.transform(table)
    acc = float(np.mean(out["prediction"] == y))
    # Memorization regime: hash buckets of ~half-positive/half-negative
    # categories bound the ceiling; well above chance proves learning.
    assert acc > 0.8, acc


def test_gbt_sparse_model_persistence_round_trip(tmp_path):
    from flinkml_tpu.models import GBTClassifier, GBTClassifierModel

    table, _ = _sparse_cat_table(n=200)
    model = (
        GBTClassifier().set_num_trees(5).set_max_depth(3)
        .set_num_hash_features(64).set_seed(0).fit(table)
    )
    (out,) = model.transform(table)
    model.save(str(tmp_path / "sgbt"))
    loaded = GBTClassifierModel.load(str(tmp_path / "sgbt"))
    assert loaded._hash_features == 64
    (out2,) = loaded.transform(table)
    np.testing.assert_array_equal(out["prediction"], out2["prediction"])
    # Model-data tables carry the bundling width too.
    m3 = GBTClassifierModel()
    m3.copy_params_from(model)
    m3.set_model_data(*model.get_model_data())
    (out3,) = m3.transform(table)
    np.testing.assert_array_equal(out["prediction"], out3["prediction"])


def test_gbt_sparse_streamed_fit(tmp_path):
    from flinkml_tpu.models import GBTClassifier

    tables = []
    ys = []
    for s in range(4):
        t, y = _sparse_cat_table(n=200, seed=s)
        tables.append(t)
        ys.append(y)
    model = (
        GBTClassifier(cache_dir=str(tmp_path / "sp"),
                      cache_memory_budget_bytes=1)
        .set_num_trees(10).set_max_depth(4).set_num_hash_features(256)
        .set_learning_rate(0.5).set_seed(0)
        .fit(iter(tables))
    )
    assert model._hash_features == 256
    (out,) = model.transform(tables[0])
    acc = float(np.mean(out["prediction"] == ys[0]))
    assert acc > 0.7, acc


def test_random_forest_on_sparse_input():
    from flinkml_tpu.models import RandomForestClassifier

    table, y = _sparse_cat_table(n=300)
    model = (
        RandomForestClassifier().set_num_trees(20).set_max_depth(6)
        .set_num_hash_features(128).set_seed(0).fit(table)
    )
    (out,) = model.transform(table)
    assert float(np.mean(out["prediction"] == y)) > 0.7


def test_cumsum_histogram_layout_matches_segment(mesh, monkeypatch):
    """FLINKML_TPU_GBT_HISTOGRAM=cumsum (pack-time-sorted cells +
    chunked run totals) must build the identical forest: same splits,
    same leaf values, same raw predictions."""
    from flinkml_tpu.models.gbt import GBTClassifier

    rng = np.random.default_rng(3)
    n = 512
    x = rng.uniform(-1, 1, size=(n, 5)).astype(np.float32)
    y = ((x[:, 0] * x[:, 1] > 0)).astype(np.float64)
    t = Table({"features": x, "label": y})

    def fit(layout):
        monkeypatch.setenv("FLINKML_TPU_GBT_HISTOGRAM", layout)
        m = (
            GBTClassifier(mesh=mesh).set_num_trees(6).set_max_depth(3)
            .set_max_bins(16).set_subsample(0.8).set_seed(0).fit(t)
        )
        (out,) = m.transform(t)
        return m, np.asarray(out["rawPrediction"])

    m_seg, raw_seg = fit("segment")
    m_cum, raw_cum = fit("cumsum")
    np.testing.assert_array_equal(m_seg._feats, m_cum._feats)
    np.testing.assert_allclose(m_seg._leaves, m_cum._leaves, rtol=1e-5)
    np.testing.assert_allclose(raw_cum, raw_seg, rtol=1e-5, atol=1e-6)


def test_gbt_hist_tables_reconstruct_histograms():
    from flinkml_tpu.models.gbt import gbt_hist_tables

    rng = np.random.default_rng(0)
    p, n_local, d, n_bins = 2, 24, 3, 4
    b = rng.integers(0, n_bins, size=(p * n_local, d)).astype(np.int32)
    srow, ends, cols = gbt_hist_tables(b, p, n_bins)
    cells = n_local * d
    g = rng.normal(size=p * n_local)
    for dev in range(p):
        shard = b[dev * n_local:(dev + 1) * n_local]
        expect = np.zeros(d * n_bins)
        np.add.at(
            expect,
            (np.arange(d)[None, :] * n_bins + shard).reshape(-1),
            np.repeat(g[dev * n_local:(dev + 1) * n_local], d),
        )
        sr = srow[dev * cells:(dev + 1) * cells]
        e = ends[dev * (ends.size // p):(dev + 1) * (ends.size // p)]
        c = cols[dev * (cols.size // p):(dev + 1) * (cols.size // p)]
        contrib = g[dev * n_local + sr]
        csum = np.cumsum(contrib)
        tvals = csum[e]
        seg = tvals - np.concatenate([[0.0], tvals[:-1]])
        got = np.zeros(d * n_bins)
        np.add.at(got, c, seg)
        np.testing.assert_allclose(got, expect, atol=1e-10)
