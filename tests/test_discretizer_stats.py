"""KBinsDiscretizer / OnlineStandardScaler / Correlation vs sklearn/scipy."""

import numpy as np
import pytest
from scipy.stats import spearmanr
from sklearn.preprocessing import KBinsDiscretizer as SkKBins

from flinkml_tpu.models import (
    Correlation,
    KBinsDiscretizer,
    KBinsDiscretizerModel,
    OnlineStandardScaler,
    StandardScaler,
)
from flinkml_tpu.models.stats import _average_ranks, correlation_matrix
from flinkml_tpu.table import Table


def _x(n=500, d=4, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(loc=2.0, scale=3.0, size=(n, d))


# -- KBinsDiscretizer --------------------------------------------------------

@pytest.mark.parametrize("strategy", ["uniform", "quantile", "kmeans"])
def test_kbins_matches_sklearn(strategy):
    x = _x(seed=1)
    t = Table({"input": x})
    model = KBinsDiscretizer().set_num_bins(5).set_strategy(strategy).fit(t)
    (out,) = model.transform(t)
    ref = SkKBins(
        n_bins=5, encode="ordinal", strategy=strategy,
        **({"subsample": None} if strategy != "uniform" else {}),
    ).fit_transform(x)
    agreement = (out["output"] == ref).mean()
    # kmeans bin placement depends on the Lloyd init (ours is
    # quantile-seeded, sklearn's differs), so only rough agreement is
    # guaranteed; uniform/quantile should agree everywhere.
    assert agreement > (0.85 if strategy == "kmeans" else 0.999), agreement


def test_kbins_clips_out_of_range_and_roundtrips(tmp_path):
    x = _x(seed=2)
    t = Table({"input": x})
    model = KBinsDiscretizer().set_num_bins(4).fit(t)
    probe = Table({"input": np.asarray([[-1e9] * 4, [1e9] * 4])})
    (out,) = model.transform(probe)
    np.testing.assert_array_equal(out["output"][0], [0.0] * 4)
    np.testing.assert_array_equal(out["output"][1], [3.0] * 4)
    model.save(str(tmp_path / "kb"))
    loaded = KBinsDiscretizerModel.load(str(tmp_path / "kb"))
    np.testing.assert_array_equal(loaded.bin_edges, model.bin_edges)


def test_kbins_constant_feature_single_bin():
    x = _x(seed=3)
    x[:, 2] = 5.0
    t = Table({"input": x})
    model = KBinsDiscretizer().set_num_bins(4).fit(t)
    (out,) = model.transform(t)
    assert np.all(out["output"][:, 2] == 0.0)


# -- OnlineStandardScaler ----------------------------------------------------

def test_online_scaler_matches_batch_exactly():
    x = _x(n=1000, seed=4)
    t = Table({"input": x})
    online = OnlineStandardScaler().set_global_batch_size(64).fit(t)
    batch = StandardScaler().fit(t)
    (o1,) = online.transform(t)
    (o2,) = batch.transform(t)
    # Batch scaler sums in f32 on device; online merges in f64 on the
    # host — near-zero standardized values can differ at f32 epsilon.
    np.testing.assert_allclose(o1["output"], o2["output"], rtol=1e-5,
                               atol=1e-6)
    assert online._model_version == int(np.ceil(1000 / 64))


def test_online_scaler_stream_and_flags():
    x = _x(n=300, seed=5)
    batches = [Table({"input": x[i: i + 50]}) for i in range(0, 300, 50)]
    model = (
        OnlineStandardScaler().set_with_mean(False).fit_stream(iter(batches))
    )
    (out,) = model.transform(Table({"input": x}))
    std = x.std(axis=0)
    np.testing.assert_allclose(out["output"], x / std, rtol=1e-9)
    assert model.model_version == 6
    with pytest.raises(ValueError, match="empty"):
        OnlineStandardScaler().fit_stream(iter([]))


# -- Correlation -------------------------------------------------------------

def test_average_ranks_matches_scipy():
    from scipy.stats import rankdata

    rng = np.random.default_rng(6)
    col = rng.integers(0, 5, 40).astype(float)   # heavy ties
    np.testing.assert_allclose(_average_ranks(col), rankdata(col))


def test_pearson_matches_numpy():
    x = _x(n=800, seed=7)
    x[:, 3] = 0.8 * x[:, 0] + 0.2 * x[:, 3]   # induce correlation
    corr = correlation_matrix(x, "pearson")
    np.testing.assert_allclose(corr, np.corrcoef(x, rowvar=False),
                               rtol=1e-4, atol=1e-5)


def test_spearman_matches_scipy():
    rng = np.random.default_rng(8)
    x = rng.normal(size=(400, 3))
    x[:, 1] = np.exp(x[:, 0]) + 0.3 * rng.normal(size=400)  # monotone link
    corr = correlation_matrix(x, "spearman")
    ref = spearmanr(x).statistic
    np.testing.assert_allclose(corr, ref, rtol=1e-4, atol=1e-5)
    assert corr[0, 1] > 0.9


def test_correlation_operator_and_constant_columns():
    x = _x(n=100, seed=9)
    x[:, 1] = 7.0
    (out,) = Correlation().transform(Table({"features": x}))
    corr = out["corr"][0]
    assert corr.shape == (4, 4)
    assert corr[1, 1] == 1.0
    assert np.isnan(corr[0, 1]) and np.isnan(corr[1, 0])
    np.testing.assert_allclose(np.diag(corr), 1.0)


def test_kmeans_strategy_skewed_ties():
    # 9 zeros + one outlier: quantile seeding over RAW values collapses
    # to one center; seeding from distinct values must keep 2 bins.
    col = np.asarray([0.0] * 9 + [100.0])
    t = Table({"input": col[:, None]})
    model = KBinsDiscretizer().set_num_bins(2).set_strategy("kmeans").fit(t)
    (out,) = model.transform(t)
    np.testing.assert_array_equal(out["output"][:, 0], [0.0] * 9 + [1.0])


def test_online_scaler_version_persists(tmp_path):
    from flinkml_tpu.models import OnlineStandardScalerModel

    x = _x(n=100, seed=10)
    model = OnlineStandardScaler().set_global_batch_size(10).fit(
        Table({"input": x})
    )
    assert model.model_version == 10
    model.save(str(tmp_path / "oss"))
    loaded = OnlineStandardScalerModel.load(str(tmp_path / "oss"))
    assert loaded.model_version == 10
    np.testing.assert_allclose(
        loaded.transform(Table({"input": x}))[0]["output"],
        model.transform(Table({"input": x}))[0]["output"],
    )
