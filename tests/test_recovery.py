"""Self-healing training (ISSUE 9): numerics sentinel,
rollback-and-quarantine recovery, chaos soak.

Acceptance contract (the E2E test below): an OnlineLogisticRegression
fed a stream with a poisoned (all-NaN) batch trains WITHOUT operator
intervention to a finite model bit-identical to the same run with that
batch excluded; the quarantine ledger names exactly that batch range;
and the run survives a kill+resume mid-recovery (the ledger rides every
snapshot's ``extra``).
"""

import json
import os

import numpy as np
import pytest

from flinkml_tpu import faults
from flinkml_tpu.iteration import (
    CheckpointManager,
    IterationConfig,
    TerminateOnMaxIter,
    iterate,
)
from flinkml_tpu.models import OnlineKMeans, OnlineLogisticRegression
from flinkml_tpu.models.online_scaler import OnlineStandardScaler
from flinkml_tpu.recovery import (
    DATA_POISON,
    SYSTEMIC,
    NonFiniteModelError,
    NumericsError,
    NumericsSentinel,
    QuarantineLedger,
    RecoveryPolicy,
)
from flinkml_tpu.table import Table

N_BATCHES = 12
POISON = 5
INTERVAL = 2


def lr_batches(seed=0, n=N_BATCHES, rows=48, dim=5, poison=None):
    rng = np.random.default_rng(seed)
    true = rng.normal(size=dim) * 2
    out = []
    for i in range(n):
        x = rng.normal(size=(rows, dim))
        if poison is not None and i == poison:
            x = np.full_like(x, np.nan)
        out.append(Table({"features": x,
                          "label": (x @ true > 0).astype(np.float64)}))
    return out


def _lr():
    return OnlineLogisticRegression().set_alpha(0.5).set_reg(0.01)


def _policy(**kw):
    kw.setdefault("backoff_s", 0.0)
    return RecoveryPolicy(**kw)


# ---------------------------------------------------------------------------
# The sentinel
# ---------------------------------------------------------------------------

class TestSentinel:
    def test_clean_state_passes(self):
        s = NumericsSentinel()
        s.check({"w": np.ones(4)}, 0.5, epoch=0, source_index=0)
        assert s.checks == 1 and s.raises == 0

    def test_nonfinite_state_is_data_poison(self):
        s = NumericsSentinel()
        with pytest.raises(NumericsError) as ei:
            s.check({"w": np.array([1.0, np.nan])}, 0.5, epoch=3,
                    source_index=7)
        assert ei.value.classification == DATA_POISON
        assert ei.value.epoch == 3 and ei.value.source_index == 7
        assert ei.value.exact

    def test_nonfinite_loss_is_data_poison(self):
        s = NumericsSentinel()
        with pytest.raises(NumericsError, match="non-finite loss"):
            s.check({"w": np.ones(4)}, float("inf"), epoch=1,
                    source_index=1)

    def test_int_leaves_and_none_loss_pass(self):
        s = NumericsSentinel()
        s.check({"w": np.ones(2), "version": 3}, None, epoch=0,
                source_index=0)
        assert s.raises == 0

    def test_magnitude_streak_is_systemic(self):
        s = NumericsSentinel(max_abs=10.0, systemic_streak=3)
        big = {"w": np.full(2, 100.0)}
        s.check(big, 0.1, epoch=0, source_index=0)
        s.check(big, 0.1, epoch=1, source_index=1)
        with pytest.raises(NumericsError) as ei:
            s.check(big, 0.1, epoch=2, source_index=2)
        assert ei.value.classification == SYSTEMIC

    def test_magnitude_streak_resets_on_clean_epoch(self):
        s = NumericsSentinel(max_abs=10.0, systemic_streak=2)
        s.check({"w": np.full(2, 100.0)}, 0.1, epoch=0, source_index=0)
        s.check({"w": np.ones(2)}, 0.1, epoch=1, source_index=1)  # resets
        s.check({"w": np.full(2, 100.0)}, 0.1, epoch=2, source_index=2)
        assert s.raises == 0

    def test_interval_checks_are_inexact_and_pinpointable(self):
        s = NumericsSentinel(interval=4)
        bad = {"w": np.array([np.nan])}
        # epochs 0-2 not due; epoch 3 due ((3+1) % 4 == 0)
        s.check(bad, 0.1, epoch=0, source_index=0)
        s.check(bad, 0.1, epoch=2, source_index=2)
        assert s.checks == 0
        with pytest.raises(NumericsError) as ei:
            s.check(bad, 0.1, epoch=3, source_index=3)
        assert not ei.value.exact
        # pinpoint mode: every epoch due again, detections exact
        s.begin_pinpoint(3)
        with pytest.raises(NumericsError) as ei2:
            s.check(bad, 0.1, epoch=1, source_index=1)
        assert ei2.value.exact

    def test_validation(self):
        with pytest.raises(ValueError):
            NumericsSentinel(interval=0)
        with pytest.raises(ValueError):
            NumericsSentinel(systemic_streak=0)


# ---------------------------------------------------------------------------
# Ledger + policy
# ---------------------------------------------------------------------------

class TestLedgerAndPolicy:
    def test_ledger_ranges_merge_and_roundtrip(self):
        led = QuarantineLedger()
        for i in (7, 3, 4, 5):
            assert led.add(i)
        assert not led.add(4)  # dupe
        assert led.ranges() == [(3, 6), (7, 8)]
        rt = QuarantineLedger.from_json_dict(led.to_json_dict())
        assert rt.indices() == [3, 4, 5, 7]
        assert 5 in rt and 6 not in rt

    def test_source_position(self):
        led = QuarantineLedger([1, 5])
        # delivered d -> source watermark: quarantined batches BELOW the
        # watermark were read-and-discarded and count; one sitting AT it
        # is skipped at the next read (delivered order: 0,2,3,4,6,...).
        assert led.source_position(0) == 0
        assert led.source_position(1) == 1   # batch 1 not read yet
        assert led.source_position(2) == 3   # 0,2 delivered; 1 skipped
        assert led.source_position(4) == 5   # 0,2,3,4 delivered
        assert led.source_position(5) == 7   # ...,6 delivered; 1,5 skipped
        assert QuarantineLedger().source_position(9) == 9

    def test_policy_validation_and_actions(self):
        p = RecoveryPolicy()
        assert p.action_for(DATA_POISON) == "rollback_quarantine"
        assert p.action_for(SYSTEMIC) == "abort"
        p2 = RecoveryPolicy(actions={SYSTEMIC: "stop_at_last_valid"})
        assert p2.action_for(SYSTEMIC) == "stop_at_last_valid"
        with pytest.raises(ValueError):
            RecoveryPolicy(max_retries=0)
        with pytest.raises(ValueError):
            RecoveryPolicy(actions={"nope": "abort"})
        with pytest.raises(ValueError):
            RecoveryPolicy(actions={SYSTEMIC: "rollback_quarantine"})

    def test_policy_backoff_jitter_bounds(self):
        import random

        p = RecoveryPolicy(backoff_s=0.1, backoff_jitter=0.5,
                           max_backoff_s=10.0)
        d = p.backoff(3, random.Random(0))  # base 0.4
        assert 0.4 <= d <= 0.6
        assert RecoveryPolicy(backoff_s=0.0).backoff(5) == 0.0
        assert RecoveryPolicy(backoff_s=4.0, max_backoff_s=1.0).backoff(9) \
            <= 1.0


# ---------------------------------------------------------------------------
# E2E acceptance: poisoned stream self-heals without operator intervention
# ---------------------------------------------------------------------------

def test_poisoned_stream_self_heals_bit_exact(tmp_path):
    """The ISSUE 9 acceptance criterion, first half: a NaN batch in the
    stream is detected, rolled back past, quarantined, and the fit
    converges — finite and bit-identical to the same stream with the
    poisoned batch excluded; the ledger names exactly that batch."""
    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=POISON)) if i != POISON]
    )

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    healed = _lr().fit_stream(
        lr_batches(poison=POISON), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL, recovery=_policy(),
    )
    assert np.isfinite(healed.coefficient).all()
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.model_version == golden.model_version == N_BATCHES - 1
    summary = healed.recovery_summary
    assert summary["quarantined"] == [POISON]
    assert summary["quarantine_ranges"] == [(POISON, POISON + 1)]
    assert summary["rollbacks"] == 1
    assert summary["retries"] == {DATA_POISON: 1}
    # The ledger rides the snapshot manifest (resume honors it).
    ckpt = os.path.join(str(tmp_path / "ckpt"),
                        f"ckpt-{N_BATCHES - 1}", "meta.json")
    with open(ckpt) as f:
        extra = json.load(f)["extra"]
    assert extra["quarantine"] == {"ranges": [[POISON, POISON + 1]]}


def test_poisoned_stream_survives_kill_mid_recovery(tmp_path):
    """Second half: the healed run is KILLED after recovery (a
    kill-after-commit past the quarantine), and the resumed process —
    which knows nothing of the first — honors the ledger from the
    snapshot manifest and completes to the same bit-exact model."""
    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=POISON)) if i != POISON]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    # Kill at the first commit at-or-after epoch 8 (the quarantine of
    # batch 5 happened around epoch 5 — the ledger is in that snapshot).
    with faults.armed(faults.FaultPlan(
            faults.KillAfterCheckpoint(min_epoch=8))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(
                lr_batches(poison=POISON), checkpoint_manager=mgr,
                checkpoint_interval=INTERVAL, recovery=_policy(),
            )
    recorded = None
    # the committed snapshot already carries the quarantine record
    epochs = mgr.all_epochs()
    with open(os.path.join(str(tmp_path / "ckpt"),
                           f"ckpt-{epochs[-1]}", "meta.json")) as f:
        recorded = json.load(f)["extra"].get("quarantine")
    assert recorded == {"ranges": [[POISON, POISON + 1]]}

    resumed = _lr().fit_stream(
        lr_batches(poison=POISON), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL, resume=True, recovery=_policy(),
    )
    np.testing.assert_array_equal(resumed.coefficient, golden.coefficient)
    assert resumed.model_version == golden.model_version
    # The resumed session quarantined nothing NEW (the ledger came from
    # the manifest), and its summary carries the inherited skips.
    assert resumed.recovery_summary["quarantined"] == [POISON]
    assert resumed.recovery_summary["rollbacks"] == 0


def test_resume_honors_ledger_without_policy(tmp_path):
    """A ledgered snapshot resumed WITHOUT a recovery policy still skips
    the quarantined range — the ledger is part of the snapshot contract,
    not of the policy object."""
    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=POISON)) if i != POISON]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(
            faults.KillAfterCheckpoint(min_epoch=8))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(
                lr_batches(poison=POISON), checkpoint_manager=mgr,
                checkpoint_interval=INTERVAL, recovery=_policy(),
            )
    resumed = _lr().fit_stream(
        lr_batches(poison=POISON), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL, resume=True,  # no recovery=
    )
    np.testing.assert_array_equal(resumed.coefficient, golden.coefficient)
    assert resumed.model_version == golden.model_version


def test_poison_batch_fault_heals_identically(tmp_path):
    """The same acceptance shape driven by the PoisonBatch fault at the
    train.step seam instead of NaN data — the seam poisons batch 5's
    floats before the step consumes them, and re-fires on every retry
    (only the quarantine heals it)."""
    clean = lr_batches()
    golden = _lr().fit_stream(
        [b for i, b in enumerate(clean) if i != POISON]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.PoisonBatch(POISON))) as plan:
        healed = _lr().fit_stream(
            clean, checkpoint_manager=mgr,
            checkpoint_interval=INTERVAL, recovery=_policy(),
        )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.recovery_summary["quarantined"] == [POISON]
    assert any(site == "train.step" for site, _, _ in plan.log)


def test_adjacent_poisoned_batches_quarantine_as_one_range(tmp_path):
    """Two adjacent NaN batches heal as two rollbacks and ONE merged
    ledger range."""
    batches = lr_batches()
    for i in (POISON, POISON + 1):
        batches[i] = Table({
            "features": np.full((48, 5), np.nan),
            "label": np.zeros(48),
        })
    golden = _lr().fit_stream(
        [b for i, b in enumerate(batches)
         if i not in (POISON, POISON + 1)]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    healed = _lr().fit_stream(
        batches, checkpoint_manager=mgr, checkpoint_interval=INTERVAL,
        recovery=_policy(),
    )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.recovery_summary["quarantine_ranges"] == \
        [(POISON, POISON + 2)]
    assert healed.recovery_summary["rollbacks"] == 2


def test_recovery_without_manager_replays_from_scratch(tmp_path):
    """No checkpoint manager: the rollback is an (explicit, logged)
    fresh start with the ledger applied — still converges to the
    excluded-batch golden."""
    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=POISON)) if i != POISON]
    )
    healed = _lr().fit_stream(lr_batches(poison=POISON),
                              recovery=_policy())
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.recovery_summary["quarantined"] == [POISON]


# ---------------------------------------------------------------------------
# Compound recovery (satellite): numerics fault + damaged rollback target
# ---------------------------------------------------------------------------

def km_batches(seed=1, n=N_BATCHES, rows=40, dim=4):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-8, 8, size=(3, dim))
    out = []
    for _ in range(n):
        assign = rng.integers(0, 3, size=rows)
        x = centers[assign] + rng.normal(scale=0.4, size=(rows, dim))
        out.append(Table({"features": x}))
    return out


def sc_batches(seed=2, n=N_BATCHES, rows=32, dim=6):
    rng = np.random.default_rng(seed)
    return [Table({"input": rng.normal(size=(rows, dim)) * (1 + i)})
            for i in range(n)]


@pytest.mark.parametrize("trainer", ["lr", "kmeans", "scaler"])
def test_compound_nangrad_plus_corrupt_rollback_target(tmp_path, trainer):
    """The compound satellite, per online trainer: NaNGrad at epoch 7
    AND a corrupted rollback target (the epoch-6 interval commit) ⇒ the
    recovery's restore_latest walks back ONE MORE snapshot (epoch 4),
    quarantines batch 7, and converges to finite-model parity with the
    batch-7-excluded run."""
    k = 7
    if trainer == "lr":
        make, batches = _lr, lr_batches()
        final = lambda m: m.coefficient
        version = lambda m: m.model_version
    elif trainer == "kmeans":
        make = lambda: OnlineKMeans().set_k(3).set_seed(11) \
            .set_decay_factor(0.9)
        batches = km_batches()
        final = lambda m: m.centroids
        version = lambda m: m.model_version
    else:
        make, batches = OnlineStandardScaler, sc_batches()
        final = lambda m: np.stack([m._mean, m._std])
        version = lambda m: m.model_version

    golden = make().fit_stream(
        [b for i, b in enumerate(batches) if i != k]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    # Plan order: corrupt the epoch-6 commit the moment it lands, then
    # let NaNGrad poison epoch 7's step — the rollback target is already
    # damaged when the recovery engine reaches for it.
    with faults.armed(faults.FaultPlan(
            faults.CorruptSnapshot(min_epoch=6, target="arrays"),
            faults.NaNGrad(k))) as plan:
        healed = make().fit_stream(
            batches, checkpoint_manager=mgr,
            checkpoint_interval=INTERVAL, recovery=_policy(),
        )
    assert np.isfinite(final(healed)).all()
    np.testing.assert_array_equal(final(healed), final(golden))
    assert version(healed) == version(golden) == N_BATCHES - 1
    assert healed.recovery_summary["quarantined"] == [k]
    # Both faults fired: the corrupt at the epoch-6 commit, the NaN at
    # epoch 7 — and recovery had to fall back PAST the corrupt snapshot.
    sites = [site for site, _, _ in plan.log]
    assert "checkpoint.committed" in sites and "train.step" in sites


@pytest.mark.no_retrace
def test_compound_shuffled_dataset_nangrad_torn_write(tmp_path):
    """The shuffled-Dataset variant of the compound satellite: a
    seeded-shuffle Dataset feed where TornWrite kills the epoch-6
    commit (a crash — the snapshot never lands, so the restart resumes
    from the epoch-4 one: the rollback target fell one snapshot back)
    and NaNGrad then poisons the resumed run's epoch 7 ⇒ quarantine of
    the poisoned SOURCE batch, healed model bit-identical to the golden
    run whose feed skips that batch — shuffle order preserved
    throughout (cursor replay)."""
    from flinkml_tpu.data import Dataset

    rows = np.concatenate([np.asarray(b.column("features"))
                           for b in lr_batches(seed=3)])
    labels = np.concatenate([np.asarray(b.column("label"))
                             for b in lr_batches(seed=3)])

    def ds():
        return Dataset.from_arrays(
            Table({"features": rows, "label": labels}), batch_size=48
        ).shuffle(4, seed=13)

    k = 7
    # Golden: the same shuffled sequence with delivered batch 7 removed.
    seq = list(ds())
    golden = _lr().fit_stream([b for i, b in enumerate(seq) if i != k])

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(
            faults.TornWrite(6), faults.NaNGrad(k))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(ds(), checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL,
                             recovery=_policy())
        assert mgr.latest_epoch() == 4  # 6 torn: one snapshot back
        healed = _lr().fit_stream(
            ds(), checkpoint_manager=mgr, checkpoint_interval=INTERVAL,
            resume=True, recovery=_policy(),
        )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.model_version == golden.model_version == N_BATCHES - 1
    assert healed.recovery_summary["quarantined"] == [k]
    # The terminal snapshot's cursor advanced past the quarantined batch
    # (source watermark = delivered + skipped).
    with open(os.path.join(str(tmp_path / "ckpt"),
                           f"ckpt-{N_BATCHES - 1}", "meta.json")) as f:
        extra = json.load(f)["extra"]
    assert extra["data_cursor"]["emitted"] == N_BATCHES
    assert extra["quarantine"] == {"ranges": [[k, k + 1]]}


def test_torn_write_restart_then_poison_composes(tmp_path):
    """TornWrite kills the epoch-6 commit (a crash, restarted like an
    orchestrator would) and the SAME stream then poisons batch 7 on the
    resumed run: the restart path and the in-loop heal compose to
    excluded-batch parity."""
    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=7)) if i != 7]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.TornWrite(6))):
        with pytest.raises(faults.FaultInjected):
            _lr().fit_stream(lr_batches(poison=7), checkpoint_manager=mgr,
                             checkpoint_interval=INTERVAL,
                             recovery=_policy())
        assert mgr.latest_epoch() == 4  # 6 torn — one snapshot back
        resumed = _lr().fit_stream(
            lr_batches(poison=7), checkpoint_manager=mgr,
            checkpoint_interval=INTERVAL, resume=True, recovery=_policy(),
        )
    np.testing.assert_array_equal(resumed.coefficient, golden.coefficient)
    assert resumed.recovery_summary["quarantined"] == [7]


# ---------------------------------------------------------------------------
# Classification, escalation, actions
# ---------------------------------------------------------------------------

def test_sentinel_without_recovery_raises_typed(tmp_path):
    with pytest.raises(NumericsError) as ei:
        _lr().fit_stream(lr_batches(poison=POISON),
                         sentinel=NumericsSentinel())
    assert ei.value.classification == DATA_POISON
    assert ei.value.source_index == POISON


def test_infloss_fault_quarantines_and_heals(tmp_path):
    clean = lr_batches()
    golden = _lr().fit_stream(
        [b for i, b in enumerate(clean) if i != POISON]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    with faults.armed(faults.FaultPlan(faults.InfLoss(POISON))):
        healed = _lr().fit_stream(
            clean, checkpoint_manager=mgr, checkpoint_interval=INTERVAL,
            recovery=_policy(),
        )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.recovery_summary["retries"] == {DATA_POISON: 1}


def test_systemic_divergence_aborts_with_context(tmp_path):
    """A magnitude divergence (finite but exploding) is systemic: no
    batch to quarantine, the default action aborts with a typed error."""
    def step(carry, batch, epoch):
        return {"w": carry["w"] * 100.0}, 0.1

    with pytest.raises(NumericsError) as ei:
        iterate(
            step, {"w": np.ones(3)},
            [np.zeros(1)] * 20,
            IterationConfig(
                TerminateOnMaxIter(2**31 - 1),
                sentinel=NumericsSentinel(max_abs=1e4, systemic_streak=2),
                recovery=_policy(),
            ),
        )
    assert ei.value.classification == SYSTEMIC
    assert "unrecoverable" in str(ei.value)


def test_systemic_stop_at_last_valid_returns_snapshot(tmp_path):
    """The stop_at_last_valid action: the run terminates with the
    newest valid (finite) snapshot instead of raising."""
    def step(carry, batch, epoch):
        return {"w": carry["w"] * 10.0, "version": carry["version"] + 1}, 0.1

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    result = iterate(
        step, {"w": np.ones(3), "version": 0},
        [np.zeros(1)] * 30,
        IterationConfig(
            TerminateOnMaxIter(2**31 - 1),
            checkpoint_interval=2, checkpoint_manager=mgr,
            sentinel=NumericsSentinel(max_abs=1e6, systemic_streak=2),
            recovery=_policy(
                actions={SYSTEMIC: "stop_at_last_valid"}
            ),
        ),
    )
    assert result.recovery["stopped_early"]
    assert np.isfinite(result.state["w"]).all()
    assert np.all(np.abs(result.state["w"]) <= 1e6)
    # The returned state IS a committed snapshot.
    assert result.state["version"] in mgr.all_epochs()


def test_quarantine_budget_escalates(tmp_path):
    """Every batch poisoned: the quarantine budget trips and the run
    escalates to the systemic action instead of quarantining the whole
    feed."""
    batches = [Table({"features": np.full((8, 3), np.nan),
                      "label": np.zeros(8)})
               for _ in range(10)]
    with pytest.raises(NumericsError) as ei:
        _lr().fit_stream(batches,
                         recovery=_policy(quarantine_budget=3))
    assert ei.value.classification == SYSTEMIC
    assert "budget" in str(ei.value)


def test_continue_stream_cannot_heal(tmp_path):
    """A live one-shot stream (stream_resume='continue') cannot be
    rolled back: the poison escalates to a loud systemic abort rather
    than silently dropping data."""
    with pytest.raises(NumericsError) as ei:
        _lr().fit_stream(iter(lr_batches(poison=POISON)),
                         stream_resume="continue",
                         recovery=_policy())
    assert "cannot be quarantined" in str(ei.value)


def test_one_shot_stream_inexact_verdict_cannot_pinpoint():
    """A one-shot generator feed with an interval-checked sentinel:
    the inexact verdict must NOT trigger a pinpoint retry (re-iterating
    the consumed stream would silently train on a truncated tail) —
    loud escalation instead."""
    def gen():
        yield from lr_batches(poison=POISON)

    with pytest.raises(NumericsError) as ei:
        _lr().fit_stream(gen(), sentinel=NumericsSentinel(interval=4),
                         recovery=_policy())
    assert ei.value.classification == SYSTEMIC
    assert "not replayable" in str(ei.value)


def test_tuple_feed_keeps_stream_semantics():
    """A TUPLE of batches trains exactly like the same list (the
    runtime treats bare tuples as static pytrees, so peek_stream must
    keep routing tuple feeds through the chained-iterator path)."""
    batches = lr_batches(n=4)
    from_list = _lr().fit_stream(list(batches))
    from_tuple = _lr().fit_stream(tuple(batches))
    np.testing.assert_array_equal(from_tuple.coefficient,
                                  from_list.coefficient)
    assert from_tuple.model_version == 4


def test_data_poison_action_overrides(tmp_path):
    """A user may opt poison verdicts OUT of healing: 'abort' raises
    the typed error (no quarantine), 'stop_at_last_valid' returns the
    newest valid snapshot's model."""
    with pytest.raises(NumericsError) as ei:
        _lr().fit_stream(
            lr_batches(poison=POISON),
            recovery=_policy(actions={DATA_POISON: "abort"}),
        )
    assert ei.value.classification == DATA_POISON
    assert "unrecoverable" in str(ei.value)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    stopped = _lr().fit_stream(
        lr_batches(poison=POISON), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL,
        recovery=_policy(actions={DATA_POISON: "stop_at_last_valid"}),
    )
    assert np.isfinite(stopped.coefficient).all()
    assert stopped.recovery_summary["stopped_early"]
    assert stopped.recovery_summary["quarantined"] == []  # no healing
    assert stopped.model_version == 4  # the newest pre-poison commit


def test_interval_sentinel_heals_with_min_retry_budget(tmp_path):
    """The pinpoint re-run's exact localization counts as PROGRESS:
    even max_retries=1 (the validator's minimum) heals one poisoned
    batch under an interval sentinel — the pinpoint rollback must not
    consume the no-progress budget."""
    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=POISON)) if i != POISON]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    healed = _lr().fit_stream(
        lr_batches(poison=POISON), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL,
        sentinel=NumericsSentinel(interval=4),
        recovery=_policy(max_retries=1),
    )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.recovery_summary["quarantined"] == [POISON]


def test_fresh_run_never_rolls_back_to_stale_snapshots(tmp_path):
    """A FRESH fit (resume=False) over a dirty checkpoint directory
    must not let recovery resurrect the previous run's model: rollback
    only targets snapshots this run committed (or restored at entry)."""
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    # Previous run over DIFFERENT data leaves stale ckpt-2..ckpt-12.
    _lr().fit_stream(lr_batches(seed=99), checkpoint_manager=mgr,
                     checkpoint_interval=INTERVAL)
    assert mgr.latest_epoch() == N_BATCHES

    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=POISON)) if i != POISON]
    )
    healed = _lr().fit_stream(
        lr_batches(poison=POISON), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL, recovery=_policy(),
    )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.model_version == golden.model_version == N_BATCHES - 1
    assert healed.recovery_summary["quarantined"] == [POISON]


def test_inplace_mutating_step_fresh_rollback_is_pristine():
    """A step that mutates its carry arrays IN PLACE must not corrupt
    the rollback-to-fresh template (no manager: every rollback is a
    fresh start) — the heal still quarantines exactly the poisoned
    batch and ends finite."""
    B, P = 8, 3
    rng = np.random.default_rng(0)
    batches = [rng.normal(size=(4, 3)) for _ in range(B)]
    batches[P] = np.full((4, 3), np.nan)

    def step(carry, batch, epoch):
        carry["w"] += np.asarray(batch).sum(0)  # in-place!
        return carry, float(carry["w"][0])

    result = iterate(
        step, {"w": np.zeros(3)}, batches,
        IterationConfig(TerminateOnMaxIter(2**31 - 1),
                        recovery=_policy()),
    )
    assert np.isfinite(result.state["w"]).all()
    assert result.recovery["quarantined"] == [P]
    expected = np.sum([b for i, b in enumerate(batches) if i != P],
                      axis=(0, 1))
    np.testing.assert_allclose(result.state["w"], expected)


def test_two_poisons_in_one_interval_window_heal_at_min_retries(tmp_path):
    """Two poisoned batches inside a single sentinel-interval window:
    each new quarantine counts as forward progress, so even
    max_retries=1 heals both (the quarantine_budget, not the retry
    count, bounds this axis)."""
    batches = lr_batches()
    for i in (POISON, POISON + 1):
        batches[i] = Table({"features": np.full((48, 5), np.nan),
                            "label": np.zeros(48)})
    golden = _lr().fit_stream(
        [b for i, b in enumerate(batches)
         if i not in (POISON, POISON + 1)]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    healed = _lr().fit_stream(
        batches, checkpoint_manager=mgr, checkpoint_interval=INTERVAL,
        sentinel=NumericsSentinel(interval=4),
        recovery=_policy(max_retries=1),
    )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.recovery_summary["quarantine_ranges"] == \
        [(POISON, POISON + 2)]


def test_interval_sentinel_pinpoints_before_quarantining(tmp_path):
    """An interval-4 sentinel detects the poison late (inexact): the
    engine rolls back WITHOUT quarantining, re-runs with per-epoch
    checks to pinpoint the batch, then quarantines exactly it — same
    final parity, one extra rollback."""
    golden = _lr().fit_stream(
        [b for i, b in enumerate(lr_batches(poison=POISON)) if i != POISON]
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    healed = _lr().fit_stream(
        lr_batches(poison=POISON), checkpoint_manager=mgr,
        checkpoint_interval=INTERVAL,
        sentinel=NumericsSentinel(interval=4),
        recovery=_policy(),
    )
    np.testing.assert_array_equal(healed.coefficient, golden.coefficient)
    assert healed.recovery_summary["quarantined"] == [POISON]
    assert healed.recovery_summary["rollbacks"] == 2  # pinpoint + heal


def test_rollback_discards_nonfinite_snapshot_from_disk(tmp_path):
    """A non-finite snapshot the rollback walk-back skips is DELETED,
    not left as the newest epoch on disk: a kill before the retry
    re-commits that epoch would otherwise hand the poisoned carry to
    the resumed run's finiteness-unaware ``restore_latest`` — which
    would then quarantine whatever batch happened to be current."""
    from flinkml_tpu.recovery.engine import RecoverySession

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    fine = {"w": np.ones(3)}
    mgr.save(fine, 2)
    mgr.save({"w": np.array([1.0, np.nan, 1.0])}, 4)  # interval-window
    mgr.wait()

    session = RecoverySession(
        _policy(), mgr, NumericsSentinel(), QuarantineLedger(),
        {"w": np.zeros(3)}, replayable=True, initially_restored=True,
    )
    state, epoch, restored = session._rollback()
    assert restored and epoch == 2
    np.testing.assert_array_equal(state["w"], fine["w"])
    # The poisoned commit is gone: a kill-and-resume lands on the
    # finite snapshot, never the NaN carry.
    assert mgr.all_epochs() == [2]
    _, latest = mgr.restore_latest(like=fine)
    assert latest == 2


def test_read_extra_is_structure_independent(tmp_path):
    """``read_extra`` returns a snapshot's sidecar records (here the
    quarantine ledger) without a carry-shaped ``like`` — what the
    chaos soak's disk-ledger invariant reads."""
    from flinkml_tpu.iteration.checkpoint import CheckpointIntegrityError

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    ledger = QuarantineLedger([POISON])
    mgr.save({"anything": np.ones(2), "nested": {"n": np.zeros(1)}}, 3,
             extra={"quarantine": ledger.to_json_dict()})
    mgr.wait()
    recorded = mgr.read_extra(3).get("quarantine")
    assert QuarantineLedger.from_json_dict(recorded).indices() == [POISON]
    # a damaged manifest raises typed, never an empty dict
    meta = tmp_path / "ckpt" / "ckpt-3" / "meta.json"
    meta.write_text("{not json")
    with pytest.raises(CheckpointIntegrityError):
        mgr.read_extra(3)


# ---------------------------------------------------------------------------
# Publish / serve refusal
# ---------------------------------------------------------------------------

def test_registry_refuses_nonfinite_publish(tmp_path):
    from flinkml_tpu.serving import ModelRegistry

    bad = _lr().fit_stream(lr_batches(poison=0, n=2))
    assert not np.isfinite(bad.coefficient).all()
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(NonFiniteModelError, match="refusing to publish"):
        reg.publish(bad)
    assert reg.versions() == []  # nothing written
    # explicit escape hatch still writes
    assert reg.publish(bad, check_finite=False) == 1


def test_engine_refuses_nonfinite_model_and_keeps_serving(tmp_path):
    from flinkml_tpu.serving import (
        ModelRegistry,
        ServingConfig,
        ServingEngine,
    )

    good = _lr().fit_stream(lr_batches(n=3))
    bad = _lr().fit_stream(lr_batches(poison=0, n=2))
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(good)
    x = np.asarray(lr_batches(n=1)[0].column("features"))[:4]
    engine = ServingEngine(
        reg, Table({"features": x}),
        config=ServingConfig(max_batch_rows=64, max_wait_ms=1.0),
    ).start()
    try:
        v1 = engine.predict({"features": x}).version
        assert v1 == 1
        # A bypassed bad publish arrives via follow; the swap is refused
        # (isolated listener error) and v1 keeps serving.
        engine.follow_registry()
        with pytest.warns(RuntimeWarning, match="listener"):
            reg.publish(bad, check_finite=False)
        assert engine.active_version == 1
        assert engine.predict({"features": x}).version == 1
    finally:
        engine.stop()


def test_recovery_metrics_exposed():
    from flinkml_tpu.utils.metrics import metrics

    before = dict(
        metrics.group("recovery").snapshot()["counters"]
    )
    _lr().fit_stream(lr_batches(poison=POISON), recovery=_policy())
    g = metrics.group("recovery").snapshot()
    assert g["counters"]["rollbacks_total"] >= \
        before.get("rollbacks_total", 0) + 1
    assert g["counters"]["quarantined_batches"] >= \
        before.get("quarantined_batches", 0) + 1
    assert "time_to_recover_p50_ms" in g["gauges"]
    assert "time_to_recover_p99_ms" in g["gauges"]
    labeled = metrics.group(
        "recovery", labels={"class": DATA_POISON}
    ).snapshot()
    assert labeled["counters"].get("retries_total", 0) >= 1
    text = metrics.render_text()
    assert ('flinkml_retries_total{group="recovery",class="data_poison"}'
            in text)
    assert 'flinkml_rollbacks_total{group="recovery"}' in text


# ---------------------------------------------------------------------------
# Chaos soak + shrink
# ---------------------------------------------------------------------------

def test_fuzzplan_is_deterministic():
    fz = faults.FuzzPlan(seed=11, budget=30, horizon=10)
    a = [f.describe() for f in fz.sample(4).faults]
    b = [f.describe() for f in faults.FuzzPlan(seed=11, horizon=10)
         .sample(4).faults]
    assert a == b
    c = [f.describe() for f in faults.FuzzPlan(seed=12, horizon=10)
         .sample(4).faults]
    assert [f.describe() for f in fz.sample(5).faults] != a or c != a
    assert len(list(fz.schedules())) == 30
    with pytest.raises(ValueError):
        faults.FuzzPlan(seed=1, seams=("no.such.seam",))


def test_fault_plan_json_roundtrip():
    plan = faults.FaultPlan(
        faults.NaNGrad(3), faults.TornWrite(4),
        faults.CorruptSnapshot(2, "manifest"),
        faults.RaiseAtRead(5, "data.prefetch"),
    )
    js = faults.plan_to_json(plan, extra={"seed": 1})
    rt = faults.plan_from_json(js)
    assert [f.describe() for f in rt.faults] == \
        [f.describe() for f in plan.faults]
    assert json.loads(js)["seed"] == 1
    # fresh instances: fired flags reset
    assert not any(getattr(f, "fired", False) for f in rt.faults)


def test_chaos_soak_small_budget_green():
    from flinkml_tpu.recovery.fuzz import run_soak

    report = run_soak(seed=7, budget=8)
    assert report.ok, [
        (r.index, r.faults, r.failures) for r in report.failures
    ]
    assert len(report.results) == 8


def test_worker_soak_restarts_across_process_boundary():
    """The ``cluster.worker`` seam in the soak: schedules draw REAL
    ``os._exit`` worker crashes, each trainer incarnation is a child
    process, and the orchestrator-restart invariants (no silent fresh
    start, ledger parity, bit-exact coefficients vs golden) hold with
    nothing shared between incarnations but the checkpoint directory."""
    from flinkml_tpu.recovery.fuzz import run_worker_soak

    report = run_worker_soak(seed=7, budget=3)
    assert report.ok, [
        (r.index, r.faults, r.failures) for r in report.failures
    ]
    assert len(report.results) == 3
    # At least one schedule actually crossed the boundary: a hard exit
    # answered by a restart (seed 7's draws include WorkerCrash).
    assert sum(r.restarts for r in report.results) >= 1


def test_worker_schedule_crash_then_poison_heals(tmp_path):
    """One deterministic schedule: a WorkerCrash hard-exits the child
    mid-stream AND a NaNGrad poisons a later batch — the restarted
    incarnation resumes (not a fresh start), quarantines the poison,
    and lands bit-exactly on the golden run minus that batch."""
    from flinkml_tpu.recovery.fuzz import GoldenCache, run_worker_schedule

    golden = GoldenCache(0)
    plan = faults.FaultPlan(
        faults.WorkerCrash(at=4, key="epoch", exit_code=23,
                           marker=str(tmp_path / "crash.marker")),
        faults.NaNGrad(6),
    )
    result, failures, restarts = run_worker_schedule(plan, golden)
    assert not failures, failures
    assert restarts == 1
    assert result["quarantined"] == [6]
    assert result["model_version"] == 9  # 10 batches - 1 quarantined


def test_shrink_minimizes_to_the_poison(tmp_path):
    from flinkml_tpu.recovery.fuzz import (
        GoldenCache,
        run_schedule,
        shrink_schedule,
    )

    golden = GoldenCache(0)
    plan = faults.FaultPlan(faults.TornWrite(3), faults.PoisonBatch(5),
                            faults.RaiseAtEpoch(7))
    _, failures, _ = run_schedule(plan, golden, self_heal=False)
    assert failures  # un-healed poison: the seeded failing schedule
    minimal = shrink_schedule(
        plan,
        lambda p: bool(run_schedule(p, golden, self_heal=False)[1]),
    )
    assert [f.describe() for f in minimal.faults] == \
        ["PoisonBatch(at_batch=5)"]
    # ... the written repro replays, and the SAME schedule heals under
    # the recovery policy (the soak invariant).
    replay = faults.plan_from_json(faults.plan_to_json(minimal))
    _, healed_failures, _ = run_schedule(replay, golden, self_heal=True)
    assert not healed_failures
