"""Knn tests — mirrors the reference's KnnTest."""

import numpy as np
import pytest

from flinkml_tpu.models import Knn, KnnModel
from flinkml_tpu.table import Table


@pytest.fixture
def train_table(rng):
    x0 = rng.normal(loc=(0, 0), scale=0.5, size=(40, 2))
    x1 = rng.normal(loc=(6, 6), scale=0.5, size=(40, 2))
    x = np.concatenate([x0, x1])
    y = np.concatenate([np.zeros(40), np.ones(40) * 3.0])  # labels 0.0 / 3.0
    return Table({"features": x, "label": y})


def test_param_defaults():
    knn = Knn()
    assert knn.get_k() == 5
    assert knn.get_features_col() == "features"
    assert knn.get_label_col() == "label"


def test_fit_predict(train_table):
    model = Knn().fit(train_table)
    queries = Table({"features": np.array([[0.2, 0.1], [5.9, 6.2], [-0.5, 0.3]])})
    (out,) = model.transform(queries)
    np.testing.assert_array_equal(out["prediction"], [0.0, 3.0, 0.0])


def test_against_sklearn(train_table, rng):
    from sklearn.neighbors import KNeighborsClassifier

    q = rng.normal(loc=(3, 3), scale=3.0, size=(50, 2))
    model = Knn().set_k(7).fit(train_table)
    (out,) = model.transform(Table({"features": q}))
    sk = KNeighborsClassifier(n_neighbors=7).fit(
        train_table["features"], train_table["label"]
    )
    agreement = np.mean(out["prediction"] == sk.predict(q))
    assert agreement >= 0.95  # ties may break differently


def test_k_larger_than_train_votes_among_all(rng):
    """Reference parity: KnnModel's top-k queue holds all n points when
    k > n — it votes among everything rather than raising. An actual
    majority class (5 vs 8) gives the assertion power: a broken clamp
    (e.g. k=0 voting) would predict class 0 instead."""
    x = rng.normal(size=(13, 2))
    y = np.array([0.0] * 5 + [1.0] * 8)
    model = Knn().set_k(200).fit(Table({"features": x, "label": y}))
    (out,) = model.transform(Table({"features": np.zeros((3, 2))}))
    np.testing.assert_array_equal(out["prediction"], [1.0, 1.0, 1.0])


def test_chunked_queries(train_table, rng):
    model = Knn().fit(train_table)
    model_chunked = Knn().fit(train_table)
    KnnModel.CHUNK = 7  # force multiple chunks
    try:
        q = Table({"features": rng.normal(size=(23, 2))})
        (a,) = model.transform(q)
        (b,) = model_chunked.transform(q)
        np.testing.assert_array_equal(a["prediction"], b["prediction"])
    finally:
        KnnModel.CHUNK = 4096


def test_save_load(tmp_path, train_table):
    model = Knn().set_k(3).fit(train_table)
    p = str(tmp_path / "knn")
    model.save(p)
    loaded = KnnModel.load(p)
    assert loaded.get_k() == 3
    q = Table({"features": np.array([[0.0, 0.0], [6.0, 6.0]])})
    np.testing.assert_array_equal(
        model.transform(q)[0]["prediction"], loaded.transform(q)[0]["prediction"]
    )


def test_model_data_round_trip(train_table):
    model = Knn().fit(train_table)
    other = KnnModel().set_model_data(*model.get_model_data())
    q = Table({"features": np.array([[6.1, 5.9]])})
    assert other.transform(q)[0]["prediction"][0] == 3.0
