import numpy as np
import pytest

from flinkml_tpu.table import Table


def test_basic_columns():
    t = Table({"x": np.arange(10), "y": np.ones((10, 3))})
    assert t.num_rows == 10
    assert t.column_names == ["x", "y"]
    assert t["y"].shape == (10, 3)


def test_row_count_mismatch():
    with pytest.raises(ValueError):
        Table({"x": np.arange(10), "y": np.arange(9)})


def test_from_rows_and_to_rows():
    rows = [{"a": 1, "b": [1.0, 2.0]}, {"a": 2, "b": [3.0, 4.0]}]
    t = Table.from_rows(rows)
    assert t.num_rows == 2
    assert t["b"].shape == (2, 2)
    back = t.to_rows()
    assert back[1]["a"] == 2


def test_select_drop_rename_with_column():
    t = Table({"x": np.arange(5), "y": np.arange(5) * 2})
    assert t.select("x").column_names == ["x"]
    assert t.drop("x").column_names == ["y"]
    assert t.rename({"x": "z"}).column_names == ["z", "y"]
    t2 = t.with_column("w", np.zeros(5))
    assert "w" in t2 and "w" not in t


def test_slice_take_concat():
    t = Table({"x": np.arange(10)})
    assert t.slice(2, 5).num_rows == 3
    assert np.array_equal(t.take(np.array([1, 3]))["x"], [1, 3])
    assert t.concat(t).num_rows == 20


def test_batches():
    t = Table({"x": np.arange(10)})
    sizes = [b.num_rows for b in t.batches(4)]
    assert sizes == [4, 4, 2]
    sizes = [b.num_rows for b in t.batches(4, drop_remainder=True)]
    assert sizes == [4, 4]


def test_ragged_object_column():
    t = Table({"v": [[1, 2], [3, 4, 5]]})
    assert t["v"].dtype == object
    assert list(t["v"][1]) == [3, 4, 5]
