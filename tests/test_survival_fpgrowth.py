"""AFTSurvivalRegression + FPGrowth."""

import numpy as np
import pytest

from flinkml_tpu.models import (
    AFTSurvivalRegression,
    AFTSurvivalRegressionModel,
    FPGrowth,
    FPGrowthModel,
)
from flinkml_tpu.models.fpgrowth import fpgrowth
from flinkml_tpu.models.text import _object_column
from flinkml_tpu.table import Table


# -- AFT ---------------------------------------------------------------------

def _weibull_data(n=2000, seed=0, censor_frac=0.3):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    beta = np.asarray([0.8, -0.5, 0.2])
    sigma = 0.5
    # log T = beta.x + sigma * extreme_value
    eps = np.log(rng.exponential(size=n))       # standard Gumbel(min)-ish
    t_true = np.exp(x @ beta + sigma * eps)
    c_time = np.quantile(t_true, 1 - censor_frac) * rng.uniform(0.5, 1.5, n)
    observed = t_true <= c_time
    t = np.where(observed, t_true, c_time)
    return x, t, observed.astype(np.float64), beta, sigma


def _aft(**kw):
    m = (
        AFTSurvivalRegression().set_max_iter(1500).set_learning_rate(0.05)
        .set_global_batch_size(1024).set_tol(0.0).set_seed(0)
    )
    for name, v in kw.items():
        getattr(m, f"set_{name}")(v)
    return m


def test_aft_recovers_weibull_parameters():
    x, t, censor, beta, sigma = _weibull_data()
    table = Table({"features": x, "label": t, "censor": censor})
    model = _aft().fit(table)
    np.testing.assert_allclose(model.coefficients, beta, atol=0.1)
    assert abs(model.scale - sigma) < 0.1
    # Median predictions track the observed times; the ceiling is set
    # by the irreducible sigma*Gumbel noise (sd ~0.64 vs signal sd
    # ~0.96 -> max corr ~0.83) and the censoring selection effect.
    (out,) = model.transform(table)
    finite = censor == 1.0
    corr = np.corrcoef(np.log(out["prediction"][finite]),
                       np.log(t[finite]))[0, 1]
    assert corr > 0.65, corr


def test_aft_quantiles_and_persistence(tmp_path):
    x, t, censor, _, _ = _weibull_data(n=500, seed=1)
    table = Table({"features": x, "label": t, "censor": censor})
    model = _aft(max_iter=300, quantile_probabilities=[0.25, 0.5, 0.75]).fit(table)
    (out,) = model.transform(table)
    q = out["quantiles"]
    assert q.shape == (500, 3)
    assert np.all(np.diff(q, axis=1) > 0)       # quantiles increase
    np.testing.assert_allclose(q[:, 1], out["prediction"], rtol=1e-9)
    model.save(str(tmp_path / "aft"))
    loaded = AFTSurvivalRegressionModel.load(str(tmp_path / "aft"))
    np.testing.assert_allclose(loaded.coefficients, model.coefficients)
    assert loaded.scale == model.scale


def test_aft_validation():
    table = Table({
        "features": np.ones((3, 2)),
        "label": np.asarray([1.0, -1.0, 2.0]),
        "censor": np.asarray([1.0, 1.0, 1.0]),
    })
    with pytest.raises(ValueError, match="positive"):
        _aft().fit(table)
    table2 = Table({
        "features": np.ones((3, 2)),
        "label": np.ones(3),
        "censor": np.zeros(3),
    })
    with pytest.raises(ValueError, match="censored"):
        _aft().fit(table2)


# -- FPGrowth ----------------------------------------------------------------

BASKETS = [
    ["bread", "milk"],
    ["bread", "diapers", "beer", "eggs"],
    ["milk", "diapers", "beer", "cola"],
    ["bread", "milk", "diapers", "beer"],
    ["bread", "milk", "diapers", "cola"],
]


def test_fpgrowth_matches_bruteforce():
    from itertools import combinations

    out = fpgrowth(BASKETS, min_support=0.4)    # min_count = 2
    # Brute-force reference.
    items = sorted({it for b in BASKETS for it in b})
    expected = {}
    for r in range(1, len(items) + 1):
        for combo in combinations(items, r):
            cnt = sum(1 for b in BASKETS if set(combo) <= set(b))
            if cnt >= 2:
                expected[tuple(sorted(combo))] = cnt
    assert out == expected


def test_fpgrowth_rules_and_transform(tmp_path):
    t = Table({"items": _object_column(BASKETS)})
    model = (
        FPGrowth().set_min_support(0.4).set_min_confidence(0.7).fit(t)
    )
    fi = model.freq_itemsets()
    assert fi.num_rows > 0
    assert int(fi["freq"][0]) >= int(fi["freq"][fi.num_rows - 1])

    rules = model.association_rules()
    pairs = {
        (tuple(a), c): conf
        for a, c, conf in zip(rules["antecedent"], rules["consequent"],
                              rules["confidence"])
    }
    # beer appears in 3 baskets, all containing diapers: conf 1.0.
    assert pairs[(("beer",), "diapers")] == pytest.approx(1.0)

    (pred,) = model.transform(Table({"items": _object_column([["beer"]])}))
    assert "diapers" in pred["prediction"][0]
    # Items already in the basket are not re-predicted.
    (pred2,) = model.transform(
        Table({"items": _object_column([["beer", "diapers"]])})
    )
    assert "diapers" not in pred2["prediction"][0]

    model.save(str(tmp_path / "fp"))
    loaded = FPGrowthModel.load(str(tmp_path / "fp"))
    (pred3,) = loaded.transform(Table({"items": _object_column([["beer"]])}))
    assert pred3["prediction"][0] == pred["prediction"][0]
    clone = FPGrowthModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    assert clone.freq_itemsets().num_rows == fi.num_rows


def test_fpgrowth_random_corpus_matches_bruteforce():
    from itertools import combinations

    rng = np.random.default_rng(2)
    universe = [f"i{j}" for j in range(8)]
    baskets = [
        list(rng.choice(universe, size=rng.integers(1, 6), replace=False))
        for _ in range(60)
    ]
    out = fpgrowth(baskets, min_support=0.15)
    min_count = int(np.ceil(0.15 * 60))
    expected = {}
    for r in range(1, 6):
        for combo in combinations(universe, r):
            cnt = sum(1 for b in baskets if set(combo) <= set(b))
            if cnt >= min_count:
                expected[tuple(sorted(combo))] = cnt
    assert out == expected


def test_fpgrowth_empty_model_roundtrip():
    t = Table({"items": _object_column([["a"], ["b"], ["c"]])})
    model = FPGrowth().set_min_support(0.9).fit(t)
    assert model.freq_itemsets().num_rows == 0
    clone = FPGrowthModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    assert clone.freq_itemsets().num_rows == 0
    assert clone._n_baskets == 3
    (pred,) = clone.transform(t)
    assert all(p == [] for p in pred["prediction"])


def test_aft_rejects_bad_quantile_probabilities():
    x = np.ones((4, 1))
    table = Table({"features": x, "label": np.ones(4),
                   "censor": np.ones(4)})
    model = _aft(max_iter=5, quantile_probabilities=[0.5, 1.5]).fit(table)
    with pytest.raises(ValueError, match="quantileProbabilities"):
        model.transform(table)


def test_fpgrowth_rule_cache_tracks_confidence():
    t = Table({"items": _object_column(BASKETS)})
    model = FPGrowth().set_min_support(0.4).set_min_confidence(0.99).fit(t)
    (strict,) = model.transform(Table({"items": _object_column([["beer"]])}))
    model.set_min_confidence(0.5)
    (loose,) = model.transform(Table({"items": _object_column([["beer"]])}))
    assert len(loose["prediction"][0]) >= len(strict["prediction"][0])
    assert "diapers" in loose["prediction"][0]


def test_fpgrowth_save_rejects_nul_items():
    t = Table({"items": _object_column([["a\x00b", "c"], ["a\x00b", "c"]])})
    model = FPGrowth().set_min_support(0.5).fit(t)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="NUL"):
        model.save("/tmp/never-created-fp")


def test_prefixspan_matches_bruteforce():
    from itertools import product as iproduct

    from flinkml_tpu.models import PrefixSpan
    from flinkml_tpu.models.prefixspan import prefixspan

    seqs = [
        ["a", "b", "c", "a"],
        ["a", "c", "b"],
        ["b", "a", "c"],
        ["a", "b"],
    ]
    out = prefixspan(seqs, min_support=0.5, max_length=3)   # min_count 2

    def is_subseq(pat, seq):
        it = iter(seq)
        return all(x in it for x in pat)

    items = sorted({x for s in seqs for x in s})
    expected = {}
    for L in range(1, 4):
        for pat in iproduct(items, repeat=L):
            cnt = sum(1 for s in seqs if is_subseq(pat, s))
            if cnt >= 2:
                expected[pat] = cnt
    assert out == expected

    (t_out,) = (
        PrefixSpan().set_min_support(0.5).set_max_pattern_length(3)
        .transform(Table({"sequence": _object_column(seqs)}))
    )
    assert t_out.num_rows == len(expected)
    assert int(t_out["freq"][0]) == max(expected.values())
    # ("a", "b") must appear: ordered subsequence of 3 sequences.
    pats = {tuple(p) for p in t_out["sequence"]}
    assert ("a", "b") in pats and ("b", "a") in pats


def test_prefixspan_max_length_and_empty():
    from flinkml_tpu.models import PrefixSpan
    from flinkml_tpu.models.prefixspan import prefixspan

    seqs = [["x", "y", "z"]] * 3
    out = prefixspan(seqs, 0.9, max_length=2)
    assert max(len(k) for k in out) == 2
    (empty,) = (
        PrefixSpan().set_min_support(0.9).transform(
            Table({"sequence": _object_column([["a"], ["b"], ["c"]])})
        )
    )
    assert empty.num_rows == 0


def test_prefixspan_deep_patterns_no_recursion_limit():
    from flinkml_tpu.models.prefixspan import prefixspan

    out = prefixspan([["x"] * 1500] * 2, 0.5, max_length=1500)
    assert max(len(k) for k in out) == 1500


def test_aft_intercept_absorbs_log_time_offset(tmp_path):
    # ADVICE r2: Spark AFT fits an intercept by default; on data whose
    # log survival times have nonzero mean the offset must land in the
    # intercept, not bias the coefficients/scale.
    x, t, censor, beta, sigma = _weibull_data()
    offset = 2.0
    table = Table({"features": x, "label": t * np.exp(offset),
                   "censor": censor})
    model = _aft().fit(table)
    np.testing.assert_allclose(model.coefficients, beta, atol=0.1)
    assert abs(model.intercept - offset) < 0.1, model.intercept
    assert abs(model.scale - sigma) < 0.1
    # Round-trips through save/load and model-data tables.
    model.save(str(tmp_path / "aft_i"))
    loaded = AFTSurvivalRegressionModel.load(str(tmp_path / "aft_i"))
    assert abs(loaded.intercept - model.intercept) < 1e-12
    m2 = AFTSurvivalRegressionModel()
    m2.copy_params_from(model)
    m2.set_model_data(*model.get_model_data())
    assert abs(m2.intercept - model.intercept) < 1e-12


def test_aft_fit_intercept_false_preserves_old_behavior():
    x, t, censor, beta, sigma = _weibull_data()
    table = Table({"features": x, "label": t, "censor": censor})
    model = _aft(fit_intercept=False).fit(table)
    assert model.intercept == 0.0
    np.testing.assert_allclose(model.coefficients, beta, atol=0.1)
