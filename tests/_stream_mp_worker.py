"""Multi-process streamed-fit worker, launched by test_distributed.py.

Exercises the round-4 multi-process out-of-core path end to end on a real
jax.distributed (Gloo) mesh: per-process stream partitions, the agreed
SPMD replay schedule (fixed height + dummy steps), pooled init sampling,
bounded in-flight dispatch, and rank-0-write + barrier checkpointing —
the reference's partitioned-stream training (`ReplayOperator.java:62-250`
over per-subtask partitions) without a single-controller restriction.

Usage: python _stream_mp_worker.py <port> <process_id> <num_processes> <workdir>
Prints ``STREAM_OK <pid>`` on success. Writes ``result_<pid>.npz`` with
the fitted models for the parent to cross-check.
"""

import os
import sys

port, pid, nproc, workdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _stream_mp_common as C  # noqa: E402

from flinkml_tpu.iteration.checkpoint import CheckpointManager  # noqa: E402
from flinkml_tpu.iteration.datacache import cache_stream  # noqa: E402
from flinkml_tpu.models._linear_sgd import (  # noqa: E402
    train_linear_model_stream,
)
from flinkml_tpu.models.kmeans import train_kmeans_stream  # noqa: E402
from flinkml_tpu.parallel import DeviceMesh, init_distributed  # noqa: E402

idx, count = init_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc,
    process_id=pid,
)
assert (idx, count) == (pid, nproc), (idx, count)

mesh = DeviceMesh()
batches = C.local_batches(pid, nproc)

# --- 1. linear streamed fit from a durable local cache + checkpointing
# into the SHARED directory (rank 0 writes, everyone barriers).
cache = cache_stream(iter(batches))
ckpt_dir = os.path.join(workdir, "ckpt_linear")
os.makedirs(ckpt_dir, exist_ok=True)
manager = CheckpointManager(ckpt_dir)
coef = train_linear_model_stream(
    cache, mesh=mesh, checkpoint_manager=manager, checkpoint_interval=2,
    **C.LINEAR_HP,
)
manager.close()
assert np.all(np.isfinite(coef)), coef

# --- 2. resume from the shared checkpoint: the run is already terminal,
# so a resumed fit must return the identical coefficient without
# retraining (exact-resume contract on a multi-process mesh).
manager2 = CheckpointManager(ckpt_dir)
coef_resumed = train_linear_model_stream(
    cache, mesh=mesh, checkpoint_manager=manager2, resume=True,
    **C.LINEAR_HP,
)
manager2.close()
assert np.array_equal(coef, coef_resumed), (coef, coef_resumed)

# --- 3. KMeans streamed fit, fixed init (cross-checked vs single-process
# by the parent) and pooled random init (must agree across ranks).
x_batches = [{"x": b["x"]} for b in batches]
cents = train_kmeans_stream(
    iter(x_batches), k=C.K_CLUSTERS, mesh=mesh,
    initial_centroids=C.initial_centroids(), **C.KMEANS_HP,
)
cents_rand = train_kmeans_stream(
    iter(x_batches), k=C.K_CLUSTERS, mesh=mesh, **C.KMEANS_HP,
)
assert np.all(np.isfinite(cents)) and np.all(np.isfinite(cents_rand))

# --- 3b. an EMPTY local partition is legal (that rank feeds only dummy
# steps; pooled init draws entirely from the non-empty ranks).
cents_empty = train_kmeans_stream(
    iter(x_batches if pid == 0 else []),
    k=C.K_CLUSTERS, mesh=mesh, **C.KMEANS_HP,
)
assert np.all(np.isfinite(cents_empty))

# --- 4. GMM streamed fit: pooled moments + pooled init reservoir; must
# agree across ranks and recover the synthetic components (checked by
# the parent).
from flinkml_tpu.models import GaussianMixture  # noqa: E402
from flinkml_tpu.table import Table  # noqa: E402

gm_tables = [Table({"features": b}) for b in C.gmm_local_batches(pid, nproc)]
gm = (
    GaussianMixture(mesh=mesh).set_k(2).set_max_iter(20).set_tol(0.0)
    .set_seed(5).set_covariance_type("diag").fit(iter(gm_tables))
)

# --- 5. streamed-Adam runner (MLP): agreed per-chunk step schedule +
# agreed label dtype; ranks must agree bit-exactly and the model must
# learn the separable target (checked by the parent).
from flinkml_tpu.models.mlp import MLPClassifier  # noqa: E402

x_all, y_all = C.global_data()
sl = C.slice_for(pid, nproc)
bs = C.BATCH_SIZES[pid]
mlp_tables = [
    Table({
        "features": x_all[sl][i : i + bs],
        "label": (x_all[sl][i : i + bs, 0]
                  + x_all[sl][i : i + bs, 1] > 0).astype(np.float64),
    })
    for i in range(0, x_all[sl].shape[0], bs)
]
mlp = (
    MLPClassifier(mesh=mesh)
    .set_layers([C.N_FEATURES, 8, 2]).set_max_iter(8)
    .set_global_batch_size(64).set_learning_rate(0.05)
    .set_tol(0.0).set_seed(0)
    .fit(iter(mlp_tables))
)
(mlp_out,) = mlp.transform(Table({"features": x_all}))
mlp_acc = float(
    (mlp_out.column("prediction") == (x_all[:, 0] + x_all[:, 1] > 0)).mean()
)

# --- 6. GBT streamed fit: pooled bin edges + gathered base score +
# rank-local per-row state + globally psum'd histograms. Ranks must
# agree on the forest structure bit-exactly.
from flinkml_tpu.models import GBTClassifier  # noqa: E402

gbt_tables = [
    Table({
        "features": t.column("features"),
        "label": (np.asarray(t.column("features"))[:, 0]
                  + np.asarray(t.column("features"))[:, 1] > 0)
        .astype(np.float64),
    })
    for t in mlp_tables
]
gbt = (
    GBTClassifier(mesh=mesh).set_num_trees(3).set_max_depth(2)
    .set_max_bins(16).set_learning_rate(0.3).set_seed(0)
    .fit(iter(gbt_tables))
)
(gbt_out,) = gbt.transform(Table({"features": x_all}))
gbt_acc = float(
    (gbt_out.column("prediction") == (x_all[:, 0] + x_all[:, 1] > 0)).mean()
)

# --- 7. PCA streamed fit: cache-less lockstep single pass (agreed shift,
# per-step height agreement, dummy steps on the drained rank).
from flinkml_tpu.models.pca import PCA  # noqa: E402

pca = (
    PCA(mesh=mesh).set_k(3).set_input_col("features")
    .fit(iter(Table({"features": t.column("features")})
              for t in mlp_tables))
)

# --- 8. LDA streamed fit (round-4 multi-process: per-process corpus
# partitions through the agreed replay schedule; topics replicated).
from flinkml_tpu.models.lda import LDA  # noqa: E402

lda = (
    LDA(mesh=mesh).set_k(2).set_max_iter(8).set_seed(3)
    .fit(iter(Table({"features": b})
              for b in C.lda_local_batches(pid, nproc)))
)
lda_topics = lda.topics_matrix

# --- 9. ALS streamed fit (round-4 multi-process: per-process ratings
# partitions; id vocabularies unioned through the device fabric, agreed
# chunk schedule with dummy fills; factors replicated).
from flinkml_tpu.models.als import ALS  # noqa: E402

als = (
    ALS(mesh=mesh).set_rank(C.ALS_RANK).set_max_iter(10)
    .set_reg_param(0.01).set_seed(0)
    .fit(iter(Table(b) for b in C.als_local_batches(pid, nproc)))
)
au, ai, ar = C.als_global_ratings()
pred = np.sum(
    als._user_factors[np.searchsorted(als._user_ids, au)]
    * als._item_factors[np.searchsorted(als._item_ids, ai)],
    axis=1,
)
als_rmse = float(np.sqrt(np.mean((pred - ar) ** 2)))

# --- 9b. an EMPTY ratings partition is legal: the empty rank adopts the
# agreed vocabularies and dispatches only dummy chunks; factors still
# replicate.
als_empty = (
    ALS(mesh=mesh).set_rank(C.ALS_RANK).set_max_iter(2)
    .set_reg_param(0.01).set_seed(0)
    .fit(iter(Table(b) for b in
              (C.als_local_batches(pid, nproc) if pid == 0 else [])))
)
als_empty_uf = als_empty._user_factors
als_empty_if = als_empty._item_factors

# --- 10. Online (unbounded) operators, round-4 multi-process: FTRL and
# decayed KMeans run psum'd lockstep steps per arriving batch (uneven
# per-rank batch counts force the zero-weight dummy tail); the scaler
# merges per-rank moments exactly at stream end.
from flinkml_tpu.models.online_kmeans import OnlineKMeans  # noqa: E402
from flinkml_tpu.models.online_logistic_regression import (  # noqa: E402
    OnlineLogisticRegression,
)
from flinkml_tpu.models.online_scaler import (  # noqa: E402
    OnlineStandardScaler,
)

olr = (
    OnlineLogisticRegression(mesh=mesh).set_alpha(0.5).set_beta(0.1)
    .set_reg(0.001).set_elastic_net(0.5)
    .fit_stream(iter(Table({"features": b["x"], "label": b["y"]})
                     for b in batches))
)
olr_coef = olr._coefficient
olr_version = olr._model_version

okm = (
    OnlineKMeans(mesh=mesh).set_k(C.K_CLUSTERS).set_seed(7)
    .set_decay_factor(0.9)
    .fit_stream(iter(Table({"features": b["x"]}) for b in batches))
)
okm_cents = okm._centroids

osc = OnlineStandardScaler().set_input_col("features").fit_stream(
    iter(Table({"features": b["x"]}) for b in batches)
)
osc_mean = osc._mean
osc_std = osc._std
osc_version = osc.model_version
# Exactness: the merged moments equal the GLOBAL dataset's f64 moments
# (the scaler accumulates in f64; Chan merge is split-invariant to fp
# rounding).
x_g64 = C.global_data()[0].astype(np.float64)
np.testing.assert_allclose(
    osc_mean, x_g64.mean(axis=0), rtol=1e-9, atol=1e-12
)
np.testing.assert_allclose(
    osc_std, x_g64.std(axis=0), rtol=1e-9, atol=1e-12
)

# --- 11. Word2Vec streamed fit (round-4 multi-process: per-process doc
# partitions; STRING vocabulary unioned through the device fabric as
# UTF-8 bytes; agreed-step SGNS dispatches with zero-weight dummies).
from flinkml_tpu.models.word2vec import Word2Vec  # noqa: E402

w2v_doc_batches = C.w2v_local_docs(pid, nproc)
w2v = (
    Word2Vec(mesh=mesh).set_input_col("tok").set_vector_size(8)
    .set_min_count(1).set_max_iter(8).set_learning_rate(2.0)
    .set_batch_size(512).set_seed(0)
    .fit(iter(
        Table({"tok": np.asarray(b, dtype=object)})
        for b in w2v_doc_batches
    ))
)
w2v_vocab = np.asarray(w2v.vocabulary, dtype=str)
w2v_vecs = w2v.vectors

# --- 11b. an EMPTY document partition is legal: the empty rank adopts
# the agreed (unioned) vocabulary and feeds only zero-weight dummy
# chunks; vectors still replicate.
w2v_empty = (
    Word2Vec(mesh=mesh).set_input_col("tok").set_vector_size(8)
    .set_min_count(1).set_max_iter(2).set_seed(0)
    .fit(iter(
        Table({"tok": np.asarray(b, dtype=object)})
        for b in (w2v_doc_batches if pid == 0 else [])
    ))
)
w2v_empty_vecs = w2v_empty.vectors

# --- round 5: sparse-native CSR streaming across ranks — per-process
# SparseVector partitions (uneven sizes, uneven nnz -> agreed global ELL
# width + dummy tail), cross-checked vs single-process by the parent.
from flinkml_tpu.models.logistic_regression import (  # noqa: E402
    LogisticRegression,
)

sp_est = LogisticRegression(mesh=mesh)
for k, v in C.SPARSE_HP.items():
    getattr(sp_est, f"set_{k}")(v)
sp_coef = sp_est.fit(iter(C.sparse_local_tables(pid, nproc)))._coefficient

np.savez(
    os.path.join(workdir, f"result_{pid}.npz"),
    coef=coef, sp_coef=sp_coef, cents=cents, cents_rand=cents_rand,
    cents_empty=cents_empty,
    gmm_means=gm.means, gmm_weights=gm.weights,
    mlp_w0=np.asarray(mlp._weights[0]), mlp_acc=np.float64(mlp_acc),
    gbt_feats=gbt._feats, gbt_leaves=gbt._leaves,
    gbt_acc=np.float64(gbt_acc),
    pca_components=pca.components, pca_variances=pca.explained_variance,
    lda_topics=lda_topics,
    als_user_f=als._user_factors, als_item_f=als._item_factors,
    als_rmse=np.float64(als_rmse),
    olr_coef=olr_coef, olr_version=np.int64(olr_version),
    okm_cents=okm_cents,
    osc_mean=osc_mean, osc_std=osc_std,
    osc_version=np.int64(osc_version),
    w2v_vocab=w2v_vocab, w2v_vecs=w2v_vecs,
    als_empty_uf=als_empty_uf, als_empty_if=als_empty_if,
    w2v_empty_vecs=w2v_empty_vecs,
)
print(f"STREAM_OK {pid}")
