"""libsvm ingest tests: native parser vs sklearn golden + python fallback."""

import os

import numpy as np
import pytest
import scipy.sparse as sp

from flinkml_tpu.io.libsvm import (
    _load_native,
    read_libsvm,
    read_libsvm_dense,
)


@pytest.fixture
def svm_file(tmp_path, rng):
    mat = sp.random(200, 40, density=0.15, random_state=0, format="csr")
    mat.data = np.round(mat.data, 6)
    y = rng.integers(0, 2, 200).astype(np.float64)
    path = str(tmp_path / "data.svm")
    with open(path, "w") as f:
        for i in range(200):
            toks = [str(y[i])]
            for j in range(mat.indptr[i], mat.indptr[i + 1]):
                toks.append(f"{mat.indices[j] + 1}:{float(mat.data[j])!r}")  # 1-based
            f.write(" ".join(toks) + "\n")
    return path, mat, y


def test_native_parser_compiles():
    assert _load_native() is not None, "g++ compile of native parser failed"


@pytest.mark.parametrize("use_native", [True, False])
def test_against_sklearn_golden(svm_file, use_native):
    from sklearn.datasets import load_svmlight_file

    path, mat, y = svm_file
    labels, indptr, indices, values, nf = read_libsvm(path, use_native=use_native)
    gx, gy = load_svmlight_file(path)
    np.testing.assert_array_equal(labels, gy)
    assert nf == gx.shape[1]
    ours = sp.csr_matrix((values.astype(np.float64), indices, indptr), shape=(200, nf))
    diff = abs(ours - gx).max()
    assert diff < 1e-6, diff


def test_native_matches_python_fallback(svm_file):
    path, _, _ = svm_file
    a = read_libsvm(path, use_native=True)
    b = read_libsvm(path, use_native=False)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_dense_reader(svm_file):
    path, mat, y = svm_file
    x, labels = read_libsvm_dense(path)
    np.testing.assert_array_equal(labels, y)
    np.testing.assert_allclose(x, mat.toarray(), atol=1e-6)


def test_zero_based_detection(tmp_path):
    path = str(tmp_path / "zb.svm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.0\n0 1:1.0\n")
    labels, indptr, indices, values, nf = read_libsvm(path)
    # Index 0 present -> detected as 0-based; max index 3 -> 4 features.
    assert nf == 4
    np.testing.assert_array_equal(indices, [0, 3, 1])


def test_comments_and_blank_lines(tmp_path):
    path = str(tmp_path / "c.svm")
    with open(path, "w") as f:
        f.write("# header comment\n\n1 1:2.0\n\n0 2:3.0 # trailing\n")
    labels, indptr, indices, values, nf = read_libsvm(path)
    assert labels.tolist() == [1.0, 0.0]
    np.testing.assert_array_equal(indices, [0, 1])


def test_empty_file_raises(tmp_path):
    path = str(tmp_path / "e.svm")
    open(path, "w").close()
    with pytest.raises(ValueError, match="empty"):
        read_libsvm(path)


def test_n_features_override_and_check(svm_file):
    path, _, _ = svm_file
    *_, nf = read_libsvm(path, n_features=100)
    assert nf == 100
    with pytest.raises(ValueError, match="n_features"):
        read_libsvm(path, n_features=3)


@pytest.mark.parametrize("use_native", [True, False])
def test_malformed_label_raises(tmp_path, use_native):
    path = str(tmp_path / "bad.svm")
    with open(path, "w") as f:
        f.write("x 1:2.0\n1 1:3.0\n")
    with pytest.raises(ValueError, match="label"):
        read_libsvm(path, use_native=use_native)
    # Partially-numeric label is also rejected.
    with open(path, "w") as f:
        f.write("1.5x 1:2.0\n")
    os.remove(path + "x") if os.path.exists(path + "x") else None
    with pytest.raises(ValueError, match="label"):
        read_libsvm(path, use_native=use_native)


@pytest.mark.parametrize(
    "line,expected_nnz",
    [
        ("1 5:\n", 0),        # empty value
        ("1 5: 6:2.0\n", 0),  # whitespace after colon ends the line
        ("1 5:abc\n", 0),     # non-numeric value
        ("1 5:2.0x\n", 0),    # trailing garbage on value
        ("1 5:2.0#c\n", 0),   # comment glued to value
        ("1 garbage 3:4.0\n", 0),  # malformed token ends line
        ("1 2:1.0 5:\n", 1),  # valid pair before malformed one survives
    ],
)
def test_malformed_pairs_native_fallback_agree(tmp_path, line, expected_nnz):
    path = str(tmp_path / "m.svm")
    with open(path, "w") as f:
        f.write(line + "0 1:1.0\n")  # well-formed second line
    a = read_libsvm(path, use_native=True)
    b = read_libsvm(path, use_native=False)
    for x, y in zip(a[:4], b[:4]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # nnz of first row:
    assert a[1][1] - a[1][0] == expected_nnz


def test_multithreaded_consistency(svm_file):
    path, _, _ = svm_file
    a = read_libsvm(path, n_threads=1)
    b = read_libsvm(path, n_threads=8)
    for x, y in zip(a[:4], b[:4]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_read_libsvm_table_sparse_pipeline(svm_file):
    """Table reader: SparseVector column matching the file exactly, and
    consumable by the sparse LogisticRegression end to end."""
    from flinkml_tpu.io import read_libsvm_table
    from flinkml_tpu.linalg import SparseVector
    from flinkml_tpu.models import LogisticRegression

    path, mat, y = svm_file
    table = read_libsvm_table(path)
    col = table["features"]
    assert col.dtype == object and isinstance(col[0], SparseVector)
    dense = np.stack([v.to_array() for v in col])
    np.testing.assert_allclose(dense, mat.toarray(), rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(table["label"], y)
    # Rows hold sorted unique indices (the SparseVector invariant).
    for v in col[:20]:
        assert (np.diff(v.indices) > 0).all()

    model = (
        LogisticRegression().set_seed(0).set_max_iter(100)
        .set_global_batch_size(200).set_learning_rate(1.0).fit(table)
    )
    (out,) = model.transform(table)
    assert out["prediction"].shape == y.shape


def test_read_libsvm_table_duplicate_index_raises(tmp_path):
    from flinkml_tpu.io import read_libsvm_table

    path = str(tmp_path / "dup.svm")
    with open(path, "w") as f:
        f.write("1 1:2.0 1:3.0 2:1.0\n")
    with pytest.raises(ValueError, match="duplicate feature index"):
        read_libsvm_table(path)


def test_read_libsvm_table_unsorted_indices(tmp_path):
    from flinkml_tpu.io import read_libsvm_table

    path = str(tmp_path / "unsorted.svm")
    with open(path, "w") as f:
        f.write("1 5:5.0 2:2.0 9:9.0\n0 3:3.0 1:1.0\n")
    t = read_libsvm_table(path, n_features=10)
    v0 = t["features"][0]
    np.testing.assert_array_equal(v0.indices, [1, 4, 8])  # 1-based input
    np.testing.assert_array_equal(v0.values, [2.0, 5.0, 9.0])
