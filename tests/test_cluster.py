"""flinkml_tpu.cluster: the multi-process worker runtime.

Three layers of coverage:

1. transport framing edge cases against scripted sockets — torn frames,
   oversized refusal on BOTH sides, deadline expiry mid-read, worker
   death mid-response — every failure a TYPED error (the router's
   failover table is built on types, not messages);
2. the worker server + client in-process (op dispatch, error-frame
   reconstruction, batch-sized embedding exchange, request
   correlation);
3. the full multi-process scenarios in clean child processes
   (``tests/_cluster_child.py``: bitwise parity / kill-mid-traffic /
   warm respawn / cross-process lease reclaim;
   ``tests/_elastic_rank.py``: a real world-shrink resume through the
   rank-scoped snapshot family's layout tags).
"""

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from flinkml_tpu import faults
from flinkml_tpu.cluster import (
    ElasticProcessWorld,
    WorkerClient,
    rendezvous_env,
)
from flinkml_tpu.cluster import protocol
from flinkml_tpu.cluster.errors import (
    ConnectionClosedError,
    FrameError,
    OversizedFrameError,
    RemoteError,
    TransportTimeoutError,
    WorkerDiedError,
    decode_error,
    encode_error,
)
from flinkml_tpu.cluster.worker import WorkerServer
from flinkml_tpu.parallel import init_distributed
from flinkml_tpu.serving.errors import (
    ServingOverloadError,
    ServingSchemaError,
)

_HERE = os.path.dirname(os.path.abspath(__file__))


def _child_env():
    return {**os.environ, "PYTHONPATH": os.pathsep.join(
        [os.path.dirname(_HERE)]
        + ([os.environ["PYTHONPATH"]]
           if os.environ.get("PYTHONPATH") else [])
    )}


# ---------------------------------------------------------------------------
# 1. Framing edge cases (scripted sockets, no backend)
# ---------------------------------------------------------------------------

def _pair():
    a, b = socket.socketpair()
    return a, b


def test_frame_roundtrip():
    a, b = _pair()
    try:
        protocol.send_frame(a, protocol.REQUEST, 7,
                            {"op": "ping", "x": np.arange(3)})
        ftype, rid, payload = protocol.recv_frame(
            b, deadline=time.monotonic() + 2.0
        )
        assert (ftype, rid) == (protocol.REQUEST, 7)
        assert payload["op"] == "ping"
        np.testing.assert_array_equal(payload["x"], np.arange(3))
    finally:
        a.close(), b.close()


def test_torn_frame_is_typed():
    """Peer dies mid-frame: the receiver sees a FrameError naming the
    tear, never a hang or a bare EOFError."""
    a, b = _pair()
    frame = protocol.encode_frame(protocol.RESPONSE, 1, {"k": "v" * 100})
    a.sendall(frame[: len(frame) // 2])
    a.close()
    with pytest.raises(FrameError, match="torn frame"):
        protocol.recv_frame(b, deadline=time.monotonic() + 2.0)
    b.close()


def test_clean_eof_is_connection_closed():
    """EOF at a frame BOUNDARY is the distinct clean-hangup type (a
    reader loop exits quietly instead of reporting a tear)."""
    a, b = _pair()
    a.close()
    with pytest.raises(ConnectionClosedError):
        protocol.recv_frame(b, deadline=time.monotonic() + 2.0)
    b.close()


def test_bad_magic_is_typed():
    a, b = _pair()
    a.sendall(b"HTTP" + b"\x00" * (protocol.HEADER_SIZE - 4) + b"junk")
    with pytest.raises(FrameError, match="magic"):
        protocol.recv_frame(b, deadline=time.monotonic() + 2.0)
    a.close(), b.close()


def test_oversized_payload_refused_on_send():
    """The sender refuses before a byte leaves — the embedding-exchange
    guard (batch-sized payloads only)."""
    a, b = _pair()
    with pytest.raises(OversizedFrameError, match="batch-sized"):
        protocol.send_frame(a, protocol.REQUEST, 1,
                            {"rows": np.zeros(4096)}, max_payload=64)
    a.close(), b.close()


def test_oversized_header_refused_before_payload_read():
    """A peer DECLARING an oversized payload is refused at the header —
    the receiver never allocates or reads the lie."""
    a, b = _pair()
    header = struct.pack(">4sBQQ", protocol.MAGIC, protocol.RESPONSE,
                         1, 1 << 40)
    a.sendall(header)
    with pytest.raises(OversizedFrameError, match="refusing"):
        protocol.recv_frame(b, deadline=time.monotonic() + 2.0,
                            max_payload=1024)
    a.close(), b.close()


def test_deadline_expires_mid_read():
    """Half a frame then silence: the deadline is enforced PER BYTE, so
    the stall surfaces as TransportTimeoutError (a TimeoutError) at the
    deadline — not an unbounded block."""
    a, b = _pair()
    frame = protocol.encode_frame(protocol.RESPONSE, 1, {"k": "v" * 64})
    a.sendall(frame[:protocol.HEADER_SIZE + 4])  # header + partial body
    t0 = time.monotonic()
    with pytest.raises(TransportTimeoutError, match="mid-read"):
        protocol.recv_frame(b, deadline=t0 + 0.5)
    assert time.monotonic() - t0 < 5.0
    assert isinstance(TransportTimeoutError("x"), TimeoutError)
    a.close(), b.close()


def test_frame_reader_reassembles_across_polls():
    """FrameReader buffers partial bytes across poll() wakeups — a
    deadline-sweeping reader loop must never tear a slow frame."""
    a, b = _pair()
    frame = protocol.encode_frame(protocol.RESPONSE, 9, {"n": 42})
    reader = protocol.FrameReader(b)
    got = []

    def drip():
        for i in range(0, len(frame), 7):
            a.sendall(frame[i:i + 7])
            time.sleep(0.01)

    t = threading.Thread(target=drip)
    t.start()
    deadline = time.monotonic() + 5.0
    while not got and time.monotonic() < deadline:
        out = reader.poll(timeout_s=0.02)
        if out is not None:
            got.append(out)
    t.join()
    assert got and got[0][1] == 9 and got[0][2] == {"n": 42}
    a.close(), b.close()


# ---------------------------------------------------------------------------
# 2. Error frames: typed reconstruction across the boundary
# ---------------------------------------------------------------------------

def test_known_errors_cross_as_themselves():
    for exc in (ServingSchemaError("bad column"),
                ServingOverloadError("queue full"),
                OversizedFrameError("too big"),
                faults.FaultInjected("scripted")):
        back = decode_error(encode_error(exc))
        assert type(back) is type(exc)
        assert str(exc) in str(back)


def test_unknown_error_becomes_remote_error():
    payload = {"etype": "SomeWorkerOnlyError", "message": "boom"}
    back = decode_error(payload)
    assert isinstance(back, RemoteError)
    assert back.etype == "SomeWorkerOnlyError"
    assert back.remote_message == "boom"


# ---------------------------------------------------------------------------
# 3. Worker server + client in-process (fake engine; no spawn)
# ---------------------------------------------------------------------------

class _FakeResponse:
    def __init__(self, columns):
        self.columns = columns
        self.version = 3
        self.shed = False


class _FakeActive:
    def __init__(self, model):
        self.model = model


class _FakeEmbeddingStage:
    def __init__(self, vocab=64, dim=4):
        self._rows = np.arange(vocab * dim, dtype=np.float32
                               ).reshape(vocab, dim)


class _FakeEngine:
    """Just enough engine surface for WorkerServer's op table."""

    def __init__(self):
        self._active = _FakeActive(_FakeEmbeddingStage())
        self.stopped = False

    def predict(self, columns, timeout_ms=None):
        feats = np.asarray(columns["features"])
        if feats.ndim != 2:
            raise ServingSchemaError("features must be rank 2")
        return _FakeResponse({"prediction": feats.sum(axis=1)})

    def stats(self):
        return {"name": "fake"}

    def stop(self, drain=True, timeout=None):
        self.stopped = True


@pytest.fixture()
def worker_pair():
    server = WorkerServer(_FakeEngine(), name="fake", max_payload=1 << 20)
    port = server.bind()
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    client = WorkerClient("127.0.0.1", port).connect()
    yield server, client
    client.close()
    server.shutdown()


def test_worker_ops_roundtrip(worker_pair):
    _, client = worker_pair
    assert client.call("ping")["ok"] is True
    out = client.call("predict", {
        "columns": {"features": np.ones((4, 3))}, "timeout_ms": 1000,
    })
    np.testing.assert_array_equal(out["columns"]["prediction"],
                                  np.full(4, 3.0))
    assert out["version"] == 3


def test_worker_typed_error_surfaces_as_itself(worker_pair):
    """A ServingSchemaError raised inside the worker re-raises
    client-side AS ServingSchemaError — the router failover table needs
    no cluster-specific rows."""
    _, client = worker_pair
    with pytest.raises(ServingSchemaError, match="rank 2"):
        client.call("predict", {
            "columns": {"features": np.ones(3)}, "timeout_ms": 1000,
        })


def test_embedding_exchange_is_batch_sized_only(worker_pair):
    _, client = worker_pair
    out = client.call("embedding_rows", {"ids": np.array([0, 5, 2])})
    stage = _FakeEmbeddingStage()
    np.testing.assert_array_equal(out["rows"], stage._rows[[0, 5, 2]])
    # A vocab-sized request is refused with the framing cap's own typed
    # error — never a vocab-sized transfer.
    with pytest.raises(OversizedFrameError, match="batch-sized"):
        client.call("embedding_rows", {"ids": np.arange(64)})
    with pytest.raises(ValueError, match="out of range"):
        client.call("embedding_rows", {"ids": np.array([-1])})


def test_unknown_op_is_typed(worker_pair):
    _, client = worker_pair
    with pytest.raises(ValueError, match="unknown worker op"):
        client.call("nonsense")


def test_client_correlates_out_of_order_responses():
    """Two in-flight requests answered in REVERSE order each complete
    their own callback (request-id correlation, one connection)."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]

    def serve():
        conn, _ = listener.accept()
        frames = [protocol.recv_frame(conn, deadline=time.monotonic() + 5)
                  for _ in range(2)]
        for ftype, rid, payload in reversed(frames):
            protocol.send_frame(conn, protocol.RESPONSE, rid,
                                {"echo": payload["tag"]})
        time.sleep(0.2)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    client = WorkerClient("127.0.0.1", port).connect()
    results = {}
    done = threading.Event()

    def on_done(tag):
        def _cb(result, error):
            results[tag] = (result, error)
            if len(results) == 2:
                done.set()
        return _cb

    client.submit("a", {"tag": "first"}, on_done=on_done("first"))
    client.submit("b", {"tag": "second"}, on_done=on_done("second"))
    assert done.wait(5.0)
    assert results["first"][0]["echo"] == "first"
    assert results["second"][0]["echo"] == "second"
    client.close()
    listener.close()


def test_worker_death_mid_response_fails_inflight_typed():
    """The worker dies after HALF a response frame: the in-flight
    request fails with WorkerDiedError (retire-and-failover signal),
    not a hang and not a parse crash."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]

    def serve():
        conn, _ = listener.accept()
        protocol.recv_frame(conn, deadline=time.monotonic() + 5)
        frame = protocol.encode_frame(
            protocol.RESPONSE, 1, {"big": "x" * 4096}
        )
        conn.sendall(frame[: len(frame) // 2])  # tear it
        conn.close()

    threading.Thread(target=serve, daemon=True).start()
    client = WorkerClient("127.0.0.1", port).connect()
    box = {}
    done = threading.Event()

    def _cb(result, error):
        box["error"] = error
        done.set()

    client.submit("predict", {"x": 1}, on_done=_cb)
    assert done.wait(5.0)
    assert isinstance(box["error"], WorkerDiedError)
    client.close()
    listener.close()


def test_silent_worker_times_out_only_overdue_requests():
    """A worker that accepts and never answers: the reader sweep fails
    exactly the requests whose transport deadline passed."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    conns = []
    threading.Thread(
        target=lambda: conns.append(listener.accept()[0]), daemon=True
    ).start()
    client = WorkerClient("127.0.0.1", port).connect()
    outcomes = {}
    events = {k: threading.Event() for k in ("soon", "later")}

    def _cb(key):
        def cb(result, error):
            outcomes[key] = error
            events[key].set()
        return cb

    now = time.monotonic()
    client.submit("a", {}, deadline=now + 0.3, on_done=_cb("soon"))
    client.submit("b", {}, deadline=now + 30.0, on_done=_cb("later"))
    assert events["soon"].wait(5.0)
    assert isinstance(outcomes["soon"], TransportTimeoutError)
    assert not events["later"].is_set()  # the healthy deadline survives
    assert client.inflight == 1
    client.close()
    listener.close()


# ---------------------------------------------------------------------------
# 4. init_distributed env family (satellite: one rendezvous path)
# ---------------------------------------------------------------------------

def _patch_rendezvous(monkeypatch):
    calls = []

    def fake_initialize(**kwargs):
        calls.append(kwargs)

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False)
    # With a FAKE rendezvous there is no distributed client, so the gloo
    # collectives pick would poison the first real backend init.
    import flinkml_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist, "_enable_cpu_collectives", lambda: None)
    return calls


def test_init_distributed_framework_env_wins(monkeypatch):
    """FLINKML_TPU_COORD_ADDR family beats the generic JAX_* launcher
    vars — spawned workers and operator-launched processes share ONE
    rendezvous path."""
    calls = _patch_rendezvous(monkeypatch)
    monkeypatch.setenv("FLINKML_TPU_COORD_ADDR", "10.0.0.9:9999")
    monkeypatch.setenv("FLINKML_TPU_WORLD_SIZE", "4")
    monkeypatch.setenv("FLINKML_TPU_RANK", "2")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.1.1.1:1111")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "8")
    monkeypatch.setenv("JAX_PROCESS_ID", "7")
    init_distributed()
    assert calls == [{
        "coordinator_address": "10.0.0.9:9999",
        "num_processes": 4, "process_id": 2,
    }]


def test_init_distributed_jax_env_fallback(monkeypatch):
    calls = _patch_rendezvous(monkeypatch)
    for var in ("FLINKML_TPU_COORD_ADDR", "FLINKML_TPU_WORLD_SIZE",
                "FLINKML_TPU_RANK"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.1.1.1:1111")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "3")
    monkeypatch.setenv("JAX_PROCESS_ID", "1")
    init_distributed()
    assert calls == [{
        "coordinator_address": "10.1.1.1:1111",
        "num_processes": 3, "process_id": 1,
    }]


def test_init_distributed_explicit_args_beat_env(monkeypatch):
    calls = _patch_rendezvous(monkeypatch)
    monkeypatch.setenv("FLINKML_TPU_COORD_ADDR", "10.0.0.9:9999")
    monkeypatch.setenv("FLINKML_TPU_WORLD_SIZE", "4")
    monkeypatch.setenv("FLINKML_TPU_RANK", "2")
    init_distributed("10.2.2.2:2222", 2, 0)
    assert calls == [{
        "coordinator_address": "10.2.2.2:2222",
        "num_processes": 2, "process_id": 0,
    }]


def test_rendezvous_env_exports_the_family():
    env = rendezvous_env(rank=3, world=4, port=8476, base={})
    assert env == {
        "FLINKML_TPU_COORD_ADDR": "127.0.0.1:8476",
        "FLINKML_TPU_WORLD_SIZE": "4",
        "FLINKML_TPU_RANK": "3",
    }


# ---------------------------------------------------------------------------
# 5. WorkerCrash fault (the cluster.worker seam)
# ---------------------------------------------------------------------------

def test_worker_crash_plan_json_roundtrip(tmp_path):
    marker = str(tmp_path / "crash.marker")
    plan = faults.FaultPlan(faults.WorkerCrash(
        at=5, key="epoch", exit_code=29, marker=marker,
    ))
    back = faults.plan_from_json(faults.plan_to_json(plan))
    (f,) = back.faults
    assert isinstance(f, faults.WorkerCrash)
    assert (f.at, f.key, f.exit_code, f.marker) == (5, "epoch", 29, marker)


def test_worker_crash_marker_gives_crash_once_across_restarts(tmp_path):
    """The marker file is the cross-RESTART once-flag: a restarted
    child re-arming the same plan must not die at the same trigger
    forever (``should_fire`` only — ``apply`` is a real os._exit)."""
    marker = str(tmp_path / "crash.marker")
    f = faults.WorkerCrash(at=3, key="epoch", marker=marker)
    assert not f.should_fire({"epoch": 2})
    assert f.should_fire({"epoch": 3})
    open(marker, "w").close()  # "the previous incarnation fired"
    assert not f.should_fire({"epoch": 3})


def test_fuzz_plan_requires_marker_dir_for_worker_seam(tmp_path):
    with pytest.raises(ValueError, match="marker_dir"):
        faults.FuzzPlan(seed=1, seams=("cluster.worker",))
    plan = faults.FuzzPlan(seed=1, seams=("cluster.worker",),
                           marker_dir=str(tmp_path))
    sampled = plan.sample(0)
    assert any(isinstance(f, faults.WorkerCrash) for f in sampled.faults)


# ---------------------------------------------------------------------------
# 6. The full multi-process scenarios (clean children)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster_child_report():
    """Parity / kill-mid-traffic / warm-respawn / lease-reclaim in a
    fresh interpreter (the suite conftest's jax persistent cache poisons
    XLA:CPU executable serialization in-process — the compile-count half
    of the acceptance needs a clean process; see
    ``tests/_cluster_child.py``)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_HERE, "_cluster_child.py")],
        capture_output=True, text=True, timeout=420, env=_child_env(),
    )
    assert proc.returncode == 0, (
        f"cluster child failed:\n{proc.stdout}\n{proc.stderr}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_cluster_pool_bitwise_parity(cluster_child_report):
    rep = cluster_child_report
    assert rep["parity_bitwise"] is True, rep
    assert rep["sha_ref"] == rep["sha_pool"]


def test_worker_killed_mid_traffic_loses_zero_requests(
        cluster_child_report):
    """The acceptance pin: a WorkerCrash (real os._exit, armed over the
    transport) mid-closed-loop-traffic loses ZERO requests — the typed
    WorkerDiedError rides the router's retire-and-failover path."""
    rep = cluster_child_report
    assert rep["crashed_rc"] == 23, rep
    assert rep["requests_ok"] > 0, rep
    assert rep["requests_lost"] == 0, rep
    assert rep["health_after_crash"]["r1"] == "HEALTHY", rep


def test_respawn_rejoins_warm_zero_new_compiles(cluster_child_report):
    """A respawned worker warms from the pool's shared artifact store:
    retarget LOADS, zero new XLA compiles, parity still bitwise."""
    rep = cluster_child_report
    assert rep["respawned"], rep
    assert rep["respawn_fusion"]["compiles"] == 0.0, rep
    assert rep["respawn_fusion"]["aot_loads"] > 0, rep
    assert rep["post_respawn_parity"] is True, rep


def test_cross_process_lease_reclaim(cluster_child_report):
    """A slice lease held INSIDE a worker revokes and releases over the
    transport — the revoke→release handshake is process-transparent."""
    rep = cluster_child_report
    assert rep["lease_reclaimed"], rep
    assert all(ls["released"] for ls in rep["lease_reclaimed"]), rep


def test_cluster_metrics_published(cluster_child_report):
    rep = cluster_child_report
    assert rep["workers_alive_gauge"] == 2.0, rep
    assert rep["transport_p99_ms"] is not None, rep
    assert rep["spawn_ms_samples"] == 3, rep  # 2 initial + 1 respawn


def test_elastic_world_shrinks_and_resumes_bit_exact(tmp_path):
    """World size = PROCESS count: a 2-process world loses its highest
    rank to a WorkerCrash, the supervisor relaunches the survivor as
    world 1, and the survivor reassembles the rank-scoped snapshot
    family through its layout tags — finishing bit-identical to a
    continuous golden run, resumed from the crash-time epoch (never a
    silent fresh start)."""
    wd = str(tmp_path)
    script = os.path.join(_HERE, "_elastic_rank.py")
    world = ElasticProcessWorld(
        lambda rank, w, rnd: [sys.executable, script, wd],
        env=_child_env(), workdir=wd, round_timeout_s=180,
    )
    final_world = world.run(2, min_world=1)
    assert final_world == 1
    assert world.rounds[0]["lost"] == 1
    assert 23 in world.rounds[0]["exit_codes"]

    subprocess.run([sys.executable, script, wd, "golden"],
                   check=True, timeout=180, env=_child_env())
    res = json.load(open(os.path.join(wd, "result.json")))
    gold = json.load(open(os.path.join(wd, "result-golden.json")))
    assert res["resumed_from"] > 0, res  # not a silent fresh start
    assert res["w"] == gold["w"]
    assert res["rows"] == gold["rows"]
