"""Two-process jax.distributed worker, launched by test_distributed.py.

Exercises the real multi-process branch of the DCN control plane
(``init_distributed`` → ``jax.distributed.initialize``), a global mesh
spanning both processes, ``host_barrier`` across non-addressable devices,
``process_slice`` partitioning, a cross-process data-plane psum, and the
multi-host checkpoint commit ordering (every host finishes its shard →
barrier → host 0 commits the manifest → barrier → everyone sees it) —
the role SharedProgressAligner.java:127-158 plays in the reference.

Usage: python _dist_worker.py <port> <process_id> <num_processes> <workdir>
Prints ``WORKER_OK <pid>`` on success; any assertion kills the exit code.
"""

import json
import os
import sys

port, pid, nproc, workdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from flinkml_tpu.iteration.checkpoint import CheckpointManager  # noqa: E402
from flinkml_tpu.parallel import (  # noqa: E402
    DeviceMesh,
    host_barrier,
    init_distributed,
    process_slice,
)

# --- control plane startup (the branch single-process tests cannot reach).
idx, count = init_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc,
    process_id=pid,
)
assert (idx, count) == (pid, nproc), (idx, count)
# Idempotent: a second call must be a no-op, not a crash.
idx2, count2 = init_distributed(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc,
    process_id=pid,
)
assert (idx2, count2) == (pid, nproc)

# --- global mesh over every process's devices.
dm = DeviceMesh()
assert dm.num_devices == jax.device_count()
assert jax.device_count() == nproc * jax.local_device_count()

# --- barrier rides devices this process cannot address (the fix under test:
# the input must be materialized per-addressable-device, not host-globally).
assert host_barrier(dm, tag=1) == dm.axis_size()
assert host_barrier(dm, tag=5) == 5 * dm.axis_size()

# --- host data partitioning.
s = process_slice(10)
all_slices = [process_slice(10, p, nproc) for p in range(nproc)]
assert s == all_slices[pid]
covered = [i for sl in all_slices for i in range(sl.start, sl.stop)]
assert covered == list(range(10)), covered

# --- data plane: a psum across processes through the collectives helper.
import numpy as np  # noqa: E402
from flinkml_tpu.parallel.collectives import (  # noqa: E402
    all_reduce_sum,
    keyed_aggregate,
    map_partition,
)

n_local_dev = jax.local_device_count()
local = np.full((n_local_dev, 4), float(pid + 1), dtype=np.float32)
global_batch = jax.make_array_from_process_local_data(
    dm.data_sharding(), local
)
summed = all_reduce_sum(dm, global_batch)
expected = sum((p + 1) * n_local_dev for p in range(nproc))
got = np.asarray(summed.addressable_shards[0].data)
assert np.allclose(got, expected), (got, expected)

# --- keyed aggregation across processes (segment_sum + psum): rows on
# every device contribute to shared key buckets.
rows_per_dev = 4
vals_local = np.ones((n_local_dev * rows_per_dev, 2), dtype=np.float32)
keys_local = np.tile(
    np.arange(rows_per_dev, dtype=np.int32), n_local_dev
)
vals_g = jax.make_array_from_process_local_data(dm.data_sharding(), vals_local)
keys_g = jax.make_array_from_process_local_data(dm.data_sharding(), keys_local)
agg = keyed_aggregate(dm, vals_g, keys_g, num_segments=rows_per_dev)
agg_host = np.asarray(agg.addressable_shards[0].data)
total_devices = nproc * n_local_dev
assert np.allclose(agg_host, np.full((rows_per_dev, 2), total_devices)), agg_host

# --- mapPartition across processes: per-shard function, sharded output.
part = map_partition(
    dm, lambda shard: shard - shard.sum(), vals_g
)
# Every shard has rows_per_dev ones per column -> shard.sum() = 2*rows_per_dev.
local_out = np.concatenate(
    [np.asarray(s.data) for s in part.addressable_shards]
)
assert np.allclose(local_out, 1.0 - 2.0 * rows_per_dev), local_out[:2]

# --- checkpoint commit ordering: shard files → barrier → manifest commit
# by host 0 → barrier → visible everywhere (the two-phase commit the
# reference delegates to Flink's checkpoint coordinator).
shard_path = os.path.join(workdir, f"shard-{pid}.npz")
np.savez(shard_path, data=np.full((2,), pid, dtype=np.int64))
host_barrier(dm, tag=2)
manifest = os.path.join(workdir, "manifest.json")
if pid == 0:
    # Every shard must already exist — the barrier guaranteed it.
    shards = [f"shard-{p}.npz" for p in range(nproc)]
    missing = [f for f in shards if not os.path.exists(os.path.join(workdir, f))]
    assert not missing, missing
    mgr = CheckpointManager(
        os.path.join(workdir, "ckpt"), world_size=dm.num_devices
    )
    mgr.save({"w": np.arange(3.0)}, epoch=7, extra={"shards": shards})
    tmp = manifest + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"epoch": 7, "shards": shards}, f)
    os.replace(tmp, manifest)
host_barrier(dm, tag=3)
# After the commit barrier every process must see the manifest + checkpoint.
assert os.path.exists(manifest)
with open(manifest) as f:
    assert json.load(f)["epoch"] == 7
mgr = CheckpointManager(
    os.path.join(workdir, "ckpt"), world_size=dm.num_devices
)
state, epoch = mgr.restore_latest(like={"w": np.zeros(3)})
assert epoch == 7 and np.array_equal(state["w"], np.arange(3.0))

print(f"WORKER_OK {pid}", flush=True)
