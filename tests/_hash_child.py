"""Clean-process feature-hash determinism probe behind
``tests/test_features.py``.

Why a child process: the hardening claim is that hashed row ids are
independent of ``PYTHONHASHSEED``, interpreter instance, and anything
else a process randomizes at startup — ``hash()``-based code would pass
any in-process test and still scatter a model's rows across restarts.
The parent runs this script twice under DIFFERENT ``PYTHONHASHSEED``
values and asserts the JSON reports (and the committed golden vectors)
are bit-identical.
"""

import json
import os
import sys


KEYS = ["", "a", "hello", "user:12345", "日本語", "the quick brown fox",
        0, 1, -1, 7, 123456789, 2**31, -(2**31), 2**63 - 1, -(2**63)]


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from flinkml_tpu.features.hashing import (
        _hash_ints_vectorized,
        _key_bytes,
        hash_buckets,
        murmur3_32,
    )

    report = {
        "python_hash_seed": os.environ.get("PYTHONHASHSEED"),
        "hashes": {},
        "buckets": {},
    }
    for seed in (0, 1, 42, 0x9747B28C):
        report["hashes"][str(seed)] = {
            repr(k): int(murmur3_32(_key_bytes(k), seed)) for k in KEYS
        }
    for b in (16, 1024, 1 << 20):
        report["buckets"][str(b)] = {
            repr(k): int(hash_buckets([k], seed=42, num_buckets=b)[0])
            for k in KEYS
        }
    int_keys = np.array([k for k in KEYS if isinstance(k, int)], np.int64)
    vec = _hash_ints_vectorized(int_keys, 42)
    scalar = [murmur3_32(_key_bytes(int(k)), 42) for k in int_keys]
    report["vectorized_matches_scalar"] = (
        [int(v) for v in vec] == [int(s) for s in scalar]
    )
    json.dump(report, sys.stdout)
    print()


if __name__ == "__main__":
    main()
