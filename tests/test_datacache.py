"""Data cache / replay tests — mirrors the reference's
``DataCacheWriteReadTest`` / ``DataCacheSnapshotTest`` / ``ReplayOperatorTest``
(SURVEY.md §4 tier 1)."""

import numpy as np
import pytest

from flinkml_tpu.iteration.datacache import (
    DataCacheSnapshot,
    DataCacheWriter,
    PrefetchingDeviceFeed,
    cache_stream,
    replay,
)


def _batches(n_batches=4, rows=8, dim=3, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "features": rng.normal(size=(rows, dim)).astype(np.float32),
            "label": rng.integers(0, 2, size=rows).astype(np.float32),
        }
        for _ in range(n_batches)
    ]


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert set(x) == set(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


def test_write_read_in_memory():
    batches = _batches()
    w = DataCacheWriter()
    for b in batches:
        w.append(b)
    cache = w.finish()
    assert cache.num_rows == 32
    assert cache.num_batches == 4
    _assert_batches_equal(batches, list(cache.reader()))
    # Re-readable (epoch replay requires multiple passes).
    _assert_batches_equal(batches, list(cache.reader()))


def test_spill_to_disk_beyond_budget(tmp_path):
    batches = _batches(n_batches=6)
    one = sum(a.nbytes for a in batches[0].values())
    w = DataCacheWriter(str(tmp_path), memory_budget_bytes=2 * one)
    for b in batches:
        w.append(b)
    cache = w.finish()
    assert len(cache.mem_batches) == 2
    assert len(cache.segments) == 4
    assert all(s.path.startswith(str(tmp_path)) for s in cache.segments)
    _assert_batches_equal(batches, list(cache.reader()))


def test_reader_position_resume(tmp_path):
    batches = _batches(n_batches=5)
    w = DataCacheWriter(str(tmp_path), memory_budget_bytes=0)
    for b in batches:
        w.append(b)
    cache = w.finish()
    r = cache.reader()
    next(r)
    next(r)
    assert r.position == 2
    resumed = cache.reader(start_position=r.position)
    _assert_batches_equal(batches[2:], list(resumed))


def test_append_after_finish_raises():
    w = DataCacheWriter()
    w.append(_batches(1)[0])
    w.finish()
    with pytest.raises(RuntimeError):
        w.append(_batches(1)[0])


def test_ragged_columns_rejected():
    w = DataCacheWriter(directory=".")
    with pytest.raises(ValueError):
        w.append({"a": np.zeros(3), "b": np.zeros(4)})


def test_budget_without_directory_rejected():
    with pytest.raises(ValueError, match="spill directory"):
        DataCacheWriter(memory_budget_bytes=1024)


def test_mem_batches_are_frozen_against_mutation():
    batches = _batches(1)
    original = np.array(batches[0]["features"], copy=True)
    cache = cache_stream(iter(batches))
    out = next(cache.reader())
    with pytest.raises(ValueError):
        out["features"][0, 0] = 99.0  # in-place mutation must fail loudly
    # Dict-level replacement is fine and must not alter the cache.
    out["features"] = np.zeros_like(np.asarray(out["features"]))
    np.testing.assert_array_equal(next(cache.reader())["features"], original)


def test_spilled_batches_leave_caller_buffer_reusable(tmp_path):
    from flinkml_tpu.iteration.datacache import DataCacheWriter

    writer = DataCacheWriter(directory=str(tmp_path), memory_budget_bytes=0)
    buf = np.arange(12, dtype=np.float64).reshape(3, 4)
    writer.append({"features": buf})
    buf[:] = -1.0  # spilled → producer may reuse its staging buffer
    cache = writer.finish()
    np.testing.assert_array_equal(
        next(cache.reader())["features"],
        np.arange(12, dtype=np.float64).reshape(3, 4),
    )


def test_feed_close_while_worker_blocked_exits():
    feed = PrefetchingDeviceFeed(iter(_batches(8)), depth=1)
    next(feed)  # worker now blocked on a full queue
    feed.close()
    feed._thread.join(timeout=5)
    assert not feed._thread.is_alive()
    with pytest.raises(StopIteration):
        next(feed)


def test_object_dtype_rejected_on_spill(tmp_path):
    w = DataCacheWriter(str(tmp_path), memory_budget_bytes=0)
    obj = np.empty(2, dtype=object)
    obj[0], obj[1] = [1], [2, 3]
    with pytest.raises(TypeError):
        w.append({"a": obj})


def test_snapshot_persist_recover(tmp_path):
    batches = _batches(n_batches=4)
    one = sum(a.nbytes for a in batches[0].values())
    w = DataCacheWriter(str(tmp_path / "spill"), memory_budget_bytes=2 * one)
    for b in batches:
        w.append(b)
    cache = w.finish()
    snap = tmp_path / "snap"
    DataCacheSnapshot.persist(cache, str(snap))
    recovered = DataCacheSnapshot.recover(str(snap))
    assert recovered.num_rows == cache.num_rows
    _assert_batches_equal(batches, list(recovered.reader()))


def test_replay_epochs():
    batches = _batches(n_batches=3)
    cache = cache_stream(iter(batches))
    seen = list(replay(cache, num_epochs=2))
    assert [e for e, _ in seen] == [0, 0, 0, 1, 1, 1]
    _assert_batches_equal(batches, [b for e, b in seen if e == 1])


def test_prefetching_device_feed_matches():
    import jax.numpy as jnp

    batches = _batches(n_batches=5)
    feed = PrefetchingDeviceFeed(iter(batches), depth=2)
    out = list(feed)
    assert len(out) == 5
    for host, dev in zip(batches, out):
        assert isinstance(dev["features"], jnp.ndarray)
        np.testing.assert_array_equal(host["features"], np.asarray(dev["features"]))


def test_spill_preserves_append_order(tmp_path):
    """A mid-stream spill must not reorder replay (big batch between small)."""
    small1 = {"a": np.full((2, 2), 1.0, dtype=np.float32)}
    big = {"a": np.full((64, 64), 2.0, dtype=np.float32)}
    small2 = {"a": np.full((2, 2), 3.0, dtype=np.float32)}
    budget = small1["a"].nbytes + small2["a"].nbytes + 1  # big spills, smalls fit
    w = DataCacheWriter(str(tmp_path), memory_budget_bytes=budget)
    for b in (small1, big, small2):
        w.append(b)
    cache = w.finish()
    assert len(cache.segments) == 1 and len(cache.mem_batches) == 2
    vals = [b["a"].flat[0] for b in cache.reader()]
    assert vals == [1.0, 2.0, 3.0]


def test_snapshot_preserves_mixed_order(tmp_path):
    small1 = {"a": np.full((2,), 1.0, dtype=np.float32)}
    big = {"a": np.full((1024,), 2.0, dtype=np.float32)}
    small2 = {"a": np.full((2,), 3.0, dtype=np.float32)}
    w = DataCacheWriter(str(tmp_path / "spill"), memory_budget_bytes=64)
    for b in (small1, big, small2):
        w.append(b)
    cache = w.finish()
    DataCacheSnapshot.persist(cache, str(tmp_path / "snap"))
    rec = DataCacheSnapshot.recover(str(tmp_path / "snap"))
    assert [b["a"].flat[0] for b in rec.reader()] == [1.0, 2.0, 3.0]


def test_object_dtype_rejected_in_memory_too():
    w = DataCacheWriter()  # no directory: pure RAM path must still reject
    obj = np.empty(2, dtype=object)
    obj[0], obj[1] = [1], [2, 3]
    with pytest.raises(TypeError):
        w.append({"a": obj})


def test_replay_empty_cache_terminates():
    cache = cache_stream(iter([]))
    assert list(replay(cache, num_epochs=None)) == []


def test_feed_next_after_exhaustion_raises_stopiteration():
    feed = PrefetchingDeviceFeed(iter(_batches(2)), depth=1)
    list(feed)
    with pytest.raises(StopIteration):
        next(feed)  # must not deadlock on the drained queue
    with pytest.raises(StopIteration):
        next(feed)


def test_prefetching_device_feed_propagates_errors():
    def gen():
        yield {"a": np.zeros(2)}
        raise ValueError("boom")

    feed = PrefetchingDeviceFeed(gen(), depth=1)
    next(feed)
    with pytest.raises(ValueError, match="boom"):
        next(feed)


def test_concurrent_readers_are_independent(tmp_path):
    """Two readers iterating the SAME sealed (spilled) cache concurrently
    each see every batch exactly once, in order — reader position is
    per-reader state, not cache state (``DataCacheReader.java:35-135``:
    the reference's cache serves multiple consumers)."""
    import threading

    writer = DataCacheWriter(str(tmp_path / "c"), memory_budget_bytes=1)
    for i in range(8):
        writer.append({"x": np.full((16, 3), float(i), np.float32)})
    cache = writer.finish()

    seen = [[], []]
    errs = []

    def consume(slot):
        try:
            for batch in cache.reader():
                seen[slot].append(float(batch["x"][0, 0]))
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=consume, args=(s,)) for s in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    expected = [float(i) for i in range(8)]
    assert seen[0] == expected and seen[1] == expected


def test_concurrent_streamed_fits_from_one_cache(tmp_path, mesh):
    """Two streamed KMeans fits replaying ONE sealed cache from separate
    threads produce exactly the sequential result — the cache is safely
    shareable across concurrent training jobs (prefetch threads, segment
    reads, device dispatch)."""
    import threading

    from flinkml_tpu.models.kmeans import train_kmeans_stream

    rng = np.random.default_rng(0)
    centers = rng.uniform(-10, 10, size=(3, 4)).astype(np.float32)
    writer = DataCacheWriter(str(tmp_path / "c"), memory_budget_bytes=1)
    for _ in range(4):
        a = rng.integers(0, 3, size=48)
        writer.append({
            "x": (centers[a] + rng.normal(scale=0.3, size=(48, 4)))
            .astype(np.float32)
        })
    cache = writer.finish()

    args = dict(k=3, mesh=mesh, max_iter=5, seed=2, column="x")
    golden = train_kmeans_stream(cache, **args)

    results = [None, None]
    errs = []

    def fit(slot):
        try:
            results[slot] = train_kmeans_stream(cache, **args)
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=fit, args=(s,)) for s in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    np.testing.assert_array_equal(results[0], golden)
    np.testing.assert_array_equal(results[1], golden)
