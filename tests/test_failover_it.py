"""System-level fault-injection ITs — reference parity with
``BoundedAllRoundCheckpointITCase`` (SURVEY.md §4): a failure is injected
at several points during a real distributed LR training run on the
8-device mesh; after resume-from-checkpoint the final coefficients must
EXACTLY match the uninterrupted run.

The reference parameterizes failure at record {1000, 4000, 8000, 15900}
across a 2TMx2-slot MiniCluster; the analog here is failure at several
epochs across the 8-device CPU mesh, since the epoch is the unit of
recovery (the loop carry is the only state).
"""

import numpy as np
import pytest

from flinkml_tpu.iteration import CheckpointManager, IterationListener
from flinkml_tpu.models.logistic_regression import train_logistic_regression
from flinkml_tpu.parallel import DeviceMesh


class FailingListener(IterationListener):
    """The FailingMap analog (operators/FailingMap.java:24-45): raises
    exactly once, at a chosen epoch, on the first attempt only."""

    def __init__(self, fail_at_epoch: int):
        self.fail_at_epoch = fail_at_epoch
        self.fired = False

    def on_epoch_watermark_incremented(self, epoch: int, state) -> None:
        if not self.fired and epoch == self.fail_at_epoch:
            self.fired = True
            raise RuntimeError(f"injected failure at epoch {epoch}")


def _data(n=256, d=12, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ rng.normal(size=d) > 0).astype(np.float32)
    return x, y, np.ones(n, dtype=np.float32)


def _train(mesh, x, y, w, mgr=None, resume=False, listeners=()):
    return train_logistic_regression(
        x, y, w, mesh=mesh, max_iter=12, learning_rate=0.5,
        global_batch_size=128, reg=0.01, tol=0.0, seed=7, mode="host",
        checkpoint_manager=mgr, checkpoint_interval=3, resume=resume,
        listeners=listeners,
    )


@pytest.mark.parametrize("fail_at_epoch", [4, 5, 10])
def test_lr_failover_resume_exact(tmp_path, fail_at_epoch):
    mesh = DeviceMesh()
    x, y, w = _data()

    golden = _train(
        mesh, x, y, w, CheckpointManager(str(tmp_path / "golden"))
    )

    mgr = CheckpointManager(str(tmp_path / f"f{fail_at_epoch}"))
    listener = FailingListener(fail_at_epoch)
    with pytest.raises(RuntimeError, match="injected"):
        _train(mesh, x, y, w, mgr, listeners=[listener])
    # Recovery point: the last multiple-of-3 checkpoint before the failure.
    assert mgr.latest_epoch() is not None
    assert mgr.latest_epoch() <= fail_at_epoch + 1

    recovered = _train(mesh, x, y, w, mgr, resume=True, listeners=[listener])
    np.testing.assert_array_equal(recovered, golden)


def test_lr_failover_before_first_checkpoint(tmp_path):
    """Failure before any checkpoint exists: resume starts fresh and must
    still reach the exact golden result."""
    mesh = DeviceMesh()
    x, y, w = _data(seed=3)
    golden = _train(mesh, x, y, w)

    mgr = CheckpointManager(str(tmp_path / "early"))
    listener = FailingListener(0)
    with pytest.raises(RuntimeError, match="injected"):
        _train(mesh, x, y, w, mgr, listeners=[listener])
    assert mgr.latest_epoch() is None

    recovered = _train(mesh, x, y, w, mgr, resume=True, listeners=[listener])
    np.testing.assert_array_equal(recovered, golden)
