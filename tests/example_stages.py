"""Hand-written fixture stages for Pipeline/Graph tests.

Mirrors the reference's ``ExampleStages`` fixtures
(``flink-ml-core/src/test/java/.../api/ExampleStages.java``): ``SumEstimator``
fits a ``SumModel`` whose delta is the sum of the train column; the model
adds its delta to every input value.
"""

from typing import List, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator, Estimator, Model, Transformer
from flinkml_tpu.io import read_write
from flinkml_tpu.params import IntParam
from flinkml_tpu.table import Table


class SumModel(Model):
    """Adds a fitted delta to the 'value' column."""

    DELTA = IntParam("delta", "value added to inputs", 0)

    def __init__(self):
        super().__init__()

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        delta = self.get(SumModel.DELTA)
        return (table.with_column("value", table["value"] + delta),)

    def set_model_data(self, *inputs: Table) -> "SumModel":
        (table,) = inputs
        self.set(SumModel.DELTA, int(table["delta"][0]))
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"delta": np.array([self.get(SumModel.DELTA)])})]


class SumEstimator(Estimator):
    """Fits SumModel with delta = sum of the 'value' column."""

    def __init__(self):
        super().__init__()

    def fit(self, *inputs: Table) -> SumModel:
        (table,) = inputs
        model = SumModel()
        model.set(SumModel.DELTA, int(np.sum(table["value"])))
        return model


class UnionAlgoOperator(AlgoOperator):
    """Concatenates all input tables — a multi-input AlgoOperator fixture."""

    def __init__(self):
        super().__init__()

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        out = inputs[0]
        for t in inputs[1:]:
            out = out.concat(t)
        return (out,)
