"""Sequence-parallel attention vs the single-device full-softmax reference.

Runs on the 8-device CPU mesh (conftest) — every ppermute/all_to_all hop
is real.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.parallel.ring import (
    _full_attention,
    ring_attention,
    ulysses_attention,
)


def _qkv(b=2, h=8, l=64, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.normal(size=(b, h, l, d)), dtype=jnp.float32
    )
    return mk(), mk(), mk()


def _reference(q, k, v, causal):
    return np.asarray(_full_attention(q, k, v, causal))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    q, k, v = _qkv()
    out = ring_attention(q, k, v, DeviceMesh(), causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), _reference(q, k, v, causal), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    q, k, v = _qkv()
    out = ulysses_attention(q, k, v, DeviceMesh(), causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), _reference(q, k, v, causal), rtol=2e-4, atol=2e-5
    )


def test_ring_attention_long_sequence_many_blocks():
    # L_local > 1 block per device and uneven content across blocks.
    q, k, v = _qkv(b=1, h=2, l=128, d=8, seed=3)
    out = ring_attention(q, k, v, DeviceMesh(), causal=True)
    np.testing.assert_allclose(
        np.asarray(out), _reference(q, k, v, True), rtol=2e-4, atol=2e-5
    )


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(h=6)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, DeviceMesh())


def test_rejects_indivisible_sequence():
    q, k, v = _qkv(l=60)
    with pytest.raises(ValueError, match="divide"):
        ring_attention(q, k, v, DeviceMesh())


def test_rejects_bad_rank():
    q = jnp.zeros((4, 8, 16))
    with pytest.raises(ValueError, match="batch, heads, seq"):
        ring_attention(q, q, q, DeviceMesh())


def test_causal_first_token_attends_only_itself():
    q, k, v = _qkv(b=1, h=1, l=64, d=4, seed=9)
    out = np.asarray(ring_attention(q, k, v, DeviceMesh(), causal=True))
    # Row 0 can only attend to key 0 -> output equals v[0].
    np.testing.assert_allclose(
        out[0, 0, 0], np.asarray(v)[0, 0, 0], rtol=1e-5, atol=1e-6
    )


def test_custom_axis_name_mesh():
    """Regression: the shard axis is the mesh's first axis, whatever its
    name — not a hardcoded "data"."""
    q, k, v = _qkv(b=1, h=8, l=64, d=8, seed=4)
    mesh = DeviceMesh({"seq": 8})
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), _reference(q, k, v, True), rtol=2e-4, atol=2e-5
    )
    out_u = ulysses_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(
        np.asarray(out_u), _reference(q, k, v, False), rtol=2e-4, atol=2e-5
    )
