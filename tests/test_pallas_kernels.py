"""Golden-value tests for the Pallas hot-loop kernels.

Run in interpreter mode on the CPU test mesh (same kernel code the TPU
compiles); every kernel is compared against the straight-line jnp math it
fuses, which itself is covered against sklearn/numpy elsewhere.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from flinkml_tpu.models._linear_sgd import _margin_grad
from flinkml_tpu.ops.pallas_kernels import (
    _pick_tile,
    fused_kmeans_step,
    fused_linear_grad,
)


def _ref_linear_grad(x, y, w, coef, loss):
    dot = x @ coef
    mult, per_ex = _margin_grad(loss, dot, y, w)
    return x.T @ mult, jnp.sum(per_ex), jnp.sum(w)


@pytest.mark.parametrize("loss", ["logistic", "hinge", "squared"])
@pytest.mark.parametrize("n,d", [(8, 4), (64, 123), (48, 16)])
def test_fused_linear_grad_matches_unfused(loss, n, d):
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=n), dtype=jnp.float32)
    w = jnp.asarray(rng.uniform(0.5, 2.0, size=n), dtype=jnp.float32)
    coef = jnp.asarray(rng.normal(size=d), dtype=jnp.float32)
    grad, loss_sum, wsum = fused_linear_grad(
        x, y, w, coef, loss=loss, interpret=True
    )
    g_ref, l_ref, w_ref = _ref_linear_grad(x, y, w, coef, loss)
    np.testing.assert_allclose(grad, g_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(loss_sum, l_ref, rtol=1e-5)
    np.testing.assert_allclose(wsum, w_ref, rtol=1e-6)


@pytest.mark.parametrize("loss", ["logistic", "hinge", "squared"])
def test_fused_linear_grad_bf16_inputs(loss):
    """bf16 storage, f32 compute/accumulation (acc_dt): outputs come back
    bf16 and match an f32 reference within bf16 quantization — the path
    Mosaic cannot lower with all-bf16 math (transcendentals)."""
    rng = np.random.default_rng(3)
    n, d = 64, 32
    x32 = rng.normal(size=(n, d)).astype(np.float32)
    y32 = rng.integers(0, 2, size=n).astype(np.float32)
    w32 = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    c32 = rng.normal(size=d).astype(np.float32)
    xb, yb, wb, cb = (jnp.asarray(a, jnp.bfloat16) for a in (x32, y32, w32, c32))
    grad, loss_sum, wsum = fused_linear_grad(
        xb, yb, wb, cb, loss=loss, interpret=True
    )
    assert grad.dtype == jnp.bfloat16
    assert loss_sum.dtype == jnp.bfloat16 and wsum.dtype == jnp.bfloat16
    # f32 reference over the bf16-rounded inputs; bf16 has ~3 decimal
    # digits, so compare at ~1% of the result scale.
    g_ref, l_ref, w_ref = _ref_linear_grad(
        jnp.asarray(xb, jnp.float32), jnp.asarray(yb, jnp.float32),
        jnp.asarray(wb, jnp.float32), jnp.asarray(cb, jnp.float32), loss,
    )
    scale = float(jnp.max(jnp.abs(g_ref))) + 1e-6
    np.testing.assert_allclose(
        np.asarray(grad, np.float32), np.asarray(g_ref),
        atol=0.02 * scale, rtol=0.02,
    )
    np.testing.assert_allclose(
        float(loss_sum), float(l_ref), rtol=0.02
    )
    np.testing.assert_allclose(float(wsum), float(w_ref), rtol=0.01)


def test_fused_linear_grad_zero_weight_rows_are_noops():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(16, 5)), dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=16), dtype=jnp.float32)
    w = jnp.ones(16, dtype=jnp.float32).at[8:].set(0.0)
    coef = jnp.asarray(rng.normal(size=5), dtype=jnp.float32)
    grad, loss_sum, wsum = fused_linear_grad(
        x, y, w, coef, loss="logistic", interpret=True
    )
    g_ref, l_ref, _ = _ref_linear_grad(x[:8], y[:8], w[:8], coef, "logistic")
    np.testing.assert_allclose(grad, g_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(loss_sum, l_ref, rtol=1e-5)
    assert float(wsum) == 8.0


@pytest.mark.parametrize("n,d,k", [(32, 4, 3), (64, 7, 5), (8, 2, 2)])
def test_fused_kmeans_step_matches_onehot(n, d, k):
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(n, d)), dtype=jnp.float32)
    w = jnp.asarray((rng.uniform(size=n) > 0.2), dtype=jnp.float32)
    cents = jnp.asarray(rng.normal(size=(k, d)), dtype=jnp.float32)
    sums, counts = fused_kmeans_step(x, w, cents, interpret=True)

    d2 = ((x[:, None, :] - cents[None, :, :]) ** 2).sum(-1)
    assign = jnp.argmin(d2, axis=1)
    onehot = jnp.eye(k, dtype=x.dtype)[assign] * w[:, None]
    np.testing.assert_allclose(sums, onehot.T @ x, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(counts, onehot.sum(0), rtol=1e-6)


def test_fused_kmeans_step_tie_breaks_to_lowest_index():
    # Two identical centroids: argmin must pick index 0, like jnp.argmin.
    x = jnp.asarray([[1.0, 0.0]] * 8, dtype=jnp.float32)
    w = jnp.ones(8, dtype=jnp.float32)
    cents = jnp.asarray([[0.0, 0.0], [0.0, 0.0]], dtype=jnp.float32)
    sums, counts = fused_kmeans_step(x, w, cents, interpret=True)
    np.testing.assert_allclose(counts, [8.0, 0.0])
    np.testing.assert_allclose(sums[0], [8.0, 0.0])


def test_pick_tile_rejects_unpadded():
    with pytest.raises(ValueError):
        _pick_tile(13)
    assert _pick_tile(512) == 512
    assert _pick_tile(24) == 8


# ---------------------------------------------------------------------------
# End-to-end: trainers with the Pallas path forced on (interpret on CPU)
# ---------------------------------------------------------------------------

def _lr_data(n=64, d=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    true = rng.normal(size=d)
    y = (x @ true + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    return x, y, np.ones(n)


def test_train_linear_model_pallas_matches_xla(monkeypatch):
    from flinkml_tpu.models._linear_sgd import train_linear_model
    from flinkml_tpu.parallel import DeviceMesh

    x, y, w = _lr_data()
    kw = dict(
        loss="logistic", mesh=DeviceMesh(), max_iter=30, learning_rate=0.5,
        global_batch_size=64, reg=0.01, elastic_net=0.0, tol=0.0, seed=1,
        dtype=np.float32,
    )
    monkeypatch.setenv("FLINKML_TPU_PALLAS", "never")
    coef_xla = train_linear_model(x, y, w, **kw)
    monkeypatch.setenv("FLINKML_TPU_PALLAS", "always")
    coef_pl = train_linear_model(x, y, w, **kw)
    np.testing.assert_allclose(coef_pl, coef_xla, rtol=2e-4, atol=2e-5)


def test_train_kmeans_pallas_matches_xla(monkeypatch):
    from flinkml_tpu.models.kmeans import train_kmeans
    from flinkml_tpu.parallel import DeviceMesh

    rng = np.random.default_rng(5)
    x = np.concatenate(
        [rng.normal(loc=c, scale=0.3, size=(40, 3)) for c in (-3.0, 0.0, 3.0)]
    )
    kw = dict(k=3, mesh=DeviceMesh(), max_iter=10, seed=2)
    monkeypatch.setenv("FLINKML_TPU_PALLAS", "never")
    c_xla = train_kmeans(x.astype(np.float32), **kw)
    monkeypatch.setenv("FLINKML_TPU_PALLAS", "always")
    c_pl = train_kmeans(x.astype(np.float32), **kw)
    np.testing.assert_allclose(
        np.sort(c_pl, axis=0), np.sort(c_xla, axis=0), rtol=1e-4, atol=1e-4
    )


def test_pallas_active_rejects_unknown_kernel():
    from flinkml_tpu.ops.pallas_kernels import pallas_active

    with pytest.raises(KeyError, match="unknown kernel"):
        pallas_active("kmean")  # typo'd name must fail loudly
