"""Stage save/load tests — mirrors the reference's StageTest save/load
round-trips (``StageTest.java:1-395``) and ReadWriteUtils behavior."""

import json
import os

import numpy as np
import pytest

from flinkml_tpu.io import read_write
from flinkml_tpu.table import Table

from tests.example_stages import SumEstimator, SumModel


def test_save_creates_metadata(tmp_path):
    m = SumModel().set_delta(3)
    p = str(tmp_path / "m")
    m.save(p)
    with open(os.path.join(p, "metadata")) as f:
        meta = json.load(f)
    assert meta["className"].endswith("SumModel")
    assert meta["paramMap"]["delta"] == 3


def test_save_refuses_overwrite(tmp_path):
    m = SumModel()
    p = str(tmp_path / "m")
    m.save(p)
    with pytest.raises(IOError):
        m.save(p)


def test_generic_load_stage_dispatches_class(tmp_path):
    m = SumModel().set_delta(9)
    p = str(tmp_path / "m")
    m.save(p)
    loaded = read_write.load_stage(p)
    assert isinstance(loaded, SumModel)
    assert loaded.get_delta() == 9


def test_load_with_class_check(tmp_path):
    e = SumEstimator()
    p = str(tmp_path / "e")
    e.save(p)
    meta = read_write.load_metadata(p)
    with pytest.raises(ValueError):
        read_write.load_metadata(p, expected_class_name="not.the.Class")
    assert meta["className"].endswith("SumEstimator")


def test_load_wrong_class_raises(tmp_path):
    e = SumEstimator()
    p = str(tmp_path / "e")
    e.save(p)
    with pytest.raises(ValueError):
        SumModel.load(p)


def test_copy_params_across_stage_types(tmp_path):
    src = SumModel().set_delta(42)
    dst = SumModel()
    dst.copy_params_from(src)
    assert dst.get_delta() == 42
    assert dst.get_param_map_json()["delta"] == 42


def test_model_arrays_round_trip(tmp_path):
    p = str(tmp_path / "m")
    arrays = {"coef": np.arange(5.0), "intercept": np.array([1.5])}
    read_write.save_model_arrays(p, arrays)
    back = read_write.load_model_arrays(p)
    assert np.array_equal(back["coef"], arrays["coef"])
    assert back["intercept"][0] == 1.5
