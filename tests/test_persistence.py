"""Stage save/load tests — mirrors the reference's StageTest save/load
round-trips (``StageTest.java:1-395``) and ReadWriteUtils behavior."""

import json
import os

import numpy as np
import pytest

from flinkml_tpu.io import read_write
from flinkml_tpu.table import Table

from tests.example_stages import SumEstimator, SumModel


def test_save_creates_metadata(tmp_path):
    m = SumModel().set_delta(3)
    p = str(tmp_path / "m")
    m.save(p)
    with open(os.path.join(p, "metadata")) as f:
        meta = json.load(f)
    assert meta["className"].endswith("SumModel")
    assert meta["paramMap"]["delta"] == 3


def test_save_refuses_overwrite(tmp_path):
    m = SumModel()
    p = str(tmp_path / "m")
    m.save(p)
    with pytest.raises(IOError):
        m.save(p)


def test_generic_load_stage_dispatches_class(tmp_path):
    m = SumModel().set_delta(9)
    p = str(tmp_path / "m")
    m.save(p)
    loaded = read_write.load_stage(p)
    assert isinstance(loaded, SumModel)
    assert loaded.get_delta() == 9


def test_load_with_class_check(tmp_path):
    e = SumEstimator()
    p = str(tmp_path / "e")
    e.save(p)
    meta = read_write.load_metadata(p)
    with pytest.raises(ValueError):
        read_write.load_metadata(p, expected_class_name="not.the.Class")
    assert meta["className"].endswith("SumEstimator")


def test_load_wrong_class_raises(tmp_path):
    e = SumEstimator()
    p = str(tmp_path / "e")
    e.save(p)
    with pytest.raises(ValueError):
        SumModel.load(p)


def test_copy_params_across_stage_types(tmp_path):
    src = SumModel().set_delta(42)
    dst = SumModel()
    dst.copy_params_from(src)
    assert dst.get_delta() == 42
    assert dst.get_param_map_json()["delta"] == 42


def test_model_arrays_round_trip(tmp_path):
    p = str(tmp_path / "m")
    arrays = {"coef": np.arange(5.0), "intercept": np.array([1.5])}
    read_write.save_model_arrays(p, arrays)
    back = read_write.load_model_arrays(p)
    assert np.array_equal(back["coef"], arrays["coef"])
    assert back["intercept"][0] == 1.5


def test_content_fingerprint_deterministic_and_sensitive():
    a = {"coef": np.arange(5.0), "b": np.array([1.5])}
    fp = read_write.content_fingerprint(a, {"p": 1})
    assert fp == read_write.content_fingerprint(
        {"b": np.array([1.5]), "coef": np.arange(5.0)}, {"p": 1}
    )  # name order irrelevant
    assert fp != read_write.content_fingerprint(a, {"p": 2})  # params count
    tampered = {"coef": np.arange(5.0), "b": np.array([1.5000001])}
    assert fp != read_write.content_fingerprint(tampered, {"p": 1})
    # dtype/shape changes with identical bytes still change the hash
    assert fp != read_write.content_fingerprint(
        {"coef": np.arange(5.0).reshape(5, 1), "b": np.array([1.5])}, {"p": 1}
    )


def test_save_tamper_load_raises_integrity_error(tmp_path):
    """save → tamper → load: models persisted via _save_with_arrays record
    a content fingerprint; a bit flip in the arrays fails the load with
    the named error (the serving registry's integrity guarantee)."""
    from flinkml_tpu.models.kmeans import KMeansModel
    from flinkml_tpu.table import Table

    m = KMeansModel().set(KMeansModel.FEATURES_COL, "f")
    m.set_model_data(Table({"centroids": np.ones((1, 3, 2))}))
    p = str(tmp_path / "model")
    m.save(p)
    meta = read_write.load_metadata(p)
    assert read_write.FINGERPRINT_KEY in meta
    assert KMeansModel.load(p).centroids.shape == (3, 2)  # clean load OK
    assert read_write.verify_fingerprint(p) == meta[read_write.FINGERPRINT_KEY]

    arrays = read_write.load_model_arrays(p)
    arrays["centroids"][0, 0] += 1.0
    os.remove(os.path.join(p, read_write.MODEL_DATA_DIR, "model.npz"))
    read_write.save_model_arrays(p, arrays)
    with pytest.raises(read_write.ModelIntegrityError):
        KMeansModel.load(p)
    with pytest.raises(read_write.ModelIntegrityError):
        read_write.verify_fingerprint(p)


def test_pre_fingerprint_saves_still_load(tmp_path):
    """Metadata without a recorded fingerprint (older saves) loads
    without verification — forward compatibility, not a hard break."""
    from flinkml_tpu.models.kmeans import KMeansModel
    from flinkml_tpu.table import Table

    m = KMeansModel().set(KMeansModel.FEATURES_COL, "f")
    m.set_model_data(Table({"centroids": np.ones((1, 2, 2))}))
    p = str(tmp_path / "model")
    m.save(p)
    meta_path = os.path.join(p, read_write.METADATA_FILE)
    with open(meta_path) as f:
        meta = json.load(f)
    del meta[read_write.FINGERPRINT_KEY]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    assert KMeansModel.load(p).centroids.shape == (2, 2)
    assert read_write.verify_fingerprint(p) is None
