"""Seeded-findings fixture for the analysis CLI gate.

NOT a runnable example — this file exists so
``python -m flinkml_tpu.analysis tests/analysis_fixtures/ --fail-on-findings``
has known-bad input to flag (the CI gate asserts a non-zero exit here and
a zero exit on ``examples/``). Every pipeline below carries a deliberate
defect; the expected rule is noted inline.
"""

from flinkml_tpu.models import (
    MaxAbsScaler,
    MinMaxScaler,
    StandardScaler,
    VectorAssembler,
)
from flinkml_tpu.pipeline import Pipeline

# FML107: the first scaler reads "scaled", which only the SECOND stage
# produces — consumers ordered before their producer.
pipe_misordered = Pipeline([
    MinMaxScaler().set(MinMaxScaler.INPUT_COL, "scaled")
                  .set(MinMaxScaler.OUTPUT_COL, "unit"),
    StandardScaler().set(StandardScaler.INPUT_COL, "features")
                    .set(StandardScaler.OUTPUT_COL, "scaled"),
])

# FML102: the assembler emits a column named "features" — colliding with
# the source-data column it just read (silent overwrite of user data).
pipe_collision = Pipeline([
    VectorAssembler().set_input_cols(["features", "extra"])
                     .set(VectorAssembler.HANDLE_INVALID, "keep")
                     .set(VectorAssembler.OUTPUT_COL, "features"),
    MaxAbsScaler().set(MaxAbsScaler.INPUT_COL, "features")
                  .set(MaxAbsScaler.OUTPUT_COL, "norm"),
])

# FML102 (in-place overwrite): output column equals the input column.
pipe_inplace = Pipeline([
    StandardScaler().set(StandardScaler.INPUT_COL, "x")
                    .set(StandardScaler.OUTPUT_COL, "x"),
    MinMaxScaler().set(MinMaxScaler.INPUT_COL, "x")
                  .set(MinMaxScaler.OUTPUT_COL, "y"),
])
