"""Param system tests — mirrors the reference's StageTest param coverage
(``flink-ml-core/src/test/java/.../api/StageTest.java``)."""

import pytest

from flinkml_tpu.params import (
    BoolParam,
    FloatArrayParam,
    FloatParam,
    IntArrayParam,
    IntParam,
    Param,
    ParamValidators,
    StringArrayParam,
    StringParam,
    WithParams,
)


class MyStage(WithParams):
    BOOLEAN_PARAM = BoolParam("booleanParam", "Description", False)
    INT_PARAM = IntParam("intParam", "Description", 1, ParamValidators.lt_eq(100))
    FLOAT_PARAM = FloatParam("floatParam", "Description", 3.0, ParamValidators.lt_eq(100.0))
    STRING_PARAM = StringParam("stringParam", "Description", "5")
    INT_ARRAY_PARAM = IntArrayParam("intArrayParam", "Description", [6, 7])
    FLOAT_ARRAY_PARAM = FloatArrayParam("floatArrayParam", "Description", [10.0, 11.0])
    STRING_ARRAY_PARAM = StringArrayParam("stringArrayParam", "Description", ["14", "15"])

    def __init__(self):
        super().__init__()


def test_defaults():
    s = MyStage()
    assert s.get(MyStage.BOOLEAN_PARAM) is False
    assert s.get(MyStage.INT_PARAM) == 1
    assert s.get(MyStage.FLOAT_PARAM) == 3.0
    assert s.get(MyStage.STRING_PARAM) == "5"
    assert s.get(MyStage.INT_ARRAY_PARAM) == [6, 7]


def test_set_get_and_chaining():
    s = MyStage()
    assert s.set(MyStage.INT_PARAM, 7) is s
    assert s.get(MyStage.INT_PARAM) == 7


def test_snake_case_sugar():
    s = MyStage()
    s.set_int_param(42)
    assert s.get_int_param() == 42
    s.set_string_array_param(["a", "b"])
    assert s.get_string_array_param() == ["a", "b"]
    with pytest.raises(AttributeError):
        s.set_nonexistent_param(1)


def test_validator_rejects():
    s = MyStage()
    with pytest.raises(ValueError):
        s.set(MyStage.INT_PARAM, 101)


def test_invalid_default_rejected():
    with pytest.raises(ValueError):
        IntParam("bad", "d", 200, ParamValidators.lt_eq(100))


def test_validators():
    v = ParamValidators
    assert v.gt(5)(6) and not v.gt(5)(5)
    assert v.gt_eq(5)(5) and not v.gt_eq(5)(4)
    assert v.lt(5)(4) and not v.lt(5)(5)
    assert v.lt_eq(5)(5) and not v.lt_eq(5)(6)
    assert v.in_range(0, 1)(0.5) and not v.in_range(0, 1)(2)
    assert not v.in_range(0, 1, lower_inclusive=False)(0)
    assert not v.in_range(0, 1, upper_inclusive=False)(1)
    assert v.in_array(["a", "b"])("a") and not v.in_array(["a", "b"])("c")
    assert v.not_null()(0) and not v.not_null()(None)
    assert v.non_empty_array()([1]) and not v.non_empty_array()([])
    assert not v.gt(5)(None)


def test_json_round_trip():
    s = MyStage()
    s.set(MyStage.INT_PARAM, 9)
    s.set(MyStage.FLOAT_ARRAY_PARAM, [1.5, 2.5])
    encoded = s.get_param_map_json()
    restored = MyStage().load_param_map_json(encoded)
    for p in MyStage.params():
        assert restored.get(p) == s.get(p), p.name


def test_json_decode_coerces_types():
    s = MyStage().load_param_map_json({"intParam": 3.0, "floatParam": 7})
    assert s.get(MyStage.INT_PARAM) == 3 and isinstance(s.get(MyStage.INT_PARAM), int)
    assert s.get(MyStage.FLOAT_PARAM) == 7.0 and isinstance(s.get(MyStage.FLOAT_PARAM), float)


def test_unknown_json_params_tolerated():
    MyStage().load_param_map_json({"unknownParam": 1})


def test_param_inheritance():
    class Child(MyStage):
        EXTRA = IntParam("extraParam", "d", 0)

    c = Child()
    names = [p.name for p in Child.params()]
    assert "intParam" in names and "extraParam" in names
    assert c.get(Child.EXTRA) == 0


def test_get_undefined_param_raises():
    foreign = IntParam("foreign", "d", 0)
    with pytest.raises(ValueError):
        MyStage().get(foreign)


def test_set_undefined_param_raises():
    foreign = IntParam("foreign", "d", 0)
    with pytest.raises(ValueError):
        MyStage().set(foreign, 5)
