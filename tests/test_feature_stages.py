"""StandardScaler / MinMaxScaler / VectorAssembler vs sklearn + semantics."""

import numpy as np
import pytest
from sklearn.preprocessing import MinMaxScaler as SkMinMax
from sklearn.preprocessing import StandardScaler as SkStandard

from flinkml_tpu.models import (
    MinMaxScaler,
    MinMaxScalerModel,
    StandardScaler,
    StandardScalerModel,
    VectorAssembler,
)
from flinkml_tpu.table import Table


def _x(n=103, d=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(loc=3.0, scale=2.5, size=(n, d))
    x[:, 2] = 7.0  # constant feature: degenerate std/span
    return x


def test_standard_scaler_matches_sklearn():
    x = _x()
    t = Table({"input": x})
    model = StandardScaler().fit(t)
    (out,) = model.transform(t)
    ref = SkStandard().fit_transform(x)
    np.testing.assert_allclose(out.column("output"), ref, rtol=1e-5, atol=1e-5)


def test_standard_scaler_flags():
    x = _x(seed=1)
    t = Table({"input": x})
    m = StandardScaler().set(StandardScaler.WITH_MEAN, False).fit(t)
    (out,) = m.transform(t)
    ref = SkStandard(with_mean=False).fit_transform(x)
    np.testing.assert_allclose(out.column("output"), ref, rtol=1e-5, atol=1e-5)
    m2 = StandardScaler().set(StandardScaler.WITH_STD, False).fit(t)
    (out2,) = m2.transform(t)
    np.testing.assert_allclose(
        out2.column("output"), x - x.mean(0), rtol=1e-5, atol=1e-5
    )


def test_standard_scaler_save_load(tmp_path):
    t = Table({"input": _x(seed=2)})
    model = StandardScaler().fit(t)
    model.save(str(tmp_path / "ss"))
    loaded = StandardScalerModel.load(str(tmp_path / "ss"))
    np.testing.assert_allclose(
        loaded.transform(t)[0].column("output"),
        model.transform(t)[0].column("output"),
    )


def test_min_max_scaler_matches_sklearn():
    x = _x(seed=3)
    t = Table({"input": x})
    model = MinMaxScaler().fit(t)
    (out,) = model.transform(t)
    ref = SkMinMax().fit_transform(x)
    got = np.asarray(out.column("output"), dtype=np.float64)
    # Constant column: we map to mid-range 0.5; sklearn maps to min_.
    np.testing.assert_allclose(
        np.delete(got, 2, axis=1), np.delete(ref, 2, axis=1),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(got[:, 2], 0.5)


def test_min_max_scaler_custom_range_and_roundtrip(tmp_path):
    x = _x(seed=4)
    t = Table({"input": x})
    model = (MinMaxScaler().set(MinMaxScaler.MIN, -2.0)
             .set(MinMaxScaler.MAX, 2.0).fit(t))
    (out,) = model.transform(t)
    got = np.asarray(out.column("output"), dtype=np.float64)
    assert got[:, 0].min() == pytest.approx(-2.0)
    assert got[:, 0].max() == pytest.approx(2.0)
    model.save(str(tmp_path / "mm"))
    loaded = MinMaxScalerModel.load(str(tmp_path / "mm"))
    np.testing.assert_allclose(
        loaded.transform(t)[0].column("output"), got
    )


def test_min_max_rejects_bad_range():
    with pytest.raises(ValueError, match="min"):
        (MinMaxScaler().set(MinMaxScaler.MIN, 2.0)
         .set(MinMaxScaler.MAX, 1.0).fit(Table({"input": _x()})))


def test_vector_assembler_concatenates():
    t = Table({
        "a": np.asarray([1.0, 2.0, 3.0]),
        "b": np.asarray([[10.0, 20.0], [30.0, 40.0], [50.0, 60.0]]),
    })
    va = VectorAssembler().set_input_cols(["a", "b"])
    (out,) = va.transform(t)
    np.testing.assert_allclose(
        out.column("features"),
        [[1, 10, 20], [2, 30, 40], [3, 50, 60]],
    )


def test_vector_assembler_handle_invalid():
    t = Table({
        "a": np.asarray([1.0, np.nan, 3.0]),
        "b": np.asarray([4.0, 5.0, 6.0]),
    })
    va = VectorAssembler().set_input_cols(["a", "b"])
    with pytest.raises(ValueError, match="non-finite"):
        va.transform(t)
    va.set_handle_invalid("skip")
    (out,) = va.transform(t)
    np.testing.assert_allclose(out.column("features"), [[1, 4], [3, 6]])
    np.testing.assert_allclose(out.column("b"), [4, 6])  # rows dropped everywhere
    va.set_handle_invalid("keep")
    (out2,) = va.transform(t)
    assert np.isnan(out2.column("features")[1, 0])


def test_scalers_in_pipeline():
    from flinkml_tpu.pipeline import Pipeline

    x = _x(seed=5)
    t = Table({"input": x})
    pipe = Pipeline([
        StandardScaler(),
        MinMaxScaler().set(MinMaxScaler.INPUT_COL, "output")
                      .set(MinMaxScaler.OUTPUT_COL, "scaled"),
    ])
    model = pipe.fit(t)
    (out,) = model.transform(t)
    got = np.asarray(out.column("scaled"), np.float64)
    # f32 device extrema vs f64 transform: allow rounding slop at the edges.
    assert np.nanmin(got) >= -1e-6 and np.nanmax(got) <= 1.0 + 1e-6


def test_standard_scaler_large_mean_no_cancellation():
    """Regression: one-pass E[x^2]-E[x]^2 in f32 catastrophically cancels
    for |mean| >> std; the two-pass centered form must not."""
    rng = np.random.default_rng(11)
    x = rng.normal(loc=1e5, scale=1.0, size=(256, 3))
    model = StandardScaler().fit(Table({"input": x}))
    (out,) = model.transform(Table({"input": x}))
    got = np.asarray(out.column("output"), np.float64)
    np.testing.assert_allclose(got.std(axis=0), 1.0, rtol=1e-3)
    np.testing.assert_allclose(got.mean(axis=0), 0.0, atol=1e-3)
