"""Clean-process autoscale scenario behind ``tests/test_autoscaler.py``.

Why a child process: the scale-up acceptance ("a new replica warms via
compile-cache retarget loads — zero new XLA compiles in-process") is
serialization-dependent, and the suite conftest's jax persistent cache
poisons XLA:CPU executable serialization process-wide (the PR 11
finding documented in ``tests/_compile_cache_child.py``). This script
runs the scenario in a fresh interpreter — which is also the production
shape: a serving process that autoscales never touched the test cache —
and prints a JSON report the pytest module asserts over.
"""

import json
import os
import sys
import tempfile


def main() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from flinkml_tpu import compile_cache, pipeline_fusion
    from flinkml_tpu.models.logistic_regression import LogisticRegression
    from flinkml_tpu.models.scalers import StandardScaler
    from flinkml_tpu.pipeline import PipelineModel
    from flinkml_tpu.serving import ReplicaPool, ServingConfig
    from flinkml_tpu.table import Table
    from flinkml_tpu.utils.metrics import metrics

    rng = np.random.default_rng(0)
    x = rng.normal(size=(400, 8))
    y = (x @ rng.normal(size=8) > 0).astype(np.float64)
    train = Table({"features": x, "label": y})
    sc = (StandardScaler().set(StandardScaler.INPUT_COL, "features")
          .set(StandardScaler.OUTPUT_COL, "scaled").fit(train))
    (t2,) = sc.transform(train)
    lr = (LogisticRegression()
          .set(LogisticRegression.FEATURES_COL, "scaled")
          .set(LogisticRegression.LABEL_COL, "label")
          .set_max_iter(3).fit(t2))
    model = PipelineModel([sc, lr])

    store_dir = tempfile.mkdtemp(prefix="autoscale-child-")
    compile_cache.configure(store_dir)

    def counters():
        return dict(
            metrics.group("pipeline.fusion").snapshot()["counters"]
        )

    pool = ReplicaPool(
        model, Table({"features": x[:4]}),
        config=ServingConfig(max_batch_rows=32, max_queue_rows=256,
                             max_wait_ms=1.0),
        n_replicas=2, output_cols=("prediction",), name="child_pool",
    ).start()
    baseline = np.asarray(
        pool.predict({"features": x[:16]}).column("prediction")
    )
    after_start = counters()

    # The autoscaler's scale-up path, twice (fresh devices each time).
    r2 = pool.add_replica()
    r3 = pool.add_replica()
    after_scale = counters()

    # The new replicas serve, bitwise-identically (route to them
    # directly through their engines — the pool's router would balance).
    scaled_preds = [
        np.asarray(r.engine.predict(
            {"features": x[:16]}).column("prediction"))
        for r in (r2, r3)
    ]
    parity = all(np.array_equal(baseline, p) for p in scaled_preds)
    pool.stop()

    print(json.dumps({
        "compiles_after_start": after_start.get("compiles", 0),
        "compiles_after_scale": after_scale.get("compiles", 0),
        "new_compiles_on_scale_up": (
            after_scale.get("compiles", 0) - after_start.get("compiles", 0)
        ),
        "aot_loads_on_scale_up": (
            after_scale.get("aot_loads", 0) - after_start.get("aot_loads", 0)
        ),
        "scaled_replica_parity_bitwise": bool(parity),
        "replicas": 4,
    }))


if __name__ == "__main__":
    sys.exit(main())
