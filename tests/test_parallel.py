"""Distributed-primitive tests on the virtual 8-device CPU mesh — the
MiniCluster analog of the reference's AllReduceImplTest /
BroadcastUtilsTest / DataStreamUtilsTest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flinkml_tpu.parallel import (
    DeviceMesh,
    all_reduce_sum,
    broadcast,
    keyed_aggregate,
    map_partition,
    pad_to_multiple,
)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_mesh_defaults(mesh):
    assert mesh.num_devices == 8
    assert mesh.axis_names == ("data",)
    assert mesh.axis_size() == 8


def test_mesh_too_large():
    with pytest.raises(ValueError):
        DeviceMesh({"data": 16})


def test_multi_axis_mesh():
    m = DeviceMesh({"data": 4, "model": 2})
    assert m.axis_size("data") == 4
    assert m.axis_size("model") == 2


def test_shard_batch_and_replicate(mesh):
    x = np.arange(16.0).reshape(16, 1)
    sharded = mesh.shard_batch(x)
    assert sharded.sharding.spec == P("data")
    rep = mesh.replicate(np.ones(3))
    assert rep.sharding.spec == P()
    with pytest.raises(ValueError):
        mesh.shard_batch(np.ones((9, 2)))


def test_pad_to_multiple():
    x = np.ones((9, 2))
    padded, n = pad_to_multiple(x, 8)
    assert padded.shape == (16, 2) and n == 9
    assert padded[9:].sum() == 0
    same, n2 = pad_to_multiple(np.ones((8, 2)), 8)
    assert same.shape == (8, 2) and n2 == 8


def test_all_reduce_sum_matches_reference_semantics(mesh, rng):
    # Each of P=8 "tasks" holds one double[]; result = elementwise sum on all.
    contributions = rng.normal(size=(8, 100))
    result = all_reduce_sum(mesh, mesh.shard_batch(contributions))
    np.testing.assert_allclose(np.asarray(result), contributions.sum(0), rtol=1e-12)
    assert result.sharding.spec == P()


def test_all_reduce_sum_multiple_rows_per_device(mesh, rng):
    contributions = rng.normal(size=(24, 5))
    result = all_reduce_sum(mesh, contributions)
    np.testing.assert_allclose(np.asarray(result), contributions.sum(0), rtol=1e-12)


def test_all_reduce_inside_jit(mesh, rng):
    x = mesh.shard_batch(rng.normal(size=(8, 10)))

    @jax.jit
    def step(x):
        return all_reduce_sum(mesh, x) * 2.0

    np.testing.assert_allclose(np.asarray(step(x)), np.asarray(x).sum(0) * 2, rtol=1e-12)


def test_broadcast(mesh):
    model = {"w": np.arange(5.0), "b": np.float64(2.0)}
    rep = broadcast(mesh, model)
    assert rep["w"].sharding.spec == P()
    np.testing.assert_array_equal(np.asarray(rep["w"]), model["w"])


def test_keyed_aggregate(mesh, rng):
    n, k = 64, 5
    values = rng.normal(size=(n, 3))
    keys = rng.integers(0, k, size=n)
    result = keyed_aggregate(mesh, values, keys, k)
    expected = np.zeros((k, 3))
    np.add.at(expected, keys, values)
    np.testing.assert_allclose(np.asarray(result), expected, rtol=1e-10)


def test_keyed_aggregate_scalar_values(mesh, rng):
    values = rng.normal(size=32)
    keys = rng.integers(0, 4, size=32)
    result = keyed_aggregate(mesh, values, keys, 4)
    expected = np.bincount(keys, weights=values, minlength=4)
    np.testing.assert_allclose(np.asarray(result), expected, rtol=1e-10)


def test_map_partition_per_shard(mesh):
    # Each shard of 2 rows -> its local sum; 8 partitions concatenated.
    x = np.arange(16.0).reshape(16, 1)

    def local_sum(shard):
        return jnp.sum(shard, axis=0, keepdims=True)

    out = np.asarray(map_partition(mesh, local_sum, x))
    assert out.shape == (8, 1)
    expected = x.reshape(8, 2).sum(1, keepdims=True)
    np.testing.assert_allclose(out, expected)


def test_map_partition_replicated_output(mesh):
    x = np.arange(16.0)

    def global_mean(shard):
        total = jax.lax.psum(jnp.sum(shard), DeviceMesh.DATA_AXIS)
        count = jax.lax.psum(shard.shape[0], DeviceMesh.DATA_AXIS)
        return total / count

    out = map_partition(mesh, global_mean, x, out_specs=P())
    assert float(out) == pytest.approx(x.mean())
