"""BLAS facade + distance + sparse kernel tests with numpy golden values —
the TPU analog of BLASTest (``flink-ml-core/src/test/java/.../linalg/BLASTest.java``)."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from flinkml_tpu.linalg import Vectors
from flinkml_tpu.ops import blas
from flinkml_tpu.ops.distance import DistanceMeasure
from flinkml_tpu.ops.sparse import BatchedCSR


@pytest.fixture
def xs(rng):
    return rng.normal(size=(7, 5))


def test_asum_axpy_dot_norm2_scal(rng):
    x = rng.normal(size=8)
    y = rng.normal(size=8)
    assert float(blas.asum(x)) == pytest.approx(np.abs(x).sum())
    assert np.allclose(blas.axpy(2.5, x, y), 2.5 * x + y)
    assert float(blas.dot(x, y)) == pytest.approx(np.dot(x, y))
    assert float(blas.norm2(x)) == pytest.approx(np.linalg.norm(x))
    assert np.allclose(blas.scal(3.0, x), 3.0 * x)


def test_gemv(rng):
    a = rng.normal(size=(4, 6))
    x = rng.normal(size=6)
    y = rng.normal(size=4)
    assert np.allclose(blas.gemv(2.0, a, x), 2.0 * a @ x)
    assert np.allclose(blas.gemv(2.0, a, x, 0.5, y), 2.0 * a @ x + 0.5 * y)
    xt = rng.normal(size=4)
    assert np.allclose(blas.gemv(1.0, a, xt, trans=True), a.T @ xt)


def test_blas_ops_jit_compatible(rng):
    """Every facade op must trace under jit (the whole point of the layer)."""
    x = jnp.asarray(rng.normal(size=8))
    y = jnp.asarray(rng.normal(size=8))
    f = jax.jit(lambda x, y: blas.axpy(2.0, x, y) + blas.dot(x, y) * blas.norm2(x))
    np.testing.assert_allclose(
        np.asarray(f(x, y)),
        2.0 * np.asarray(x) + np.asarray(y) + np.dot(x, y) * np.linalg.norm(x),
        rtol=1e-6,
    )


def test_squared_distances(xs, rng):
    ys = rng.normal(size=(3, 5))
    d2 = np.asarray(blas.squared_distances(xs, ys))
    expected = ((xs[:, None, :] - ys[None, :, :]) ** 2).sum(-1)
    assert np.allclose(d2, expected, atol=1e-8)


def test_euclidean_distance_measure(xs, rng):
    m = DistanceMeasure.get_instance("euclidean")
    ys = rng.normal(size=(3, 5))
    assert float(m.distance(xs[0], ys[0])) == pytest.approx(
        np.linalg.norm(xs[0] - ys[0])
    )
    pw = np.asarray(m.pairwise(xs, ys))
    expected = np.linalg.norm(xs[:, None, :] - ys[None, :, :], axis=-1)
    assert np.allclose(pw, expected, atol=1e-7)
    nearest = np.asarray(m.nearest(xs, ys))
    assert np.array_equal(nearest, expected.argmin(-1))


def test_cosine_and_manhattan(rng):
    a, b = rng.normal(size=5), rng.normal(size=5)
    cos = DistanceMeasure.get_instance("cosine")
    assert float(cos.distance(a, b)) == pytest.approx(
        1 - np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b))
    )
    man = DistanceMeasure.get_instance("manhattan")
    assert float(man.distance(a, b)) == pytest.approx(np.abs(a - b).sum())


def test_unknown_measure():
    with pytest.raises(ValueError):
        DistanceMeasure.get_instance("chebyshev")


# -- BatchedCSR ------------------------------------------------------------

def test_batched_csr_from_sparse_vectors():
    vecs = [
        Vectors.sparse(6, [0, 4], [1.0, 2.0]),
        Vectors.sparse(6, [2], [3.0]),
        Vectors.sparse(6, [], []),
    ]
    b = BatchedCSR.from_sparse_vectors(vecs)
    assert b.num_rows == 3 and b.dim == 6 and b.max_nnz == 2
    dense = np.asarray(b.to_dense())
    expected = np.stack([v.to_array() for v in vecs])
    assert np.allclose(dense, expected)


def test_batched_csr_matvec_rmatvec(rng):
    mat = sp.random(20, 15, density=0.3, random_state=42, format="csr")
    b = BatchedCSR.from_scipy(mat, dtype=np.float64)
    w = rng.normal(size=15)
    assert np.allclose(np.asarray(b.matvec(w)), mat @ w, atol=1e-10)
    c = rng.normal(size=20)
    assert np.allclose(np.asarray(b.rmatvec(c)), mat.T @ c, atol=1e-10)


def test_batched_csr_padding_is_noop(rng):
    # Padded lanes (index 0, value 0) must not contribute even when a real
    # feature 0 exists.
    vecs = [Vectors.sparse(4, [0], [5.0]), Vectors.sparse(4, [1, 2], [1.0, 1.0])]
    b = BatchedCSR.from_sparse_vectors(vecs)
    w = np.array([10.0, 1.0, 1.0, 1.0])
    out = np.asarray(b.matvec(w))
    assert np.allclose(out, [50.0, 2.0])
    grad = np.asarray(b.rmatvec(np.array([1.0, 1.0])))
    assert np.allclose(grad, [5.0, 1.0, 1.0, 0.0])


def test_batched_csr_jit(rng):
    mat = sp.random(8, 10, density=0.4, random_state=7, format="csr")
    b = BatchedCSR.from_scipy(mat, dtype=np.float64)
    w = jnp.asarray(rng.normal(size=10))

    @jax.jit
    def f(idx, vals, w):
        return jnp.sum(BatchedCSR(idx, vals, 10).matvec(w))

    assert float(f(b.indices, b.values, w)) == pytest.approx(float((mat @ np.asarray(w)).sum()))
