"""Pass 7 (memory liveness, FML70x) + the memory-aware plan/serving
wiring: the jaxpr peak-live walker, the FML701-704 rules, the
``*.memory.json`` consumer, ``infer_plan``'s quant-tier mode, and the
serving engine's load-time budget gate."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flinkml_tpu.analysis.memory import (
    DONATION_MIN_ELEMS,
    MemoryEstimate,
    check_memory_file,
    check_memory_fn,
    check_tier_ladder,
    estimate_fn_memory,
    estimate_serving_bytes,
    _probe_program,
)
from flinkml_tpu.sharding.plan import (
    BATCH_PARALLEL,
    EMBEDDING,
    FSDP,
    NoFeasiblePlanError,
    QUANT_TIER_LADDER,
    REPLICATED,
    human_bytes,
    infer_plan,
    per_device_state_bytes_tiered,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


# ---------------------------------------------------------------------------
# the liveness estimator
# ---------------------------------------------------------------------------

def test_estimate_counts_arguments_and_outputs():
    est = estimate_fn_memory(
        lambda x: (x * 2.0).sum(), np.zeros((1024, 8), np.float32)
    )
    assert isinstance(est, MemoryEstimate)
    assert est.argument_bytes == 1024 * 8 * 4
    assert est.output_bytes == 4  # the scalar sum
    # The undonated argument is resident for the whole program, so the
    # peak can never undercut it.
    assert est.peak_bytes >= est.argument_bytes
    assert "peak" in est.render() and "KiB" in est.render()


def test_liveness_frees_dead_intermediates():
    """A long elementwise chain must NOT estimate as the sum of every
    intermediate: each x_i dies at the next eqn, so the intermediate
    peak stays O(2 buffers), not O(chain length)."""

    def chain(x):
        for _ in range(16):
            x = x * 1.0001 + 1.0
        return x

    est = estimate_fn_memory(chain, np.zeros((4096,), np.float32))
    buf = 4096 * 4
    # 16 iterations x 2 eqns each; without last-use frees the
    # intermediate peak would be ~32 buffers.
    assert est.temp_peak_bytes <= 4 * buf


def test_donated_argument_aliases_the_update():
    """Donating the state buffer lets the update write in place: the
    peak drops by one state-sized buffer — exactly the FML703 claim."""

    def step(state, grad):
        return state - grad

    a = np.zeros((8192,), np.float32)
    undonated = estimate_fn_memory(step, a, a, param_argnums=(0,))
    donated = estimate_fn_memory(step, a, a, param_argnums=(0,),
                                 donate_argnums=(0,))
    assert donated.peak_bytes == undonated.peak_bytes - 8192 * 4


def test_params_are_sized_by_the_plan_slice():
    """Under FSDP on an 8-way axis a 1-D state leaf costs 1/8th per
    device; the batch-parallel plan replicates it."""
    state = {"coef": np.zeros((8192,), np.float32)}
    xb = np.zeros((4, 8192), np.float32)

    def step(state, xb):
        return {"coef": state["coef"] - xb.sum(0)}

    mesh = {"data": 1, "fsdp": 8}
    fsdp = estimate_fn_memory(step, state, xb, plan=FSDP, mesh=mesh,
                              param_argnums=(0,))
    repl = estimate_fn_memory(step, state, xb, plan=BATCH_PARALLEL,
                              mesh=mesh, param_argnums=(0,))
    assert fsdp.param_bytes == 8192 * 4 // 8
    assert repl.param_bytes == 8192 * 4


def test_batch_sharded_intermediates_divide_the_leading_dim():
    x = np.zeros((800, 16), np.float32)
    est = estimate_fn_memory(lambda x: (x * 2.0).sum(),
                             x, plan=BATCH_PARALLEL,
                             mesh={"data": 8})
    # ceil(800 / 8) = 100 rows per device.
    assert est.argument_bytes == 100 * 16 * 4


def test_control_flow_recursion_does_not_crash_and_adds_scratch():
    def body(c, x):
        return c + (x * 2.0).sum(), ()

    def f(xs):
        out, _ = jax.lax.scan(body, 0.0, xs)
        return jax.lax.cond(out > 0, lambda: out * 2, lambda: out)

    est = estimate_fn_memory(f, np.zeros((64, 128), np.float32))
    assert est.peak_bytes >= 64 * 128 * 4


def test_jitted_subprogram_is_walked():
    inner = jax.jit(lambda x: jnp.tanh(x) * jnp.exp(x) + jnp.sin(x))
    est = estimate_fn_memory(lambda x: inner(x).sum(),
                             np.zeros((2048,), np.float32))
    # The pjit sub-jaxpr's intermediates register as scratch.
    assert est.temp_peak_bytes >= 2048 * 4


# ---------------------------------------------------------------------------
# FML701 — peak vs budget
# ---------------------------------------------------------------------------

def test_fml701_fires_over_budget_and_is_quiet_under_it():
    fn, args, p, d = _probe_program(
        {"name": "sgd_step", "dim": 4096, "rows": 64, "donate": True}
    )
    over = check_memory_fn(fn, *args, plan=FSDP,
                           mesh={"data": 1, "fsdp": 8},
                           hbm_budget_bytes=1024, param_argnums=p,
                           donate_argnums=d, program="sgd_step")
    assert "FML701" in [f.rule for f in over]
    (f701,) = [f for f in over if f.rule == "FML701"]
    assert "KiB" in f701.message or "MiB" in f701.message
    clean = check_memory_fn(fn, *args, plan=FSDP,
                            mesh={"data": 1, "fsdp": 8},
                            hbm_budget_bytes=1 << 30, param_argnums=p,
                            donate_argnums=d, program="sgd_step")
    assert "FML701" not in [f.rule for f in clean]


# ---------------------------------------------------------------------------
# FML702 — vocab-scale hot-path intermediates
# ---------------------------------------------------------------------------

def test_fml702_flags_one_hot_densification():
    fn, args, p, d = _probe_program(
        {"name": "embedding_dense_grad", "vocab": 4096, "dim": 16,
         "rows": 32}
    )
    fs = check_memory_fn(fn, *args, plan=REPLICATED, mesh={},
                         hbm_budget_bytes=1 << 30, param_argnums=p,
                         donate_argnums=d, program="dense_grad")
    rules = [f.rule for f in fs]
    assert "FML702" in rules
    f702 = next(f for f in fs if f.rule == "FML702")
    assert "4096" in f702.message


def test_fml702_exempts_batch_sized_lookup_and_state_output():
    """The contract shape — gather batch rows, scatter the update back.
    The updated table is a program OUTPUT (sanctioned state), so only a
    dying vocab-scale intermediate may flag."""
    fn, args, p, d = _probe_program(
        {"name": "embedding_lookup", "vocab": 4096, "dim": 16, "rows": 32}
    )
    fs = check_memory_fn(fn, *args, plan=REPLICATED, mesh={},
                         hbm_budget_bytes=1 << 30, param_argnums=p,
                         donate_argnums=d, program="lookup")
    assert [f.rule for f in fs] == []

    def scatter_update(state, ids, delta):
        table = state["emb/embedding"]
        return {"emb/embedding": table.at[ids].add(delta)}

    vocab, dim, rows = 4096, 16, 32
    table = jax.ShapeDtypeStruct((vocab, dim), np.float32)
    ids = jax.ShapeDtypeStruct((rows,), np.int32)
    delta = jax.ShapeDtypeStruct((rows, dim), np.float32)
    fs = check_memory_fn(scatter_update, {"emb/embedding": table}, ids,
                         delta, plan=REPLICATED, mesh={},
                         hbm_budget_bytes=1 << 30, param_argnums=(0,),
                         donate_argnums=(0,), program="scatter_update")
    assert "FML702" not in [f.rule for f in fs]


def test_fml702_ignores_small_tables():
    """A tiny table's whole-row intermediate is not "vocab-scale"."""

    def dense(state, ids, grad):
        table = state["t/embedding"]
        oh = jax.nn.one_hot(ids, table.shape[0], dtype=table.dtype)
        return {"t/embedding": table + oh.T @ grad}

    table = jax.ShapeDtypeStruct((64, 8), np.float32)  # < min rows
    ids = jax.ShapeDtypeStruct((4,), np.int32)
    grad = jax.ShapeDtypeStruct((4, 8), np.float32)
    fs = check_memory_fn(dense, {"t/embedding": table}, ids, grad,
                         plan=REPLICATED, mesh={},
                         hbm_budget_bytes=1 << 30, param_argnums=(0,),
                         donate_argnums=(0,), program="tiny")
    assert "FML702" not in [f.rule for f in fs]


# ---------------------------------------------------------------------------
# FML703 — undonated same-shape state updates (live, on the real step)
# ---------------------------------------------------------------------------

def test_fml703_live_on_undonated_sgd_step():
    """The REAL training step (sharding.apply.linear_step_fn) traced
    without donation flags every same-shape state leaf; with donation it
    is clean — the exact missed-donate_argnums shape, demonstrated on
    the program the product actually compiles."""
    fn, args, p, d = _probe_program(
        {"name": "sgd_step", "dim": 4096, "rows": 64, "donate": False}
    )
    fs = check_memory_fn(fn, *args, plan=REPLICATED, mesh={},
                         hbm_budget_bytes=1 << 30, param_argnums=p,
                         donate_argnums=d, program="sgd_step")
    cols = sorted(f.column for f in fs if f.rule == "FML703")
    assert cols == ["coef", "momentum"]
    fn, args, p, d = _probe_program(
        {"name": "sgd_step", "dim": 4096, "rows": 64, "donate": True}
    )
    fs = check_memory_fn(fn, *args, plan=REPLICATED, mesh={},
                         hbm_budget_bytes=1 << 30, param_argnums=p,
                         donate_argnums=d, program="sgd_step")
    assert [f.rule for f in fs] == []


def test_fml703_adam_flags_every_slot_but_not_the_step_counter():
    fn, args, p, d = _probe_program(
        {"name": "adam_step", "dim": 512, "rows": 16, "donate": False}
    )
    fs = check_memory_fn(fn, *args, plan=REPLICATED, mesh={},
                         hbm_budget_bytes=1 << 30, param_argnums=p,
                         donate_argnums=d, program="adam_step")
    cols = sorted(f.column for f in fs if f.rule == "FML703")
    # coef/m/v flag; the scalar step counter is below the elems floor.
    assert cols == ["coef", "m", "v"]
    assert 512 >= DONATION_MIN_ELEMS


# ---------------------------------------------------------------------------
# FML704 — no tier fits
# ---------------------------------------------------------------------------

def test_fml704_lists_every_tier_footprint():
    fs = check_tier_ladder({"data": 1, "fsdp": 8},
                           {"emb/embedding": (1 << 20, 64)}, 4096)
    assert [f.rule for f in fs] == ["FML704"]
    msg = fs[0].message
    for tier in QUANT_TIER_LADDER:
        assert f"@{tier}" in msg
    assert "at any quant tier" in msg and "MiB" in msg


def test_tier_ladder_quiet_when_a_tier_fits():
    shapes = {"emb/embedding": (1 << 14, 64)}
    # f32 fsdp footprint: (2^14/8)*64*4*2 = 1 MiB -> a 2 MiB budget fits.
    assert check_tier_ladder({"data": 1, "fsdp": 8}, shapes, 2 << 20) == []


# ---------------------------------------------------------------------------
# infer_plan memory-aware mode
# ---------------------------------------------------------------------------

def test_infer_plan_tiered_returns_plan_and_tier():
    shapes = {"coef": (8192,)}
    plan, tier = infer_plan({"data": 1, "fsdp": 8}, shapes, 1 << 20,
                            quant_tiers=True)
    assert plan.name == "batch_parallel" and tier == "float32"


def test_infer_plan_routes_over_budget_f32_to_int8():
    """The ROADMAP item 3 shape: a parameter universe infeasible at f32
    re-runs the footprint against the quantized widths and CHOOSES
    quantization to fit the budget."""
    mesh = {"data": 1, "fsdp": 8, "tp": 1}
    shapes = {"emb/embedding": (1 << 16, 64)}
    # Serving footprints (no optimizer slots): int8 stores 1 B codes, so
    # it sits BELOW bf16 — slots would stay f32 and invert the order.
    bf16 = per_device_state_bytes_tiered(FSDP, mesh, shapes, "bfloat16",
                                         optimizer_slots=0)
    int8 = per_device_state_bytes_tiered(FSDP, mesh, shapes, "int8",
                                         optimizer_slots=0)
    assert int8 < bf16
    budget = (bf16 + int8) // 2  # below every float tier, above int8
    with pytest.raises(NoFeasiblePlanError):
        infer_plan(mesh, shapes, budget, optimizer_slots=0)  # f32 mode
    plan, tier = infer_plan(mesh, shapes, budget, optimizer_slots=0,
                            quant_tiers=True)
    assert tier == "int8"
    assert per_device_state_bytes_tiered(
        plan, mesh, shapes, tier, optimizer_slots=0
    ) <= budget


def test_tiered_footprint_math():
    mesh = {"data": 1, "fsdp": 8}
    shapes = {"emb/embedding": (1024, 64)}
    slice_elems = (1024 // 8) * 64
    assert per_device_state_bytes_tiered(FSDP, mesh, shapes, "float32") \
        == 4 * slice_elems * 2
    assert per_device_state_bytes_tiered(FSDP, mesh, shapes, "bfloat16") \
        == 2 * slice_elems * 2
    # int8: 1 B codes + 4 B x 64 scale columns; the slot stays f32.
    assert per_device_state_bytes_tiered(FSDP, mesh, shapes, "int8") \
        == (slice_elems + 4 * 64) + 4 * slice_elems
    with pytest.raises(ValueError, match="unknown quant tier"):
        per_device_state_bytes_tiered(FSDP, mesh, shapes, "int4")


def test_no_feasible_plan_message_is_human():
    with pytest.raises(NoFeasiblePlanError) as ei:
        infer_plan({"data": 1, "fsdp": 8}, {"coef": (1 << 22,)}, 1000)
    msg = str(ei.value)
    assert "MiB" in msg and " B)" in msg  # human units + raw parens
    # the budget is stated ONCE (in the header), not per candidate
    assert msg.count("hbm_budget_bytes") == 1


# ---------------------------------------------------------------------------
# *.memory.json consumer + CLI
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule,path", [
    ("FML701", "bad_memory_fml701_over_budget.memory.json"),
    ("FML702", "bad_memory_fml702_dense_grad.memory.json"),
    ("FML703", "bad_memory_fml703_undonated.memory.json"),
    ("FML704", "bad_memory_fml704_no_tier_fits.memory.json"),
])
def test_seeded_memory_fixtures_flag_their_rule(rule, path):
    findings = check_memory_file(os.path.join(FIXTURES, path))
    assert rule in [f.rule for f in findings]


def test_unreadable_memory_file_fails_loudly(tmp_path):
    bad = tmp_path / "broken.memory.json"
    bad.write_text("{not json")
    assert [f.rule for f in check_memory_file(str(bad))] == ["FML701"]
    empty = tmp_path / "empty.memory.json"
    empty.write_text("{}")  # neither a program nor a tier ladder
    assert [f.rule for f in check_memory_file(str(empty))] == ["FML701"]
    badprog = tmp_path / "prog.memory.json"
    badprog.write_text(json.dumps(
        {"program": {"name": "nonsense_step"}}
    ))
    assert [f.rule for f in check_memory_file(str(badprog))] == ["FML701"]


def test_cli_runs_the_memory_pass_and_dir_walk_finds_fixtures(capsys):
    from flinkml_tpu.analysis.__main__ import main

    fixture = os.path.join(
        FIXTURES, "bad_memory_fml701_over_budget.memory.json"
    )
    assert main([fixture, "--no-selfcheck"]) == 1
    capsys.readouterr()  # drop the text report
    # The extension->bucket walk picks .memory.json out of a directory
    # target (the refactor's whole point: one table, no missed ext).
    assert main([FIXTURES, "--no-selfcheck", "--format", "json"]) == 1
    found = json.loads(capsys.readouterr().out)
    assert {"FML701", "FML702", "FML703", "FML704"} <= \
        {f["rule"] for f in found}


# ---------------------------------------------------------------------------
# calibration vs XLA's own memory_analysis (CPU twin of the bench stage)
# ---------------------------------------------------------------------------

def test_estimate_calibrated_against_xla_memory_analysis():
    def f(x):
        h = jnp.tanh(x @ x.T)
        return (h * h).sum()

    x = np.zeros((256, 256), np.float32)
    compiled = jax.jit(f).lower(x).compile()
    ma = compiled.memory_analysis()
    actual = (int(ma.temp_size_in_bytes) + int(ma.argument_size_in_bytes)
              + int(ma.output_size_in_bytes))
    est = estimate_fn_memory(f, x)
    assert 0.5 * actual <= est.peak_bytes <= 2.0 * actual, (
        f"estimate {est.peak_bytes} vs XLA {actual}"
    )


# ---------------------------------------------------------------------------
# serving load-time budget gate
# ---------------------------------------------------------------------------

def test_estimate_serving_bytes_tier_ordering():
    from flinkml_tpu.models.logistic_regression import (
        LogisticRegressionModel,
    )
    from flinkml_tpu.table import Table

    d = 64
    lr = LogisticRegressionModel().set(
        LogisticRegressionModel.FEATURES_COL, "features"
    )
    lr.set_model_data(Table({"coefficient": np.ones((1, d))}))
    schema = {"features": (np.dtype(np.float64), (d,))}
    full = estimate_serving_bytes(lr, schema, 64, policy=None)
    int8 = estimate_serving_bytes(lr, schema, 64,
                                  policy="int8_inference")
    mixed = estimate_serving_bytes(lr, schema, 64,
                                   policy="mixed_inference")
    assert int8 < full and mixed < full
    assert full > 3 * 64 * d * 8  # three batch buffers floor


def test_serving_budget_gate_refuses_swap_and_keeps_old_model(tmp_path):
    from flinkml_tpu.models.logistic_regression import (
        LogisticRegression,
        LogisticRegressionModel,
    )
    from flinkml_tpu.serving import (
        ModelRegistry,
        ServingConfig,
        ServingEngine,
        ServingMemoryError,
    )
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8))
    y = (x @ rng.normal(size=8) > 0).astype(np.float64)
    small = LogisticRegression().set(
        LogisticRegression.FEATURES_COL, "features"
    ).set(LogisticRegression.LABEL_COL, "label").set_max_iter(3).fit(
        Table({"features": x, "label": y})
    )
    # v2: finite (passes the sentinel) but with a multi-MiB learned
    # array — over any KiB-scale budget. It is refused BEFORE warmup,
    # so it never has to transform.
    big = LogisticRegressionModel().set(
        LogisticRegressionModel.FEATURES_COL, "features"
    )
    big.set_model_data(
        Table({"coefficient": np.ones((1, 1 << 20))})
    )

    reg = ModelRegistry(str(tmp_path / "reg"))
    v1 = reg.publish(small)
    eng = ServingEngine(
        reg, Table({"features": x[:4]}),
        ServingConfig(max_batch_rows=64, warmup_row_counts=(4,),
                      hbm_budget_bytes=1 << 20),
        output_cols=("prediction",),
    ).start()
    try:
        assert eng.predict(Table({"features": x[:4]})).version == v1
        v2 = reg.publish(big)
        with pytest.raises(ServingMemoryError, match="keeps serving"):
            eng.swap_to(v2)
        # The refused swap left v1 active and serving.
        assert eng.predict(Table({"features": x[:4]})).version == v1
    finally:
        eng.stop()


def test_human_bytes_rendering():
    assert human_bytes(12 * (1 << 20)) == "12.00 MiB (12582912 B)"
    assert human_bytes(512) == "512 B"
    assert human_bytes(1 << 30) == "1.00 GiB (1073741824 B)"


def test_fml503_messages_are_humanized():
    from flinkml_tpu.analysis.sharding_check import check_plan

    findings = check_plan(
        REPLICATED, {"data": 8},
        param_shapes={"emb/embedding": (1 << 20, 64)},
        hbm_budget_bytes=1 << 20,
    )
    f503 = [f for f in findings if f.rule == "FML503"]
    assert f503 and all(
        "MiB" in f.message and " B)" in f.message for f in f503
    )
