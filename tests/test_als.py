"""ALS: explicit reconstruction, implicit ranking, regularization
semantics, cold start, persistence, recommendations."""

import numpy as np
import pytest

from flinkml_tpu.models import ALS, ALSModel
from flinkml_tpu.table import Table


def _low_rank_ratings(n_users=40, n_items=30, rank=4, frac=0.6, seed=0,
                      noise=0.0):
    rng = np.random.default_rng(seed)
    u = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    v = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    full = u @ v.T
    mask = rng.uniform(size=full.shape) < frac
    users, items = np.nonzero(mask)
    r = full[users, items] + noise * rng.normal(size=users.shape[0])
    return users.astype(np.int64), items.astype(np.int64), r, full


def _als(rank=6, iters=12, reg=0.01, **kw):
    als = (
        ALS().set_rank(rank).set_max_iter(iters).set_reg_param(reg)
        .set_seed(0)
    )
    for name, v in kw.items():
        getattr(als, f"set_{name}")(v)
    return als


def test_explicit_reconstructs_low_rank_matrix():
    users, items, r, full = _low_rank_ratings()
    t = Table({"user": users, "item": items, "rating": r})
    model = _als().fit(t)
    # In-sample predictions recover the observed ratings.
    (out,) = model.transform(t)
    rmse = float(np.sqrt(np.mean((out["prediction"] - r) ** 2)))
    assert rmse < 0.05, rmse
    # And generalize to the held-out entries of the low-rank matrix.
    all_u, all_i = np.meshgrid(
        np.arange(full.shape[0]), np.arange(full.shape[1]), indexing="ij"
    )
    t_all = Table({"user": all_u.ravel(), "item": all_i.ravel()})
    (pred_all,) = model.transform(t_all)
    rmse_all = float(np.sqrt(np.mean(
        (pred_all["prediction"] - full.ravel()) ** 2
    )))
    assert rmse_all < 0.15, rmse_all


def test_regularization_shrinks_factors():
    users, items, r, _ = _low_rank_ratings(seed=1)
    t = Table({"user": users, "item": items, "rating": r})
    small = _als(reg=0.001).fit(t)
    large = _als(reg=10.0).fit(t)
    assert (
        np.linalg.norm(large.user_factors)
        < 0.2 * np.linalg.norm(small.user_factors)
    )


def test_cold_start_nan_and_unseen_ids():
    users, items, r, _ = _low_rank_ratings(seed=2)
    t = Table({"user": users, "item": items, "rating": r})
    model = _als(iters=3).fit(t)
    probe = Table({"user": np.asarray([0, 9999]), "item": np.asarray([0, 0])})
    (out,) = model.transform(probe)
    assert np.isfinite(out["prediction"][0])
    assert np.isnan(out["prediction"][1])


def test_string_ids_work():
    users = np.asarray(["alice", "bob", "alice", "carol", "bob", "carol"])
    items = np.asarray(["x", "x", "y", "y", "z", "z"])
    r = np.asarray([5.0, 4.0, 1.0, 2.0, 3.0, 5.0])
    t = Table({"user": users, "item": items, "rating": r})
    model = _als(rank=2, iters=8, reg=0.1).fit(t)
    (out,) = model.transform(t)
    assert np.all(np.isfinite(out["prediction"]))
    # In-sample ordering is roughly preserved for alice: x (5) > y (1).
    pa = model.transform(
        Table({"user": np.asarray(["alice", "alice"]),
               "item": np.asarray(["x", "y"])})
    )[0]["prediction"]
    assert pa[0] > pa[1]


def test_implicit_ranks_interacted_items_higher():
    rng = np.random.default_rng(3)
    n_users, n_items = 20, 15
    # Two taste clusters: even users like even items, odd like odd.
    users, items, counts = [], [], []
    for u in range(n_users):
        liked = [i for i in range(n_items) if i % 2 == u % 2]
        for i in rng.choice(liked, size=5):
            users.append(u)
            items.append(i)
            counts.append(float(rng.integers(1, 10)))
    t = Table({
        "user": np.asarray(users), "item": np.asarray(items),
        "rating": np.asarray(counts),
    })
    model = _als(rank=4, iters=10, reg=0.1, implicit_prefs=True,
                 alpha=10.0).fit(t)
    ids, scores = model.recommend_for_all_users(5)
    # Top recommendations for user 0 (even cluster) are mostly even items.
    top0 = ids[0]
    assert (top0 % 2 == 0).mean() >= 0.8
    assert np.all(np.diff(scores[0]) <= 1e-6)  # scores sorted descending


def test_implicit_rejects_negative_ratings():
    t = Table({"user": np.asarray([0]), "item": np.asarray([0]),
               "rating": np.asarray([-1.0])})
    with pytest.raises(ValueError, match="non-negative"):
        _als(implicit_prefs=True).fit(t)


def test_save_load_and_model_data_roundtrip(tmp_path):
    users, items, r, _ = _low_rank_ratings(seed=4)
    t = Table({"user": users, "item": items, "rating": r})
    model = _als(iters=4).fit(t)
    model.save(str(tmp_path / "als"))
    loaded = ALSModel.load(str(tmp_path / "als"))
    np.testing.assert_array_equal(loaded.user_factors, model.user_factors)
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_allclose(p2["prediction"], p1["prediction"])
    clone = ALSModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    (p3,) = clone.transform(t)
    np.testing.assert_allclose(p3["prediction"], p1["prediction"])


def test_chunked_path_matches_single_chunk():
    users, items, r, _ = _low_rank_ratings(seed=5)
    t = Table({"user": users, "item": items, "rating": r})
    big = _als(iters=3).fit(t)
    small_chunk = _als(iters=3)
    small_chunk.CHUNK = 64  # force many chunks
    small = small_chunk.fit(t)
    np.testing.assert_allclose(
        small.user_factors, big.user_factors, rtol=2e-4, atol=2e-5
    )


def test_deterministic_given_seed():
    users, items, r, _ = _low_rank_ratings(seed=6)
    t = Table({"user": users, "item": items, "rating": r})
    m1 = _als(iters=3).fit(t)
    m2 = _als(iters=3).fit(t)
    np.testing.assert_array_equal(m1.user_factors, m2.user_factors)


def test_reg_zero_underdetermined_user_stays_finite():
    # User 0 has fewer ratings than rank: with regParam=0 its system is
    # singular; the 1e-6 lambda floor must keep everything finite.
    users = np.asarray([0, 0, 1, 1, 1, 1, 1, 1, 1, 1])
    items = np.asarray([0, 1, 0, 1, 2, 3, 4, 5, 6, 7])
    r = np.linspace(1, 5, 10)
    t = Table({"user": users, "item": items, "rating": r})
    model = _als(rank=6, iters=4, reg=0.0).fit(t)
    assert np.isfinite(model.user_factors).all()
    assert np.isfinite(model.item_factors).all()
    (out,) = model.transform(t)
    assert np.isfinite(out["prediction"]).all()


def test_cumsum_reduction_matches_segment(monkeypatch):
    """FLINKML_TPU_ALS_REDUCTION=cumsum (target-sorted COO + chunked run
    totals) must produce the same factors as the segment_sum reduction,
    explicit and implicit modes (allclose — summation order differs)."""
    from flinkml_tpu.models.als import ALS

    rng = np.random.default_rng(7)
    nnz = 3000
    t = Table({
        "user": rng.integers(0, 64, size=nnz).astype(np.int32),
        "item": rng.integers(0, 50, size=nnz).astype(np.int32),
        "rating": rng.uniform(1, 5, size=nnz).astype(np.float32),
    })

    for implicit in (False, True):
        def fit(layout):
            monkeypatch.setenv("FLINKML_TPU_ALS_REDUCTION", layout)
            est = ALS().set_rank(6).set_max_iter(4).set_seed(0)
            if implicit:
                est = est.set_implicit_prefs(True)
            return est.fit(t)

        m_seg = fit("segment")
        m_cum = fit("cumsum")
        np.testing.assert_allclose(
            m_cum._user_factors, m_seg._user_factors, rtol=5e-4, atol=5e-5
        )
        np.testing.assert_allclose(
            m_cum._item_factors, m_seg._item_factors, rtol=5e-4, atol=5e-5
        )


def test_cumsum_reduction_empty_and_tiny_tables(monkeypatch):
    """The cumsum layout must match segment on degenerate inputs: an
    empty run-table path (zero chunks) and a single-rating table."""
    from flinkml_tpu.models.als import ALS, als_run_tables

    empty_e, empty_c = als_run_tables(np.zeros(0, np.int32), 2, 8)
    assert empty_e.shape[0] == 0 and empty_c.shape[0] == 0

    t = Table({
        "user": np.asarray([3], np.int32),
        "item": np.asarray([1], np.int32),
        "rating": np.asarray([4.0], np.float32),
    })
    monkeypatch.setenv("FLINKML_TPU_ALS_REDUCTION", "cumsum")
    m_cum = ALS().set_rank(3).set_max_iter(2).set_seed(0).fit(t)
    monkeypatch.setenv("FLINKML_TPU_ALS_REDUCTION", "segment")
    m_seg = ALS().set_rank(3).set_max_iter(2).set_seed(0).fit(t)
    np.testing.assert_allclose(
        m_cum._user_factors, m_seg._user_factors, rtol=1e-5
    )
