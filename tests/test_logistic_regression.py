"""LogisticRegression tests — mirrors the reference's LogisticRegressionTest
(``flink-ml-lib/src/test/java/.../classification/LogisticRegressionTest.java``):
param defaults, fit/predict on the reference's 10-row dataset, save/load,
model-data get/set, plus sklearn golden comparison and multi-device runs."""

import numpy as np
import pytest

from flinkml_tpu.models import LogisticRegression, LogisticRegressionModel
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


def reference_train_table():
    # The reference's binomialTrainData (LogisticRegressionTest.java:64-75):
    # features [i, 2, 3, 4], label 0 for i in 1..5, 1 for i in 11..15,
    # weight cycling 1..5.
    feats, labels, weights = [], [], []
    for i, base in ((1, 0.0), (11, 1.0)):
        for k in range(5):
            feats.append([i + k, 2.0, 3.0, 4.0])
            labels.append(base)
            weights.append(float(k + 1))
    return Table(
        {
            "features": np.asarray(feats, dtype=np.float64),
            "label": np.asarray(labels),
            "weight": np.asarray(weights),
        }
    )


def test_param_defaults():
    lr = LogisticRegression()
    assert lr.get_features_col() == "features"
    assert lr.get_label_col() == "label"
    assert lr.get_prediction_col() == "prediction"
    assert lr.get_raw_prediction_col() == "rawPrediction"
    assert lr.get_max_iter() == 20
    assert lr.get_learning_rate() == 0.1
    assert lr.get_global_batch_size() == 32
    assert lr.get_reg() == 0.0
    assert lr.get_tol() == 1e-6
    assert lr.get_multi_class() == "auto"
    assert lr.get_weight_col() is None


def test_fit_predict_reference_dataset():
    table = reference_train_table()
    lr = LogisticRegression().set_weight_col("weight").set_seed(42).set_max_iter(200)
    model = lr.fit(table)
    (out,) = model.transform(table)
    # Separable data: all predictions must match labels.
    np.testing.assert_array_equal(out["prediction"], table["label"])
    raw = out["rawPrediction"]
    assert raw.shape == (10, 2)
    np.testing.assert_allclose(raw.sum(axis=1), 1.0, atol=1e-6)
    # Class-1 rows get p > 0.5.
    assert (raw[5:, 1] > 0.5).all() and (raw[:5, 1] < 0.5).all()


def test_coefficient_direction_matches_reference():
    # Reference converges to ≈ [0.528, -0.286, -0.429, -0.572]
    # (LogisticRegressionTest.java:91-94): positive on the discriminative
    # feature, negative on constants 2,3,4 with ratios 2:3:4.
    table = reference_train_table()
    model = (
        LogisticRegression()
        .set_weight_col("weight")
        .set_seed(0)
        .set_max_iter(500)
        .fit(table)
    )
    coef = model.coefficient
    assert coef[0] > 0 > coef[1] > coef[2] > coef[3]
    np.testing.assert_allclose(coef[2] / coef[1], 1.5, rtol=0.05)
    np.testing.assert_allclose(coef[3] / coef[1], 2.0, rtol=0.05)


def test_against_sklearn(rng):
    from sklearn.linear_model import LogisticRegression as SkLR

    n, d = 400, 6
    x = rng.normal(size=(n, d))
    true_coef = rng.normal(size=d) * 2
    y = (x @ true_coef + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    table = Table({"features": x, "label": y})

    model = (
        LogisticRegression()
        .set_seed(7)
        .set_max_iter(300)
        .set_global_batch_size(400)
        .set_learning_rate(1.0)
        .fit(table)
    )
    (out,) = model.transform(table)
    ours = np.mean(out["prediction"] == y)

    sk = SkLR(C=np.inf, fit_intercept=False, max_iter=1000).fit(x, y)
    theirs = sk.score(x, y)
    assert ours >= theirs - 0.02, (ours, theirs)
    # Coefficient direction agreement.
    cos = np.dot(model.coefficient, sk.coef_[0]) / (
        np.linalg.norm(model.coefficient) * np.linalg.norm(sk.coef_[0])
    )
    assert cos > 0.99


def test_regularization_shrinks_coefficients():
    table = reference_train_table()
    base = LogisticRegression().set_seed(1).set_max_iter(200).fit(table)
    regd = LogisticRegression().set_seed(1).set_max_iter(200).set_reg(0.5).fit(table)
    assert np.linalg.norm(regd.coefficient) < np.linalg.norm(base.coefficient)


def test_deterministic_given_seed():
    table = reference_train_table()
    c1 = LogisticRegression().set_seed(3).set_max_iter(50).fit(table).coefficient
    c2 = LogisticRegression().set_seed(3).set_max_iter(50).fit(table).coefficient
    np.testing.assert_array_equal(c1, c2)


def test_multi_device_training():
    # 8-device data-parallel run on a dataset that doesn't divide evenly.
    rng = np.random.default_rng(5)
    n = 203
    x = rng.normal(size=(n, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    table = Table({"features": x, "label": y})
    model = (
        LogisticRegression(mesh=DeviceMesh())
        .set_seed(11)
        .set_max_iter(200)
        .set_global_batch_size(256)
        .set_learning_rate(0.5)
        .fit(table)
    )
    (out,) = model.transform(table)
    assert np.mean(out["prediction"] == y) > 0.95


def test_sharded_transform_matches_single_device():
    rng = np.random.default_rng(9)
    x = rng.normal(size=(101, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    table = Table({"features": x, "label": y})
    model = LogisticRegression().set_seed(0).set_max_iter(50).fit(table)
    single = model.transform(table)[0]
    model.mesh = DeviceMesh()
    sharded = model.transform(table)[0]
    np.testing.assert_array_equal(single["prediction"], sharded["prediction"])
    np.testing.assert_allclose(
        single["rawPrediction"], sharded["rawPrediction"], rtol=1e-6
    )


def test_host_mode_checkpoint_resume(tmp_path):
    from flinkml_tpu.iteration import CheckpointManager
    from flinkml_tpu.models.logistic_regression import train_logistic_regression

    rng = np.random.default_rng(3)
    x = rng.normal(size=(64, 3))
    y = (x[:, 0] > 0).astype(np.float64)
    w = np.ones(64)
    mesh = DeviceMesh()
    kw = dict(
        mesh=mesh, max_iter=30, learning_rate=0.5, global_batch_size=64,
        reg=0.0, tol=0.0, seed=5, mode="host",
    )
    golden = train_logistic_regression(x, y, w, **kw)
    mgr = CheckpointManager(str(tmp_path))
    partial = train_logistic_regression(
        x, y, w, **{**kw, "max_iter": 10},
        checkpoint_manager=mgr, checkpoint_interval=5,
    )
    assert mgr.latest_epoch() == 10
    resumed = train_logistic_regression(
        x, y, w, **kw, checkpoint_manager=mgr, checkpoint_interval=5, resume=True
    )
    np.testing.assert_allclose(resumed, golden, rtol=1e-12)


def test_device_mode_resume_requires_manager():
    """Device mode checkpoints via chunked dispatches (round 2); resume
    still demands a manager to restore from."""
    from flinkml_tpu.models.logistic_regression import train_logistic_regression

    with pytest.raises(ValueError, match="checkpoint_manager"):
        train_logistic_regression(
            np.ones((4, 2)), np.zeros(4), np.ones(4), mesh=DeviceMesh(),
            max_iter=1, learning_rate=0.1, global_batch_size=4, reg=0.0,
            tol=0.0, seed=0, resume=True,
        )


def test_save_load_round_trip(tmp_path):
    table = reference_train_table()
    model = LogisticRegression().set_seed(2).set_max_iter(100).fit(table)
    p = str(tmp_path / "lr_model")
    model.save(p)
    loaded = LogisticRegressionModel.load(p)
    np.testing.assert_array_equal(loaded.coefficient, model.coefficient)
    (a,) = model.transform(table)
    (b,) = loaded.transform(table)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])


def test_get_set_model_data():
    table = reference_train_table()
    model = LogisticRegression().set_seed(2).set_max_iter(50).fit(table)
    data = model.get_model_data()
    assert data[0].column("coefficient").shape == (1, 4)
    other = LogisticRegressionModel().set_model_data(*data)
    np.testing.assert_array_equal(other.coefficient, model.coefficient)


def test_validation_errors():
    table = reference_train_table()
    bad = Table({"features": np.ones((3, 2)), "label": np.array([0.0, 1.0, 2.0])})
    with pytest.raises(ValueError, match="labels"):
        # Forced binomial on 3 classes must reject.
        LogisticRegression().set_multi_class("binomial").fit(bad)
    frac = Table({"features": np.ones((3, 2)), "label": np.array([0.0, 1.5, 2.0])})
    with pytest.raises(ValueError, match="integer labels"):
        LogisticRegression().set_multi_class("multinomial").fit(frac)
    with pytest.raises(ValueError):
        LogisticRegressionModel().transform(table)  # no model data


def test_multinomial_softmax_matches_sklearn(rng):
    """multiClass='auto' on >2 classes trains a softmax [k, d] model;
    probabilities match sklearn's multinomial optimum."""
    from sklearn.linear_model import LogisticRegression as SkLR

    n, d, k = 450, 6, 3
    x = rng.normal(size=(n, d))
    beta = rng.normal(size=(k, d))
    # Heavy class noise keeps the optimum finite and well-conditioned so
    # full-batch GD and sklearn's lbfgs land on the same point.
    y = np.argmax(x @ beta.T + rng.normal(scale=2.0, size=(n, k)), axis=1)
    t = Table({"features": x, "label": y.astype(np.float64)})
    model = (
        LogisticRegression().set_seed(0).set_max_iter(8000)
        .set_global_batch_size(n).set_learning_rate(2.0).set_tol(0.0)
        .fit(t)
    )
    assert model.coefficient.shape == (k, d)
    (out,) = model.transform(t)
    assert out["rawPrediction"].shape == (n, k)
    np.testing.assert_allclose(out["rawPrediction"].sum(axis=1), 1.0, atol=1e-6)

    sk = SkLR(C=np.inf, fit_intercept=False, max_iter=5000, tol=1e-10).fit(x, y)
    sk_proba = sk.predict_proba(x)
    np.testing.assert_allclose(
        np.asarray(out["rawPrediction"]), sk_proba, atol=5e-3
    )
    acc = np.mean(out["prediction"] == y)
    assert acc >= sk.score(x, y) - 0.02


def test_multinomial_save_load_and_model_data(rng, tmp_path):
    x = rng.normal(size=(90, 4))
    y = rng.integers(0, 3, 90).astype(np.float64)
    t = Table({"features": x, "label": y})
    model = (
        LogisticRegression().set_seed(1).set_max_iter(50)
        .set_global_batch_size(90).fit(t)
    )
    p = str(tmp_path / "softmax")
    model.save(p)
    loaded = LogisticRegressionModel.load(p)
    np.testing.assert_array_equal(loaded.coefficient, model.coefficient)
    other = LogisticRegressionModel().set_model_data(*model.get_model_data())
    np.testing.assert_array_equal(other.coefficient, model.coefficient)
    (a,) = model.transform(t)
    (b,) = loaded.transform(t)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])


def test_multinomial_two_classes_agrees_with_binomial(rng):
    """Forced multinomial on 2 classes: probabilities agree with the
    binomial model (softmax with k=2 ≡ sigmoid on the margin diff)."""
    x = rng.normal(size=(200, 5))
    # Noisy labels -> finite optimum; at the optimum softmax(k=2) and
    # the binomial sigmoid agree exactly.
    y = (x[:, 0] + 1.5 * rng.normal(size=200) > 0).astype(np.float64)
    t = Table({"features": x, "label": y})
    kw = lambda: (LogisticRegression().set_seed(0).set_max_iter(6000)
                  .set_tol(0.0).set_global_batch_size(200)
                  .set_learning_rate(1.0))
    softmax_m = kw().set_multi_class("multinomial").fit(t)
    binom_m = kw().set_multi_class("binomial").fit(t)
    (a,) = softmax_m.transform(t)
    (b,) = binom_m.transform(t)
    np.testing.assert_array_equal(a["prediction"], b["prediction"])
    np.testing.assert_allclose(
        a["rawPrediction"][:, 1], b["rawPrediction"][:, 1], atol=5e-3
    )


def test_multinomial_stream_fit_rejected():
    src = iter([Table({"features": np.ones((4, 2)),
                       "label": np.zeros(4)})])
    with pytest.raises(ValueError, match="streamed"):
        LogisticRegression().set_multi_class("multinomial").fit(src)


def test_auto_stream_with_multiclass_labels_says_streamed(rng):
    """'auto' + >2-class streamed data: the error names the streamed-fit
    limitation, not just binomial labels."""
    x = rng.normal(size=(8, 2))
    src = iter([Table({"features": x,
                       "label": np.array([0.0, 1, 2, 0, 1, 2, 0, 1])})])
    with pytest.raises(ValueError, match="streamed"):
        LogisticRegression().fit(src)


def test_multinomial_labels_must_cover_classes(rng):
    x = rng.normal(size=(6, 2))
    # Missing class 0 (labels 1..3): phantom-class guard.
    t = Table({"features": x, "label": np.array([1.0, 2, 3, 1, 2, 3])})
    with pytest.raises(ValueError, match="covering 0..k-1"):
        LogisticRegression().fit(t)
    # One absurd outlier label: must not allocate a [500001, d] model.
    t2 = Table({"features": x,
                "label": np.array([0.0, 1, 2, 0, 1, 500000.0])})
    with pytest.raises(ValueError, match="covering 0..k-1"):
        LogisticRegression().fit(t2)


def test_in_pipeline(tmp_path):
    from flinkml_tpu.pipeline import Pipeline, PipelineModel

    table = reference_train_table()
    pipeline = Pipeline([LogisticRegression().set_seed(4).set_max_iter(100)])
    pm = pipeline.fit(table)
    p = str(tmp_path / "pipe")
    pm.save(p)
    loaded = PipelineModel.load(p)
    (out,) = loaded.transform(table)
    np.testing.assert_array_equal(out["prediction"], table["label"])
