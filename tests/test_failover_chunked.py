"""Fault-injection ITs for the CHUNKED DEVICE training path.

Round-1 gap (VERDICT "missing" #2): the fastest mode — whole loop on
device — could not checkpoint; fault tolerance required mode='host' (one
dispatch per epoch). Round-2 design: the carry-style trainer
(``_linear_sgd._dense_trainer``) takes ``(coef, epoch, loss)`` and
``epoch_end`` as runtime values, so the SAME compiled executable runs the
loop in K-epoch dispatches with a carry snapshot between dispatches
(``_run_chunked``). These tests assert the contract that makes that a real
fault-tolerance story (reference: ``Checkpoints.java:43-211`` — mid-
iteration checkpointing with exactly-once replay):

  1. chunked == unchunked bit-exactly (same executable, same trajectory);
  2. a crash between chunks + resume reproduces the uninterrupted result
     EXACTLY (the ``BoundedAllRoundCheckpointITCase`` analog);
  3. tol-based early termination behaves identically chunked.
"""

import numpy as np
import pytest

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.models.logistic_regression import train_logistic_regression
from flinkml_tpu.parallel import DeviceMesh


class CrashAfterSave(CheckpointManager):
    """Simulates a process crash right after checkpoint N is committed —
    the chunk boundary is the unit of recovery in the device path (the
    FailingMap analog, operators/FailingMap.java:24-45: fires once, on
    the first attempt only)."""

    def __init__(self, directory: str, crash_after_epoch: int):
        super().__init__(directory)
        self.crash_after_epoch = crash_after_epoch
        self.fired = False

    def save(self, state, epoch, extra=None, **kw):
        path = super().save(state, epoch, extra, **kw)
        if not self.fired and epoch >= self.crash_after_epoch:
            self.fired = True
            raise RuntimeError(f"injected crash after checkpoint {epoch}")
        return path


def _data(n=256, d=12, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ rng.normal(size=d) > 0).astype(np.float32)
    return x, y, np.ones(n, dtype=np.float32)


def _train(mesh, x, y, w, mgr=None, resume=False, interval=3, max_iter=12,
           tol=0.0):
    return train_logistic_regression(
        x, y, w, mesh=mesh, max_iter=max_iter, learning_rate=0.5,
        global_batch_size=128, reg=0.01, tol=tol, seed=7, mode="device",
        checkpoint_manager=mgr, checkpoint_interval=interval, resume=resume,
    )


def test_chunked_matches_single_dispatch_exactly(tmp_path):
    """K-epoch dispatches with snapshots == one whole-loop dispatch,
    bit-for-bit: they are the same compiled program."""
    mesh = DeviceMesh()
    x, y, w = _data()
    golden = _train(mesh, x, y, w)  # no manager: one dispatch
    chunked = _train(mesh, x, y, w, CheckpointManager(str(tmp_path / "c")))
    np.testing.assert_array_equal(chunked, golden)


@pytest.mark.parametrize("crash_after_epoch", [3, 6, 9])
def test_chunked_failover_resume_exact(tmp_path, crash_after_epoch):
    mesh = DeviceMesh()
    x, y, w = _data()
    golden = _train(mesh, x, y, w)

    mgr = CrashAfterSave(str(tmp_path / f"f{crash_after_epoch}"),
                         crash_after_epoch)
    with pytest.raises(RuntimeError, match="injected"):
        _train(mesh, x, y, w, mgr)
    assert mgr.latest_epoch() is not None
    assert mgr.latest_epoch() >= crash_after_epoch

    recovered = _train(mesh, x, y, w, mgr, resume=True)
    np.testing.assert_array_equal(recovered, golden)


def test_chunked_resume_skips_completed_work(tmp_path):
    """Resuming at the final epoch does no further dispatches and returns
    the checkpointed coefficient unchanged."""
    mesh = DeviceMesh()
    x, y, w = _data(seed=5)
    mgr = CheckpointManager(str(tmp_path / "done"))
    done = _train(mesh, x, y, w, mgr)
    assert mgr.latest_epoch() == 12
    resumed = _train(mesh, x, y, w, mgr, resume=True)
    np.testing.assert_array_equal(resumed, done)


def test_chunked_tol_termination_matches(tmp_path):
    """Early tol stop must fire identically whether the loop is chunked or
    not (the termination predicate runs on-device inside the chunk AND on
    the host between chunks, on the same carried loss)."""
    mesh = DeviceMesh()
    x, y, w = _data(seed=2)
    tol = 0.4  # loose enough to trigger before max_iter
    golden = _train(mesh, x, y, w, max_iter=40, tol=tol)
    mgr = CheckpointManager(str(tmp_path / "tol"))
    chunked = _train(mesh, x, y, w, mgr, max_iter=40, tol=tol)
    np.testing.assert_array_equal(chunked, golden)
    # The checkpointed epoch reflects the early stop, not max_iter.
    assert mgr.latest_epoch() < 40


def test_rescale_guard_still_enforced(tmp_path):
    """A checkpoint from the 8-device mesh must refuse to restore into a
    1-device mesh (HeadOperator.java:130-146 parity)."""
    import jax

    mesh = DeviceMesh()
    if mesh.mesh.size < 2:
        pytest.skip("needs a multi-device mesh")
    x, y, w = _data()
    mgr = CheckpointManager(str(tmp_path / "guard"))
    _train(mesh, x, y, w, mgr)

    small = DeviceMesh({"data": 1}, devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="rescal"):
        _train(small, x, y, w, mgr, resume=True)
