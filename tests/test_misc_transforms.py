"""FeatureHasher / Interaction / DCT / StopWordsRemover / RandomSplitter."""

import numpy as np
import pytest
from scipy.fft import dct as scipy_dct

from flinkml_tpu.models import (
    DCT,
    FeatureHasher,
    Interaction,
    RandomSplitter,
    StopWordsRemover,
    Tokenizer,
)
from flinkml_tpu.table import Table


# -- FeatureHasher -----------------------------------------------------------

def _hash_table():
    return Table({
        "age": np.asarray([25.0, 40.0]),
        "city": np.asarray(["sf", "nyc"]),
        "clicks": np.asarray([3.0, 0.0]),
    })


def test_feature_hasher_numeric_and_categorical():
    t = _hash_table()
    (out,) = (
        FeatureHasher().set_input_cols(["age", "city", "clicks"])
        .set_output_col("f").set_num_features(64).transform(t)
    )
    v0, v1 = out["f"][0], out["f"][1]
    assert v0.size() == 64
    # Row 0: age bucket holds 25.0, clicks bucket 3.0, city=sf bucket 1.0.
    assert sorted(v0.values.tolist()) == [1.0, 3.0, 25.0]
    # Row 1: clicks contributes 0.0 at its bucket; age 40, city=nyc 1.
    assert 40.0 in v1.values.tolist() and 1.0 in v1.values.tolist()
    # Determinism across instances.
    (out2,) = (
        FeatureHasher().set_input_cols(["age", "city", "clicks"])
        .set_output_col("f").set_num_features(64).transform(t)
    )
    assert out2["f"][0] == v0


def test_feature_hasher_same_category_same_bucket():
    t = Table({"city": np.asarray(["sf", "sf", "nyc"])})
    (out,) = (
        FeatureHasher().set_input_cols(["city"]).set_output_col("f")
        .set_num_features(32).transform(t)
    )
    assert out["f"][0] == out["f"][1]
    assert out["f"][0] != out["f"][2]


def test_feature_hasher_rejects_vector_columns():
    t = Table({"v": np.zeros((3, 2))})
    with pytest.raises(ValueError, match="VectorAssembler"):
        FeatureHasher().set_input_cols(["v"]).set_output_col("f").transform(t)


# -- Interaction -------------------------------------------------------------

def test_interaction_outer_products():
    a = np.asarray([[1.0, 2.0], [3.0, 4.0]])
    b = np.asarray([[5.0, 6.0, 7.0], [1.0, 0.0, 2.0]])
    s = np.asarray([2.0, 10.0])
    t = Table({"a": a, "b": b, "s": s})
    (out,) = (
        Interaction().set_input_cols(["s", "a", "b"]).set_output_col("i")
        .transform(t)
    )
    got = out["i"]
    assert got.shape == (2, 6)
    expected0 = 2.0 * np.outer([1.0, 2.0], [5.0, 6.0, 7.0]).ravel()
    np.testing.assert_allclose(got[0], expected0)
    with pytest.raises(ValueError, match="at least 2"):
        Interaction().set_input_cols(["a"]).set_output_col("i").transform(t)


# -- DCT ---------------------------------------------------------------------

def test_dct_matches_scipy_and_inverts():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 8))
    t = Table({"input": x})
    (out,) = DCT().transform(t)
    np.testing.assert_allclose(
        out["output"], scipy_dct(x, type=2, norm="ortho", axis=1), rtol=1e-12
    )
    (back,) = DCT().set_inverse(True).transform(
        out.rename({"output": "input"}).select("input")
    )
    np.testing.assert_allclose(back["output"], x, atol=1e-12)


# -- StopWordsRemover --------------------------------------------------------

def test_stop_words_default_english():
    t = Table({"text": np.asarray(["The cat IS on the mat"])})
    (tok,) = Tokenizer().set_input_col("text").set_output_col("tok").transform(t)
    (out,) = (
        StopWordsRemover().set_input_cols(["tok"]).set_output_cols(["clean"])
        .transform(tok)
    )
    assert out["clean"][0] == ["cat", "mat"]


def test_stop_words_case_sensitive_and_custom():
    t = Table({"tok": np.asarray([None], dtype=object)})
    tok = Table({"tok": np.empty(1, dtype=object)})
    tok["tok"][0] = ["Keep", "keep", "drop"]
    (out,) = (
        StopWordsRemover().set_input_cols(["tok"]).set_output_cols(["c"])
        .set_stop_words(["keep"]).set_case_sensitive(True)
        .transform(tok)
    )
    assert out["c"][0] == ["Keep", "drop"]
    (out2,) = (
        StopWordsRemover().set_input_cols(["tok"]).set_output_cols(["c"])
        .set_stop_words(["keep"]).transform(tok)
    )
    assert out2["c"][0] == ["drop"]


# -- RandomSplitter ----------------------------------------------------------

def test_random_splitter_partitions_everything():
    rng = np.random.default_rng(1)
    t = Table({"x": rng.normal(size=5000), "id": np.arange(5000)})
    train, test = RandomSplitter().set_weights([0.8, 0.2]).set_seed(0).transform(t)
    assert train.num_rows + test.num_rows == 5000
    assert abs(train.num_rows / 5000 - 0.8) < 0.02
    # Disjoint and complete.
    ids = np.concatenate([train["id"], test["id"]])
    assert len(np.unique(ids)) == 5000


def test_random_splitter_deterministic_and_three_way():
    t = Table({"id": np.arange(1000)})
    s1 = RandomSplitter().set_weights([1.0, 1.0, 2.0]).set_seed(7).transform(t)
    s2 = RandomSplitter().set_weights([1.0, 1.0, 2.0]).set_seed(7).transform(t)
    for a, b in zip(s1, s2):
        np.testing.assert_array_equal(a["id"], b["id"])
    assert len(s1) == 3
    assert abs(s1[2].num_rows / 1000 - 0.5) < 0.06
    with pytest.raises(ValueError, match="positive"):
        RandomSplitter().set_weights([1.0, -1.0]).transform(t)


def test_stop_words_missing_output_cols_clear_error():
    t = Table({"tok": np.empty(1, dtype=object)})
    t["tok"][0] = ["a"]
    with pytest.raises(ValueError, match="outputCols"):
        StopWordsRemover().set_input_cols(["tok"]).transform(t)
