"""Out-of-core streamed fit ITs for the recommendation/text families —
ALS, LDA, Word2Vec (round-4: VERDICT r3 item 5; reference parity
``ReplayOperator.java:62-250`` — every bounded iteration trains from
replayed cached partitions).

Contract (mirrors test_stream_fit.py): spill==RAM EXACT (the memory
budget is a capacity knob, not a numerics knob), the estimator stream
path works end-to-end and learns, and checkpoint/resume reproduces the
uninterrupted run exactly.
"""

import numpy as np
import pytest

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.iteration.datacache import cache_stream
from flinkml_tpu.table import Table


def _crash_manager_cls(crash_at_epoch):
    class Crash(CheckpointManager):
        fired = False

        def save(self, state, epoch, extra=None, **kw):
            p = super().save(state, epoch, extra, **kw)
            if not Crash.fired and epoch >= crash_at_epoch:
                Crash.fired = True
                raise RuntimeError("injected crash")
            return p

    return Crash


# -- ALS ---------------------------------------------------------------------

def _rating_batches(n_users=40, n_items=30, rank=3, per_batch=256,
                    n_batches=4, seed=0):
    rng = np.random.default_rng(seed)
    uf = rng.normal(size=(n_users, rank))
    vf = rng.normal(size=(n_items, rank))
    out = []
    for _ in range(n_batches):
        u = rng.integers(0, n_users, size=per_batch).astype(np.int64)
        i = rng.integers(0, n_items, size=per_batch).astype(np.int64)
        r = np.einsum("nk,nk->n", uf[u], vf[i]).astype(np.float32)
        out.append({"user": u, "item": i, "rating": r})
    return out


def _als(mesh, **kw):
    from flinkml_tpu.models.als import ALS

    return (
        ALS(mesh=mesh, **kw)
        .set_rank(4).set_max_iter(5).set_reg_param(0.05).set_seed(0)
    )


def test_als_stream_spilled_matches_in_ram_exactly(tmp_path, mesh):
    batches = _rating_batches()
    ram = _als(mesh).fit(cache_stream(iter(batches)))
    spill_cache = cache_stream(
        iter(batches), directory=str(tmp_path / "spill"),
        memory_budget_bytes=1,
    )
    spilled = _als(mesh).fit(spill_cache)
    np.testing.assert_array_equal(spilled.user_factors, ram.user_factors)
    np.testing.assert_array_equal(spilled.item_factors, ram.item_factors)
    assert any((tmp_path / "spill").glob("segment-*.bin"))


def test_als_stream_learns_and_tables_path(tmp_path, mesh):
    """Estimator path from an iterable of Tables: the streamed model
    reconstructs the observed ratings (same sanity bar as the in-RAM
    ALS tests)."""
    batches = _rating_batches(n_batches=6)
    tables = [Table(b) for b in batches]
    model = _als(
        mesh, cache_dir=str(tmp_path / "als"), cache_memory_budget_bytes=1
    ).set_max_iter(10).fit(iter(tables))
    big = {k: np.concatenate([b[k] for b in batches]) for k in batches[0]}
    (out,) = model.transform(Table({"user": big["user"], "item": big["item"]}))
    pred = out.column("prediction")
    rmse = float(np.sqrt(np.mean((pred - big["rating"]) ** 2)))
    assert rmse < 0.25, rmse


def test_als_stream_resume_exact(tmp_path, mesh):
    cache = cache_stream(iter(_rating_batches()))
    golden = _als(mesh).set_max_iter(6).fit(cache)

    mgr = _crash_manager_cls(2)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        _als(mesh, checkpoint_manager=mgr,
             checkpoint_interval=2).set_max_iter(6).fit(cache)
    assert mgr.latest_epoch() == 2

    rec = _als(mesh, checkpoint_manager=mgr, checkpoint_interval=2,
               resume=True).set_max_iter(6).fit(cache)
    np.testing.assert_array_equal(rec.user_factors, golden.user_factors)
    np.testing.assert_array_equal(rec.item_factors, golden.item_factors)


def test_als_in_ram_rejects_checkpoint_knobs(mesh):
    b = _rating_batches(n_batches=1)[0]
    with pytest.raises(ValueError, match="streamed fits only"):
        _als(mesh, checkpoint_manager=CheckpointManager("/tmp/x")).fit(
            Table(b)
        )


# -- LDA ---------------------------------------------------------------------

def _doc_batches(n_batches=4, per_batch=48, vocab=30, seed=0):
    """Two topic blocks: docs draw tokens from the low or high half."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        c = np.zeros((per_batch, vocab), np.float32)
        for r in range(per_batch):
            half = rng.integers(0, 2)
            lo, hi = (0, vocab // 2) if half == 0 else (vocab // 2, vocab)
            idx = rng.integers(lo, hi, size=20)
            np.add.at(c[r], idx, 1.0)
        # Sealed-cache batches carry the estimator's features column.
        out.append({"features": c})
    return out


def _lda(mesh, **kw):
    from flinkml_tpu.models.lda import LDA

    return LDA(mesh=mesh, **kw).set_k(2).set_max_iter(8).set_tol(0.0) \
        .set_seed(0)


def test_lda_stream_spilled_matches_in_ram_exactly(tmp_path, mesh):
    batches = _doc_batches()
    ram = _lda(mesh).fit(cache_stream(iter(batches)))
    spill_cache = cache_stream(
        iter(batches), directory=str(tmp_path / "spill"),
        memory_budget_bytes=1,
    )
    spilled = _lda(mesh).fit(spill_cache)
    np.testing.assert_array_equal(
        spilled.topics_matrix, ram.topics_matrix
    )
    assert any((tmp_path / "spill").glob("segment-*.bin"))


def test_lda_stream_learns_topic_split(tmp_path, mesh):
    """The streamed fit separates the two vocabulary halves into the two
    topics (each topic's mass concentrates on one half)."""
    batches = _doc_batches(n_batches=6)
    tables = [Table({"features": b["features"]}) for b in batches]
    model = _lda(
        mesh, cache_dir=str(tmp_path / "lda"), cache_memory_budget_bytes=1
    ).fit(iter(tables))
    tm = model.topics_matrix  # [2, V]
    v = tm.shape[1]
    lo_mass = tm[:, : v // 2].sum(axis=1)
    # One topic mostly low-half, the other mostly high-half.
    assert abs(lo_mass[0] - lo_mass[1]) > 0.5, lo_mass


def test_lda_stream_resume_exact(tmp_path, mesh):
    cache = cache_stream(iter(_doc_batches()))
    golden = _lda(mesh).fit(cache)

    mgr = _crash_manager_cls(3)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        _lda(mesh, checkpoint_manager=mgr, checkpoint_interval=3).fit(cache)
    assert mgr.latest_epoch() == 3

    rec = _lda(mesh, checkpoint_manager=mgr, checkpoint_interval=3,
               resume=True).fit(cache)
    np.testing.assert_array_equal(rec.topics_matrix, golden.topics_matrix)


# -- Word2Vec ----------------------------------------------------------------

def _sentence_tables(n_batches=3, per_batch=40, seed=0):
    """Token docs over two disjoint cliques: words co-occur only within
    their clique."""
    rng = np.random.default_rng(seed)
    cliques = [[f"a{i}" for i in range(6)], [f"b{i}" for i in range(6)]]
    out = []
    for _ in range(n_batches):
        docs = []
        for _ in range(per_batch):
            words = cliques[rng.integers(0, 2)]
            docs.append(list(rng.choice(words, size=8)))
        out.append(Table({"tokens": np.asarray(docs, dtype=object)}))
    return out


def _w2v(mesh, **kw):
    from flinkml_tpu.models.word2vec import Word2Vec

    return (
        Word2Vec(mesh=mesh, **kw)
        .set_input_col("tokens").set_vector_size(16).set_window_size(2)
        .set_min_count(1).set_max_iter(3).set_seed(0)
    )


def test_w2v_stream_spilled_matches_ram_exactly(tmp_path, mesh):
    ram = _w2v(mesh).fit(iter(_sentence_tables()))
    spilled = _w2v(
        mesh, cache_dir=str(tmp_path / "w2v"), cache_memory_budget_bytes=1
    ).fit(iter(_sentence_tables()))
    assert list(ram.vocabulary) == list(spilled.vocabulary)
    np.testing.assert_array_equal(spilled.vectors, ram.vectors)
    assert any((tmp_path / "w2v").glob("segment-*.bin"))


def test_w2v_stream_separates_cliques(mesh):
    model = _w2v(mesh).set_max_iter(8).fit(iter(_sentence_tables()))
    vecs = model.vectors / np.linalg.norm(model.vectors, axis=1,
                                          keepdims=True)
    idx = {t: i for i, t in enumerate(model.vocabulary)}
    same = float(vecs[idx["a0"]] @ vecs[idx["a1"]])
    cross = float(vecs[idx["a0"]] @ vecs[idx["b0"]])
    assert same > cross, (same, cross)


def test_w2v_stream_resume_exact(tmp_path, mesh):
    golden = _w2v(mesh).set_max_iter(4).fit(iter(_sentence_tables()))

    mgr = _crash_manager_cls(2)(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        _w2v(mesh, checkpoint_manager=mgr,
             checkpoint_interval=2).set_max_iter(4).fit(
            iter(_sentence_tables())
        )
    assert mgr.latest_epoch() == 2

    rec = _w2v(mesh, checkpoint_manager=mgr, checkpoint_interval=2,
               resume=True).set_max_iter(4).fit(iter(_sentence_tables()))
    np.testing.assert_array_equal(rec.vectors, golden.vectors)
