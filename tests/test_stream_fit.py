"""Out-of-core / streamed training ITs — the round-2 integration of the
datacache subsystem into a product fit path (VERDICT "missing" #1).

Reference parity: every bounded iteration in the reference trains from a
disk-backed replayable cache (``ReplayOperator.java:62-250``,
``DataCacheWriter.java:36-139``) so datasets larger than memory work by
construction. The contract tested here:

  1. training via a spilled-to-disk cache == training via the RAM-resident
     cache, EXACTLY (the memory budget is a capacity knob, not a numerics
     knob);
  2. the estimator-level ``fit(iterable_of_tables)`` path produces the same
     model as the low-level stream trainer;
  3. fitting from a sealed DataCache replays without a caching pass and
     supports exact checkpoint-resume (the cache is durable);
  4. the streamed model actually learns (sanity on accuracy).
"""

import numpy as np
import pytest

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.iteration.datacache import DataCacheWriter, cache_stream
from flinkml_tpu.models._linear_sgd import train_linear_model_stream
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


def _make_batches(n_batches=6, rows=64, d=10, seed=0):
    rng = np.random.default_rng(seed)
    true = rng.normal(size=d)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(rows, d)).astype(np.float32)
        y = (x @ true > 0).astype(np.float32)
        out.append({"x": x, "y": y, "w": np.ones(rows, np.float32)})
    return out


def _train(batches, mesh, **kw):
    args = dict(
        loss="logistic", mesh=mesh, max_iter=8, learning_rate=0.5,
        reg=0.01, elastic_net=0.0, tol=0.0,
    )
    args.update(kw)
    return train_linear_model_stream(batches, **args)


def test_spilled_cache_matches_in_ram_exactly(tmp_path, mesh):
    """The VERDICT 'done' criterion: a dataset trained through the
    disk-spilled cache matches the in-RAM result exactly."""
    batches = _make_batches()
    in_ram = _train(iter(batches), mesh)  # no dir: RAM-only cache
    # Budget of 1 byte: every batch past the first append spills to disk.
    spilled = _train(
        iter(batches), mesh,
        cache_dir=str(tmp_path / "spill"), memory_budget_bytes=1,
    )
    np.testing.assert_array_equal(spilled, in_ram)
    # Spill actually happened.
    assert any((tmp_path / "spill").glob("segment-*.bin"))


def test_variable_batch_sizes(tmp_path, mesh):
    """Ragged batches pad to the row tile with weight-0 rows — exact."""
    rng = np.random.default_rng(3)
    d = 6
    true = rng.normal(size=d)
    batches = []
    for rows in (64, 37, 128, 5):
        x = rng.normal(size=(rows, d)).astype(np.float32)
        batches.append({
            "x": x, "y": (x @ true > 0).astype(np.float32),
            "w": np.ones(rows, np.float32),
        })
    in_ram = _train(iter(batches), mesh)
    spilled = _train(
        iter(batches), mesh,
        cache_dir=str(tmp_path / "rag"), memory_budget_bytes=1,
    )
    np.testing.assert_array_equal(spilled, in_ram)


def test_estimator_fit_from_table_stream(mesh):
    batches = _make_batches()
    tables = [
        Table({"features": b["x"], "label": b["y"], "weight": b["w"]})
        for b in batches
    ]
    est = (
        LogisticRegression(mesh=mesh)
        .set_weight_col("weight")
        .set_max_iter(8)
        .set_learning_rate(0.5)
        .set_reg(0.01)
        .set_tol(0.0)
    )
    model = est.fit(iter(tables))
    coef = model.get_model_data()[0].column("coefficient")[0]
    direct = _train(iter(batches), mesh)
    np.testing.assert_array_equal(np.asarray(coef), direct)

    # The streamed model predicts (learns the separator).
    big = np.concatenate([b["x"] for b in batches])
    lbl = np.concatenate([b["y"] for b in batches])
    (out,) = model.transform(Table({"features": big, "label": lbl}))
    acc = float((out.column("prediction") == lbl).mean())
    assert acc > 0.9


def test_fit_from_sealed_datacache(mesh):
    """A sealed DataCache input replays every epoch (no caching pass) and
    matches the one-shot stream result."""
    batches = _make_batches(seed=11)
    streamed = _train(iter(batches), mesh)
    cache = cache_stream(iter(batches))
    cached = _train(cache, mesh)
    np.testing.assert_array_equal(cached, streamed)


def test_datacache_resume_exact(tmp_path, mesh):
    """Crash mid-training from a durable cache; resume from the checkpoint
    reproduces the uninterrupted trajectory exactly."""
    batches = _make_batches(seed=7)
    cache = cache_stream(iter(batches), directory=str(tmp_path / "cache"))

    golden = _train(cache, mesh, max_iter=9)

    class Crash(CheckpointManager):
        fired = False

        def save(self, state, epoch, extra=None):
            p = super().save(state, epoch, extra)
            if not Crash.fired and epoch >= 3:
                Crash.fired = True
                raise RuntimeError("injected crash")
            return p

    mgr = Crash(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        _train(cache, mesh, max_iter=9, checkpoint_manager=mgr,
               checkpoint_interval=3)
    assert mgr.latest_epoch() == 3

    recovered = _train(cache, mesh, max_iter=9, checkpoint_manager=mgr,
                       checkpoint_interval=3, resume=True)
    np.testing.assert_array_equal(recovered, golden)


def test_resume_after_tol_termination_is_noop(tmp_path, mesh):
    """A run that stopped on tol saves its terminal checkpoint; resuming it
    must NOT apply further updates (the restored loss re-triggers the
    criterion)."""
    batches = _make_batches(seed=4)
    cache = cache_stream(iter(batches))
    mgr = CheckpointManager(str(tmp_path / "tolck"))
    done = _train(cache, mesh, max_iter=30, tol=0.5,
                  checkpoint_manager=mgr, checkpoint_interval=5)
    stopped_at = mgr.latest_epoch()
    assert stopped_at is not None and stopped_at < 30
    resumed = _train(cache, mesh, max_iter=30, tol=0.5,
                     checkpoint_manager=mgr, checkpoint_interval=5,
                     resume=True)
    np.testing.assert_array_equal(resumed, done)
    assert mgr.latest_epoch() == stopped_at


def test_zero_weight_batch_raises(mesh):
    """An all-zero-weight batch would inf the step size; it must fail
    loudly, not silently NaN the model."""
    batches = _make_batches(n_batches=2)
    batches[1]["w"] = np.zeros_like(batches[1]["w"])
    with pytest.raises(ValueError, match="zero total weight"):
        _train(iter(batches), mesh)


def test_datacache_bad_labels_raise(mesh):
    """Labels outside {0,1} inside a DataCache must raise exactly like the
    in-RAM path (the validate hook covers cached batches)."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.where(x[:, 0] > 0, 1.0, -1.0).astype(np.float32)  # SVM-style
    cache = cache_stream(iter([{"features": x, "label": y}]))
    est = LogisticRegression(mesh=mesh).set_max_iter(2)
    with pytest.raises(ValueError, match="labels"):
        est.fit(cache)


def test_caller_arrays_stay_writable(mesh):
    """Caching must not freeze caller-owned buffers: the writer freezes its
    own copies, never the user's arrays."""
    batches = _make_batches(n_batches=2)
    _train(iter(batches), mesh)
    batches[0]["x"][0, 0] = 123.0  # must not raise


def test_manager_without_interval_saves_terminal(tmp_path, mesh):
    """A manager with no interval still gets the terminal carry (matching
    the dense chunked path), so fault tolerance is never silently off."""
    mgr = CheckpointManager(str(tmp_path / "noint"))
    _train(iter(_make_batches()), mesh, checkpoint_manager=mgr)
    assert mgr.latest_epoch() == 8  # max_iter


def test_one_shot_stream_rejects_resume(mesh):
    with pytest.raises(ValueError, match="durable"):
        _train(iter(_make_batches()), mesh, resume=True,
               checkpoint_manager=CheckpointManager("/tmp/unused-ckpt"))


def test_empty_stream_raises(mesh):
    with pytest.raises(ValueError, match="empty"):
        _train(iter([]), mesh)
