"""Out-of-core / streamed training ITs — the round-2 integration of the
datacache subsystem into a product fit path (VERDICT "missing" #1).

Reference parity: every bounded iteration in the reference trains from a
disk-backed replayable cache (``ReplayOperator.java:62-250``,
``DataCacheWriter.java:36-139``) so datasets larger than memory work by
construction. The contract tested here:

  1. training via a spilled-to-disk cache == training via the RAM-resident
     cache, EXACTLY (the memory budget is a capacity knob, not a numerics
     knob);
  2. the estimator-level ``fit(iterable_of_tables)`` path produces the same
     model as the low-level stream trainer;
  3. fitting from a sealed DataCache replays without a caching pass and
     supports exact checkpoint-resume (the cache is durable);
  4. the streamed model actually learns (sanity on accuracy).
"""

import numpy as np
import pytest

from flinkml_tpu.iteration import CheckpointManager
from flinkml_tpu.iteration.datacache import DataCacheWriter, cache_stream
from flinkml_tpu.models._linear_sgd import train_linear_model_stream
from flinkml_tpu.models.logistic_regression import LogisticRegression
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


def _make_batches(n_batches=6, rows=64, d=10, seed=0):
    rng = np.random.default_rng(seed)
    true = rng.normal(size=d)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(rows, d)).astype(np.float32)
        y = (x @ true > 0).astype(np.float32)
        out.append({"x": x, "y": y, "w": np.ones(rows, np.float32)})
    return out


def _train(batches, mesh, **kw):
    args = dict(
        loss="logistic", mesh=mesh, max_iter=8, learning_rate=0.5,
        reg=0.01, elastic_net=0.0, tol=0.0,
    )
    args.update(kw)
    return train_linear_model_stream(batches, **args)


def test_spilled_cache_matches_in_ram_exactly(tmp_path, mesh):
    """The VERDICT 'done' criterion: a dataset trained through the
    disk-spilled cache matches the in-RAM result exactly."""
    batches = _make_batches()
    in_ram = _train(iter(batches), mesh)  # no dir: RAM-only cache
    # Budget of 1 byte: every batch past the first append spills to disk.
    spilled = _train(
        iter(batches), mesh,
        cache_dir=str(tmp_path / "spill"), memory_budget_bytes=1,
    )
    np.testing.assert_array_equal(spilled, in_ram)
    # Spill actually happened.
    assert any((tmp_path / "spill").glob("segment-*.bin"))


def test_variable_batch_sizes(tmp_path, mesh):
    """Ragged batches pad to the row tile with weight-0 rows — exact."""
    rng = np.random.default_rng(3)
    d = 6
    true = rng.normal(size=d)
    batches = []
    for rows in (64, 37, 128, 5):
        x = rng.normal(size=(rows, d)).astype(np.float32)
        batches.append({
            "x": x, "y": (x @ true > 0).astype(np.float32),
            "w": np.ones(rows, np.float32),
        })
    in_ram = _train(iter(batches), mesh)
    spilled = _train(
        iter(batches), mesh,
        cache_dir=str(tmp_path / "rag"), memory_budget_bytes=1,
    )
    np.testing.assert_array_equal(spilled, in_ram)


def test_estimator_fit_from_table_stream(mesh):
    batches = _make_batches()
    tables = [
        Table({"features": b["x"], "label": b["y"], "weight": b["w"]})
        for b in batches
    ]
    est = (
        LogisticRegression(mesh=mesh)
        .set_weight_col("weight")
        .set_max_iter(8)
        .set_learning_rate(0.5)
        .set_reg(0.01)
        .set_tol(0.0)
    )
    model = est.fit(iter(tables))
    coef = model.get_model_data()[0].column("coefficient")[0]
    direct = _train(iter(batches), mesh)
    np.testing.assert_array_equal(np.asarray(coef), direct)

    # The streamed model predicts (learns the separator).
    big = np.concatenate([b["x"] for b in batches])
    lbl = np.concatenate([b["y"] for b in batches])
    (out,) = model.transform(Table({"features": big, "label": lbl}))
    acc = float((out.column("prediction") == lbl).mean())
    assert acc > 0.9


def test_kmeans_stream_batching_invariance(mesh):
    """The streamed result is a property of the DATA, not of how the
    stream happened to be batched: any split of the same rows gives the
    same centroids up to f32 summation order (per-batch partials sum to
    the same totals)."""
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    rng = np.random.default_rng(9)
    centers = rng.uniform(-10, 10, size=(3, 5)).astype(np.float32)
    a = rng.integers(0, 3, size=384)
    x = (centers[a] + rng.normal(scale=0.4, size=(384, 5))).astype(
        np.float32
    )
    init = np.ascontiguousarray(x[:3])

    def batches(sizes):
        off = 0
        for s in sizes:
            yield {"x": x[off:off + s]}
            off += s

    base = train_kmeans_stream(iter(batches((64,) * 6)), k=3, mesh=mesh,
                               max_iter=5, seed=0, initial_centroids=init)
    for split in ((37, 91, 128, 40, 64, 24), (200, 184)):
        assert sum(split) == 384
        other = train_kmeans_stream(
            iter(batches(split)), k=3, mesh=mesh, max_iter=5, seed=0,
            initial_centroids=init,
        )
        np.testing.assert_allclose(other, base, rtol=1e-4, atol=1e-5)


def test_linear_svc_and_regression_streamed_fit(tmp_path, mesh):
    """Round 4: every linear estimator exposes the streamed path (the
    loss-generic stream trainer was previously reachable only through
    LogisticRegression). Spilled estimator fit == the low-level stream
    trainer with the matching loss, exactly."""
    from flinkml_tpu.models.linear_regression import LinearRegression
    from flinkml_tpu.models.linear_svc import LinearSVC

    batches = _make_batches(seed=21)
    tables = lambda: iter(
        Table({"features": b["x"], "label": b["y"], "weight": b["w"]})
        for b in batches
    )

    svc = (
        LinearSVC(mesh=mesh, cache_dir=str(tmp_path / "svc"),
                  cache_memory_budget_bytes=1)
        .set_weight_col("weight").set_max_iter(8).set_learning_rate(0.5)
        .set_reg(0.01).set_tol(0.0)
    ).fit(tables())
    direct = _train(iter(batches), mesh, loss="hinge")
    np.testing.assert_array_equal(
        np.asarray(svc.get_model_data()[0].column("coefficient")[0]), direct
    )
    assert any((tmp_path / "svc").glob("segment-*.bin"))

    # Regression: continuous labels through the squared-loss stream path.
    reg_batches = []
    rng = np.random.default_rng(8)
    true = rng.normal(size=10)
    for _ in range(4):
        x = rng.normal(size=(64, 10)).astype(np.float32)
        reg_batches.append({
            "x": x, "y": (x @ true).astype(np.float32),
            "w": np.ones(64, np.float32),
        })
    lin = (
        LinearRegression(mesh=mesh)
        .set_weight_col("weight").set_max_iter(8).set_learning_rate(0.1)
        .set_reg(0.0).set_tol(0.0)
    ).fit(iter(
        Table({"features": b["x"], "label": b["y"], "weight": b["w"]})
        for b in reg_batches
    ))
    direct_reg = _train(iter(reg_batches), mesh, loss="squared",
                        learning_rate=0.1, reg=0.0)
    np.testing.assert_array_equal(
        np.asarray(lin.get_model_data()[0].column("coefficient")[0]),
        direct_reg,
    )


def test_linear_regression_normal_solver_rejects_stream(mesh):
    from flinkml_tpu.models.linear_regression import LinearRegression

    with pytest.raises(ValueError, match="solver='sgd'"):
        LinearRegression(mesh=mesh).set_solver("normal").fit(
            iter(_make_batches())
        )


def test_fit_from_sealed_datacache(mesh):
    """A sealed DataCache input replays every epoch (no caching pass) and
    matches the one-shot stream result."""
    batches = _make_batches(seed=11)
    streamed = _train(iter(batches), mesh)
    cache = cache_stream(iter(batches))
    cached = _train(cache, mesh)
    np.testing.assert_array_equal(cached, streamed)


def test_datacache_resume_exact(tmp_path, mesh):
    """Crash mid-training from a durable cache; resume from the checkpoint
    reproduces the uninterrupted trajectory exactly."""
    batches = _make_batches(seed=7)
    cache = cache_stream(iter(batches), directory=str(tmp_path / "cache"))

    golden = _train(cache, mesh, max_iter=9)

    class Crash(CheckpointManager):
        fired = False

        def save(self, state, epoch, extra=None, **kw):
            p = super().save(state, epoch, extra, **kw)
            if not Crash.fired and epoch >= 3:
                Crash.fired = True
                raise RuntimeError("injected crash")
            return p

    mgr = Crash(str(tmp_path / "ckpt"))
    with pytest.raises(RuntimeError, match="injected"):
        _train(cache, mesh, max_iter=9, checkpoint_manager=mgr,
               checkpoint_interval=3)
    assert mgr.latest_epoch() == 3

    recovered = _train(cache, mesh, max_iter=9, checkpoint_manager=mgr,
                       checkpoint_interval=3, resume=True)
    np.testing.assert_array_equal(recovered, golden)


def test_resume_after_tol_termination_is_noop(tmp_path, mesh):
    """A run that stopped on tol saves its terminal checkpoint; resuming it
    must NOT apply further updates (the restored loss re-triggers the
    criterion)."""
    batches = _make_batches(seed=4)
    cache = cache_stream(iter(batches))
    mgr = CheckpointManager(str(tmp_path / "tolck"))
    done = _train(cache, mesh, max_iter=30, tol=0.5,
                  checkpoint_manager=mgr, checkpoint_interval=5)
    stopped_at = mgr.latest_epoch()
    assert stopped_at is not None and stopped_at < 30
    resumed = _train(cache, mesh, max_iter=30, tol=0.5,
                     checkpoint_manager=mgr, checkpoint_interval=5,
                     resume=True)
    np.testing.assert_array_equal(resumed, done)
    assert mgr.latest_epoch() == stopped_at


def test_zero_weight_batch_raises(mesh):
    """An all-zero-weight batch would inf the step size; it must fail
    loudly, not silently NaN the model."""
    batches = _make_batches(n_batches=2)
    batches[1]["w"] = np.zeros_like(batches[1]["w"])
    with pytest.raises(ValueError, match="zero total weight"):
        _train(iter(batches), mesh)


def test_datacache_bad_labels_raise(mesh):
    """Labels outside {0,1} inside a DataCache must raise exactly like the
    in-RAM path (the validate hook covers cached batches)."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.where(x[:, 0] > 0, 1.0, -1.0).astype(np.float32)  # SVM-style
    cache = cache_stream(iter([{"features": x, "label": y}]))
    est = LogisticRegression(mesh=mesh).set_max_iter(2)
    with pytest.raises(ValueError, match="labels"):
        est.fit(cache)


def test_caller_arrays_stay_writable(mesh):
    """Caching must not freeze caller-owned buffers: the writer freezes its
    own copies, never the user's arrays."""
    batches = _make_batches(n_batches=2)
    _train(iter(batches), mesh)
    batches[0]["x"][0, 0] = 123.0  # must not raise


def test_manager_without_interval_saves_terminal(tmp_path, mesh):
    """A manager with no interval still gets the terminal carry (matching
    the dense chunked path), so fault tolerance is never silently off."""
    mgr = CheckpointManager(str(tmp_path / "noint"))
    _train(iter(_make_batches()), mesh, checkpoint_manager=mgr)
    assert mgr.latest_epoch() == 8  # max_iter


def test_one_shot_stream_rejects_resume(mesh):
    with pytest.raises(ValueError, match="durable"):
        _train(iter(_make_batches()), mesh, resume=True,
               checkpoint_manager=CheckpointManager("/tmp/unused-ckpt"))


def test_empty_stream_raises(mesh):
    with pytest.raises(ValueError, match="empty"):
        _train(iter([]), mesh)


# -- streamed KMeans (round-3: out-of-core beyond linear models) -------------

def _blob_batches(n_batches=6, rows=64, d=5, k=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(k, d)).astype(np.float32)
    out = []
    for _ in range(n_batches):
        a = rng.integers(0, k, size=rows)
        x = centers[a] + rng.normal(scale=0.3, size=(rows, d)).astype(
            np.float32
        )
        out.append({"x": x.astype(np.float32)})
    return out, centers


def test_kmeans_stream_spilled_matches_in_ram_exactly(tmp_path, mesh):
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    batches, _ = _blob_batches()
    args = dict(k=3, mesh=mesh, max_iter=5, seed=7)
    in_ram = train_kmeans_stream(iter(batches), **args)
    spilled = train_kmeans_stream(
        iter(batches), cache_dir=str(tmp_path / "spill"),
        memory_budget_bytes=1, **args,
    )
    np.testing.assert_array_equal(spilled, in_ram)
    assert any((tmp_path / "spill").glob("segment-*.bin"))


def test_kmeans_stream_matches_whole_loop_device_program(mesh):
    """Streamed batch-accumulated Lloyd == the whole-loop-on-device
    program, given the same init (the batch split only reorders f32
    additions)."""
    from flinkml_tpu.models.kmeans import train_kmeans, train_kmeans_stream

    batches, _ = _blob_batches()
    x_all = np.concatenate([b["x"] for b in batches])
    k, iters = 3, 5
    rng = np.random.default_rng(42)
    init = np.ascontiguousarray(
        x_all[rng.choice(x_all.shape[0], size=k, replace=False)]
    )
    whole = train_kmeans(
        x_all, k=k, mesh=mesh, max_iter=iters, seed=0,
        initial_centroids=init,
    )
    streamed = train_kmeans_stream(
        iter(batches), k=k, mesh=mesh, max_iter=iters, seed=0,
        initial_centroids=init,
    )
    np.testing.assert_allclose(streamed, whole, rtol=1e-4, atol=1e-5)


def test_kmeans_estimator_streamed_fit_clusters(tmp_path, mesh):
    from flinkml_tpu.models import KMeans
    from flinkml_tpu.table import Table

    batches, centers = _blob_batches(n_batches=8, rows=128)
    tables = [Table({"features": b["x"]}) for b in batches]
    model = (
        KMeans(mesh=mesh, cache_dir=str(tmp_path / "km"),
               cache_memory_budget_bytes=1)
        .set_k(3).set_max_iter(10).set_seed(1)
        .fit(iter(tables))
    )
    got = np.sort(np.round(model.centroids).astype(int), axis=0)
    want = np.sort(np.round(centers).astype(int), axis=0)
    np.testing.assert_array_equal(got, want)


def test_kmeans_stream_from_sealed_cache(mesh):
    from flinkml_tpu.models import KMeans

    batches, _ = _blob_batches()
    cache = cache_stream({"features": b["x"]} for b in batches)
    model = KMeans(mesh=mesh).set_k(3).set_max_iter(5).set_seed(3).fit(cache)
    assert model.centroids.shape == (3, 5)


def test_kmeans_stream_kmeanspp_init(mesh):
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    batches, _ = _blob_batches()
    out = train_kmeans_stream(
        iter(batches), k=3, mesh=mesh, max_iter=5, seed=0,
        init_mode="k-means++",
    )
    assert out.shape == (3, 5)
    assert np.isfinite(out).all()


# -- streamed GBT (round-3: out-of-core beyond linear models) ----------------

def _gbt_batches(n_batches=5, rows=96, d=4, seed=0, regression=False):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        x = rng.uniform(-1, 1, size=(rows, d)).astype(np.float32)
        raw = x[:, 0] * x[:, 1] + 0.5 * x[:, 2]
        y = raw if regression else (raw > 0).astype(np.float32)
        out.append({
            "x": x, "y": y.astype(np.float32),
            "w": np.ones(rows, np.float32),
        })
    return out


def test_gbt_stream_spilled_matches_in_ram_exactly(tmp_path, mesh):
    from flinkml_tpu.iteration.datacache import cache_stream
    from flinkml_tpu.models._gbt_stream import train_gbt_stream

    batches = _gbt_batches()
    args = dict(
        mesh=mesh, logistic=True, num_trees=4, depth=3, max_bins=16,
        learning_rate=0.3, reg_lambda=1.0, subsample=1.0, seed=0,
    )
    ram = train_gbt_stream(cache_stream(iter(batches)), **args)
    spill_cache = cache_stream(
        iter(batches), directory=str(tmp_path / "spill"),
        memory_budget_bytes=1,
    )
    spilled = train_gbt_stream(spill_cache, **args)
    for a, b in zip(ram, spilled):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert any((tmp_path / "spill").glob("segment-*.bin"))


def test_gbt_stream_matches_in_ram_builder(mesh):
    """With the reservoir covering every row (exact edges), identical
    subsampling (off), and the same seed, the streamed level-wise build
    must pick the same splits as the whole-forest device program."""
    from flinkml_tpu.models.gbt import GBTClassifier
    from flinkml_tpu.table import Table

    batches = _gbt_batches()
    x_all = np.concatenate([b["x"] for b in batches])
    y_all = np.concatenate([b["y"] for b in batches])
    t = Table({"features": x_all, "label": y_all})
    est = lambda: (
        GBTClassifier(mesh=mesh).set_num_trees(4).set_max_depth(3)
        .set_max_bins(16).set_learning_rate(0.3).set_seed(0)
    )
    in_ram = est().fit(t)
    tables = [Table({"features": b["x"], "label": b["y"]}) for b in batches]
    streamed = est().fit(iter(tables))
    np.testing.assert_array_equal(streamed._feats, in_ram._feats)
    np.testing.assert_allclose(streamed._thrs, in_ram._thrs, rtol=1e-6)
    np.testing.assert_allclose(
        streamed._leaves, in_ram._leaves, rtol=2e-3, atol=2e-4
    )


def test_gbt_classifier_streamed_fit_learns(tmp_path, mesh):
    from flinkml_tpu.models import GBTClassifier
    from flinkml_tpu.table import Table

    batches = _gbt_batches(n_batches=8, rows=128)
    tables = [Table({"features": b["x"], "label": b["y"]}) for b in batches]
    model = (
        GBTClassifier(mesh=mesh, cache_dir=str(tmp_path / "gbt"),
                      cache_memory_budget_bytes=1)
        .set_num_trees(20).set_max_depth(4).set_max_bins(32)
        .set_learning_rate(0.3).set_seed(0)
        .fit(iter(tables))
    )
    x_all = np.concatenate([b["x"] for b in batches])
    y_all = np.concatenate([b["y"] for b in batches])
    (out,) = model.transform(Table({"features": x_all}))
    acc = float(np.mean(out["prediction"] == y_all))
    assert acc > 0.9, acc


def test_gbt_regressor_streamed_fit_learns(mesh):
    from flinkml_tpu.models import GBTRegressor
    from flinkml_tpu.table import Table

    batches = _gbt_batches(n_batches=8, rows=128, regression=True)
    tables = [Table({"features": b["x"], "label": b["y"]}) for b in batches]
    model = (
        GBTRegressor(mesh=mesh).set_num_trees(30).set_max_depth(4)
        .set_max_bins(32).set_learning_rate(0.3).set_seed(0)
        .fit(iter(tables))
    )
    x_all = np.concatenate([b["x"] for b in batches])
    y_all = np.concatenate([b["y"] for b in batches])
    (out,) = model.transform(Table({"features": x_all}))
    rmse = float(np.sqrt(np.mean((out["prediction"] - y_all) ** 2)))
    assert rmse < 0.15, rmse


def test_gbt_stream_rejects_rf_and_validation_fraction(mesh):
    from flinkml_tpu.models import GBTClassifier, RandomForestClassifier
    from flinkml_tpu.table import Table

    tables = [
        Table({"features": b["x"], "label": b["y"]})
        for b in _gbt_batches(n_batches=2)
    ]
    with pytest.raises(ValueError, match="boosted"):
        RandomForestClassifier(mesh=mesh).fit(iter(tables))
    with pytest.raises(ValueError, match="validationFraction"):
        (GBTClassifier(mesh=mesh).set_validation_fraction(0.2)
         .fit(iter(tables)))


# -- streamed GMM (round-3) --------------------------------------------------

def test_gmm_streamed_fit_recovers_components(tmp_path, mesh):
    from flinkml_tpu.models import GaussianMixture
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(0)
    true_means = np.asarray([[-4.0, 0.0], [4.0, 2.0], [0.0, -5.0]])
    tables = []
    for _ in range(6):
        a = rng.integers(0, 3, 256)
        x = true_means[a] + rng.normal(scale=0.4, size=(256, 2))
        tables.append(Table({"features": x.astype(np.float32)}))
    model = (
        GaussianMixture(mesh=mesh, cache_dir=str(tmp_path / "gmm"),
                        cache_memory_budget_bytes=1)
        .set_k(3).set_max_iter(30).set_tol(1e-5).set_seed(0)
        .fit(iter(tables))
    )
    got = np.sort(np.round(model.means).astype(int), axis=0)
    want = np.sort(true_means.astype(int), axis=0)
    np.testing.assert_array_equal(got, want)
    assert np.allclose(model.weights.sum(), 1.0)


def test_gmm_streamed_matches_in_ram(mesh):
    """Same data, same seed: the streamed EM (batch-accumulated stats,
    reservoir-covering-all-rows init) matches the in-RAM fit closely."""
    from flinkml_tpu.models import GaussianMixture
    from flinkml_tpu.table import Table

    rng = np.random.default_rng(3)
    true_means = np.asarray([[-3.0, 1.0], [3.0, -1.0]])
    a = rng.integers(0, 2, 600)
    x = (true_means[a] + rng.normal(scale=0.5, size=(600, 2))).astype(
        np.float32
    )
    est = lambda: (
        GaussianMixture(mesh=mesh).set_k(2).set_max_iter(25)
        .set_tol(0.0).set_seed(0).set_covariance_type("diag")
    )
    in_ram = est().fit(Table({"features": x}))
    tables = [
        Table({"features": x[i * 150:(i + 1) * 150]}) for i in range(4)
    ]
    streamed = est().fit(iter(tables))
    order_a = np.argsort(in_ram.means[:, 0])
    order_b = np.argsort(streamed.means[:, 0])
    np.testing.assert_allclose(
        streamed.means[order_b], in_ram.means[order_a], atol=0.05
    )
    np.testing.assert_allclose(
        streamed.weights[order_b], in_ram.weights[order_a], atol=0.02
    )


# -- round 5: sparse-native streaming (the Criteo-1TB-shaped gap) ----------

def _sparse_tables(n_batches, rows, dim, nnz, seed=0):
    from flinkml_tpu.linalg import Vectors

    out = []
    for b in range(n_batches):
        r = np.random.default_rng(seed + b)
        vecs = []
        for _ in range(rows):
            idx = np.sort(r.choice(dim, nnz, replace=False))
            vecs.append(Vectors.sparse(dim, idx.tolist(), r.normal(size=nnz)))
        y = (r.random(rows) > 0.5).astype(np.float64)
        out.append(Table({
            "features": np.array(vecs, dtype=object), "label": y,
        }))
    return out


def test_sparse_streamed_fit_matches_densified_stream(mesh):
    """SparseVector feature streams route to the sparse-native trainer;
    the SGD trajectory must be bit-identical to densifying each batch
    (same per-batch steps, same math — only the gradient reduction
    primitive differs)."""
    from flinkml_tpu.models._data import labeled_data

    dim = 5_000
    tables = _sparse_tables(4, 48, dim, 5)
    est = lambda: (
        LogisticRegression(mesh=mesh).set_max_iter(3).set_learning_rate(0.5)
    )
    m_sparse = est().fit(iter(tables))

    def densify(t):
        x, y, _ = labeled_data(t, "features", "label", None)
        return Table({"features": x, "label": y})

    m_dense = est().fit(iter(densify(t) for t in tables))
    # f32 production runs are bit-identical; the suite's x64 conftest
    # exposes ~1e-9 summation-order noise between the two reductions.
    np.testing.assert_allclose(
        m_sparse._coefficient, m_dense._coefficient, atol=1e-7
    )


def test_sparse_streamed_fit_high_dim_stays_o_nnz(mesh):
    """dim = 2e6 with 5 nnz/row: the densifying path would materialize
    and CACHE ~1.5 GB per 200-row batch; the sparse-native path must
    complete with O(nnz) footprint (this test running at all, quickly,
    is the assertion)."""
    dim = 2_000_000
    m = (
        LogisticRegression(mesh=mesh).set_max_iter(2)
        .fit(iter(_sparse_tables(3, 200, dim, 5)))
    )
    assert m._coefficient.shape == (dim,)
    assert np.isfinite(m._coefficient).all()


def test_sparse_streamed_resume_exact_from_csr_cache(tmp_path, mesh):
    """The sparse stream's durable form: a sealed DataCache of flat CSR
    batches (1-row 2-D components + dim). Resume must be bit-exact."""
    from flinkml_tpu.models._data import labeled_sparse_data
    from flinkml_tpu.models._linear_sgd import streamed_linear_fit

    dim = 3_000
    tables = _sparse_tables(3, 32, dim, 4)

    def csr_dicts():
        for t in tables:
            indptr, indices, values, d, y, w = labeled_sparse_data(
                t, "features", "label", None
            )
            yield {
                "indptr": np.asarray(indptr)[None, :],
                "indices": np.asarray(indices)[None, :],
                "values": np.asarray(values)[None, :],
                "y": np.asarray(y)[None, :],
                "w": np.asarray(w)[None, :],
                "dim": np.asarray([[d]], np.int64),
            }

    cache = cache_stream(csr_dicts())
    hyper = dict(
        features_col="features", label_col="label", weight_col=None,
        loss="logistic", mesh=mesh, max_iter=6, learning_rate=0.5,
        reg=0.01, elastic_net=0.0, tol=0.0,
    )
    golden = streamed_linear_fit(cache, **hyper)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    streamed_linear_fit(
        cache, checkpoint_manager=mgr, checkpoint_interval=2,
        **{**hyper, "max_iter": 3},
    )
    resumed = streamed_linear_fit(
        cache, checkpoint_manager=mgr, resume=True, **hyper,
    )
    np.testing.assert_array_equal(resumed, golden)


def test_sparse_streamed_csr_cache_edge_cases(tmp_path, mesh):
    """Weightless CSR caches get unit weights; a batch from a different
    feature space fails loudly instead of silently clamping."""
    from flinkml_tpu.models._linear_sgd import streamed_linear_fit

    def csr_row(dim, seed):
        r = np.random.default_rng(seed)
        n, nnz = 16, 3
        indptr = np.arange(n + 1, dtype=np.int64) * nnz
        return {
            "indptr": indptr[None, :],
            "indices": r.integers(0, dim, n * nnz).astype(np.int32)[None, :],
            "values": r.normal(size=n * nnz).astype(np.float32)[None, :],
            "y": (r.random(n) > 0.5).astype(np.float32)[None, :],
            "dim": np.asarray([[dim]], np.int64),
        }

    hyper = dict(
        features_col="features", label_col="label", weight_col=None,
        loss="logistic", mesh=mesh, max_iter=2, learning_rate=0.5,
        reg=0.0, elastic_net=0.0, tol=0.0,
    )
    # No "w" key: unit-weight default, same as the dense cache contract.
    coef = streamed_linear_fit(
        cache_stream(iter([csr_row(500, 0)])), **hyper
    )
    assert coef.shape == (500,) and np.isfinite(coef).all()

    # Mismatched dim in a later batch: loud error.
    with pytest.raises(ValueError, match="dim"):
        streamed_linear_fit(
            cache_stream(iter([csr_row(500, 0), csr_row(900, 1)])), **hyper
        )


def _flat_csr_batch(indptr, indices, values, y, dim):
    """One batch in the flat CSR stream format (each component a 2-D row)."""
    return {
        "indptr": np.asarray(indptr, np.int64)[None],
        "indices": np.asarray(indices, np.int32)[None],
        "values": np.asarray(values, np.float32)[None],
        "y": np.asarray(y, np.float32)[None],
        "dim": np.array([[dim]], np.int64),
    }


def test_csr_stream_rejects_non_monotone_indptr(mesh):
    """ADVICE r5 (medium): a non-monotone indptr passes the ragged check
    (indices.size == indptr[-1]) but raises rank-locally inside the ELL
    fill at place time — stranding peers mid-collective. It must be
    rejected at ingest, where the failure rides the held-error
    rendezvous like every other input check."""
    dim = 32
    bad = _flat_csr_batch(
        [0, 5, 3, 9], np.zeros(9), np.ones(9), np.ones(3), dim
    )
    with pytest.raises(ValueError, match="non-decreasing"):
        _train([bad], mesh, sparse_dim=dim)
    # indptr not starting at 0 is the same class of corruption.
    bad0 = _flat_csr_batch(
        [1, 4, 9], np.zeros(9), np.ones(9), np.ones(2), dim
    )
    with pytest.raises(ValueError, match="start at 0"):
        _train([bad0], mesh, sparse_dim=dim)


def test_csr_stream_rejects_out_of_range_indices(mesh):
    """ADVICE r5 (low): out-of-range column ids never raise on device —
    the jitted gather/scatter clamps them, silently misattributing
    gradient mass to boundary columns. Both polarities must be rejected
    at ingest."""
    dim = 32
    neg = _flat_csr_batch(
        [0, 2, 4], [1, -3, 5, 2], np.ones(4), np.ones(2), dim
    )
    with pytest.raises(ValueError, match="column indices"):
        _train([neg], mesh, sparse_dim=dim)
    high = _flat_csr_batch(
        [0, 2, 4], [1, 3, dim, 2], np.ones(4), np.ones(2), dim
    )
    with pytest.raises(ValueError, match="column indices"):
        _train([high], mesh, sparse_dim=dim)


def test_check_csr_structure_accepts_valid_and_returns_nnz():
    """The shared validator must not reject well-formed CSR (including
    empty rows and boundary column ids) and returns diff(indptr) so the
    callers' ELL-width accounting stays single-pass."""
    from flinkml_tpu.models._linear_sgd import _check_csr_structure

    nnz = _check_csr_structure(
        np.array([0, 2, 2, 5]), np.array([0, 31, 4, 0, 30]), 32
    )
    np.testing.assert_array_equal(nnz, [2, 0, 3])
    with pytest.raises(ValueError):
        _check_csr_structure(np.array([], np.int64), np.array([], np.int64), 32)
