"""Fused pipeline execution tests (`flinkml_tpu.pipeline_fusion`).

The contract under test:

  1. For every kernel-capable stage, the fused columnar kernel reproduces
     the per-stage ``transform`` output BITWISE (same dtypes, same values)
     — the fused and per-stage paths are interchangeable, not
     approximations of each other.
  2. Mixed kernel/non-kernel chains keep working: runs of fusable stages
     compile as one program each, non-fusable stages run per-stage, and
     the end-to-end output equals fully per-stage execution.
  3. The compile cache is shape-bucketed: repeated ``transform`` calls
     with differing row counts inside one power-of-two bucket cause zero
     recompiles (asserted via the ``on_compile`` hook).
  4. Device-column laziness: fused outputs stay resident on device — no
     device→host copy happens until ``Table.column`` is called, and a
     5-stage all-kernel chain costs exactly 1 host→device upload per
     ``transform`` and 1 device→host download per column read.
"""

import numpy as np
import pytest

from flinkml_tpu import pipeline_fusion
from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.models.kmeans import KMeans
from flinkml_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from flinkml_tpu.models.one_hot_encoder import OneHotEncoder
from flinkml_tpu.models.scalers import (
    MaxAbsScaler,
    MinMaxScaler,
    RobustScaler,
    StandardScaler,
)
from flinkml_tpu.models.vector_assembler import VectorAssembler
from flinkml_tpu.pipeline import PipelineModel
from flinkml_tpu.table import Table


@pytest.fixture(scope="module", autouse=True)
def _fresh_compile_cache(tmp_path_factory):
    """Bit-parity assertions require every compared program to be compiled
    in THIS session: XLA's persistent compilation cache can serve a binary
    compiled under an earlier session whose codegen conditions differed,
    and two such binaries for the same HLO may disagree by 1 ulp in
    transcendental lowering (observed on sigmoid). A fresh cache dir for
    this module keeps both sides of every comparison same-session."""
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update(
        "jax_compilation_cache_dir",
        str(tmp_path_factory.mktemp("fusion_xla_cache")),
    )
    yield
    jax.config.update("jax_compilation_cache_dir", old)


@pytest.fixture(autouse=True)
def _fusion_state():
    """Each test sees an enabled executor, an empty program cache, and no
    leaked compile hooks."""
    pipeline_fusion.set_enabled(True)
    pipeline_fusion.reset_cache()
    saved = list(pipeline_fusion.on_compile)
    yield
    pipeline_fusion.on_compile[:] = saved
    pipeline_fusion.set_enabled(True)
    pipeline_fusion.reset_cache()


def _counters(group):
    from flinkml_tpu.utils.metrics import metrics

    return dict(metrics.group(group).snapshot()["counters"])


def _delta(before, after, key):
    return after.get(key, 0.0) - before.get(key, 0.0)


def _data(n=101, d=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return Table({"features": x, "label": y})


def _assert_bitwise(expected, actual, cols):
    for c in cols:
        ev, av = expected.column(c), actual.column(c)
        assert ev.dtype == av.dtype, f"{c}: {ev.dtype} != {av.dtype}"
        np.testing.assert_array_equal(ev, av, err_msg=f"column {c!r}")


# ---------------------------------------------------------------------------
# 1. kernel == transform, per stage
# ---------------------------------------------------------------------------

def _standard_scaler(t):
    m = (
        StandardScaler()
        .set(StandardScaler.INPUT_COL, "features")
        .set(StandardScaler.OUTPUT_COL, "out")
        .fit(t)
    )
    return m, t


def _minmax_scaler(t):
    m = (
        MinMaxScaler()
        .set(MinMaxScaler.INPUT_COL, "features")
        .set(MinMaxScaler.OUTPUT_COL, "out")
        .fit(t)
    )
    return m, t


def _maxabs_scaler(t):
    m = (
        MaxAbsScaler()
        .set(MaxAbsScaler.INPUT_COL, "features")
        .set(MaxAbsScaler.OUTPUT_COL, "out")
        .fit(t)
    )
    return m, t


def _robust_scaler(t):
    m = (
        RobustScaler()
        .set(RobustScaler.INPUT_COL, "features")
        .set(RobustScaler.OUTPUT_COL, "out")
        .fit(t)
    )
    return m, t


def _vector_assembler(t):
    m = (
        VectorAssembler()
        .set(VectorAssembler.INPUT_COLS, ["features", "label"])
        .set(VectorAssembler.HANDLE_INVALID, "keep")
        .set(VectorAssembler.OUTPUT_COL, "out")
    )
    return m, t


def _one_hot(t):
    train = Table({
        "c1": np.array([0.0, 1.0, 2.0, 2.0]),
        "c2": np.array([0.0, 1.0, 0.0, 1.0]),
    })
    m = (
        OneHotEncoder()
        .set_input_cols(["c1", "c2"])
        .set_output_cols(["o1", "o2"])
        .set_handle_invalid("keep")
        .fit(train)
    )
    # Includes an out-of-range category (5.0) and the dropped-last value
    # (2.0): the keep catch-all slot and the all-zero row must both match.
    apply = Table({
        "c1": np.array([0.0, 2.0, 5.0, 1.0]),
        "c2": np.array([1.0, 0.0, 1.0, 0.0]),
    })
    return m, apply


def _logreg_binomial(t):
    m = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, "features")
        .set(LogisticRegression.LABEL_COL, "label")
        .fit(t)
    )
    return m, t


def _logreg_multinomial(t):
    rng = np.random.default_rng(7)
    coef = rng.normal(size=(3, t.column("features").shape[1]))
    m = LogisticRegressionModel().set(
        LogisticRegression.FEATURES_COL, "features"
    )
    m.set_model_data(Table({"coefficient": coef[None]}))
    return m, t


def _kmeans(t):
    m = (
        KMeans()
        .set(KMeans.FEATURES_COL, "features")
        .set(KMeans.K, 3)
        .fit(t)
    )
    return m, t


_STAGE_BUILDERS = {
    "standard_scaler": _standard_scaler,
    "minmax_scaler": _minmax_scaler,
    "maxabs_scaler": _maxabs_scaler,
    "robust_scaler": _robust_scaler,
    "vector_assembler": _vector_assembler,
    "one_hot_encoder": _one_hot,
    "logreg_binomial": _logreg_binomial,
    "logreg_multinomial": _logreg_multinomial,
    "kmeans": _kmeans,
}


@pytest.mark.parametrize("name", sorted(_STAGE_BUILDERS))
def test_kernel_bitwise_equals_transform(name):
    """Every kernel-capable stage: the fused kernel's output columns are
    bitwise-identical (values AND dtypes) to per-stage ``transform``."""
    stage, table = _STAGE_BUILDERS[name](_data())
    kernel = stage.transform_kernel()
    assert kernel is not None, f"{name} should be kernel-capable"
    (expected,) = stage.transform(table)
    actual = pipeline_fusion.execute_kernel_chain(table, [kernel])
    _assert_bitwise(expected, actual, kernel.output_cols)


def test_kernel_gates_return_none():
    """Configurations a pure device function cannot express fall back:
    unfitted models, error-mode assemblers/encoders, sparse encoders."""
    fitted, _ = _standard_scaler(_data())
    assert fitted.transform_kernel() is not None
    assert LogisticRegressionModel().transform_kernel() is None  # unfitted
    va = VectorAssembler().set(VectorAssembler.INPUT_COLS, ["features"])
    assert va.set(VectorAssembler.HANDLE_INVALID, "error").transform_kernel() is None
    enc, _ = _one_hot(None)
    assert enc.set_handle_invalid("error").transform_kernel() is None
    enc2, _ = _one_hot(None)
    assert enc2.set(type(enc2).OUTPUT_FORMAT, "sparse").transform_kernel() is None


# ---------------------------------------------------------------------------
# 2. chains: all-kernel and mixed
# ---------------------------------------------------------------------------

def _five_stage_chain(t):
    """features -> s1 -> s2 -> s3 -> s4 -> prediction: all kernel-capable."""
    stages = []
    cur = t
    prev = "features"
    for i, cls in enumerate(
        (StandardScaler, MinMaxScaler, MaxAbsScaler, RobustScaler), start=1
    ):
        m = (
            cls()
            .set(cls.INPUT_COL, prev)
            .set(cls.OUTPUT_COL, f"s{i}")
            .fit(cur)
        )
        (cur,) = m.transform(cur)
        prev = f"s{i}"
        stages.append(m)
    lr = (
        LogisticRegression()
        .set(LogisticRegression.FEATURES_COL, prev)
        .set(LogisticRegression.LABEL_COL, "label")
        .fit(cur)
    )
    stages.append(lr)
    return PipelineModel(stages)


_OUT_COLS = ("s1", "s2", "s3", "s4", "prediction", "rawPrediction")


def test_five_stage_pipeline_fused_bitwise_equals_per_stage():
    t = _data(n=101)
    pm = _five_stage_chain(t)
    pipeline_fusion.set_enabled(False)
    (expected,) = pm.transform(t)
    pipeline_fusion.set_enabled(True)

    before = _counters("pipeline.fusion")
    (fused,) = pm.transform(t)
    after = _counters("pipeline.fusion")

    _assert_bitwise(expected, fused, _OUT_COLS)
    # The whole chain is one segment / one compiled program.
    assert _delta(before, after, "fused_segments") == 1
    assert _delta(before, after, "fused_stages") == 5
    assert _delta(before, after, "compiles") == 1


class _HostDouble(AlgoOperator):
    """Non-fusable fixture stage: doubles a column in host numpy."""

    def __init__(self, col):
        super().__init__()
        self._col = col

    def transform(self, *inputs):
        (table,) = inputs
        return (table.with_column(self._col, table.column(self._col) * 2.0),)


def test_mixed_kernel_and_host_stages():
    """A non-kernel stage splits the chain into two fused segments with a
    per-stage hop between; output equals fully per-stage execution."""
    t = _data(n=64)
    s1 = StandardScaler().set(StandardScaler.INPUT_COL, "features").set(
        StandardScaler.OUTPUT_COL, "a"
    ).fit(t)
    s2 = MaxAbsScaler().set(MaxAbsScaler.INPUT_COL, "a").set(
        MaxAbsScaler.OUTPUT_COL, "b"
    ).fit(s1.transform(t)[0])
    host = _HostDouble("b")
    t3 = host.transform(s2.transform(s1.transform(t)[0])[0])[0]
    s3 = MinMaxScaler().set(MinMaxScaler.INPUT_COL, "b").set(
        MinMaxScaler.OUTPUT_COL, "c"
    ).fit(t3)
    s4 = RobustScaler().set(RobustScaler.INPUT_COL, "c").set(
        RobustScaler.OUTPUT_COL, "d"
    ).fit(s3.transform(t3)[0])
    pm = PipelineModel([s1, s2, host, s3, s4])

    pipeline_fusion.set_enabled(False)
    (expected,) = pm.transform(t)
    pipeline_fusion.set_enabled(True)
    before = _counters("pipeline.fusion")
    (fused,) = pm.transform(t)
    after = _counters("pipeline.fusion")

    _assert_bitwise(expected, fused, ("a", "b", "c", "d"))
    assert _delta(before, after, "fused_segments") == 2
    assert _delta(before, after, "fused_stages") == 4


def test_single_kernel_stage_runs_per_stage():
    """A lone fusable stage between non-fusable ones is not worth a fused
    dispatch (len(run) < 2): it must take the plain transform path."""
    t = _data()
    s = StandardScaler().set(StandardScaler.INPUT_COL, "features").set(
        StandardScaler.OUTPUT_COL, "a"
    ).fit(t)
    pm = PipelineModel([_HostDouble("features"), s, _HostDouble("a")])
    before = _counters("pipeline.fusion")
    (out,) = pm.transform(t)
    after = _counters("pipeline.fusion")
    assert _delta(before, after, "fused_segments") == 0
    assert not out.is_device_resident("a")


def test_disable_switch_restores_per_stage_path():
    t = _data()
    pm = _five_stage_chain(t)
    pipeline_fusion.set_enabled(False)
    before = _counters("pipeline.fusion")
    (out,) = pm.transform(t)
    after = _counters("pipeline.fusion")
    assert _delta(before, after, "fused_segments") == 0
    assert not out.is_device_resident("prediction")


# ---------------------------------------------------------------------------
# 3. shape-bucketed compile cache
# ---------------------------------------------------------------------------

def test_row_bucket_zero_recompiles_within_bucket():
    """Row counts 100, 77, 96 all pad to the 128 bucket: one compile
    serves them all; crossing to 129 rows compiles exactly once more."""
    t = _data(n=200)
    pm = _five_stage_chain(t)
    compiles = []
    pipeline_fusion.on_compile.append(compiles.append)

    before = _counters("pipeline.fusion")
    (out100,) = pm.transform(t.slice(0, 100))
    assert len(compiles) == 1
    (out77,) = pm.transform(t.slice(0, 77))
    (out96,) = pm.transform(t.slice(0, 96))
    assert len(compiles) == 1, "row counts within one bucket must not retrace"
    assert pipeline_fusion.compiled_program_count() == 1
    after = _counters("pipeline.fusion")
    assert _delta(before, after, "cache_hits") == 2

    (out129,) = pm.transform(t.slice(0, 129))
    assert len(compiles) == 2, "crossing a bucket boundary compiles once"
    assert pipeline_fusion.compiled_program_count() == 2

    # Padding must never leak into results: row counts differ, rows agree.
    np.testing.assert_array_equal(
        out100.column("prediction")[:77], out77.column("prediction")
    )
    assert out77.column("prediction").shape[0] == 77
    assert out129.column("prediction").shape[0] == 129


def test_model_data_change_reuses_program():
    """Constants are traced arguments: refreshing model data of the same
    shape must hit the compiled program, not retrace."""
    t = _data()
    pm = _five_stage_chain(t)
    compiles = []
    pipeline_fusion.on_compile.append(compiles.append)
    pm.transform(t)
    assert len(compiles) == 1
    lrm = pm.stages[-1]
    lrm.set_model_data(Table({"coefficient": lrm._coefficient[None] * 0.5}))
    pm.transform(t)
    assert len(compiles) == 1


def test_row_bucket_policy():
    assert pipeline_fusion.row_bucket(1) == pipeline_fusion.MIN_ROW_BUCKET
    assert pipeline_fusion.row_bucket(8) == 8
    assert pipeline_fusion.row_bucket(9) == 16
    assert pipeline_fusion.row_bucket(128) == 128
    assert pipeline_fusion.row_bucket(129) == 256


# ---------------------------------------------------------------------------
# 4. device residency: laziness and transfer counts
# ---------------------------------------------------------------------------

def test_device_columns_materialize_lazily():
    t = _data()
    pm = _five_stage_chain(t)
    (out,) = pm.transform(t)
    for c in _OUT_COLS:
        assert out.is_device_resident(c)

    before = _counters("table")
    after = _counters("table")
    assert _delta(before, after, "device_to_host_materializations") == 0

    out.column("prediction")
    mid = _counters("table")
    assert _delta(before, mid, "device_to_host_materializations") == 1
    # Cached: a second read is free.
    out.column("prediction")
    assert _delta(before, _counters("table"),
                  "device_to_host_materializations") == 1


def test_five_stage_chain_single_transfer_pair():
    """Acceptance: a 5-stage all-kernel chain costs exactly ONE
    host→device upload per transform call (the features column) and ONE
    device→host download to read the result column — N-stage round trips
    are gone."""
    t = _data(n=101)
    pm = _five_stage_chain(t)
    # Features-only table: label was only needed for fitting.
    apply = t.select("features")

    before_f = _counters("pipeline.fusion")
    (out,) = pm.transform(apply)
    after_f = _counters("pipeline.fusion")
    assert _delta(before_f, after_f, "host_to_device_transfers") == 1
    # The upload moves the host column's actual bytes (101 float64 rows
    # of [n, 6] features); bucket padding happens device-side.
    assert _delta(before_f, after_f, "host_to_device_bytes") == 101 * 6 * 8
    assert _delta(before_f, after_f, "host_transfer_bytes_avoided") > 0

    before_t = _counters("table")
    out.column("prediction")
    after_t = _counters("table")
    assert _delta(before_t, after_t, "device_to_host_materializations") == 1


def test_relational_ops_stay_zero_copy_on_device_columns():
    t = _data()
    pm = _five_stage_chain(t)
    (out,) = pm.transform(t)
    before = _counters("table")
    sub = out.select("prediction", "s4").rename({"s4": "scaled"}).drop(
        "prediction"
    )
    assert sub.is_device_resident("scaled")
    assert _delta(before, _counters("table"),
                  "device_to_host_materializations") == 0


def test_intermediate_columns_are_lazy_and_dce_correct():
    """Columns consumed inside a fused run (s1..s3 here) are not computed
    by the eager program: they come back as lazy device columns whose
    first read executes a dead-code-eliminated program for just that
    column — and whose values still bitwise-match per-stage execution.
    Pinned inputs (s4, feeding the context-sensitive logreg kernel) are
    materialized eagerly for bit parity."""
    t = _data(n=101)
    pm = _five_stage_chain(t)
    pipeline_fusion.set_enabled(False)
    (expected,) = pm.transform(t)
    pipeline_fusion.set_enabled(True)

    compiles = []
    pipeline_fusion.on_compile.append(compiles.append)
    (out,) = pm.transform(t)
    assert len(compiles) == 1, "eager path is ONE program"
    from flinkml_tpu.table import LazyDeviceColumn

    for c in ("s1", "s2", "s3"):
        assert isinstance(out._columns[c], LazyDeviceColumn)
    for c in ("s4", "prediction", "rawPrediction"):
        assert not isinstance(out._columns[c], LazyDeviceColumn)
        assert out.is_device_resident(c)

    # First read of a lazy column compiles its DCE'd program; the value is
    # still bitwise per-stage. A second lazy column compiles again; reads
    # of already-read columns don't.
    _assert_bitwise(expected, out, ("s1",))
    assert len(compiles) == 2
    _assert_bitwise(expected, out, ("s2", "s1"))
    assert len(compiles) == 3
    # Same chain, same bucket, fresh transform: lazy reads now cache-hit.
    (out2,) = pm.transform(t)
    _assert_bitwise(expected, out2, ("s1", "s2"))
    assert len(compiles) == 3


def test_device_column_upload_and_object_column_rejection():
    t = _data()
    d1 = t.device_column("features")
    d2 = t.device_column("features")
    assert d1 is d2, "host->device upload must be cached per table"
    ragged = Table({"obj": np.array([{1: 2}, {3: 4}], dtype=object)})
    with pytest.raises(TypeError, match="no device representation"):
        ragged.device_column("obj")


def test_fused_chain_consumes_device_resident_input():
    """A second PipelineModel.transform over the previous fused output
    reads device-backed columns with zero fresh uploads."""
    t = _data(n=101)
    pm = _five_stage_chain(t)
    (out,) = pm.transform(t.select("features"))
    s = StandardScaler().set(StandardScaler.INPUT_COL, "s4").set(
        StandardScaler.OUTPUT_COL, "z1"
    ).fit(out)
    m = MaxAbsScaler().set(MaxAbsScaler.INPUT_COL, "z1").set(
        MaxAbsScaler.OUTPUT_COL, "z2"
    ).fit(s.transform(out)[0])
    before = _counters("pipeline.fusion")
    (out2,) = PipelineModel([s, m]).transform(out.select("s4"))
    after = _counters("pipeline.fusion")
    assert _delta(before, after, "fused_segments") == 1
    assert _delta(before, after, "host_to_device_transfers") == 0
    assert out2.is_device_resident("z2")
