"""Word2Vec: embedding quality on a synthetic topic corpus, synonyms,
doc vectors, persistence."""

import numpy as np
import pytest

from flinkml_tpu.models import Tokenizer, Word2Vec, Word2VecModel
from flinkml_tpu.table import Table


def _topic_corpus(n_docs=600, seed=0):
    """Two disjoint topics: words inside a topic co-occur, across don't."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "mouse", "bird"]
    tools = ["hammer", "wrench", "drill", "saw", "pliers"]
    docs = []
    for _ in range(n_docs):
        pool = animals if rng.uniform() < 0.5 else tools
        docs.append(" ".join(rng.choice(pool, size=8)))
    return docs, animals, tools


def _fit(docs, **kw):
    t = Table({"text": np.asarray(docs)})
    (tok,) = Tokenizer().set_input_col("text").set_output_col("tok").transform(t)
    w2v = (
        Word2Vec().set_input_col("tok").set_output_col("vec")
        .set_vector_size(16).set_window_size(3).set_min_count(2)
        .set_max_iter(10).set_learning_rate(2.0).set_batch_size(512)
        .set_seed(0)
    )
    for name, v in kw.items():
        getattr(w2v, f"set_{name}")(v)
    return w2v.fit(tok), tok


def _cos(a, b):
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


def test_topic_structure_in_embeddings():
    docs, animals, tools = _topic_corpus()
    model, _ = _fit(docs)
    vecs = {w: model.vectors[list(model.vocabulary).index(w)]
            for w in animals + tools}
    within = np.mean([
        _cos(vecs[a], vecs[b]) for a in animals for b in animals if a != b
    ])
    across = np.mean([
        _cos(vecs[a], vecs[t]) for a in animals for t in tools
    ])
    assert within > across + 0.3, (within, across)


def test_find_synonyms_prefers_same_topic():
    docs, animals, tools = _topic_corpus(seed=1)
    model, _ = _fit(docs)
    words, sims = model.find_synonyms("cat", 4)
    assert "cat" not in words
    same_topic = sum(1 for w in words if w in animals)
    assert same_topic >= 3, words
    assert np.all(np.diff(sims) <= 1e-6)


def test_doc_vectors_and_oov():
    docs, animals, tools = _topic_corpus(seed=2)
    model, tok = _fit(docs)
    (out,) = model.transform(tok)
    assert out["vec"].shape == (len(docs), 16)
    # A doc of only OOV tokens maps to the zero vector.
    oov = Table({"text": np.asarray(["zzz qqq"])})
    (otok,) = Tokenizer().set_input_col("text").set_output_col("tok").transform(oov)
    (ovec,) = model.transform(otok)
    np.testing.assert_array_equal(ovec["vec"][0], np.zeros(16))


def test_min_count_prunes_and_validation():
    docs = ["a a a a b", "a a c"]
    t = Table({"text": np.asarray(docs)})
    (tok,) = Tokenizer().set_input_col("text").set_output_col("tok").transform(t)
    model = (
        Word2Vec().set_input_col("tok").set_output_col("v")
        .set_min_count(2).set_vector_size(4).set_max_iter(1)
        .set_seed(0).fit(tok)
    )
    assert list(model.vocabulary) == ["a"]
    with pytest.raises(ValueError, match="minCount"):
        (
            Word2Vec().set_input_col("tok").set_output_col("v")
            .set_min_count(100).fit(tok)
        )


def test_persistence_and_determinism(tmp_path):
    docs, _, _ = _topic_corpus(n_docs=100, seed=3)
    model, tok = _fit(docs, max_iter=2)
    model.save(str(tmp_path / "w2v"))
    loaded = Word2VecModel.load(str(tmp_path / "w2v"))
    np.testing.assert_array_equal(loaded.vocabulary, model.vocabulary)
    (v1,) = model.transform(tok)
    (v2,) = loaded.transform(tok)
    np.testing.assert_allclose(v2["vec"], v1["vec"])
    model2, _ = _fit(docs, max_iter=2)
    np.testing.assert_array_equal(model2.vectors, model.vectors)


def test_sharded_trainer_matches_dense(monkeypatch):
    """Above the vocab threshold the in-RAM fit switches to the
    vocab-sharded ring trainer; forcing the threshold to 0 must
    reproduce the dense trainer's vectors on the same seed (identical
    sampling sequence; f32 summation order differs only through the
    ring's masked partial adds)."""
    docs, animals, tools = _topic_corpus()
    dense_model, _ = _fit(docs)
    monkeypatch.setenv("FLINKML_W2V_SHARD_VOCAB", "0")
    sharded_model, _ = _fit(docs)
    dv = dense_model._vectors
    sv = sharded_model._vectors
    np.testing.assert_allclose(sv, dv, rtol=2e-3, atol=2e-4)
    # And the sharded embedding still carries the topic structure.
    vec = {str(t): sv[i] for i, t in enumerate(sharded_model._vocab)}
    same = _cos(vec["cat"], vec["dog"])
    cross = _cos(vec["cat"], vec["hammer"])
    assert same > cross, (same, cross)


def test_onehot_accum_matches_scatter(monkeypatch):
    """FLINKML_TPU_W2V_ACCUM=onehot (the gated scatter-free one-hot
    matmul accumulation — the sort-class candidate mirroring the
    sparse-LR/GBT/ALS cumsum gates) follows the identical sampling
    sequence as the default scatter layout; the vectors agree up to f32
    summation order, and the embedding still carries the topic
    structure. Pinned so a measured device winner can flip the default
    without a numerics question."""
    docs, animals, tools = _topic_corpus(seed=4)
    scatter_model, _ = _fit(docs)
    monkeypatch.setenv("FLINKML_TPU_W2V_ACCUM", "onehot")
    onehot_model, _ = _fit(docs)
    np.testing.assert_array_equal(
        onehot_model.vocabulary, scatter_model.vocabulary
    )
    np.testing.assert_allclose(
        onehot_model._vectors, scatter_model._vectors, rtol=2e-3, atol=2e-4
    )
    vec = {str(t): onehot_model._vectors[i]
           for i, t in enumerate(onehot_model._vocab)}
    assert _cos(vec["cat"], vec["dog"]) > _cos(vec["cat"], vec["hammer"])


def test_w2v_accum_gate_rejects_unknown(monkeypatch):
    from flinkml_tpu.models.word2vec import _w2v_accum

    monkeypatch.setenv("FLINKML_TPU_W2V_ACCUM", "bogus")
    with pytest.raises(ValueError, match="FLINKML_TPU_W2V_ACCUM"):
        _w2v_accum()


def test_streamed_fit_shards_above_vocab_threshold(monkeypatch):
    """Above the threshold, the single-process streamed fit switches to
    the vocab-sharded ring trainer (same SGD trajectory as the dense
    streamed trainer up to ring summation order) instead of psumming a
    [vocab, dim] gradient per step."""
    docs, _, _ = _topic_corpus(n_docs=200)
    t = Table({"text": np.asarray(docs)})
    (tok,) = (
        Tokenizer().set_input_col("text").set_output_col("tok").transform(t)
    )

    def fit():
        return (
            Word2Vec().set_input_col("tok").set_output_col("vec")
            .set_vector_size(8).set_min_count(2).set_max_iter(3)
            .set_learning_rate(1.0).set_batch_size(256).set_seed(0)
            .fit(iter([tok]))
        )

    dense_model = fit()
    monkeypatch.setenv("FLINKML_W2V_SHARD_VOCAB", "0")
    sharded_model = fit()
    np.testing.assert_allclose(
        sharded_model._vectors, dense_model._vectors, rtol=2e-3, atol=2e-4
    )
