"""Normalizer / ElementwiseProduct / VectorSlicer / PolynomialExpansion /
Binarizer / Bucketizer / MaxAbsScaler / RobustScaler / Imputer vs sklearn
+ semantics."""

import numpy as np
import pytest
from sklearn.preprocessing import (
    Binarizer as SkBinarizer,
    KBinsDiscretizer,
    MaxAbsScaler as SkMaxAbs,
    Normalizer as SkNormalizer,
    PolynomialFeatures,
    RobustScaler as SkRobust,
)

from flinkml_tpu.models import (
    Binarizer,
    Bucketizer,
    ElementwiseProduct,
    Imputer,
    ImputerModel,
    MaxAbsScaler,
    MaxAbsScalerModel,
    Normalizer,
    PolynomialExpansion,
    RobustScaler,
    RobustScalerModel,
    VectorSlicer,
)
from flinkml_tpu.table import Table


def _x(n=57, d=4, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(loc=1.0, scale=3.0, size=(n, d))


# -- Normalizer --------------------------------------------------------------

@pytest.mark.parametrize("p", [1.0, 2.0, 3.0, float("inf")])
def test_normalizer_matches_sklearn(p):
    x = _x()
    t = Table({"input": x})
    norm = {1.0: "l1", 2.0: "l2", float("inf"): "max"}.get(p)
    (out,) = Normalizer().set(Normalizer.P, p).transform(t)
    if norm is not None:
        ref = SkNormalizer(norm=norm).fit_transform(x)
    else:
        ref = x / np.linalg.norm(x, ord=3, axis=1, keepdims=True)
    np.testing.assert_allclose(out.column("output"), ref, rtol=1e-12)


def test_normalizer_zero_row_stays_zero():
    t = Table({"input": np.zeros((3, 2))})
    (out,) = Normalizer().transform(t)
    np.testing.assert_array_equal(out.column("output"), np.zeros((3, 2)))


# -- ElementwiseProduct / VectorSlicer ---------------------------------------

def test_elementwise_product():
    x = _x(d=3)
    t = Table({"input": x})
    (out,) = (
        ElementwiseProduct().set_scaling_vec([2.0, 0.0, -1.0]).transform(t)
    )
    np.testing.assert_allclose(
        out.column("output"), x * np.array([2.0, 0.0, -1.0])
    )
    with pytest.raises(ValueError, match="dim"):
        ElementwiseProduct().set_scaling_vec([1.0]).transform(t)


def test_vector_slicer():
    x = _x(d=5)
    t = Table({"input": x})
    (out,) = VectorSlicer().set_indices([3, 0, 3]).transform(t)
    np.testing.assert_array_equal(out.column("output"), x[:, [3, 0, 3]])
    with pytest.raises(ValueError, match="within"):
        VectorSlicer().set_indices([5]).transform(t)


# -- PolynomialExpansion -----------------------------------------------------

def test_polynomial_expansion_matches_sklearn_as_set():
    x = _x(n=11, d=3, seed=1)
    t = Table({"input": x})
    (out,) = PolynomialExpansion().set_degree(3).transform(t)
    got = out.column("output")
    ref = PolynomialFeatures(degree=3, include_bias=False).fit_transform(x)
    assert got.shape == ref.shape
    # Same monomial set (ordering differs from sklearn's) — compare as
    # sorted column multisets row by row.
    np.testing.assert_allclose(np.sort(got, axis=1), np.sort(ref, axis=1),
                               rtol=1e-9)


def test_polynomial_expansion_degree1_is_identity():
    x = _x(n=5, d=2)
    t = Table({"input": x})
    (out,) = PolynomialExpansion().set_degree(1).transform(t)
    np.testing.assert_array_equal(out.column("output"), x)


# -- Binarizer ---------------------------------------------------------------

def test_binarizer_scalar_and_vector():
    x = _x(n=20, d=3, seed=2)
    s = x[:, 0]
    t = Table({"vec": x, "scalar": s})
    (out,) = (
        Binarizer()
        .set_input_cols(["vec", "scalar"]).set_output_cols(["bv", "bs"])
        .set_thresholds([0.5, 0.0])
        .transform(t)
    )
    np.testing.assert_array_equal(
        out.column("bv"), SkBinarizer(threshold=0.5).fit_transform(x)
    )
    np.testing.assert_array_equal(out.column("bs"), (s > 0).astype(float))


# -- Bucketizer --------------------------------------------------------------

def test_bucketizer_bins_match_kbins_edges():
    rng = np.random.default_rng(3)
    v = rng.uniform(0, 10, size=200)
    kb = KBinsDiscretizer(n_bins=4, encode="ordinal", strategy="quantile")
    ref = kb.fit_transform(v[:, None])[:, 0]
    edges = kb.bin_edges_[0].copy()
    edges[0], edges[-1] = -np.inf, np.inf
    t = Table({"v": v})
    (out,) = (
        Bucketizer()
        .set_input_cols(["v"]).set_output_cols(["b"])
        .set_splits_array([list(edges)])
        .transform(t)
    )
    np.testing.assert_array_equal(out.column("b"), ref)


def test_bucketizer_edges_and_last_bucket_inclusive():
    t = Table({"v": np.asarray([0.0, 1.0, 5.0, 10.0])})
    (out,) = (
        Bucketizer().set_input_cols(["v"]).set_output_cols(["b"])
        .set_splits_array([[0.0, 1.0, 10.0]])
        .transform(t)
    )
    # 0.0 -> bucket 0; 1.0 -> bucket 1 (left-inclusive); 10.0 -> last bucket
    np.testing.assert_array_equal(out.column("b"), [0.0, 1.0, 1.0, 1.0])


def test_bucketizer_handle_invalid():
    t = Table({"v": np.asarray([0.5, -1.0, np.nan]),
               "id": np.asarray([1.0, 2.0, 3.0])})
    bkt = (
        Bucketizer().set_input_cols(["v"]).set_output_cols(["b"])
        .set_splits_array([[0.0, 1.0]])
    )
    with pytest.raises(ValueError, match="outside"):
        bkt.transform(t)
    (skipped,) = bkt.set_handle_invalid("skip").transform(t)
    np.testing.assert_array_equal(skipped.column("id"), [1.0])
    (kept,) = bkt.set_handle_invalid("keep").transform(t)
    np.testing.assert_array_equal(kept.column("b"), [0.0, 1.0, 1.0])


def test_bucketizer_rejects_bad_splits():
    t = Table({"v": np.asarray([0.5])})
    with pytest.raises(ValueError, match="strictly"):
        (
            Bucketizer().set_input_cols(["v"]).set_output_cols(["b"])
            .set_splits_array([[1.0, 1.0]])
            .transform(t)
        )


# -- MaxAbsScaler ------------------------------------------------------------

def test_max_abs_scaler_matches_sklearn(tmp_path):
    x = _x(seed=4)
    x[:, 1] = 0.0  # all-zero feature: degenerate max-abs
    t = Table({"input": x})
    model = MaxAbsScaler().fit(t)
    (out,) = model.transform(t)
    ref = SkMaxAbs().fit_transform(x)
    np.testing.assert_allclose(out.column("output"), ref, rtol=1e-5, atol=1e-6)
    model.save(str(tmp_path / "mas"))
    loaded = MaxAbsScalerModel.load(str(tmp_path / "mas"))
    np.testing.assert_allclose(
        loaded.transform(t)[0].column("output"), out.column("output")
    )


# -- RobustScaler ------------------------------------------------------------

def test_robust_scaler_matches_sklearn(tmp_path):
    x = _x(n=201, seed=5)
    x[0] = 1e6  # outlier robustness is the point
    t = Table({"input": x})
    model = (
        RobustScaler().set_with_centering(True).fit(t)
    )
    (out,) = model.transform(t)
    ref = SkRobust(with_centering=True).fit_transform(x)
    np.testing.assert_allclose(out.column("output"), ref, rtol=1e-7, atol=1e-9)
    model.save(str(tmp_path / "rs"))
    loaded = RobustScalerModel.load(str(tmp_path / "rs"))
    np.testing.assert_allclose(
        loaded.transform(t)[0].column("output"), out.column("output")
    )


def test_robust_scaler_flags_and_validation():
    x = _x(n=50, seed=6)
    t = Table({"input": x})
    m = RobustScaler().set_with_scaling(False).set_with_centering(True).fit(t)
    (out,) = m.transform(t)
    np.testing.assert_allclose(
        out.column("output"), x - np.median(x, axis=0), rtol=1e-12
    )
    with pytest.raises(ValueError, match="lower"):
        RobustScaler().set_lower(0.8).set_upper(0.2).fit(t)


# -- Imputer -----------------------------------------------------------------

def test_imputer_strategies(tmp_path):
    v1 = np.asarray([1.0, np.nan, 3.0, np.nan, 8.0])
    v2 = np.asarray([2.0, 2.0, -1.0, 7.0, np.nan])
    t = Table({"a": v1, "b": v2})

    def impute(strategy):
        return (
            Imputer()
            .set_input_cols(["a", "b"]).set_output_cols(["oa", "ob"])
            .set_strategy(strategy)
            .fit(t).transform(t)[0]
        )

    mean = impute("mean")
    np.testing.assert_allclose(mean.column("oa")[1], (1 + 3 + 8) / 3)
    np.testing.assert_allclose(mean.column("ob")[4], (2 + 2 - 1 + 7) / 4)
    med = impute("median")
    np.testing.assert_allclose(med.column("oa")[1], 3.0)
    freq = impute("mostFrequent")
    np.testing.assert_allclose(freq.column("ob")[4], 2.0)

    model = (
        Imputer().set_input_cols(["a", "b"]).set_output_cols(["oa", "ob"])
        .fit(t)
    )
    model.save(str(tmp_path / "imp"))
    loaded = ImputerModel.load(str(tmp_path / "imp"))
    np.testing.assert_allclose(
        loaded.transform(t)[0].column("oa"), model.transform(t)[0].column("oa")
    )


def test_imputer_custom_missing_value():
    t = Table({"a": np.asarray([1.0, -999.0, 3.0])})
    (out,) = (
        Imputer().set_input_cols(["a"]).set_output_cols(["o"])
        .set_missing_value(-999.0)
        .fit(t).transform(t)[0],
    )
    np.testing.assert_allclose(out.column("o"), [1.0, 2.0, 3.0])


def test_imputer_all_missing_errors():
    t = Table({"a": np.asarray([np.nan, np.nan])})
    with pytest.raises(ValueError, match="no non-missing"):
        Imputer().set_input_cols(["a"]).set_output_cols(["o"]).fit(t)


def test_most_frequent_tie_breaks_smallest():
    t = Table({"a": np.asarray([5.0, 5.0, 2.0, 2.0, np.nan])})
    (out,) = (
        Imputer().set_input_cols(["a"]).set_output_cols(["o"])
        .set_strategy("mostFrequent").fit(t).transform(t)
    )
    assert out.column("o")[4] == 2.0


def test_imputer_vector_columns(tmp_path):
    rng = np.random.default_rng(20)
    vec = rng.normal(size=(30, 3))
    vec[5, 1] = np.nan
    vec[9, 2] = np.nan
    scalar = rng.normal(size=30)
    scalar[3] = np.nan
    t = Table({"v": vec, "s": scalar})
    model = (
        Imputer().set_input_cols(["v", "s"]).set_output_cols(["ov", "os"])
        .set_strategy("mean").fit(t)
    )
    (out,) = model.transform(t)
    assert not np.isnan(out["ov"]).any()
    assert not np.isnan(out["os"]).any()
    # Per-dimension means, not a global one.
    expected = np.nanmean(vec[:, 1])
    np.testing.assert_allclose(out["ov"][5, 1], expected)
    np.testing.assert_allclose(out["ov"][5, [0, 2]], vec[5, [0, 2]])
    # Persistence keeps the widths.
    model.save(str(tmp_path / "vimp"))
    loaded = ImputerModel.load(str(tmp_path / "vimp"))
    np.testing.assert_allclose(
        loaded.transform(t)[0]["ov"], out["ov"]
    )
    # Shape mismatches are rejected clearly.
    with pytest.raises(ValueError, match="fit as"):
        model.transform(Table({"v": scalar, "s": scalar}))


def test_imputer_rejects_zero_width_vector():
    t = Table({"v": np.zeros((5, 0))})
    with pytest.raises(ValueError, match="d >= 1"):
        Imputer().set_input_cols(["v"]).set_output_cols(["o"]).fit(t)
