"""One rank of an elastic process world, launched by test_cluster.py.

Driven by :class:`flinkml_tpu.cluster.ElasticProcessWorld`: world size
IS the process count, the rendezvous rides the ``FLINKML_TPU_COORD_ADDR``
env family through env-driven :func:`init_distributed` (the satellite
contract), and a :class:`~flinkml_tpu.faults.WorkerCrash` hard-exits the
highest rank mid-run — a real ``os._exit`` across a real process
boundary. The supervisor relaunches the survivors as a smaller world;
this script then finds the dead world's rank-scoped snapshot family and
re-lays it out to the new world via the checkpoint layout tags
(``reshard_rank_state``), finishing bit-identically to a continuous
single-process golden run.

State is two leaves chosen to exercise both layout tags:
``w`` (replicated — every rank must agree bit-exactly) and ``rows``
(``sharded:0`` — per-rank chunks reassemble and re-split on rescale).
The epoch math depends only on the epoch, so any resume path that is
NOT a silent fresh start reproduces the golden bits.

Usage: python _elastic_rank.py <workdir> [golden]
Writes ``<workdir>/result.json`` (or ``result-golden.json``) from the
final world's rank 0.
"""

import glob
import json
import os
import sys

EPOCHS = 6
KILL_EPOCH = 3
ROWS, DIM = 8, 3


def main() -> int:
    workdir = sys.argv[1]
    golden = len(sys.argv) > 2 and sys.argv[2] == "golden"

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from flinkml_tpu import faults
    from flinkml_tpu.iteration import CheckpointManager
    from flinkml_tpu.iteration.checkpoint import (
        rank_scoped,
        reshard_rank_state,
    )
    from flinkml_tpu.parallel import init_distributed

    # Env-driven rendezvous: ElasticProcessWorld exported the
    # FLINKML_TPU_COORD_ADDR family; world 1 degrades to a no-op.
    rank, world = init_distributed()

    ckdir = os.path.join(workdir, "ckpt-golden" if golden else "ckpt")
    mgr = CheckpointManager(ckdir, max_to_keep=10, rescale="reshard")
    layouts = {"w": "replicated", "rows": "sharded:0"}

    if not golden and world > 1 and rank == world - 1:
        # The chaos half: this rank dies at the epoch-KILL_EPOCH seam.
        # The marker file keeps the crash once-per-run ACROSS restarts —
        # a relaunched rank re-arming the same plan must not die again.
        faults.arm(faults.FaultPlan(faults.WorkerCrash(
            at=KILL_EPOCH, key="epoch", exit_code=23,
            marker=os.path.join(workdir, "crash.marker"),
        )))

    chunk = ROWS // world
    sl = slice(rank * chunk, (rank + 1) * chunk)

    scoped = rank_scoped(mgr)
    family = sorted(glob.glob(os.path.join(ckdir, "rank-*")))
    resumed_from = 0
    if world == 1 and family:
        # Survivor of a shrunken world: reassemble the dead world's
        # rank-scoped family and re-split it for (rank 0, world 1) —
        # the newest epoch EVERY old rank committed.
        epoch = min(
            CheckpointManager(d, rescale="reshard").latest_epoch() or 0
            for d in family
        )
        like = {"w": np.zeros(DIM), "rows": np.zeros((chunk, 2))}
        state = reshard_rank_state(ckdir, epoch, like, (rank, world),
                                   layouts=layouts)
        resumed_from = epoch
    elif scoped.latest_epoch() is not None:
        like = {"w": np.zeros(DIM), "rows": np.zeros((chunk, 2))}
        state, resumed_from = scoped.restore(
            scoped.latest_epoch(), like=like
        )
    else:
        state = {
            "w": np.zeros(DIM),
            "rows": np.arange(ROWS * 2, dtype=np.float64
                              ).reshape(ROWS, 2)[sl],
        }

    for epoch in range(resumed_from + 1, EPOCHS + 1):
        if faults.ACTIVE is not None:
            faults.fire("cluster.worker", rank=rank, epoch=epoch)
        # Epoch-only math: world-independent by construction, so any
        # honest resume reproduces the golden bits exactly.
        state = {
            "w": state["w"] + float(epoch) * np.arange(1.0, DIM + 1.0),
            "rows": state["rows"] * 1.5 + float(epoch),
        }
        scoped.save(state, epoch, layouts=layouts)
    scoped.wait()

    if rank == 0 and world == 1:
        out = os.path.join(
            workdir, "result-golden.json" if golden else "result.json"
        )
        with open(out, "w") as f:
            json.dump({
                "resumed_from": resumed_from,
                "epochs": EPOCHS,
                "w": state["w"].tolist(),
                "rows": np.asarray(state["rows"]).tolist(),
            }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
