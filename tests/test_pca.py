"""PCA vs sklearn: components, projections, explained variance,
persistence, pipeline composition."""

import numpy as np
import pytest
from sklearn.decomposition import PCA as SkPCA

from flinkml_tpu.models import PCA, PCAModel
from flinkml_tpu.table import Table


def _data(n=400, d=6, seed=0):
    rng = np.random.default_rng(seed)
    # Anisotropic: a few dominant directions so ordering is unambiguous.
    basis = rng.normal(size=(d, d))
    scales = np.array([10.0, 5.0, 2.0, 1.0, 0.5, 0.1])
    return rng.normal(size=(n, d)) * scales @ basis + rng.normal(size=d) * 3


def test_pca_matches_sklearn():
    x = _data()
    t = Table({"input": x})
    model = PCA().set_k(3).fit(t)
    sk = SkPCA(n_components=3).fit(x)
    # Eigenvalues (explained variance) match tightly.
    np.testing.assert_allclose(
        model.explained_variance, sk.explained_variance_, rtol=1e-4
    )
    np.testing.assert_allclose(
        model.explained_variance_ratio, sk.explained_variance_ratio_, rtol=1e-4
    )
    # Components match up to sign.
    for ours, theirs in zip(model.components, sk.components_):
        dot = abs(float(ours @ theirs))
        np.testing.assert_allclose(dot, 1.0, atol=1e-4)
    # Projections match up to per-component sign.
    (out,) = model.transform(t)
    ref = sk.transform(x)
    got = out.column("output")
    signs = np.sign((got * ref).sum(axis=0))
    np.testing.assert_allclose(got * signs, ref, atol=1e-3)


def test_pca_sign_deterministic():
    x = _data(seed=1)
    t = Table({"input": x})
    c1 = PCA().set_k(2).fit(t).components
    c2 = PCA().set_k(2).fit(t).components
    np.testing.assert_array_equal(c1, c2)
    # Max-|entry| of each component is positive.
    for comp in c1:
        assert comp[np.argmax(np.abs(comp))] > 0


def test_pca_save_load(tmp_path):
    x = _data(seed=2)
    t = Table({"input": x})
    model = PCA().set_k(4).fit(t)
    model.save(str(tmp_path / "pca"))
    loaded = PCAModel.load(str(tmp_path / "pca"))
    np.testing.assert_array_equal(loaded.components, model.components)
    np.testing.assert_allclose(
        loaded.transform(t)[0].column("output"),
        model.transform(t)[0].column("output"),
    )


def test_pca_model_data_roundtrip():
    x = _data(seed=3)
    t = Table({"input": x})
    model = PCA().set_k(2).fit(t)
    clone = PCAModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    np.testing.assert_allclose(
        clone.transform(t)[0].column("output"),
        model.transform(t)[0].column("output"),
    )


def test_pca_k_validation():
    t = Table({"input": np.random.default_rng(0).normal(size=(10, 3))})
    with pytest.raises(ValueError, match="k=5"):
        PCA().set_k(5).fit(t)


def test_pca_in_pipeline_before_trainer():
    from flinkml_tpu.models import LogisticRegression
    from flinkml_tpu.pipeline import Pipeline

    rng = np.random.default_rng(4)
    x = rng.normal(size=(300, 8))
    y = (x[:, 0] - x[:, 3] > 0).astype(np.float64)
    t = Table({"input": x, "label": y})
    pipe = Pipeline([
        PCA().set_k(5).set_output_col("features"),
        LogisticRegression().set_max_iter(40).set_global_batch_size(300)
        .set_learning_rate(1.0).set_seed(0),
    ])
    pm = pipe.fit(t)
    (pred,) = pm.transform(t)
    assert (pred["prediction"] == y).mean() > 0.9
