"""MLPClassifier / FMClassifier / FMRegressor / IsotonicRegression."""

import numpy as np
import pytest
from sklearn.isotonic import IsotonicRegression as SkIso
from sklearn.metrics import r2_score, roc_auc_score

from flinkml_tpu.models import (
    FMClassifier,
    FMClassifierModel,
    FMRegressor,
    FMRegressorModel,
    IsotonicRegression,
    IsotonicRegressionModel,
    MLPClassifier,
    MLPClassifierModel,
)
from flinkml_tpu.models.isotonic import pav
from flinkml_tpu.table import Table


# -- MLP ---------------------------------------------------------------------

def _xor_data(n=1200, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-1, 1, size=(n, 2))
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float64)
    return x, y


def _mlp(layers, **kw):
    m = (
        MLPClassifier().set_layers(layers).set_max_iter(600)
        .set_learning_rate(0.01).set_global_batch_size(256).set_tol(0.0)
        .set_seed(0)
    )
    for name, v in kw.items():
        getattr(m, f"set_{name}")(v)
    return m


def test_mlp_solves_xor():
    x, y = _xor_data()
    t = Table({"features": x, "label": y})
    model = _mlp([2, 16, 2]).fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.97
    probs = out["rawPrediction"]
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-9)


def test_mlp_multiclass():
    rng = np.random.default_rng(1)
    x = np.concatenate([
        rng.normal(size=(100, 2)) * 0.4 + c
        for c in ([0, 0], [4, 0], [0, 4])
    ])
    y = np.repeat([0.0, 1.0, 2.0], 100)
    t = Table({"features": x, "label": y})
    model = _mlp([2, 8, 3], max_iter=400).fit(t)
    (out,) = model.transform(t)
    assert (out["prediction"] == y).mean() > 0.97


def test_mlp_validation_and_persistence(tmp_path):
    x, y = _xor_data(n=300, seed=2)
    t = Table({"features": x, "label": y})
    with pytest.raises(ValueError, match="layers"):
        MLPClassifier().fit(t)
    with pytest.raises(ValueError, match="feature dim"):
        _mlp([5, 2]).fit(t)
    with pytest.raises(ValueError, match="class ids"):
        _mlp([2, 2]).fit(Table({"features": x, "label": y + 5}))
    model = _mlp([2, 8, 2], max_iter=50).fit(t)
    model.save(str(tmp_path / "mlp"))
    loaded = MLPClassifierModel.load(str(tmp_path / "mlp"))
    (p1,) = model.transform(t)
    (p2,) = loaded.transform(t)
    np.testing.assert_allclose(p2["rawPrediction"], p1["rawPrediction"])
    clone = MLPClassifierModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    (p3,) = clone.transform(t)
    np.testing.assert_allclose(p3["prediction"], p1["prediction"])


def test_mlp_deterministic():
    x, y = _xor_data(n=200, seed=3)
    t = Table({"features": x, "label": y})
    m1 = _mlp([2, 4, 2], max_iter=30).fit(t)
    m2 = _mlp([2, 4, 2], max_iter=30).fit(t)
    for a, b in zip(m1._weights, m2._weights):
        np.testing.assert_array_equal(a, b)


# -- FM ----------------------------------------------------------------------

def test_fm_classifier_learns_interactions():
    # Pure pairwise-interaction signal: linear models score ~chance.
    rng = np.random.default_rng(4)
    x = rng.choice([0.0, 1.0], size=(1500, 8))
    y = ((x[:, 0] * x[:, 1] + x[:, 2] * x[:, 3]) > 0.5).astype(np.float64)
    t = Table({"features": x, "label": y})
    model = (
        FMClassifier().set_factor_size(8).set_max_iter(800)
        .set_learning_rate(0.05).set_global_batch_size(512).set_tol(0.0)
        .set_seed(0).fit(t)
    )
    (out,) = model.transform(t)
    auc = roc_auc_score(y, out["rawPrediction"][:, 1])
    assert auc > 0.95, auc


def test_fm_regressor_and_persistence(tmp_path):
    rng = np.random.default_rng(5)
    x = rng.normal(size=(1000, 5))
    y = 2.0 + x[:, 0] - x[:, 4] + 1.5 * x[:, 1] * x[:, 2]
    t = Table({"features": x, "label": y})
    model = (
        FMRegressor().set_factor_size(6).set_max_iter(1500)
        .set_learning_rate(0.05).set_global_batch_size(512).set_tol(0.0)
        .set_seed(0).fit(t)
    )
    (out,) = model.transform(t)
    assert r2_score(y, out["prediction"]) > 0.95
    model.save(str(tmp_path / "fm"))
    loaded = FMRegressorModel.load(str(tmp_path / "fm"))
    np.testing.assert_allclose(
        loaded.transform(t)[0]["prediction"], out["prediction"]
    )
    clone = FMRegressorModel()
    clone.copy_params_from(model)
    clone.set_model_data(*model.get_model_data())
    np.testing.assert_allclose(
        clone.transform(t)[0]["prediction"], out["prediction"]
    )


def test_fm_reg_shrinks_factors():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(300, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    t = Table({"features": x, "label": y})

    def fit(reg):
        return (
            FMClassifier().set_factor_size(4).set_max_iter(300)
            .set_learning_rate(0.05).set_global_batch_size(256)
            .set_tol(0.0).set_seed(0).set_reg(reg).fit(t)
        )

    small, large = fit(0.0), fit(1.0)
    assert np.linalg.norm(large._v) < np.linalg.norm(small._v)
    assert np.linalg.norm(large._w) < np.linalg.norm(small._w)


def test_fm_rejects_nonbinary_labels():
    t = Table({"features": np.zeros((3, 2)),
               "label": np.asarray([0.0, 1.0, 2.0])})
    with pytest.raises(ValueError, match="0, 1"):
        FMClassifier().fit(t)


# -- Isotonic ----------------------------------------------------------------

def test_pav_matches_sklearn():
    rng = np.random.default_rng(7)
    x = rng.uniform(0, 10, 200)
    y = 0.5 * x + rng.normal(size=200)
    sk = SkIso().fit(x, y)
    bnd, val = pav(x, y, np.ones_like(x))
    np.testing.assert_allclose(np.interp(x, bnd, val), sk.predict(x),
                               rtol=1e-9, atol=1e-9)


def test_isotonic_weighted_and_decreasing(tmp_path):
    x = np.asarray([1.0, 2.0, 3.0, 4.0])
    y = np.asarray([1.0, 3.0, 2.0, 4.0])
    w = np.asarray([1.0, 1.0, 3.0, 1.0])
    t = Table({"features": x, "label": y, "w": w})
    model = IsotonicRegression().set_weight_col("w").fit(t)
    sk = SkIso().fit(x, y, sample_weight=w)
    (out,) = model.transform(t)
    np.testing.assert_allclose(out["prediction"], sk.predict(x), rtol=1e-12)
    # Decreasing.
    td = Table({"features": x, "label": y[::-1].copy()})
    md = IsotonicRegression().set_isotonic(False).fit(td)
    skd = SkIso(increasing=False).fit(x, y[::-1])
    np.testing.assert_allclose(
        md.transform(td)[0]["prediction"], skd.predict(x), rtol=1e-12
    )
    model.save(str(tmp_path / "iso"))
    loaded = IsotonicRegressionModel.load(str(tmp_path / "iso"))
    np.testing.assert_allclose(
        loaded.transform(t)[0]["prediction"], out["prediction"]
    )


def test_isotonic_interpolation_and_clamping():
    x = np.asarray([0.0, 10.0])
    y = np.asarray([0.0, 1.0])
    t = Table({"features": x, "label": y})
    model = IsotonicRegression().fit(t)
    probe = Table({"features": np.asarray([-5.0, 5.0, 15.0])})
    (out,) = model.transform(probe)
    np.testing.assert_allclose(out["prediction"], [0.0, 0.5, 1.0])


def test_isotonic_duplicate_x_ties():
    x = np.asarray([1.0, 1.0, 2.0])
    y = np.asarray([0.0, 2.0, 0.5])
    t = Table({"features": x, "label": y})
    model = IsotonicRegression().fit(t)
    sk = SkIso().fit(x, y)
    np.testing.assert_allclose(
        model.transform(t)[0]["prediction"], sk.predict(x), rtol=1e-12
    )


def test_isotonic_zero_weight_rows_dropped():
    x = np.asarray([1.0, 2.0, 3.0])
    y = np.asarray([5.0, 4.0, 3.0])
    w = np.asarray([0.0, 0.0, 1.0])
    t = Table({"features": x, "label": y, "w": w})
    model = IsotonicRegression().set_weight_col("w").fit(t)
    (out,) = model.transform(t)
    # Only the weight-1 row matters: constant fit at 3.0.
    np.testing.assert_allclose(out["prediction"], 3.0)
    with pytest.raises(ValueError, match="all weights"):
        IsotonicRegression().set_weight_col("w").fit(
            Table({"features": x, "label": y, "w": np.zeros(3)})
        )


def test_isotonic_accepts_vector_column():
    from flinkml_tpu.linalg import Vectors

    col = np.empty(3, dtype=object)
    for i, v in enumerate([1.0, 2.0, 3.0]):
        col[i] = Vectors.dense(v)
    t = Table({"features": col, "label": np.asarray([1.0, 2.0, 3.0])})
    model = IsotonicRegression().fit(t)
    (out,) = model.transform(t)
    np.testing.assert_allclose(out["prediction"], [1.0, 2.0, 3.0])


def test_mlp_regressor_fits_nonlinear_function(tmp_path):
    from sklearn.metrics import r2_score as _r2

    from flinkml_tpu.models import MLPRegressor, MLPRegressorModel

    rng = np.random.default_rng(21)
    x = rng.uniform(-2, 2, size=(1500, 2))
    y = np.sin(x[:, 0]) * 2 + x[:, 1] ** 2
    t = Table({"features": x, "label": y})
    model = (
        MLPRegressor().set_layers([2, 32, 1]).set_max_iter(1500)
        .set_learning_rate(0.01).set_global_batch_size(512).set_tol(0.0)
        .set_seed(0).fit(t)
    )
    (out,) = model.transform(t)
    assert _r2(y, out["prediction"]) > 0.95
    model.save(str(tmp_path / "mlpr"))
    loaded = MLPRegressorModel.load(str(tmp_path / "mlpr"))
    np.testing.assert_allclose(
        loaded.transform(t)[0]["prediction"], out["prediction"]
    )
    with pytest.raises(ValueError, match=r"hidden\.\.\., 1"):
        MLPRegressor().set_layers([2, 8, 2]).fit(t)


def test_mlp_regressor_has_no_raw_prediction_param():
    from flinkml_tpu.models import MLPRegressor

    assert MLPRegressor().get_param("rawPredictionCol") is None
