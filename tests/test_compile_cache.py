"""Persistent AOT compile cache: store round-trips, invalidation rules,
corruption robustness, concurrency, and the replica-pool one-compile
contract (ISSUE 11).

The serialization-dependent scenarios run ONCE in a clean child process
(``tests/_compile_cache_child.py``) and are asserted over here: once
jax's persistent compilation cache — which the suite's conftest enables —
LOADS one executable in a process, XLA:CPU registers its jit-kernels as
resident-but-not-re-emittable and every later compile sharing a
content-identical kernel serializes broken (the store's post-serialize
load check refuses such artifacts by design; `test_poisoned_serialize_
degrades_in_this_process` pins exactly that). A fresh process is also
the production cold-start shape the subsystem exists for. The remaining
tests (key semantics, activation, degraded modes) run in-process.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from flinkml_tpu import compile_cache, pipeline_fusion
from flinkml_tpu.compile_cache.store import CompileCacheStore, _key_hash
from flinkml_tpu.table import Table
from flinkml_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def _clean_cache_state():
    """Every test starts with no active store and an empty program
    cache, and leaves the process the same way (other test modules
    count compiles)."""
    compile_cache.reset()
    compile_cache.configure(None)
    pipeline_fusion.reset_cache()
    yield
    compile_cache.reset()
    compile_cache.configure(None)
    pipeline_fusion.reset_cache()


@pytest.fixture(scope="module")
def child_report():
    """The clean-process scenario report (one child run per module)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "_compile_cache_child.py")],
        capture_output=True, text=True, timeout=300,
        env={**os.environ,
             "PYTHONPATH": os.pathsep.join(
                 [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
                 + ([os.environ["PYTHONPATH"]]
                    if os.environ.get("PYTHONPATH") else [])
             )},
    )
    assert proc.returncode == 0, (
        f"compile-cache child scenarios crashed rc={proc.returncode}:\n"
        f"{proc.stderr[-3000:]}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


# -- clean-process scenarios (see module docstring) --------------------------


def test_disk_roundtrip_bitwise_parity(child_report):
    """Cold run compiles + stores; a fresh store over the same directory
    loads from disk; outputs are bitwise identical to the plain jit
    path both ways."""
    r = child_report["roundtrip"]
    assert r["stores"] > 0
    assert r["aot_files"] == r["stores"]
    assert r["warm_hits"] == r["stores"]  # every program loaded, none...
    assert r["warm_extra_misses"] == 0    # ...recompiled
    assert r["cold_bitwise"] and r["warm_bitwise"]


def test_corrupt_entry_falls_back_loudly(child_report):
    """Torn/corrupt cache entries log a warning, are deleted, and the
    caller recompiles and REWRITES them — correctness is never at the
    cache's mercy."""
    r = child_report["corrupt"]
    assert r["corrupt_entries"] == r["torn_files"] > 0
    assert r["warned"], "corruption was silent"
    assert r["served_bitwise"]
    assert r["rewritten_hits"] > 0  # replaced artifacts load again


def test_env_fingerprint_mismatch_invalidates(child_report):
    """A jax-version bump changes the env-hash namespace, and even a
    byte-identical artifact copied across namespaces is refused by the
    embedded env dict — never loaded stale."""
    r = child_report["env_mismatch"]
    assert r["namespaces_differ"]
    assert r["copied_entry_refused"]
    assert r["env_mismatches"] == 1


def test_concurrent_writers_share_one_build(child_report):
    """Racing get_or_compile calls on one key pay ONE build (per-key
    lock); independent stores racing on one path never publish a torn
    entry (temp-file + os.replace), and the entry reloads from disk."""
    r = child_report["race"]
    assert r["results"] == r["racing_threads"] == 4
    assert r["builds_one_store"] == 1
    assert r["compiled_outcomes"] == 1
    assert r["reload_outcome"] == "disk"
    assert r["reload_correct"]


def test_pool_spinup_pays_one_compile_per_program(child_report):
    """The ISSUE 11 bugfix pin: an N-replica pool warms the same
    (program, bucket, policy) identities ONCE — replica 0 compiles,
    every other replica loads the retargeted artifact. Without the
    shared store each per-device placement silently re-paid the full
    XLA compile inside jax.jit."""
    r = child_report["pool"]
    assert r["programs"] > 0
    assert r["misses"] == r["programs"]          # one compile per program
    assert r["hits"] == 3 * r["programs"]        # 3 replicas load it
    assert r["retarget_loads"] >= 2 * r["programs"]
    assert r["steady_state_compiles"] == 0
    assert r["bitwise_vs_direct"]


def test_retargeted_load_cross_device_parity(child_report):
    """One artifact compiled on the default device serves a transform
    pinned to a different device bitwise-identically."""
    r = child_report["retarget"]
    assert r["retarget_loads"] > 0
    assert r["bitwise"]


def test_plan_step_disk_roundtrip(child_report):
    """The third compile site: a fresh process's plan-sharded trainer
    loads its step executable from disk, numerically identical."""
    r = child_report["plan_step"]
    assert r["cold_misses"] >= 1 and r["cold_stores"] >= 1
    assert r["warm_hits"] >= 1
    assert r["cold_equal"] and r["warm_equal"]


# -- in-process behavior -----------------------------------------------------


def _fitted_mini_chain():
    from flinkml_tpu.models.scalers import MaxAbsScaler, StandardScaler
    from flinkml_tpu.pipeline import PipelineModel

    rng = np.random.default_rng(1)
    x = rng.normal(size=(70, 7))
    t = Table({"features": x})
    scaler = (StandardScaler().set(StandardScaler.INPUT_COL, "features")
              .set(StandardScaler.OUTPUT_COL, "mid").fit(t))
    (t1,) = scaler.transform(t)
    mx = (MaxAbsScaler().set(MaxAbsScaler.INPUT_COL, "mid")
          .set(MaxAbsScaler.OUTPUT_COL, "scaled").fit(t1))
    # TWO kernel stages, because only runs of >= 2 route through the
    # fused executor (the compile-cache seam).
    return PipelineModel([scaler, mx]), t


def test_poisoned_serialize_degrades_in_this_process():
    """In THIS process — where the suite's jax persistent compilation
    cache has loaded executables — an unserializable program must
    degrade to compile-only (post-serialize load check or serialize
    failure), never crash and never persist a bad artifact. Whichever
    way this process's history falls, transforms keep serving and every
    on-disk artifact is loadable."""
    import tempfile

    scaler, t = _fitted_mini_chain()
    (baseline,) = scaler.transform(t)
    base = np.asarray(baseline.column("scaled"))
    d = tempfile.mkdtemp(prefix="cc-inproc-")
    compile_cache.configure(d)
    pipeline_fusion.reset_cache()
    (out,) = scaler.transform(t)
    assert np.asarray(out.column("scaled")).tobytes() == base.tobytes()
    # Whatever was persisted must load in a fresh store; a poisoned
    # program must NOT have been persisted at all.
    stored = [os.path.join(r, f) for r, _, fs in os.walk(d)
              for f in fs if f.endswith(".aot")]
    compile_cache.reset()
    compile_cache.configure(d)
    pipeline_fusion.reset_cache()
    before = metrics.group("compile_cache").snapshot()["counters"]
    (again,) = scaler.transform(t)
    after = metrics.group("compile_cache").snapshot()["counters"]
    assert np.asarray(again.column("scaled")).tobytes() == base.tobytes()
    assert after.get("corrupt_entries", 0) == before.get(
        "corrupt_entries", 0
    ), "a poisoned artifact reached disk"
    if stored:
        assert after.get("hits", 0) > before.get("hits", 0)


def test_memory_store_shares_within_process():
    """A directory-less store dedupes compiles in-process (what
    ReplicaPool relies on) and persists nothing."""
    store = CompileCacheStore(None)
    compile_cache.configure(store)
    scaler, t = _fitted_mini_chain()
    scaler.transform(t)
    misses1 = metrics.group("compile_cache").snapshot()["counters"].get(
        "misses", 0
    )
    assert misses1 > 0
    pipeline_fusion.reset_cache()
    # reset_cache drops the store's memory layer too — re-transform
    # recompiles (no disk behind a memory store).
    scaler.transform(Table({"features": np.asarray(t.column("features"))}))
    misses2 = metrics.group("compile_cache").snapshot()["counters"].get(
        "misses", 0
    )
    assert misses2 > misses1
    assert store.entry_path(("k",)) is None


def test_serialization_unsupported_degrades(tmp_path, monkeypatch):
    """With the AOT serialization API unavailable the store degrades to
    compile-only: same results, nothing persisted, loud counter."""
    from flinkml_tpu.compile_cache import store as store_mod

    monkeypatch.setattr(store_mod, "_SUPPORT", [False])
    monkeypatch.setattr(store_mod, "_WARNED_UNSUPPORTED", [False])
    scaler, t = _fitted_mini_chain()
    compile_cache.configure(str(tmp_path))
    pipeline_fusion.reset_cache()
    (out,) = scaler.transform(t)
    assert out.column("scaled") is not None
    assert not [f for _, _, fs in os.walk(tmp_path)
                for f in fs if f.endswith(".aot")]
    assert metrics.group("compile_cache").snapshot()["counters"].get(
        "fallbacks", 0
    ) > 0


def test_stable_key_repr_and_hash():
    from flinkml_tpu.precision import resolve_policy
    from flinkml_tpu.sharding.plan import FSDP, FSDP_TP

    policy = resolve_policy("mixed")
    k1 = ("pipeline_fusion", ("fp", 8, policy), FSDP)
    k2 = ("pipeline_fusion", ("fp", 8, resolve_policy("mixed")), FSDP)
    assert compile_cache.stable_key_repr(k1) == \
        compile_cache.stable_key_repr(k2)
    assert _key_hash(k1) == _key_hash(k2)
    assert _key_hash(k1) != _key_hash(
        ("pipeline_fusion", ("fp", 8, policy), FSDP_TP)
    )
    # dicts render order-independently
    assert compile_cache.stable_key_repr({"b": 1, "a": 2}) == \
        compile_cache.stable_key_repr(dict([("a", 2), ("b", 1)]))


def test_env_var_activates_store(tmp_path, monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_DIR_VAR, str(tmp_path))
    compile_cache.reset()
    store = compile_cache.active_store()
    assert store is not None and store.directory == str(tmp_path)
    compile_cache.reset()
    monkeypatch.delenv(compile_cache.ENV_DIR_VAR)
    assert compile_cache.active_store() is None
