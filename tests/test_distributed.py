"""Tests for the multi-host control-plane helpers.

Single-process here; multi-host behavior is exercised through
``process_slice``'s explicit-argument form and the barrier riding the
8-device CPU mesh (participation of every device = participation of every
host's devices on a real pod).
"""

import os

import jax
import pytest

from flinkml_tpu.parallel import (
    DeviceMesh,
    host_barrier,
    init_distributed,
    process_slice,
)


def test_init_distributed_single_process_noop():
    idx, count = init_distributed()
    assert (idx, count) == (0, 1)


# -- rendezvous retry-with-backoff (ISSUE 4 satellite) ----------------------

def _patch_rendezvous(monkeypatch, outcomes, sleeps):
    """Route the initialize/is_initialized pair through a script:
    ``outcomes`` is a list of exceptions to raise (None = succeed)."""
    calls = []

    def fake_initialize(**kwargs):
        calls.append(kwargs)
        outcome = outcomes[len(calls) - 1]
        if outcome is not None:
            raise outcome

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False)
    import flinkml_tpu.parallel.distributed as dist

    monkeypatch.setattr(dist.time, "sleep", lambda s: sleeps.append(s))
    return calls


def test_init_distributed_retries_transient_rendezvous(monkeypatch):
    sleeps = []
    calls = _patch_rendezvous(monkeypatch, [
        RuntimeError("DEADLINE_EXCEEDED: barrier timed out"),
        RuntimeError("UNAVAILABLE: failed to connect to coordinator"),
        None,
    ], sleeps)
    idx, count = init_distributed("10.0.0.1:8476", 2, 0,
                                  max_attempts=3, backoff_s=0.5)
    assert len(calls) == 3
    # Exponential base PLUS jitter (ISSUE 9 satellite): each sleep lies
    # in [base, base * (1 + jitter)] — N ranks never retry in lockstep.
    assert 0.5 <= sleeps[0] <= 0.5 * 1.25
    assert 1.0 <= sleeps[1] <= 1.0 * 1.25
    # The real backend is still the single local process.
    assert (idx, count) == (jax.process_index(), jax.process_count())


def test_init_distributed_backoff_jitter_decorrelates():
    """The jitter draw is per-call uniform: two processes retrying the
    same attempt get different delays (with overwhelming probability
    over 32 draws), always inside [base, base*(1+jitter)]."""
    from flinkml_tpu.parallel.distributed import retry_backoff_s

    draws = {retry_backoff_s(3, 1.0, jitter=0.5) for _ in range(32)}
    assert len(draws) > 1, "jitter produced identical delays"
    assert all(4.0 <= d <= 6.0 for d in draws)
    assert retry_backoff_s(1, 0.0) == 0.0  # disabled backoff stays 0
    import random

    assert (retry_backoff_s(2, 1.0, jitter=0.5, rng=random.Random(7))
            == retry_backoff_s(2, 1.0, jitter=0.5, rng=random.Random(7)))


def test_init_distributed_fails_fast_on_non_transient(monkeypatch):
    sleeps = []
    calls = _patch_rendezvous(monkeypatch, [
        RuntimeError("INVALID_ARGUMENT: process id 7 out of range"),
        None,
    ], sleeps)
    with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
        init_distributed("10.0.0.1:8476", 2, 0, max_attempts=5)
    assert len(calls) == 1 and sleeps == []


def test_init_distributed_exhausts_attempts(monkeypatch):
    sleeps = []
    err = RuntimeError("connection refused")
    calls = _patch_rendezvous(monkeypatch, [err, err], sleeps)
    with pytest.raises(RuntimeError, match="connection refused"):
        init_distributed("10.0.0.1:8476", 2, 0,
                         max_attempts=2, backoff_s=0.25)
    assert len(calls) == 2 and len(sleeps) == 1
    assert 0.25 <= sleeps[0] <= 0.25 * 1.25


def test_init_distributed_total_deadline_cap(monkeypatch):
    """ISSUE 9 satellite: a total-deadline cap bounds the whole retry
    ladder — when the next (jittered) backoff would overrun it, the
    last transient failure is raised instead of sleeping toward an
    unbounded rendezvous."""
    sleeps = []
    err = RuntimeError("connection refused")
    calls = _patch_rendezvous(monkeypatch, [err] * 10, sleeps)
    import flinkml_tpu.parallel.distributed as dist

    t = [0.0]
    monkeypatch.setattr(dist.time, "monotonic", lambda: t[0])
    with pytest.raises(RuntimeError, match="connection refused"):
        # backoff 10s, deadline 5s: the FIRST retry sleep (>= 10s)
        # already overruns the budget — exactly one attempt, no sleep.
        init_distributed("10.0.0.1:8476", 2, 0,
                         max_attempts=10, backoff_s=10.0, deadline_s=5.0)
    assert len(calls) == 1 and sleeps == []

    with pytest.raises(ValueError, match="deadline_s"):
        init_distributed("10.0.0.1:8476", 2, 0, deadline_s=-1.0)


def test_host_barrier_sums_over_all_devices():
    mesh = DeviceMesh()
    assert host_barrier(mesh, tag=1) == mesh.axis_size()
    assert host_barrier(mesh, tag=3) == 3 * mesh.axis_size()


def test_host_barrier_default_mesh():
    assert host_barrier(tag=1) == len(jax.devices())


@pytest.mark.parametrize(
    "n,count,expected",
    [
        (10, 2, [(0, 5), (5, 10)]),
        (10, 3, [(0, 4), (4, 7), (7, 10)]),  # remainder to low hosts
        (2, 4, [(0, 1), (1, 2), (2, 2), (2, 2)]),
    ],
)
def test_process_slice_partitions_exactly(n, count, expected):
    slices = [process_slice(n, p, count) for p in range(count)]
    assert [(s.start, s.stop) for s in slices] == expected
    # Exact cover: concatenation of slices is 0..n.
    rows = [i for s in slices for i in range(s.start, s.stop)]
    assert rows == list(range(n))


def test_process_slice_defaults_to_this_process():
    s = process_slice(100)
    assert s == slice(0, 100)  # single-process: everything


def test_two_process_control_plane(tmp_path):
    """Launch 2 real processes through jax.distributed (Gloo over localhost).

    Covers the branch no single-process test can: ``init_distributed``
    actually calling ``jax.distributed.initialize`` (the reference's
    MiniCluster ITs exercise SharedProgressAligner the same way —
    SURVEY.md §4 tier 3), ``host_barrier`` over a mesh with
    non-addressable devices, ``process_slice`` with a real process
    count, a cross-process all-reduce, and barrier-ordered checkpoint
    manifest commit. See tests/_dist_worker.py for the worker body.
    """
    # One local device per process: the mesh must span processes, not be
    # satisfiable host-locally.
    _launch_multiprocess_workers(tmp_path, local_devices=1)


def test_two_process_multi_device_data_plane(tmp_path):
    """2 processes × 2 local CPU devices = a 4-device global mesh with
    mixed addressable/non-addressable shards per process — the layout a
    real multi-host pod has. Exercises all_reduce_sum, keyed_aggregate,
    and map_partition across the process boundary."""
    _launch_multiprocess_workers(tmp_path, local_devices=2)


@pytest.mark.parametrize("n_procs", [2, 4])
def test_sustained_cross_process_dispatch(tmp_path, n_procs):
    """Regression: ≥60 sustained collective steps on a multi-process mesh.

    An unsynchronized host loop deadlocks the Gloo backend between 20 and
    60 in-flight ``psum`` dispatches; ``synced_loop`` (the framework's
    bounded-dispatch policy) must sustain 80 — on 2 processes AND on a
    4-process pod (the control plane is not a pairwise special case). See
    tests/_sync_cadence_worker.py for the worker body.
    """
    _launch_multiprocess_workers(
        tmp_path, local_devices=1,
        worker_script="_sync_cadence_worker.py",
        ok_token="CADENCE_OK", check_artifacts=False, n_procs=n_procs,
    )


def test_two_process_streamed_fit(tmp_path):
    """Streamed out-of-core training across 2 real processes (× 2 local
    devices): per-process stream partitions, agreed SPMD schedule with
    unequal batch counts/heights, pooled init sampling, shared-directory
    checkpoint + exact resume. The fitted models must (a) be identical
    on every rank (replicated training state), and (b) match the
    single-process fit over the concatenated per-step batches — the
    equivalence contract of `iteration/stream_sync.py`. Reference: the
    partitioned-stream training the reference runs across TaskManagers
    (`ReplayOperator.java:62-250`, `LogisticRegression.java:334-386`)."""
    _streamed_fit_check(tmp_path, nproc=2, local_devices=2)


def test_four_process_streamed_fit(tmp_path):
    """The same full streamed/online catalog on a 4-process pod: the
    agreement layer (schedules, vocab unions, pooled init, failure
    agreement) is not a pairwise special case."""
    _streamed_fit_check(tmp_path, nproc=4, local_devices=1)


def _streamed_fit_check(tmp_path, nproc, local_devices):
    import sys

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _stream_mp_common as C
    from flinkml_tpu.models._linear_sgd import train_linear_model_stream
    from flinkml_tpu.models.kmeans import train_kmeans_stream

    workdir = _launch_multiprocess_workers(
        tmp_path, local_devices=local_devices,
        worker_script="_stream_mp_worker.py",
        ok_token="STREAM_OK", check_artifacts=False, n_procs=nproc,
        timeout_s=90 * nproc,
    )

    results = [
        np.load(workdir / f"result_{p}.npz") for p in range(nproc)
    ]
    # (a) replicated training state: every rank fitted the same model.
    for key in ("coef", "cents", "cents_rand", "cents_empty", "gmm_means",
                "gmm_weights", "mlp_w0", "gbt_feats", "gbt_leaves",
                "pca_components", "pca_variances", "lda_topics",
                "als_user_f", "als_item_f", "olr_coef", "okm_cents",
                "osc_mean", "osc_std", "w2v_vocab", "w2v_vecs",
                "als_empty_uf", "als_empty_if", "w2v_empty_vecs"):
        for p in range(1, nproc):
            assert np.array_equal(results[0][key], results[p][key]), (
                key, p
            )

    # Word2Vec: same-group tokens (shared contexts) embed closer than
    # cross-group ones; the vocabulary is the union of ALL ranks'
    # partitions.
    vocab = list(results[0]["w2v_vocab"])
    assert set(vocab) == {f"{g}{i}" for g in "ab" for i in range(5)}
    vecs = results[0]["w2v_vecs"]
    unit = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    a0, a1 = vocab.index("a0"), vocab.index("a1")
    b0 = vocab.index("b0")
    assert unit[a0] @ unit[a1] > unit[a0] @ unit[b0]

    # ALS: the factors reconstruct the planted low-rank ratings.
    assert float(results[0]["als_rmse"]) < 0.05, results[0]["als_rmse"]

    # Online FTRL learns the separable target's sign pattern; versions
    # count GLOBAL steps (max of the ranks' batch counts, not the sum).
    x_g, y_g = C.global_data()
    acc = float(
        (((x_g @ results[0]["olr_coef"]) > 0) == (y_g > 0.5)).mean()
    )
    assert acc > 0.8, acc
    max_batches = max(
        len(C.local_batches(p, nproc)) for p in range(nproc)
    )
    assert int(results[0]["olr_version"]) == max_batches
    assert int(results[0]["osc_version"]) == sum(
        len(C.local_batches(p, nproc)) for p in range(nproc)
    )

    # GMM: pooled moments + pooled init recover the planted components.
    got = np.sort(results[0]["gmm_means"], axis=0)
    np.testing.assert_allclose(got, C.GMM_MEANS, atol=0.3)

    # LDA: the two fitted topics separate the planted vocab halves.
    topics = results[0]["lda_topics"]  # [2, V], rows sum to 1
    first_half = topics[:, : C.LDA_VOCAB // 2].sum(axis=1)
    assert sorted(first_half) == pytest.approx([0.0, 1.0], abs=0.1), (
        first_half
    )
    # MLP (streamed-Adam runner) and GBT learn the separable target.
    assert float(results[0]["mlp_acc"]) > 0.9, results[0]["mlp_acc"]
    assert float(results[0]["gbt_acc"]) > 0.85, results[0]["gbt_acc"]

    # (b) single-process equivalence on the concatenated-step stream.
    mesh = DeviceMesh()
    exp_coef = train_linear_model_stream(
        iter(C.combined_batches(nproc)), mesh=mesh, **C.LINEAR_HP
    )
    np.testing.assert_allclose(
        results[0]["coef"], exp_coef, rtol=2e-4, atol=2e-5
    )
    # (b2) sparse-native CSR streaming: the 2-rank fit over SparseVector
    # partitions must match the single-process fit whose step-t batch
    # concatenates every rank's batch t.
    from flinkml_tpu.models.logistic_regression import LogisticRegression

    sp_est = LogisticRegression(mesh=mesh)
    for k, v in C.SPARSE_HP.items():
        getattr(sp_est, f"set_{k}")(v)
    exp_sp = sp_est.fit(iter(C.sparse_combined_tables(nproc)))._coefficient
    np.testing.assert_allclose(
        results[0]["sp_coef"], exp_sp, rtol=2e-4, atol=2e-5
    )
    exp_cents = train_kmeans_stream(
        iter({"x": b["x"]} for b in C.combined_batches(nproc)),
        k=C.K_CLUSTERS, mesh=mesh,
        initial_centroids=C.initial_centroids(), **C.KMEANS_HP,
    )
    np.testing.assert_allclose(
        results[0]["cents"], exp_cents, rtol=2e-4, atol=2e-4
    )


def test_two_process_rank_local_failures_abort_all_ranks(tmp_path):
    """Regression for the rank-local-failure hang class: a failure on ONE
    rank (raising source iterator, ragged batch in streamed ingest, a
    missing/corrupt rank-scoped checkpoint shard) must abort EVERY rank
    together through the agreement layer — never strand the healthy rank
    in its next collective. Also pins the straddled-checkpoint resume
    protocol (newest COMMON tree, or an agreed restart when the rank
    checkpoint sets are disjoint). See tests/_hang_guard_worker.py for
    the cases; a hang fails this test's subprocess timeout."""
    _launch_multiprocess_workers(
        tmp_path, local_devices=1,
        worker_script="_hang_guard_worker.py",
        ok_token="GUARD_OK", check_artifacts=False,
    )


def _launch_multiprocess_workers(
    tmp_path, local_devices, worker_script="_dist_worker.py",
    ok_token="WORKER_OK", check_artifacts=True, n_procs=2,
    timeout_s=180,
):
    import shutil
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__), worker_script)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if local_devices > 1:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={local_devices}"
        )
    else:
        env.pop("XLA_FLAGS", None)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    # Workers share the suite's persistent XLA cache: repeat runs (and
    # retries) skip recompiling the cross-process programs, which
    # otherwise dominate these tests' wall clock.
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

    def attempt(workdir):
        # Probe a free ephemeral port. The bind-then-close window is racy
        # (another process can claim it before the coordinator binds), so
        # the whole launch retries on a fresh port below.
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(port), str(p), str(n_procs),
                 workdir],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for p in range(n_procs)
        ]
        outputs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout_s)
                outputs.append(out)
        except subprocess.TimeoutExpired:
            # Kill the stragglers, then drain EVERY remaining pipe:
            # ranks after the wedged one may have finished and printed —
            # that output is the evidence for diagnosing which rank
            # wedged.
            for p in procs:
                if p.poll() is None:
                    p.kill()
            while len(outputs) < n_procs:
                try:
                    out, _ = procs[len(outputs)].communicate(timeout=5)
                except Exception:  # noqa: BLE001 — diagnostics only
                    out = "<timeout>"
                outputs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        ok = all(
            p.returncode == 0 and f"{ok_token} {rank}" in out
            for rank, (p, out) in enumerate(zip(procs, outputs))
        )
        return ok, outputs

    for retry in range(3):
        workdir = tmp_path / f"run{retry}"
        workdir.mkdir()
        ok, outputs = attempt(str(workdir))
        if ok:
            break
        shutil.rmtree(workdir, ignore_errors=True)
    assert ok, "all attempts failed; last outputs:\n" + "\n----\n".join(outputs)
    if check_artifacts:
        # The committed artifacts exist on the shared filesystem.
        assert (workdir / "manifest.json").exists()
        assert (workdir / "ckpt").is_dir()
    return workdir
