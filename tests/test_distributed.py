"""Tests for the multi-host control-plane helpers.

Single-process here; multi-host behavior is exercised through
``process_slice``'s explicit-argument form and the barrier riding the
8-device CPU mesh (participation of every device = participation of every
host's devices on a real pod).
"""

import jax
import pytest

from flinkml_tpu.parallel import (
    DeviceMesh,
    host_barrier,
    init_distributed,
    process_slice,
)


def test_init_distributed_single_process_noop():
    idx, count = init_distributed()
    assert (idx, count) == (0, 1)


def test_host_barrier_sums_over_all_devices():
    mesh = DeviceMesh()
    assert host_barrier(mesh, tag=1) == mesh.axis_size()
    assert host_barrier(mesh, tag=3) == 3 * mesh.axis_size()


def test_host_barrier_default_mesh():
    assert host_barrier(tag=1) == len(jax.devices())


@pytest.mark.parametrize(
    "n,count,expected",
    [
        (10, 2, [(0, 5), (5, 10)]),
        (10, 3, [(0, 4), (4, 7), (7, 10)]),  # remainder to low hosts
        (2, 4, [(0, 1), (1, 2), (2, 2), (2, 2)]),
    ],
)
def test_process_slice_partitions_exactly(n, count, expected):
    slices = [process_slice(n, p, count) for p in range(count)]
    assert [(s.start, s.stop) for s in slices] == expected
    # Exact cover: concatenation of slices is 0..n.
    rows = [i for s in slices for i in range(s.start, s.stop)]
    assert rows == list(range(n))


def test_process_slice_defaults_to_this_process():
    s = process_slice(100)
    assert s == slice(0, 100)  # single-process: everything
