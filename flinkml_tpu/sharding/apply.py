"""Threading a :class:`ShardingPlan` through trainer hot loops.

The plan names WHAT shards; this module makes pjit DO it: one jitted
step whose ``in_shardings``/``out_shardings`` come straight from the
plan, so parameters AND optimizer state (SGD momentum, Adam m/v) live
sharded FSDP-style across the mesh, batches arrive sharded along the
plan's batch axes, and GSPMD inserts the collectives (the gradient
reduce-scatter / parameter all-gather pair FSDP is). A model whose
replicated per-device footprint exceeds one chip's HBM slice trains
end-to-end because no device ever holds more than its plan shard of
the state.

Every entry point validates the plan against the mesh BEFORE any
compile via the FML5xx pass (:mod:`flinkml_tpu.analysis.sharding_check`)
— a wrong-axis or non-dividing plan fails in milliseconds with a rule
id, not minutes later inside XLA.

Checkpointing composes through the plan too:
``CheckpointManager.save(state, epoch, plan=plan)`` records layout tags
*derived from the plan* (``sharded:<dim>`` per family), so the elastic
resharded-resume machinery (PR 6) restores a plan-sharded snapshot at a
different world size with the same one-source-of-truth tags training
used. The loop runs the same ``rank.lost`` fault seam + preemption
watchdog protocol as :func:`flinkml_tpu.iteration.iterate`, so the
elastic kill/shrink/resume story covers plan-sharded training.
"""

from __future__ import annotations

import functools
import math
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import flinkml_tpu.faults as faults
from flinkml_tpu.ops.losses import margin_terms
from flinkml_tpu.sharding.plan import ShardingPlan, layouts_for, state_names
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("sharding")


class PlanValidationError(ValueError):
    """A :class:`ShardingPlan` failed FML5xx validation against its mesh
    — raised BEFORE any compile, carrying the rendered findings (rule
    ids + fix hints). The ahead-of-time half of the plan contract: a
    plan that reaches pjit has already passed the same checks
    ``python -m flinkml_tpu.analysis`` runs on ``.plan.json``
    fixtures."""


def _inner_mesh(mesh):
    """The ``jax.sharding.Mesh`` inside a ``DeviceMesh`` (or the mesh
    itself)."""
    return getattr(mesh, "mesh", mesh)


def validate_plan(plan: ShardingPlan, mesh,
                  param_shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                  hbm_budget_bytes: Optional[int] = None,
                  dtype_bytes: int = 4,
                  optimizer_slots: int = 1) -> None:
    """Run the FML5xx pass; raise :class:`PlanValidationError` on any
    error-severity finding."""
    from flinkml_tpu.analysis.sharding_check import check_plan

    findings = check_plan(
        plan, mesh, param_shapes=param_shapes,
        hbm_budget_bytes=hbm_budget_bytes, dtype_bytes=dtype_bytes,
        optimizer_slots=optimizer_slots,
    )
    errors = [f for f in findings if f.severity == "error"]
    if errors:
        raise PlanValidationError(
            f"sharding plan {plan.name!r} failed validation against the "
            "mesh:\n" + "\n".join(f.render() for f in errors)
        )


# -- sharding construction ---------------------------------------------------


def state_shardings(plan: ShardingPlan, mesh, state):
    """A ``NamedSharding`` pytree for ``state`` per the plan's family
    table (leaf names follow :func:`~flinkml_tpu.sharding.plan.
    state_names`'s ``a/b/c`` key-path convention)."""
    from jax.sharding import NamedSharding

    m = _inner_mesh(mesh)
    names = iter(state_names(state))

    def one(leaf):
        name, _ = next(names)
        return NamedSharding(
            m, plan.partition_spec(name, ndim=int(np.ndim(leaf)))
        )

    return jax.tree_util.tree_map(one, state)


def batch_sharding(plan: ShardingPlan, mesh):
    """The ``NamedSharding`` for a batch array: leading dim over the
    plan's batch axes."""
    from jax.sharding import NamedSharding

    return NamedSharding(_inner_mesh(mesh), plan.batch_partition_spec())


def shard_state(plan: ShardingPlan, mesh, state):
    """``device_put`` every state leaf per the plan — the placement step
    that turns a host (or replicated) pytree into the FSDP-sharded
    working set."""
    return jax.tree_util.tree_map(
        jax.device_put, state, state_shardings(plan, mesh, state)
    )


def batch_world(plan: ShardingPlan, mesh) -> int:
    """The product of the plan's batch-axis sizes — what batch row
    counts must divide (pad with zero-weight rows otherwise)."""
    sizes = _inner_mesh(mesh).shape
    n = 1
    for axis in plan.batch_axes:
        n *= int(sizes[axis])
    return n


# -- the plan-threaded linear trainer ---------------------------------------


def init_linear_state(dim: int, optimizer: str, dtype) -> Dict[str, Any]:
    """The parameter + optimizer-state pytree for the linear family:
    SGD carries a same-shaped ``momentum`` buffer, Adam carries
    ``m``/``v`` plus the scalar step count. Dict-keyed so the plan's
    family patterns (and the checkpoint layout derivation) see names."""
    zeros = np.zeros(int(dim), dtype=np.dtype(dtype))
    if optimizer == "sgd":
        return {"coef": zeros, "momentum": zeros.copy()}
    if optimizer == "adam":
        return {"coef": zeros, "m": zeros.copy(), "v": zeros.copy(),
                "step": np.zeros((), dtype=np.dtype(dtype))}
    raise ValueError(f"optimizer must be 'sgd' or 'adam', got {optimizer!r}")


def _soft_threshold(x, t):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def linear_step_fn(loss: str, optimizer: str, dtype_name: str,
                   learning_rate: float, momentum: float,
                   reg_l2: float, reg_l1: float, policy=None):
    """The pure ``(state, xb, yb, wb) -> (new_state, loss)`` step of the
    linear family — the ONE definition behind the plan-sharded trainer,
    the FML6xx precision-flow validation, and the ``*.policy.json``
    fixture example programs (a fixture exercises the same jaxpr the
    product compiles).

    ``dtype_name`` is the STORAGE dtype of the state and the batch (what
    hyperparameter constants bake to). ``policy`` (a
    :class:`~flinkml_tpu.precision.PrecisionPolicy`, preset name, or
    None) enables the mixed-precision contract when it narrows compute
    below params: the batch and coefficient are cast down to
    ``policy.compute`` at the step boundary (SNIPPETS.md [3]'s
    ``to_bf16``), both matmuls carry ``preferred_element_type =
    policy.accum`` so the dot accumulators run full-width, and every
    state/optimizer update runs at the storage dtype. The builder does
    NOT second-guess a mis-declared combination — a ``dtype_name``
    narrower than ``policy.params`` produces a step that genuinely
    accumulates narrow, which is exactly what
    :func:`~flinkml_tpu.analysis.precision.validate_precision` refuses
    pre-compile (FML601/FML603)."""
    from flinkml_tpu.precision import resolve_policy

    policy = resolve_policy(policy)
    dt = jnp.dtype(dtype_name)
    lr = jnp.asarray(learning_rate, dt)
    mom = jnp.asarray(momentum, dt)
    l2 = jnp.asarray(reg_l2, dt)
    l1 = jnp.asarray(reg_l1, dt)
    mixed = policy is not None and policy.mixed
    if mixed:
        cdt = jnp.dtype(policy.compute_dtype)
        adt = jnp.dtype(policy.accum_dtype)

    def step(state, xb, yb, wb):
        coef = state["coef"]
        if mixed:
            # Step-boundary down-cast: the forward/backward matmuls run
            # at policy.compute, their accumulators at policy.accum.
            xb_c = xb.astype(cdt)
            coef_c = coef.astype(cdt)
            dot = jnp.matmul(xb_c, coef_c, preferred_element_type=adt)
        else:
            dot = xb @ coef
        mult, per_ex = margin_terms(loss, dot, yb, wb)
        wsum = jnp.maximum(jnp.sum(wb), jnp.asarray(1e-12, dt))
        if mixed:
            grad = jnp.matmul(
                xb_c.T, mult.astype(cdt), preferred_element_type=adt
            ) / wsum + 2.0 * l2 * coef
            grad = grad.astype(dt)  # state math runs at the storage dtype
        else:
            grad = xb.T @ mult / wsum + 2.0 * l2 * coef
        if optimizer == "sgd":
            buf = mom * state["momentum"] + grad
            new_coef = _soft_threshold(coef - lr * buf, lr * l1)
            new_state = {"coef": new_coef, "momentum": buf}
        else:  # adam
            t = state["step"] + 1.0
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = b1 * state["m"] + (1.0 - b1) * grad
            v = b2 * state["v"] + (1.0 - b2) * grad * grad
            update = (m / (1.0 - b1 ** t)) / (
                jnp.sqrt(v / (1.0 - b2 ** t)) + eps
            )
            new_coef = _soft_threshold(coef - lr * update, lr * l1)
            new_state = {"coef": new_coef, "m": m, "v": v, "step": t}
        loss_val = (jnp.sum(per_ex) + l2 * jnp.sum(jnp.square(coef))) / wsum
        return new_state, loss_val

    return step


def validate_linear_precision(policy, step, dim: int, rows: int, dt,
                              optimizer: str, plan=None,
                              program: str = "linear_step") -> None:
    """The pre-compile FML6xx gate for a linear-family step: trace
    ``step`` abstractly over the REAL state/batch specs and raise
    :class:`~flinkml_tpu.precision.PrecisionValidationError` on any
    finding — plus FML605 when ``plan`` is given and its HBM-budget
    width (the storage ``dt``) disagrees with ``policy.params``."""
    import jax

    from flinkml_tpu.analysis.precision import (
        check_policy_plan,
        validate_precision,
    )

    dt = np.dtype(dt)
    state = init_linear_state(dim, optimizer, dt)
    state_spec = jax.tree_util.tree_map(
        lambda leaf: jax.ShapeDtypeStruct(np.shape(leaf), leaf.dtype), state
    )
    batch = jax.ShapeDtypeStruct((int(rows), int(dim)), dt)
    vec = jax.ShapeDtypeStruct((int(rows),), dt)
    extra = check_policy_plan(
        policy, dtype_bytes=dt.itemsize,
        plan_name=getattr(plan, "name", None),
    ) if plan is not None else ()
    validate_precision(
        step, state_spec, batch, vec, vec,
        policy=policy, param_argnums=(0,), program=program,
        extra_findings=extra,
    )


class _PlanStepProgram:
    """The plan-sharded step behind an AOT seam: with no active
    :mod:`flinkml_tpu.compile_cache` store this IS the jitted step
    (identical dispatch path to before); with one, each batch shape is
    AOT-compiled through the store, so a fresh process — an elastic
    reshard restart, a recovery re-spawn — loads the serialized
    executable instead of re-paying the XLA compile. SPMD executables
    are placement-bound, so the artifact key carries the mesh's device
    ids and topology: a different device set misses (recompiles) rather
    than mis-loading."""

    def __init__(self, jitted, aot_key: tuple, device_ids: tuple):
        self._jitted = jitted
        self._aot_key = aot_key
        self._device_ids = device_ids
        self._programs: Dict[tuple, Any] = {}
        self._lock = threading.Lock()

    def __call__(self, state, xb, yb, wb):
        from flinkml_tpu import compile_cache

        store = compile_cache.active_store()
        if store is None:
            return self._jitted(state, xb, yb, wb)
        shape_key = (tuple(xb.shape), tuple(yb.shape), tuple(wb.shape))
        with self._lock:
            program = self._programs.get(shape_key)
        if program is None:
            program, _ = store.get_or_compile(
                self._aot_key + (shape_key,),
                lambda: self._jitted.lower(state, xb, yb, wb).compile(),
                device_ids=self._device_ids,
            )
            with self._lock:
                program = self._programs.setdefault(shape_key, program)
        return program(state, xb, yb, wb)


@functools.lru_cache(maxsize=64)
def _plan_linear_step(mesh, plan: ShardingPlan, loss: str, optimizer: str,
                      dim: int, dtype_name: str,
                      learning_rate: float, momentum: float,
                      reg_l2: float, reg_l1: float, policy=None):
    """ONE jitted plan-sharded step: margin gradient on the (data ×
    fsdp)-sharded batch, update on the fsdp-sharded state. The plan AND
    the precision policy are part of the cache key (both frozen +
    hashable), so two plans — or a bf16 and an f32 program — never alias
    one executable. Returned wrapped in :class:`_PlanStepProgram`, the
    persistent-compile-cache seam."""
    dt = jnp.dtype(dtype_name)
    state0 = init_linear_state(dim, optimizer, dt)
    state_sh = state_shardings(plan, mesh, state0)
    b_sh = batch_sharding(plan, mesh)
    step = linear_step_fn(
        loss, optimizer, dtype_name, learning_rate, momentum,
        reg_l2, reg_l1, policy=policy,
    )

    from jax.sharding import NamedSharding, PartitionSpec as P

    inner = _inner_mesh(mesh)
    scalar_sh = NamedSharding(inner, P())
    jitted = jax.jit(
        step,
        in_shardings=(state_sh, b_sh, b_sh, b_sh),
        out_shardings=(state_sh, scalar_sh),
    )
    device_ids = tuple(int(d.id) for d in inner.devices.flatten())
    aot_key = (
        "sharding.plan_step",
        tuple((str(a), int(s)) for a, s in inner.shape.items()),
        device_ids,
        plan, loss, optimizer, int(dim), dtype_name,
        float(learning_rate), float(momentum),
        float(reg_l2), float(reg_l1), policy,
    )
    return _PlanStepProgram(jitted, aot_key, device_ids)


def train_linear_plan(
    x: np.ndarray,
    y: np.ndarray,
    w: Optional[np.ndarray],
    plan: ShardingPlan,
    mesh,
    *,
    loss: str = "logistic",
    optimizer: str = "sgd",
    max_iter: int = 100,
    learning_rate: float = 0.1,
    momentum: float = 0.9,
    global_batch_size: Optional[int] = None,
    reg: float = 0.0,
    elastic_net: float = 0.0,
    tol: float = 0.0,
    dtype=None,
    precision=None,
    hbm_budget_bytes: Optional[int] = None,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
    sentinel=None,
) -> np.ndarray:
    """Plan-sharded linear-model training; returns the (global) host
    coefficient.

    The hot loop: one jitted plan-sharded step per epoch over a clamped
    rotating window of ``global_batch_size`` rows (the whole table when
    None) — the window is a function of the EPOCH alone, never of the
    mesh, so the same data trajectory runs at every world size (what
    makes plan × elastic resume composable). Rows pad to the plan's
    batch world with zero-weight rows (exact no-ops).

    ``hbm_budget_bytes`` feeds the pre-compile FML5xx validation: a
    replicated-but-huge family fails FML503 *here*, before XLA sees the
    program. ``checkpoint_manager`` snapshots the full parameter +
    optimizer state with PLAN-DERIVED layout tags
    (``save(..., plan=plan)``), so a snapshot taken at this mesh's world
    resumes at another under ``rescale="reshard"``. The loop honors the
    ``rank.lost`` fault seam and an ambient
    :class:`~flinkml_tpu.utils.preemption.PreemptionWatchdog` exactly
    like :func:`~flinkml_tpu.iteration.iterate`: a lost peer stops the
    loop cleanly at the epoch boundary with a terminal snapshot.

    ``precision`` (a :class:`~flinkml_tpu.precision.PrecisionPolicy`, a
    preset name like ``"mixed"``, or a policy JSON dict) declares the
    mixed-precision contract: the step's matmuls run at
    ``policy.compute`` with ``policy.accum`` accumulators while the
    parameter + optimizer state stays stored at ``dtype`` (which a
    compliant policy declares as ``policy.params``). The step's jaxpr is
    validated against the policy BEFORE any compile by the FML6xx
    precision-flow pass — a bf16-accumulating combination (e.g.
    ``dtype=bfloat16`` under the ``mixed`` policy) raises
    :class:`~flinkml_tpu.precision.PrecisionValidationError` carrying
    FML601/FML603 findings, exactly like :class:`PlanValidationError`
    for FML5xx. See ``docs/development/precision.md``.

    ``sentinel`` (a :class:`~flinkml_tpu.recovery.NumericsSentinel`)
    runs the same fused on-device numerics verdict as ``iterate`` over
    the plan-SHARDED state + loss at every epoch boundary — the verdict
    reduction shards with the state, so no gather is introduced — and
    raises a typed ``NumericsError`` before a non-finite state can be
    snapshotted. The loop also fires the ``train.step`` fault seam
    (pre/post), so the NaNGrad/InfLoss/PoisonBatch chaos faults cover
    plan-sharded training too.
    """
    from flinkml_tpu.iteration.checkpoint import begin_resume, should_snapshot
    from flinkml_tpu.utils import preemption

    if loss not in ("logistic", "hinge", "squared"):
        raise ValueError(f"unsupported loss {loss!r}")
    from flinkml_tpu.precision import resolve_policy

    policy = resolve_policy(precision)
    x = np.asarray(x)
    n, dim = x.shape
    if n == 0:
        raise ValueError("training table is empty")
    if dtype is not None:
        dt = np.dtype(dtype)
    elif policy is not None:
        # The policy DECLARES the storage width: an undeclared dtype
        # under a policy trains at policy.params (f64 input data under
        # x64 would otherwise conflict with params=float32 — FML605).
        # An EXPLICIT dtype still wins, and a conflicting one is
        # refused below (FML601/603/605).
        dt = policy.params_dtype
    else:
        dt = x.dtype
    # Canonicalize against the x64 flag so f64 inputs under 32-bit jax
    # train (consistently) in f32 instead of warning per scalar.
    dt = np.dtype(jax.dtypes.canonicalize_dtype(dt))
    x = x.astype(dt, copy=False)
    y = np.asarray(y, dtype=dt)
    w = (np.ones(n, dtype=dt) if w is None else np.asarray(w, dtype=dt))

    validate_plan(
        plan, mesh, param_shapes={"coef": (dim,)},
        hbm_budget_bytes=hbm_budget_bytes, dtype_bytes=dt.itemsize,
        optimizer_slots=1 if optimizer == "sgd" else 2,
    )

    world = _inner_mesh(mesh).size
    resume_epoch = begin_resume(checkpoint_manager, resume, world)
    state_h = init_linear_state(dim, optimizer, dt)
    epoch = 0
    if resume_epoch is not None:
        restored = checkpoint_manager.restore_latest(state_h)
        if restored is not None:
            state_h, epoch = restored
            _log.info(
                "plan-sharded resume: plan=%s epoch=%d world=%d",
                plan.name, epoch, world,
            )
    state = shard_state(plan, mesh, state_h)

    l2 = reg * (1.0 - elastic_net)
    l1 = reg * elastic_net
    if policy is not None:
        # The FML6xx gate, pre-compile: the SAME pure step the jitted
        # program below compiles, traced abstractly and checked against
        # the declared policy (plus FML605 when the plan's HBM math
        # width disagrees with policy.params).
        validate_linear_precision(
            policy,
            linear_step_fn(loss, optimizer, dt.name, float(learning_rate),
                           float(momentum), float(l2), float(l1),
                           policy=policy),
            dim, batch_world(plan, mesh), dt, optimizer, plan=plan,
            program=f"train_linear_plan[{optimizer}/{loss}]",
        )
    step = _plan_linear_step(
        _inner_mesh(mesh), plan, loss, optimizer, dim, dt.name,
        float(learning_rate), float(momentum), float(l2), float(l1),
        policy,
    )
    from flinkml_tpu.parallel.mesh import pad_to_multiple

    b_sh = batch_sharding(plan, mesh)
    bw = batch_world(plan, mesh)
    bs = n if global_batch_size is None else min(int(global_batch_size), n)
    n_windows = max(-(-n // bs), 1)
    window_cache: Dict[int, Tuple] = {}

    def window(epoch: int):
        # The clamped rotating tile of _linear_sgd._window, host-side:
        # a function of the epoch only, identical at every world. There
        # are only n_windows distinct windows per run, so each one pads
        # and uploads ONCE and stays device-resident (the full-batch
        # default is a single resident upload, matching the replicated
        # trainer's shard_batch economics). Padded rows carry weight 0
        # (w pads with zeros), so they are exact no-ops in the step.
        widx = epoch % n_windows
        cached = window_cache.get(widx)
        if cached is not None:
            return cached
        start = min(widx * bs, max(n - bs, 0))
        batch = tuple(
            jax.device_put(pad_to_multiple(a[start:start + bs], bw)[0],
                           b_sh)
            for a in (x, y, w)
        )
        window_cache[widx] = batch
        return batch

    watchdog = preemption.active()
    cur_loss = math.inf
    preempted = False
    terminal = False
    while epoch < max_iter:
        if faults.ACTIVE is not None:
            # Elastic seam: a scripted RankLost marks a peer dead at this
            # epoch boundary; the watchdog converts it into a clean
            # shrink-triggering stop (hard crash without one) — the same
            # contract as iterate's epoch boundary.
            faults.fire("rank.lost", epoch=epoch, watchdog=watchdog)
        if watchdog is not None and watchdog.requested:
            preempted = True
            break
        batch = window(epoch)
        if faults.ACTIVE is not None:
            # train.step pre seam: a PoisonBatch replaces the (cached,
            # device-resident) window with a NaN twin for THIS step only
            # — the cache keeps the clean window.
            fctx = {"phase": "pre", "epoch": epoch, "source_index": epoch,
                    "batch": batch}
            faults.fire_into("train.step", fctx)
            batch = fctx["batch"]
        state, loss_dev = step(state, *batch)
        if faults.ACTIVE is not None:
            # train.step post seam: NaNGrad poisons the sharded state,
            # InfLoss the loss.
            fctx = {"phase": "post", "epoch": epoch, "source_index": epoch,
                    "state": state, "criteria": loss_dev}
            faults.fire_into("train.step", fctx)
            state, loss_dev = fctx["state"], fctx["criteria"]
        epoch += 1
        cur_loss = float(loss_dev)
        if sentinel is not None:
            # Same verdict as iterate's epoch boundary, over the SHARDED
            # state — before the snapshot below can persist a bad state.
            sentinel.check(state, cur_loss, epoch=epoch - 1,
                           source_index=epoch - 1)
        terminal = tol > 0.0 and cur_loss <= tol
        if should_snapshot(checkpoint_manager, checkpoint_interval, epoch,
                           max_iter, terminal=terminal):
            checkpoint_manager.save(
                jax.tree_util.tree_map(np.asarray, state), epoch, plan=plan
            )
        if terminal:
            break
    if preempted and checkpoint_manager is not None:
        # The preemption's final snapshot (iterate's terminal-commit
        # contract): the survivors resume from exactly this epoch.
        checkpoint_manager.save(
            jax.tree_util.tree_map(np.asarray, state), epoch, plan=plan
        )
    if checkpoint_manager is not None:
        checkpoint_manager.wait()
    return np.asarray(state["coef"])


def plan_layouts(plan: ShardingPlan, state):
    """Public alias of :func:`flinkml_tpu.sharding.plan.layouts_for` —
    the tag pytree ``save(plan=...)`` derives (exposed for tests and
    for callers composing with ``reshard_rank_state``)."""
    return layouts_for(plan, state)
