"""flinkml_tpu.sharding — the declarative sharding layer (ROADMAP item 1).

The multichip dryruns (``MULTICHIP_r01..r05.json``) prove dp / sp / tp /
pp / ep shardings compile and run on an 8-device mesh, but until this
subsystem nothing user-facing could *ask* for them. A
:class:`~flinkml_tpu.sharding.plan.ShardingPlan` is a small frozen value
between the model code and ``pjit``: it maps parameter FAMILIES (name
patterns) to ``PartitionSpec``s over the named mesh axes ``data`` /
``fsdp`` / ``tp``, declares how batches shard, and is validated against
the mesh BEFORE any compile by the FML5xx analysis pass
(:mod:`flinkml_tpu.analysis.sharding_check`).

Three layers:

- :mod:`.plan` — the plan value itself: presets (``REPLICATED``,
  ``BATCH_PARALLEL``, ``FSDP``, ``FSDP_TP``), ``infer_plan`` (cheapest
  plan whose per-device footprint fits an HBM budget), JSON round-trip,
  and checkpoint layout-tag derivation (``layouts_for`` — the single
  source of truth the elastic-resume layer consumes).
- :mod:`.apply` — threads a plan through trainer hot loops: parameters
  AND optimizer state (SGD momentum, Adam m/v) shard FSDP-style under
  one jitted step whose in/out shardings come from the plan, batches
  shard along the plan's batch axes, and GSPMD inserts the collectives.
- :mod:`flinkml_tpu.analysis.sharding_check` — FML501 (unknown/illegal
  axis), FML502 (axis size does not divide the sharded dim), FML503
  (replicated-but-huge parameter vs the HBM budget), FML504 (two plans
  in one program implying conflicting collective orders).

See ``docs/development/sharding.md``.
"""

from flinkml_tpu.sharding.plan import (  # noqa: F401
    BATCH_PARALLEL,
    EMBEDDING,
    EMBEDDING_FAMILY_PATTERNS,
    FSDP,
    FSDP_TP,
    NoFeasiblePlanError,
    PRESETS,
    REPLICATED,
    ShardingPlan,
    infer_plan,
    is_embedding_param,
    layouts_for,
    per_device_state_bytes,
)
from flinkml_tpu.sharding.apply import (  # noqa: F401
    PlanValidationError,
    batch_sharding,
    shard_state,
    state_shardings,
    train_linear_plan,
)

__all__ = [
    "ShardingPlan",
    "REPLICATED",
    "BATCH_PARALLEL",
    "FSDP",
    "FSDP_TP",
    "EMBEDDING",
    "EMBEDDING_FAMILY_PATTERNS",
    "PRESETS",
    "infer_plan",
    "is_embedding_param",
    "layouts_for",
    "per_device_state_bytes",
    "NoFeasiblePlanError",
    "PlanValidationError",
    "batch_sharding",
    "shard_state",
    "state_shardings",
    "train_linear_plan",
]
