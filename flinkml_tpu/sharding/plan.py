"""ShardingPlan — canonical ``PartitionSpec``s per parameter family.

The mold is SNIPPETS.md [1] (``SpecLayout``: per-family specs keyed to
named mesh axes) and [2] (centralized presets like ``BATCH_SHARDING`` /
``MODEL_SHARDING``): a small declarative value between the model code
and ``pjit``. A plan answers three questions, each consumed by a
different layer:

1. *How does parameter ``name`` shard?* — ``spec_for``/``partition_spec``
   (consumed by :mod:`flinkml_tpu.sharding.apply`'s jitted steps);
2. *How do batches shard?* — ``batch_axes``/``batch_partition_spec``;
3. *How does a checkpointed leaf relate to the world size?* —
   ``layout_tag``/:func:`layouts_for` (consumed by
   :meth:`flinkml_tpu.iteration.checkpoint.CheckpointManager.save`'s
   ``plan=`` integration, which makes elastic resharded resume and
   plan-sharded training compose through ONE source of truth).

Family matching: ``rules`` is an ordered ``(pattern, spec)`` table;
``fnmatch`` patterns match the parameter's name (and, for nested
pytrees, its ``a/b/c`` key path) — FIRST match wins, unmatched names
take ``default_spec``. Spec entries are ``None`` (dim replicated), an
axis name, or a tuple of axis names (dim sharded over the product).
A spec longer than a parameter's rank TRUNCATES to the rank — the rule
that lets one ``FSDP_TP`` table serve both ``[d, h]`` matrices
(``("fsdp", "tp")``) and ``[d]`` vectors (``("fsdp",)``).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import math
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

#: The canonical mesh axis names (SNIPPETS.md [1]'s ``SpecLayout`` axes).
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"

SpecEntry = Union[None, str, Tuple[str, ...]]
Spec = Tuple[SpecEntry, ...]


class NoFeasiblePlanError(ValueError):
    """:func:`infer_plan` found no candidate plan whose per-device
    parameter + optimizer-state footprint fits the HBM budget on the
    given mesh. The message lists every candidate's footprint so the
    caller can see how far off the budget is (and whether the fix is a
    bigger mesh, an ``fsdp``/``tp`` axis the mesh lacks, or a larger
    budget)."""


def _normalize_entry(entry: Any) -> SpecEntry:
    if entry is None:
        return None
    if isinstance(entry, str):
        return entry
    if isinstance(entry, (tuple, list)):
        out = tuple(entry)
        if not all(isinstance(a, str) for a in out):
            raise ValueError(f"spec axis names must be strings, got {entry!r}")
        return out
    raise ValueError(
        f"spec entries must be None, an axis name, or a tuple of axis "
        f"names; got {entry!r}"
    )


def _normalize_spec(spec: Any) -> Spec:
    if spec is None:
        return ()
    if isinstance(spec, str):
        return (spec,)
    return tuple(_normalize_entry(e) for e in spec)


def entry_axes(entry: SpecEntry) -> Tuple[str, ...]:
    """The axis names one spec entry shards its dim over (() if none)."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """A frozen mapping from parameter families to partition specs over
    named mesh axes, plus the batch sharding. Hashable (usable as a
    compile-cache key) and JSON round-trippable (usable as an analysis
    fixture).

    ``rules``: ordered ``(fnmatch pattern, spec)`` pairs; first match
    wins. ``batch_axes``: the axes the batch's leading (row) dim shards
    over — ``()`` means replicated batches. ``default_spec``: the spec
    for names no rule matches (replicated by default — the safe
    fallback the checkpoint layer's ``replicated`` tag mirrors).
    """

    name: str
    rules: Tuple[Tuple[str, Spec], ...] = ()
    batch_axes: Tuple[str, ...] = ()
    default_spec: Spec = ()

    def __post_init__(self):
        object.__setattr__(
            self, "rules",
            tuple((str(p), _normalize_spec(s)) for p, s in self.rules),
        )
        object.__setattr__(
            self, "batch_axes", tuple(str(a) for a in self.batch_axes)
        )
        object.__setattr__(
            self, "default_spec", _normalize_spec(self.default_spec)
        )

    # -- family resolution -------------------------------------------------
    def spec_for(self, name: str, ndim: Optional[int] = None) -> Spec:
        """The spec for parameter ``name`` (first matching rule, else the
        default), truncated to ``ndim`` entries when given."""
        spec = self.default_spec
        last = name.rsplit("/", 1)[-1]
        for pattern, rule_spec in self.rules:
            if fnmatch.fnmatchcase(name, pattern) or \
                    fnmatch.fnmatchcase(last, pattern):
                spec = rule_spec
                break
        if ndim is not None:
            spec = spec[:ndim]
        return spec

    def partition_spec(self, name: str, ndim: Optional[int] = None):
        """The jax ``PartitionSpec`` for parameter ``name``."""
        from jax.sharding import PartitionSpec as P

        return P(*self.spec_for(name, ndim))

    def batch_partition_spec(self):
        """``PartitionSpec`` for a batch: leading dim over ``batch_axes``
        (as one composite entry), trailing dims replicated."""
        from jax.sharding import PartitionSpec as P

        if not self.batch_axes:
            return P()
        return P(self.batch_axes if len(self.batch_axes) > 1
                 else self.batch_axes[0])

    # -- introspection -----------------------------------------------------
    def param_axes(self, name: str, ndim: Optional[int] = None
                   ) -> Tuple[str, ...]:
        """Every axis name ``name``'s spec shards over, in dim order."""
        out: List[str] = []
        for entry in self.spec_for(name, ndim):
            out.extend(entry_axes(entry))
        return tuple(out)

    def is_sharded(self, name: str, ndim: Optional[int] = None) -> bool:
        return bool(self.param_axes(name, ndim))

    def shard_dim(self, name: str, ndim: Optional[int] = None
                  ) -> Optional[int]:
        """The FIRST dim index ``name``'s spec shards (None when fully
        replicated) — the dim the checkpoint ``sharded:<axis>`` layout
        tag records."""
        for i, entry in enumerate(self.spec_for(name, ndim)):
            if entry_axes(entry):
                return i
        return None

    def required_axes(self) -> Tuple[str, ...]:
        """Every mesh axis the plan references (params + batch), in
        first-use order."""
        seen: Dict[str, None] = {}
        for axis in self.batch_axes:
            seen.setdefault(axis)
        for _, spec in tuple(self.rules) + (("*", self.default_spec),):
            for entry in spec:
                for axis in entry_axes(entry):
                    seen.setdefault(axis)
        return tuple(seen)

    # -- checkpoint layout derivation --------------------------------------
    def layout_tag(self, name: str, ndim: Optional[int] = None) -> str:
        """The checkpoint leaf layout tag this plan implies for
        parameter ``name``: ``sharded:<dim>`` for the first sharded dim,
        else ``replicated``. This is the ONE source of truth tying
        plan-sharded training to elastic resharded resume: a snapshot
        of a plan-sharded state records the assembled global value plus
        this tag, so restore at a different world revalidates the same
        dim the plan shards."""
        from flinkml_tpu.iteration.checkpoint import (
            LAYOUT_REPLICATED,
            sharded,
        )

        dim = self.shard_dim(name, ndim)
        return LAYOUT_REPLICATED if dim is None else sharded(dim)

    # -- serialization -----------------------------------------------------
    def to_json_dict(self) -> dict:
        def enc(entry: SpecEntry):
            return list(entry) if isinstance(entry, tuple) else entry

        return {
            "name": self.name,
            "rules": [[p, [enc(e) for e in s]] for p, s in self.rules],
            "batch_axes": list(self.batch_axes),
            "default_spec": [enc(e) for e in self.default_spec],
        }

    @staticmethod
    def from_json_dict(d: Mapping) -> "ShardingPlan":
        def dec(entry):
            return tuple(entry) if isinstance(entry, list) else entry

        return ShardingPlan(
            name=str(d.get("name", "plan")),
            rules=tuple(
                (p, tuple(dec(e) for e in s)) for p, s in d.get("rules", ())
            ),
            batch_axes=tuple(d.get("batch_axes", ())),
            default_spec=tuple(dec(e) for e in d.get("default_spec", ())),
        )


# -- presets (SNIPPETS.md [2]'s BATCH_SHARDING/MODEL_SHARDING, grown up) ----

#: Everything replicated, batches replicated — the single-device-
#: equivalent program; the baseline every parity test compares against.
REPLICATED = ShardingPlan("replicated")

#: Classic data parallelism: parameters replicated, batches sharded over
#: ``data``. The cheapest plan with any parallelism (one gradient psum
#: per step).
BATCH_PARALLEL = ShardingPlan("batch_parallel", batch_axes=(DATA_AXIS,))

#: FSDP/ZeRO-3: parameters AND optimizer state shard dim 0 over
#: ``fsdp``; batches shard over ``data × fsdp`` (the fsdp axis does
#: double duty as a batch axis, the standard composition). Per-device
#: state footprint divides by the fsdp axis size.
FSDP = ShardingPlan(
    "fsdp",
    rules=(("*", (FSDP_AXIS,)),),
    batch_axes=(DATA_AXIS, FSDP_AXIS),
)

#: FSDP × tensor parallelism: matrices shard dim 0 over ``fsdp`` and
#: dim 1 over ``tp`` (SNIPPETS.md [1]'s ``qkv_projection`` shape);
#: vectors truncate to ``("fsdp",)``.
FSDP_TP = ShardingPlan(
    "fsdp_tp",
    rules=(("*", (FSDP_AXIS, TP_AXIS)),),
    batch_axes=(DATA_AXIS, FSDP_AXIS),
)

#: Name patterns of the EMBEDDING parameter family: ``[vocab, dim]``
#: tables whose rows are accessed SPARSELY (by id), so their shard
#: layout must keep rows whole. :mod:`flinkml_tpu.embeddings` names its
#: parameters ``<table>/embedding`` (optimizer slots
#: ``<table>/embedding_slot<i>``) to land in this family.
EMBEDDING_FAMILY_PATTERNS: Tuple[str, ...] = ("*embedding*",)


def is_embedding_param(name: str) -> bool:
    """Whether ``name`` belongs to the embedding family (matched on the
    full ``a/b/c`` key path and on its last component, the same double
    match :meth:`ShardingPlan.spec_for` applies)."""
    import fnmatch as _fn

    last = name.rsplit("/", 1)[-1]
    return any(
        _fn.fnmatchcase(name, p) or _fn.fnmatchcase(last, p)
        for p in EMBEDDING_FAMILY_PATTERNS
    )


#: The embedding plan (SNIPPETS.md [1]'s ``embeddings()`` spec —
#: ``PS((fsdp, tp), None)``): embedding-family tables shard their VOCAB
#: dim over the ``fsdp × tp`` PRODUCT with rows kept whole (the sparse
#: lookup/exchange primitives of :mod:`flinkml_tpu.embeddings` move
#: whole rows between shards); every other family shards FSDP×TP-style.
EMBEDDING = ShardingPlan(
    "embedding",
    rules=(
        ("*embedding*", ((FSDP_AXIS, TP_AXIS),)),
        ("*", (FSDP_AXIS, TP_AXIS)),
    ),
    batch_axes=(DATA_AXIS, FSDP_AXIS),
)

PRESETS: Dict[str, ShardingPlan] = {
    p.name: p
    for p in (REPLICATED, BATCH_PARALLEL, FSDP, FSDP_TP, EMBEDDING)
}


# -- footprint model + inference -------------------------------------------


_BYTE_UNITS = (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10))


def human_bytes(n: int) -> str:
    """``n`` in human units with the raw byte count in parens —
    ``"12.00 MiB (12582912 B)"``. Operators diff the MiB, machines diff
    the parens; every footprint/budget message renders through here so
    no gate ever prints a bare ten-digit byte string again."""
    n = int(n)
    for unit, div in _BYTE_UNITS:
        if n >= div:
            return f"{n / div:.2f} {unit} ({n} B)"
    return f"{n} B"


def _axis_sizes(mesh) -> Dict[str, int]:
    """Normalize a mesh spec — a ``DeviceMesh``, a ``jax.sharding.Mesh``,
    or a plain ``{axis: size}`` dict — to axis sizes."""
    if isinstance(mesh, Mapping):
        return {str(k): int(v) for k, v in mesh.items()}
    inner = getattr(mesh, "mesh", mesh)  # DeviceMesh wraps .mesh
    shape = getattr(inner, "shape", None)
    if isinstance(shape, Mapping):
        return {str(k): int(v) for k, v in shape.items()}
    raise TypeError(
        f"cannot read mesh axis sizes from {mesh!r}; pass a DeviceMesh, "
        "a jax Mesh, or an {axis: size} dict"
    )


def shard_slice_elems(plan: ShardingPlan, axis_sizes: Mapping[str, int],
                      name: str, shape: Sequence[int]) -> int:
    """Elements of parameter ``name``'s LARGEST per-device slice under
    ``plan``: the product over dims of ``ceil(extent / axis product)``.
    Per-DIM ceil (not ceil of total/factor) because an unevenly sharded
    dim pads to its ceiling — this is exactly the padded layout
    :class:`~flinkml_tpu.embeddings.EmbeddingTable` places, so the
    footprint ``infer_plan`` accepts and the FML503 check the table
    runs over its padded shape agree at every budget."""
    spec = plan.spec_for(name, ndim=len(shape))
    elems = 1
    for dim_idx, extent in enumerate(shape):
        factor = 1
        if dim_idx < len(spec):
            for axis in entry_axes(spec[dim_idx]):
                factor *= int(axis_sizes.get(axis, 1))
        elems *= math.ceil(int(extent) / factor)
    return elems


def per_device_state_bytes(
    plan: ShardingPlan,
    mesh,
    param_shapes: Mapping[str, Sequence[int]],
    dtype_bytes: int = 4,
    optimizer_slots: int = 1,
) -> int:
    """Per-device bytes of the parameters PLUS their optimizer state
    under ``plan``. ``optimizer_slots`` counts same-shaped optimizer
    companions per parameter (1 for SGD momentum, 2 for Adam m/v) —
    they shard exactly like their parameter, so the multiplier applies
    uniformly. Ceil-divides per sharded DIM (an uneven shard's largest
    slice is what must fit — see :func:`shard_slice_elems`)."""
    axis_sizes = _axis_sizes(mesh)
    slots = 1 + int(optimizer_slots)
    total = 0
    for name, shape in param_shapes.items():
        total += shard_slice_elems(plan, axis_sizes, name, shape) \
            * dtype_bytes * slots
    return total


#: The quantization-tier ladder :func:`infer_plan`'s memory-aware mode
#: walks, widest first: full float32, bf16 storage, then the int8
#: post-training-quantized tier (ROADMAP item 3's "re-run the footprint
#: against the quantized width so infer_plan can CHOOSE quantization to
#: fit a budget"). Each tier maps to the per-leaf width model of
#: :func:`per_device_state_bytes_tiered`.
QUANT_TIER_LADDER: Tuple[str, ...] = ("float32", "bfloat16", "int8")


def _tier_leaf_bytes(name: str, shape: Sequence[int], slice_elems: int,
                     tier: str, optimizer_slots: int) -> int:
    """Per-device bytes of one parameter leaf (plus its same-layout
    optimizer slots) under a quant tier. The int8 tier mirrors the fused
    executor's PTQ eligibility rule (:func:`flinkml_tpu.precision
    .quantizable`): float leaves of at least ``INT8_MIN_CONST_ELEMS``
    elements store 1 B/elem codes plus one float32 scale per last-axis
    column (replicated — scales are dim-sized, noise next to the codes);
    smaller leaves stay float32. Optimizer slots are never quantized —
    they hold running accumulators, not servable constants — so they
    cost the tier's FLOAT width (float32 for the int8 tier)."""
    from flinkml_tpu.precision import INT8_MIN_CONST_ELEMS

    total_elems = 1
    for d in shape:
        total_elems *= int(d)
    if tier == "float32":
        param, slot = 4 * slice_elems, 4 * slice_elems
    elif tier == "bfloat16":
        param, slot = 2 * slice_elems, 2 * slice_elems
    elif tier == "int8":
        if total_elems >= INT8_MIN_CONST_ELEMS and len(shape) >= 1:
            scale_cols = int(shape[-1]) if len(shape) >= 2 else 1
            param = 1 * slice_elems + 4 * scale_cols
        else:
            param = 4 * slice_elems
        slot = 4 * slice_elems
    else:
        raise ValueError(
            f"unknown quant tier {tier!r} (ladder: {QUANT_TIER_LADDER})"
        )
    return param + slot * int(optimizer_slots)


def per_device_state_bytes_tiered(
    plan: ShardingPlan,
    mesh,
    param_shapes: Mapping[str, Sequence[int]],
    tier: str = "float32",
    optimizer_slots: int = 1,
) -> int:
    """Per-device parameter + optimizer-state bytes under ``plan`` AND a
    quantization tier — the per-leaf-width generalization of
    :func:`per_device_state_bytes`'s scalar ``dtype_bytes`` (which stays
    as the fast FML503 screen). Sharded extents use the same per-dim
    ceil as :func:`shard_slice_elems`, so this model, the FML503 check,
    and the :class:`~flinkml_tpu.embeddings.EmbeddingTable` padded
    layout agree at every budget boundary."""
    axis_sizes = _axis_sizes(mesh)
    total = 0
    for name, shape in param_shapes.items():
        slice_elems = shard_slice_elems(plan, axis_sizes, name, shape)
        total += _tier_leaf_bytes(
            name, shape, slice_elems, tier, optimizer_slots
        )
    return total


#: The static candidate order: ascending communication cost (data
#: parallel's one psum < FSDP's all-gather/reduce-scatter pair <
#: FSDP×TP's extra tp collectives < EMBEDDING's per-step sparse row
#: exchange) — what :func:`infer_plan` uses when the tuning table has
#: no measured order for the current mesh.
STATIC_CANDIDATE_ORDER: Tuple[ShardingPlan, ...] = (
    BATCH_PARALLEL, FSDP, FSDP_TP, EMBEDDING,
)


def _splits_embedding_rows(plan: ShardingPlan, name: str,
                           shape: Sequence[int]) -> bool:
    """Whether ``plan`` would shard a NON-leading dim of embedding-family
    parameter ``name`` — a layout the sparse lookup/exchange primitives
    cannot host (they move whole rows between shards), so
    :func:`infer_plan` must skip it for embedding params even when the
    footprint fits."""
    spec = plan.spec_for(name, ndim=len(shape))
    return any(entry_axes(e) for e in spec[1:])


def _tuned_candidates() -> Tuple[ShardingPlan, ...]:
    """The measured candidate order for the current mesh (autotune knob
    ``infer_plan_order``), else :data:`STATIC_CANDIDATE_ORDER`. Unknown
    names in a table entry are skipped; presets it omits keep their
    static relative order at the back."""
    from flinkml_tpu.autotune import tuned_default

    names = tuned_default("infer_plan_order", None)
    if not names:
        return STATIC_CANDIDATE_ORDER
    by_name = {p.name: p for p in STATIC_CANDIDATE_ORDER}
    ordered = [by_name[n] for n in names if n in by_name]
    ordered += [p for p in STATIC_CANDIDATE_ORDER if p not in ordered]
    return tuple(ordered)


def infer_plan(
    mesh,
    param_shapes: Mapping[str, Sequence[int]],
    hbm_budget_bytes: int,
    dtype_bytes: int = 4,
    optimizer_slots: int = 1,
    candidates: Optional[Sequence[ShardingPlan]] = None,
    quant_tiers: Optional[Sequence[str]] = None,
) -> Union[ShardingPlan, Tuple[ShardingPlan, str]]:
    """The best plan whose per-device parameter + optimizer-state
    footprint fits ``hbm_budget_bytes`` on ``mesh``.

    ``candidates`` are tried in order. The default order is the tuning
    table's MEASURED preset order for this mesh when one is committed
    (``infer_plan_order`` — the autotune search promotes a preset past a
    cheaper one only on a decisive throughput win), else the static
    ascending-communication-cost order, in which "first fit" IS
    "cheapest fit". Candidates referencing axes the mesh does not have
    are skipped (a 1-D ``data`` mesh cannot host FSDP). Raises
    :class:`NoFeasiblePlanError` with every candidate's footprint when
    nothing fits.

    **Memory-aware mode**: ``quant_tiers`` (``True`` for the full
    :data:`QUANT_TIER_LADDER`, or an explicit subsequence of it) makes
    the search tier-major — every candidate at float32 first, then at
    bf16 storage, then at the int8 PTQ tier — and the return value
    becomes ``(plan, quant_tier)``: a parameter universe that is budget-
    infeasible at f32 routes to a fitting quantized tier instead of
    refusing. Footprints then come from the per-leaf width model
    (:func:`per_device_state_bytes_tiered`) instead of the scalar
    ``dtype_bytes``. When NO tier fits, the :class:`NoFeasiblePlanError`
    lists every tier's footprint per candidate — the FML704 shape.
    """
    if candidates is None:
        candidates = _tuned_candidates()
    axis_sizes = _axis_sizes(mesh)
    budget = int(hbm_budget_bytes)
    tiered = quant_tiers is not None
    tiers: Sequence[Optional[str]] = (
        (tuple(QUANT_TIER_LADDER) if quant_tiers is True
         else tuple(quant_tiers)) if tiered else (None,)
    )
    embedding_params = [
        n for n, s in param_shapes.items()
        if is_embedding_param(n) and len(s) > 1
    ]
    tried: List[str] = []
    skipped: set = set()
    for tier in tiers:
        for plan in candidates:
            if plan.name in skipped:
                continue
            missing = [a for a in plan.required_axes()
                       if a not in axis_sizes]
            if missing:
                tried.append(f"{plan.name}: mesh lacks axes {missing}")
                skipped.add(plan.name)
                continue
            split = [
                n for n in embedding_params
                if _splits_embedding_rows(plan, n, param_shapes[n])
            ]
            if split:
                # A plan that splits an embedding table's ROW payload
                # (e.g. FSDP_TP's dim-1 tp shard) cannot host the sparse
                # lookup/exchange primitives — skip it for this
                # parameter universe even though its footprint would fit.
                tried.append(
                    f"{plan.name}: splits embedding rows of {split} "
                    "across a non-leading dim (the sparse exchange "
                    "moves whole rows)"
                )
                skipped.add(plan.name)
                continue
            if tier is None:
                footprint = per_device_state_bytes(
                    plan, axis_sizes, param_shapes, dtype_bytes,
                    optimizer_slots,
                )
            else:
                footprint = per_device_state_bytes_tiered(
                    plan, axis_sizes, param_shapes, tier, optimizer_slots
                )
            if footprint <= budget:
                return (plan, tier) if tiered else plan
            label = plan.name if tier is None else f"{plan.name}@{tier}"
            tried.append(f"{label}: {human_bytes(footprint)}/device")
    raise NoFeasiblePlanError(
        f"no sharding plan fits hbm_budget_bytes={human_bytes(budget)} "
        f"on mesh {axis_sizes}"
        + (" at any quant tier" if tiered else "")
        + ": " + "; ".join(tried)
        + ". Add an fsdp/tp mesh axis, shrink the model, or raise the "
        "budget."
    )


# -- pytree naming + layout derivation --------------------------------------


def _key_name(key) -> str:
    """One pytree path entry's name (DictKey/GetAttrKey/SequenceKey/
    FlattenedIndexKey all duck-type to something printable)."""
    for attr in ("key", "name", "idx"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def state_names(state) -> Tuple[Tuple[str, Any], ...]:
    """``(name, leaf)`` per leaf of ``state``, names joined as ``a/b/c``
    key paths — the naming convention every plan-aware consumer
    (sharding application, layout derivation, validation) shares."""
    import jax

    leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(state)
    return tuple(
        ("/".join(_key_name(k) for k in path) or "param", leaf)
        for path, leaf in leaves_with_paths
    )


def layouts_for(plan: ShardingPlan, state):
    """The checkpoint layout-tag pytree ``plan`` implies for ``state`` —
    what :meth:`CheckpointManager.save`'s ``plan=`` kwarg records
    instead of hand-written ``layouts=`` tags (the ISSUE 7 single
    source of truth)."""
    import jax

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(state)
    tags = [
        plan.layout_tag(
            "/".join(_key_name(k) for k in path) or "param",
            ndim=int(np.ndim(leaf)),
        )
        for path, leaf in leaves_with_paths
    ]
    return jax.tree_util.tree_unflatten(treedef, tags)
