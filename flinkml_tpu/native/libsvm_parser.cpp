// Multithreaded libsvm-format parser with a C ABI for ctypes.
//
// Role in the framework (SURVEY.md §7 hard part (e)): the TPU must not be
// input-bound, and libsvm text (a9a, Criteo exports) parses at ~15 MB/s in
// pure Python. This parser splits the buffer at line boundaries across
// threads, makes one counting pass (rows / nnz / index base) and one filling
// pass into caller-allocated numpy buffers — zero copies beyond the fill.
// The reference has no native layer at all (pure JVM, SURVEY.md §2); this is
// the TPU framework's ingest equivalent of its record-stream sources.
//
// Parsing contract (kept in lockstep with the Python fallback in
// flinkml_tpu/io/libsvm.py):
//   - a line whose label does not parse as a number is a hard error;
//   - a malformed "index:value" token (missing ':', bad index, empty or
//     bad value, whitespace after ':') ends that line's feature list;
//   - '#' starts a comment; blank lines are skipped.
// Both passes run the SAME tokenizer (parse_line with a null/real writer),
// so counts and fills can never desynchronize.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -o libsvm_parser.so \
//            libsvm_parser.cpp -lpthread
// (flinkml_tpu.io.libsvm compiles this on demand and caches the .so.)

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Writer {
  double* labels = nullptr;
  int64_t* indptr = nullptr;
  int32_t* indices = nullptr;
  float* values = nullptr;
  int64_t index_base = 0;
};

struct Chunk {
  const char* begin;
  const char* end;
  int64_t rows = 0;
  int64_t nnz = 0;
  int64_t row_offset = 0;  // filled after prefix sum
  int64_t nnz_offset = 0;
  int64_t min_index = INT64_MAX;
  bool bad_label = false;
};

struct Parser {
  const char* buf;
  int64_t len;
  std::vector<Chunk> chunks;
  int64_t total_rows = 0;
  int64_t total_nnz = 0;
  int64_t min_index = INT64_MAX;
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline bool is_ws(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\n';
}

// Parse one chunk. When `w` is null this is the counting pass; otherwise it
// writes through `w` at the chunk's offsets. Identical control flow either
// way — the single source of truth for the parsing contract above.
void parse_chunk(Chunk* c, const Writer* w) {
  const char* p = c->begin;
  int64_t row = c->row_offset;
  int64_t at = c->nnz_offset;
  while (p < c->end) {
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(c->end - p)));
    if (!line_end) line_end = c->end;
    const char* q = skip_ws(p, line_end);
    if (q < line_end && *q != '#') {
      // Label: must parse as a number (hard error otherwise). Copy the
      // token so strtod cannot run past line_end.
      char* after = nullptr;
      double label = strtod(q, &after);
      // Strict: the label token must be fully numeric up to whitespace.
      if (after == q || after > line_end ||
          (after < line_end && !is_ws(*after))) {
        c->bad_label = true;
        return;
      }
      q = after;
      if (w) {
        w->labels[row] = label;
        w->indptr[row] = at;
      }
      // index:value pairs.
      while (true) {
        q = skip_ws(q, line_end);
        if (q >= line_end || *q == '#') break;
        long long idx = strtoll(q, &after, 10);
        if (after == q || after >= line_end || *after != ':') break;
        q = after + 1;
        // Value must start immediately after ':' (no whitespace) and
        // actually consume characters, inside this line.
        if (q >= line_end || is_ws(*q)) break;
        double v = strtod(q, &after);
        if (after == q || after > line_end) break;
        // The value must end at whitespace or line end ('2.0x' / '2.0#c'
        // are malformed tokens and end the line without emitting).
        if (after < line_end && !is_ws(*after)) break;
        q = after;
        if (idx < c->min_index) c->min_index = idx;
        if (w) {
          w->indices[at] = static_cast<int32_t>(idx - w->index_base);
          w->values[at] = static_cast<float>(v);
        }
        ++at;
      }
      ++row;
    }
    p = line_end + 1;
  }
  c->rows = row - c->row_offset;
  c->nnz = at - c->nnz_offset;
}

}  // namespace

extern "C" {

// Phase 1: split + count. Returns an opaque handle (NULL on failure) and
// writes total rows / nnz / detected index base (0 or 1). A malformed label
// anywhere returns NULL with *out_rows = -2.
void* libsvm_open(const char* buf, int64_t len, int32_t n_threads,
                  int64_t* out_rows, int64_t* out_nnz,
                  int64_t* out_index_base) {
  if (!buf || len <= 0 || n_threads < 1) return nullptr;
  auto* parser = new Parser{buf, len, {}, 0, 0, INT64_MAX};

  // Split at line boundaries.
  int64_t target = len / n_threads;
  const char* start = buf;
  const char* end = buf + len;
  for (int t = 0; t < n_threads && start < end; ++t) {
    const char* stop =
        (t == n_threads - 1) ? end : buf + (t + 1) * target;
    if (stop > end) stop = end;
    if (stop < end) {
      const char* nl = static_cast<const char*>(
          memchr(stop, '\n', static_cast<size_t>(end - stop)));
      stop = nl ? nl + 1 : end;
    }
    if (stop > start) {
      Chunk c;
      c.begin = start;
      c.end = stop;
      parser->chunks.push_back(c);
      start = stop;
    }
  }

  std::vector<std::thread> workers;
  for (auto& c : parser->chunks)
    workers.emplace_back(parse_chunk, &c, nullptr);
  for (auto& w : workers) w.join();

  for (auto& c : parser->chunks) {
    if (c.bad_label) {
      delete parser;
      *out_rows = -2;
      return nullptr;
    }
    c.row_offset = parser->total_rows;
    c.nnz_offset = parser->total_nnz;
    parser->total_rows += c.rows;
    parser->total_nnz += c.nnz;
    if (c.min_index < parser->min_index) parser->min_index = c.min_index;
  }
  *out_rows = parser->total_rows;
  *out_nnz = parser->total_nnz;
  // libsvm convention: 1-based unless a 0 index appears.
  *out_index_base = (parser->min_index == 0) ? 0 : 1;
  return parser;
}

// Phase 2: fill caller-allocated buffers.
// labels: [rows] f64; indptr: [rows+1] i64; indices: [nnz] i32;
// values: [nnz] f32. Returns 0 on success.
int32_t libsvm_fill(void* handle, double* labels, int64_t* indptr,
                    int32_t* indices, float* values, int64_t index_base) {
  auto* parser = static_cast<Parser*>(handle);
  if (!parser) return -1;
  Writer w{labels, indptr, indices, values, index_base};
  std::vector<std::thread> workers;
  for (auto& c : parser->chunks)
    workers.emplace_back(parse_chunk, &c, &w);
  for (auto& t : workers) t.join();
  indptr[parser->total_rows] = parser->total_nnz;
  return 0;
}

void libsvm_close(void* handle) {
  delete static_cast<Parser*>(handle);
}

}  // extern "C"
