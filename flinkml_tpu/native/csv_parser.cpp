// Multithreaded numeric-CSV parser with a C ABI for ctypes.
//
// Companion to libsvm_parser.cpp (same role: SURVEY.md §7 hard part (e) —
// vectorized ingest so the TPU is never input-bound; the reference reads
// CSV through Flink's table connectors, record-at-a-time on the JVM).
//
// Scope: numeric CSV — every field parses as a floating-point number,
// empty fields become NaN. No quoting support (documented; ML feature
// tables are numeric). '\r\n' and '\n' line endings; blank lines skipped.
// The column count is fixed by the first data row; any row with a
// different field count is a hard error reported by row number.
//
// Two passes over thread-private chunks split at line boundaries:
//   pass 1 counts rows and validates field counts,
//   pass 2 fills a caller-allocated COLUMN-MAJOR float64 buffer
//   (out[col * rows + row]) so each column is a contiguous numpy view —
//   zero per-column copies on the Python side.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -o csv_parser.so \
//            csv_parser.cpp -lpthread
// (flinkml_tpu.io.csv compiles this on demand and caches the .so.)

#include <charconv>
#include <string>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Chunk {
  const char* begin;
  const char* end;
  int64_t rows = 0;
  int64_t row_offset = 0;  // filled after prefix sum
  int64_t bad_row = -1;    // chunk-local index of first malformed row
};

struct Parser {
  const char* buf;
  int64_t len;
  char delim;
  std::vector<Chunk> chunks;
  int64_t total_rows = 0;
  int64_t cols = 0;
  int64_t bad_row = -1;  // global row number of first malformed row
};

// Field count of one line (delimiters + 1); lines are never empty here.
inline int64_t count_fields(const char* p, const char* eol, char delim) {
  int64_t n = 1;
  for (; p < eol; ++p) n += (*p == delim);
  return n;
}

// Parses one line into out (nullptr = count/validate only).
// Returns the number of fields, or -1 on a malformed numeric field.
inline int64_t parse_line(const char* p, const char* eol, char delim,
                          double* out, int64_t stride, int64_t row) {
  int64_t field = 0;
  while (true) {
    const char* fstart = p;
    while (p < eol && *p != delim) ++p;
    const char* fend = p;
    // Trim surrounding spaces/tabs and a trailing '\r'.
    while (fstart < fend && (*fstart == ' ' || *fstart == '\t')) ++fstart;
    while (fend > fstart &&
           (fend[-1] == ' ' || fend[-1] == '\t' || fend[-1] == '\r'))
      --fend;
    double v;
    if (fstart == fend) {
      v = __builtin_nan("");  // empty field -> NaN
    } else {
      // from_chars: locale-free, non-copying; accept a leading '+' for
      // parity with the Python fallback's float(). Out-of-range values
      // (1e400, 1e-400) take a rare strtod path so overflow saturates to
      // +/-inf and underflow to ~0 exactly as Python does.
      const char* numstart = (*fstart == '+') ? fstart + 1 : fstart;
      auto [endp, ec] = std::from_chars(numstart, fend, v);
      if (ec == std::errc::result_out_of_range && endp == fend) {
        // Heap copy: fields like "1" + 400 zeros are valid (-> inf).
        std::string tmp(numstart, static_cast<size_t>(fend - numstart));
        v = strtod(tmp.c_str(), nullptr);
      } else if (ec != std::errc() || endp != fend) {
        return -1;
      }
    }
    if (out != nullptr) out[field * stride + row] = v;
    ++field;
    if (p >= eol) break;
    ++p;  // skip delimiter
  }
  return field;
}

// True if the line is blank (only spaces/tabs/'\r').
inline bool is_blank(const char* p, const char* eol) {
  for (; p < eol; ++p)
    if (*p != ' ' && *p != '\t' && *p != '\r') return false;
  return true;
}

void split_chunks(Parser& ps, int nthreads) {
  int64_t target = ps.len / nthreads + 1;
  const char* pos = ps.buf;
  const char* bufend = ps.buf + ps.len;
  for (int t = 0; t < nthreads && pos < bufend; ++t) {
    const char* end = pos + target;
    if (end >= bufend) {
      end = bufend;
    } else {
      while (end < bufend && *end != '\n') ++end;
      if (end < bufend) ++end;  // include the newline
    }
    Chunk c;
    c.begin = pos;
    c.end = end;
    ps.chunks.push_back(c);
    pos = end;
  }
}

void count_chunk(Chunk& c, char delim, int64_t cols) {
  const char* p = c.begin;
  int64_t local = 0;
  while (p < c.end) {
    const char* eol = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(c.end - p)));
    const char* line_end = eol ? eol : c.end;
    if (!is_blank(p, line_end)) {
      if (count_fields(p, line_end, delim) != cols && c.bad_row < 0)
        c.bad_row = local;
      ++local;
    }
    p = eol ? eol + 1 : c.end;
  }
  c.rows = local;
}

void fill_chunk(const Chunk& c, char delim, int64_t cols, int64_t total_rows,
                double* out, int64_t* bad) {
  const char* p = c.begin;
  int64_t row = c.row_offset;
  while (p < c.end) {
    const char* eol = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(c.end - p)));
    const char* line_end = eol ? eol : c.end;
    if (!is_blank(p, line_end)) {
      int64_t got = parse_line(p, line_end, delim, out, total_rows, row);
      if (got != cols && *bad < 0) *bad = row;
      ++row;
    }
    p = eol ? eol + 1 : c.end;
  }
}

}  // namespace

extern "C" {

// Pass 1: scan the buffer, return a parser handle + dimensions.
// cols_out is taken from the first non-blank line. status: 0 ok,
// 1 inconsistent/invalid row (bad_row_out = its 0-based data-row number),
// 2 empty input.
void* csv_open(const char* buf, int64_t len, int32_t nthreads, char delim,
               int64_t* rows_out, int64_t* cols_out, int64_t* bad_row_out,
               int32_t* status) {
  auto* ps = new Parser{buf, len, delim, {}, 0, 0, -1};
  *status = 0;
  *bad_row_out = -1;
  if (len <= 0) {
    *rows_out = *cols_out = 0;
    *status = 2;
    return ps;
  }
  // Column count from the first non-blank line (single-threaded peek).
  {
    const char* p = buf;
    const char* bufend = buf + len;
    while (p < bufend) {
      const char* eol = static_cast<const char*>(
          memchr(p, '\n', static_cast<size_t>(bufend - p)));
      const char* line_end = eol ? eol : bufend;
      if (!is_blank(p, line_end)) {
        ps->cols = count_fields(p, line_end, delim);
        break;
      }
      p = eol ? eol + 1 : bufend;
    }
  }
  if (ps->cols == 0) {
    *rows_out = *cols_out = 0;
    *status = 2;
    return ps;
  }
  if (nthreads <= 0) nthreads = (int32_t)std::thread::hardware_concurrency();
  if (nthreads < 1) nthreads = 1;
  split_chunks(*ps, nthreads);
  std::vector<std::thread> threads;
  for (auto& c : ps->chunks)
    threads.emplace_back(count_chunk, std::ref(c), delim, ps->cols);
  for (auto& t : threads) t.join();
  int64_t offset = 0;
  for (auto& c : ps->chunks) {
    if (c.bad_row >= 0 && ps->bad_row < 0) ps->bad_row = offset + c.bad_row;
    c.row_offset = offset;
    offset += c.rows;
  }
  ps->total_rows = offset;
  *rows_out = ps->total_rows;
  *cols_out = ps->cols;
  if (ps->bad_row >= 0) {
    *bad_row_out = ps->bad_row;
    *status = 1;
  }
  return ps;
}

// Pass 2: fill the caller-allocated column-major [cols x rows] buffer.
// Returns 0 ok, 1 malformed field (bad_row_out = data-row number).
int32_t csv_fill(void* handle, double* out, int64_t* bad_row_out) {
  auto* ps = static_cast<Parser*>(handle);
  *bad_row_out = -1;
  std::vector<int64_t> bads(ps->chunks.size(), -1);
  std::vector<std::thread> threads;
  for (size_t i = 0; i < ps->chunks.size(); ++i)
    threads.emplace_back(fill_chunk, std::cref(ps->chunks[i]), ps->delim,
                         ps->cols, ps->total_rows, out, &bads[i]);
  for (auto& t : threads) t.join();
  for (int64_t b : bads)
    if (b >= 0 && (*bad_row_out < 0 || b < *bad_row_out)) *bad_row_out = b;
  return *bad_row_out >= 0 ? 1 : 0;
}

void csv_close(void* handle) { delete static_cast<Parser*>(handle); }

}  // extern "C"
