"""Columnar Table — the data-plane analog of Flink's ``Table``.

The reference moves data as row streams (``Table`` ↔ ``DataStream<Row>``,
e.g. ``LogisticRegression.java:111-130`` maps rows to POJOs one at a time).
On TPU, per-record processing wastes the MXU; the native representation is a
batched columnar store: each column is an array with leading axis = rows
(feature columns are 2-D ``[rows, dim]``). This single type replaces the
reference's Table conversions and record-at-a-time operators.

Columns live in one of two homes:

  - **host**: a numpy array (the ingest format, and the only home for
    object/ragged columns);
  - **device**: a ``jax.Array`` resident in accelerator memory — the output
    format of the fused pipeline executor
    (:mod:`flinkml_tpu.pipeline_fusion`), which keeps intermediate columns
    on device across stage boundaries instead of round-tripping per stage.

The relational ops (``select`` / ``with_column`` / ``drop`` / ``rename``)
are **zero-copy for device-backed columns**: they rebind buffers under new
names without touching the host. ``column(name)`` materializes a
device-backed column to numpy **lazily** (cached after the first fetch);
``device_column(name)`` hands back the device buffer with no host copy
(uploading a host column on first use, also cached). Row-indexed ops
(``take`` / ``slice`` / ``concat`` / ``to_rows``) operate on the host
representation.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Mapping, Optional

import numpy as np


def _is_device_array(x: Any) -> bool:
    """True for a jax.Array (without importing jax when it can't be one)."""
    if isinstance(x, np.ndarray) or x is None:
        return False
    mod = type(x).__module__
    if not (mod == "jax" or mod.startswith("jax.") or mod.startswith("jaxlib")):
        return False
    import jax

    return isinstance(x, jax.Array)


class PaddedDeviceColumn:
    """A device-resident column whose backing buffer carries extra padding
    rows beyond the column's logical row count.

    The fused pipeline executor (:mod:`flinkml_tpu.pipeline_fusion`)
    computes on row-bucket-padded buffers; wrapping its outputs instead of
    slicing them keeps result construction free of device work — the
    prefix slice happens lazily at access time (and on the CPU backend a
    host read is a zero-copy view). Rows past ``rows`` are unspecified
    (bucket-padding garbage); every consumer must go through
    :meth:`Table.column` / :meth:`Table.device_column`, which slice.
    """

    __slots__ = ("buf", "rows")

    def __init__(self, buf, rows: int):
        if buf.shape[0] < rows:
            raise ValueError(
                f"padded buffer has {buf.shape[0]} rows < logical {rows}"
            )
        self.buf = buf
        self.rows = int(rows)

    @property
    def shape(self):
        return (self.rows,) + tuple(self.buf.shape[1:])

    @property
    def ndim(self) -> int:
        return self.buf.ndim

    @property
    def dtype(self):
        return self.buf.dtype

    def to_host(self) -> np.ndarray:
        """The logical rows as a host numpy array (one device→host
        transfer; :meth:`Table.column` caches the result per table)."""
        return np.asarray(self.buf)[: self.rows]


class LazyDeviceColumn(PaddedDeviceColumn):
    """A :class:`PaddedDeviceColumn` whose buffer is not computed yet.

    The fused pipeline executor materializes only a run's *terminal*
    columns eagerly; intermediates consumed inside the run are wrapped in
    this class with a thunk that, on first access, executes a
    dead-code-eliminated program computing just that column. Shape and
    dtype are known statically (from an abstract trace), so table
    construction and relational ops never trigger the compute.
    """

    __slots__ = ("_thunk", "_buf", "_padded_shape", "_dtype")

    def __init__(self, thunk, rows: int, padded_shape, dtype):
        if padded_shape[0] < rows:
            raise ValueError(
                f"padded buffer has {padded_shape[0]} rows < logical {rows}"
            )
        self._thunk = thunk
        self._buf = None
        self._padded_shape = tuple(padded_shape)
        self._dtype = dtype
        self.rows = int(rows)

    @property
    def buf(self):
        if self._buf is None:
            self._buf = self._thunk()
            self._thunk = None
        elif getattr(self._buf, "is_deleted", None) is not None \
                and self._buf.is_deleted():
            # A materialized buffer later donated/freed must fail loudly,
            # not hand jax's cryptic deleted-array error (or stale data)
            # to whoever touches the column next.
            raise RuntimeError(
                "lazy device column buffer has been donated or freed "
                "after materialization; re-run the producing transform "
                "to recompute it"
            )
        return self._buf

    @property
    def shape(self):
        return (self.rows,) + self._padded_shape[1:]

    @property
    def ndim(self) -> int:
        return len(self._padded_shape)

    @property
    def dtype(self):
        return self._dtype


class SortedSparseColumn(PaddedDeviceColumn):
    """A device-resident SPARSE column in the pipeline-guaranteed sorted
    layout: CSR-style ``indptr`` over padded-ELL ``indices``/``values``
    blocks (zero-padded to the fused executor's power-of-two row bucket,
    exactly like every dense :class:`PaddedDeviceColumn`), plus the
    pack-time global sort tables that make the gradient scatter's
    ``indices_are_sorted=True`` fast path FREE at step time:

    - ``buf``          — ``[bucket, width]`` float values (the inherited
      padded buffer; ``width`` is quantized to a power of two so batch
      nnz jitter inside a bucket causes zero retraces),
    - ``indices``      — ``[bucket, width]`` int32 column ids, per-row
      ascending (``SparseVector`` construction guarantees it); padding
      cells carry index 0 / value 0 (the ELL no-op convention),
    - ``indptr``       — ``[bucket + 1]`` int32 CSR row pointers over
      the LOGICAL nnz (padding rows contribute 0),
    - ``perm`` / ``segment_ids`` — ``[bucket * width]`` int32: a stable
      argsort of the flat index block, computed ONCE on the prefetch
      worker thread. A consumer's scatter is
      ``segment_sum(take(contrib, perm), segment_ids,
      indices_are_sorted=True)`` with no runtime sort.

    ``indices_are_sorted`` is recorded on the column — downstream
    kernels assert the guarantee from provenance instead of trusting a
    caller flag (the FML404 contract). Who sorts: the packer (pack
    time, worker thread). Who asserts: the consumer, by reading this
    attribute. Padding semantics: padded cells sort to the front as
    segment 0 / value 0 no-op adds, so the tables cover the FULL padded
    block and are batch-size independent.
    """

    __slots__ = ("indices", "indptr", "perm", "segment_ids", "dim",
                 "indices_are_sorted", "_host_rows")

    def __init__(self, values, indices, indptr, perm, segment_ids,
                 dim: int, rows: int, host_rows=None):
        super().__init__(values, rows)
        if tuple(indices.shape) != tuple(values.shape):
            raise ValueError(
                f"indices shape {tuple(indices.shape)} != values shape "
                f"{tuple(values.shape)}"
            )
        bucket, width = values.shape
        if indptr.shape != (bucket + 1,):
            raise ValueError(
                f"indptr shape {tuple(indptr.shape)} != ({bucket + 1},)"
            )
        if perm.shape != (bucket * width,) or \
                segment_ids.shape != (bucket * width,):
            raise ValueError(
                "perm/segment_ids must be flat [bucket * width] tables"
            )
        self.indices = indices
        self.indptr = indptr
        self.perm = perm
        self.segment_ids = segment_ids
        self.dim = int(dim)
        self.indices_are_sorted = True
        self._host_rows = host_rows

    def to_host(self) -> np.ndarray:
        """The logical rows as the object array of ``SparseVector``s the
        column was packed from (kept by the packer; reconstructed from
        the CSR buffers when the column was built device-side)."""
        if self._host_rows is not None:
            return self._host_rows
        from flinkml_tpu.linalg import SparseVector

        vals = np.asarray(self.buf)
        idx = np.asarray(self.indices)
        ptr = np.asarray(self.indptr)
        out = np.empty(self.rows, dtype=object)
        for r in range(self.rows):
            k = int(ptr[r + 1] - ptr[r])
            # Columns built without a true per-row nnz count every ELL
            # cell, so index-0 padding duplicates — fold duplicates by
            # sum (the no-op padding convention makes that exact).
            ui, inv = np.unique(idx[r, :k], return_inverse=True)
            uv = np.zeros(ui.size, dtype=np.float64)
            np.add.at(uv, inv, vals[r, :k].astype(np.float64))
            out[r] = SparseVector._from_sorted(
                self.dim, ui.astype(np.int64), uv
            )
        self._host_rows = out
        return out


def _is_device_backed(x: Any) -> bool:
    return _is_device_array(x) or isinstance(x, PaddedDeviceColumn)


def _materialization_metrics():
    """The table metric group (lazy import: metrics pulls in the iteration
    runtime, which must not become a hard dependency of the data plane)."""
    from flinkml_tpu.utils.metrics import metrics

    return metrics.group("table")


class Table:
    """Immutable named-column container backed by host numpy arrays and/or
    device-resident ``jax.Array`` columns.

    All columns share the same leading dimension (row count). Columns may be:
      - 1-D arrays (scalar columns: labels, weights, categories),
      - N-D arrays (vector/matrix columns: features ``[rows, dim]``),
      - object arrays (ragged data, e.g. sparse vectors before densify),
      - ``jax.Array`` buffers (device-resident columns; see module docstring).
    """

    def __init__(self, columns: Mapping[str, Any]):
        if not columns:
            raise ValueError("Table requires at least one column")
        conv: Dict[str, Any] = {}
        n_rows: Optional[int] = None
        for name, col in columns.items():
            if isinstance(col, np.ndarray) or _is_device_backed(col):
                arr = col
            else:
                arr = _to_array(col)
            if arr.ndim == 0:
                # Scalar columns become single-row columns so every column
                # supports row slicing uniformly.
                arr = arr.reshape(1)
            if n_rows is None:
                n_rows = arr.shape[0]
            elif arr.shape[0] != n_rows:
                raise ValueError(
                    f"Column {name!r} has {arr.shape[0]} rows, expected {n_rows}"
                )
            conv[name] = arr
        self._columns = conv
        self._num_rows = int(n_rows or 0)
        # Lazy per-home caches: a device column fetched to host (or a host
        # column uploaded to device) is converted at most once per Table.
        self._host_cache: Dict[str, np.ndarray] = {}
        self._device_cache: Dict[str, Any] = {}

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_columns(**columns: Any) -> "Table":
        return Table(columns)

    @staticmethod
    def from_rows(rows: Iterable[Mapping[str, Any]]) -> "Table":
        rows = list(rows)
        if not rows:
            raise ValueError("Table.from_rows requires at least one row")
        names = list(rows[0].keys())
        return Table({n: _to_array([r[n] for r in rows]) for n in names})

    # -- schema ------------------------------------------------------------
    @property
    def column_names(self) -> List[str]:
        return list(self._columns.keys())

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def _raw_column(self, name: str) -> Any:
        if name not in self._columns:
            raise KeyError(
                f"Column {name!r} not in table (has {self.column_names})"
            )
        return self._columns[name]

    def is_device_resident(self, name: str) -> bool:
        """True when the column's backing buffer lives in device memory."""
        return _is_device_backed(self._raw_column(name))

    def column(self, name: str) -> np.ndarray:
        """The column as a host numpy array.

        Device-backed columns materialize lazily HERE (one device→host
        transfer, cached); until this call they cost no host bandwidth.
        """
        col = self._raw_column(name)
        if not _is_device_backed(col):
            return col
        if name not in self._host_cache:
            if isinstance(col, PaddedDeviceColumn):
                host = col.to_host()
            else:
                host = np.asarray(col)
            group = _materialization_metrics()
            group.counter("device_to_host_materializations")
            group.counter("device_to_host_bytes", float(host.nbytes))
            self._host_cache[name] = host
        return self._host_cache[name]

    __getitem__ = column

    def device_column(self, name: str):
        """The column as a device-resident ``jax.Array`` — no host copy for
        device-backed columns; host columns upload on first use (cached).

        Object (ragged) columns have no device representation and raise.
        """
        col = self._raw_column(name)
        if _is_device_array(col):
            return col
        if isinstance(col, PaddedDeviceColumn):
            if name not in self._device_cache:
                self._device_cache[name] = col.buf[: col.rows]
            return self._device_cache[name]
        if col.dtype == object:
            raise TypeError(
                f"Column {name!r} is an object (ragged) column; it has no "
                "device representation"
            )
        if name not in self._device_cache:
            import jax
            import jax.numpy as jnp

            # Uploads preserve the host dtype exactly (a float64 column
            # stays float64 even when the ambient x64 flag is off): the
            # fused executor's bit-parity contract depends on the device
            # copy being the same bits as the host column.
            with jax.experimental.enable_x64(True):
                self._device_cache[name] = jnp.asarray(col)
        return self._device_cache[name]

    def has_device_copy(self, name: str) -> bool:
        """True when :meth:`device_column` would cost no host→device copy
        (the column is device-backed, or its upload is already cached)."""
        return _is_device_backed(self._raw_column(name)) or name in self._device_cache

    def device_column_padded(self, name: str, rows: int):
        """:meth:`device_column` zero-padded on device to ``rows`` rows,
        cached per ``(column, rows)`` — the fused pipeline executor's
        ingest path. Tables are immutable, so repeated ``transform`` calls
        over the same table reuse the padded buffer with zero host work.
        """
        key = (name, int(rows))
        if key not in self._device_cache:
            raw = self._raw_column(name)
            if isinstance(raw, PaddedDeviceColumn) and raw.buf.shape[0] == rows:
                # A fused-executor output re-entering a fused run at the
                # same bucket: hand the padded buffer straight through
                # (rows past the logical count are unspecified either way;
                # kernels see only what the validity mask admits).
                self._device_cache[key] = raw.buf
            elif (isinstance(raw, np.ndarray) and raw.dtype != object
                    and name not in self._device_cache
                    and int(rows) > raw.shape[0]):
                # Host-resident source: pad on HOST (one memcpy) and
                # upload the padded buffer — a pure transfer. The old
                # device-side jnp.concatenate pad compiled one XLA
                # program PER (rows, pad) shape pair; a serving replica
                # flushing partial batches of arbitrary sizes (the
                # underloaded-pool shape) hit a fresh ~50 ms compile on
                # almost every dispatch, collapsing multi-replica
                # throughput. Bit-identical to the device pad: zeros are
                # zeros.
                import jax
                import jax.numpy as jnp

                buf = np.zeros((int(rows),) + raw.shape[1:], raw.dtype)
                buf[:raw.shape[0]] = raw
                with jax.experimental.enable_x64(True):
                    self._device_cache[key] = jnp.asarray(buf)
            else:
                import jax
                import jax.numpy as jnp

                arr = self.device_column(name)
                pad = int(rows) - arr.shape[0]
                if pad > 0:
                    with jax.experimental.enable_x64(True):
                        arr = jnp.concatenate(
                            [arr, jnp.zeros((pad,) + arr.shape[1:], arr.dtype)]
                        )
                self._device_cache[key] = arr
        return self._device_cache[key]

    # -- relational ops ----------------------------------------------------
    # Zero-copy on device-backed columns: buffers are rebound, never fetched.
    def select(self, *names: str) -> "Table":
        return Table({n: self._raw_column(n) for n in names})

    def with_column(self, name: str, values: Any) -> "Table":
        cols = dict(self._columns)
        if isinstance(values, np.ndarray) or _is_device_backed(values):
            cols[name] = values
        else:
            cols[name] = _to_array(values)
        return Table(cols)

    def drop(self, *names: str) -> "Table":
        cols = {n: c for n, c in self._columns.items() if n not in names}
        return Table(cols)

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        return Table({mapping.get(n, n): c for n, c in self._columns.items()})

    # Row-indexed ops operate on the host representation.
    def take(self, indices: np.ndarray) -> "Table":
        return Table({n: self.column(n)[indices] for n in self._columns})

    def slice(self, start: int, stop: int) -> "Table":
        return Table({n: self.column(n)[start:stop] for n in self._columns})

    def concat(self, other: "Table") -> "Table":
        if set(self.column_names) != set(other.column_names):
            raise ValueError("concat requires identical column sets")
        return Table(
            {n: np.concatenate([self.column(n), other.column(n)]) for n in self.column_names}
        )

    # -- iteration ---------------------------------------------------------
    def batches(self, batch_size: int, drop_remainder: bool = False) -> Iterator["Table"]:
        """Yield consecutive row slices of at most ``batch_size`` rows."""
        n = self._num_rows
        stop = (n // batch_size) * batch_size if drop_remainder else n
        for start in range(0, stop, batch_size):
            yield self.slice(start, min(start + batch_size, n))

    def to_rows(self) -> List[Dict[str, Any]]:
        return [
            {n: self.column(n)[i] for n in self._columns} for i in range(self._num_rows)
        ]

    def __repr__(self) -> str:  # pragma: no cover
        cols = ", ".join(
            f"{n}:{c.dtype}{list(c.shape[1:])}{'@device' if _is_device_backed(c) else ''}"
            for n, c in self._columns.items()
        )
        return f"Table[{self._num_rows} rows; {cols}]"


def _to_array(values: Any) -> np.ndarray:
    """Convert a python sequence to a numpy column, keeping ragged data as object."""
    try:
        arr = np.asarray(values)
        if arr.dtype == object and arr.ndim == 0:
            arr = np.asarray([values])
    except ValueError:
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    if arr.dtype == object:
        # Ragged rows (e.g. variable-length lists / sparse vectors).
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        return out
    return arr
