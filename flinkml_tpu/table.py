"""Columnar Table — the data-plane analog of Flink's ``Table``.

The reference moves data as row streams (``Table`` ↔ ``DataStream<Row>``,
e.g. ``LogisticRegression.java:111-130`` maps rows to POJOs one at a time).
On TPU, per-record processing wastes the MXU; the native representation is a
batched columnar store: each column is a host numpy array with leading axis =
rows (feature columns are 2-D ``[rows, dim]``), shipped to device HBM as
batches via ``jax.device_put``. This single type replaces the reference's
Table conversions and record-at-a-time operators.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Mapping, Optional

import numpy as np


class Table:
    """Immutable named-column container backed by host numpy arrays.

    All columns share the same leading dimension (row count). Columns may be:
      - 1-D arrays (scalar columns: labels, weights, categories),
      - N-D arrays (vector/matrix columns: features ``[rows, dim]``),
      - object arrays (ragged data, e.g. sparse vectors before densify).
    """

    def __init__(self, columns: Mapping[str, Any]):
        if not columns:
            raise ValueError("Table requires at least one column")
        conv: Dict[str, np.ndarray] = {}
        n_rows: Optional[int] = None
        for name, col in columns.items():
            arr = col if isinstance(col, np.ndarray) else _to_array(col)
            if arr.ndim == 0:
                # Scalar columns become single-row columns so every column
                # supports row slicing uniformly.
                arr = arr.reshape(1)
            if n_rows is None:
                n_rows = arr.shape[0]
            elif arr.shape[0] != n_rows:
                raise ValueError(
                    f"Column {name!r} has {arr.shape[0]} rows, expected {n_rows}"
                )
            conv[name] = arr
        self._columns = conv
        self._num_rows = int(n_rows or 0)

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_columns(**columns: Any) -> "Table":
        return Table(columns)

    @staticmethod
    def from_rows(rows: Iterable[Mapping[str, Any]]) -> "Table":
        rows = list(rows)
        if not rows:
            raise ValueError("Table.from_rows requires at least one row")
        names = list(rows[0].keys())
        return Table({n: _to_array([r[n] for r in rows]) for n in names})

    # -- schema ------------------------------------------------------------
    @property
    def column_names(self) -> List[str]:
        return list(self._columns.keys())

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def column(self, name: str) -> np.ndarray:
        if name not in self._columns:
            raise KeyError(
                f"Column {name!r} not in table (has {self.column_names})"
            )
        return self._columns[name]

    __getitem__ = column

    # -- relational ops ----------------------------------------------------
    def select(self, *names: str) -> "Table":
        return Table({n: self.column(n) for n in names})

    def with_column(self, name: str, values: Any) -> "Table":
        cols = dict(self._columns)
        cols[name] = _to_array(values) if not isinstance(values, np.ndarray) else values
        return Table(cols)

    def drop(self, *names: str) -> "Table":
        cols = {n: c for n, c in self._columns.items() if n not in names}
        return Table(cols)

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        return Table({mapping.get(n, n): c for n, c in self._columns.items()})

    def take(self, indices: np.ndarray) -> "Table":
        return Table({n: c[indices] for n, c in self._columns.items()})

    def slice(self, start: int, stop: int) -> "Table":
        return Table({n: c[start:stop] for n, c in self._columns.items()})

    def concat(self, other: "Table") -> "Table":
        if set(self.column_names) != set(other.column_names):
            raise ValueError("concat requires identical column sets")
        return Table(
            {n: np.concatenate([self._columns[n], other.column(n)]) for n in self.column_names}
        )

    # -- iteration ---------------------------------------------------------
    def batches(self, batch_size: int, drop_remainder: bool = False) -> Iterator["Table"]:
        """Yield consecutive row slices of at most ``batch_size`` rows."""
        n = self._num_rows
        stop = (n // batch_size) * batch_size if drop_remainder else n
        for start in range(0, stop, batch_size):
            yield self.slice(start, min(start + batch_size, n))

    def to_rows(self) -> List[Dict[str, Any]]:
        return [
            {n: c[i] for n, c in self._columns.items()} for i in range(self._num_rows)
        ]

    def __repr__(self) -> str:  # pragma: no cover
        cols = ", ".join(
            f"{n}:{c.dtype}{list(c.shape[1:])}" for n, c in self._columns.items()
        )
        return f"Table[{self._num_rows} rows; {cols}]"


def _to_array(values: Any) -> np.ndarray:
    """Convert a python sequence to a numpy column, keeping ragged data as object."""
    try:
        arr = np.asarray(values)
        if arr.dtype == object and arr.ndim == 0:
            arr = np.asarray([values])
    except ValueError:
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    if arr.dtype == object:
        # Ragged rows (e.g. variable-length lists / sparse vectors).
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        return out
    return arr
