"""Tensor and pipeline parallelism over named mesh axes.

SURVEY.md §2.5: the reference has no TP/PP (its models are GLMs with one
``double[]`` of state), but the mesh substrate must expose the axes so
model sharding layers on. These are those layers, in the standard TPU
formulation — shardings + compiler-inserted or explicit collectives, not
message passing:

  - **Column-parallel linear** (Megatron fan-out): weights ``[d_in,
    d_out]`` sharded on d_out; activations replicated in; outputs sharded.
    No communication in the forward pass.
  - **Row-parallel linear** (fan-in): weights sharded on d_in; activations
    sharded in; one ``psum`` over the model axis produces replicated
    outputs. Composing column→row gives the classic 2-collective MLP
    block.
  - **Pipeline stages**: layer params stacked on the pipeline axis, each
    device applies its stage and ``ppermute``s activations to the next —
    a GPipe-style microbatch loop with ICI neighbor hops.

All primitives work on any mesh whose axis names include the given one,
so they compose with the data axis (e.g. ``{"data": 2, "model": 4}``).
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional

import flinkml_tpu._jax_compat  # noqa: F401  (jax version shims; install before first jax use)
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flinkml_tpu.parallel.mesh import DeviceMesh


def _axis_check(dm: DeviceMesh, axis: str) -> int:
    if axis not in dm.axis_names:
        raise ValueError(
            f"mesh has axes {dm.axis_names}, no axis named {axis!r}"
        )
    return dm.axis_size(axis)


@functools.lru_cache(maxsize=64)
def _mlp_fn(mesh, axis: str, activation_name: str):
    activation = getattr(jax.nn, activation_name)

    def local(x, w1, b1, w2, b2):
        # Column-parallel: local [d, d_ff/P] slice — no comm.
        h = activation(x @ w1 + b1)
        # Row-parallel: local partial product, then one psum.
        return jax.lax.psum(h @ w2, axis) + b2

    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(),            # x replicated over the model axis
                P(None, axis),  # w1 [d_in, d_ff] sharded on d_ff
                P(axis),        # b1 [d_ff]
                P(axis, None),  # w2 [d_ff, d_out] sharded on d_ff
                P(),            # b2 [d_out] replicated
            ),
            out_specs=P(),
        )
    )


def tensor_parallel_mlp(x, w1, b1, w2, b2, mesh: Optional[DeviceMesh] = None,
                        axis: str = "model", activation: str = "gelu"):
    """The canonical TP block: column-parallel ``w1`` + activation +
    row-parallel ``w2`` with a single ``psum``.

    Shapes: ``x [.., d_in]``, ``w1 [d_in, d_ff]``, ``b1 [d_ff]``,
    ``w2 [d_ff, d_out]``, ``b2 [d_out]``; ``d_ff`` must divide by the
    size of ``axis``. Output replicated over ``axis``.
    """
    dm = mesh if mesh is not None else DeviceMesh({"model": len(jax.devices())})
    p_size = _axis_check(dm, axis)
    d_ff = w1.shape[1]
    if d_ff % p_size != 0:
        raise ValueError(f"d_ff {d_ff} must divide by axis size {p_size}")
    if w2.shape[0] != d_ff or b1.shape[0] != d_ff:
        raise ValueError("w1/b1/w2 d_ff dimensions disagree")
    fn = _mlp_fn(dm.mesh, axis, activation)
    return fn(jnp.asarray(x), jnp.asarray(w1), jnp.asarray(b1),
              jnp.asarray(w2), jnp.asarray(b2))


@functools.lru_cache(maxsize=64)
def _pipeline_fn(mesh, axis: str, stage: Callable, n_microbatches: int):
    # Cache key includes the stage FUNCTION, so re-registering a name with
    # a new function compiles fresh instead of silently reusing the old one.

    def local(x_mb, params):
        """x_mb: [n_microbatches, ...] (replicated); params: [1, ...] —
        this device's stage slice of the stage-sharded stack. GPipe
        schedule: at step t, device s processes microbatch (t - s);
        activations ppermute forward one hop per step."""
        params = params[0]  # drop the sharded stage dim (1 per device)
        p_size = jax.lax.axis_size(axis)
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        n_steps = n_microbatches + p_size - 1

        def body(t, carry):
            acts, outputs = carry
            # Device s works on the microbatch that entered at t - s.
            mb = t - jnp.asarray(idx, jnp.int32)
            active = (mb >= 0) & (mb < n_microbatches)
            processed = stage(acts, params)
            acts_new = jnp.where(active, processed, acts)
            # Last stage banks its finished microbatch.
            is_last = idx == p_size - 1
            bank = jnp.clip(mb, 0, n_microbatches - 1)
            outputs = jnp.where(
                active & is_last,
                outputs.at[bank].set(acts_new),
                outputs,
            )
            # Rotate activations to the next stage; stage 0 loads the next
            # incoming microbatch instead of the wrap-around payload.
            rotated = jax.lax.ppermute(acts_new, axis, perm)
            nxt = jnp.clip(t + 1, 0, n_microbatches - 1)
            acts = jnp.where(
                (idx == 0) & (t + 1 < n_microbatches), x_mb[nxt], rotated
            )
            return acts, outputs

        # pcast-to-varying: inputs are replicated but the carry becomes
        # device-varying after the first rotation.
        init_acts = jax.lax.pcast(x_mb[0], (axis,), to="varying")
        outputs = jax.lax.pcast(
            jnp.zeros((n_microbatches,) + x_mb[0].shape, dtype=x_mb.dtype),
            (axis,), to="varying",
        )
        _, outputs = jax.lax.fori_loop(0, n_steps, body, (init_acts, outputs))
        # Only the last stage banked real outputs; psum-mask replicates them.
        last = p_size - 1
        return jax.lax.psum(
            jnp.where(jax.lax.axis_index(axis) == last, outputs, 0.0), axis
        )

    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(axis)),   # microbatches replicated; params staged
            out_specs=P(),
        )
    )


# Pipeline stages must be named (hashable for the jit cache) pure fns
# (acts, params) -> acts.
_STAGE_REGISTRY: dict = {}


def register_pipeline_stage(name: str, fn: Callable) -> None:
    """Register a stage function ``(acts, params) -> acts`` for
    :func:`pipeline_parallel_apply`."""
    _STAGE_REGISTRY[name] = fn


register_pipeline_stage(
    "linear_tanh", lambda a, p: jnp.tanh(a @ p)
)


@functools.lru_cache(maxsize=64)
def _expert_fn(mesh, axis: str, activation_name: str):
    activation = getattr(jax.nn, activation_name)

    def local(x, gates, w1, w2):
        # One expert slice per device ([1, ...] of the expert-stacked
        # weights); dense dispatch: every device evaluates its expert on
        # all tokens, the gate mask + psum combine (exact MoE; the
        # all-to-all capacity-routed variant is an optimization on top).
        e = jax.lax.axis_index(axis)
        h = activation(x @ w1[0])
        y = h @ w2[0]
        return jax.lax.psum(gates[:, e][:, None] * y, axis)

    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(), P(axis), P(axis)),
            out_specs=P(),
        )
    )


def expert_parallel_ffn(x, gates, w1, w2, mesh: Optional[DeviceMesh] = None,
                        axis: str = "expert", activation: str = "gelu"):
    """Expert-parallel mixture-of-experts FFN: expert e's weights live on
    device e of ``axis``; outputs are the gate-weighted sum of expert
    outputs (one ``psum``).

    Shapes: ``x [n, d_in]``, ``gates [n, E]`` (rows of mixture weights,
    e.g. a softmax or a one-hot top-1), ``w1 [E, d_in, d_ff]``,
    ``w2 [E, d_ff, d_out]``; ``E`` must equal the size of ``axis``.
    """
    dm = mesh if mesh is not None else DeviceMesh({"expert": len(jax.devices())})
    p_size = _axis_check(dm, axis)
    e = w1.shape[0]
    if e != p_size or w2.shape[0] != e or gates.shape[1] != e:
        raise ValueError(
            f"expert count mismatch: w1 {w1.shape[0]}, w2 {w2.shape[0]}, "
            f"gates {gates.shape[1]}, axis size {p_size}"
        )
    fn = _expert_fn(dm.mesh, axis, activation)
    return fn(jnp.asarray(x), jnp.asarray(gates), jnp.asarray(w1),
              jnp.asarray(w2))


@functools.lru_cache(maxsize=64)
def _routed_expert_fn(mesh, axis: str, capacity: int, activation_name: str):
    activation = getattr(jax.nn, activation_name)

    def local(xl, logits_l, w1, w2):
        """Switch-style top-1 routed MoE. xl [n_loc, d] token-sharded;
        logits_l [n_loc, E]; w1/w2 [1, ...] — this device's expert."""
        n_loc, d = xl.shape
        e_count = logits_l.shape[1]
        probs = jax.nn.softmax(logits_l, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                 # [n]
        gate = jnp.max(probs, axis=-1)                      # [n]
        onehot = jax.nn.one_hot(expert, e_count, dtype=xl.dtype)  # [n, E]
        # 0-based rank of each token within its expert's send buffer;
        # tokens beyond capacity are dropped (their combine weight is 0).
        # Rank bookkeeping runs in int32 regardless of the data dtype —
        # a bf16 cumsum cannot count past 256 and would silently collide
        # buffer slots.
        onehot_i = jax.nn.one_hot(expert, e_count, dtype=jnp.int32)
        ranks = jnp.cumsum(onehot_i, axis=0) * onehot_i      # [n, E]: rank+1
        pos_tok = jnp.sum(ranks, axis=1) - 1                 # [n]
        keep_tok = pos_tok < capacity
        # one_hot(-1) is all-zeros, which zeroes dropped tokens out of the
        # dispatch AND the combine.
        poshot = jax.nn.one_hot(
            jnp.where(keep_tok, pos_tok, -1), capacity, dtype=xl.dtype
        )                                                    # [n, C]
        mask = onehot[:, :, None] * poshot[:, None, :]       # [n, E, C]
        dispatch = jnp.einsum("nec,nd->ecd", mask, xl)       # [E, C, d]
        # Exchange: device p receives every peer's buffer for expert p.
        recv = jax.lax.all_to_all(
            dispatch, axis, split_axis=0, concat_axis=0, tiled=True
        )                                                    # [P, C, d]
        h = activation(recv.reshape(-1, d) @ w1[0])
        y = (h @ w2[0]).reshape(recv.shape[0], capacity, -1)
        back = jax.lax.all_to_all(
            y, axis, split_axis=0, concat_axis=0, tiled=True
        )                                                    # [E, C, d_out]
        combined = jnp.einsum("nec,ecd->nd", mask, back)
        return combined * gate[:, None]

    return jax.jit(
        jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=P(axis),
        )
    )


def routed_expert_ffn(x, router_logits, w1, w2,
                      mesh: Optional[DeviceMesh] = None,
                      axis: str = "expert", capacity_factor: float = 1.25,
                      activation: str = "gelu"):
    """Top-1 routed expert-parallel MoE (Switch-style): tokens are
    dispatched to their expert's device over one ``all_to_all``, processed
    there, and returned by a second ``all_to_all`` — communication scales
    with tokens actually routed, not tokens × experts.

    Shapes: ``x [n, d_in]`` (token-sharded over ``axis``),
    ``router_logits [n, E]``, ``w1 [E, d_in, d_ff]``, ``w2 [E, d_ff,
    d_out]``; ``E`` must equal the axis size and ``n`` divide by it.
    Per-device-per-expert capacity = ``ceil(n_local / E *
    capacity_factor)``; over-capacity tokens are dropped (zero output),
    the standard Switch behavior.
    """
    dm = mesh if mesh is not None else DeviceMesh({"expert": len(jax.devices())})
    p_size = _axis_check(dm, axis)
    n, e_count = router_logits.shape[0], router_logits.shape[1]
    if e_count != p_size or w1.shape[0] != e_count or w2.shape[0] != e_count:
        raise ValueError(
            f"expert count mismatch: logits {e_count}, w1 {w1.shape[0]}, "
            f"w2 {w2.shape[0]}, axis size {p_size}"
        )
    if n % p_size != 0 or x.shape[0] != n:
        raise ValueError(
            f"token count {n} must match x rows {x.shape[0]} and divide by "
            f"the mesh size {p_size}"
        )
    n_local = n // p_size
    capacity = max(1, math.ceil(n_local * capacity_factor / p_size))
    fn = _routed_expert_fn(dm.mesh, axis, capacity, activation)
    return fn(jnp.asarray(x), jnp.asarray(router_logits), jnp.asarray(w1),
              jnp.asarray(w2))


def pipeline_parallel_apply(x_microbatches, stage_params, stage: str,
                            mesh: Optional[DeviceMesh] = None,
                            axis: str = "pipe"):
    """GPipe-style pipeline over ``axis``: device s applies stage s.

    Args:
        x_microbatches: ``[n_microbatches, ...]`` inputs (replicated).
        stage_params: ``[n_stages, ...]`` per-stage params, sharded on
            ``axis`` (n_stages must equal the axis size).
        stage: name registered via :func:`register_pipeline_stage`.
    Returns:
        ``[n_microbatches, ...]`` outputs after all stages, replicated.
    """
    dm = mesh if mesh is not None else DeviceMesh({"pipe": len(jax.devices())})
    p_size = _axis_check(dm, axis)
    if stage_params.shape[0] != p_size:
        raise ValueError(
            f"stage_params has {stage_params.shape[0]} stages but axis "
            f"{axis!r} has {p_size} devices"
        )
    if stage not in _STAGE_REGISTRY:
        raise ValueError(f"unknown pipeline stage {stage!r}")
    n_mb = int(x_microbatches.shape[0])
    fn = _pipeline_fn(dm.mesh, axis, _STAGE_REGISTRY[stage], n_mb)
    return fn(jnp.asarray(x_microbatches), jnp.asarray(stage_params))
