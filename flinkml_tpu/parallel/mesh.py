"""Device mesh and sharding helpers — the parallelism substrate.

The reference's parallelism is Flink operator parallelism: P subtasks over
partitioned streams, wired by Netty shuffles (SURVEY.md §2.5). Here the
substrate is a named ``jax.sharding.Mesh``: data parallelism is a sharded
leading batch axis, model replication is a replicated sharding, and every
cross-device exchange is an XLA collective over ICI inserted by the compiler
or written explicitly in ``flinkml_tpu.parallel.collectives``.

The default mesh is 1-D over all local devices with axis ``"data"``; multi-
axis meshes (e.g. ``{"data": 4, "model": 2}``) are supported so model/expert
sharding can be layered on without changing this substrate.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import flinkml_tpu._jax_compat  # noqa: F401  (jax version shims; install before first jax use)
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class DeviceMesh:
    """A named device mesh plus sharding conveniences.

    Replaces (SURVEY.md §2.5): Flink operator parallelism (data axis),
    ``.broadcast()`` partitioners + per-TM ``BroadcastContext`` (replicated
    sharding), and co-location constraints (meaningless in SPMD — every
    device runs the same program).
    """

    DATA_AXIS = "data"
    #: Model/optimizer state sharding axis (FSDP/ZeRO-3) and tensor-
    #: parallel axis — the named axes the ``flinkml_tpu.sharding``
    #: plans key their ``PartitionSpec``s to.
    FSDP_AXIS = "fsdp"
    TP_AXIS = "tp"

    def __init__(
        self,
        axis_shapes: Optional[Dict[str, int]] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        if devices is None:
            devices = jax.devices()
        if axis_shapes is None:
            axis_shapes = {self.DATA_AXIS: len(devices)}
        names = tuple(axis_shapes.keys())
        shape = tuple(axis_shapes.values())
        n = int(np.prod(shape))
        if n > len(devices):
            raise ValueError(
                f"mesh shape {dict(axis_shapes)} needs {n} devices, "
                f"only {len(devices)} available"
            )
        device_array = np.asarray(devices[:n]).reshape(shape)
        self.mesh = Mesh(device_array, names)

    # -- basic properties --------------------------------------------------
    @property
    def axis_names(self) -> Tuple[str, ...]:
        return self.mesh.axis_names

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    def axis_size(self, name: str = DATA_AXIS) -> int:
        return self.mesh.shape[name]

    # -- plan-shaped construction ------------------------------------------
    @classmethod
    def for_plan(cls, plan, devices: Optional[Sequence[jax.Device]] = None,
                 tp_size: Optional[int] = None) -> "DeviceMesh":
        """A mesh shaped for a :class:`~flinkml_tpu.sharding.plan.
        ShardingPlan`'s required axes over the given devices (all local
        devices by default).

        - only ``data`` (or no axes at all): 1-D ``{"data": n}`` — the
          classic substrate, unchanged;
        - ``fsdp`` without ``tp``: ``{"data": 1, "fsdp": n}`` — every
          device serves both batch and state sharding (the plans' batch
          axes are ``("data", "fsdp")``, so batches still split n ways);
        - ``fsdp`` + ``tp``: ``{"data": 1, "fsdp": n // tp, "tp": tp}``
          with ``tp_size`` defaulting to 2 (must divide the device
          count).
        """
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        axes = set(plan.required_axes())
        if cls.TP_AXIS in axes and cls.FSDP_AXIS in axes:
            tp = int(tp_size) if tp_size is not None else min(2, n)
            if n % tp != 0:
                raise ValueError(
                    f"tp_size {tp} does not divide {n} devices"
                )
            return cls({cls.DATA_AXIS: 1, cls.FSDP_AXIS: n // tp,
                        cls.TP_AXIS: tp}, devices=devices)
        if cls.FSDP_AXIS in axes:
            return cls({cls.DATA_AXIS: 1, cls.FSDP_AXIS: n},
                       devices=devices)
        return cls({cls.DATA_AXIS: n}, devices=devices)

    # -- elastic re-shaping ------------------------------------------------
    def shrink(self, new_size: int, axis: str = DATA_AXIS) -> "DeviceMesh":
        """A new mesh over a SUBSET of this mesh's devices: ``axis``
        reduced to ``new_size`` (the leading ``new_size`` slots in mesh
        order — survivors keep their relative order, matching
        :func:`~flinkml_tpu.parallel.distributed.compact_rank`'s dense
        renumbering). The elastic shrink's device-plane half: after the
        survivors re-rendezvous at world M, the training mesh is
        ``old_mesh.shrink(M * local_devices)`` — or simply a fresh
        ``DeviceMesh()`` of the new world's devices."""
        new_size = int(new_size)
        old = self.axis_size(axis)
        if not (1 <= new_size <= old):
            raise ValueError(
                f"cannot shrink axis {axis!r} from {old} to {new_size}"
            )
        shapes = {name: self.mesh.shape[name] for name in self.axis_names}
        shapes[axis] = new_size
        # Move the shrinking axis's index innermost-last so "the leading
        # new_size slots along `axis`" selects device rows in mesh order.
        idx = tuple(
            slice(0, new_size) if name == axis else slice(None)
            for name in self.axis_names
        )
        devices = self.mesh.devices[idx].reshape(-1)
        return DeviceMesh(shapes, devices=list(devices))

    # -- shardings ---------------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def data_sharding(self) -> NamedSharding:
        """Leading axis split across the data axis; trailing axes replicated."""
        return self.sharding(self.DATA_AXIS)

    def replicated_sharding(self) -> NamedSharding:
        return self.sharding()

    # -- placement ---------------------------------------------------------
    def shard_batch(self, array) -> jax.Array:
        """Place a host batch onto the mesh, split along the leading axis.

        The batch's leading dimension must be divisible by the data-axis size
        (use :func:`pad_to_multiple` first when it is not) — mirroring the
        reference's ``globalBatchSize / parallelism`` contract
        (``LogisticRegression.java:334-342``).
        """
        n = self.axis_size(self.DATA_AXIS)
        if array.shape[0] % n != 0:
            raise ValueError(
                f"batch dimension {array.shape[0]} not divisible by data-axis "
                f"size {n}; pad with pad_to_multiple first"
            )
        return jax.device_put(array, self.data_sharding())

    def replicate(self, tree):
        """Replicate a pytree of arrays onto every device (broadcast-model)."""
        sharding = self.replicated_sharding()
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), tree
        )

    def to_host(self, arr) -> np.ndarray:
        """Fetch a device array to host, multi-process-safe.

        Fully-addressable arrays (single-process, or replicated outputs)
        fetch directly. A data-sharded array on a multi-process mesh
        spans non-addressable devices, so it is all-gathered across
        processes first — in that case this is a COLLECTIVE: every
        process must call it, in the same order (the SPMD transform
        convention: all ranks run the same inference over the same
        global table and all receive the full result).
        """
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(arr)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))

    def local_rows(self, arr) -> np.ndarray:
        """Fetch THIS PROCESS's contiguous row block of a data-sharded
        output — the inverse of :meth:`global_batch`.

        For per-row state that lives on the rank owning the rows (GBT's
        node assignments), a full :meth:`to_host` gather would move every
        other rank's rows across DCN just to throw them away; the local
        addressable shards ARE this process's block, in row order.
        Single-process (fully addressable): the whole array.
        """
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(arr)
        shards = sorted(
            arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        return np.concatenate([np.asarray(s.data) for s in shards])

    def global_batch(self, local_rows) -> jax.Array:
        """Assemble a globally-sharded batch from THIS PROCESS's rows.

        The multi-host ingest primitive (the reference's per-subtask
        stream partitions): each host passes only its
        :func:`~flinkml_tpu.parallel.process_slice` of the dataset; the
        returned array is the concatenation of every host's rows, sharded
        over the data axis, without any host materializing the whole
        dataset. Single-process this is exactly :meth:`shard_batch`.

        ``local_rows`` must be divisible by the local device count (every
        process contributes equally per device — pad the *global* dataset
        so every host slice divides evenly).
        """
        local_rows = np.asarray(local_rows)
        if jax.process_count() == 1:
            return self.shard_batch(local_rows)
        return jax.make_array_from_process_local_data(
            self.data_sharding(), local_rows
        )


def pad_to_multiple(array: np.ndarray, multiple: int, axis: int = 0):
    """Zero-pad ``array`` along ``axis`` to a multiple; returns (padded, n_valid).

    Algorithms carry ``n_valid`` (or a weight column) so padded rows never
    contribute to sums — the TPU version of the reference's exact per-task
    record counts.
    """
    n = array.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return array, n
    pad_width = [(0, 0)] * array.ndim
    pad_width[axis] = (0, target - n)
    return np.pad(array, pad_width), n
