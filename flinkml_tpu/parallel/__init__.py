from flinkml_tpu.parallel.mesh import DeviceMesh, pad_to_multiple
from flinkml_tpu.parallel.collectives import (
    all_reduce_sum,
    broadcast,
    keyed_aggregate,
    map_partition,
)
from flinkml_tpu.parallel.broadcast_utils import (
    BroadcastContext,
    get_broadcast_variable,
    with_broadcast,
)
from flinkml_tpu.parallel.dispatch import (
    DispatchGuard,
    default_sync_interval,
    synced_loop,
)
from flinkml_tpu.parallel.distributed import (
    agree_resume_epoch,
    compact_rank,
    host_barrier,
    init_distributed,
    process_slice,
    rescale_world,
)
from flinkml_tpu.parallel.ring import ring_attention, ulysses_attention
from flinkml_tpu.parallel.tensor import (
    expert_parallel_ffn,
    pipeline_parallel_apply,
    register_pipeline_stage,
    routed_expert_ffn,
    tensor_parallel_mlp,
)

__all__ = [
    "DeviceMesh",
    "pad_to_multiple",
    "all_reduce_sum",
    "broadcast",
    "keyed_aggregate",
    "map_partition",
    "BroadcastContext",
    "get_broadcast_variable",
    "with_broadcast",
    "DispatchGuard",
    "default_sync_interval",
    "synced_loop",
    "agree_resume_epoch",
    "compact_rank",
    "host_barrier",
    "init_distributed",
    "process_slice",
    "rescale_world",
    "ring_attention",
    "ulysses_attention",
    "expert_parallel_ffn",
    "pipeline_parallel_apply",
    "register_pipeline_stage",
    "routed_expert_ffn",
    "tensor_parallel_mlp",
]
