from flinkml_tpu.parallel.mesh import DeviceMesh, pad_to_multiple
from flinkml_tpu.parallel.collectives import (
    all_reduce_sum,
    broadcast,
    keyed_aggregate,
    map_partition,
)

__all__ = [
    "DeviceMesh",
    "pad_to_multiple",
    "all_reduce_sum",
    "broadcast",
    "keyed_aggregate",
    "map_partition",
]
