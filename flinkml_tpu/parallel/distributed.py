"""Multi-host runtime: initialization, control-plane barrier, data slicing.

SURVEY.md §5 "Distributed communication backend" maps the reference's three
channels onto TPU pods:

  1. data-plane (Flink's Netty credit-based shuffles between subtasks,
     ``AllReduceImpl.java:79-93``) → XLA collectives over **ICI**, emitted
     by the compiler from shardings (see ``parallel/collectives.py``);
  2. feedback-plane (in-JVM ``FeedbackChannel`` between co-located
     tail/head, ``TailOperator.java:81-88``) → the host loop carry —
     no channel exists;
  3. control-plane (``OperatorEventGateway`` RPC between head subtasks and
     the JobManager-resident ``SharedProgressAligner``,
     ``SharedProgressAligner.java:127-158``) → **this module**: the
     ``jax.distributed`` coordination service over DCN for process startup,
     plus a device-mediated global barrier for the few host-side sync
     points (checkpoint commit, termination agreement).

On a single host everything degrades to no-ops, so the same training
script runs unchanged from a laptop CPU mesh to a multi-host pod slice.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import flinkml_tpu._jax_compat  # noqa: F401  (jax version shims; install before first jax use)
import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.utils import logging as flog

_log = flog.get_logger("distributed")

# Substrings that mark a rendezvous failure as TRANSIENT (worth retrying:
# the coordinator is still coming up, DNS lag, a dropped TCP handshake).
# Anything else — bad address, auth, rank mismatch — fails fast.
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline",
    "timed out",
    "timeout",
    "connection refused",
    "connection reset",
    "failed to connect",
    "connect failed",
    "temporarily",
    "barrier",
)


def _is_transient_rendezvous_error(err: BaseException) -> bool:
    msg = str(err).lower()
    return any(marker in msg for marker in _TRANSIENT_MARKERS)


def retry_backoff_s(attempt: int, backoff_s: float,
                    jitter: float = 0.25,
                    rng: Optional["random.Random"] = None) -> float:
    """The jittered exponential delay before retry ``attempt`` (1-based):
    ``backoff_s * 2**(attempt-1) * (1 + U[0, jitter])``.

    The jitter is the point: N ranks that hit the same transient
    rendezvous failure retry in LOCKSTEP under pure exponential backoff
    — they re-collide at the coordinator on every attempt, indefinitely.
    A per-process uniform draw decorrelates the herd (each process seeds
    from its own entropy), which is the standard
    thundering-herd-breaking shape. Exposed for tests and for other
    retry sites (the recovery engine's policy uses the same shape)."""
    import random

    if backoff_s <= 0:
        return 0.0
    base = backoff_s * (2 ** (max(int(attempt), 1) - 1))
    r = (rng or random).random()
    return base * (1.0 + max(0.0, float(jitter)) * r)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    max_attempts: int = 3,
    backoff_s: float = 1.0,
    backoff_jitter: float = 0.25,
    deadline_s: Optional[float] = None,
) -> Tuple[int, int]:
    """Join the jax.distributed coordination service (DCN control plane).

    Call once per process before any device computation, on every host of
    the pod slice. Arguments default from the environment — first the
    framework's own rendezvous family (``FLINKML_TPU_COORD_ADDR`` /
    ``FLINKML_TPU_WORLD_SIZE`` / ``FLINKML_TPU_RANK``, what
    :mod:`flinkml_tpu.cluster`'s spawned workers and operator-launched
    processes both export, so every launcher shares ONE rendezvous
    path), then the standard ``JAX_COORDINATOR_ADDRESS`` /
    ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` set by most TPU
    launchers; with no coordinator configured this is a single-process
    no-op.

    Transient rendezvous failures (coordinator still booting, dropped
    connections, deadline overruns — the normal churn of a pod slice
    coming up host by host) are retried up to ``max_attempts`` times
    with exponential backoff **plus per-process jitter**
    (:func:`retry_backoff_s` — N ranks retrying in pure-exponential
    lockstep re-collide at the coordinator indefinitely; the jitter
    decorrelates them). ``deadline_s`` caps the TOTAL time spent
    rendezvousing (attempts + sleeps): when the next backoff would
    overrun it, the retry ladder stops and the last failure is raised —
    a pod that cannot form within its startup budget should fail loudly,
    not spin. Non-transient errors (bad address, rank mismatch) still
    fail fast on the first occurrence.

    Returns ``(process_index, process_count)``.
    """
    coordinator_address = (
        coordinator_address
        or os.environ.get("FLINKML_TPU_COORD_ADDR")
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("FLINKML_TPU_WORLD_SIZE")
        or os.environ.get("JAX_NUM_PROCESSES", "1")
    )
    process_id = process_id if process_id is not None else int(
        os.environ.get("FLINKML_TPU_RANK")
        or os.environ.get("JAX_PROCESS_ID", "0")
    )
    # The guard must not touch any backend-initializing API
    # (jax.process_count() et al. would create the XLA backend, after which
    # jax.distributed.initialize() unconditionally raises) — so the decision
    # is made from the arguments/environment plus jax.distributed's own
    # state, which is safe to query before backend init.
    if (
        coordinator_address
        and num_processes > 1
        and not jax.distributed.is_initialized()
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        _enable_cpu_collectives()
        t0 = time.monotonic()
        for attempt in range(1, max_attempts + 1):
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                )
                _log.info(
                    "rendezvous with %s succeeded (attempt %d/%d, "
                    "process %d of %d)", coordinator_address, attempt,
                    max_attempts, process_id, num_processes,
                )
                break
            except Exception as e:  # noqa: BLE001 — classified below
                delay = retry_backoff_s(attempt, backoff_s, backoff_jitter)
                elapsed = time.monotonic() - t0
                overrun = (
                    deadline_s is not None
                    and elapsed + delay > deadline_s
                )
                if (
                    attempt == max_attempts
                    or overrun
                    or not _is_transient_rendezvous_error(e)
                ):
                    _log.error(
                        "rendezvous with %s failed %s (attempt %d/%d, "
                        "%.1fs elapsed): %r",
                        coordinator_address,
                        "permanently" if attempt == max_attempts
                        else ("at the total deadline "
                              f"({deadline_s}s)" if overrun
                              else "fast (non-transient)"),
                        attempt, max_attempts, elapsed, e,
                    )
                    raise
                _log.warning(
                    "transient rendezvous failure with %s (attempt %d/%d), "
                    "retrying in %.2fs (jittered): %r", coordinator_address,
                    attempt, max_attempts, delay, e,
                )
                time.sleep(delay)
    index, count = jax.process_index(), jax.process_count()
    flog.set_rank(index, count)  # pin the log tag to the real rank
    return index, count


def _enable_cpu_collectives() -> None:
    """Select a cross-process collectives backend for multi-process CPU
    meshes (the virtual-pod dev/test path; TPU pods use ICI and never get
    here). XLA:CPU defaults to no collectives implementation and raises
    "Multiprocess computations aren't implemented on the CPU backend" at
    first cross-process dispatch, so pick gloo when this jaxlib ships it.
    Must run before the CPU backend is created; an explicit user setting
    wins."""
    platforms = jax.config.jax_platforms or os.environ.get(
        "JAX_PLATFORMS", ""
    )
    if "cpu" not in str(platforms).split(","):
        return
    current = getattr(jax.config, "jax_cpu_collectives_implementation", None)
    if current not in (None, "none"):
        return
    try:
        from jax._src.lib import xla_client

        if not hasattr(xla_client._xla, "make_gloo_tcp_collectives"):
            return
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover — best effort on exotic builds
        return


def host_barrier(mesh=None, tag: int = 0) -> int:
    """Global barrier across all hosts/devices; returns ``tag``'s psum.

    The SPMD data-plane is implicitly synchronized; this is for the rare
    *host-side* rendezvous (the reference used coordinator RPC +
    ``SharedProgressAligner``): e.g. "all hosts finished writing their
    checkpoint shard" before committing a manifest. Implemented as a tiny
    ``psum`` so it rides the same ICI/DCN fabric as the data plane and
    needs no extra service.

    ``mesh``: a :class:`flinkml_tpu.parallel.DeviceMesh` (defaults to a
    fresh all-devices mesh).
    """
    from flinkml_tpu.parallel.mesh import DeviceMesh

    dm = mesh if mesh is not None else DeviceMesh()
    axis = dm.axis_names[0]

    def _one(x):
        return jax.lax.psum(x, axis)

    # Build the input per-device via callback so each process only touches
    # its addressable devices — a host-local global array would need a
    # device_put onto non-addressable devices on a multi-host pod.
    global_shape = (dm.axis_size(),)
    sharding = jax.sharding.NamedSharding(dm.mesh, P(axis))
    full = np.full(global_shape, tag, dtype=np.int32)
    arr = jax.make_array_from_callback(global_shape, sharding,
                                       lambda idx: full[idx])
    summed = jax.jit(
        jax.shard_map(
            _one, mesh=dm.mesh, in_specs=P(axis), out_specs=P(None)
        )
    )(arr)
    # Host blocks until every participant contributed (output is fully
    # replicated, so every host can read shard 0 locally).
    return int(np.asarray(summed.addressable_shards[0].data)[0])


def agree_resume_epoch(manager, mesh=None, old_world: Optional[int] = None,
                       new_world: Optional[int] = None) -> Optional[int]:
    """The elastic survivors' rendezvous: agree the newest snapshot of
    ``manager`` (a :class:`~flinkml_tpu.iteration.CheckpointManager`)
    that EVERY remaining rank can restore.

    Each rank nominates its local newest verified epoch
    (``manager.newest_valid_epoch()`` — integrity-checked, so a rank
    whose shared-FS view of the latest snapshot is torn nominates the
    one before it); the agreement is then two existing rendezvous
    primitives over the same ICI/DCN fabric as the data plane:

    1. :func:`~flinkml_tpu.iteration.stream_sync.agree_all_ok` — any
       rank with NO valid snapshot at all aborts every rank together
       (resuming the others from epoch k while one starts fresh would
       split-brain the fleet);
    2. :func:`~flinkml_tpu.iteration.stream_sync.agree_min` over the
       nominated epochs — the newest COMMONLY-valid snapshot.

    Fires the ``rendezvous.rescale`` fault seam (with both worlds in
    context) so tests can script a shrink rendezvous that fails.
    Single-process this degrades to the local newest-valid epoch (None
    when the directory holds no valid snapshot — a fresh start).
    """
    import flinkml_tpu.faults as faults

    local = manager.newest_valid_epoch()
    if faults.ACTIVE is not None:  # scripted shrink-rendezvous failure
        faults.fire("rendezvous.rescale",
                    local_epoch=-1 if local is None else int(local),
                    old_world=old_world, new_world=new_world)
    if jax.process_count() == 1:
        _log.info(
            "elastic resume rendezvous (single process): newest valid "
            "epoch %s under %s", local, manager.directory,
        )
        return local
    from flinkml_tpu.iteration.stream_sync import agree_all_ok, agree_min

    agree_all_ok(
        local is not None, mesh,
        f"elastic resume: a valid snapshot under {manager.directory}",
    )
    agreed = agree_min(int(local), mesh)
    # min-of-newest is only COMMONLY valid if every survivor still holds
    # (and can verify) that epoch — a rank whose older snapshots were
    # pruned (max_to_keep) or torn in its shared-FS view would otherwise
    # discover the gap mid-restore and strand the peers in the training
    # collectives: exactly the split-brain the rendezvous exists to
    # prevent. Abort together instead.
    agree_all_ok(
        agreed == local or manager.verify(agreed), mesh,
        f"elastic resume: agreed snapshot epoch {agreed} restorable on "
        "every survivor",
    )
    _log.info(
        "elastic resume rendezvous: local newest valid epoch %s, agreed "
        "epoch %s (world %s -> %s)", local, agreed, old_world, new_world,
    )
    return agreed


def compact_rank(old_rank: int, lost_ranks) -> Optional[int]:
    """A survivor's process id in the shrunken world: its position among
    the surviving old ranks (dense, order-preserving — old rank 3 with
    rank 1 lost becomes new rank 2). None when ``old_rank`` is itself
    lost. This is the id a survivor passes to :func:`rescale_world`."""
    lost = set(int(r) for r in lost_ranks)
    old_rank = int(old_rank)
    if old_rank in lost:
        return None
    return old_rank - sum(1 for r in lost if r < old_rank)


def rescale_world(new_world: int, new_rank: int,
                  coordinator_address: Optional[str] = None,
                  **init_kwargs) -> Tuple[int, int]:
    """Re-join the coordination service at a NEW world size — the
    control-plane half of an elastic shrink/grow: tear down the old
    ``jax.distributed`` membership (if any) and rendezvous again as
    process ``new_rank`` of ``new_world`` (survivor ranks compacted via
    :func:`compact_rank`). Single-host (no coordinator configured, world
    1) this is a no-op returning ``(0, 1)`` — the CPU test path.

    The data-plane re-layout is NOT here: restore the carry through a
    ``rescale="reshard"`` manager and re-split the feed via its cursor
    (see ``docs/development/fault_tolerance.md``, "Elastic resume").
    """
    new_world, new_rank = int(new_world), int(new_rank)
    if new_world < 1 or not (0 <= new_rank < new_world):
        raise ValueError(
            f"invalid rescaled assignment rank {new_rank} of {new_world}"
        )
    if jax.distributed.is_initialized():
        _log.warning("leaving old world for rescale (rank %d of new %d)",
                     new_rank, new_world)
        jax.distributed.shutdown()
    if new_world == 1 and not (
        coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    ):
        flog.set_rank(0, 1)
        return 0, 1
    return init_distributed(
        coordinator_address=coordinator_address,
        num_processes=new_world,
        process_id=new_rank,
        **init_kwargs,
    )


def require_single_controller(what: str) -> None:
    """Raise a clear error when ``what`` runs under a multi-process mesh.

    Most streamed out-of-core fits ARE multi-process-capable (round 4:
    the linear family, KMeans, GMM, GBT, PCA, and the streamed-Adam
    runner behind MLP/FM train from per-process stream partitions via
    ``iteration/stream_sync.py``). The families still guarded here keep
    id-keyed or per-document host state in layouts that are not yet
    process-partitioned (ALS's factor blocks, LDA's document
    statistics, Word2Vec's pair cache) — on a multi-process mesh they would
    die opaquely inside ``device_put`` (non-addressable devices), so the
    defined behavior is this explicit rejection; multi-host training for
    them uses the in-RAM paths with ``mesh.global_batch`` per-host
    ingest (``examples/multihost_pod.py``).
    """
    if jax.process_count() > 1:
        _log.error("%s rejected under a multi-process mesh "
                   "(single-controller only)", what)
        raise RuntimeError(
            f"{what} is single-controller: it places full global batches "
            "from one process, which cannot address a multi-process "
            "mesh's remote devices. Run it single-process, or use the "
            "in-RAM fit with per-host `mesh.global_batch` ingest "
            "(docs/development/parallelism.md, examples/multihost_pod.py). "
            "Multi-process streamed fits are available for the linear "
            "family, KMeans, GaussianMixture, GBT, PCA, and MLP/FM."
        )


def process_slice(n: int, process_index: Optional[int] = None,
                  process_count: Optional[int] = None) -> slice:
    """This host's contiguous row range of a global dataset of ``n`` rows.

    Multi-host input pipeline convention: each host reads only its slice
    (the reference's per-subtask stream partitions), then shards it over
    its addressable devices; global batch = concat of host slices.
    Remainder rows go to the low-index hosts, one each.
    """
    p = jax.process_index() if process_index is None else process_index
    c = jax.process_count() if process_count is None else process_count
    base, rem = divmod(n, c)
    start = p * base + min(p, rem)
    return slice(start, start + base + (1 if p < rem else 0))
