"""Broadcast variables — the ``BroadcastUtils`` analog.

Parity (SURVEY.md §2.1): ``BroadcastUtils.withBroadcastStream(inputs,
bcStreams, fn)`` (``ml/common/broadcast/BroadcastUtils.java:67-155``) builds
``fn``'s subgraph in a draft environment, co-locates a receiver operator
with the consumer, and *blocks/caches the input to disk* until every
broadcast variable has fully arrived, exposing them through a per-TM static
registry (``BroadcastContext.java:40-84``) via
``getBroadcastVariable(name)``.

TPU-native redesign: a broadcast variable is a *replicated device value* —
``jax.device_put`` with a fully-replicated sharding over the mesh. The
receiver/caching/blocking machinery (≈1.9k LoC in the reference) does not
exist because SPMD replication is a data placement performed before the
consumer runs, not a runtime protocol. What survives is the API shape: a
named registry scoped to one ``with_broadcast`` call, readable from inside
the user function via :func:`get_broadcast_variable` — so algorithm code
keeps the reference's idiom (e.g.
``LogisticRegressionModel.PredictLabelFunction`` reads the model via
``getBroadcastVariable``, ``LogisticRegressionModel.java:133-170``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Mapping, Optional, Sequence

from flinkml_tpu.parallel.mesh import DeviceMesh

_local = threading.local()


class BroadcastContext:
    """Per-thread registry of live broadcast variables.

    Parity: the reference's static per-TM ``BroadcastContext`` map; here the
    scope is one ``with_broadcast`` call on the calling thread (nested calls
    shadow outer names, like nested broadcast scopes).
    """

    @staticmethod
    def _stack() -> list:
        if not hasattr(_local, "stack"):
            _local.stack = []
        return _local.stack

    @staticmethod
    def lookup(name: str) -> Any:
        for frame in reversed(BroadcastContext._stack()):
            if name in frame:
                return frame[name]
        raise KeyError(
            f"no broadcast variable {name!r} in scope; available: "
            f"{sorted(set().union(*BroadcastContext._stack()) if BroadcastContext._stack() else set())}"
        )


def get_broadcast_variable(name: str) -> Any:
    """Read a broadcast variable from inside a ``with_broadcast`` function.

    Parity: ``BroadcastStreamingRuntimeContext.getBroadcastVariable``.
    """
    return BroadcastContext.lookup(name)


def with_broadcast(
    fn: Callable,
    inputs: Sequence[Any] = (),
    broadcast_variables: Optional[Mapping[str, Any]] = None,
    mesh: Optional[DeviceMesh] = None,
):
    """Run ``fn(*inputs)`` with named variables replicated to every device.

    Parity: ``BroadcastUtils.withBroadcastStream`` — except nothing blocks:
    each variable is placed replicated (over ``mesh`` if given, else the
    default device) *before* ``fn`` runs, which is exactly the guarantee the
    reference's cache-until-ready wrapper fights its runtime to provide.
    """
    broadcast_variables = dict(broadcast_variables or {})
    placed = {
        name: (mesh.replicate(v) if mesh is not None else _default_put(v))
        for name, v in broadcast_variables.items()
    }
    stack = BroadcastContext._stack()
    stack.append(placed)
    try:
        return fn(*inputs)
    finally:
        stack.pop()


def _default_put(value: Any):
    import jax

    return jax.device_put(value)
