"""Distributed primitives — the §2.5 checklist as XLA collectives.

Reference → TPU mapping (SURVEY.md §2.5):

  - ``DataStreamUtils.allReduceSum`` (``AllReduceImpl.java:52-299``: 3-hop
    chunked reduce-scatter + all-gather over keyed Netty shuffles, 4KB
    chunks) → :func:`all_reduce_sum`: one fused ``jax.lax.psum`` over ICI.
  - ``BroadcastUtils.withBroadcastStream`` (per-TM cache + blocking wrapper,
    ``BroadcastUtils.java:67-155``) → :func:`broadcast`: a replicated
    sharding; no caching/blocking machinery exists because SPMD replication
    is a data placement, not a runtime protocol.
  - keyed ``keyBy``+window/reduce aggregation (KMeans ``KMeans.java:174-235``,
    NaiveBayes, OneHotEncoder) → :func:`keyed_aggregate`: per-shard
    ``segment_sum`` + cross-device psum.
  - ``DataStreamUtils.mapPartition`` (buffer-all-then-apply operator,
    ``DataStreamUtils.java:62-106``) → :func:`map_partition`: a per-shard
    function under ``shard_map`` — the shard IS the partition, already
    materialized, so no ListState buffering exists.

All functions accept host numpy or device arrays and are jit-compatible when
used with device inputs (each wraps a ``jax.shard_map`` region).
"""

from __future__ import annotations

from typing import Callable

import flinkml_tpu._jax_compat  # noqa: F401  (jax version shims; install before first jax use)
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flinkml_tpu.parallel.mesh import DeviceMesh


def all_reduce_sum(mesh: DeviceMesh, contributions) -> jax.Array:
    """Sum per-device contributions; every device gets the full result.

    ``contributions``: array of shape ``[P, ...]`` (one slice per device, as
    in the reference where each of P subtasks holds one ``double[]``) or
    ``[P*k, ...]`` — the leading axis is sharded over the data axis and
    summed away.

    Replaces ``AllReduceImpl.allReduceSum``; the 4KB chunking, chunk→task
    routing and reassembly (AllReduceImpl.java:69-232) all disappear into a
    single ICI collective.
    """
    axis = DeviceMesh.DATA_AXIS

    def local_sum(x):
        return jax.lax.psum(jnp.sum(x, axis=0), axis)

    return jax.shard_map(
        local_sum, mesh=mesh.mesh, in_specs=P(axis), out_specs=P()
    )(contributions)


def broadcast(mesh: DeviceMesh, tree):
    """Replicate value(s) to all devices — the broadcast-variable analog."""
    return mesh.replicate(tree)


def keyed_aggregate(
    mesh: DeviceMesh, values, keys, num_segments: int
) -> jax.Array:
    """Sum ``values`` grouped by integer ``keys``; replicated result.

    values: ``[n, ...]`` (leading axis sharded over data), keys: ``[n]``
    int32 in ``[0, num_segments)``. Returns ``[num_segments, ...]`` summed
    across all shards — the keyed shuffle+reduce of the reference collapsed
    into on-device segment-sum + one psum.
    """
    axis = DeviceMesh.DATA_AXIS

    def local(v, k):
        seg = jax.ops.segment_sum(v, k, num_segments=num_segments)
        return jax.lax.psum(seg, axis)

    return jax.shard_map(
        local, mesh=mesh.mesh, in_specs=(P(axis), P(axis)), out_specs=P()
    )(values, jnp.asarray(keys, dtype=jnp.int32))


def map_partition(
    mesh: DeviceMesh,
    fn: Callable,
    *arrays,
    out_specs=None,
):
    """Apply ``fn`` once per shard (= per partition) of the inputs.

    ``fn`` receives each input's local shard (leading axis = local rows) and
    must return array(s) of fixed shape; with the default ``out_specs`` the
    per-shard results are concatenated along the leading axis, mirroring
    ``mapPartition``'s one-output-stream-per-partition. Pass ``out_specs=P()``
    for functions whose result is already replicated (e.g. after an
    internal psum).
    """
    axis = DeviceMesh.DATA_AXIS
    if out_specs is None:
        out_specs = P(axis)
    in_specs = tuple(P(axis) for _ in arrays)
    return jax.shard_map(
        fn, mesh=mesh.mesh, in_specs=in_specs, out_specs=out_specs
    )(*arrays)
