"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no attention or sequence parallelism (SURVEY.md §2.5 —
its models are GLMs/clustering), but this framework's parallel substrate is
designed so model/sequence sharding layers on without changes; these
primitives are that extension, built the TPU way:

  - **Ring attention** (blockwise attention + flash-style online softmax):
    Q stays resident, K/V blocks rotate around the mesh axis via
    ``lax.ppermute`` (XLA lowers to ICI neighbor exchanges that overlap
    with the block matmuls). Peak memory per device is O(L_local²)
    instead of O(L²), so sequence length scales linearly with devices.
  - **Ulysses** (all-to-all sequence parallelism): reshard
    sequence-sharded activations to head-sharded via one ``all_to_all``,
    run ordinary full attention locally per head group, reshard back.
    Cheaper collectives for moderate L; requires heads % devices == 0.

Both compute exact attention — tests compare against the single-device
full-softmax reference to float32 tolerance, causal and non-causal.
"""

from __future__ import annotations

import functools
from typing import Optional

import flinkml_tpu._jax_compat  # noqa: F401  (jax version shims; install before first jax use)
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flinkml_tpu.parallel.mesh import DeviceMesh

_NEG = -1e30  # finite "-inf": keeps exp()/max() NaN-free on fully masked rows


def _block_update(q, k, v, m, l, o, scale, q_off, k_off, causal):
    """One blockwise attention step with online-softmax accumulators.

    q [B,H,Lq,D] against one K/V block [B,H,Lk,D]; (m, l, o) are the
    running max, normalizer, and unnormalized output.
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[2])
        k_pos = k_off + jnp.arange(k.shape[2])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask, scores, _NEG)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def _finalize(m, l, o):
    # Rows with no unmasked key (l == 0) return 0 rather than NaN.
    return jnp.where(l > 0, o / jnp.maximum(l, 1e-30), 0.0)


def _ring_attention_local(q, k, v, axis: str, causal: bool):
    """Per-device ring pass. All inputs [B, H, L_local, D], seq-sharded."""
    p_size = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    l_loc = q.shape[2]
    scale = 1.0 / (q.shape[3] ** 0.5)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    # pcast-to-varying: the accumulators are constants, but the loop carry
    # must be marked device-varying to match the per-device outputs.
    m = jax.lax.pcast(
        jnp.full(q.shape[:3] + (1,), _NEG, dtype=q.dtype), (axis,), to="varying"
    )
    l = jax.lax.pcast(
        jnp.zeros(q.shape[:3] + (1,), dtype=q.dtype), (axis,), to="varying"
    )
    o = jnp.zeros_like(q)

    def body(s, carry):
        m, l, o, kb, vb = carry
        # After s forward rotations, this device holds the block that
        # device (idx - s) mod P owns — its global key offset follows.
        src = (jnp.asarray(idx, jnp.int32) - jnp.asarray(s, jnp.int32)
               + p_size) % p_size
        m, l, o = _block_update(
            q, kb, vb, m, l, o, scale, idx * l_loc, src * l_loc, causal
        )
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        return m, l, o, kb, vb

    m, l, o, _, _ = jax.lax.fori_loop(0, p_size, body, (m, l, o, k, v))
    return _finalize(m, l, o)


def _full_attention(q, k, v, causal: bool, q_off=0):
    """Plain full-softmax attention (the Ulysses local step and the
    single-device fallback)."""
    scale = 1.0 / (q.shape[3] ** 0.5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[2])
        k_pos = jnp.arange(k.shape[2])
        scores = jnp.where(q_pos[:, None] >= k_pos[None, :], scores, _NEG)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _ulysses_local(q, k, v, axis: str, causal: bool):
    """All-to-all reshard: seq-sharded [B,H,L/P,D] -> head-sharded
    [B,H/P,L,D], full attention, reshard back."""
    def seq_to_heads(x):
        return jax.lax.all_to_all(
            x, axis, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = _full_attention(qh, kh, vh, causal)
    return heads_to_seq(oh)


@functools.lru_cache(maxsize=32)
def _sharded_attention(mesh, axis: str, kind: str, causal: bool):
    local = {
        "ring": _ring_attention_local,
        "ulysses": _ulysses_local,
    }[kind]
    fn = functools.partial(local, axis=axis, causal=causal)
    spec = P(None, None, axis, None)  # [B, H, L, D] sharded on L
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
    )


def ring_attention(q, k, v, mesh: Optional[DeviceMesh] = None,
                   causal: bool = False):
    """Exact attention over sequence-sharded Q/K/V ``[B, H, L, D]``.

    ``L`` must divide by the mesh size. K/V blocks rotate over the mesh
    axis (``ppermute`` on ICI) with flash-style online-softmax
    accumulation; activations never materialize ``[L, L]`` scores.
    """
    dm = mesh if mesh is not None else DeviceMesh()
    return _dispatch(q, k, v, dm, "ring", causal)


def ulysses_attention(q, k, v, mesh: Optional[DeviceMesh] = None,
                      causal: bool = False):
    """Exact attention via all-to-all sequence→head resharding.

    Requires ``H % mesh_size == 0`` and ``L % mesh_size == 0``.
    """
    dm = mesh if mesh is not None else DeviceMesh()
    return _dispatch(q, k, v, dm, "ulysses", causal)


def _dispatch(q, k, v, dm: DeviceMesh, kind: str, causal: bool):
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    if q.ndim != 4:
        raise ValueError(f"expected [batch, heads, seq, dim], got {q.shape}")
    p_size = dm.axis_size(dm.axis_names[0])
    if q.shape[2] % p_size != 0:
        raise ValueError(
            f"sequence length {q.shape[2]} must divide by mesh size {p_size}"
        )
    if kind == "ulysses" and q.shape[1] % p_size != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by the mesh "
            f"size ({p_size})"
        )
    if p_size == 1:
        return _full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               causal)
    fn = _sharded_attention(dm.mesh, dm.axis_names[0], kind, causal)
    return fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
