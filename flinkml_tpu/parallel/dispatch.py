"""Bounded in-flight dispatch — backpressure for multi-process meshes.

JAX dispatch is asynchronous: a jitted call enqueues an XLA program and
returns futures immediately. On a single process the runtime's own queue
depth bounds outstanding work, but on a multi-process mesh nothing bounds
the number of *cross-process collective* programs in flight — and the CPU
(Gloo) backend wedges permanently when a host loop enqueues too many
collective steps without ever synchronizing (measured on a 2-process
mesh: ≤20 in-flight ``psum`` steps drain in milliseconds; 60 deadlock
the pod).

The reference never faces this because Flink's credit-based network flow
control backpressures every shuffle a collective rides
(``AllReduceImpl.java:52-299`` runs on those channels). This module is
that policy for SPMD hosts: materialize the loop carry every ``interval``
dispatches, so at most ``interval`` collective programs are ever
outstanding. Single-process meshes default to unbounded (XLA's own queue
is sufficient and extra host syncs only add latency).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional

import jax

import flinkml_tpu.faults as faults

_ENV_INTERVAL = "FLINKML_SYNC_INTERVAL"
_DEFAULT_MULTIPROCESS_INTERVAL = 8

# -- collective-dispatch locking -------------------------------------------
#
# Mutexes for whole training loops launched from concurrent host THREADS
# over this process's devices. Two multi-device SPMD programs dispatched
# concurrently from different threads interleave their per-device
# collective enqueues in different orders on different devices — on the
# CPU backend that deadlocks the collective rendezvous outright (observed:
# two threaded `train_kmeans_stream` calls over an 8-virtual-device mesh
# wedge with every thread asleep); on real fabrics it is undefined
# dispatch-order territory. Concurrent fits time-share a mesh by
# serializing here: correctness over parallelism (the devices are one
# shared resource either way). Reentrant so nested training loops (e.g. a
# fit inside a tuning fold) self-compose.
#
# PR 1 shipped this as one process-wide lock. It is now *per device set*:
# fits over disjoint meshes proceed concurrently, and every acquisition is
# tracked so `flinkml_tpu.analysis.collectives.check_dispatch_trace` can
# statically verify that no two threads dispatch collective programs over
# shared devices without a common lock (rule FML302) — the lock is
# analyzer-verified, not just hoped-for.

_HELD_LOCKS = threading.local()  # per-thread list of held lock tokens


def _held_list():
    lst = getattr(_HELD_LOCKS, "tokens", None)
    if lst is None:
        lst = _HELD_LOCKS.tokens = []
    return lst


class TrackedRLock:
    """An RLock that records, per thread, that it is held — so dispatch
    trace events can carry the lock tokens the dispatching thread holds."""

    def __init__(self, token: str):
        self.token = token
        self._lock = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _held_list().append(self.token)
        return ok

    def release(self) -> None:
        self._lock.release()
        held = _held_list()
        # Remove ONE entry (reentrant acquisitions push one token each).
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.token:
                del held[i]
                break

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()


def held_lock_tokens() -> tuple:
    """Tokens of every tracked lock the calling thread currently holds."""
    return tuple(dict.fromkeys(_held_list()))


class _CompositeLock:
    """Acquires several :class:`TrackedRLock`s in canonical (token-sorted)
    order — the mutex for a device set that overlaps other registered
    sets. Global ordering makes nested/concurrent composites
    deadlock-free, and sharing at least one component lock with every
    overlapping fit gives mutual exclusion: a later-registered overlapping
    set's composite always includes the earlier set's lock."""

    def __init__(self, locks):
        self._locks = sorted(locks, key=lambda l: l.token)

    def acquire(self) -> bool:
        for lock in self._locks:
            lock.acquire()
        return True

    def release(self) -> None:
        for lock in reversed(self._locks):
            lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()


class _GlobalLock:
    """The ``mesh=None`` mutex: the process lock plus EVERY registered
    mesh lock. The mesh-lock snapshot is taken *after* the process lock is
    held — new device sets register under the process lock, so no mesh
    lock can appear between the snapshot and the acquisition: nothing
    slips past a global holder."""

    def acquire(self) -> bool:
        _PROCESS_LOCK.acquire()
        with _MESH_LOCKS_GUARD:
            held = sorted(_MESH_LOCKS.values(), key=lambda l: l.token)
        for lock in held:
            lock.acquire()
        # Stack of per-acquire snapshots: reentrant acquires may see more
        # registered locks than the outer one.
        self._held_stack = getattr(self, "_held_stack", [])
        self._held_stack.append(held)
        return True

    def release(self) -> None:
        for lock in reversed(self._held_stack.pop()):
            lock.release()
        _PROCESS_LOCK.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()


_PROCESS_LOCK = TrackedRLock("lock:process")
_MESH_LOCKS: dict = {}  # frozenset(device ids) -> TrackedRLock
_MESH_LOCKS_GUARD = threading.Lock()


def _device_id_set(mesh) -> frozenset:
    """Normalize a lock subject to its device-id set: a ``DeviceMesh``,
    a raw ``jax.sharding.Mesh``, or a plain sequence of ``jax.Device``s
    / integer device ids (how a serving replica pool names a per-replica
    slice without building a mesh around it)."""
    if isinstance(mesh, (list, tuple, set, frozenset)):
        return frozenset(
            d if isinstance(d, int) else d.id for d in mesh
        )
    devices = getattr(mesh, "mesh", mesh).devices
    return frozenset(d.id for d in devices.flatten())


def local_execution_lock(mesh=None):
    """The collective-dispatch mutex for ``mesh``'s device set (see
    above). Hold it (``with local_execution_lock(mesh):``) around any
    host-driven loop that dispatches multi-device collective programs and
    may legally be called from concurrent threads.

    ``mesh=None`` is globally exclusive (the conservative PR 1
    behaviour): it acquires the process lock plus every registered mesh
    lock, so it serializes against every mesh-keyed fit — and new mesh
    locks cannot register while it is held (registration synchronizes on
    the process lock), so no fit can slip past it. With a mesh (or a
    plain device sequence — a replica pool's per-slice placement),
    identical device sets share one tracked lock, disjoint sets get
    independent locks (concurrent fits over disjoint meshes — and pool
    replicas over disjoint slices — proceed in parallel), and a set that
    overlaps other registered sets gets a composite acquiring every
    intersecting lock in canonical order — overlapping fits always share
    at least one component lock, so the rendezvous-interleaving hazard
    cannot occur (and the shared token is visible to the analyzer's
    FML302/FML303 checks).
    """
    if mesh is None:
        return _GlobalLock()
    key = _device_id_set(mesh)
    with _MESH_LOCKS_GUARD:
        lock = _MESH_LOCKS.get(key)
    if lock is None:
        # First sighting of this device set: registering under the
        # process lock means a process-wide (mesh=None) holder — whose
        # composite predates this lock and so cannot contain it —
        # finishes before any fit over the new set can start. Lock order
        # is PROCESS then GUARD everywhere, never the reverse.
        with _PROCESS_LOCK:
            with _MESH_LOCKS_GUARD:
                lock = _MESH_LOCKS.get(key)
                if lock is None:
                    lock = _MESH_LOCKS[key] = TrackedRLock(
                        "lock:mesh:" + ",".join(str(i) for i in sorted(key))
                    )
    with _MESH_LOCKS_GUARD:
        overlapping = [
            l for k, l in _MESH_LOCKS.items() if k != key and (k & key)
        ]
    if overlapping:
        return _CompositeLock([lock] + overlapping)
    return lock


# -- slice leases ----------------------------------------------------------
#
# Training/serving colocation (ROADMAP item 3): a training job LEASES the
# mesh slice it runs on, so the serving autoscaler can see which devices
# are spoken for — and reclaim them under load. A lease is a cooperative
# contract, not a lock: the holder keeps dispatching (under its own
# local_execution_lock) until it observes `revoke_requested()` at a safe
# boundary (an epoch edge), releases the slice, and the reclaimer places
# serving work on the freed devices. Dispatch-trace events record any
# ACTIVE lease whose devices a *foreign* thread dispatches over, which is
# what the analyzer's FML304 check audits: serving-pool work landing on a
# still-leased slice means the reclaim handshake was skipped.

_LEASES: dict = {}  # token -> SliceLease
_LEASES_GUARD = threading.Lock()


class SliceLease:
    """One training job's claim on a device slice (see above). Create
    via :func:`lease_devices`; use as a context manager (releases on
    exit) or call :meth:`release` explicitly at the safe boundary."""

    def __init__(self, holder: str, device_ids):
        self.holder = str(holder)
        self.devices = frozenset(int(i) for i in device_ids)
        self.token = (
            f"lease:{self.holder}:"
            + ",".join(str(i) for i in sorted(self.devices))
        )
        self._revoke = threading.Event()
        self._released = threading.Event()
        self.revoke_reason: Optional[str] = None
        self._holder_thread = threading.get_ident()

    # -- holder side -------------------------------------------------------
    @property
    def active(self) -> bool:
        return not self._released.is_set()

    def revoke_requested(self) -> bool:
        """Poll at safe boundaries (epoch edges): True once a reclaimer
        asked for the slice back — finish the boundary, checkpoint, and
        :meth:`release`."""
        return self._revoke.is_set()

    def release(self) -> None:
        """Give the slice back (idempotent). Unregisters the lease, so
        later dispatches over these devices stop carrying its token."""
        with _LEASES_GUARD:
            _LEASES.pop(self.token, None)
        self._released.set()

    def __enter__(self) -> "SliceLease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- reclaimer side ----------------------------------------------------
    def request_revoke(self, reason: str = "") -> None:
        """Ask the holder to vacate (sets the flag the holder polls);
        the reclaimer then :meth:`wait_released` with a bound."""
        if reason and self.revoke_reason is None:
            self.revoke_reason = reason
        self._revoke.set()

    def wait_released(self, timeout: Optional[float] = None) -> bool:
        return self._released.wait(timeout)

    def snapshot(self) -> dict:
        return {
            "token": self.token,
            "holder": self.holder,
            "devices": sorted(self.devices),
            "active": self.active,
            "revoke_requested": self.revoke_requested(),
            "revoke_reason": self.revoke_reason,
        }


def lease_devices(mesh, holder: str) -> SliceLease:
    """Register a :class:`SliceLease` for ``mesh``'s device set (a
    ``DeviceMesh``, raw mesh, or plain device/id sequence — the same
    subjects :func:`local_execution_lock` accepts)."""
    lease = SliceLease(holder, _device_id_set(mesh))
    with _LEASES_GUARD:
        if lease.token in _LEASES:
            raise ValueError(
                f"lease {lease.token!r} is already registered; release "
                "the existing lease before re-leasing the slice"
            )
        _LEASES[lease.token] = lease
    return lease


def active_leases() -> tuple:
    """Every currently registered (unreleased) lease."""
    with _LEASES_GUARD:
        return tuple(_LEASES.values())


def leased_device_ids() -> frozenset:
    """Union of every active lease's device ids — the autoscaler's
    'spoken for' set when choosing a placement."""
    with _LEASES_GUARD:
        out: set = set()
        for lease in _LEASES.values():
            out |= lease.devices
        return frozenset(out)


def _foreign_lease_tokens(ids) -> tuple:
    """Tokens of active leases overlapping ``ids`` held by OTHER
    threads — the holder's own dispatches are its business; anyone
    else's on a leased slice is the FML304 shape."""
    me = threading.get_ident()
    dev = set(ids)
    with _LEASES_GUARD:
        return tuple(
            l.token for l in _LEASES.values()
            if l._holder_thread != me and (l.devices & dev)
        )


# -- dispatch trace observers ----------------------------------------------
#
# Training loops report their collective dispatches here (cheap: a list
# check when no observer is installed). Observers receive plain event
# dicts in the `analysis.collectives.DispatchEvent` schema, so the
# analyzer can audit real runs and tests can assert on the program shape.

_DISPATCH_OBSERVERS: list = []


def add_dispatch_observer(callback) -> None:
    """Register ``callback(event_dict)`` for collective dispatch events."""
    _DISPATCH_OBSERVERS.append(callback)


def remove_dispatch_observer(callback) -> None:
    _DISPATCH_OBSERVERS.remove(callback)


def has_dispatch_observers() -> bool:
    return bool(_DISPATCH_OBSERVERS)


def record_collective_dispatch(program: str, devices, collectives=()) -> None:
    """Report one host-driven dispatch of a collective program. ``devices``
    is an iterable of ``jax.Device`` or integer device ids; the event
    carries the calling thread and the tracked locks it holds."""
    if not _DISPATCH_OBSERVERS:
        return
    ids = tuple(
        d if isinstance(d, int) else d.id for d in devices
    )
    t = threading.current_thread()
    event = {
        "thread": f"{t.name}({t.ident})",
        "program": program,
        "devices": ids,
        "collectives": list(collectives),
        "locks": held_lock_tokens(),
        # Active leases OTHER threads hold over these devices: a
        # serving-pool program carrying one here is the FML304 shape
        # (dispatching on a slice training still owns).
        "leases": _foreign_lease_tokens(ids),
    }
    for cb in list(_DISPATCH_OBSERVERS):
        cb(event)


def default_sync_interval() -> int:
    """The framework's in-flight dispatch bound for this process.

    ``0`` means unbounded (single-process meshes: the local runtime queue
    is bound enough). Multi-process meshes default to
    ``8`` — comfortably under the measured ~20-dispatch wedge threshold
    of the Gloo CPU backend while keeping the device pipeline fed.
    Override with ``FLINKML_SYNC_INTERVAL`` (any positive integer, or
    ``0`` to disable at your own risk).
    """
    env = os.environ.get(_ENV_INTERVAL)
    if env is not None:
        return max(0, int(env))
    if jax.process_count() > 1:
        return _DEFAULT_MULTIPROCESS_INTERVAL
    return 0


class DispatchGuard:
    """Counts dispatches and blocks on the carry every ``interval`` steps.

    Usage::

        guard = DispatchGuard()           # policy from default_sync_interval()
        for i in range(n_steps):
            carry = stepper(carry, batch)
            carry = guard.after_dispatch(carry)

    ``after_dispatch`` returns its argument unchanged so it can be chained
    into the loop carry assignment. Pass ``interval=0`` to make it a no-op
    (single-process default), or an explicit positive bound.
    """

    def __init__(self, interval: Optional[int] = None):
        self.interval = (
            default_sync_interval() if interval is None else max(0, int(interval))
        )
        self._since_sync = 0

    def after_dispatch(self, carry: Any) -> Any:
        if faults.ACTIVE is not None:  # host↔device transfer seam
            faults.fire("dispatch.transfer", count=self._since_sync + 1)
        self._since_sync += 1
        if self.interval and self._since_sync >= self.interval:
            jax.block_until_ready(carry)
            self._since_sync = 0
        return carry

    def flush(self, carry: Any) -> Any:
        """Force a synchronization point (end of a training phase)."""
        if faults.ACTIVE is not None:
            faults.fire("dispatch.transfer", count=self._since_sync)
        if self._since_sync:
            jax.block_until_ready(carry)
            self._since_sync = 0
        return carry


def synced_loop(
    n_steps: int,
    step_fn: Callable[[Any, int], Any],
    init: Any,
    interval: Optional[int] = None,
) -> Any:
    """Run ``carry = step_fn(carry, i)`` ``n_steps`` times with bounded
    in-flight dispatch.

    The host-loop counterpart of ``iteration.device_loop.device_iterate``
    for bodies that must stay host-driven (per-step data feeding,
    listeners) on a multi-process mesh: every ``interval`` dispatches the
    carry is materialized, so cross-process collectives can never pile up
    past the backend's safe queue depth. With ``interval=None`` the
    framework default applies (unbounded single-process, 8 multi-process).
    """
    guard = DispatchGuard(interval)
    carry = init
    for i in range(int(n_steps)):
        carry = guard.after_dispatch(step_fn(carry, i))
    return guard.flush(carry)
