"""Bounded in-flight dispatch — backpressure for multi-process meshes.

JAX dispatch is asynchronous: a jitted call enqueues an XLA program and
returns futures immediately. On a single process the runtime's own queue
depth bounds outstanding work, but on a multi-process mesh nothing bounds
the number of *cross-process collective* programs in flight — and the CPU
(Gloo) backend wedges permanently when a host loop enqueues too many
collective steps without ever synchronizing (measured on a 2-process
mesh: ≤20 in-flight ``psum`` steps drain in milliseconds; 60 deadlock
the pod).

The reference never faces this because Flink's credit-based network flow
control backpressures every shuffle a collective rides
(``AllReduceImpl.java:52-299`` runs on those channels). This module is
that policy for SPMD hosts: materialize the loop carry every ``interval``
dispatches, so at most ``interval`` collective programs are ever
outstanding. Single-process meshes default to unbounded (XLA's own queue
is sufficient and extra host syncs only add latency).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional

import jax

_ENV_INTERVAL = "FLINKML_SYNC_INTERVAL"
_DEFAULT_MULTIPROCESS_INTERVAL = 8

#: Process-wide mutex for whole training loops launched from concurrent
#: host THREADS over this process's devices. Two multi-device SPMD
#: programs dispatched concurrently from different threads interleave
#: their per-device collective enqueues in different orders on different
#: devices — on the CPU backend that deadlocks the collective rendezvous
#: outright (observed: two threaded `train_kmeans_stream` calls over an
#: 8-virtual-device mesh wedge with every thread asleep); on real fabrics
#: it is undefined dispatch-order territory. Concurrent fits time-share
#: the mesh by serializing here: correctness over parallelism (the
#: devices are one shared resource either way). Reentrant so nested
#: training loops (e.g. a fit inside a tuning fold) self-compose.
_LOCAL_EXECUTION_LOCK = threading.RLock()


def local_execution_lock() -> threading.RLock:
    """The process-wide collective-dispatch mutex (see above). Hold it
    (``with local_execution_lock():``) around any host-driven loop that
    dispatches multi-device collective programs and may legally be called
    from concurrent threads."""
    return _LOCAL_EXECUTION_LOCK


def default_sync_interval() -> int:
    """The framework's in-flight dispatch bound for this process.

    ``0`` means unbounded (single-process meshes: the local runtime queue
    is bound enough). Multi-process meshes default to
    ``8`` — comfortably under the measured ~20-dispatch wedge threshold
    of the Gloo CPU backend while keeping the device pipeline fed.
    Override with ``FLINKML_SYNC_INTERVAL`` (any positive integer, or
    ``0`` to disable at your own risk).
    """
    env = os.environ.get(_ENV_INTERVAL)
    if env is not None:
        return max(0, int(env))
    if jax.process_count() > 1:
        return _DEFAULT_MULTIPROCESS_INTERVAL
    return 0


class DispatchGuard:
    """Counts dispatches and blocks on the carry every ``interval`` steps.

    Usage::

        guard = DispatchGuard()           # policy from default_sync_interval()
        for i in range(n_steps):
            carry = stepper(carry, batch)
            carry = guard.after_dispatch(carry)

    ``after_dispatch`` returns its argument unchanged so it can be chained
    into the loop carry assignment. Pass ``interval=0`` to make it a no-op
    (single-process default), or an explicit positive bound.
    """

    def __init__(self, interval: Optional[int] = None):
        self.interval = (
            default_sync_interval() if interval is None else max(0, int(interval))
        )
        self._since_sync = 0

    def after_dispatch(self, carry: Any) -> Any:
        self._since_sync += 1
        if self.interval and self._since_sync >= self.interval:
            jax.block_until_ready(carry)
            self._since_sync = 0
        return carry

    def flush(self, carry: Any) -> Any:
        """Force a synchronization point (end of a training phase)."""
        if self._since_sync:
            jax.block_until_ready(carry)
            self._since_sync = 0
        return carry


def synced_loop(
    n_steps: int,
    step_fn: Callable[[Any, int], Any],
    init: Any,
    interval: Optional[int] = None,
) -> Any:
    """Run ``carry = step_fn(carry, i)`` ``n_steps`` times with bounded
    in-flight dispatch.

    The host-loop counterpart of ``iteration.device_loop.device_iterate``
    for bodies that must stay host-driven (per-step data feeding,
    listeners) on a multi-process mesh: every ``interval`` dispatches the
    carry is materialized, so cross-process collectives can never pile up
    past the backend's safe queue depth. With ``interval=None`` the
    framework default applies (unbounded single-process, 8 multi-process).
    """
    guard = DispatchGuard(interval)
    carry = init
    for i in range(int(n_steps)):
        carry = guard.after_dispatch(step_fn(carry, i))
    return guard.flush(carry)
