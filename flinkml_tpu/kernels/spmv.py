"""Pallas padded-ELL CSR SpMV — the sparse forward-margin matvec.

The sparse trainers' forward pass is one ELL matvec per step:
``dot[r] = sum_s values[r, s] * w[indices[r, s]]`` over a padded
``[rows, width]`` block (the ELL convention: index 0 / value 0 cells
contribute exactly 0). XLA lowers ``w[indices]`` as one gather that
materializes the whole ``[rows, width]`` gathered matrix before the
reduce; this kernel tiles the rows (grid over ``rows / ROW_TILE``) so
the gather target is one ``[ROW_TILE, width]`` VMEM-resident block and
the multiply-reduce never leaves VMEM. Per row the op tree — gather,
elementwise multiply, ``sum`` over the width axis — is identical to the
XLA reference ``jnp.sum(values * w[indices], axis=1)``, so results are
bit-identical to the JITTED reference at every dtype (the product path
is always jitted; an eager reference can differ in the last f32 bit
because XLA's unfused reduce uses a different association tree).

``w`` stays whole in one block (every row may touch every feature), so
the compiled path refuses ``dim`` past the one-block ceiling
(``MAX_COMPILED_DIM``). The gate (:mod:`flinkml_tpu.kernels._gate`,
site ``spmv``) keeps XLA the default; the bench's ``sparse_hot_loops``
stage measures the ratio and the device re-tune decides.
"""

from __future__ import annotations

from typing import Optional

#: Row tile (grid unit). 8 = f32 sublane count; rows pad up to a
#: multiple with zero rows that are sliced off after the call.
ROW_TILE = 8

#: One-block ceiling for ``w`` on the COMPILED (non-interpret) path:
#: the weight vector must stay VMEM-resident for every row tile.
MAX_COMPILED_DIM = 1 << 22


def unsupported_reason(indices, values, w, interpret: bool) -> Optional[str]:
    """Why the Pallas kernel cannot run these operands (None = it can).
    The wording lands verbatim in :class:`KernelUnsupportedError`."""
    import jax.numpy as jnp

    if indices.ndim != 2 or values.ndim != 2:
        return (f"indices/values must be [rows, width], got ranks "
                f"{indices.ndim}/{values.ndim}")
    if tuple(indices.shape) != tuple(values.shape):
        return (f"indices shape {tuple(indices.shape)} != values shape "
                f"{tuple(values.shape)}")
    if w.ndim != 1:
        return f"w must be [dim], got rank {w.ndim}"
    if not jnp.issubdtype(indices.dtype, jnp.integer):
        return f"indices dtype {indices.dtype} is not integer"
    if not jnp.issubdtype(values.dtype, jnp.floating):
        return (f"values dtype {values.dtype} is not floating (supported: "
                "bfloat16/float32, + float64 under the interpreter)")
    if values.dtype != w.dtype:
        return f"values dtype {values.dtype} != w dtype {w.dtype}"
    if not interpret:
        if values.dtype == jnp.float64:
            return "float64 is interpreter-only (TPU has no f64 lanes)"
        if w.shape[0] > MAX_COMPILED_DIM:
            return (f"dim {w.shape[0]} exceeds the one-block compiled "
                    f"ceiling of {MAX_COMPILED_DIM} (MAX_COMPILED_DIM) "
                    "for the VMEM-resident weight vector")
    return None


def _spmv_body(idx_ref, val_ref, w_ref, out_ref):
    import jax.numpy as jnp

    gathered = jnp.take(w_ref[...], idx_ref[...], axis=0)
    out_ref[...] = jnp.sum(val_ref[...] * gathered, axis=1)


def pallas_spmv(indices, values, w, *, interpret: Optional[bool] = None):
    """``sum(values * w[indices], axis=1)`` over a padded ELL block —
    bit-compatible with the XLA reference at every dtype. Unsupported
    operands raise :class:`KernelUnsupportedError` (same typed refusal
    as the gated dispatcher)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from flinkml_tpu.kernels import _gate

    if interpret is None:
        interpret = _gate.interpret_mode()
    reason = unsupported_reason(indices, values, w, interpret)
    if reason is not None:
        raise _gate.KernelUnsupportedError(
            f"kernels[spmv]: pallas_spmv cannot run these operands: "
            f"{reason}"
        )
    rows, width = values.shape
    idx32 = indices.astype(jnp.int32)
    pad = (-rows) % ROW_TILE
    if pad:
        idx32 = jnp.concatenate([idx32, jnp.zeros((pad, width), jnp.int32)])
        values = jnp.concatenate(
            [values, jnp.zeros((pad, width), values.dtype)]
        )
    grid = (idx32.shape[0] // ROW_TILE,)
    out = pl.pallas_call(
        _spmv_body,
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_TILE, width), lambda i: (i, 0)),
            pl.BlockSpec((ROW_TILE, width), lambda i: (i, 0)),
            pl.BlockSpec((w.shape[0],), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((ROW_TILE,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((idx32.shape[0],), values.dtype),
        interpret=interpret,
    )(idx32, values, w)
    return out[:rows] if pad else out


def spmv(indices, values, w, *, backend: Optional[str] = None):
    """The gated dispatcher: ``jnp.sum(values * w[indices], axis=1)``
    under ``"xla"``, :func:`pallas_spmv` under ``"pallas"``.
    ``backend=None`` resolves the gate (env > autotune table > xla); a
    passed backend is an explicit request and refuses unsupported
    operands loudly. Zero-row and zero-width blocks always take the XLA
    path (nothing to tile)."""
    import jax.numpy as jnp

    from flinkml_tpu.kernels import _gate

    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    w = jnp.asarray(w)
    if values.ndim == 2 and 0 in values.shape:
        return jnp.sum(values * jnp.take(w, indices, axis=0), axis=1)
    interpret = _gate.interpret_mode()
    chosen = _gate.resolve_checked(
        "spmv", unsupported_reason(indices, values, w, interpret), backend,
    )
    if chosen == "pallas":
        return pallas_spmv(indices, values, w, interpret=interpret)
    return jnp.sum(values * jnp.take(w, indices, axis=0), axis=1)


def factory_backend() -> str:
    """The resolved spmv backend for callers that bake it into a jit
    static argument (the lru-key idiom — see the gate module)."""
    from flinkml_tpu.kernels import _gate

    return _gate.backend_for("spmv")
