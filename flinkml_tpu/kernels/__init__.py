"""Hand-written Pallas kernels for the hot inner loops (ROADMAP item 2).

PAPER.md's blueprint is a "JAX/XLA/pjit/**Pallas** design"; this package
is the Pallas half: a gated second backend for the four loops where the
executor's speed was hostage to XLA codegen (the bf16 fused-chain CPU
ratio of 0.24–0.49 in PR 10 is the motivating number):

- ``fused_chain`` — the fused 5-stage transform chain as ONE row-tiled
  Pallas kernel per bucket, validity mask applied in-kernel
  (:mod:`flinkml_tpu.kernels.chain`);
- ``segment_sum`` — the padded-ELL sparse gradient scatter-accumulate
  with an ``indices_are_sorted`` run-flush specialization and a
  multi-block cell grid (:mod:`flinkml_tpu.kernels.segsum`);
- ``spmv`` — the padded-ELL CSR matvec behind the sparse trainers'
  forward margins and ``BatchedCSR.matvec``, row-tiled so the gather
  never materializes off-block (:mod:`flinkml_tpu.kernels.spmv`);
- ``topk`` — the bucketed top-k behind KNN voting and LSH candidate
  ranking as k masked row-max passes (:mod:`flinkml_tpu.kernels.topk`).

Everything rides the established gate idiom
(:mod:`flinkml_tpu.kernels._gate`): env-gated
(``FLINKML_TPU_KERNELS=pallas|xla`` or per-site pairs), measured
defaults from the autotune table's ``kernel_backend_<site>`` knobs
(XLA stays the default until a >1.10x committed win), lru-keyed (the
backend joins the fused executor's program/AOT cache identity, the
trainer factories' lru keys, and jit static args — a flip re-keys, it
never aliases), pinned-numerics equivalence (``interpret=True`` CPU
parity tests in ``tests/test_kernels.py``; bitwise at f32, policy
tolerance under bf16), and loud refusal on unsupported dtypes/shapes
(:class:`KernelUnsupportedError` on explicit requests, warn-once XLA
fallback for table-chosen backends).

See ``docs/development/kernels.md`` for the supported-shape tables,
the equivalence-test recipe, and the device re-tune runbook.
"""

from flinkml_tpu.kernels._gate import (  # noqa: F401
    BACKENDS,
    ENV_INTERPRET_VAR,
    ENV_VAR,
    KNOB_PREFIX,
    SITES,
    KernelUnsupportedError,
    backend_for,
    interpret_mode,
    resolve_backend,
)
from flinkml_tpu.kernels.segsum import (  # noqa: F401
    pallas_segment_sum,
    segment_sum,
)
from flinkml_tpu.kernels.segsum import (  # noqa: F401
    factory_backend as segsum_backend,
)
from flinkml_tpu.kernels.spmv import (  # noqa: F401
    pallas_spmv,
    spmv,
)
from flinkml_tpu.kernels.spmv import (  # noqa: F401
    factory_backend as spmv_backend,
)
from flinkml_tpu.kernels.topk import (  # noqa: F401
    pallas_top_k,
    top_k,
)
from flinkml_tpu.kernels.topk import (  # noqa: F401
    factory_backend as topk_backend,
)

__all__ = [
    "BACKENDS",
    "ENV_INTERPRET_VAR",
    "ENV_VAR",
    "KNOB_PREFIX",
    "SITES",
    "KernelUnsupportedError",
    "backend_for",
    "interpret_mode",
    "resolve_backend",
    "pallas_segment_sum",
    "segment_sum",
    "segsum_backend",
    "pallas_spmv",
    "spmv",
    "spmv_backend",
    "pallas_top_k",
    "top_k",
    "topk_backend",
]
