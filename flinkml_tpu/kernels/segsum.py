"""Pallas padded-ELL segment-sum — the sparse gradient scatter-accumulate.

The sparse trainers' dominant op at Criteo scale is one flat
``segment_sum`` per step: ``contrib [cells]`` (or ``[cells, k]`` for the
row-payload W2V accumulator) scatter-added into ``[num_segments]`` by
``ids [cells]``. XLA lowers the unsorted case through a per-step bitonic
sort over every cell (the round-4 A/B in
:func:`flinkml_tpu.models._linear_sgd._sparse_layout`); this kernel
streams the cells once instead, accumulating into the VMEM-resident
output block:

- **unsorted**: one sequential pass, ``out[ids[j]] += v[j]`` — addition
  order equals XLA's CPU scatter order (element order), so the f32
  result is bit-identical to ``jax.ops.segment_sum``.
- **``indices_are_sorted=True``**: run-flush specialization — a carried
  ``(current id, accumulator)`` pair flushes to ``out`` only at run
  boundaries, turning ``cells`` read-modify-writes of the output into
  ``runs`` predicated stores. Left-to-right addition within a run keeps
  bit-parity with the sorted XLA scatter.

Single-block kernel by design: the whole padded flat array and the
``[num_segments, k]`` output live in one block, which is exactly right
for the interpreter (CI) and for trainer shapes whose output is the
VMEM-resident ``[dim]`` gradient; the supported-shape ceiling below
refuses sizes that could not fit VMEM on a real device rather than
compiling something that spills. The device re-tune (bench stage
``pallas``) decides whether this beats XLA's scatter on hardware — the
gate (:mod:`flinkml_tpu.kernels._gate`) keeps XLA the default until a
measured win is committed.
"""

from __future__ import annotations

from typing import Optional

#: Supported-shape ceiling for the COMPILED (non-interpret) path: cells
#: beyond this cannot stream through one VMEM block on current TPUs.
MAX_COMPILED_CELLS = 1 << 22

_FLOAT_KINDS = "f"  # jnp dtype.kind for floating


def unsupported_reason(values, ids, num_segments: int,
                       interpret: bool) -> Optional[str]:
    """Why the Pallas kernel cannot run these operands (None = it can).
    The wording lands verbatim in :class:`KernelUnsupportedError`."""
    import jax.numpy as jnp

    v = jnp.asarray(values) if not hasattr(values, "dtype") else values
    i = jnp.asarray(ids) if not hasattr(ids, "dtype") else ids
    if v.ndim not in (1, 2):
        return f"values must be [cells] or [cells, k], got rank {v.ndim}"
    if i.ndim != 1:
        return f"ids must be [cells], got rank {i.ndim}"
    if v.shape[0] != i.shape[0]:
        return f"values rows {v.shape[0]} != ids rows {i.shape[0]}"
    if not jnp.issubdtype(v.dtype, jnp.floating):
        return (f"values dtype {v.dtype} is not floating (supported: "
                "bfloat16/float32, + float64 under the interpreter)")
    if not jnp.issubdtype(i.dtype, jnp.integer):
        return f"ids dtype {i.dtype} is not integer"
    if num_segments < 1:
        return f"num_segments must be >= 1, got {num_segments}"
    if not interpret:
        if v.dtype == jnp.float64:
            return "float64 is interpreter-only (TPU has no f64 lanes)"
        if v.shape[0] > MAX_COMPILED_CELLS:
            return (f"{v.shape[0]} cells exceed the one-block compiled "
                    f"ceiling of {MAX_COMPILED_CELLS}")
    return None


def _unsorted_body(ids_ref, val_ref, out_ref):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    out_ref[...] = jnp.zeros_like(out_ref)
    cells = val_ref.shape[0]

    def body(j, carry):
        idx = ids_ref[j]
        out_ref[pl.ds(idx, 1), :] = (
            out_ref[pl.ds(idx, 1), :] + val_ref[pl.ds(j, 1), :]
        )
        return carry

    jax.lax.fori_loop(0, cells, body, 0)


def _sorted_body(ids_ref, val_ref, out_ref):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    out_ref[...] = jnp.zeros_like(out_ref)
    cells = val_ref.shape[0]

    def body(j, carry):
        cur, acc = carry
        idx = ids_ref[j]
        v = val_ref[pl.ds(j, 1), :][0]
        flush = idx != cur

        @pl.when(flush)
        def _():
            out_ref[pl.ds(cur, 1), :] = (
                out_ref[pl.ds(cur, 1), :] + acc[None, :]
            )

        return idx, jnp.where(flush, v, acc + v)

    cur, acc = jax.lax.fori_loop(
        0, cells, body,
        (ids_ref[0], jnp.zeros_like(val_ref[pl.ds(0, 1), :][0])),
    )
    out_ref[pl.ds(cur, 1), :] = out_ref[pl.ds(cur, 1), :] + acc[None, :]


def pallas_segment_sum(values, ids, num_segments: int, *,
                       indices_are_sorted: bool = False,
                       interpret: Optional[bool] = None):
    """The Pallas scatter-accumulate (module docstring). Same contract
    as ``jax.ops.segment_sum(values, ids, num_segments,
    indices_are_sorted=...)`` for in-range ids; out-of-range ids are the
    caller's bug on both backends (padding rides the ELL convention:
    index 0 / value 0 is a no-op add)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from flinkml_tpu.kernels import _gate

    if interpret is None:
        interpret = _gate.interpret_mode()
    flat = values.ndim == 1
    v2 = values[:, None] if flat else values
    cells, k = v2.shape
    ids32 = ids.astype(jnp.int32)
    body = _sorted_body if indices_are_sorted else _unsorted_body
    out = pl.pallas_call(
        body,
        in_specs=[
            pl.BlockSpec((cells,), lambda: (0,)),
            pl.BlockSpec((cells, k), lambda: (0, 0)),
        ],
        out_specs=pl.BlockSpec((num_segments, k), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_segments, k), v2.dtype),
        interpret=interpret,
    )(ids32, v2)
    return out[:, 0] if flat else out


def segment_sum(values, ids, num_segments: int, *,
                indices_are_sorted: bool = False,
                backend: Optional[str] = None):
    """The gated dispatcher: ``jax.ops.segment_sum`` under ``"xla"``,
    :func:`pallas_segment_sum` under ``"pallas"``. ``backend=None``
    resolves the gate (env > autotune table > xla); passing a backend
    is an explicit request and refuses unsupported operands loudly.
    Zero-cell and zero-segment inputs always take the XLA path (nothing
    to measure, and the kernel needs >= 1 of each)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu.kernels import _gate

    values = jnp.asarray(values)
    ids = jnp.asarray(ids)
    if values.shape[0] == 0 or num_segments == 0:
        return jax.ops.segment_sum(
            values, ids, num_segments=num_segments,
            indices_are_sorted=indices_are_sorted,
        )
    interpret = _gate.interpret_mode()
    chosen = _gate.resolve_checked(
        "segment_sum",
        unsupported_reason(values, ids, num_segments, interpret),
        backend,
    )
    if chosen == "pallas":
        return pallas_segment_sum(
            values, ids, num_segments,
            indices_are_sorted=indices_are_sorted, interpret=interpret,
        )
    return jax.ops.segment_sum(
        values, ids, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def factory_backend() -> str:
    """The segment-sum backend for a trainer FACTORY to bake into its
    ``functools.lru_cache`` key (the established layout-gate idiom:
    resolve once at fit time, thread down as a static argument, so a
    gate flip re-keys the jitted trainer instead of silently reusing
    the old program)."""
    from flinkml_tpu.kernels import _gate

    return _gate.backend_for("segment_sum")
