"""Pallas padded-ELL segment-sum — the sparse gradient scatter-accumulate.

The sparse trainers' dominant op at Criteo scale is one flat
``segment_sum`` per step: ``contrib [cells]`` (or ``[cells, k]`` for the
row-payload W2V accumulator) scatter-added into ``[num_segments]`` by
``ids [cells]``. XLA lowers the unsorted case through a per-step bitonic
sort over every cell (the round-4 A/B in
:func:`flinkml_tpu.models._linear_sgd._sparse_layout`); this kernel
streams the cells once instead, accumulating into the VMEM-resident
output block:

- **unsorted**: one sequential pass, ``out[ids[j]] += v[j]`` — addition
  order equals XLA's CPU scatter order (element order), so the f32
  result is bit-identical to ``jax.ops.segment_sum``.
- **``indices_are_sorted=True``**: run-flush specialization — a carried
  ``(current id, accumulator)`` pair flushes to ``out`` only at run
  boundaries, turning ``cells`` read-modify-writes of the output into
  ``runs`` predicated stores. Left-to-right addition within a run keeps
  bit-parity with the sorted XLA scatter.

The CELL axis streams through a grid: up to ``BLOCK_CELLS`` cells per
grid step, with the output block revisited (constant index map) so the
accumulator persists across steps — TPU grids iterate sequentially, so
element-order addition is preserved and parity stays bitwise at any
cell count. The sorted run-flush carry rides two tiny extra output refs
(current id + accumulator row) between grid steps, so a run spanning a
block boundary is still added left-to-right and flushed exactly once.
The remaining supported-shape ceiling (``MAX_COMPILED_CELLS``) is the
OUTPUT block ``num_segments * k``, which must stay VMEM-resident for
the whole pass; the compiled path refuses sizes past it rather than
compiling something that spills. The device re-tune (bench stage
``pallas``) decides whether this beats XLA's scatter on hardware — the
gate (:mod:`flinkml_tpu.kernels._gate`) keeps XLA the default until a
measured win is committed.
"""

from __future__ import annotations

import functools
from typing import Optional

#: Supported-shape ceiling for the COMPILED (non-interpret) path, in
#: cells of the OUTPUT block (``num_segments * k``): the segment axis
#: must fit one VMEM block; the cell axis streams through the grid and
#: has no ceiling.
MAX_COMPILED_CELLS = 1 << 22

#: Cells per grid step. One block up to here (the committed-measurement
#: shape); larger inputs grid over ``ceil(cells / BLOCK_CELLS)`` steps.
BLOCK_CELLS = 1 << 19

_FLOAT_KINDS = "f"  # jnp dtype.kind for floating


def unsupported_reason(values, ids, num_segments: int,
                       interpret: bool) -> Optional[str]:
    """Why the Pallas kernel cannot run these operands (None = it can).
    The wording lands verbatim in :class:`KernelUnsupportedError`."""
    import jax.numpy as jnp

    v = jnp.asarray(values) if not hasattr(values, "dtype") else values
    i = jnp.asarray(ids) if not hasattr(ids, "dtype") else ids
    if v.ndim not in (1, 2):
        return f"values must be [cells] or [cells, k], got rank {v.ndim}"
    if i.ndim != 1:
        return f"ids must be [cells], got rank {i.ndim}"
    if v.shape[0] != i.shape[0]:
        return f"values rows {v.shape[0]} != ids rows {i.shape[0]}"
    if not jnp.issubdtype(v.dtype, jnp.floating):
        return (f"values dtype {v.dtype} is not floating (supported: "
                "bfloat16/float32, + float64 under the interpreter)")
    if not jnp.issubdtype(i.dtype, jnp.integer):
        return f"ids dtype {i.dtype} is not integer"
    if num_segments < 1:
        return f"num_segments must be >= 1, got {num_segments}"
    if not interpret:
        if v.dtype == jnp.float64:
            return "float64 is interpreter-only (TPU has no f64 lanes)"
        k = 1 if v.ndim == 1 else v.shape[1]
        if num_segments * k > MAX_COMPILED_CELLS:
            return (f"output block num_segments*k = {num_segments * k} "
                    f"exceeds the one-block compiled ceiling of "
                    f"{MAX_COMPILED_CELLS} (MAX_COMPILED_CELLS); the "
                    "grid streams the cell axis, but the segment axis "
                    "must fit one VMEM-resident block")
    return None


def _unsorted_body(ids_ref, val_ref, out_ref):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    out_ref[...] = jnp.zeros_like(out_ref)
    cells = val_ref.shape[0]

    def body(j, carry):
        idx = ids_ref[j]
        out_ref[pl.ds(idx, 1), :] = (
            out_ref[pl.ds(idx, 1), :] + val_ref[pl.ds(j, 1), :]
        )
        return carry

    jax.lax.fori_loop(0, cells, body, 0)


def _sorted_body(ids_ref, val_ref, out_ref):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    out_ref[...] = jnp.zeros_like(out_ref)
    cells = val_ref.shape[0]

    def body(j, carry):
        cur, acc = carry
        idx = ids_ref[j]
        v = val_ref[pl.ds(j, 1), :][0]
        flush = idx != cur

        @pl.when(flush)
        def _():
            out_ref[pl.ds(cur, 1), :] = (
                out_ref[pl.ds(cur, 1), :] + acc[None, :]
            )

        return idx, jnp.where(flush, v, acc + v)

    cur, acc = jax.lax.fori_loop(
        0, cells, body,
        (ids_ref[0], jnp.zeros_like(val_ref[pl.ds(0, 1), :][0])),
    )
    out_ref[pl.ds(cur, 1), :] = out_ref[pl.ds(cur, 1), :] + acc[None, :]


def _unsorted_grid_body(ids_ref, val_ref, out_ref, *, total_cells: int):
    # Multi-block variant: the output block has a constant index map, so
    # it stays resident while the grid walks cell blocks sequentially —
    # addition order is still element order, parity stays bitwise. The
    # padded tail cells (last block only) are predicated off entirely
    # instead of relying on id-0/value-0 no-op adds, which could flip a
    # -0.0 accumulator to +0.0.
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    block = val_ref.shape[0]

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    def body(j, carry):
        @pl.when(i * block + j < total_cells)
        def _():
            idx = ids_ref[j]
            out_ref[pl.ds(idx, 1), :] = (
                out_ref[pl.ds(idx, 1), :] + val_ref[pl.ds(j, 1), :]
            )
        return carry

    jax.lax.fori_loop(0, block, body, 0)


def _sorted_grid_body(ids_ref, val_ref, out_ref, carry_id_ref,
                      carry_acc_ref, *, total_cells: int):
    # Multi-block run-flush: the (current id, accumulator) carry lives in
    # two tiny revisited output refs between grid steps, so a run that
    # spans a block boundary keeps accumulating left-to-right and is
    # flushed exactly once — the per-cell op tree is identical to the
    # single-block body, which keeps parity with the sorted XLA scatter
    # bitwise. The last block does the final flush; earlier blocks park
    # the carry instead.
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    last = pl.num_programs(0) - 1
    block = val_ref.shape[0]

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)
        carry_id_ref[0, 0] = ids_ref[0]
        carry_acc_ref[0, :] = jnp.zeros_like(carry_acc_ref[0, :])

    def body(j, carry):
        cur, acc = carry
        valid = i * block + j < total_cells
        idx = ids_ref[j]
        v = val_ref[pl.ds(j, 1), :][0]
        flush = (idx != cur) & valid

        @pl.when(flush)
        def _():
            out_ref[pl.ds(cur, 1), :] = (
                out_ref[pl.ds(cur, 1), :] + acc[None, :]
            )

        ncur = jnp.where(valid, idx, cur)
        nacc = jnp.where(valid, jnp.where(flush, v, acc + v), acc)
        return ncur, nacc

    cur, acc = jax.lax.fori_loop(
        0, block, body, (carry_id_ref[0, 0], carry_acc_ref[0, :])
    )

    @pl.when(i == last)
    def _():
        out_ref[pl.ds(cur, 1), :] = (
            out_ref[pl.ds(cur, 1), :] + acc[None, :]
        )

    @pl.when(i != last)
    def _():
        carry_id_ref[0, 0] = cur
        carry_acc_ref[0, :] = acc


def pallas_segment_sum(values, ids, num_segments: int, *,
                       indices_are_sorted: bool = False,
                       interpret: Optional[bool] = None):
    """The Pallas scatter-accumulate (module docstring). Same contract
    as ``jax.ops.segment_sum(values, ids, num_segments,
    indices_are_sorted=...)`` for in-range ids; out-of-range ids are the
    caller's bug on both backends (padding rides the ELL convention:
    index 0 / value 0 is a no-op add). Unsupported operands raise
    :class:`KernelUnsupportedError` — direct callers get the same typed
    refusal as the gated dispatcher, with the same wording."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from flinkml_tpu.kernels import _gate

    if interpret is None:
        interpret = _gate.interpret_mode()
    reason = unsupported_reason(values, ids, num_segments, interpret)
    if reason is not None:
        raise _gate.KernelUnsupportedError(
            f"kernels[segment_sum]: pallas_segment_sum cannot run these "
            f"operands: {reason}"
        )
    flat = values.ndim == 1
    v2 = values[:, None] if flat else values
    cells, k = v2.shape
    ids32 = ids.astype(jnp.int32)
    if cells <= BLOCK_CELLS:
        body = _sorted_body if indices_are_sorted else _unsorted_body
        out = pl.pallas_call(
            body,
            in_specs=[
                pl.BlockSpec((cells,), lambda: (0,)),
                pl.BlockSpec((cells, k), lambda: (0, 0)),
            ],
            out_specs=pl.BlockSpec((num_segments, k), lambda: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((num_segments, k), v2.dtype),
            interpret=interpret,
        )(ids32, v2)
        return out[:, 0] if flat else out
    grid = pl.cdiv(cells, BLOCK_CELLS)
    pad = grid * BLOCK_CELLS - cells
    if pad:
        # Padding is predicated off inside the bodies (total_cells);
        # zeros here only square up the block shape.
        ids32 = jnp.concatenate([ids32, jnp.zeros((pad,), jnp.int32)])
        v2 = jnp.concatenate([v2, jnp.zeros((pad, k), v2.dtype)])
    in_specs = [
        pl.BlockSpec((BLOCK_CELLS,), lambda i: (i,)),
        pl.BlockSpec((BLOCK_CELLS, k), lambda i: (i, 0)),
    ]
    out_spec = pl.BlockSpec((num_segments, k), lambda i: (0, 0))
    if indices_are_sorted:
        out, _, _ = pl.pallas_call(
            functools.partial(_sorted_grid_body, total_cells=cells),
            grid=(grid,),
            in_specs=in_specs,
            out_specs=(
                out_spec,
                pl.BlockSpec((1, 1), lambda i: (0, 0)),
                pl.BlockSpec((1, k), lambda i: (0, 0)),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((num_segments, k), v2.dtype),
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
                jax.ShapeDtypeStruct((1, k), v2.dtype),
            ),
            interpret=interpret,
        )(ids32, v2)
    else:
        out = pl.pallas_call(
            functools.partial(_unsorted_grid_body, total_cells=cells),
            grid=(grid,),
            in_specs=in_specs,
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((num_segments, k), v2.dtype),
            interpret=interpret,
        )(ids32, v2)
    return out[:, 0] if flat else out


def segment_sum(values, ids, num_segments: int, *,
                indices_are_sorted: bool = False,
                backend: Optional[str] = None):
    """The gated dispatcher: ``jax.ops.segment_sum`` under ``"xla"``,
    :func:`pallas_segment_sum` under ``"pallas"``. ``backend=None``
    resolves the gate (env > autotune table > xla); passing a backend
    is an explicit request and refuses unsupported operands loudly.
    Zero-cell and zero-segment inputs always take the XLA path (nothing
    to measure, and the kernel needs >= 1 of each)."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu.kernels import _gate

    values = jnp.asarray(values)
    ids = jnp.asarray(ids)
    if values.shape[0] == 0 or num_segments == 0:
        return jax.ops.segment_sum(
            values, ids, num_segments=num_segments,
            indices_are_sorted=indices_are_sorted,
        )
    interpret = _gate.interpret_mode()
    chosen = _gate.resolve_checked(
        "segment_sum",
        unsupported_reason(values, ids, num_segments, interpret),
        backend,
    )
    if chosen == "pallas":
        return pallas_segment_sum(
            values, ids, num_segments,
            indices_are_sorted=indices_are_sorted, interpret=interpret,
        )
    return jax.ops.segment_sum(
        values, ids, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def factory_backend() -> str:
    """The segment-sum backend for a trainer FACTORY to bake into its
    ``functools.lru_cache`` key (the established layout-gate idiom:
    resolve once at fit time, thread down as a static argument, so a
    gate flip re-keys the jitted trainer instead of silently reusing
    the old program)."""
    from flinkml_tpu.kernels import _gate

    return _gate.backend_for("segment_sum")
