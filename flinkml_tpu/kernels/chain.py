"""Pallas fused transform chain — one kernel per row bucket.

The fused executor (:mod:`flinkml_tpu.pipeline_fusion`) compiles a run
of kernel-capable stages into ONE ``jax.jit`` program; under XLA the
per-bucket program is a fused jaxpr that XLA re-schedules per bucket.
This module lowers the same chain as ONE Pallas kernel instead: the
grid walks row tiles of the bucket, each ``[TILE, …]`` block of every
external input column stays VMEM-resident while the scaler/assembler/
encoder/model stages run back-to-back on it, the validity mask is built
in-kernel from the traced row count (``rows < n`` per tile — identical
values to the XLA chain's ``arange(bucket) < n``), and each output
column's tile is stored once at the end. Model constants ride as full
(untiled) blocks, so model-data refreshes reuse the compiled kernel
exactly like the XLA path.

Semantics are pinned to :func:`flinkml_tpu.pipeline_fusion._chain_fn`:

- same policy boundary — a mixed :class:`PrecisionPolicy` casts float
  externals/constants to ``policy.compute`` BEFORE the kernel and
  builds the mask at ``policy.compute``;
- same trace-time policy pinning (kernel fns resolve
  ``active_policy()`` while tracing — inside the Pallas body that trace
  happens under the captured policy, never the reader thread's);
- row-local ops are bit-identical under the interpreter (elementwise
  and per-row reductions do not see the tiling); the f32 matmul
  carve-out documented on the executor applies to compiled TPU runs.

No ``optimization_barrier`` between stages: stages run inside one
Mosaic kernel where XLA's cross-stage algebraic rewriting (the thing
the barrier fences) never happens, and the interpreter evaluates the
ops stage-by-stage anyway.

Supported shapes/dtypes (the refusal surface — see
``docs/development/kernels.md``): >= 1 kernel; every external input,
constant, and output row-leading or constant-shaped with dtype kind in
f/i/u/b; no weak-typed (python-scalar) constants — Pallas refs are
strong-typed and would change jnp promotion; float64 only under the
interpreter; bucket divisible by the row tile (always true — buckets
are powers of two >= 8).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

#: Row-tile ceiling: small buckets run as one tile (shape-identical to
#: the XLA program); larger buckets tile at 128 rows (MXU-friendly).
MAX_ROW_TILE = 128


def row_tile(bucket: int) -> int:
    return bucket if bucket <= MAX_ROW_TILE else MAX_ROW_TILE


def _sorted_consts(kernel) -> Tuple[str, ...]:
    return tuple(sorted(kernel.constants))


def _apply_chain(kernels, ext_names, out_names, ext_arrays, const_arrays,
                 valid):
    """The chain math, shared by the eval-shape probe and the kernel
    body — the SAME per-kernel call protocol as ``_chain_fn`` (consts
    sorted by name; each kernel sees exactly its input columns)."""
    cols = dict(zip(ext_names, ext_arrays))
    for kernel, cv in zip(kernels, const_arrays):
        consts = dict(zip(_sorted_consts(kernel), cv))
        outs = kernel.fn(
            {c: cols[c] for c in kernel.input_cols}, consts, valid
        )
        cols.update(outs)
    return tuple(cols[c] for c in out_names)


def _eval_out_struct(kernels, ext_names, out_names, bucket, policy,
                     ext_vals, const_vals, mask_dt):
    """Abstract output specs of the (policy-cast) chain, traced under
    the captured policy exactly as the real program will be."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu import pipeline_fusion as pf

    prev = pf.active_policy()
    pf._POLICY.value = policy
    try:
        return jax.eval_shape(
            lambda e, c: _apply_chain(
                kernels, ext_names, out_names, e, c,
                jnp.zeros((bucket,), mask_dt),
            ),
            tuple(ext_vals), tuple(const_vals),
        )
    finally:
        pf._POLICY.value = prev


def _mask_dtype(policy):
    import jax.numpy as jnp

    mixed = policy is not None and policy.mixed
    return jnp.dtype(policy.compute_dtype) if mixed else jnp.float32


def _cast_boundary(policy, ext_vals, const_vals):
    """The sanctioned program-boundary down-cast — identical to
    ``_chain_fn``'s ``_to_compute`` over externals and constants."""
    import jax.numpy as jnp

    mixed = policy is not None and policy.mixed
    if not mixed:
        return tuple(ext_vals), tuple(tuple(cv) for cv in const_vals)
    dt = jnp.dtype(policy.compute_dtype)

    def to_compute(v):
        if jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(dt)
        return v

    return (
        tuple(to_compute(v) for v in ext_vals),
        tuple(tuple(to_compute(v) for v in cv) for cv in const_vals),
    )


def unsupported_reason(kernels, ext_names: Sequence[str],
                       out_names: Sequence[str], bucket: int, policy,
                       ext_vals, const_vals,
                       interpret: bool) -> Optional[str]:
    """Why the Pallas chain cannot run this program (None = it can).
    Checked only when the gate resolves to ``pallas`` — the default-off
    path never pays the abstract trace."""
    import jax.numpy as jnp

    if not kernels:
        return "empty chain"
    for kernel, cv in zip(kernels, const_vals):
        for name, v in zip(_sorted_consts(kernel), cv):
            if getattr(v, "weak_type", False):
                return (
                    f"constant {name!r} of {type(kernel).__name__} is "
                    "weak-typed (python-scalar model datum) — Pallas "
                    "refs are strong-typed and would change promotion"
                )
            if not interpret and v.dtype == jnp.float64:
                return (f"constant {name!r} is float64 — "
                        "interpreter-only (TPU has no f64 lanes)")
    for name, v in zip(ext_names, ext_vals):
        if v.dtype.kind not in "fiub":
            return f"input column {name!r} has dtype {v.dtype}"
        if not interpret and v.dtype == jnp.float64:
            return (f"input column {name!r} is float64 — "
                    "interpreter-only (TPU has no f64 lanes)")
    mask_dt = _mask_dtype(policy)
    ext_c, const_c = _cast_boundary(policy, ext_vals, const_vals)
    try:
        out_struct = _eval_out_struct(
            kernels, tuple(ext_names), tuple(out_names), bucket, policy,
            ext_c, const_c, mask_dt,
        )
    except Exception as e:  # noqa: BLE001 — the reason IS the refusal
        return f"chain does not abstract-trace: {type(e).__name__}: {e}"
    for name, s in zip(out_names, out_struct):
        if s.ndim == 0 or s.shape[0] != bucket:
            return (f"output {name!r} is not row-leading "
                    f"(shape {s.shape}, bucket {bucket}) — cross-row "
                    "kernels have no Pallas chain path")
        if not interpret and s.dtype == jnp.float64:
            return (f"output {name!r} is float64 — interpreter-only "
                    "(TPU has no f64 lanes)")
    return None


def pallas_chain_fn(kernels, ext_names: Sequence[str],
                    out_names: Sequence[str], bucket: int, policy=None):
    """Drop-in replacement for ``pipeline_fusion._chain_fn`` — the same
    ``run(ext_vals, const_vals, n_valid) -> {col: array}`` contract,
    lowered through one row-tiled ``pallas_call`` per program."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from flinkml_tpu import pipeline_fusion as pf
    from flinkml_tpu.kernels import _gate

    kernels = tuple(kernels)
    ext_names = tuple(ext_names)
    out_names = tuple(out_names)
    mask_dt = _mask_dtype(policy)
    tile = row_tile(bucket)
    interpret = _gate.interpret_mode()

    def run(ext_vals, const_vals, n_valid):
        # Pin the captured policy for the whole trace (same rationale as
        # _chain_fn: kernel fns resolve active_policy() at trace time,
        # and a lazy column may trace on another thread).
        prev = pf.active_policy()
        pf._POLICY.value = policy
        try:
            ext_c, const_c = _cast_boundary(policy, ext_vals, const_vals)
            out_struct = _eval_out_struct(
                kernels, ext_names, out_names, bucket, policy,
                ext_c, const_c, mask_dt,
            )
            for name, s in zip(out_names, out_struct):
                if s.ndim == 0 or s.shape[0] != bucket:
                    raise _gate.KernelUnsupportedError(
                        f"kernels[fused_chain]: output {name!r} is not "
                        f"row-leading (shape {s.shape}, bucket {bucket})"
                    )

            # Flatten constants; 0-d scalars ride as (1,) blocks and are
            # restored inside the body (Pallas blocks are >= 1-d).
            flat_consts, was_scalar, split = [], [], []
            for cv in const_c:
                split.append(len(cv))
                for v in cv:
                    was_scalar.append(v.ndim == 0)
                    flat_consts.append(v.reshape(1) if v.ndim == 0 else v)
            n_ext, n_const = len(ext_c), len(flat_consts)

            def body(n_ref, *refs):
                ext_refs = refs[:n_ext]
                const_refs = refs[n_ext:n_ext + n_const]
                out_refs = refs[n_ext + n_const:]
                i = pl.program_id(0)
                rows = jax.lax.broadcasted_iota(
                    jnp.int32, (tile, 1), 0
                )[:, 0] + i * tile
                valid = (rows < n_ref[0]).astype(mask_dt)
                ext_arrays = tuple(r[...] for r in ext_refs)
                flat = [
                    r[...][0] if scalar else r[...]
                    for r, scalar in zip(const_refs, was_scalar)
                ]
                const_arrays, pos = [], 0
                for count in split:
                    const_arrays.append(tuple(flat[pos:pos + count]))
                    pos += count
                outs = _apply_chain(
                    kernels, ext_names, out_names, ext_arrays,
                    tuple(const_arrays), valid,
                )
                for o_ref, o in zip(out_refs, outs):
                    o_ref[...] = o

            def tiled(shape):
                trailing = tuple(shape[1:])
                zeros = (0,) * len(trailing)
                return pl.BlockSpec(
                    (tile,) + trailing, lambda i, _z=zeros: (i,) + _z
                )

            def full(shape):
                zeros = (0,) * len(shape)
                return pl.BlockSpec(
                    tuple(shape), lambda i, _z=zeros: _z
                )

            outs = pl.pallas_call(
                body,
                grid=(bucket // tile,),
                in_specs=(
                    [full((1,))]
                    + [tiled(v.shape) for v in ext_c]
                    + [full(v.shape) for v in flat_consts]
                ),
                out_specs=tuple(tiled(s.shape) for s in out_struct),
                out_shape=tuple(
                    jax.ShapeDtypeStruct(s.shape, s.dtype)
                    for s in out_struct
                ),
                interpret=interpret,
            )(
                jnp.asarray(n_valid, jnp.int32).reshape(1),
                *ext_c, *flat_consts,
            )
            return dict(zip(out_names, outs))
        finally:
            pf._POLICY.value = prev

    return run
