"""The kernel-backend gate — the established measured-default idiom
(:func:`flinkml_tpu.models._linear_sgd._sparse_layout`) applied to the
choice between XLA's lowering and the hand-written Pallas kernels.

Four *sites* exist, one per hot inner loop:

- ``fused_chain``  — the fused pipeline executor's per-bucket chain
  program (:mod:`flinkml_tpu.kernels.chain`),
- ``segment_sum``  — the padded-ELL sparse gradient scatter-accumulate
  shared by the linear SGD trainers, ``BatchedCSR.rmatvec``, and the
  Word2Vec embedding accumulator (:mod:`flinkml_tpu.kernels.segsum`),
- ``spmv``         — the padded-ELL CSR matvec behind the sparse
  trainers' forward margins and ``BatchedCSR.matvec``
  (:mod:`flinkml_tpu.kernels.spmv`),
- ``topk``         — the bucketed top-k behind KNN voting and LSH
  candidate ranking (:mod:`flinkml_tpu.kernels.topk`).

Lookup precedence per site (exactly the sort-class layout gates'):
``FLINKML_TPU_KERNELS`` env var > the mesh-keyed autotune table's
``kernel_backend_<site>`` knob > the static default ``"xla"``. The env
var takes either one backend for every site (``pallas``/``xla``) or a
per-site list (``fused_chain=pallas,topk=xla``); anything else raises.

Refusal contract: a Pallas backend selected EXPLICITLY (env var or a
``backend=`` argument) refuses unsupported dtypes/shapes LOUDLY with
:class:`KernelUnsupportedError` — never a silent wrong-numerics
fallback. A Pallas backend that came from the tuning table degrades to
``"xla"`` with one warning (a committed table must never take training
down — the same never-crash discipline as a stale autotune entry).

Resolved backends are cached per (env value, site) — the lru key every
consumer must thread into ITS compile cache: the fused executor's
program key, the trainer factories' ``functools.lru_cache`` keys, and
``jax.jit`` static args all carry the backend, so flipping the gate can
never alias a Pallas program with an XLA one.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Optional, Tuple

from flinkml_tpu.utils.logging import get_logger

_log = get_logger("kernels")

#: The four gated sites (one per hot inner loop — module docstring).
SITES = ("fused_chain", "segment_sum", "spmv", "topk")

#: Known backends. ``xla`` is the static default everywhere; ``pallas``
#: must win a measured A/B (the autotune ``kernel_backend_*`` knobs) or
#: be asked for explicitly.
BACKENDS = ("xla", "pallas")

#: Env gate: one backend for all sites, or ``site=backend`` pairs.
ENV_VAR = "FLINKML_TPU_KERNELS"

#: Force/forbid interpreter-mode ``pallas_call`` (default: interpret on
#: every non-TPU backend so CPU CI runs the kernels device-free).
ENV_INTERPRET_VAR = "FLINKML_TPU_KERNELS_INTERPRET"

#: The autotune knob family (``kernel_backend_<site>``).
KNOB_PREFIX = "kernel_backend_"

_WARNED: set = set()


class KernelUnsupportedError(ValueError):
    """An explicitly-requested Pallas kernel cannot run this dtype/shape.

    Raised INSTEAD of silently falling back: the caller asked for the
    Pallas backend by name (env var or argument), so degrading quietly
    would misreport what was measured. The message names the site, the
    offending dtype/shape, and the supported set.
    """


@functools.lru_cache(maxsize=64)
def _parse_env(raw: str) -> Dict[str, str]:
    """``FLINKML_TPU_KERNELS`` → ``{site: backend}`` (``"*"`` = every
    site). Raises ``ValueError`` on unknown sites/backends — a typo'd
    gate must fail loudly, not silently select the default."""
    raw = raw.strip()
    if not raw:
        return {}
    if "=" not in raw:
        if raw not in BACKENDS:
            raise ValueError(
                f"{ENV_VAR}={raw!r}: expected one of {BACKENDS} or "
                f"site=backend pairs over sites {SITES}"
            )
        return {"*": raw}
    out: Dict[str, str] = {}
    for pair in raw.split(","):
        site, _, backend = pair.partition("=")
        site, backend = site.strip(), backend.strip()
        if site not in SITES or backend not in BACKENDS:
            raise ValueError(
                f"{ENV_VAR}={raw!r}: bad pair {pair!r} — sites {SITES}, "
                f"backends {BACKENDS}"
            )
        out[site] = backend
    return out


def resolve_backend(site: str) -> Tuple[str, bool]:
    """``(backend, explicit)`` for ``site``: the env var wins (explicit),
    then the current mesh's ``kernel_backend_<site>`` autotune entry
    (not explicit), then ``"xla"``."""
    if site not in SITES:
        raise ValueError(f"unknown kernel site {site!r}; known: {SITES}")
    env = _parse_env(os.environ.get(ENV_VAR, ""))
    chosen = env.get(site, env.get("*"))
    if chosen is not None:
        return chosen, True
    from flinkml_tpu.autotune import tuned_default

    return tuned_default(KNOB_PREFIX + site, "xla", allowed=BACKENDS), False


def backend_for(site: str) -> str:
    """The resolved backend name for ``site`` (gate precedence in the
    module docstring), ignoring per-call support — use the site
    dispatchers for a support-checked choice."""
    return resolve_backend(site)[0]


def interpret_mode() -> bool:
    """Whether ``pallas_call`` should run under the interpreter: yes on
    every non-TPU backend (CPU CI stays device-free), overridable with
    ``FLINKML_TPU_KERNELS_INTERPRET=0/1`` (device runs can force the
    interpreter for a parity bisect)."""
    forced = os.environ.get(ENV_INTERPRET_VAR)
    if forced is not None:
        if forced not in ("0", "1"):
            raise ValueError(
                f"{ENV_INTERPRET_VAR}={forced!r}: expected '0' or '1'"
            )
        return forced == "1"
    import jax

    return jax.default_backend() != "tpu"


def refuse_or_fallback(site: str, explicit: bool, reason: str) -> str:
    """The refusal contract: explicit Pallas + unsupported → raise
    :class:`KernelUnsupportedError`; table-chosen Pallas + unsupported
    → one warning, then ``"xla"``."""
    if explicit:
        raise KernelUnsupportedError(
            f"kernels[{site}]: the pallas backend was requested "
            f"explicitly but cannot run here: {reason}. Unset "
            f"{ENV_VAR} (or pass backend='xla') to use the XLA lowering."
        )
    tag = (site, reason)
    if tag not in _WARNED:
        _WARNED.add(tag)
        _log.warning(
            "kernels[%s]: tuning table selected pallas but %s; using the "
            "XLA lowering for this site", site, reason,
        )
    return "xla"


def resolve_checked(site: str, unsupported_reason: Optional[str],
                    backend: Optional[str] = None) -> str:
    """Gate resolution + the support check in one step.

    A ``backend`` argument that merely THREADS THROUGH what the gate
    itself currently resolves (the factory idiom: consumers resolve
    once at fit time and pass the result down as lru-key material)
    inherits the gate's own explicitness — a table-chosen pallas still
    degrades warn-once on unsupported operands instead of crashing the
    consumer. A backend that DISAGREES with the gate is a genuinely
    explicit per-call request and refuses loudly."""
    gate_backend, gate_explicit = resolve_backend(site)
    if backend is None:
        backend, explicit = gate_backend, gate_explicit
    else:
        if backend not in BACKENDS:
            raise ValueError(
                f"backend={backend!r}: expected one of {BACKENDS}"
            )
        explicit = True if backend != gate_backend else gate_explicit
    if backend == "pallas" and unsupported_reason is not None:
        return refuse_or_fallback(site, explicit, unsupported_reason)
    return backend
