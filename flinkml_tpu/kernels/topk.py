"""Pallas bucketed top-k — KNN voting and LSH candidate ranking.

``jax.lax.top_k`` over a ``[nq, n]`` distance matrix sorts every row's
full n-vector to keep k of it. For the small k the neighbor queries use
(k ≪ n), k passes of a masked row-max over a VMEM-resident tile do the
same work as k sweeps of the VPU with no sort network: the kernel tiles
the query rows (grid over ``rows / TILE``), keeps each ``[TILE, n]``
block resident, and per pass records the row max + its first index, then
masks exactly that column out. Selected values are exact copies of input
elements and ``argmax`` takes the FIRST maximum, so values AND indices
are bit-identical to ``lax.top_k`` (both break ties toward the lower
index).

The gate (:mod:`flinkml_tpu.kernels._gate`, site ``topk``) keeps XLA the
default; the bench's ``pallas[_cpu]`` stage measures the ratio and the
device re-tune decides. Callers thread the resolved backend into their
``jax.jit`` static args (``knn._knn_vote``) so a gate flip re-keys the
program instead of silently reusing the old one.
"""

from __future__ import annotations

from typing import Optional, Tuple

#: Query-row tile (grid unit). 8 = f32 sublane count; rows pad up to a
#: multiple with -inf rows that are sliced off after the call.
ROW_TILE = 8

#: k passes unroll into the kernel body; beyond this the unrolled body
#: stops being the cheap path and a sort is the right tool — refuse.
MAX_K = 128


def unsupported_reason(x, k: int, interpret: bool) -> Optional[str]:
    """Why the Pallas kernel cannot rank these operands (None = it can)."""
    import jax.numpy as jnp

    if x.ndim not in (1, 2):
        return f"operand must be [n] or [rows, n], got rank {x.ndim}"
    n = x.shape[-1]
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return (f"operand dtype {x.dtype} is not floating (the mask "
                "sentinel is -inf; integer ranking has no Pallas path)")
    if not 1 <= k <= n:
        return f"k={k} outside [1, n={n}]"
    if k > MAX_K:
        return f"k={k} exceeds the unrolled-pass ceiling of {MAX_K}"
    if not interpret and x.dtype == jnp.float64:
        return "float64 is interpreter-only (TPU has no f64 lanes)"
    return None


def _topk_body(x_ref, val_ref, idx_ref, *, k: int):
    import jax
    import jax.numpy as jnp

    work = x_ref[...]
    col = jax.lax.broadcasted_iota(jnp.int32, work.shape, 1)
    neg_inf = jnp.full_like(work, -jnp.inf)
    # Selected columns are excluded via a taken-mask, NOT by overwriting
    # the value with -inf: a row whose remaining entries ARE -inf would
    # then re-select column 0 forever instead of walking the untaken
    # -inf entries in ascending index order the way lax.top_k does.
    taken = jnp.zeros(work.shape, jnp.bool_)
    for j in range(k):
        cand = jnp.where(taken, neg_inf, work)
        m = jnp.max(cand, axis=1)
        a = jnp.argmax(cand, axis=1).astype(jnp.int32)
        # All untaken entries at -inf: the masked and unmasked values
        # tie, so argmax must not land on an already-taken column —
        # take the first UNTAKEN index instead.
        first_untaken = jnp.argmax(~taken, axis=1).astype(jnp.int32)
        a = jnp.where(jnp.isneginf(m), first_untaken, a)
        val_ref[:, j] = m
        idx_ref[:, j] = a
        taken = taken | (col == a[:, None])


def pallas_top_k(x, k: int, *, interpret: Optional[bool] = None) -> Tuple:
    """``(values, indices)`` of the k largest entries of each row of
    ``x`` — bit-compatible with ``jax.lax.top_k(x, k)`` (descending
    values, ties toward the lower index, int32 indices)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from flinkml_tpu.kernels import _gate

    if interpret is None:
        interpret = _gate.interpret_mode()
    squeeze = x.ndim == 1
    x2 = x[None, :] if squeeze else x
    rows, n = x2.shape
    pad = (-rows) % ROW_TILE
    if pad:
        x2 = jnp.concatenate(
            [x2, jnp.full((pad, n), -jnp.inf, x2.dtype)]
        )
    grid = (x2.shape[0] // ROW_TILE,)
    vals, idxs = pl.pallas_call(
        functools.partial(_topk_body, k=k),
        grid=grid,
        in_specs=[pl.BlockSpec((ROW_TILE, n), lambda i: (i, 0))],
        out_specs=(
            pl.BlockSpec((ROW_TILE, k), lambda i: (i, 0)),
            pl.BlockSpec((ROW_TILE, k), lambda i: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((x2.shape[0], k), x2.dtype),
            jax.ShapeDtypeStruct((x2.shape[0], k), jnp.int32),
        ),
        interpret=interpret,
    )(x2)
    if pad:
        vals, idxs = vals[:rows], idxs[:rows]
    if squeeze:
        vals, idxs = vals[0], idxs[0]
    return vals, idxs


def top_k(x, k: int, *, backend: Optional[str] = None) -> Tuple:
    """The gated dispatcher: ``jax.lax.top_k`` under ``"xla"``, the
    masked-pass kernel under ``"pallas"``. ``backend=None`` resolves the
    gate (env > autotune table > xla); a passed backend is an explicit
    request and refuses unsupported operands loudly."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu.kernels import _gate

    x = jnp.asarray(x)
    interpret = _gate.interpret_mode()
    chosen = _gate.resolve_checked(
        "topk", unsupported_reason(x, k, interpret), backend,
    )
    if chosen == "pallas":
        return pallas_top_k(x, k, interpret=interpret)
    return jax.lax.top_k(x, k)


def factory_backend() -> str:
    """The resolved topk backend for callers that bake it into a jit
    static argument (the lru-key idiom — see the gate module)."""
    from flinkml_tpu.kernels import _gate

    return _gate.backend_for("topk")
