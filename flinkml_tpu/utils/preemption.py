"""Preemption watchdog: SIGTERM/soft-deadline → final checkpoint + drain.

TPU preemption is the canonical failure mode this framework targets: the
scheduler sends SIGTERM, grants a short grace window, then kills the VM.
The reference survives the analogous TaskManager loss through Flink's
checkpoint coordinator; here the contract is host-side and explicit:

  1. :class:`PreemptionWatchdog` installs signal handlers (and/or a
     soft-deadline timer) that set a **flag** — handlers do no work, so
     they are async-signal-safe and never interrupt a collective
     mid-flight.
  2. Every :func:`flinkml_tpu.iteration.iterate` loop polls the flag at
     its epoch boundary (the only globally consistent point in SPMD
     lockstep). On preemption the loop stops cleanly, commits one final
     checkpoint through its configured manager, and marks its result
     ``preempted=True`` — a later ``resume=True`` run continues
     bit-exactly.
  3. The loop then calls :meth:`finalize`, which drains every registered
     :class:`~flinkml_tpu.serving.engine.ServingEngine`
     (``stop(drain=True)``: in-flight requests finish, new ones are
     rejected) so serving responses are never cut off mid-batch.

Use it scoped::

    with PreemptionWatchdog(soft_deadline_s=3500) as wd:
        wd.register_engine(engine)
        model = online_lr.fit_stream(stream, checkpoint_manager=mgr,
                                     checkpoint_interval=50)

Any ``iterate``-based loop inside the ``with`` observes the watchdog via
:func:`active` — no per-trainer plumbing needed (an explicit
``IterationConfig.watchdog`` overrides the ambient one).
"""

from __future__ import annotations

import signal
import threading
from typing import Any, List, Optional, Sequence

from flinkml_tpu.utils.logging import get_logger

_log = get_logger("preemption")

_ACTIVE: Optional["PreemptionWatchdog"] = None


def active() -> Optional["PreemptionWatchdog"]:
    """The installed watchdog (what ``iterate`` polls), or None."""
    return _ACTIVE


class PreemptionWatchdog:
    """See module docstring.

    Args:
        signals: signals to trap while installed (default: SIGTERM).
            Installation is skipped with a warning off the main thread
            (CPython restriction); :meth:`request` still works there.
        soft_deadline_s: optionally also request preemption after this
            many seconds — the belt-and-suspenders for schedulers that
            kill without signaling.
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,),
                 soft_deadline_s: Optional[float] = None):
        self.signals = tuple(signals)
        self.soft_deadline_s = soft_deadline_s
        self._event = threading.Event()
        self._engines: List[Any] = []
        self._prev_handlers: dict = {}
        self._timer: Optional[threading.Timer] = None
        self._finalized = False
        self.reason: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "PreemptionWatchdog":
        global _ACTIVE
        for sig in self.signals:
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError:  # not the main thread
                _log.warning(
                    "cannot trap signal %s off the main thread; relying on "
                    "request()/soft deadline only", sig,
                )
        if self.soft_deadline_s is not None:
            self._timer = threading.Timer(
                self.soft_deadline_s,
                lambda: self.request(
                    f"soft deadline ({self.soft_deadline_s}s) reached"
                ),
            )
            self._timer.daemon = True
            self._timer.start()
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev_handlers.clear()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if _ACTIVE is self:
            _ACTIVE = None

    __enter__ = install

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- preemption request ------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        # Async-signal-safe: set the flag, nothing else. The training
        # loop observes it at its next epoch boundary.
        self.reason = f"signal {signum}"
        self._event.set()

    def request(self, reason: str = "manual request") -> None:
        """Programmatic preemption (tests, external health checks)."""
        if not self._event.is_set():
            self.reason = reason
            _log.warning("preemption requested: %s", reason)
        self._event.set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    # -- shutdown actions ----------------------------------------------------
    def register_engine(self, engine: Any) -> None:
        """Serving engines to drain cleanly on preemption (anything with
        ``stop(drain=True)``)."""
        self._engines.append(engine)

    @property
    def finalized(self) -> bool:
        return self._finalized

    def finalize(self) -> None:
        """Drain registered engines; idempotent. Called by the training
        loop AFTER its final checkpoint committed, so the snapshot is
        durable before serving winds down."""
        if self._finalized:
            return
        self._finalized = True
        for engine in self._engines:
            try:
                engine.stop(drain=True)
                _log.info("drained serving engine %r on preemption", engine)
            except Exception as e:  # noqa: BLE001 — drain best-effort
                _log.error("engine drain failed on preemption: %r", e)
