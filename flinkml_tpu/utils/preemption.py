"""Preemption watchdog: SIGTERM/soft-deadline → final checkpoint + drain.

TPU preemption is the canonical failure mode this framework targets: the
scheduler sends SIGTERM, grants a short grace window, then kills the VM.
The reference survives the analogous TaskManager loss through Flink's
checkpoint coordinator; here the contract is host-side and explicit:

  1. :class:`PreemptionWatchdog` installs signal handlers (and/or a
     soft-deadline timer) that set a **flag** — handlers do no work, so
     they are async-signal-safe and never interrupt a collective
     mid-flight.
  2. Every :func:`flinkml_tpu.iteration.iterate` loop polls the flag at
     its epoch boundary (the only globally consistent point in SPMD
     lockstep). On preemption the loop stops cleanly, commits one final
     checkpoint through its configured manager, and marks its result
     ``preempted=True`` — a later ``resume=True`` run continues
     bit-exactly.
  3. The loop then calls :meth:`finalize`, which drains every registered
     :class:`~flinkml_tpu.serving.engine.ServingEngine`
     (``stop(drain=True)``: in-flight requests finish, new ones are
     rejected) so serving responses are never cut off mid-batch.

Use it scoped::

    with PreemptionWatchdog(soft_deadline_s=3500) as wd:
        wd.register_engine(engine)
        model = online_lr.fit_stream(stream, checkpoint_manager=mgr,
                                     checkpoint_interval=50)

Any ``iterate``-based loop inside the ``with`` observes the watchdog via
:func:`active` — no per-trainer plumbing needed (an explicit
``IterationConfig.watchdog`` overrides the ambient one).

**Shrink on SIGTERM / rank loss (elastic resume, ISSUE 6).** Losing a
peer host mid-epoch is the same shape as losing this one: the watchdog
additionally tracks LOST PEER RANKS (:meth:`PreemptionWatchdog
.notify_rank_lost` — fed by the orchestrator's health channel, or by the
scripted :class:`~flinkml_tpu.faults.RankLost` fault at the
``rank.lost`` seam). A rank loss requests a clean stop exactly like
SIGTERM — final checkpoint committed, engines drained — and the
SURVIVORS then continue at the shrunken world:

    with PreemptionWatchdog() as wd:
        result = trainer.fit_stream(feed, checkpoint_manager=mgr, ...)
    if wd.shrink_requested:
        plan = wd.plan_elastic_resume(mgr, world=old_world)
        # plan.new_world survivors agree on plan.epoch (the newest
        # commonly-valid snapshot), re-init at world M, resume with a
        # rescale="reshard" manager + an ElasticFeed at plan.new_world.

The agreement rides :func:`flinkml_tpu.parallel.distributed
.agree_resume_epoch` (the existing ``agree_all_ok`` rendezvous +
device-mediated min), exercised by the ``rendezvous.rescale`` fault
seam. See ``docs/development/fault_tolerance.md`` ("Elastic resume").
"""

from __future__ import annotations

import dataclasses
import signal
import threading
from typing import Any, List, Optional, Sequence

from flinkml_tpu.utils.logging import get_logger

_log = get_logger("preemption")


@dataclasses.dataclass(frozen=True)
class ElasticResumePlan:
    """The survivors' agreed shrink/grow decision: resume from snapshot
    ``epoch`` (the newest commonly-valid one; None when no snapshot
    exists anywhere — a fresh start at the new world), moving from
    ``old_world`` ranks to ``new_world``."""

    epoch: Optional[int]
    old_world: int
    new_world: int

_ACTIVE: Optional["PreemptionWatchdog"] = None


def active() -> Optional["PreemptionWatchdog"]:
    """The installed watchdog (what ``iterate`` polls), or None."""
    return _ACTIVE


class PreemptionWatchdog:
    """See module docstring.

    Args:
        signals: signals to trap while installed (default: SIGTERM).
            Installation is skipped with a warning off the main thread
            (CPython restriction); :meth:`request` still works there.
        soft_deadline_s: optionally also request preemption after this
            many seconds — the belt-and-suspenders for schedulers that
            kill without signaling.
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,),
                 soft_deadline_s: Optional[float] = None):
        self.signals = tuple(signals)
        self.soft_deadline_s = soft_deadline_s
        self._event = threading.Event()
        self._engines: List[Any] = []
        self._prev_handlers: dict = {}
        self._timer: Optional[threading.Timer] = None
        self._finalized = False
        self.reason: Optional[str] = None
        #: Peer ranks reported dead (see :meth:`notify_rank_lost`) —
        #: what the elastic shrink path sizes the survivor world from.
        self.lost_ranks: List[int] = []

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "PreemptionWatchdog":
        global _ACTIVE
        for sig in self.signals:
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError:  # not the main thread
                _log.warning(
                    "cannot trap signal %s off the main thread; relying on "
                    "request()/soft deadline only", sig,
                )
        if self.soft_deadline_s is not None:
            self._timer = threading.Timer(
                self.soft_deadline_s,
                lambda: self.request(
                    f"soft deadline ({self.soft_deadline_s}s) reached"
                ),
            )
            self._timer.daemon = True
            self._timer.start()
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev_handlers.clear()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if _ACTIVE is self:
            _ACTIVE = None

    __enter__ = install

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- preemption request ------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        # Async-signal-safe: set the flag, nothing else. The training
        # loop observes it at its next epoch boundary.
        self.reason = f"signal {signum}"
        self._event.set()

    def request(self, reason: str = "manual request") -> None:
        """Programmatic preemption (tests, external health checks)."""
        if not self._event.is_set():
            self.reason = reason
            _log.warning("preemption requested: %s", reason)
        self._event.set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    # -- elastic world changes ----------------------------------------------
    def notify_rank_lost(self, rank: int, reason: Optional[str] = None) -> None:
        """A peer host is gone (preempted VM, dead health check, the
        scripted :class:`~flinkml_tpu.faults.RankLost` fault). Recorded
        in :attr:`lost_ranks` and treated exactly like SIGTERM on this
        host: the training loop stops cleanly at its next epoch
        boundary with a final checkpoint — the survivors then agree an
        elastic resume at the shrunken world
        (:meth:`plan_elastic_resume`)."""
        rank = int(rank)
        if rank not in self.lost_ranks:
            self.lost_ranks.append(rank)
        self.request(reason or f"rank {rank} lost (shrink requested)")

    @property
    def shrink_requested(self) -> bool:
        """True when at least one peer rank was reported lost — the
        signal to resume at a smaller world rather than just restart."""
        return bool(self.lost_ranks)

    def survivor_world(self, old_world: int) -> int:
        """The world size after dropping the lost ranks (floored at 1 —
        this host is, by construction, still alive)."""
        return max(1, int(old_world) - len(set(self.lost_ranks)))

    def plan_elastic_resume(self, manager: Any, world: int,
                            new_world: Optional[int] = None,
                            mesh=None) -> ElasticResumePlan:
        """The survivors' shrink (or grow) decision: agree the newest
        commonly-valid snapshot of ``manager`` across the remaining
        ranks (:func:`flinkml_tpu.parallel.distributed
        .agree_resume_epoch` — fires the ``rendezvous.rescale`` seam)
        and return the :class:`ElasticResumePlan` to resume from.
        ``new_world`` defaults to :meth:`survivor_world` of ``world``."""
        from flinkml_tpu.parallel.distributed import agree_resume_epoch

        target = (int(new_world) if new_world is not None
                  else self.survivor_world(world))
        epoch = agree_resume_epoch(manager, mesh=mesh,
                                   old_world=int(world), new_world=target)
        plan = ElasticResumePlan(epoch=epoch, old_world=int(world),
                                 new_world=target)
        _log.warning(
            "elastic resume planned: world %d -> %d from snapshot epoch "
            "%s (lost ranks: %s)", plan.old_world, plan.new_world,
            plan.epoch, sorted(set(self.lost_ranks)),
        )
        return plan

    # -- shutdown actions ----------------------------------------------------
    def register_engine(self, engine: Any) -> None:
        """Serving engines to drain cleanly on preemption (anything with
        ``stop(drain=True)``)."""
        self._engines.append(engine)

    @property
    def finalized(self) -> bool:
        return self._finalized

    def finalize(self) -> None:
        """Drain registered engines; idempotent. Called by the training
        loop AFTER its final checkpoint committed, so the snapshot is
        durable before serving winds down."""
        if self._finalized:
            return
        self._finalized = True
        for engine in self._engines:
            try:
                engine.stop(drain=True)
                _log.info("drained serving engine %r on preemption", engine)
            except Exception as e:  # noqa: BLE001 — drain best-effort
                _log.error("engine drain failed on preemption: %r", e)
