"""Streaming row sampling for one-pass estimators.

``RowReservoir`` is uniform reservoir sampling (Algorithm R) over row
blocks: streamed fits use it to draw a bounded, seed-deterministic row
sample during the epoch-0 caching pass — for centroid init (KMeans) and
quantile bin edges (GBT) — without a second full pass or unbounded host
memory. The reference has no analog because its algorithms always cache
the full partition (``ListState``) before using it; here the sample IS
the bounded substitute for "look at all rows twice".
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class RowReservoir:
    """Uniform sample of up to ``capacity`` rows from a stream of blocks.

    Block-vectorized Algorithm R: the fill phase copies rows directly;
    afterwards row number ``s`` (1-based, global) replaces a uniform slot
    with probability ``capacity / s``. Accepted replacements are applied
    in stream order so the result matches the sequential algorithm.
    Deterministic for a fixed seed + stream.
    """

    def __init__(self, capacity: int, seed: int = 0):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._rng = np.random.default_rng(seed)
        self._buf: Optional[np.ndarray] = None
        self.rows_seen = 0

    def add(self, block: np.ndarray) -> None:
        block = np.asarray(block)
        if block.ndim < 1 or block.shape[0] == 0:
            return
        if self._buf is None:
            self._buf = np.empty(
                (self.capacity,) + block.shape[1:], dtype=block.dtype
            )
        m = block.shape[0]
        i = 0
        if self.rows_seen < self.capacity:  # fill phase
            take = min(self.capacity - self.rows_seen, m)
            self._buf[self.rows_seen:self.rows_seen + take] = block[:take]
            self.rows_seen += take
            i = take
        if i < m:
            # Global 1-based index of each remaining row.
            s = self.rows_seen + np.arange(1, m - i + 1)
            accept = self._rng.random(m - i) < self.capacity / s
            idx = np.nonzero(accept)[0]
            slots = self._rng.integers(0, self.capacity, size=len(idx))
            for j, slot in zip(idx, slots):  # few accepts once t >> cap
                self._buf[slot] = block[i + j]
            self.rows_seen += m - i

    def sample(self) -> np.ndarray:
        """The sampled rows (a copy), length ``min(rows_seen, capacity)``."""
        if self._buf is None:
            return np.empty((0,))
        return self._buf[: min(self.rows_seen, self.capacity)].copy()
