"""Single-tenant device-client mutex.

The TPU in this image is reached through a single-tenant tunnel: two
concurrent clients can wedge it for every later client (observed round 2:
a second client during a bench run left the device unreachable for 8+
hours — BASELINE.md "Tunnel wedge observed"). The reference has no analog
because Flink multiplexes one cluster across jobs; here the mutex is the
framework's admission control for the device, the way Flink's slot pool is
for TaskManagers.

Mechanism: an exclusive ``flock`` on a well-known file. Every process that
may open the real device (bench stages, probe tools, ad-hoc scripts) takes
the lock first; CPU-only processes (``JAX_PLATFORMS=cpu``, as set by
``tests/conftest.py``) skip it. A parent that holds the lock marks the
environment so its child processes — bench stage children inherit
``os.environ`` — do not deadlock re-acquiring it.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import time

LOCK_PATH_ENV = "FLINKML_TPU_DEVICE_LOCK"
DEFAULT_LOCK_PATH = "/tmp/flinkml_tpu.device.lock"
_HELD_ENV = "_FLINKML_TPU_DEVICE_LOCK_HELD"


def _targets_cpu_only() -> bool:
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if not platforms:
        return False
    return all(p.strip() in ("cpu", "") for p in platforms.split(","))


@contextlib.contextmanager
def device_client_lock(timeout_s: float = 900.0, poll_s: float = 0.5,
                       force: bool = False):
    """Hold the exclusive device-client lock for the duration of the block.

    Yields True when this process acquired the lock, False when the lock
    was skipped (CPU-only process, or an ancestor already holds it).
    Raises TimeoutError if another client holds the lock past
    ``timeout_s`` — the caller should NOT proceed to the device.

    ``force=True`` bypasses the CPU-only skip (for tests of the lock
    itself).
    """
    if not force:
        if _targets_cpu_only():
            yield False
            return
        if os.environ.get(_HELD_ENV):
            yield False
            return
    path = os.environ.get(LOCK_PATH_ENV, DEFAULT_LOCK_PATH)
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
    try:
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"device-client lock {path} held by another process "
                        f"for > {timeout_s:.0f}s; refusing to open a second "
                        "client against the single-tenant device"
                    )
                time.sleep(poll_s)
        try:
            os.ftruncate(fd, 0)
            os.write(fd, f"pid={os.getpid()}\n".encode())
        except OSError:
            pass  # lock content is diagnostic only
        os.environ[_HELD_ENV] = "1"
        try:
            yield True
        finally:
            os.environ.pop(_HELD_ENV, None)
            fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)
