"""Operational utilities: metrics, tracing/profiling.

SURVEY.md §5: the reference has no bespoke observability subsystem — it
re-registers Flink ``InternalOperatorMetricGroup``s per wrapped operator
(``AbstractWrapperOperator.java:103``) and per-round ``LatencyStats``
(``AbstractPerRoundWrapperOperator.java:106,500-553``), and leans on Flink
metric reporters. The TPU equivalents live here: a metrics registry with
per-step timers (:mod:`flinkml_tpu.utils.metrics`) and ``jax.profiler``
integration (:mod:`flinkml_tpu.utils.profiling`).
"""

from flinkml_tpu.utils.logging import enable_console, get_logger, rank_tag
from flinkml_tpu.utils.metrics import (
    EpochMetricsListener,
    Meter,
    MetricGroup,
    MetricsRegistry,
    default_registry,
    metrics,
)
from flinkml_tpu.utils.preemption import ElasticResumePlan, PreemptionWatchdog
from flinkml_tpu.utils.profiling import (
    StepTimer,
    annotate,
    trace,
)

__all__ = [
    "EpochMetricsListener",
    "Meter",
    "MetricGroup",
    "MetricsRegistry",
    "default_registry",
    "metrics",
    "StepTimer",
    "annotate",
    "trace",
    "enable_console",
    "get_logger",
    "rank_tag",
    "PreemptionWatchdog",
    "ElasticResumePlan",
]
