"""Tracing / profiling: ``jax.profiler`` integration + device-accurate timers.

SURVEY.md §5 "Tracing / profiling": the reference relies on Flink operator
metrics and latency markers; the TPU equivalent is ``jax.profiler`` traces
(viewable in XProf/TensorBoard) plus per-step wall timing that accounts for
JAX's async dispatch. These helpers degrade gracefully: if the profiler
cannot start (e.g. unsupported on the backend), ``trace`` becomes a no-op
rather than failing the training job.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from flinkml_tpu.utils.metrics import MetricGroup


@contextlib.contextmanager
def trace(log_dir: str, ignore_errors: bool = True) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed block into ``log_dir``.

    Usage::

        with trace("/tmp/jax-trace"):
            model = estimator.fit(train_table)
    """
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:
        if not ignore_errors:
            raise
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                if not ignore_errors:
                    raise


def annotate(name: str):
    """Named region visible in profiler timelines (host + device).

    Thin alias of ``jax.profiler.TraceAnnotation`` usable as a context
    manager or decorator.
    """
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Device-accurate step timing under async dispatch.

    ``jit`` calls return before the device finishes; naive wall-clock
    timing measures dispatch, not execution (and this build's memory notes
    say even ``block_until_ready`` can lie over tunneled devices — prefer
    whole-loop timings). ``StepTimer`` blocks on the step's outputs before
    reading the clock and optionally records into a metric group::

        timer = StepTimer(group=metrics.group("train"))
        for batch in data:
            with timer:
                state = step(state, batch)
                timer.observe(state)   # block target
    """

    def __init__(self, group: Optional[MetricGroup] = None,
                 series: str = "step_seconds"):
        self.group = group
        self.series = series
        self.times = []
        self._pending = None
        self._t0 = 0.0

    def observe(self, value) -> None:
        """Register the step output to block on at exit."""
        self._pending = value

    def __enter__(self) -> "StepTimer":
        self._pending = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None and self._pending is not None:
            jax.block_until_ready(self._pending)
        dt = time.perf_counter() - self._t0
        self.times.append(dt)
        if self.group is not None:
            self.group.record(self.series, dt)

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0
