"""Metrics registry: counters, gauges, meters, per-epoch histories.

TPU-native replacement for the reference's Flink metric plumbing: wrappers
re-register an ``InternalOperatorMetricGroup`` per wrapped operator
(``iteration/operator/AbstractWrapperOperator.java:103``) and per-round
wrappers keep ``LatencyStats`` (``AbstractPerRoundWrapperOperator.java:
106,500-553``). Here a process-wide :class:`MetricsRegistry` holds named
:class:`MetricGroup`s (the operator-metric-group analog); training loops
attach an :class:`EpochMetricsListener` to record epoch wall-times,
criteria values, and throughput without touching the loop code.

Everything is plain host-side Python — metrics never enter jitted code.
Record values AFTER ``block_until_ready`` if you need device-accurate
timing (see :class:`flinkml_tpu.utils.profiling.StepTimer`).
"""

from __future__ import annotations

import collections
import json
import re
import threading
import time
from typing import Any, Dict, List, Optional

from flinkml_tpu.iteration.runtime import IterationListener


class Meter:
    """Windowed rate meter (events/sec), like Flink's MeterView."""

    def __init__(self, window: int = 64):
        self._events: collections.deque = collections.deque(maxlen=window)

    def mark(self, n: float = 1.0, now: Optional[float] = None) -> None:
        self._events.append((time.perf_counter() if now is None else now, n))

    @property
    def rate(self) -> float:
        """Events/sec over the retained window (0.0 with <2 samples)."""
        if len(self._events) < 2:
            return 0.0
        t0, _ = self._events[0]
        t1, _ = self._events[-1]
        if t1 <= t0:
            return 0.0
        total = sum(n for _, n in list(self._events)[1:])
        return total / (t1 - t0)


class MetricGroup:
    """Named scope of counters/gauges/meters/histories (thread-safe).

    ``labels`` are extra Prometheus label pairs attached to every sample
    the group emits in :meth:`MetricsRegistry.render_text` — e.g. the
    serving pool registers one group per replica under the SAME group
    name with ``labels={"replica": "r3"}``, so per-replica gauges
    aggregate as one labeled family instead of colliding in a flat
    namespace (``flinkml_p50_ms{group="serving.pool",replica="r3"}``).
    """

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels: Dict[str, str] = dict(labels or {})
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._gauges: Dict[str, Any] = {}
        self._meters: Dict[str, Meter] = {}
        self._histories: Dict[str, List[float]] = collections.defaultdict(list)

    def counter(self, name: str, inc: float = 1.0) -> float:
        with self._lock:
            self._counters[name] += inc
            return self._counters[name]

    def gauge(self, name: str, value: Any) -> None:
        with self._lock:
            self._gauges[name] = value

    def meter(self, name: str) -> Meter:
        with self._lock:
            if name not in self._meters:
                self._meters[name] = Meter()
            return self._meters[name]

    def record(self, name: str, value: float) -> None:
        """Append to a history series (epoch times, losses, ...)."""
        with self._lock:
            self._histories[name].append(float(value))

    def history(self, name: str) -> List[float]:
        with self._lock:
            return list(self._histories[name])

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "meters": {k: m.rate for k, m in self._meters.items()},
                "histories": {k: list(v) for k, v in self._histories.items()},
            }


class LatencyWindow:
    """Sliding per-request latency ring publishing ``p50_ms``/``p99_ms``
    gauges into a group — the ONE implementation of the percentile-
    gauge semantics shared by the serving engine's per-engine window
    and the multi-tenant pool's per-SLO-class windows (a divergent copy
    would let two dashboards disagree about the same traffic).
    Thread-safe; ``record`` takes any number of samples so batch
    completions pay one lock acquisition and one sort."""

    def __init__(self, group: MetricGroup, window: int = 2048):
        self._group = group
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=int(window)
        )

    def record(self, *latencies_ms: float) -> None:
        import numpy as np

        with self._lock:
            self._ring.extend(latencies_ms)
            if not self._ring:
                return
            arr = np.asarray(self._ring)
        p50, p99 = np.percentile(arr, [50, 99])  # one sort for both
        self._group.gauge("p50_ms", float(p50))
        self._group.gauge("p99_ms", float(p99))


class MetricsRegistry:
    """Process-wide registry of metric groups.

    The analog of Flink's per-TM metric registry; ``group("model.kmeans")``
    plays the role of the re-registered operator metric group.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # key: (name, sorted label items) — label-less groups keep the
        # plain name as their snapshot key, so existing consumers see
        # exactly the old namespace.
        self._groups: Dict[Any, MetricGroup] = {}

    def group(self, name: str,
              labels: Optional[Dict[str, str]] = None) -> MetricGroup:
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            if key not in self._groups:
                self._groups[key] = MetricGroup(name, labels)
            return self._groups[key]

    @staticmethod
    def _qualified(g: MetricGroup) -> str:
        if not g.labels:
            return g.name
        inner = ",".join(
            f'{k}="{_escape_label(str(v))}"'
            for k, v in sorted(g.labels.items())
        )
        return f"{g.name}{{{inner}}}"

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            groups = list(self._groups.values())
        return {self._qualified(g): g.snapshot() for g in groups}

    def dump_json(self) -> str:
        return json.dumps(self.snapshot(), default=str, sort_keys=True)

    def render_text(self) -> str:
        """Prometheus-style text exposition of every group's counters,
        numeric gauges, and meter rates — one sample line per metric with
        the group as a label, e.g.::

            # TYPE flinkml_requests counter
            flinkml_requests{group="serving.default"} 128

        Counters render as ``counter``, gauges and meter rates as
        ``gauge`` (rates under ``<name>_rate``). Non-numeric gauges and
        histories are skipped (histories are unbounded series — scrape
        :meth:`snapshot` for those). Output is sorted, so diffs are
        stable. This backs the serving engine's stats dump; wire it to
        an HTTP endpoint for a real scrape target.

        A group's extra ``labels`` (see :class:`MetricGroup`) render as
        additional label pairs after ``group=``, e.g.::

            flinkml_queue_depth{group="serving.pool",replica="r3"} 2
        """
        with self._lock:
            groups = list(self._groups.values())
        # metric name -> (prom type, [(rendered label set, value)])
        samples: Dict[str, Any] = {}

        def add(name: str, kind: str, group: str, value: float) -> None:
            # A Prometheus metric family has ONE type: the same name used
            # as a counter in one group and a gauge in another would emit
            # a mistyped series — the later kind moves to a kind-suffixed
            # family instead (deterministic: groups are visited sorted).
            entry = samples.get(name)
            if entry is not None and entry[0] != kind:
                name = f"{name}_{kind}"
                entry = samples.get(name)
            if entry is None:
                entry = samples.setdefault(name, (kind, []))
            entry[1].append((group, value))

        for g in sorted(groups, key=self._qualified):
            pairs = [("group", g.name)] + sorted(g.labels.items())
            labelset = ",".join(
                f'{k}="{_escape_label(str(v))}"' for k, v in pairs
            )
            snap = g.snapshot()
            for k, v in snap["counters"].items():
                add(f"flinkml_{_sanitize(k)}", "counter", labelset, v)
            for k, v in snap["gauges"].items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                add(f"flinkml_{_sanitize(k)}", "gauge", labelset, v)
            for k, rate in snap["meters"].items():
                add(f"flinkml_{_sanitize(k)}_rate", "gauge", labelset, rate)
        lines: List[str] = []
        for name in sorted(samples):
            kind, values = samples[name]
            lines.append(f"# TYPE {name} {kind}")
            for labelset, value in sorted(values):
                # Full precision: '%g' would truncate counters past 6
                # significant digits (1_234_567 -> 1.23457e+06).
                rendered = (
                    str(int(value)) if float(value).is_integer()
                    else repr(float(value))
                )
                lines.append(f"{name}{{{labelset}}} {rendered}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._groups.clear()


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(value: str) -> str:
    """Prometheus label-VALUE escaping: backslash, double quote, newline."""
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


#: Default process-wide registry (import-and-use, like Flink's).
metrics = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide :data:`metrics` registry — the scrape root for
    exposition (``default_registry().render_text()``)."""
    return metrics


class EpochMetricsListener(IterationListener):
    """Records per-epoch wall time, criteria, and throughput into a group.

    Attach to :func:`flinkml_tpu.iteration.iterate` via ``listeners=[...]``.
    ``samples_per_epoch`` (if given) feeds a ``samples`` meter and a final
    ``samples_per_sec`` gauge — the bench's headline metric.
    """

    def __init__(
        self,
        group: Optional[MetricGroup] = None,
        samples_per_epoch: Optional[int] = None,
    ):
        self.group = group if group is not None else metrics.group("iteration")
        self.samples_per_epoch = samples_per_epoch
        self._last = time.perf_counter()
        self._t0 = self._last
        self._epochs = 0

    def on_epoch_watermark_incremented(self, epoch: int, state: Any) -> None:
        now = time.perf_counter()
        self.group.record("epoch_seconds", now - self._last)
        self.group.counter("epochs")
        if self.samples_per_epoch:
            self.group.meter("samples").mark(self.samples_per_epoch, now=now)
        self._last = now
        self._epochs += 1

    def on_iteration_terminated(self, state: Any) -> None:
        total = time.perf_counter() - self._t0
        self.group.gauge("total_seconds", total)
        if self.samples_per_epoch and total > 0:
            self.group.gauge(
                "samples_per_sec", self.samples_per_epoch * self._epochs / total
            )
