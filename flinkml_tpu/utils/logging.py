"""Rank-tagged operational logging (VERDICT Missing #4).

The reference inherits Flink's log4j plumbing — every operator logs
through the TaskManager with its subtask index in the MDC, so a
multi-node failure can be reconstructed from interleaved logs. This is
the TPU-pod equivalent: one process-wide logger namespace
(``flinkml_tpu.*``) whose records carry a ``[rank i/n]`` tag, so logs
aggregated across the hosts of a pod slice stay attributable.

Library stance: a ``NullHandler`` is installed on the package root
logger, so embedding applications stay silent unless they configure
handlers themselves; :func:`enable_console` is the one-liner for
operators (and the recovery runbook,
``docs/development/fault_tolerance.md``).

The rank tag is resolved WITHOUT touching jax (``jax.process_index()``
initializes the XLA backend, which must not happen as an import side
effect): it reads the standard launcher environment
(``JAX_PROCESS_ID`` / ``JAX_NUM_PROCESSES``) until
:func:`set_rank` is called — ``init_distributed`` pins the real values
right after the rendezvous succeeds.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

ROOT_NAME = "flinkml_tpu"

logging.getLogger(ROOT_NAME).addHandler(logging.NullHandler())

# (process_index, process_count) once known; None = fall back to env.
_RANK: Optional[Tuple[int, int]] = None


def set_rank(process_index: int, process_count: int) -> None:
    """Pin the rank tag (called by ``init_distributed`` after the
    rendezvous; safe to call again on re-init)."""
    global _RANK
    _RANK = (int(process_index), int(process_count))


def rank_tag() -> str:
    """``[rank i/n]`` — from :func:`set_rank` when pinned, else from the
    launcher environment (single-process default ``[rank 0/1]``)."""
    if _RANK is not None:
        i, n = _RANK
    else:
        i = int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
        n = int(os.environ.get("JAX_NUM_PROCESSES", "1") or 1)
    return f"[rank {i}/{n}]"


class _RankAdapter(logging.LoggerAdapter):
    def process(self, msg, kwargs):
        return f"{rank_tag()} {msg}", kwargs


def get_logger(name: str = ROOT_NAME) -> logging.LoggerAdapter:
    """A rank-tagged logger under the ``flinkml_tpu`` namespace.

    ``name`` may be a dotted suffix (``"checkpoint"``) or a full module
    path; either way the logger lands under the package root so one
    handler/level setting controls the whole library.
    """
    if not name.startswith(ROOT_NAME):
        name = f"{ROOT_NAME}.{name}"
    return _RankAdapter(logging.getLogger(name), {})


def enable_console(level: int = logging.INFO) -> logging.Handler:
    """Attach a stderr handler to the package root (idempotent — reuses
    an existing console handler) and set its level. Returns the handler."""
    root = logging.getLogger(ROOT_NAME)
    for h in root.handlers:
        if isinstance(h, logging.StreamHandler) and not isinstance(
            h, logging.NullHandler
        ):
            handler = h
            break
    else:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        root.addHandler(handler)
    root.setLevel(level)
    handler.setLevel(level)
    return handler
