"""PrecisionPolicy — the declared mixed-precision contract (ROADMAP 2).

The paper's premise — replacing JVM BLAS inner loops with XLA kernels —
only pays off on TPU when compute runs in bf16 *without* silently
corrupting f32 accumulators or parameters. SNIPPETS.md [2]'s
``TPU_DTYPE = bfloat16`` / ``DTYPE = float32`` split and [3]'s
``to_bf16``/``to_fp32`` param casting under pjit are the exemplar
patterns; this module hardens them from a convention into a *checked*
policy value:

- ``compute`` — the dtype the hot elementwise/matmul work runs in (the
  bandwidth/MXU savings dtype, typically ``bfloat16``);
- ``accum`` — the minimum dtype any reduction/accumulation (``reduce_sum``,
  a dot-general accumulator, an optimizer moment update, a cross-rank
  psum) may run in (typically ``float32``);
- ``params`` — the dtype parameters and optimizer state are *stored* in
  between steps (typically ``float32``; cast down to ``compute`` at step
  boundaries, exactly the [3] idiom).

A policy is frozen, hashable (it keys compile caches — bf16 and f32
programs must never alias one executable) and JSON round-trippable (it
rides ``*.policy.json`` analysis fixtures). Every policy-gated entry
point — the fused transform executor (:mod:`flinkml_tpu.pipeline_fusion`),
the plan-sharded SGD/Adam trainers (:mod:`flinkml_tpu.sharding.apply`),
and serving inference (:class:`~flinkml_tpu.serving.engine.ServingConfig`
``.precision``) — validates its jaxpr against the policy BEFORE any
compile via the FML6xx precision-flow pass
(:mod:`flinkml_tpu.analysis.precision`), raising the typed
:class:`PrecisionValidationError` carrying the findings — the same
contract shape as ``PlanValidationError`` for FML5xx.

See ``docs/development/precision.md`` for the casting contract and the
equivalence-test recipe.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional

import numpy as np

#: Canonical float dtype names a policy may declare.
_FLOAT_NAMES = ("bfloat16", "float16", "float32", "float64")

#: Rounding-significand widths (bits) — the *precision* order, which is
#: what accumulation correctness cares about. Plain itemsize would rank
#: bfloat16 (8-bit significand) equal to float16 (11-bit); both are
#: "narrow" against float32, but the distinction keeps messages honest.
_SIGNIFICAND_BITS = {"bfloat16": 8, "float16": 11, "float32": 24,
                     "float64": 53}


def float_name(dtype) -> str:
    """Canonical name of a float dtype (accepts names, np dtypes, jnp
    scalar types, ml_dtypes)."""
    if isinstance(dtype, str) and dtype in _FLOAT_NAMES:
        return dtype
    name = np.dtype(dtype).name if not isinstance(dtype, str) else dtype
    if name not in _FLOAT_NAMES:
        raise ValueError(
            f"{dtype!r} is not a float dtype a PrecisionPolicy can "
            f"declare (one of {_FLOAT_NAMES})"
        )
    return name


def significand_bits(dtype) -> int:
    """Significand width of a float dtype name/np dtype (non-floats
    return a sentinel wider than every float — integer/bool values never
    count as 'narrow')."""
    try:
        name = float_name(dtype)
    except ValueError:
        return 1 << 16
    return _SIGNIFICAND_BITS[name]


def is_narrower(a, b) -> bool:
    """Whether float dtype ``a`` rounds coarser than ``b``."""
    return significand_bits(a) < significand_bits(b)


class PrecisionValidationError(ValueError):
    """A program failed FML6xx precision-flow validation against its
    declared :class:`PrecisionPolicy` — raised BEFORE any compile,
    carrying the rendered findings (rule ids + fix hints). The
    ahead-of-time half of the precision contract: a program that reaches
    jit has already passed the same checks
    ``python -m flinkml_tpu.analysis`` runs on ``*.policy.json``
    fixtures."""

    def __init__(self, message: str, findings=()):
        super().__init__(message)
        #: The structured :class:`~flinkml_tpu.analysis.findings.Finding`
        #: list behind the rendered message (CI annotates from these).
        self.findings = list(findings)


#: Quantization schemes a policy may declare for model constants.
_QUANT_SCHEMES = ("int8",)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """The declared (compute, accum, params) dtype contract — see module
    docstring. Frozen + hashable (compile-cache key material), JSON
    round-trippable (``*.policy.json`` fixtures).

    ``quant`` declares a post-training-quantization scheme for model
    constants below the float tiers: ``"int8"`` stores/transfers every
    eligible model constant as per-column absmax-scaled int8
    (:func:`quantize_absmax`) and dequantizes to ``compute`` width
    INSIDE the fused program, so the dequant fuses into the consuming
    matmul/elementwise op. Accumulation still runs at ``accum`` — raw
    int8 accumulation (which wraps at ±127) is refused by FML606, and
    serving int8-stored params under a quant-less policy is refused by
    FML607 (the degraded values must never republish as the full-width
    tier)."""

    name: str = "custom"
    compute: str = "float32"
    accum: str = "float32"
    params: str = "float32"
    quant: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(self, "compute", float_name(self.compute))
        object.__setattr__(self, "accum", float_name(self.accum))
        object.__setattr__(self, "params", float_name(self.params))
        if not self.quant:  # "" and None both mean "no quantization"
            object.__setattr__(self, "quant", None)
        elif self.quant not in _QUANT_SCHEMES:
            raise ValueError(
                f"policy {self.name!r}: unknown quantization scheme "
                f"{self.quant!r} (one of {_QUANT_SCHEMES}, or None)"
            )
        if is_narrower(self.accum, self.compute):
            raise ValueError(
                f"policy {self.name!r}: accum ({self.accum}) narrower than "
                f"compute ({self.compute}) — accumulating below the compute "
                "width is never intentional"
            )

    # -- dtype accessors (jax imported lazily: the policy value must be
    # -- constructible in host-only config code) ---------------------------
    @property
    def compute_dtype(self):
        return _np_dtype(self.compute)

    @property
    def accum_dtype(self):
        return _np_dtype(self.accum)

    @property
    def params_dtype(self):
        return _np_dtype(self.params)

    @property
    def mixed(self) -> bool:
        """Whether the policy narrows compute below params (i.e. whether
        the gate changes any program at all)."""
        return is_narrower(self.compute, self.params)

    def describe(self) -> str:
        return (f"{self.name}(compute={self.compute}, accum={self.accum}, "
                f"params={self.params})")

    # -- serialization -----------------------------------------------------
    def to_json_dict(self) -> dict:
        out = {"name": self.name, "compute": self.compute,
               "accum": self.accum, "params": self.params}
        if self.quant is not None:
            out["quant"] = self.quant
        return out

    @staticmethod
    def from_json_dict(d: Mapping) -> "PrecisionPolicy":
        quant = d.get("quant")
        return PrecisionPolicy(
            name=str(d.get("name", "custom")),
            compute=str(d.get("compute", "float32")),
            accum=str(d.get("accum", "float32")),
            params=str(d.get("params", "float32")),
            quant=None if quant in (None, "") else str(quant),
        )


def _np_dtype(name: str):
    """np.dtype for a canonical float name (bfloat16 via ml_dtypes,
    which every jax install ships)."""
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


# -- presets -----------------------------------------------------------------

#: No mixed precision: everything at float32. Exists mostly as the
#: explicit "other side" of A/B comparisons; ``None`` (no policy) leaves
#: programs untouched.
FULL = PrecisionPolicy("full", "float32", "float32", "float32")

#: The training policy (SNIPPETS.md [3]): bf16 compute, f32 accumulation
#: AND f32-stored parameters/optimizer state, cast down at step
#: boundaries. This is the policy the plan-sharded SGD/Adam trainers
#: implement and validate against.
MIXED = PrecisionPolicy("mixed", "bfloat16", "float32", "float32")

#: The inference policy: bf16 compute with bf16 per-op accumulation
#: (model data stays f32-stored). Inference carries no cross-step
#: accumulator state, and on TPU the MXU accumulates bf16 matmuls in
#: f32 in hardware, so per-op bf16 accumulation is the standard serving
#: trade; declare :data:`MIXED` instead to REFUSE any bf16-accumulating
#: kernel at load time (the strict gate).
MIXED_INFERENCE = PrecisionPolicy(
    "mixed_inference", "bfloat16", "bfloat16", "float32"
)

#: The post-training-quantized serving tier BELOW ``mixed_inference``:
#: eligible model constants are stored and transferred as per-column
#: absmax-scaled int8 (+ one float32 scale per column) and dequantized
#: to float32 inside the fused program, where XLA fuses the dequant into
#: the consuming matmul — compute and accumulation stay at float32, so
#: nothing integer ever accumulates (FML606 refuses exactly that shape).
#: On CPU meshes this tier also beats bf16 ``mixed_inference`` rows/s
#: outright: bf16 is software-emulated there while the dequantized
#: program runs native f32 — the tunnel-immune half of the measurement
#: (the device stage re-measures both when the tunnel returns).
INT8_INFERENCE = PrecisionPolicy(
    "int8_inference", "float32", "float32", "float32", quant="int8"
)

PRESET_POLICIES = {
    p.name: p for p in (FULL, MIXED, MIXED_INFERENCE, INT8_INFERENCE)
}


def resolve_policy(policy) -> Optional[PrecisionPolicy]:
    """Accept a policy object, a preset name, a JSON dict, or None."""
    if policy is None or isinstance(policy, PrecisionPolicy):
        return policy
    if isinstance(policy, str):
        try:
            return PRESET_POLICIES[policy]
        except KeyError:
            raise ValueError(
                f"unknown precision preset {policy!r} (presets: "
                f"{sorted(PRESET_POLICIES)})"
            ) from None
    if isinstance(policy, Mapping):
        return PrecisionPolicy.from_json_dict(policy)
    raise TypeError(f"cannot interpret {policy!r} as a PrecisionPolicy")


# -- post-training quantization (the int8 tier's storage transform) ----------

#: Constants smaller than this many elements are left at float width by
#: the int8 tier: per-column scales plus dequant overhead outweigh the
#: bandwidth saved on tiny vectors. Overridable per mesh via the
#: ``int8_min_const_elems`` autotune knob (consulted by the fused
#: executor at key-construction time — the resolved set of quantized
#: constants is cache-key material through the constant specs).
INT8_MIN_CONST_ELEMS = 16


def quantizable(arr, min_elems: int = INT8_MIN_CONST_ELEMS) -> bool:
    """Whether the int8 tier quantizes this model constant: a float
    array with at least ``min_elems`` elements. Integer/bool constants
    (lookup sizes, category counts) and tiny vectors pass through at
    their storage width."""
    a = np.asarray(arr)
    try:
        float_name(a.dtype)
    except ValueError:
        return False
    return a.size >= int(min_elems) and a.ndim >= 1


def quantize_absmax(arr):
    """Per-column absmax int8 quantization of one model constant.

    For a rank-``n >= 2`` array the scale is per LAST-axis column
    (absmax over every leading axis — the per-output-column scheme for a
    ``[in, out]`` matmul weight); a 1-D vector gets one per-tensor
    scale. Returns ``(q, scale)`` with ``q`` int8 in ``[-127, 127]`` and
    ``scale`` float32 such that ``q * scale ≈ arr``; an all-zero column
    gets scale 1.0 (quantizes to zeros exactly). Symmetric around zero —
    ``-128`` is never produced, so negation round-trips."""
    a = np.asarray(arr)
    if a.ndim >= 2:
        absmax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)))
    else:
        absmax = np.max(np.abs(a)) if a.size else np.float64(0.0)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(
        np.rint(a / scale.astype(a.dtype)), -127, 127
    ).astype(np.int8)
    return q, scale


def dequantize_absmax(q, scale, dtype="float32"):
    """The inverse transform at ``dtype`` width (host-side reference;
    the fused executor performs the same two ops in-program so XLA fuses
    them into the consumer)."""
    dt = np.dtype(dtype)
    return np.asarray(q).astype(dt) * np.asarray(scale).astype(dt)


def cast_floats(tree, dtype):
    """Cast every float leaf of a pytree to ``dtype`` (the
    ``to_bf16``/``to_fp32`` idiom); non-float leaves pass through."""
    import jax

    dt = np.dtype(dtype)

    def one(leaf):
        leaf_dt = np.asarray(leaf).dtype if not hasattr(leaf, "dtype") \
            else leaf.dtype
        if np.dtype(leaf_dt) == dt or significand_bits(leaf_dt) >= (1 << 16):
            return leaf
        return leaf.astype(dt) if hasattr(leaf, "astype") else \
            np.asarray(leaf, dt)

    return jax.tree_util.tree_map(one, tree)
