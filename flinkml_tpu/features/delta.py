"""ModelDelta — the registry's incremental publish format.

A delta is itself a save/load-able stage (metadata + fingerprinted
arrays, the standard persistence layout), so it publishes through the
same atomic claim-rename-flip path as a full model and lists as a normal
registry version. What makes it a *delta* is its payload and its chain
metadata:

- **payload** — changed embedding rows per row table (``ids [m]`` +
  ``values [m, dim]``, SET semantics: the rows' new contents, not
  increments — applying a delta twice is idempotent, and applying it to
  the right base is bitwise-equal to the full snapshot it stands for)
  plus changed dense leaves (small arrays shipped whole).
- **chain metadata** — ``base_version`` (the registry version this delta
  applies on top of), ``base_fingerprint`` /``result_fingerprint``
  (``content_fingerprint`` of the base's / result's ``delta_state()``
  arrays — the chain is *fingerprint-linked*, so a pruned, corrupted, or
  swapped base is a typed :class:`~flinkml_tpu.serving.errors.
  DeltaChainError` naming the broken link, never a silently wrong
  model), ``watermark`` (the source-batch watermark of the trainer state
  this delta publishes — the pool's freshness gauge counts in these),
  and ``depth`` (chain length from the nearest full snapshot; the
  publisher compacts to a full snapshot when it hits the cap).

Resolution lives in :meth:`ModelRegistry.get`: load target, walk
``base_version`` links down to a full snapshot, apply upward verifying
every fingerprint. The serving engine's fast path
(:meth:`ServingEngine._try_delta_swap`) skips the walk when the chain
suffix starts at its ACTIVE version: clone-and-patch in place, no full
load, no warmup.
"""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

import numpy as np

from flinkml_tpu.api import Model
from flinkml_tpu.params import IntParam, StringParam
from flinkml_tpu.table import Table

_ROW_IDS = "rows.{}.ids"
_ROW_VALUES = "rows.{}.values"
_DENSE = "dense.{}"


class ModelDelta(Model):
    """See module docstring. Build with :meth:`build`; the no-arg
    constructor exists for the reflective loader."""

    #: Registry/engine dispatch marker (duck-typed so the registry never
    #: imports this module unless deltas are actually in play).
    is_model_delta = True

    BASE_VERSION = IntParam(
        "baseVersion", "Registry version this delta applies on top of.", 0
    )
    BASE_FINGERPRINT = StringParam(
        "baseFingerprint", "content_fingerprint of the base delta_state().",
        ""
    )
    RESULT_FINGERPRINT = StringParam(
        "resultFingerprint",
        "content_fingerprint of delta_state() after applying this delta.", ""
    )
    WATERMARK = IntParam(
        "watermark", "Source-batch watermark of the published state.", 0
    )
    DEPTH = IntParam(
        "depth", "Chain length from the nearest full snapshot (1 = "
        "directly on a snapshot).", 1
    )
    MODEL_CLASS = StringParam(
        "modelClass", "Dotted class name of the model this delta patches "
        "(operator forensics; resolution is structural).", ""
    )

    def __init__(self):
        super().__init__()
        self._arrays: Dict[str, np.ndarray] = {}

    @classmethod
    def build(
        cls,
        *,
        base_version: int,
        base_fingerprint: str,
        result_fingerprint: str,
        watermark: int,
        depth: int,
        row_deltas: Mapping[str, Tuple[np.ndarray, np.ndarray]],
        dense_deltas: Mapping[str, np.ndarray] = (),
        model_class: str = "",
    ) -> "ModelDelta":
        delta = cls()
        delta.set(cls.BASE_VERSION, int(base_version))
        delta.set(cls.BASE_FINGERPRINT, str(base_fingerprint))
        delta.set(cls.RESULT_FINGERPRINT, str(result_fingerprint))
        delta.set(cls.WATERMARK, int(watermark))
        delta.set(cls.DEPTH, int(depth))
        delta.set(cls.MODEL_CLASS, model_class)
        for name, (ids, values) in dict(row_deltas).items():
            ids = np.asarray(ids, np.int32).reshape(-1)
            values = np.asarray(values)
            if values.shape[0] != ids.shape[0]:
                raise ValueError(
                    f"row table {name!r}: {ids.shape[0]} ids != "
                    f"{values.shape[0]} value rows"
                )
            if ids.shape[0] != np.unique(ids).shape[0]:
                raise ValueError(
                    f"row table {name!r}: delta ids must be unique (set "
                    "semantics — duplicate ids would make the patch "
                    "order-dependent)"
                )
            delta._arrays[_ROW_IDS.format(name)] = ids
            delta._arrays[_ROW_VALUES.format(name)] = values
        for name, value in dict(dense_deltas).items():
            delta._arrays[_DENSE.format(name)] = np.asarray(value)
        return delta

    # -- typed accessors ---------------------------------------------------
    @property
    def base_version(self) -> int:
        return int(self.get(self.BASE_VERSION))

    @property
    def base_fingerprint(self) -> str:
        return self.get(self.BASE_FINGERPRINT)

    @property
    def result_fingerprint(self) -> str:
        return self.get(self.RESULT_FINGERPRINT)

    @property
    def watermark(self) -> int:
        return int(self.get(self.WATERMARK))

    @property
    def depth(self) -> int:
        return int(self.get(self.DEPTH))

    def row_deltas(self) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
        out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for key in self._arrays:
            if key.startswith("rows.") and key.endswith(".ids"):
                name = key[len("rows."):-len(".ids")]
                out[name] = (self._arrays[key],
                             self._arrays[_ROW_VALUES.format(name)])
        return out

    def dense_deltas(self) -> Dict[str, np.ndarray]:
        return {
            key[len("dense."):]: value
            for key, value in self._arrays.items()
            if key.startswith("dense.")
        }

    def payload_bytes(self) -> int:
        """Published payload size (the number the bench's delta-vs-full
        byte ratio is computed from)."""
        return int(sum(a.nbytes for a in self._arrays.values()))

    def get_model_data(self):
        """Payload as Tables so the registry's finite publish gate scans
        delta values exactly like full-model arrays (a NaN'd row patch
        must never become a version a follower could swap in)."""
        tables = []
        for name in sorted(self.row_deltas()):
            ids, values = self.row_deltas()[name]
            tables.append(Table({"ids": ids, "values": values}))
        for name in sorted(self.dense_deltas()):
            tables.append(Table(
                {name: np.asarray(self.dense_deltas()[name]).reshape(-1)}))
        return tables

    # -- stage protocol ----------------------------------------------------
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        raise TypeError(
            "a ModelDelta is not servable on its own — resolve it through "
            "ModelRegistry.get(), which applies the chain onto its base "
            "snapshot"
        )

    def save(self, path: str) -> None:
        self._save_with_arrays(path, self._arrays)

    @classmethod
    def load(cls, path: str) -> "ModelDelta":
        delta, arrays, _meta = cls._load_with_arrays(path)
        delta._arrays = dict(arrays)
        return delta
