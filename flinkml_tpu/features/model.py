"""HashedFMModel — the serving consumer of the hashed feature space.

A second-order factorization machine whose feature space IS the hash
space: ``input_col`` holds ``[n, L]`` hashed row ids (``-1`` padded, the
embedding subsystem's convention), and the margin is the sparse FM
identity over the looked-up rows::

    margin = w0 + Σ_l w[id_l] + ½ (‖Σ_l v[id_l]‖² − Σ_l ‖v[id_l]‖²)

Storage is embedding-row shaped on purpose — ``w`` is ``[B, 1]`` and
``v`` is ``[B, k]`` with ``B = num_buckets`` — so the model is
**row-delta patchable**: an incremental publish touches exactly the rows
the trainer touched (:meth:`apply_delta`), and a mesh-bound clone serves
them through :class:`~flinkml_tpu.embeddings.table.EmbeddingTable`
(``for_mesh``, the serving engine's SPMD binding contract).

Versioned-patch semantics: :meth:`apply_delta` returns a **new model**
sharing every un-touched buffer — the engine flips its active-model
reference to the clone atomically, so an in-flight batch that
snapshotted the old model keeps computing on the old rows (JAX/numpy
buffers are never mutated) and every response still carries exactly one
version — the PR 8 contract, extended to row patches.

The FML505 gate runs at construction: ``num_buckets`` must equal the
row count of ``w``/``v`` (:func:`~flinkml_tpu.features.hashing.
check_hash_vocab`), so a mis-sized hash front end is refused before any
program compiles.
"""

from __future__ import annotations

import copy
from typing import Dict, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Model
from flinkml_tpu.params import IntParam, ParamValidators, StringParam
from flinkml_tpu.features.hashing import check_hash_vocab
from flinkml_tpu.table import Table


class HashedFMModel(Model):
    """See module docstring. Build with :meth:`from_arrays` (or the
    streaming trainer's ``make_model``); the no-arg constructor exists
    for the reflective loader."""

    INPUT_COL = StringParam("inputCol", "Hashed-id rows column.", "ids")
    PREDICTION_COL = StringParam(
        "predictionCol", "Output probability column.", "prediction"
    )
    RAW_PREDICTION_COL = StringParam(
        "rawPredictionCol", "Output margin column.", "rawPrediction"
    )
    NUM_BUCKETS = IntParam(
        "numBuckets", "Hash-space size (= row count of w/v).", 1,
        ParamValidators.gt(0),
    )
    HASH_SEED = IntParam(
        "hashSeed", "Seed of the hash front end this model was trained "
        "behind (recorded so serving can rebuild the same front end).", 0,
    )
    FACTOR_SIZE = IntParam(
        "factorSize", "Dimensionality of the interaction factors.", 8,
        ParamValidators.gt(0),
    )

    def __init__(self):
        super().__init__()
        self.w0: Optional[np.ndarray] = None    # [1]
        self.w: Optional[np.ndarray] = None     # [B, 1]
        self.v: Optional[np.ndarray] = None     # [B, k]
        self.plan = None
        self._w_table = None                    # set by for_mesh
        self._v_table = None

    @classmethod
    def from_arrays(cls, w0, w, v, *, num_buckets: int, hash_seed: int = 0,
                    input_col: str = "ids", plan=None) -> "HashedFMModel":
        model = cls()
        model.w0 = np.asarray(w0, np.float32).reshape(1)
        model.w = np.asarray(w, np.float32)
        model.v = np.asarray(v, np.float32)
        if model.w.ndim != 2 or model.w.shape[1] != 1:
            raise ValueError(f"w must be [B, 1], got {model.w.shape}")
        if model.v.ndim != 2:
            raise ValueError(f"v must be [B, k], got {model.v.shape}")
        if model.w.shape[0] != model.v.shape[0]:
            raise ValueError(
                f"w rows {model.w.shape[0]} != v rows {model.v.shape[0]}"
            )
        check_hash_vocab(num_buckets, model.v.shape[0],
                         where="HashedFMModel.from_arrays")
        model.set(cls.NUM_BUCKETS, int(num_buckets))
        model.set(cls.HASH_SEED, int(hash_seed))
        model.set(cls.FACTOR_SIZE, int(model.v.shape[1]))
        model.set(cls.INPUT_COL, input_col)
        model.plan = plan
        return model

    # -- mesh binding (the engine's SPMD install contract) ----------------
    def for_mesh(self, mesh) -> "HashedFMModel":
        """A clone whose w/v live as row-sharded
        :class:`~flinkml_tpu.embeddings.table.EmbeddingTable`s placed on
        ``mesh`` — what the serving engine calls per replica slice when
        ``ServingConfig.mesh`` is set. The host arrays stay authoritative
        (deltas patch host AND table)."""
        from flinkml_tpu.embeddings.table import EmbeddingTable

        bound = self._clone()
        b, k = self.v.shape
        bound._w_table = EmbeddingTable(
            "hashed_fm/w", b, 1, mesh=mesh, plan=self.plan, rows=self.w
        )
        bound._v_table = EmbeddingTable(
            "hashed_fm/v", b, k, mesh=mesh, plan=self.plan, rows=self.v
        )
        return bound

    def _clone(self) -> "HashedFMModel":
        clone = HashedFMModel()
        clone.load_param_map_json(self.get_param_map_json())
        clone.w0, clone.w, clone.v = self.w0, self.w, self.v
        clone.plan = self.plan
        clone._w_table, clone._v_table = self._w_table, self._v_table
        return clone

    # -- the delta protocol (registry chain walk + engine fast swap) ------
    def delta_state(self) -> Dict[str, np.ndarray]:
        """The full state as named host arrays — what delta fingerprints
        chain over (``content_fingerprint(delta_state())``)."""
        return {"w0": np.asarray(self.w0), "w": np.asarray(self.w),
                "v": np.asarray(self.v)}

    def apply_delta(self, delta) -> "HashedFMModel":
        """A NEW model with ``delta``'s row patches (set semantics) and
        dense leaves applied; every untouched buffer is shared with
        self. Mesh-bound clones patch their tables through
        :meth:`EmbeddingTable.clone_with_row_delta`, so the old model's
        tables — and any in-flight batch holding them — are untouched."""
        clone = self._clone()
        for name, (ids, values) in delta.row_deltas().items():
            if name == "w":
                clone.w = _set_rows(clone.w, ids, values)
                if clone._w_table is not None:
                    clone._w_table = clone._w_table.clone_with_row_delta(
                        ids, values)
            elif name == "v":
                clone.v = _set_rows(clone.v, ids, values)
                if clone._v_table is not None:
                    clone._v_table = clone._v_table.clone_with_row_delta(
                        ids, values)
            else:
                raise KeyError(
                    f"delta patches unknown row table {name!r} "
                    "(HashedFMModel has 'w' and 'v')"
                )
        for name, value in delta.dense_deltas().items():
            if name != "w0":
                raise KeyError(
                    f"delta patches unknown dense leaf {name!r} "
                    "(HashedFMModel has 'w0')"
                )
            clone.w0 = np.asarray(value, np.float32).reshape(1)
        return clone

    # -- transform ---------------------------------------------------------
    def _margin(self, ids: np.ndarray) -> np.ndarray:
        mask = ids >= 0
        safe = np.where(mask, ids, 0)
        if self._v_table is not None:
            v_rows = np.asarray(self._v_table.lookup(safe))
            w_rows = np.asarray(self._w_table.lookup(safe))[..., 0]
        else:
            v_rows = self.v[safe]                       # [n, L, k]
            w_rows = self.w[safe, 0]                    # [n, L]
        fmask = mask.astype(np.float32)
        v_rows = v_rows * fmask[..., None]
        w_rows = w_rows * fmask
        sv = v_rows.sum(axis=1)                         # [n, k]
        sv2 = (v_rows * v_rows).sum(axis=1)             # [n, k]
        pair = 0.5 * (sv * sv - sv2).sum(axis=1)
        return (self.w0[0] + w_rows.sum(axis=1) + pair).astype(np.float32)

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        ids = np.asarray(table.column(self.get(self.INPUT_COL)))
        if ids.ndim == 1:
            ids = ids[:, None]
        if ids.ndim != 2:
            raise ValueError(
                f"column {self.get(self.INPUT_COL)!r} must hold [n] or "
                f"[n, L] hashed ids, got shape {ids.shape}"
            )
        margin = self._margin(ids.astype(np.int64))
        prob = (1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
        out = table.with_column(self.get(self.RAW_PREDICTION_COL), margin)
        return (out.with_column(self.get(self.PREDICTION_COL), prob),)

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        self._save_with_arrays(path, self.delta_state())

    @classmethod
    def load(cls, path: str) -> "HashedFMModel":
        model, arrays, _meta = cls._load_with_arrays(path)
        model.w0 = arrays["w0"].astype(np.float32)
        model.w = arrays["w"].astype(np.float32)
        model.v = arrays["v"].astype(np.float32)
        return model

    def get_model_data(self):
        """Row-space state as one Table (w0 is broadcast metadata in the
        finite-check's eyes; it rides a [1]-row table of its own)."""
        return [Table({"w": self.w, "v": self.v}), Table({"w0": self.w0})]


def _set_rows(base: np.ndarray, ids: np.ndarray,
              values: np.ndarray) -> np.ndarray:
    """Copy-on-write row patch: a fresh array sharing nothing with
    ``base`` at the patched rows' dtype/shape contract."""
    ids = np.asarray(ids, np.int64).reshape(-1)
    values = np.asarray(values, base.dtype)
    if values.shape != (ids.shape[0],) + base.shape[1:]:
        raise ValueError(
            f"row values shape {values.shape} != ({ids.shape[0]}, "
            f"*{base.shape[1:]})"
        )
    out = base.copy()
    out[ids] = values
    return out
