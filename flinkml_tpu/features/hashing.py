"""Feature hashing: seeded, process-stable raw-key → embedding-row ids.

The front half of the streaming feature platform (ROADMAP item 4): raw
high-cardinality string/int keys map straight to embedding-table rows
through a murmur3-x86-32 hash — **no vocabulary build, no host-side id
assignment**, so an unbounded stream feeds
:class:`~flinkml_tpu.embeddings.table.EmbeddingTable` training directly.

Three contracts carry the subsystem:

- **process stability** — the hash is murmur3 over explicit bytes with
  explicit ``uint32`` wrapping arithmetic. It never touches Python
  ``hash()`` (randomized per process via ``PYTHONHASHSEED``), native
  endianness, or platform word width, so the SAME (key, seed) maps to
  the SAME row in every process, on every platform, forever. A hashed
  model's rows stay addressable across trainer restarts, serving
  replicas, and checkpoint round-trips — the property the cross-process
  child test (``tests/_hash_child.py``) and the committed golden
  vectors pin.
- **measured collisions** — :class:`CollisionTracker` counts *observed*
  distinct-key collisions per bucket (capped memory) next to the
  analytic birthday-bound expectation, published as the
  ``features.hash`` metrics group, so the bucket-count/cardinality
  trade is a number on a dashboard, not a guess.
- **priced bucket/vocab coupling** — :func:`check_hash_vocab` is the
  live half of FML505: a hash front end whose ``num_buckets`` differs
  from the embedding table's vocab rows is refused pre-compile (silent
  modulo aliasing on the small side, permanently dead rows on the
  large side). The declarative half checks ``*.features.json`` fixtures
  through ``python -m flinkml_tpu.analysis``.

Key encoding (what the golden vectors fix): ``str`` hashes its UTF-8
bytes; ``bytes`` hashes as-is; ints hash their 8-byte little-endian
two's-complement encoding (so ``np.int32(7)`` and ``np.int64(7)`` and
Python ``7`` agree). Bucket id = ``murmur3_32(key, seed) % num_buckets``
computed in ``uint32`` — non-negative by construction.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from flinkml_tpu.utils.metrics import metrics

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF


class HashVocabMismatchError(ValueError):
    """The live FML505 gate: a hash front end's ``num_buckets`` does not
    equal the embedding table's vocab rows. Refused BEFORE any program
    compiles — ``num_buckets < vocab`` leaves rows the stream can never
    train (dead HBM), ``num_buckets > vocab`` silently aliases distinct
    buckets onto shared rows at lookup time."""


def check_hash_vocab(num_buckets: int, vocab: int, *, where: str = "") -> None:
    """Raise :class:`HashVocabMismatchError` unless the hash space and
    the table's row space are the same size (rule FML505)."""
    if int(num_buckets) != int(vocab):
        raise HashVocabMismatchError(
            f"FML505: hash num_buckets={int(num_buckets)} != embedding "
            f"table vocab={int(vocab)}"
            + (f" ({where})" if where else "")
            + "; the hashed id space must BE the row space — size the "
            "table to num_buckets (or re-hash to the table's vocab)"
        )


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """Reference murmur3-x86-32 over ``data`` — the scalar definition the
    vectorized int path and the golden vectors are pinned against. Pure
    Python with explicit ``uint32`` masking: bit-identical everywhere."""
    h = seed & _M32
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * _C1) & _M32
        k = ((k << 15) | (k >> 17)) & _M32
        k = (k * _C2) & _M32
        h ^= k
        h = ((h << 13) | (h >> 19)) & _M32
        h = (h * 5 + 0xE6546B64) & _M32
    tail = data[nblocks * 4:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _M32
        k = ((k << 15) | (k >> 17)) & _M32
        k = (k * _C2) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def _key_bytes(key: Any) -> bytes:
    """The canonical byte encoding of one raw key (see module docstring)."""
    if isinstance(key, bytes):
        return key
    if isinstance(key, str):
        return key.encode("utf-8")
    if isinstance(key, (int, np.integer)):
        return (int(key) & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
    raise TypeError(
        f"hashable keys are str/bytes/int, got {type(key).__name__}"
    )


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _hash_ints_vectorized(keys: np.ndarray, seed: int) -> np.ndarray:
    """murmur3_32 of each key's 8-byte little-endian encoding, vectorized
    — bit-identical to the scalar reference (two 4-byte blocks, empty
    tail, length 8), at numpy throughput for the streaming hot path."""
    k64 = keys.astype(np.int64).view(np.uint64) if keys.dtype.kind == "i" \
        else keys.astype(np.uint64)
    with np.errstate(over="ignore"):
        h = np.full(k64.shape, np.uint32(seed & _M32), np.uint32)
        lo = (k64 & np.uint64(_M32)).astype(np.uint32)
        hi = (k64 >> np.uint64(32)).astype(np.uint32)
        for block in (lo, hi):
            k = block * np.uint32(_C1)
            k = _rotl32(k, 15)
            k = k * np.uint32(_C2)
            h = h ^ k
            h = _rotl32(h, 13)
            h = h * np.uint32(5) + np.uint32(0xE6546B64)
        h = h ^ np.uint32(8)
        h = h ^ (h >> np.uint32(16))
        h = h * np.uint32(0x85EBCA6B)
        h = h ^ (h >> np.uint32(13))
        h = h * np.uint32(0xC2B2AE35)
        h = h ^ (h >> np.uint32(16))
    return h


def hash_buckets(
    keys: Any,
    *,
    seed: int,
    num_buckets: int,
    pad_key: Optional[int] = None,
) -> np.ndarray:
    """Bucket ids in ``[0, num_buckets)`` for an array of raw keys.

    ``keys`` is any-shape array-like of int (vectorized path) or
    str/bytes (scalar murmur3 per element — identical definition).
    ``pad_key`` marks padding slots: keys equal to it (an int for int
    keys, e.g. ``""`` for string keys) pass through as ``-1``, the id
    the embedding lookup/pooling layers already treat as "ignore" — so
    ``[n, L]`` ragged-padded id rows hash without resurrecting their
    padding. Returns int32 of ``keys``' shape.
    """
    if num_buckets < 1:
        raise ValueError(f"num_buckets must be >= 1, got {num_buckets}")
    arr = np.asarray(keys)
    n = np.uint32(num_buckets)
    if arr.dtype.kind in "iu":
        h = _hash_ints_vectorized(arr, seed)
        out = (h % n).astype(np.int32)
        if pad_key is not None:
            out = np.where(arr == pad_key, np.int32(-1), out)
        return out
    # str / bytes / object: scalar reference per element.
    flat = arr.reshape(-1)
    out = np.empty(flat.shape[0], np.int32)
    for i, key in enumerate(flat):
        if isinstance(key, np.str_):
            key = str(key)
        elif isinstance(key, np.bytes_):
            key = bytes(key)
        if pad_key is not None and key == pad_key:
            out[i] = np.int32(-1)
            continue
        out[i] = np.int32(np.uint32(murmur3_32(_key_bytes(key), seed)) % n)
    return out.reshape(arr.shape)


def expected_collision_fraction(num_keys: int, num_buckets: int) -> float:
    """The analytic birthday bound: the expected fraction of ``num_keys``
    distinct keys that land in an already-occupied bucket of
    ``num_buckets`` under a uniform hash —
    ``1 - n·(1 - (1 - 1/n)^k) / k``. The number the measured
    ``collision_rate`` gauge is judged against (a measured rate far
    above it means the key distribution is adversarial for this seed;
    far below means the tracker has seen too few keys to say)."""
    k, b = int(num_keys), int(num_buckets)
    if k <= 1:
        return 0.0
    expected_occupied = b * -np.expm1(k * np.log1p(-1.0 / b))
    return float(max(0.0, 1.0 - expected_occupied / k))


class CollisionTracker:
    """Measured collision accounting with capped memory.

    Tracks, per bucket, a fingerprint set of the distinct raw keys seen
    (a 64-bit secondary hash — two murmur3 runs under different seeds —
    so the tracker never stores raw keys). Once ``max_keys`` distinct
    keys are held the tracker stops admitting NEW keys (already-seen
    keys keep counting) and sets the ``saturated`` gauge — bounded
    memory under an unbounded stream, by design.

    Gauges in the ``features.hash`` group (``labels={"feature": name}``):
    ``keys_seen`` (distinct), ``buckets_used``, ``collisions`` (distinct
    keys beyond the first in their bucket), ``collision_rate``,
    ``expected_collision_rate`` (birthday bound at the same key count),
    ``saturated`` (0/1).
    """

    def __init__(self, name: str, num_buckets: int, seed: int,
                 max_keys: int = 100_000):
        self.num_buckets = int(num_buckets)
        self.seed = int(seed)
        self.max_keys = int(max_keys)
        self._buckets: Dict[int, set] = {}
        self._keys_seen = 0
        self._collisions = 0
        self._saturated = False
        self._metrics = metrics.group(
            "features.hash", labels={"feature": name}
        )

    def observe(self, raw_keys: np.ndarray, bucket_ids: np.ndarray) -> None:
        """Record one hashed batch (same shapes; ``-1`` padding slots in
        ``bucket_ids`` are skipped)."""
        flat_keys = np.asarray(raw_keys).reshape(-1)
        flat_ids = np.asarray(bucket_ids).reshape(-1)
        for key, bucket in zip(flat_keys, flat_ids):
            b = int(bucket)
            if b < 0:
                continue
            if isinstance(key, np.str_):
                key = str(key)
            elif isinstance(key, np.bytes_):
                key = bytes(key)
            data = _key_bytes(key)
            fp = (murmur3_32(data, 0x9747B28C) << 32) | murmur3_32(
                data, self.seed ^ 0x5BD1E995
            )
            seen = self._buckets.setdefault(b, set())
            if fp in seen:
                continue
            if self._keys_seen >= self.max_keys:
                self._saturated = True
                continue
            if seen:
                self._collisions += 1
            seen.add(fp)
            self._keys_seen += 1
        self.publish()

    @property
    def keys_seen(self) -> int:
        return self._keys_seen

    @property
    def collisions(self) -> int:
        return self._collisions

    @property
    def collision_rate(self) -> float:
        return self._collisions / self._keys_seen if self._keys_seen else 0.0

    def publish(self) -> None:
        g = self._metrics
        g.gauge("keys_seen", float(self._keys_seen))
        g.gauge("buckets_used", float(len(self._buckets)))
        g.gauge("collisions", float(self._collisions))
        g.gauge("collision_rate", self.collision_rate)
        g.gauge("expected_collision_rate", expected_collision_fraction(
            self._keys_seen, self.num_buckets))
        g.gauge("saturated", 1.0 if self._saturated else 0.0)


class HashedFeature:
    """The hash transform as a pipeline stage: ``transform(Table) ->
    (Table,)``, mapping ``input_col``'s raw keys (``[n]`` or ``[n, L]``
    str/int) to ``output_col`` int32 row ids — droppable in front of any
    id-consuming stage (:class:`~flinkml_tpu.embeddings.serving.
    EmbeddingLookupModel`, the hashed-FM model) and wrappable as a
    Dataset op (``Dataset.hash_column``). Stateless and deterministic
    (a pure function of (key, seed)), so the data plane's replay/resume
    contract holds through it; the optional collision tracker is
    observability only and never influences output."""

    def __init__(
        self,
        seed: int,
        num_buckets: int,
        *,
        input_col: str = "keys",
        output_col: str = "hashed_ids",
        pad_key: Optional[int] = None,
        track_collisions: bool = False,
        name: str = "hashed",
        max_tracked_keys: int = 100_000,
    ):
        if num_buckets < 1:
            raise ValueError(f"num_buckets must be >= 1, got {num_buckets}")
        self.seed = int(seed)
        self.num_buckets = int(num_buckets)
        self.input_col = input_col
        self.output_col = output_col
        self.pad_key = pad_key
        self.name = name
        self.tracker: Optional[CollisionTracker] = (
            CollisionTracker(name, num_buckets, self.seed,
                             max_keys=max_tracked_keys)
            if track_collisions else None
        )

    def __call__(self, table) -> Any:
        """Map-function form (``Dataset.map`` / ``HashOp`` compatible)."""
        raw = np.asarray(table.column(self.input_col))
        ids = hash_buckets(
            raw, seed=self.seed, num_buckets=self.num_buckets,
            pad_key=self.pad_key,
        )
        if self.tracker is not None:
            self.tracker.observe(raw, ids)
        return table.with_column(self.output_col, ids)

    def transform(self, *inputs) -> Tuple[Any, ...]:
        (table,) = inputs
        return (self(table),)

    def describe(self) -> str:
        return (f"hash({self.input_col!r} -> {self.output_col!r}, "
                f"seed={self.seed}, buckets={self.num_buckets})")
