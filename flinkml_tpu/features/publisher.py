"""DeltaPublisher — trainer state → registry, incrementally.

Publishes the :class:`~flinkml_tpu.features.trainer.
StreamingHashedFMTrainer`'s state on a batch cadence. The first publish
is a full snapshot (the chain's base). Every one after ships only what
moved: the rows the trainer touched since the last publish plus the
dense leaves, as a :class:`~flinkml_tpu.features.delta.ModelDelta`
fingerprint-chained to the previous version. When the chain reaches
``max_depth`` the next publish **compacts**: a fresh full snapshot
resets the depth to zero, bounding both the registry ``get`` walk and
the blast radius of a pruned base.

Every publish — delta or full — is stamped with the trainer's
source-batch watermark (the registry's ``watermark=`` hook), which is
what the pool's ``serving.<pool>.freshness`` gauge subtracts from the
trainer's live watermark. No wall clocks.

Byte accounting rides the ``features.publisher`` metrics group
(``delta_bytes`` / ``full_bytes`` / ``delta_ratio``) so the bench's
delta-vs-snapshot ratio and a production dashboard read the same
numbers.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from flinkml_tpu.features.delta import ModelDelta
from flinkml_tpu.utils.logging import get_logger
from flinkml_tpu.utils.metrics import metrics

_log = get_logger("features.publisher")


class DeltaPublisher:
    """See module docstring."""

    def __init__(
        self,
        registry,
        trainer,
        *,
        every_n_batches: int = 1,
        max_depth: int = 8,
        check_finite: bool = True,
        name: str = "features",
    ):
        if every_n_batches < 1:
            raise ValueError(
                f"need every_n_batches >= 1, got {every_n_batches}")
        if max_depth < 1:
            raise ValueError(f"need max_depth >= 1, got {max_depth}")
        self.registry = registry
        self.trainer = trainer
        self.every_n_batches = int(every_n_batches)
        self.max_depth = int(max_depth)
        self.check_finite = bool(check_finite)
        self._last_version: Optional[int] = None
        self._last_fingerprint: Optional[str] = None
        self._last_watermark = -1
        self._depth = 0
        self._metrics = metrics.group("features.publisher",
                                      labels={"publisher": name})

    @property
    def last_version(self) -> Optional[int]:
        return self._last_version

    @property
    def chain_depth(self) -> int:
        """Deltas since the newest full snapshot in this chain."""
        return self._depth

    def maybe_publish(self) -> Optional[int]:
        """Publish if ``every_n_batches`` trainer batches accumulated
        since the last publish; returns the new version or None."""
        if (self.trainer.watermark - self._last_watermark
                < self.every_n_batches):
            return None
        return self.publish_now()

    def publish_now(self) -> int:
        """Publish unconditionally: a full snapshot when there is no base
        yet or the chain hit ``max_depth`` (compaction), a row delta
        otherwise. Returns the registry version."""
        if self._last_version is None:
            return self._publish_full(reason="base")
        if self._depth >= self.max_depth:
            self._metrics.counter("compactions")
            _log.info("chain depth %d hit max_depth=%d: compacting to a "
                      "full snapshot", self._depth, self.max_depth)
            return self._publish_full(reason="compaction")
        return self._publish_delta()

    # -- internals ---------------------------------------------------------
    def _state_bytes(self) -> int:
        return int(sum(np.asarray(a).nbytes
                       for a in self.trainer.delta_state().values()))

    def _publish_full(self, reason: str) -> int:
        model = self.trainer.make_model()
        watermark = self.trainer.watermark
        v = self.registry.publish(model, watermark=watermark,
                                  check_finite=self.check_finite)
        self.trainer.drain_touched()  # the snapshot carries everything
        self._last_version = v
        self._last_fingerprint = self.trainer.state_fingerprint()
        self._last_watermark = watermark
        self._depth = 0
        full_bytes = self._state_bytes()
        self._metrics.counter("full_publishes")
        self._metrics.gauge("full_bytes", full_bytes)
        self._metrics.gauge("chain_depth", 0)
        _log.info("full publish (%s): version %d, watermark %d, %d bytes",
                  reason, v, watermark, full_bytes)
        return v

    def _publish_delta(self) -> int:
        ids = self.trainer.drain_touched()
        rows = self.trainer.rows_for(ids)
        watermark = self.trainer.watermark
        result_fp = self.trainer.state_fingerprint()
        delta = ModelDelta.build(
            base_version=self._last_version,
            base_fingerprint=self._last_fingerprint,
            result_fingerprint=result_fp,
            watermark=watermark,
            depth=self._depth + 1,
            row_deltas={name: (ids, values)
                        for name, values in rows.items()},
            dense_deltas={"w0": np.asarray(self.trainer.w0)},
            model_class="flinkml_tpu.features.model.HashedFMModel",
        )
        v = self.registry.publish(delta, watermark=watermark,
                                  check_finite=self.check_finite)
        self._last_version = v
        self._last_fingerprint = result_fp
        self._last_watermark = watermark
        self._depth += 1
        delta_bytes = delta.payload_bytes()
        full_bytes = self._state_bytes()
        self._metrics.counter("delta_publishes")
        self._metrics.gauge("delta_bytes", delta_bytes)
        self._metrics.gauge("full_bytes", full_bytes)
        self._metrics.gauge("delta_ratio",
                            delta_bytes / full_bytes if full_bytes else 0.0)
        self._metrics.gauge("chain_depth", self._depth)
        _log.info(
            "delta publish: version %d on base %d (depth %d), watermark "
            "%d, %d rows, %d bytes (%.1f%% of full)",
            v, delta.base_version, self._depth, watermark, ids.shape[0],
            delta_bytes, 100.0 * delta_bytes / max(full_bytes, 1),
        )
        return v
