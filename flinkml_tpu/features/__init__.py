"""flinkml_tpu.features — the streaming feature platform.

Two halves close the train-to-serve freshness loop:

- **hashing** (:mod:`.hashing`) — a seeded, process-stable hash front
  end mapping raw string/int keys straight to embedding-table rows, no
  vocabulary build, with measured collision telemetry
  (``features.hash``) and the FML505 buckets-vs-vocab gate.
- **incremental publishes** (:mod:`.delta`, :mod:`.trainer`,
  :mod:`.publisher`, :mod:`.model`) — a streaming FM trainer whose
  touched rows publish as fingerprint-chained
  :class:`~flinkml_tpu.features.delta.ModelDelta` versions that serving
  replicas patch in place, so fresh rows reach a pool without a single
  full-model republish on the hot path.

Operator guide: ``docs/operators/features.md``.
"""

from flinkml_tpu.features.delta import ModelDelta
from flinkml_tpu.features.hashing import (
    CollisionTracker,
    HashedFeature,
    HashVocabMismatchError,
    check_hash_vocab,
    expected_collision_fraction,
    hash_buckets,
    murmur3_32,
)
from flinkml_tpu.features.model import HashedFMModel
from flinkml_tpu.features.publisher import DeltaPublisher
from flinkml_tpu.features.trainer import StreamingHashedFMTrainer

__all__ = [
    "CollisionTracker",
    "DeltaPublisher",
    "HashVocabMismatchError",
    "HashedFMModel",
    "HashedFeature",
    "ModelDelta",
    "StreamingHashedFMTrainer",
    "check_hash_vocab",
    "expected_collision_fraction",
    "hash_buckets",
    "murmur3_32",
]
