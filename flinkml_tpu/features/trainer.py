"""StreamingHashedFMTrainer — unbounded hashed-id stream → FM state.

The training half of the freshness loop: consumes ``[n, L]`` hashed-id
batches (``-1`` padded — exactly what :class:`~flinkml_tpu.features.
hashing.HashedFeature` / ``Dataset.hash_column`` emit) and keeps the FM
state in :class:`~flinkml_tpu.embeddings.table.EmbeddingTable`\\ s, so
the same object trains unsharded on a laptop and row-sharded on a mesh
with no code change — updates flow through ``scatter_add`` (the
strategy-gated exchange), never a dense gradient.

What makes it a *delta source* rather than just a trainer:

- it tracks the exact row ids touched since the last publish
  (:meth:`drain_touched`), which is precisely the payload of an
  incremental publish — the publisher ships those rows' CURRENT
  contents, nothing else;
- it counts batches into a **watermark** (:attr:`watermark`), the
  freshness currency: every publish is stamped with it, and the pool's
  ``serving.<pool>.freshness`` gauge is trainer-watermark minus
  served-watermark, with no wall clock anywhere;
- :meth:`delta_state` / :meth:`state_fingerprint` expose the full state
  under the same names/fingerprint the served
  :class:`~flinkml_tpu.features.model.HashedFMModel` reports, so the
  registry can verify a delta chain end-to-end against trainer truth.

Optimizer: plain SGD on the mean logistic loss of the sparse FM margin
(the :mod:`~flinkml_tpu.models.fm` identity). Deliberately stateless
beyond the parameters — optimizer slots would just ride along as more
row tables in a delta.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from flinkml_tpu.features.model import HashedFMModel
from flinkml_tpu.io.read_write import content_fingerprint
from flinkml_tpu.utils.metrics import metrics


class StreamingHashedFMTrainer:
    """See module docstring."""

    def __init__(
        self,
        *,
        num_buckets: int,
        factor_size: int = 8,
        hash_seed: int = 0,
        learning_rate: float = 0.05,
        init_scale: float = 0.01,
        seed: int = 0,
        mesh=None,
        plan=None,
        input_col: str = "hashed_ids",
        name: str = "hashed_fm",
    ):
        from flinkml_tpu.embeddings.table import EmbeddingTable

        if num_buckets < 1:
            raise ValueError(f"need num_buckets >= 1, got {num_buckets}")
        if factor_size < 1:
            raise ValueError(f"need factor_size >= 1, got {factor_size}")
        self.num_buckets = int(num_buckets)
        self.factor_size = int(factor_size)
        self.hash_seed = int(hash_seed)
        self.learning_rate = float(learning_rate)
        self.input_col = input_col
        self.plan = plan
        self.w0 = np.zeros(1, np.float32)
        self._w_table = EmbeddingTable(
            f"{name}/w", self.num_buckets, 1, mesh=mesh, plan=plan
        )
        self._v_table = EmbeddingTable(
            f"{name}/v", self.num_buckets, self.factor_size, mesh=mesh,
            plan=plan, seed=seed, scale=init_scale,
        )
        #: Batches consumed so far — the freshness watermark every
        #: publish is stamped with.
        self.watermark = 0
        self._touched_since_publish: set = set()
        self._metrics = metrics.group("features.trainer",
                                      labels={"trainer": name})

    # -- training ----------------------------------------------------------
    def fit_batch(self, ids: np.ndarray, labels: np.ndarray) -> float:
        """One SGD step on an ``[n, L]`` hashed-id batch (``-1`` padded)
        with ``[n]`` binary labels. Returns the batch's mean logloss."""
        ids = np.asarray(ids, np.int64)
        if ids.ndim == 1:
            ids = ids[:, None]
        labels = np.asarray(labels, np.float32).reshape(-1)
        n, L = ids.shape
        if labels.shape[0] != n:
            raise ValueError(f"{n} id rows != {labels.shape[0]} labels")
        mask = ids >= 0
        if ids.max(initial=-1) >= self.num_buckets:
            raise ValueError(
                f"hashed id {int(ids.max())} out of range "
                f"[0, {self.num_buckets}) — front-end num_buckets and "
                "trainer num_buckets disagree (the FML505 condition)"
            )
        safe = np.where(mask, ids, 0)
        fmask = mask.astype(np.float32)

        v_rows = np.asarray(self._v_table.lookup(safe)) * fmask[..., None]
        w_rows = np.asarray(self._w_table.lookup(safe))[..., 0] * fmask
        sv = v_rows.sum(axis=1)                              # [n, k]
        pair = 0.5 * ((sv * sv) - (v_rows * v_rows).sum(axis=1)).sum(axis=1)
        margin = self.w0[0] + w_rows.sum(axis=1) + pair      # [n]
        prob = 1.0 / (1.0 + np.exp(-margin))
        g = (prob - labels).astype(np.float32) / float(n)    # dL/dmargin

        # Masked slots scatter id 0 with a zero row — an exact no-op add.
        flat_ids = safe.reshape(-1).astype(np.int32)
        gw = (g[:, None] * fmask).reshape(-1, 1)
        gv = (g[:, None, None] * (sv[:, None, :] - v_rows)
              * fmask[..., None]).reshape(-1, self.factor_size)
        lr = self.learning_rate
        self._w_table.scatter_add(flat_ids, (-lr * gw).astype(np.float32))
        self._v_table.scatter_add(flat_ids, (-lr * gv).astype(np.float32))
        self.w0 = (self.w0 - lr * g.sum()).astype(np.float32)

        self._touched_since_publish.update(int(i) for i in ids[mask])
        self.watermark += 1
        self._metrics.counter("batches")
        self._metrics.counter("rows", n)
        self._metrics.gauge("watermark", self.watermark)
        self._metrics.gauge("touched_rows", len(self._touched_since_publish))
        eps = 1e-7
        p = np.clip(prob, eps, 1.0 - eps)
        return float(-(labels * np.log(p)
                       + (1.0 - labels) * np.log(1.0 - p)).mean())

    # -- delta source ------------------------------------------------------
    def drain_touched(self) -> np.ndarray:
        """Sorted row ids touched since the last drain — the id set an
        incremental publish ships — and reset the tracker."""
        out = np.array(sorted(self._touched_since_publish), np.int32)
        self._touched_since_publish.clear()
        return out

    def rows_for(self, ids: np.ndarray) -> Dict[str, np.ndarray]:
        """The CURRENT contents of ``ids`` rows per table — a delta's
        values arrays."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        return {
            "w": self._w_table.to_host()[ids],
            "v": self._v_table.to_host()[ids],
        }

    def delta_state(self) -> Dict[str, np.ndarray]:
        return {"w0": np.asarray(self.w0),
                "w": self._w_table.to_host(),
                "v": self._v_table.to_host()}

    def state_fingerprint(self) -> str:
        """``content_fingerprint`` over :meth:`delta_state` — the chain
        currency; matches the served model's fingerprint bit-for-bit."""
        return content_fingerprint(self.delta_state())

    def make_model(self, plan=None) -> HashedFMModel:
        """A host-side :class:`HashedFMModel` of the current state (a
        full-snapshot publish; the engine mesh-binds it on install)."""
        state = self.delta_state()
        return HashedFMModel.from_arrays(
            state["w0"], state["w"], state["v"],
            num_buckets=self.num_buckets, hash_seed=self.hash_seed,
            input_col=self.input_col, plan=plan if plan else self.plan,
        )
