"""The five stage interfaces of the pipeline API.

Parity with the reference (``flink-ml-core/.../ml/api/``):
  - ``Stage`` = WithParams + save/load (``Stage.java:34-44``),
  - ``AlgoOperator.transform(*tables)`` (``AlgoOperator.java:31-38``),
  - ``Transformer`` marker (``Transformer.java:32``),
  - ``Model`` adds ``set_model_data``/``get_model_data`` (``Model.java:38-50``),
  - ``Estimator.fit(*tables) -> Model`` (``Estimator.java:31-38``).

TPU-first difference: tables are in-memory columnar batches (`Table`), and
fit/transform execute eagerly (JAX jit caching makes repeated execution cheap)
instead of lazily building a dataflow graph — the laziness in the reference
exists to serve Flink's deployment model, not the ML semantics.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from flinkml_tpu.io import read_write
from flinkml_tpu.params import WithParams
from flinkml_tpu.table import Table


@dataclasses.dataclass(frozen=True)
class ColumnKernel:
    """A stage's transform as a pure columnar device function — the unit the
    fused pipeline executor (:mod:`flinkml_tpu.pipeline_fusion`) composes
    into one XLA program per run of kernel-capable stages.

    ``fn(cols, consts, valid)`` maps a dict of device arrays (one per
    ``input_cols`` entry, leading axis = padded rows) plus a dict of
    constant arrays (the fitted model data, uploaded as traced arguments so
    model-data changes never force a retrace) and a float32 ``[rows]``
    validity mask (1.0 for real rows, 0.0 for bucket padding) to a dict of
    output device arrays named by ``output_cols``.

    Contract:
      - ``fn`` must be pure and total: no data-dependent host control flow,
        no raising on bad values (stages whose transform validates input
        must gate ``transform_kernel`` to configurations that don't).
      - ``fn``'s *traced structure* must be fully determined by
        ``fingerprint``: two kernels with equal fingerprints and equal
        constant shapes/dtypes must trace to the same program. Anything
        that changes the math (column names, flags, static sizes) belongs
        in the fingerprint; anything that only changes values belongs in
        ``constants``.
      - Row-wise semantics: padded rows may compute garbage; the executor
        slices them off. Cross-row reductions must apply ``valid``.
      - ``pin_inputs``: set True when ``fn`` contains ops whose XLA
        lowering is fusion-context-sensitive (transcendentals, matmuls,
        reductions — anything not exactly rounded elementwise). The
        executor then materializes this kernel's chain-produced input
        columns as program outputs, pinning the fusion boundary so the
        kernel's ops lower in the same context as the stand-alone
        per-stage program — without this, a sigmoid fused into an
        upstream scaler chain can differ from the per-stage path by
        1 ulp. Exactly-rounded elementwise kernels (scalers, one-hot,
        concat) leave it False and fuse freely.
    """

    input_cols: Tuple[str, ...]
    output_cols: Tuple[str, ...]
    fn: Callable[[Dict[str, Any], Dict[str, Any], Any], Dict[str, Any]]
    constants: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    fingerprint: Tuple = ()
    pin_inputs: bool = False


class Stage(WithParams, abc.ABC):
    """Base class for nodes in a Pipeline or Graph; save/load-able.

    Saving follows the reference convention (``Stage.java:34-44``): a stage
    directory holds a JSON ``metadata`` file; subclasses with model data add
    arrays under ``data/``. ``load`` is a classmethod; the generic loader
    (``flinkml_tpu.io.read_write.load_stage``) dispatches on the recorded
    class name, mirroring the static-load reflection convention.
    """

    def save(self, path: str) -> None:
        read_write.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "Stage":
        expected = f"{cls.__module__}.{cls.__qualname__}"
        meta = read_write.load_metadata(path, expected_class_name=expected)
        return read_write.instantiate_with_params(cls, meta["paramMap"])


class AlgoOperator(Stage):
    """A Stage that computes output tables from input tables.

    Parity: ``AlgoOperator.java:31-38``.
    """

    @abc.abstractmethod
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        """Apply the operator to the inputs; returns a tuple of result tables."""

    def transform_kernel(self) -> Optional[ColumnKernel]:
        """The stage's transform as a fusable :class:`ColumnKernel`, or
        ``None`` when the stage (or its current configuration) cannot be
        expressed as a pure columnar device function.

        ``PipelineModel.transform`` partitions its chain into maximal runs
        of kernel-capable stages and compiles each run as ONE jitted
        program (:mod:`flinkml_tpu.pipeline_fusion`); stages returning
        ``None`` execute through the regular per-stage ``transform`` path.
        The kernel must reproduce ``transform``'s output bit-for-bit on
        valid dense input (same dtypes, same op order) — the fused and
        per-stage paths are interchangeable, not approximations of each
        other.
        """
        return None


class Transformer(AlgoOperator):
    """An AlgoOperator with the semantics of a feature engineering /
    prediction step. Parity: ``Transformer.java:32``."""


class Model(Transformer):
    """A Transformer parameterized by fitted model data.

    Parity: ``Model.java:31-50`` — model data is exposed as tables so it can
    be inspected, transferred, and persisted independently of the stage.
    """

    def set_model_data(self, *inputs: Table) -> "Model":
        raise NotImplementedError(
            f"{type(self).__name__} does not support set_model_data"
        )

    def get_model_data(self) -> List[Table]:
        raise NotImplementedError(
            f"{type(self).__name__} does not support get_model_data"
        )

    # -- shared persistence scaffold ---------------------------------------
    def _save_with_arrays(self, path: str, arrays, extra=None) -> None:
        """Standard model layout: metadata JSON + named arrays under data/.

        The metadata records a sha256 content fingerprint of the arrays +
        param map; load verifies it, so a tampered/truncated/mixed-up
        model directory fails loudly
        (:class:`~flinkml_tpu.io.read_write.ModelIntegrityError`) instead
        of serving corrupt predictions."""
        extra = dict(extra or {})
        extra[read_write.FINGERPRINT_KEY] = read_write.content_fingerprint(
            arrays, self.get_param_map_json()
        )
        read_write.save_metadata(self, path, extra=extra)
        read_write.save_model_arrays(path, arrays)

    @classmethod
    def _load_with_arrays(cls, path: str):
        """Counterpart of ``_save_with_arrays``: class-checked metadata,
        fingerprint-verified arrays, params restored; returns
        ``(model, arrays, metadata)``."""
        meta = read_write.load_metadata(
            path, expected_class_name=f"{cls.__module__}.{cls.__qualname__}"
        )
        model = cls()
        model.load_param_map_json(meta["paramMap"])
        arrays = read_write.load_model_arrays(path)
        recorded = meta.get(read_write.FINGERPRINT_KEY)
        if recorded is not None:
            actual = read_write.content_fingerprint(arrays, meta["paramMap"])
            if actual != recorded:
                raise read_write.ModelIntegrityError(
                    f"model data at {path} does not match its recorded "
                    f"content fingerprint (recorded {recorded[:12]}..., "
                    f"actual {actual[:12]}...): the persisted arrays or "
                    "params were modified after save"
                )
        return model, arrays, meta


class Estimator(Stage):
    """Fits a Model from training tables. Parity: ``Estimator.java:31-38``."""

    @abc.abstractmethod
    def fit(self, *inputs: Table) -> Model:
        ...
