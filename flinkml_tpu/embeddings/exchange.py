"""Sparse lookup + gradient exchange over sharded embedding rows.

The device-side half of :mod:`flinkml_tpu.embeddings`: a family of
shard-level primitives, called INSIDE ``shard_map``, that move **batch-
sized row payloads** between the shards of a row-sharded ``[vocab, dim]``
table — never a vocab-sized dense array and never a host gather. They
generalize the Word2Vec vocab-sharded ring trainer's masked-gather /
masked-scatter loops (``flinkml_tpu/models/word2vec.py``, PR "scale
path") from one hard-coded ``data`` axis to ANY composite axis tuple a
:class:`~flinkml_tpu.sharding.plan.ShardingPlan` names — the ``EMBEDDING``
family's ``(fsdp, tp)`` product included (``ppermute``/``psum``/
``all_to_all`` all accept composite axis names; verified against this
repo's jax pin).

Ownership contract (shared by every strategy): shard ``r`` (the
flattened ``axis_index`` over ``axes``) owns global rows
``[r·shard_rows, (r+1)·shard_rows)`` of the padded table. A gather sums
per-shard contributions that are zero everywhere except the one owning
shard, so **lookups are exact** — bitwise identical across strategies
AND across world sizes (adding f32 zeros is exact). Scatter-adds differ
between strategies only in f32 summation order on duplicate ids, the
same contract the W2V ring trainer already pins against its dense twin.

Three strategies (the ``embedding_exchange`` autotune knob family):

- ``ring`` — ids + row accumulators ride ``ppermute`` hops; every
  visited shard adds the rows it owns. P hops of ``batch × dim``
  payload; the W2V formulation, lifted verbatim.
- ``all_to_all`` — ids ``all_gather`` to every shard (cheap ints), each
  shard produces its masked contribution for the full global id list,
  and ONE ``all_to_all`` routes contributions home (gather) or the
  gathered rows scatter into the local shard via the PR 12 padded-ELL
  ``segment_sum`` kernel gate (scatter). Same total traffic as the
  ring, 2 collectives instead of 2·P hops — the latency bet the device
  re-tune decides.
- ``dense_psum`` — not an exchange at all: the below-threshold
  placement where the table stays replicated and gradients ride one
  dense ``[vocab, dim]`` psum per step (the classic W2V dense trainer).
  :func:`resolve_exchange` routes small vocabs here, subsuming W2V's
  static ``_shard_vocab_threshold``; above the threshold it is refused
  (a vocab-sized psum is exactly what the subsystem exists to avoid).

Resolution precedence at every consumer (the repo's layout-gate idiom):
explicit ``FLINKML_TPU_EMBEDDING_EXCHANGE`` env var > the autotune
table's measured ``embedding_exchange`` winner for this mesh > the
static ``ring`` default. Consumers thread the resolved strategy through
their trainer factories' ``lru_cache`` keys, so a gate flip re-keys the
jitted program instead of silently reusing the old one.
"""

from __future__ import annotations

import os
from typing import Sequence, Tuple, Union

Axes = Union[str, Tuple[str, ...]]

#: The exchange strategies (and the autotune knob's candidate set).
STRATEGIES = ("ring", "all_to_all", "dense_psum")

#: Explicit strategy override (highest precedence).
ENV_VAR = "FLINKML_TPU_EMBEDDING_EXCHANGE"

#: Vocab-size override for the dense-psum threshold (lowest vocab that
#: SHARDS). ``FLINKML_W2V_SHARD_VOCAB`` is honored as a back-compat
#: alias (it predates this subsystem; 0 forces sharding — the test hook).
ENV_DENSE_VOCAB_VAR = "FLINKML_TPU_EMBEDDING_DENSE_VOCAB"

#: Below this vocab size a dense [vocab, dim] gradient psum per step
#: beats bespoke sparse collectives (the W2V measurement that set the
#: original ``_shard_vocab_threshold``).
DENSE_VOCAB_DEFAULT = 1 << 18


def dense_vocab_threshold() -> int:
    """The vocab size at or below which tables stay replicated and
    gradients ride a dense psum (the ``dense_psum`` placement)."""
    for var in (ENV_DENSE_VOCAB_VAR, "FLINKML_W2V_SHARD_VOCAB"):
        raw = os.environ.get(var)
        if raw is not None:
            return int(raw)
    return DENSE_VOCAB_DEFAULT


def exchange_strategy() -> str:
    """The SHARDED exchange algorithm (``ring`` or ``all_to_all``):
    env var > autotune table > static ``ring``.

    ``dense_psum`` is a PLACEMENT (replicated table), not a sharded
    algorithm, so the two sources treat it differently: an EXPLICIT
    ``FLINKML_TPU_EMBEDDING_EXCHANGE=dense_psum`` on a sharded table is
    refused loudly (the gate idiom — an explicit request must never be
    silently rewritten; raise the dense-vocab threshold instead to
    force the dense placement), while a table-COMMITTED ``dense_psum``
    winner quietly falls back to ``ring`` (the knob's measurement size
    says nothing about an over-threshold table, which cannot ride a
    vocab-sized psum)."""
    raw = os.environ.get(ENV_VAR)
    if raw is not None:
        if raw not in STRATEGIES:
            raise ValueError(
                f"{ENV_VAR}={raw!r}: expected one of {STRATEGIES}"
            )
        if raw == "dense_psum":
            raise ValueError(
                f"{ENV_VAR}=dense_psum: dense_psum is the replicated "
                "PLACEMENT, not a sharded exchange algorithm — to force "
                f"the dense path, raise the vocab threshold instead "
                f"({ENV_DENSE_VOCAB_VAR}, or the FLINKML_W2V_SHARD_VOCAB "
                "alias); on an already-sharded table pick 'ring' or "
                "'all_to_all'"
            )
        return raw
    from flinkml_tpu.autotune import tuned_default

    chosen = tuned_default("embedding_exchange", "ring",
                           allowed=STRATEGIES)
    return chosen if chosen in ("ring", "all_to_all") else "ring"


def resolve_exchange(vocab: int, n_shards: int) -> str:
    """The strategy for a ``vocab``-row table over ``n_shards`` shards —
    the ONE decision point subsuming W2V's static threshold:
    ``dense_psum`` (replicated table, dense gradient psum) when the
    table cannot shard (``n_shards == 1``) or is small enough that the
    dense psum measured faster; else the tuned sharded algorithm."""
    if n_shards <= 1 or vocab <= dense_vocab_threshold():
        return "dense_psum"
    return exchange_strategy()


def shard_rows_for(vocab: int, n_shards: int) -> int:
    """Rows per shard (ceil) — shard ``r`` owns
    ``[r·shard_rows, (r+1)·shard_rows)`` of the zero-padded table."""
    return -(-int(vocab) // int(n_shards))


# -- shard-level primitives (call INSIDE shard_map) -------------------------


def _vary(x, axes: Axes):
    """Mark ``x`` device-varying over ``axes`` if it is not already
    (replicated operands entering a ring/fori carry must be uniformly
    varying — the W2V ``vary`` idiom, composite-axis-ready)."""
    import jax

    want = (axes,) if isinstance(axes, str) else tuple(axes)
    vma = jax.typeof(x).vma
    if all(a in vma for a in want):
        return x
    return jax.lax.pcast(x, axes, to="varying")


def owned(ids, axes: Axes, shard_rows: int):
    """``(mask, safe local index)`` for the global ids THIS shard owns."""
    import jax
    import jax.numpy as jnp

    lo = jax.lax.axis_index(axes) * shard_rows
    local_idx = ids - lo
    mask = (local_idx >= 0) & (local_idx < shard_rows)
    return mask, jnp.clip(local_idx, 0, shard_rows - 1)


def ring_gather(pairs: Sequence, *, axes: Axes, n_shards: int,
                shard_rows: int):
    """Rows of the row-sharded tables for each ``(table_shard, ids)`` in
    ``pairs`` — ONE ``ppermute`` ring loop carries every payload (ring
    latency paid once, not per table). ``ids`` may be ``[bs]`` or
    ``[bs, n]``; returns one ``ids.shape + (dim,)`` array per pair."""
    import jax
    import jax.numpy as jnp

    ring = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    idss = tuple(_vary(ids, axes) for _, ids in pairs)
    accs = tuple(
        _vary(jnp.zeros(ids.shape + (t.shape[1],), t.dtype), axes)
        for (t, _), ids in zip(pairs, idss)
    )

    def hop(_, carry):
        idss_c, accs_c = carry
        out = []
        for (table, _), ids_c, acc_c in zip(pairs, idss_c, accs_c):
            mask, safe = owned(ids_c, axes, shard_rows)
            out.append(acc_c + jnp.where(mask[..., None], table[safe], 0.0))
        return (
            tuple(jax.lax.ppermute(i, axes, ring) for i in idss_c),
            tuple(jax.lax.ppermute(a, axes, ring) for a in out),
        )

    _, accs_out = jax.lax.fori_loop(0, n_shards, hop, (idss, accs))
    return accs_out  # n_shards hops: payloads are back home, complete


def ring_scatter_add(tables: Sequence, triples: Sequence, *, axes: Axes,
                     n_shards: int, shard_rows: int):
    """Scatter-add each ``(table_slot, ids, rows)`` in ``triples`` into
    ``tables`` (a tuple of row-sharded shards) via ONE ring loop for
    every payload; returns the updated tuple."""
    import jax
    import jax.numpy as jnp

    ring = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    idss = tuple(_vary(ids, axes) for _, ids, _ in triples)
    rowss = tuple(_vary(rows, axes) for _, _, rows in triples)

    def hop(_, carry):
        idss_c, rowss_c, tabs = carry
        tabs = list(tabs)
        for (slot, _, _), ids_c, rows_c in zip(triples, idss_c, rowss_c):
            mask, safe = owned(ids_c, axes, shard_rows)
            tabs[slot] = tabs[slot].at[safe.reshape(-1)].add(
                jnp.where(mask[..., None], rows_c, 0.0)
                .reshape(-1, rows_c.shape[-1])
            )
        return (
            tuple(jax.lax.ppermute(i, axes, ring) for i in idss_c),
            tuple(jax.lax.ppermute(x, axes, ring) for x in rowss_c),
            tuple(tabs),
        )

    _, _, tables = jax.lax.fori_loop(
        0, n_shards, hop, (idss, rowss, tuple(tables))
    )
    return tables


def _flat_sizes(idss) -> Tuple[int, ...]:
    sizes = []
    for ids in idss:
        m = 1
        for d in ids.shape:
            m *= int(d)
        sizes.append(m)
    return tuple(sizes)


def a2a_gather(pairs: Sequence, *, axes: Axes, n_shards: int,
               shard_rows: int):
    """The ``all_to_all`` gather: ids ``all_gather`` to every shard,
    each shard contributes its masked rows for the FULL global id list,
    one ``all_to_all`` routes contributions home, and the sum over
    source shards (exactly one non-zero each) completes the rows —
    bitwise equal to :func:`ring_gather`.

    Like the ring loop, every payload in ``pairs`` rides ONE collective
    round — the flattened id lists concatenate into one ``all_gather``
    and the per-table masked contributions into one ``all_to_all`` (the
    tables' dims must match, which the W2V/table consumers guarantee;
    mixed dims fall back to a round per payload). Latency is what the
    strategy competes on, so per-payload collectives would bias the
    device re-tune against it."""
    import jax
    import jax.numpy as jnp

    dims = sorted({int(t.shape[1]) for t, _ in pairs})
    if len(dims) > 1:
        out = []
        for pair in pairs:
            out.extend(a2a_gather((pair,), axes=axes, n_shards=n_shards,
                                  shard_rows=shard_rows))
        return tuple(out)
    dim = dims[0]
    ms = _flat_sizes([ids for _, ids in pairs])
    total = sum(ms)
    flat = jnp.concatenate(
        [_vary(ids.reshape(-1), axes) for _, ids in pairs]
    )                                                    # [M]
    idsg = jax.lax.all_gather(flat, axes, tiled=True)    # [P*M]
    per_src = idsg.reshape(n_shards, total)
    contribs = []
    offset = 0
    for (table, _), m in zip(pairs, ms):
        seg = per_src[:, offset:offset + m].reshape(-1)
        mask, safe = owned(seg, axes, shard_rows)
        contribs.append(
            jnp.where(mask[:, None], table[safe], 0.0)
            .reshape(n_shards, m, dim)
        )
        offset += m
    back = jax.lax.all_to_all(
        jnp.concatenate(contribs, axis=1),               # [P, M, dim]
        axes, split_axis=0, concat_axis=0, tiled=True,
    )
    rows = jnp.sum(back, axis=0)                         # [M, dim]
    out = []
    offset = 0
    for (_, ids), m in zip(pairs, ms):
        out.append(rows[offset:offset + m].reshape(ids.shape + (dim,)))
        offset += m
    return tuple(out)


def a2a_scatter_add(tables: Sequence, triples: Sequence, *, axes: Axes,
                    n_shards: int, shard_rows: int,
                    segsum_backend: str = "xla"):
    """The ``all_to_all``-family scatter: every shard ``all_gather``s the
    (ids, rows) payloads and segment-sums the rows IT owns into its
    shard — the scatter rides the PR 12 padded-ELL ``segment_sum``
    kernel gate (``segsum_backend`` is lru-key material at every
    consumer, so a kernel-gate flip re-keys the jitted trainer). Masked
    (non-owned) rows segment-sum as zeros into local row 0 — the ELL
    no-op-add convention.

    All payloads ride ONE id ``all_gather`` + ONE row ``all_gather``
    (equal-dim payloads concatenate; mixed dims fall back to a round
    per payload) — the same latency discipline as :func:`a2a_gather`;
    the per-slot segment-sums stay separate, so the per-payload f32
    accumulation order is unchanged."""
    import jax
    import jax.numpy as jnp

    from flinkml_tpu import kernels

    dims = sorted({int(rows.shape[-1]) for _, _, rows in triples})
    if len(dims) > 1:
        for triple in triples:
            tables = a2a_scatter_add(
                tables, (triple,), axes=axes, n_shards=n_shards,
                shard_rows=shard_rows, segsum_backend=segsum_backend,
            )
        return tuple(tables)
    dim = dims[0]
    tables = list(tables)
    ms = _flat_sizes([ids for _, ids, _ in triples])
    total = sum(ms)
    flat_ids = jnp.concatenate(
        [_vary(ids.reshape(-1), axes) for _, ids, _ in triples]
    )
    flat_rows = jnp.concatenate(
        [_vary(rows.reshape(-1, dim), axes) for _, _, rows in triples]
    )
    idsg = jax.lax.all_gather(flat_ids, axes, tiled=True)    # [P*M]
    rowsg = jax.lax.all_gather(flat_rows, axes, tiled=True)  # [P*M, dim]
    per_src_ids = idsg.reshape(n_shards, total)
    per_src_rows = rowsg.reshape(n_shards, total, dim)
    offset = 0
    for (slot, _, _), m in zip(triples, ms):
        seg_ids = per_src_ids[:, offset:offset + m].reshape(-1)
        seg_rows = per_src_rows[:, offset:offset + m].reshape(-1, dim)
        mask, safe = owned(seg_ids, axes, shard_rows)
        tables[slot] = tables[slot] + kernels.segment_sum(
            jnp.where(mask[:, None], seg_rows, 0.0),
            jnp.where(mask, safe, 0),
            shard_rows, backend=segsum_backend,
        )
        offset += m
    return tuple(tables)


def gather(pairs: Sequence, *, axes: Axes, n_shards: int, shard_rows: int,
           strategy: str = "ring"):
    """Strategy-dispatched sparse lookup (see the module docstring)."""
    if strategy == "ring":
        return ring_gather(pairs, axes=axes, n_shards=n_shards,
                           shard_rows=shard_rows)
    if strategy == "all_to_all":
        return a2a_gather(pairs, axes=axes, n_shards=n_shards,
                          shard_rows=shard_rows)
    raise ValueError(
        f"unknown sharded exchange strategy {strategy!r} (dense_psum is a "
        f"placement, not an exchange; expected 'ring' or 'all_to_all')"
    )


def scatter_add(tables: Sequence, triples: Sequence, *, axes: Axes,
                n_shards: int, shard_rows: int, strategy: str = "ring",
                segsum_backend: str = "xla"):
    """Strategy-dispatched sparse gradient exchange (module docstring)."""
    if strategy == "ring":
        return ring_scatter_add(tables, triples, axes=axes,
                                n_shards=n_shards, shard_rows=shard_rows)
    if strategy == "all_to_all":
        return a2a_scatter_add(tables, triples, axes=axes,
                               n_shards=n_shards, shard_rows=shard_rows,
                               segsum_backend=segsum_backend)
    raise ValueError(
        f"unknown sharded exchange strategy {strategy!r} (dense_psum is a "
        f"placement, not an exchange; expected 'ring' or 'all_to_all')"
    )


def psum_lookup(table_shard, ids, *, axes: Axes, shard_rows: int):
    """Replicated-ids lookup (the SERVING path): every shard gathers its
    masked contribution for the same global id list and one batch-sized
    ``psum`` completes the rows. Exactly one shard contributes per id,
    so the result is bitwise identical at every world size — what makes
    pool replicas and resharded resumes prediction-stable."""
    import jax
    import jax.numpy as jnp

    mask, safe = owned(ids, axes, shard_rows)
    contrib = jnp.where(mask[..., None], table_shard[safe], 0.0)
    return jax.lax.psum(contrib, axes)
