"""flinkml_tpu.embeddings — sharded embedding tables as a first-class
subsystem (ROADMAP item 1, the recommendation-scale carrier).

Every recsys-shaped member of the library (ALS, Swing, FM, Word2Vec,
LSH) stores a ``[vocab, dim]`` table; until this subsystem each was
capped by single-chip HBM — the dense trainers psum a vocab-sized
gradient per step, and the one scale path (Word2Vec's vocab-sharded
ring trainer) was welded to that one model. This package generalizes it
into a reusable primitive, exactly SNIPPETS.md [1]'s ``embeddings()``
spec (tables sharded ``PS((fsdp, tp), None)``):

- :class:`~flinkml_tpu.embeddings.table.EmbeddingTable` — rows sharded
  over the plan's ``(fsdp, tp)`` axes via the ``EMBEDDING``
  :class:`~flinkml_tpu.sharding.plan.ShardingPlan` family; optimizer
  slots shard identically; checkpoints ride plan-derived ``sharded:0``
  layout tags so world-N snapshots resume at world M.
- :mod:`~flinkml_tpu.embeddings.exchange` — the device-side sparse
  lookup (masked gather on the owning shard) and gradient exchange
  (batch-sized row payloads over ``ppermute`` rings or one
  ``all_to_all``, the scatter riding the PR 12 padded-ELL
  ``segment_sum`` kernel gate) — never a vocab-sized dense psum, never
  a host gather. Strategy is the ``embedding_exchange`` autotune knob;
  the ``dense_psum`` placement below the vocab threshold subsumes
  W2V's old static ``_shard_vocab_threshold``.
- :mod:`~flinkml_tpu.embeddings.serving` — a mesh-bindable lookup model
  serving a sharded table through the ReplicaPool's slice meshes with
  bf16 compute under ``PrecisionPolicy("mixed_inference")``.

Consumers: Word2Vec's sharded SGNS trainer is re-expressed on the
exchange primitives (pinned parity vs its dense twin), the FM trainers
shard their factor matrix + Adam slots through the plan's embedding
family, and ALS exports its factors as tables for sharded serving while
refusing loudly to train sharded (its normal-equation buffers are
vocab-sized — the primitive does not remove that wall).

See ``docs/development/embeddings.md`` for the layout contract, the
exchange algorithms, the checkpoint tag format, the serving path, and
the tuning knobs.
"""

from flinkml_tpu.embeddings.exchange import (  # noqa: F401
    ENV_DENSE_VOCAB_VAR,
    ENV_VAR,
    STRATEGIES,
    dense_vocab_threshold,
    exchange_strategy,
    resolve_exchange,
    shard_rows_for,
)
from flinkml_tpu.embeddings.table import EmbeddingTable  # noqa: F401

__all__ = [
    "ENV_DENSE_VOCAB_VAR",
    "ENV_VAR",
    "STRATEGIES",
    "EmbeddingTable",
    "dense_vocab_threshold",
    "exchange_strategy",
    "resolve_exchange",
    "shard_rows_for",
]
