"""Serving a sharded embedding table through the replica pool.

:class:`EmbeddingLookupModel` is a transformer stage (the serving
engine's contract: ``transform(Table) -> (Table,)``) that maps a column
of fixed-width id rows to pooled embedding vectors. Two properties make
it the subsystem's serving consumer:

- **Mesh-bindable** — the model carries HOST rows only; ``for_mesh``
  returns a bound clone whose :class:`~flinkml_tpu.embeddings.table.
  EmbeddingTable` is placed on THAT mesh. The serving engine calls it at
  install time when ``ServingConfig.mesh`` is set, so a
  :class:`~flinkml_tpu.serving.pool.ReplicaPool` built over
  ``slice_meshes(n)`` places one independent shard layout per replica
  slice — the table loads sharded through the pool, each replica's
  dispatches hold its slice lock (FML303-auditable), and no replica ever
  materializes the full table when its slice cannot hold it.
- **Bitwise-stable predictions** — the lookup is the exchange layer's
  :func:`~flinkml_tpu.embeddings.exchange.psum_lookup` (exactly one
  shard contributes per id), so every replica, every world size, and
  every resharded resume serves identical bytes for identical requests.

``precision`` (default the ``mixed_inference`` preset) gates the pooling
compute: gathered rows cast to ``policy.compute`` (bf16), the mean
accumulates at ``policy.accum`` (f32), and the output is emitted at the
accum width — the same step-boundary-cast contract as the fused
executor's policy scope (``docs/development/precision.md``).

Input convention: ``input_col`` holds ``[n, L]`` int id rows padded with
``-1`` (ignored by the pooling mask; an all-padding row maps to the zero
vector) or ``[n]`` single ids; ``output_col`` receives the ``[n, dim]``
pooled vectors.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from flinkml_tpu.table import Table


@functools.lru_cache(maxsize=64)
def _pooled_lookup_program(mesh, row_entry, shard_rows: int,
                           compute_dtype: str, accum_dtype: str):
    """Jitted sharded pooled lookup: masked psum gather + policy-gated
    mean pool, one program per (mesh, layout, policy) identity."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from flinkml_tpu.embeddings import exchange
    from flinkml_tpu.sharding.plan import entry_axes

    axes = entry_axes(row_entry)
    axes_arg = axes if len(axes) > 1 else axes[0]
    cdt = jnp.dtype(compute_dtype)
    adt = jnp.dtype(accum_dtype)

    def local(table_shard, ids):
        mask = ids >= 0
        safe = jnp.where(mask, ids, 0)
        rows = exchange.psum_lookup(
            table_shard, safe, axes=axes_arg, shard_rows=shard_rows
        )                                             # [n, L, dim]
        rows_c = jnp.where(mask[..., None], rows.astype(cdt), 0)
        total = jnp.sum(rows_c, axis=1, dtype=adt)    # accum at policy.accum
        count = jnp.maximum(jnp.sum(mask, axis=1), 1).astype(adt)
        return total / count[:, None]

    return jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P(row_entry), P()), out_specs=P(),
    ))


class EmbeddingLookupModel:
    """See module docstring. Build UNBOUND from host rows; the engine
    (or a caller) binds a mesh via :meth:`for_mesh`. Unbound transforms
    run the same math single-device (the parity reference)."""

    def __init__(
        self,
        rows: np.ndarray,
        *,
        input_col: str = "ids",
        output_col: str = "vector",
        precision="mixed_inference",
        plan=None,
        hbm_budget_bytes: Optional[int] = None,
        name: str = "serving",
    ):
        from flinkml_tpu.precision import resolve_policy

        self._rows = np.asarray(rows, np.float32)
        if self._rows.ndim != 2:
            raise ValueError(f"rows must be [vocab, dim], got "
                             f"{self._rows.shape}")
        self.input_col = input_col
        self.output_col = output_col
        self.name = name
        self.plan = plan
        self.hbm_budget_bytes = hbm_budget_bytes
        self.policy = resolve_policy(precision)
        self._table = None  # set by for_mesh

    # -- engine protocol ---------------------------------------------------
    def for_mesh(self, mesh) -> "EmbeddingLookupModel":
        """A clone bound to ``mesh``: shares the host rows, owns a
        table placed (plan-validated, budget-checked) on that mesh —
        what the serving engine calls per replica slice at install."""
        from flinkml_tpu.embeddings.table import EmbeddingTable

        bound = EmbeddingLookupModel(
            self._rows, input_col=self.input_col,
            output_col=self.output_col, precision=self.policy,
            plan=self.plan, hbm_budget_bytes=self.hbm_budget_bytes,
            name=self.name,
        )
        bound._table = EmbeddingTable(
            self.name, self._rows.shape[0], self._rows.shape[1],
            mesh=mesh, plan=self.plan,
            hbm_budget_bytes=self.hbm_budget_bytes, rows=self._rows,
        )
        return bound

    # -- dtype plumbing ----------------------------------------------------
    def _dtypes(self) -> Tuple[str, str]:
        if self.policy is not None and self.policy.mixed:
            return self.policy.compute_dtype, self.policy.accum_dtype
        return "float32", "float32"

    def _ids(self, table: Table) -> np.ndarray:
        ids = np.asarray(table.column(self.input_col))
        if ids.ndim == 1:
            ids = ids[:, None]
        if ids.ndim != 2:
            raise ValueError(
                f"column {self.input_col!r} must hold [n] or [n, L] int "
                f"ids, got shape {ids.shape}"
            )
        return ids.astype(np.int32)

    # -- transform ---------------------------------------------------------
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        import jax.numpy as jnp

        (table,) = inputs
        ids = self._ids(table)
        cdt, adt = self._dtypes()
        if self._table is not None and self._table.sharded:
            program = _pooled_lookup_program(
                self._table.mesh.mesh, self._table.row_entry,
                self._table.shard_rows, cdt, adt,
            )
            out = program(self._table.rows, jnp.asarray(ids))
        else:
            rows = (self._table.rows if self._table is not None
                    else jnp.asarray(self._rows))
            mask = ids >= 0
            safe = np.where(mask, ids, 0)
            gathered = rows[jnp.asarray(safe)]
            rows_c = jnp.where(
                jnp.asarray(mask)[..., None],
                gathered.astype(jnp.dtype(cdt)), 0,
            )
            total = jnp.sum(rows_c, axis=1, dtype=jnp.dtype(adt))
            count = jnp.maximum(mask.sum(axis=1), 1).astype(adt)
            out = total / jnp.asarray(count)[:, None]
        return (table.with_column(self.output_col, np.asarray(out)),)
