"""EmbeddingTable — a row-sharded ``[vocab, dim]`` parameter as a value.

The host-side half of :mod:`flinkml_tpu.embeddings`: one object that
owns the four decisions every 100M+-row table forces, each delegated to
the subsystem that already owns the mechanism:

- **layout** — rows shard over the plan's embedding axes (the
  ``EMBEDDING`` family's ``(fsdp, tp)`` product; any preset that keeps
  rows whole is legal). The plan is validated against the mesh by the
  FML5xx pass BEFORE any placement, with the table's padded shape and
  its optimizer slots counted (FML503's per-shard footprint branch), and
  ``plan=None`` routes through :func:`~flinkml_tpu.sharding.plan.
  infer_plan` — an over-budget vocab lands on the cheapest row-keeping
  plan or raises :class:`~flinkml_tpu.sharding.plan.NoFeasiblePlanError`.
- **access** — :meth:`lookup` (replicated ids, the serving path: one
  masked gather + batch-sized psum, bitwise stable at every world) and
  :meth:`scatter_add` (sharded batches, the training path: the
  strategy-gated exchange of :mod:`.exchange`).
- **optimizer state** — ``optimizer_slots`` same-shaped companions named
  ``<table>/embedding_slot<i>``, which land in the SAME plan family as
  the table (the ``*embedding*`` pattern matches both), so slots shard,
  checkpoint, and restore exactly like their parameter.
- **checkpointing** — :meth:`save` records the UNPADDED global array
  per leaf with plan-derived ``sharded:0`` layout tags
  (``CheckpointManager.save(..., plan=...)``), so a world-N snapshot
  restores at world M through the existing elastic machinery
  (:meth:`restore` re-pads and re-places for the new mesh; the restored
  host table is bit-equal to the saved one).

Naming contract: the table's parameter is ``<name>/embedding`` — the
``*embedding*`` family pattern (:data:`~flinkml_tpu.sharding.plan.
EMBEDDING_FAMILY_PATTERNS`) is what routes it to the row-sharded rule
in the ``EMBEDDING`` preset and to the embedding-aware branches of
``infer_plan`` and FML503.
"""

from __future__ import annotations

import copy
import functools
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from flinkml_tpu.embeddings import exchange
from flinkml_tpu.utils.logging import get_logger

_log = get_logger("embeddings")


def _row_entry(plan, param_name: str):
    """The plan's dim-0 spec entry for the table (None/str/tuple), after
    refusing any layout that splits the row payload."""
    from flinkml_tpu.sharding.plan import entry_axes

    spec = plan.spec_for(param_name, ndim=2)
    for dim_idx, entry in enumerate(spec[1:], start=1):
        if entry_axes(entry):
            raise ValueError(
                f"plan {plan.name!r} shards dim {dim_idx} of embedding "
                f"table {param_name!r} over {entry_axes(entry)}: the "
                "sparse lookup/exchange primitives move WHOLE rows "
                "between shards — shard dim 0 only (the EMBEDDING "
                "preset's layout)"
            )
    return spec[0] if spec else None


def _entry_axes_tuple(entry) -> Tuple[str, ...]:
    from flinkml_tpu.sharding.plan import entry_axes

    return entry_axes(entry)


@functools.lru_cache(maxsize=64)
def _lookup_program(mesh, row_entry, n_shards: int, shard_rows: int):
    """Jitted replicated-ids lookup over a row-sharded table (the
    :func:`~flinkml_tpu.embeddings.exchange.psum_lookup` program)."""
    import jax
    from jax.sharding import PartitionSpec as P

    axes = _entry_axes_tuple(row_entry)
    axes_arg = axes if len(axes) > 1 else axes[0]

    def local(table_shard, ids):
        return exchange.psum_lookup(
            table_shard, ids, axes=axes_arg, shard_rows=shard_rows
        )

    return jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P(row_entry), P()), out_specs=P(),
    ))


@functools.lru_cache(maxsize=64)
def _scatter_program(mesh, row_entry, n_shards: int, shard_rows: int,
                     strategy: str, segsum_backend: str):
    """Jitted sharded scatter-add: the global delta batch arrives split
    over the row axes (each shard routes ITS slice of the batch), so
    per-step traffic is batch-sized regardless of vocab."""
    import jax
    from jax.sharding import PartitionSpec as P

    axes = _entry_axes_tuple(row_entry)
    axes_arg = axes if len(axes) > 1 else axes[0]

    def local(table_shard, ids, delta):
        (out,) = exchange.scatter_add(
            (table_shard,), ((0, ids, delta),),
            axes=axes_arg, n_shards=n_shards, shard_rows=shard_rows,
            strategy=strategy, segsum_backend=segsum_backend,
        )
        return out

    return jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(row_entry), P(row_entry), P(row_entry)),
        out_specs=P(row_entry),
    ))


@functools.lru_cache(maxsize=64)
def _patch_program(mesh, row_entry, n_shards: int, shard_rows: int):
    """Jitted replicated-ids row SET over a row-sharded table (the
    incremental-publish path): each shard overwrites exactly the rows it
    owns and drops the rest by routing their indices out of range
    (``mode="drop"``). A SET — not an add of a difference — so the
    patched table is bitwise equal to a fresh placement of the patched
    host array, which is what makes delta-published predictions
    bit-identical to a full-snapshot publish."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axes = _entry_axes_tuple(row_entry)
    axes_arg = axes if len(axes) > 1 else axes[0]

    def local(table_shard, ids, values):
        mask, safe = exchange.owned(ids, axes_arg, shard_rows)
        idx = jnp.where(mask, safe, shard_rows)  # OOB → dropped
        return table_shard.at[idx].set(values, mode="drop")

    return jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P(row_entry), P(), P()),
        out_specs=P(row_entry),
    ))


class EmbeddingTable:
    """See the module docstring. ``rows=None`` initializes to zeros (or
    ``scale``-scaled normal rows when ``scale`` is given); a host array
    of shape ``[vocab, dim]`` seeds the table explicitly."""

    def __init__(
        self,
        name: str,
        vocab: int,
        dim: int,
        *,
        mesh=None,
        plan=None,
        dtype=np.float32,
        optimizer_slots: int = 0,
        hbm_budget_bytes: Optional[int] = None,
        rows: Optional[np.ndarray] = None,
        slots: Optional[Sequence[np.ndarray]] = None,
        seed: int = 0,
        scale: Optional[float] = None,
    ):
        from flinkml_tpu.parallel import DeviceMesh
        from flinkml_tpu.sharding.apply import validate_plan
        from flinkml_tpu.sharding.plan import EMBEDDING, REPLICATED, infer_plan

        if vocab < 1 or dim < 1:
            raise ValueError(f"need vocab >= 1 and dim >= 1, got "
                             f"({vocab}, {dim})")
        self.name = str(name)
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.optimizer_slots = int(optimizer_slots)
        self.param_name = f"{self.name}/embedding"

        if plan is None:
            if hbm_budget_bytes is not None:
                # Route through infer_plan: the mesh (given, or the
                # full EMBEDDING-shaped local mesh) decides which preset
                # fits; an over-budget vocab lands on the embedding
                # plan, a small one stays replicated/batch-parallel.
                probe_mesh = mesh or DeviceMesh.for_plan(EMBEDDING)
                plan = infer_plan(
                    probe_mesh, {self.param_name: (self.vocab, self.dim)},
                    hbm_budget_bytes, dtype_bytes=self.dtype.itemsize,
                    optimizer_slots=self.optimizer_slots,
                )
                mesh = mesh or probe_mesh
            else:
                plan = REPLICATED
        self.plan = plan
        self.mesh = mesh or DeviceMesh.for_plan(plan)
        self.row_entry = _row_entry(plan, self.param_name)

        axis_sizes = dict(self.mesh.mesh.shape)
        self.n_shards = 1
        for axis in _entry_axes_tuple(self.row_entry):
            self.n_shards *= int(axis_sizes.get(axis, 1))
        self.shard_rows = exchange.shard_rows_for(self.vocab, self.n_shards)
        self.padded_vocab = self.shard_rows * self.n_shards

        # FML5xx, pre-placement, over the PADDED shape (what is actually
        # laid out) with the optimizer slots counted.
        validate_plan(
            plan, self.mesh,
            param_shapes={self.param_name: (self.padded_vocab, self.dim)},
            hbm_budget_bytes=hbm_budget_bytes,
            dtype_bytes=self.dtype.itemsize,
            optimizer_slots=self.optimizer_slots,
        )

        if rows is None:
            if scale is None:
                host = np.zeros((self.vocab, self.dim), self.dtype)
            else:
                rng = np.random.default_rng(seed)
                host = (rng.standard_normal((self.vocab, self.dim))
                        * float(scale)).astype(self.dtype)
        else:
            host = np.asarray(rows, self.dtype)
            if host.shape != (self.vocab, self.dim):
                raise ValueError(
                    f"rows shape {host.shape} != ({self.vocab}, {self.dim})"
                )
        self.rows = self._place(host)
        if slots is not None:
            if len(slots) != self.optimizer_slots:
                raise ValueError(
                    f"{len(slots)} slot arrays != optimizer_slots="
                    f"{self.optimizer_slots}"
                )
            self.slots = tuple(self._place(np.asarray(s, self.dtype))
                               for s in slots)
        else:
            self.slots = tuple(
                self._place(np.zeros((self.vocab, self.dim), self.dtype))
                for _ in range(self.optimizer_slots)
            )

    # -- placement ---------------------------------------------------------
    @property
    def sharded(self) -> bool:
        return self.n_shards > 1

    def _sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh.mesh, P(self.row_entry))

    def _place(self, host: np.ndarray):
        """Pad the host ``[vocab, dim]`` array to the shard grid and
        ``device_put`` it row-sharded per the plan."""
        import jax

        pad = self.padded_vocab - host.shape[0]
        if pad:
            host = np.concatenate(
                [host, np.zeros((pad, host.shape[1]), host.dtype)]
            )
        return jax.device_put(host, self._sharding())

    # -- access ------------------------------------------------------------
    def lookup(self, ids):
        """Rows for (replicated) global ``ids`` — exact, and bitwise
        identical at every world size (see
        :func:`~flinkml_tpu.embeddings.exchange.psum_lookup`)."""
        import jax.numpy as jnp

        ids = jnp.asarray(ids, jnp.int32)
        if not self.sharded:
            return self.rows[ids]
        program = _lookup_program(
            self.mesh.mesh, self.row_entry, self.n_shards, self.shard_rows
        )
        return program(self.rows, ids)

    def scatter_add(self, ids, delta, strategy: Optional[str] = None):
        """``rows[ids] += delta`` through the strategy-gated exchange:
        the ``[m]`` id / ``[m, dim]`` delta batch is split over the
        shards (each routes its slice), so traffic is batch-sized. Pads
        with id-0/delta-0 no-op rows to the shard grid. Returns self."""
        import jax.numpy as jnp

        if strategy is not None and strategy not in exchange.STRATEGIES:
            # Validate BEFORE the unsharded early-return: a typo'd
            # strategy developed against a small table must fail here,
            # not first in production sharded use.
            raise ValueError(
                f"unknown exchange strategy {strategy!r}; expected one "
                f"of {exchange.STRATEGIES}"
            )
        ids = np.asarray(ids, np.int32)
        delta = np.asarray(delta, self.dtype)
        if ids.shape[0] != delta.shape[0]:
            raise ValueError(f"{ids.shape[0]} ids != {delta.shape[0]} rows")
        if not self.sharded:
            self.rows = self.rows.at[jnp.asarray(ids)].add(
                jnp.asarray(delta))
            return self
        if strategy is None:
            strategy = exchange.resolve_exchange(self.vocab, self.n_shards)
            if strategy == "dense_psum":  # sharded table: exchange anyway
                strategy = exchange.exchange_strategy()
        from flinkml_tpu import kernels

        pad = (-ids.shape[0]) % self.n_shards
        if pad:
            ids = np.concatenate([ids, np.zeros(pad, np.int32)])
            delta = np.concatenate(
                [delta, np.zeros((pad, self.dim), self.dtype)]
            )
        program = _scatter_program(
            self.mesh.mesh, self.row_entry, self.n_shards, self.shard_rows,
            strategy, kernels.segsum_backend(),
        )
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax

        batch_sh = NamedSharding(self.mesh.mesh, P(self.row_entry))
        self.rows = program(
            self.rows,
            jax.device_put(ids, batch_sh),
            jax.device_put(delta, batch_sh),
        )
        return self

    def to_host(self) -> np.ndarray:
        """The UNPADDED global ``[vocab, dim]`` host array."""
        return np.asarray(self.rows)[: self.vocab]

    # -- incremental row patch (the features delta-publish path) -----------
    def _patched_rows(self, ids, values):
        import jax.numpy as jnp

        ids = np.asarray(ids, np.int32).reshape(-1)
        values = np.asarray(values, self.dtype)
        if values.shape != (ids.shape[0], self.dim):
            raise ValueError(
                f"row values shape {values.shape} != "
                f"({ids.shape[0]}, {self.dim})"
            )
        if ids.shape[0] != np.unique(ids).shape[0]:
            raise ValueError(
                f"row delta for table {self.name!r} has duplicate ids — "
                "SET semantics require one value per row"
            )
        if ids.size and (int(ids.min()) < 0 or int(ids.max()) >= self.vocab):
            raise ValueError(
                f"row delta ids out of range [0, {self.vocab}) for table "
                f"{self.name!r}"
            )
        if not ids.size:
            return self.rows
        if not self.sharded:
            return self.rows.at[jnp.asarray(ids)].set(jnp.asarray(values))
        program = _patch_program(
            self.mesh.mesh, self.row_entry, self.n_shards, self.shard_rows
        )
        return program(self.rows, jnp.asarray(ids), jnp.asarray(values))

    def apply_row_delta(self, ids, values) -> "EmbeddingTable":
        """``rows[ids] = values`` (exact SET, unique ids, plan-respecting:
        sharded tables patch each row on its owning shard only). Rebinds
        ``self.rows`` and returns self — the TRAINER-side form. Serving
        replicas must use :meth:`clone_with_row_delta` instead so a model
        reference snapshotted by an in-flight batch keeps its rows."""
        self.rows = self._patched_rows(ids, values)
        return self

    def clone_with_row_delta(self, ids, values) -> "EmbeddingTable":
        """Functional patch: a shallow clone whose ``rows`` is the
        patched array; slots and layout are shared with self. Device
        buffers are immutable, so the old table — and any in-flight
        batch holding it through the engine's active-model snapshot —
        serves exactly its own version (the PR 8 contract, extended to
        row patches)."""
        patched = self._patched_rows(ids, values)
        clone = copy.copy(self)
        clone.rows = patched
        return clone

    # -- footprint ---------------------------------------------------------
    def per_device_bytes(self) -> int:
        """Per-device bytes of the table plus its optimizer slots under
        the current layout — the number FML503 compares to the budget."""
        return (self.shard_rows * self.dim * self.dtype.itemsize
                * (1 + self.optimizer_slots))

    def exchange_bytes_per_step(self, batch: int,
                                strategy: str = "ring") -> int:
        """Analytic per-step exchange traffic for a ``batch``-id
        gather + scatter round (all shards, both directions) — linear
        in ``batch``, INDEPENDENT of vocab; the bench stage emits this
        next to the measured rate so the traffic contract is auditable."""
        if not self.sharded or strategy == "dense_psum":
            # The dense placement's psum moves the whole table.
            return 2 * self.padded_vocab * self.dim * self.dtype.itemsize
        row_bytes = self.dim * self.dtype.itemsize
        id_bytes = 4
        # gather: ids+acc ride P hops (ring) or gather+route (a2a) —
        # both move P * batch rows in total; scatter mirrors it.
        return 2 * self.n_shards * int(batch) * (row_bytes + id_bytes)

    # -- checkpointing -----------------------------------------------------
    def _slot_name(self, i: int) -> str:
        return f"{self.param_name}_slot{i}"

    def state_dict(self) -> Dict[str, np.ndarray]:
        """Host state (unpadded global arrays) keyed by plan-family
        names — what :meth:`save` records and :meth:`restore` expects."""
        out = {self.param_name: self.to_host()}
        for i, slot in enumerate(self.slots):
            out[self._slot_name(i)] = np.asarray(slot)[: self.vocab]
        return out

    def save(self, manager, epoch: int) -> str:
        """Snapshot through ``CheckpointManager.save(..., plan=...)`` —
        layout tags derive from the plan (``sharded:0`` for the table
        and every slot), so the snapshot participates in elastic
        resharded resume like any plan-sharded state."""
        return manager.save(self.state_dict(), epoch, plan=self.plan)

    @classmethod
    def restore(
        cls,
        manager,
        name: str,
        vocab: int,
        dim: int,
        *,
        mesh=None,
        plan=None,
        dtype=np.float32,
        optimizer_slots: int = 0,
        hbm_budget_bytes: Optional[int] = None,
    ) -> Tuple["EmbeddingTable", int]:
        """Restore the newest snapshot onto a possibly DIFFERENT mesh /
        world size (the elastic path): the snapshot's global arrays
        re-pad and re-place for the new layout; the restored
        :meth:`to_host` is bit-equal to the saved one. Returns
        ``(table, epoch)``; raises if the manager holds no snapshot."""
        like = {f"{name}/embedding": np.zeros((vocab, dim), np.dtype(dtype))}
        for i in range(optimizer_slots):
            like[f"{name}/embedding_slot{i}"] = np.zeros(
                (vocab, dim), np.dtype(dtype))
        restored = manager.restore_latest(like)
        if restored is None:
            raise ValueError(
                f"no checkpoint to restore embedding table {name!r} from "
                f"under {manager.directory}"
            )
        state, epoch = restored
        table = cls(
            name, vocab, dim, mesh=mesh, plan=plan, dtype=dtype,
            optimizer_slots=optimizer_slots,
            hbm_budget_bytes=hbm_budget_bytes,
            rows=state[f"{name}/embedding"],
            slots=[state[f"{name}/embedding_slot{i}"]
                   for i in range(optimizer_slots)],
        )
        return table, epoch
