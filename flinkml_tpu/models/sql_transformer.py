"""SQLTransformer — SQL-statement row transform over a Table.

Member of the wider Flink ML operator family (upstream
``org.apache.flink.ml.feature.sqltransformer.SQLTransformer`` runs a
Flink SQL statement with ``__THIS__`` standing for the input table; the
reference snapshot has none). The TPU-native stance: there is no SQL
engine in the stack and none is needed for the operator's actual use —
feature arithmetic and row filtering inside a Pipeline — so the
statement is parsed by a small recursive-descent parser (NO ``eval``,
no arbitrary code) and evaluated as vectorized numpy expressions:

    SELECT *, (a + b) / 2 AS mean_ab FROM __THIS__ WHERE a > 0

Supported surface:
  - projection items: ``*`` (every input column) and arithmetic /
    comparison / boolean expressions with optional ``AS alias``;
  - operators: ``+ - * / %``, comparisons ``= == != <> < <= > >=``,
    ``AND OR NOT``, unary minus, parentheses;
  - functions (elementwise): ABS, LOG, EXP, SQRT, POW, SIN, COS, TAN,
    FLOOR, CEIL, SIGN, MINIMUM, MAXIMUM;
  - ``WHERE expr`` filters rows of every selected column (vector and
    string columns pass through the filter untouched).

Identifiers resolve to input columns; expressions require 1-D numeric
columns (vector columns can only be selected whole, via ``*`` or a bare
column reference). An unsupported construct raises at ``transform``
time with the offending token — a deliberate, loud subset, not a quiet
approximation of SQL.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

import numpy as np

from flinkml_tpu.api import Transformer
from flinkml_tpu.params import StringParam
from flinkml_tpu.table import Table

_FUNCS = {
    "ABS": np.abs,
    "LOG": np.log,
    "EXP": np.exp,
    "SQRT": np.sqrt,
    "SIN": np.sin,
    "COS": np.cos,
    "TAN": np.tan,
    "FLOOR": np.floor,
    "CEIL": np.ceil,
    "SIGN": np.sign,
}
_FUNCS2 = {
    "POW": np.power,
    "MINIMUM": np.minimum,
    "MAXIMUM": np.maximum,
}

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>\d+\.\d*|\.\d+|\d+)"
    r"|(?P<ident>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<op><=|>=|==|!=|<>|[-+*/%(),=<>]))"
)


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None or m.end() == pos:
            rest = text[pos:].strip()
            if not rest:
                break
            raise ValueError(f"SQLTransformer: cannot tokenize at {rest!r}")
        pos = m.end()
        for kind in ("num", "ident", "op"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    out.append(("end", ""))
    return out


class _Parser:
    """Recursive-descent expression parser producing a closure
    ``fn(columns: Dict[str, np.ndarray]) -> np.ndarray``."""

    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_op(self, op: str) -> None:
        kind, v = self.next()
        if kind != "op" or v != op:
            raise ValueError(f"SQLTransformer: expected {op!r}, got {v!r}")

    # expr := or
    def expr(self):
        return self._or()

    def _kw(self, word: str) -> bool:
        kind, v = self.peek()
        if kind == "ident" and v.upper() == word:
            self.next()
            return True
        return False

    def _or(self):
        left = self._and()
        while self._kw("OR"):
            right = self._and()
            left = (lambda a, b: lambda c: np.logical_or(a(c), b(c)))(
                left, right
            )
        return left

    def _and(self):
        left = self._not()
        while self._kw("AND"):
            right = self._not()
            left = (lambda a, b: lambda c: np.logical_and(a(c), b(c)))(
                left, right
            )
        return left

    def _not(self):
        if self._kw("NOT"):
            inner = self._not()
            return lambda c: np.logical_not(inner(c))
        return self._cmp()

    _CMP = {
        "=": np.equal, "==": np.equal, "!=": np.not_equal,
        "<>": np.not_equal, "<": np.less, "<=": np.less_equal,
        ">": np.greater, ">=": np.greater_equal,
    }

    def _cmp(self):
        left = self._add()
        kind, v = self.peek()
        if kind == "op" and v in self._CMP:
            self.next()
            op = self._CMP[v]
            right = self._add()
            return (lambda a, b, o: lambda c: o(a(c), b(c)))(left, right, op)
        return left

    def _add(self):
        left = self._mul()
        while True:
            kind, v = self.peek()
            if kind == "op" and v in ("+", "-"):
                self.next()
                right = self._mul()
                op = np.add if v == "+" else np.subtract
                left = (lambda a, b, o: lambda c: o(a(c), b(c)))(
                    left, right, op
                )
            else:
                return left

    def _mul(self):
        left = self._unary()
        while True:
            kind, v = self.peek()
            if kind == "op" and v in ("*", "/", "%"):
                self.next()
                op = {"*": np.multiply, "/": np.divide, "%": np.mod}[v]
                right = self._unary()
                left = (lambda a, b, o: lambda c: o(a(c), b(c)))(
                    left, right, op
                )
            else:
                return left

    def _unary(self):
        kind, v = self.peek()
        if kind == "op" and v == "-":
            self.next()
            inner = self._unary()
            return lambda c: np.negative(inner(c))
        return self._atom()

    def _atom(self):
        kind, v = self.next()
        if kind == "num":
            val = float(v)
            return lambda c: val
        if kind == "op" and v == "(":
            inner = self.expr()
            self.expect_op(")")
            return inner
        if kind == "ident":
            up = v.upper()
            nk, nv = self.peek()
            if nk == "op" and nv == "(":
                self.next()
                if up in _FUNCS:
                    arg = self.expr()
                    self.expect_op(")")
                    return (lambda f, a: lambda c: f(a(c)))(_FUNCS[up], arg)
                if up in _FUNCS2:
                    a1 = self.expr()
                    self.expect_op(",")
                    a2 = self.expr()
                    self.expect_op(")")
                    return (lambda f, x, y: lambda c: f(x(c), y(c)))(
                        _FUNCS2[up], a1, a2
                    )
                raise ValueError(f"SQLTransformer: unknown function {v!r}")
            name = v

            def col(c, name=name):
                if name not in c:
                    raise ValueError(
                        f"SQLTransformer: unknown column {name!r}"
                    )
                arr = np.asarray(c[name])
                if arr.ndim != 1 or not np.issubdtype(arr.dtype, np.number):
                    raise ValueError(
                        f"SQLTransformer: column {name!r} is not a 1-D "
                        "numeric column; vector/string columns can only "
                        "be selected whole"
                    )
                return arr

            return col
        raise ValueError(f"SQLTransformer: unexpected token {v!r}")


def _split_top_level_commas(tokens: List[Tuple[str, str]]):
    """Split a token list on commas not inside parentheses."""
    parts, cur, depth = [], [], 0
    for t in tokens[:-1]:  # drop the trailing ("end", "")
        if t == ("op", "("):
            depth += 1
        elif t == ("op", ")"):
            depth -= 1
        if t == ("op", ",") and depth == 0:
            parts.append(cur)
            cur = []
        else:
            cur.append(t)
    parts.append(cur)
    return parts


class SQLTransformer(Transformer):
    """See the module docstring for the supported statement surface."""

    STATEMENT = StringParam(
        "statement",
        "SELECT statement over __THIS__ (the input table).",
        "SELECT * FROM __THIS__",
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        stmt = self.get(self.STATEMENT)
        m = re.match(
            r"\s*SELECT\s+(?P<items>.+?)\s+FROM\s+__THIS__"
            r"(?:\s+WHERE\s+(?P<where>.+?))?\s*;?\s*$",
            stmt, re.IGNORECASE | re.DOTALL,
        )
        if m is None:
            raise ValueError(
                "SQLTransformer supports 'SELECT <items> FROM __THIS__ "
                f"[WHERE <expr>]'; got {stmt!r}"
            )
        columns = {n: table.column(n) for n in table.column_names}
        n_rows = table.num_rows

        # SQL semantics: WHERE filters FIRST, so projection expressions
        # never evaluate on excluded rows (e.g. a / b WHERE b <> 0 must
        # not divide by the excluded zeros).
        if m.group("where") is not None:
            parser = _Parser(_tokenize(m.group("where")))
            pred = parser.expr()
            if parser.peek()[0] != "end":
                raise ValueError("SQLTransformer: trailing tokens in WHERE")
            mask = np.asarray(pred(columns))
            if mask.ndim == 0:  # constant predicate, e.g. WHERE 1 = 1
                mask = np.broadcast_to(mask, (n_rows,))
            if mask.dtype != np.bool_ or mask.ndim != 1:
                raise ValueError(
                    "SQLTransformer: WHERE must be a boolean row predicate"
                )
            columns = {k: np.asarray(v)[mask] for k, v in columns.items()}
            n_rows = int(mask.sum())

        out: Dict[str, np.ndarray] = {}

        def assign(name: str, val) -> None:
            # Upstream Flink SQL rejects duplicate output columns; a
            # silent last-wins overwrite (SELECT a, a, two expressions
            # aliased to one name, or '*' colliding with an explicit
            # item in either order) would drop a projected column.
            if name in out:
                raise ValueError(
                    f"SQLTransformer: duplicate output column {name!r}"
                )
            out[name] = val

        for part in _split_top_level_commas(_tokenize(m.group("items"))):
            if not part:
                raise ValueError("SQLTransformer: empty projection item")
            if len(part) == 1 and part[0] == ("op", "*"):
                for name, val in columns.items():
                    assign(name, val)
                continue
            # Optional trailing "AS alias".
            alias = None
            expr_toks = part
            if (
                len(part) >= 3
                and part[-2][0] == "ident" and part[-2][1].upper() == "AS"
                and part[-1][0] == "ident"
            ):
                alias = part[-1][1]
                expr_toks = part[:-2]
            # A bare column reference passes through untouched (so
            # vector/string columns can be projected by name).
            if len(expr_toks) == 1 and expr_toks[0][0] == "ident" and (
                expr_toks[0][1] in columns
            ):
                assign(alias or expr_toks[0][1], columns[expr_toks[0][1]])
                continue
            parser = _Parser(expr_toks + [("end", "")])
            fn = parser.expr()
            if parser.peek()[0] != "end":
                raise ValueError(
                    "SQLTransformer: trailing tokens in projection item "
                    f"{' '.join(v for _, v in expr_toks)!r}"
                )
            name = alias or " ".join(v for _, v in expr_toks)
            val = np.asarray(fn(columns))
            if val.ndim == 0:  # constant column, e.g. SELECT 1 AS one
                val = np.full(n_rows, float(val))
            assign(name, val)

        if not out:
            raise ValueError("SQLTransformer: empty projection")
        return (Table(out),)
