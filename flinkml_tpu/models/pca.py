"""PCA — principal component analysis on the device mesh.

Beyond the reference snapshot but a standard member of the wider operator
family. TPU-native fit: the [d, d] covariance is accumulated as one
sharded gram-matrix pass — each device computes its local
``centered_xᵀ @ centered_x`` on the MXU and a single ``psum`` combines
them over ICI (this is the allReduce-of-partials pattern the reference
would express as mapPartition + AllReduce). The tiny [d, d] eigensolve
then runs on the host in float64 (d ≪ n; an O(d³) host eigh is noise
next to the O(n·d²) device pass, and f64 keeps close eigenvalues stable).

Sign convention: each component is flipped so its max-|entry| is
positive, making fitted models deterministic across runs and meshes.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import HasInputCol, HasOutputCol
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.models.scalers import _shard_with_mask
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


@functools.lru_cache(maxsize=32)
def _mean_and_gram_fn(mesh, axis: str):
    """One fused pass: masked count, per-feature sum, and centered gram.

    Centering uses a caller-supplied shift (first row) so the f32 gram
    accumulates small magnitudes; the exact mean correction happens on
    the host in f64 (same shift-centering discipline as the scalers).
    """

    def local(xl, wl, shift):
        c = (xl - shift) * wl[:, None]
        n = jax.lax.psum(jnp.sum(wl), axis)
        s = jax.lax.psum(jnp.sum(c, axis=0), axis)
        # Gram of masked centered rows on the MXU; one psum over ICI.
        g = jax.lax.psum((xl - shift).T @ c, axis)
        return n, s, g

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh, in_specs=(P(axis), P(axis), P()),
            out_specs=(P(), P(), P()),
        )
    )


class _PCAParams(HasInputCol, HasOutputCol):
    K = IntParam(
        "k", "Number of principal components.", 2, ParamValidators.gt(0)
    )


class PCA(_PCAParams, Estimator):
    """``fit`` also accepts an iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the
    out-of-core path: PCA is a SINGLE accumulation pass (count, centered
    sum, centered gram per batch, summed on device), so no cache replay
    is needed and the only resident state is the [d, d] gram. No
    checkpoint knobs: a single cheap pass restarts, it doesn't resume
    (checkpointing targets multi-pass iteration)."""

    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs) -> "PCAModel":
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        x = features_matrix(table, self.get(self.INPUT_COL))
        n, d = x.shape
        k = self.get(self.K)
        if k > min(n, d):
            raise ValueError(f"k={k} must be <= min(n_rows, dim) = {min(n, d)}")
        mesh = self.mesh or DeviceMesh()
        xd, wd = _shard_with_mask(x, mesh)
        shift = np.asarray(x[0], dtype=np.float32)
        cnt, s, g = _mean_and_gram_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(
            xd, wd, jnp.asarray(shift)
        )
        return self._finish(float(cnt), np.asarray(s, np.float64),
                            np.asarray(g, np.float64), shift, k)

    def _fit_stream(self, source) -> "PCAModel":
        """Out-of-core single-pass PCA (see class docstring).

        Multi-process (round 4): each process feeds its own stream
        partition, iterated in SPMD lockstep WITHOUT caching
        (``stream_sync.synced_stream`` — one tiny agreement collective
        per step instead of the cache-first double IO the replay
        trainers need); the centering shift is agreed from the
        lowest-indexed non-empty rank, per-step padded heights are
        agreed, and exhausted ranks dispatch zero-weight dummy steps.
        """
        import jax

        from flinkml_tpu.iteration.datacache import DataCache

        input_col = self.get(self.INPUT_COL)
        k = self.get(self.K)
        mesh = self.mesh or DeviceMesh()
        multi = jax.process_count() > 1
        fn = _mean_and_gram_fn(mesh.mesh, DeviceMesh.DATA_AXIS)

        column = input_col if isinstance(source, DataCache) else None
        batches = source.reader() if isinstance(source, DataCache) else source

        def extract(b):
            if column is not None:
                return np.asarray(b[column], np.float32)
            return features_matrix(b, input_col).astype(np.float32)

        d = [None]

        def check_x(x):
            # Validates an already-extracted matrix — extraction happens
            # exactly once per batch (the stream below is pre-mapped), not
            # once in the check and again in the loop body.
            if x.ndim != 2 or x.shape[0] == 0:
                raise ValueError(
                    f"stream batches must be non-empty [n, d], got {x.shape}"
                )
            if d[0] is None:
                d[0] = x.shape[1]
            elif x.shape[1] != d[0]:
                raise ValueError(
                    f"batch feature dim {x.shape[1]} != first batch's {d[0]}"
                )

        cnt = 0.0
        s = g = None
        shift = None

        if not multi:
            for b in batches:
                x = extract(b)
                check_x(x)
                if shift is None:
                    shift = np.array(x[0])  # first row of the stream
                xd, wd = _shard_with_mask(x, mesh)
                cb, sb, gb = fn(xd, wd, jnp.asarray(shift))
                cnt += float(cb)
                s = np.asarray(sb, np.float64) if s is None else (
                    s + np.asarray(sb, np.float64)
                )
                g = np.asarray(gb, np.float64) if g is None else (
                    g + np.asarray(gb, np.float64)
                )
            if shift is None:
                raise ValueError("training stream is empty")
        else:
            from flinkml_tpu.iteration.stream_sync import (
                agree_first_item_dim,
                gather_vectors,
                synced_padded_stream,
            )

            row_tile = mesh.axis_size() * 8
            # Pre-map to extracted matrices: one extract per batch, and
            # extract/iterator failures ride the agreements (first item:
            # agree_first_item_dim; the rest: synced_stream's per-step
            # agreement) instead of raising rank-locally.
            first, it, dim = agree_first_item_dim(
                (extract(b) for b in batches), check_x,
                lambda x: x.shape[1], mesh,
            )
            d[0] = dim  # empty ranks adopt the agreed dim
            # Agreed centering shift: the first row of the lowest-indexed
            # non-empty rank (gathered exactly; identical everywhere).
            cand = np.zeros(1 + dim)
            if first is not None:
                cand[0] = 1.0
                cand[1:] = first[0].astype(np.float64)
            rows = gather_vectors(cand, mesh)
            nonempty = np.nonzero(rows[:, 0] > 0)[0]
            shift = rows[nonempty[0], 1:].astype(np.float32)

            import itertools

            stream = itertools.chain([first] if first is not None else [], it)
            # Fixed agreed heights + zero-weight padding/dummies come
            # from the shared lockstep loop body (one collective per
            # step; items are tuples, hence the (x,) wrapping).
            for (x_pad,), w, _h in synced_padded_stream(
                ((x,) for x in stream), mesh,
                check=lambda item: check_x(item[0]),
                row_tile=row_tile, dummy_cols=((dim,),),
            ):
                cb, sb, gb = fn(
                    mesh.global_batch(x_pad),
                    mesh.global_batch(w),
                    jnp.asarray(shift),
                )
                cnt += float(cb)
                s = np.asarray(sb, np.float64) if s is None else (
                    s + np.asarray(sb, np.float64)
                )
                g = np.asarray(gb, np.float64) if g is None else (
                    g + np.asarray(gb, np.float64)
                )

        if k > min(int(cnt), d[0] if d[0] is not None else int(cnt)):
            raise ValueError(
                f"k={k} must be <= min(n_rows, dim) = "
                f"{min(int(cnt), d[0] if d[0] is not None else int(cnt))}"
            )
        return self._finish(cnt, s, g, shift, k)

    def _finish(self, cnt: float, s: np.ndarray, g: np.ndarray,
                shift: np.ndarray, k: int) -> "PCAModel":
        """Host f64 eigensolve from the accumulated (count, sum, gram) —
        shared by the in-RAM single pass and the streamed accumulation."""
        mean_c = s / cnt                                  # mean of (x - shift)
        # cov of x = E[(x-shift)(x-shift)ᵀ] - mean_c mean_cᵀ, over n-1.
        cov = (g / cnt - np.outer(mean_c, mean_c)) * (cnt / max(cnt - 1, 1))
        eigvals, eigvecs = np.linalg.eigh(cov)
        idx = np.argsort(eigvals)[::-1][:k]
        components = eigvecs[:, idx].T                     # [k, d]
        variances = np.maximum(eigvals[idx], 0.0)
        # Deterministic sign: the max-|entry| of each component is positive.
        flip = np.sign(
            components[np.arange(k), np.argmax(np.abs(components), axis=1)]
        )
        flip[flip == 0] = 1.0
        components = components * flip[:, None]
        total_var = float(np.maximum(np.trace(cov), 1e-300))
        model = PCAModel()
        model.copy_params_from(self)
        model.set_model_data(Table({
            "mean": (shift.astype(np.float64) + mean_c)[None, :],
            "components": components[None, :, :],
            "explainedVariance": variances[None, :],
            "explainedVarianceRatio": (variances / total_var)[None, :],
        }))
        return model


class PCAModel(_PCAParams, Model):
    def __init__(self):
        super().__init__()
        self._mean: Optional[np.ndarray] = None
        self._components: Optional[np.ndarray] = None
        self._explained_variance: Optional[np.ndarray] = None
        self._explained_variance_ratio: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "PCAModel":
        (table,) = inputs
        self._mean = np.asarray(table.column("mean"), np.float64)[0]
        self._components = np.asarray(table.column("components"), np.float64)[0]
        self._explained_variance = np.asarray(
            table.column("explainedVariance"), np.float64
        )[0]
        self._explained_variance_ratio = np.asarray(
            table.column("explainedVarianceRatio"), np.float64
        )[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "mean": self._mean[None, :],
            "components": self._components[None, :, :],
            "explainedVariance": self._explained_variance[None, :],
            "explainedVarianceRatio": self._explained_variance_ratio[None, :],
        })]

    @property
    def components(self) -> np.ndarray:
        self._require()
        return self._components

    @property
    def explained_variance(self) -> np.ndarray:
        self._require()
        return self._explained_variance

    @property
    def explained_variance_ratio(self) -> np.ndarray:
        self._require()
        return self._explained_variance_ratio

    def _require(self) -> None:
        if self._components is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.INPUT_COL))
        proj = (x - self._mean) @ self._components.T
        return (table.with_column(self.get(self.OUTPUT_COL), proj),)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {
            "mean": self._mean,
            "components": self._components,
            "explainedVariance": self._explained_variance,
            "explainedVarianceRatio": self._explained_variance_ratio,
        })

    @classmethod
    def load(cls, path: str) -> "PCAModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._mean = arrays["mean"]
        model._components = arrays["components"]
        model._explained_variance = arrays["explainedVariance"]
        model._explained_variance_ratio = arrays["explainedVarianceRatio"]
        return model
