"""Shared scaffold for models whose data is a single coefficient vector."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from flinkml_tpu.table import Table


class CoefficientModelMixin:
    """set/get model data, save/load, and the fitted-check for coefficient
    models (LogisticRegression, LinearSVC, LinearRegression, online LR)."""

    _coefficient: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table):
        (table,) = inputs
        self._coefficient = np.asarray(
            table.column("coefficient"), dtype=np.float64
        ).reshape(-1)
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        return [Table({"coefficient": self._coefficient[None, :]})]

    @property
    def coefficient(self) -> np.ndarray:
        self._require_model()
        return self._coefficient

    def _require_model(self) -> None:
        if self._coefficient is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    def save(self, path: str) -> None:
        self._require_model()
        self._save_with_arrays(path, {"coefficient": self._coefficient})

    @classmethod
    def load(cls, path: str):
        model, arrays, _ = cls._load_with_arrays(path)
        model._coefficient = arrays["coefficient"]
        return model
