"""StringIndexer / IndexToString — categorical values ↔ dense indices.

Beyond the reference snapshot (whose only categorical stage is
OneHotEncoder, SURVEY.md §2.3) but the canonical upstream companion: index
string/numeric categories so they can feed OneHotEncoder and the linear
models. Semantics follow the wider Flink ML operator family:

  - ``fit`` collects per-column distinct values ordered by
    ``stringOrderType`` ∈ {arbitrary, frequencyDesc, frequencyAsc,
    alphabetAsc, alphabetDesc}; ties in the frequency orders break by
    value ascending so indexing is deterministic.
  - ``transform`` maps each value to its double-valued index;
    ``handleInvalid`` = "error" (raise on unseen), "skip" (drop the whole
    row from every column), or "keep" (unseen values map to the
    catch-all index ``len(vocabulary)``).
  - ``IndexToStringModel`` is the inverse transform, driven by the same
    model data.

TPU stance: category vocabularies are host metadata — strings never ship
to the device (XLA has no string type); the indexing itself is a
vectorized ``searchsorted`` over the vocabulary, after which downstream
stages (OneHotEncoder → sparse LR) carry the data onto the mesh. Numeric
input columns keep their numeric dtype in the vocabulary (and
"alphabet" order means value order for them); string columns index by
exact string match.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasHandleInvalid,
    HasInputCols,
    HasOutputCols,
)
from flinkml_tpu.params import IntParam, ParamValidators, StringParam
from flinkml_tpu.table import Table

ARBITRARY = "arbitrary"
FREQUENCY_DESC = "frequencyDesc"
FREQUENCY_ASC = "frequencyAsc"
ALPHABET_ASC = "alphabetAsc"
ALPHABET_DESC = "alphabetDesc"


class _StringIndexerParams(HasInputCols, HasOutputCols, HasHandleInvalid):
    MAX_INDEX_NUM = IntParam(
        "maxIndexNum",
        "Cap each column's vocabulary at the first N values in order; "
        "beyond-cap values are handled as unseen by handleInvalid. "
        "Deliberate divergence from upstream Flink ML: the cap applies "
        "under EVERY stringOrderType here (upstream honors it only for "
        "frequencyDesc) — capping an alphabetical order keeps the N "
        "alphabetically-first values.",
        2**31 - 1, ParamValidators.gt(1),
    )
    STRING_ORDER_TYPE = StringParam(
        "stringOrderType",
        "How to order distinct values before assigning indices.",
        ARBITRARY,
        ParamValidators.in_array(
            [ARBITRARY, FREQUENCY_DESC, FREQUENCY_ASC, ALPHABET_ASC, ALPHABET_DESC]
        ),
    )


def _column_values(table: Table, col: str) -> np.ndarray:
    """A column as a flat array suitable for vocab work: object/str columns
    become unicode arrays; numeric columns pass through."""
    values = table.column(col)
    if values.ndim != 1:
        raise ValueError(f"Column {col!r} must be scalar, has shape {values.shape}")
    if values.dtype == object or values.dtype.kind in "US":
        return values.astype(str)
    return values


def _ordered_vocab(values: np.ndarray, order_type: str) -> np.ndarray:
    if values.dtype.kind == "f":
        # NaN can never be matched by the equality lookup, so it must not
        # enter the vocabulary — NaN rows are handled by handleInvalid at
        # transform time instead.
        values = values[~np.isnan(values)]
        if values.size == 0:
            raise ValueError("column has no non-NaN values to index")
    uniq, counts = np.unique(values, return_counts=True)
    if order_type in (ARBITRARY, ALPHABET_ASC):
        return uniq  # np.unique is ascending — deterministic "arbitrary"
    if order_type == ALPHABET_DESC:
        return uniq[::-1].copy()
    # Frequency orders; ties break by value ascending (uniq is pre-sorted
    # and np.argsort is stable).
    if order_type == FREQUENCY_DESC:
        return uniq[np.argsort(-counts, kind="stable")]
    return uniq[np.argsort(counts, kind="stable")]


def _sorted_lookup_table(vocab: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Precompute (sorted_vocab, order) once per fitted column."""
    order = np.argsort(vocab, kind="stable")
    return vocab[order], order


def _lookup(
    values: np.ndarray, sorted_vocab: np.ndarray, order: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized vocab lookup: returns (indices, found_mask); indices are
    valid only where found. NaN values never match (vocabularies are
    NaN-free by construction)."""
    if len(sorted_vocab) == 0:
        z = np.zeros(len(values), dtype=np.int64)
        return z, np.zeros(len(values), dtype=bool)
    if sorted_vocab.dtype.kind in "US":
        values = np.asarray(values, dtype=str)
    elif values.dtype.kind in "US":
        raise TypeError(
            "string queries against a numeric-sorted vocabulary: pass the "
            "stringified lookup table (see _VocabModelBase._str_lookup)"
        )
    pos = np.searchsorted(sorted_vocab, values)
    pos_clipped = np.minimum(pos, len(sorted_vocab) - 1)
    found = sorted_vocab[pos_clipped] == values
    return order[pos_clipped], found


class StringIndexer(_StringIndexerParams, Estimator):
    """Fit per-column category vocabularies (multi-column, like the wider
    Flink ML StringIndexer)."""

    def fit(self, *inputs: Table) -> "StringIndexerModel":
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        if not input_cols:
            raise ValueError("inputCols must be set")
        order_type = self.get(self.STRING_ORDER_TYPE)
        cap = self.get(self.MAX_INDEX_NUM)
        vocabs = [
            _ordered_vocab(_column_values(table, col), order_type)[:cap]
            for col in input_cols
        ]
        model = StringIndexerModel()
        model.copy_params_from(self)
        model._set_vocabs(vocabs)
        return model


class _VocabModelBase(_StringIndexerParams, Model):
    """Shared vocab-backed model scaffold: model-data tables, persistence
    (one npz key per ragged column vocabulary), and the fitted-state
    guard. StringIndexerModel and IndexToStringModel differ only in the
    direction of the mapping."""

    def __init__(self):
        super().__init__()
        self._vocabs: Optional[List[np.ndarray]] = None
        self._lookup_tables: List[Tuple[np.ndarray, np.ndarray]] = []
        self._str_lookup_tables: List[
            Optional[Tuple[np.ndarray, np.ndarray]]
        ] = []

    def _set_vocabs(self, vocabs: List[np.ndarray]) -> None:
        self._vocabs = [np.asarray(v) for v in vocabs]
        # (sorted_vocab, order) per column, fixed at fit time so transform
        # never re-sorts a (possibly high-cardinality) vocabulary.
        self._lookup_tables = [_sorted_lookup_table(v) for v in self._vocabs]
        self._str_lookup_tables: List[
            Optional[Tuple[np.ndarray, np.ndarray]]
        ] = [None] * len(self._vocabs)

    def _str_lookup(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        """Stringified lookup table for column ``i``, built once on first
        use: a numeric-sorted vocab is not lexicographically sorted after
        str coercion (e.g. [2, 10] -> ['2', '10']), so it must be
        re-sorted — but once per model, not per transform."""
        if self._str_lookup_tables[i] is None:
            sorted_vocab, order = self._lookup_tables[i]
            as_str = np.asarray(sorted_vocab, dtype=str)
            resort = np.argsort(as_str, kind="stable")
            self._str_lookup_tables[i] = (as_str[resort], order[resort])
        return self._str_lookup_tables[i]

    def set_model_data(self, *inputs: Table):
        (table,) = inputs
        order = np.argsort(np.asarray(table.column("columnIndex")))
        terms = table.column("terms")
        self._set_vocabs([terms[i] for i in order])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        terms = np.empty(len(self._vocabs), dtype=object)
        for i, v in enumerate(self._vocabs):
            terms[i] = v
        return [
            Table({"columnIndex": np.arange(len(self._vocabs)), "terms": terms})
        ]

    def _require_model(self) -> None:
        if self._vocabs is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    def save(self, path: str) -> None:
        self._require_model()
        # One npz key per column (vocabularies are ragged); string vocabs
        # persist as native unicode arrays — no pickling.
        arrays = {f"terms_{i}": v for i, v in enumerate(self._vocabs)}
        arrays["numColumns"] = np.asarray(len(self._vocabs))
        self._save_with_arrays(path, arrays)

    @classmethod
    def load(cls, path: str):
        model, arrays, _ = cls._load_with_arrays(path)
        n = int(arrays["numColumns"])
        model._set_vocabs([arrays[f"terms_{i}"] for i in range(n)])
        return model

    def _check_columns(self, input_cols, output_cols) -> None:
        if len(input_cols) != len(output_cols):
            raise ValueError(
                f"{len(input_cols)} input columns vs {len(output_cols)} output columns"
            )
        if len(input_cols) != len(self._vocabs):
            raise ValueError(
                f"model was fit on {len(self._vocabs)} columns, got {len(input_cols)}"
            )


class StringIndexerModel(_VocabModelBase):
    # -- transform ---------------------------------------------------------
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        input_cols = self.get(self.INPUT_COLS)
        output_cols = self.get(self.OUTPUT_COLS)
        handle_invalid = self.get(self.HANDLE_INVALID)
        self._check_columns(input_cols, output_cols)
        out = table
        keep_mask = np.ones(table.num_rows, dtype=bool)
        for i, (col, out_col, vocab) in enumerate(
            zip(input_cols, output_cols, self._vocabs)
        ):
            values = _column_values(table, col)
            sorted_vocab, order = self._lookup_tables[i]
            if (
                values.dtype.kind in "US"
                and len(sorted_vocab)
                and sorted_vocab.dtype.kind not in "US"
            ):
                sorted_vocab, order = self._str_lookup(i)
            idx, found = _lookup(values, sorted_vocab, order)
            if handle_invalid == HasHandleInvalid.ERROR_INVALID:
                if not found.all():
                    bad = np.asarray(values)[~found][:5]
                    raise ValueError(
                        f"Column {col!r} contains values not seen during "
                        f"fitting: {list(bad)}"
                    )
            elif handle_invalid == HasHandleInvalid.SKIP_INVALID:
                keep_mask &= found
            else:  # keep: unseen → catch-all index len(vocab)
                idx = np.where(found, idx, len(vocab))
            out = out.with_column(out_col, idx.astype(np.float64))
        if not keep_mask.all():
            out = out.take(np.nonzero(keep_mask)[0])
        return (out,)


class IndexToStringModel(_VocabModelBase):
    """Inverse of StringIndexerModel: double indices → original values,
    driven by the same model data (the upstream family's
    ``IndexToStringModel``).

    The catch-all index ``len(vocab)`` — what StringIndexerModel emits for
    unseen values under ``handleInvalid='keep'`` — round-trips to a
    sentinel instead of raising: ``'__unknown__'`` for string
    vocabularies, ``NaN`` for numeric ones. Indices outside
    ``[0, len(vocab)]`` still raise."""

    UNKNOWN_SENTINEL = "__unknown__"

    @staticmethod
    def from_indexer(indexer: StringIndexerModel) -> "IndexToStringModel":
        """Build the inverse transformer from a fitted StringIndexerModel."""
        model = IndexToStringModel()
        model.copy_params_from(indexer)
        model.set_model_data(*indexer.get_model_data())
        return model

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        input_cols = self.get(self.INPUT_COLS)
        output_cols = self.get(self.OUTPUT_COLS)
        self._check_columns(input_cols, output_cols)
        out = table
        for col, out_col, vocab in zip(input_cols, output_cols, self._vocabs):
            values = np.asarray(table.column(col), dtype=np.float64)
            idx = values.astype(np.int64)
            if not np.all(values == idx):
                raise ValueError(
                    f"Column {col!r} contains non-integral indices"
                )
            invalid = (idx < 0) | (idx > len(vocab))
            if invalid.any():
                raise ValueError(
                    f"Column {col!r} contains indices outside "
                    f"[0, {len(vocab)}]: {idx[invalid][:5]}"
                )
            catch_all = idx == len(vocab)
            if len(vocab) == 0:  # every index is the catch-all
                res = np.zeros(len(idx), dtype=np.float64)
                catch_all = np.ones(len(idx), dtype=bool)
            else:
                res = vocab[np.where(catch_all, 0, idx)]
            # keep-mode round-trip: the catch-all index becomes a
            # sentinel rather than an error. The output dtype is fixed
            # per vocab kind (object for strings, float64 for numerics)
            # REGARDLESS of whether this batch contains a catch-all, so
            # downstream schema checks never flip dtype between batches.
            if vocab.dtype.kind in "USO":
                res = res.astype(object)
                res[catch_all] = self.UNKNOWN_SENTINEL
            else:
                res = res.astype(np.float64)
                res[catch_all] = np.nan
            out = out.with_column(out_col, res)
        return (out,)
