"""MulticlassClassificationEvaluator, RegressionEvaluator,
ClusteringEvaluator.

Members of the wider Flink ML evaluator family (the reference snapshot
has none). All are one-pass reductions over host-resident columns —
except the clustering silhouette, whose O(n·k) distance work runs as one
batched device program on the MXU (the same gemm-shaped kernel as KMeans
assignment).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasWeightCol,
)
from flinkml_tpu.params import StringArrayParam, StringParam
from flinkml_tpu.table import Table


def _weighted(values, w):
    return float(np.sum(values * w) / np.sum(w))


def multiclass_metrics(labels, predictions, weights=None) -> Dict[str, float]:
    """Weighted multiclass metrics from a confusion matrix.

    Per-class precision/recall/F1 aggregate weighted by true-class
    support (the sklearn ``average='weighted'`` convention, matching the
    upstream evaluator's weightedPrecision/weightedRecall/weightedF1).
    """
    y = np.asarray(labels, dtype=np.float64).reshape(-1)
    p = np.asarray(predictions, dtype=np.float64).reshape(-1)
    w = (np.ones_like(y) if weights is None
         else np.asarray(weights, dtype=np.float64).reshape(-1))
    if y.shape != p.shape or y.shape != w.shape:
        raise ValueError("labels/predictions/weights lengths differ")
    if not (np.isfinite(y).all() and np.isfinite(p).all()):
        raise ValueError(
            "labels/predictions contain NaN/inf (drop cold-start NaN "
            "predictions before evaluating)"
        )
    classes, inv = np.unique(np.concatenate([y, p]), return_inverse=True)
    k = len(classes)
    yi, pi = inv[: len(y)], inv[len(y):]
    # Weighted confusion matrix via bincount on flattened (true, pred).
    conf = np.bincount(yi * k + pi, weights=w, minlength=k * k).reshape(k, k)
    support = conf.sum(axis=1)              # weighted rows per true class
    predicted = conf.sum(axis=0)
    tp = np.diag(conf)
    total = conf.sum()
    accuracy = float(tp.sum() / total)
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = np.where(predicted > 0, tp / predicted, 0.0)
        recall = np.where(support > 0, tp / support, 0.0)
        f1 = np.where(
            precision + recall > 0,
            2 * precision * recall / (precision + recall),
            0.0,
        )
    sw = support / total
    return {
        "accuracy": accuracy,
        "weightedPrecision": float(np.sum(precision * sw)),
        "weightedRecall": float(np.sum(recall * sw)),
        "weightedF1": float(np.sum(f1 * sw)),
    }


_MULTI_SUPPORTED = (
    "accuracy", "weightedPrecision", "weightedRecall", "weightedF1",
)


class MulticlassClassificationEvaluator(
    HasLabelCol, HasPredictionCol, HasWeightCol, AlgoOperator
):
    METRICS_NAMES = StringArrayParam(
        "metricsNames", "Names of the output metrics.",
        ["accuracy", "weightedF1"],
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        names = self.get(self.METRICS_NAMES)
        unknown = [n for n in names if n not in _MULTI_SUPPORTED]
        if unknown:
            raise ValueError(
                f"unsupported metrics {unknown}; supported: "
                f"{list(_MULTI_SUPPORTED)}"
            )
        weight_col = self.get(self.WEIGHT_COL)
        metrics = multiclass_metrics(
            table.column(self.get(self.LABEL_COL)),
            table.column(self.get(self.PREDICTION_COL)),
            table.column(weight_col) if weight_col else None,
        )
        return (Table({n: np.asarray([metrics[n]]) for n in names}),)


def regression_metrics(labels, predictions, weights=None) -> Dict[str, float]:
    y = np.asarray(labels, dtype=np.float64).reshape(-1)
    p = np.asarray(predictions, dtype=np.float64).reshape(-1)
    w = (np.ones_like(y) if weights is None
         else np.asarray(weights, dtype=np.float64).reshape(-1))
    if y.shape != p.shape or y.shape != w.shape:
        raise ValueError("labels/predictions/weights lengths differ")
    err = p - y
    mse = _weighted(err * err, w)
    mae = _weighted(np.abs(err), w)
    mean_y = _weighted(y, w)
    ss_tot = float(np.sum(w * (y - mean_y) ** 2))
    ss_res = float(np.sum(w * err * err))
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else float("nan")
    # sklearn convention: 1 - Var_w(err) / Var_w(y).
    var_y = ss_tot / float(np.sum(w))
    var_err = _weighted((err - _weighted(err, w)) ** 2, w)
    explained = 1.0 - var_err / var_y if var_y > 0 else float("nan")
    return {
        "mse": mse,
        "rmse": float(np.sqrt(mse)),
        "mae": mae,
        "r2": r2,
        "explainedVariance": explained,
    }


_REG_SUPPORTED = ("mse", "rmse", "mae", "r2", "explainedVariance")


class RegressionEvaluator(
    HasLabelCol, HasPredictionCol, HasWeightCol, AlgoOperator
):
    METRICS_NAMES = StringArrayParam(
        "metricsNames", "Names of the output metrics.", ["rmse", "r2"],
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        names = self.get(self.METRICS_NAMES)
        unknown = [n for n in names if n not in _REG_SUPPORTED]
        if unknown:
            raise ValueError(
                f"unsupported metrics {unknown}; supported: "
                f"{list(_REG_SUPPORTED)}"
            )
        weight_col = self.get(self.WEIGHT_COL)
        metrics = regression_metrics(
            table.column(self.get(self.LABEL_COL)),
            table.column(self.get(self.PREDICTION_COL)),
            table.column(weight_col) if weight_col else None,
        )
        return (Table({n: np.asarray([metrics[n]]) for n in names}),)


def simplified_silhouette(x: np.ndarray, assignment: np.ndarray) -> float:
    """Simplified (centroid-based) silhouette: a(i) = distance to own
    centroid, b(i) = distance to nearest other centroid — the O(n·k)
    form the upstream evaluator uses (exact silhouette is O(n²)).

    The [n, k] distance matrix is one batched device gemm (same shape as
    the KMeans assignment step).
    """
    import jax.numpy as jnp

    from flinkml_tpu.ops.blas import squared_distances

    x = np.asarray(x, dtype=np.float64)
    a = np.asarray(assignment)
    clusters, idx = np.unique(a, return_inverse=True)
    k = len(clusters)
    if k < 2:
        raise ValueError("silhouette requires at least 2 clusters")
    if k >= x.shape[0]:
        raise ValueError("silhouette requires n_points > n_clusters")
    cents = np.stack([x[idx == c].mean(axis=0) for c in range(k)])
    d = np.sqrt(np.maximum(np.asarray(
        squared_distances(jnp.asarray(x, jnp.float32),
                          jnp.asarray(cents, jnp.float32)),
        dtype=np.float64,
    ), 0.0))
    n = x.shape[0]
    own = d[np.arange(n), idx]
    d_other = d.copy()
    d_other[np.arange(n), idx] = np.inf
    nearest_other = d_other.min(axis=1)
    denom = np.maximum(np.maximum(own, nearest_other), 1e-300)
    return float(np.mean((nearest_other - own) / denom))


class ClusteringEvaluator(HasFeaturesCol, HasPredictionCol, AlgoOperator):
    """Simplified silhouette over a features + cluster-assignment table."""

    METRICS_NAMES = StringArrayParam(
        "metricsNames", "Names of the output metrics.", ["silhouette"],
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        names = self.get(self.METRICS_NAMES)
        unknown = [n for n in names if n != "silhouette"]
        if unknown:
            raise ValueError(
                f"unsupported metrics {unknown}; supported: ['silhouette']"
            )
        from flinkml_tpu.models._data import features_matrix

        value = simplified_silhouette(
            features_matrix(table, self.get(self.FEATURES_COL)),
            np.asarray(table.column(self.get(self.PREDICTION_COL))),
        )
        return (Table({"silhouette": np.asarray([value])}),)
