"""OnlineKMeans — decayed mini-batch k-means over an unbounded stream.

Member of the wider Flink ML family (apache/flink-ml's ``OnlineKMeans``;
the reference snapshot has only the bounded KMeans, SURVEY.md §2.3) and
the second user of the unbounded-iteration mode (with
``OnlineLogisticRegression``): one centroid update per arriving batch,
with the standard decay rule shared by Spark's streaming k-means and
flink-ml::

    n'       = decay * n + count_batch
    centroid = (decay * n * centroid + sum_batch) / n'      (n' > 0)

``decayFactor`` = 1 gives the running exact mini-batch mean; 0 forgets
history entirely each batch. Initial centroids come from a fitted
``KMeansModel`` via ``set_initial_model_data`` (how flink-ml requires it)
or, if unset, from ``k`` random rows of the first batch.
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasDecayFactor,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasPredictionCol,
    HasSeed,
)
from flinkml_tpu.iteration import (
    IterationConfig,
    TerminateOnMaxIter,
    iterate,
)
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.ops import blas
from flinkml_tpu.ops.distance import DistanceMeasure
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.table import Table


class _OnlineKMeansParams(
    HasFeaturesCol, HasPredictionCol, HasGlobalBatchSize, HasDecayFactor,
    HasSeed,
):
    K = IntParam(
        "k", "The number of clusters to create.", 2, ParamValidators.gt(1)
    )


@jax.jit
def _batch_stats(x, centroids):
    """One assignment pass: per-centroid batch sums and counts."""
    d2 = blas.squared_distances(x, centroids)
    assign = jnp.argmin(d2, axis=-1)
    onehot = jax.nn.one_hot(assign, centroids.shape[0], dtype=x.dtype)
    return onehot.T @ x, jnp.sum(onehot, axis=0)


@functools.lru_cache(maxsize=16)
def _batch_stats_sharded(mesh, axis: str):
    """Multi-process assignment pass: per-device partial sums/counts
    combined with one ``psum`` (zero-weight padding/dummy rows are exact
    no-ops)."""
    from jax.sharding import PartitionSpec as P

    def local(xl, wl, centroids):
        d2 = blas.squared_distances(xl, centroids)
        assign = jnp.argmin(d2, axis=-1)
        onehot = (
            jax.nn.one_hot(assign, centroids.shape[0], dtype=xl.dtype)
            * wl[:, None]
        )
        return (
            jax.lax.psum(onehot.T @ xl, axis),
            jax.lax.psum(jnp.sum(onehot, axis=0), axis),
        )

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P()), out_specs=(P(), P()),
        )
    )


class OnlineKMeans(_OnlineKMeansParams, Estimator):
    def __init__(self, mesh=None):
        super().__init__()
        self.mesh = mesh
        self._initial_centroids: Optional[np.ndarray] = None

    def set_initial_model_data(self, *inputs: Table) -> "OnlineKMeans":
        """Warm-start from a (bounded) KMeansModel's model-data table."""
        (table,) = inputs
        c = np.asarray(table.column("centroids"), dtype=np.float64)
        self._initial_centroids = c.reshape(c.shape[-2], c.shape[-1])
        return self

    def fit(self, *inputs: Table) -> "OnlineKMeansModel":
        """Consume the table as a stream of globalBatchSize mini-batches."""
        (table,) = inputs
        batch_size = self.get(self.GLOBAL_BATCH_SIZE)
        return self.fit_stream(table.batches(batch_size))

    def fit_stream(
        self,
        batches: Iterable[Table],
        *,
        checkpoint_manager=None,
        checkpoint_interval: int = 0,
        resume: bool = False,
        stream_resume: str = "replay",
        sentinel=None,
        recovery=None,
    ) -> "OnlineKMeansModel":
        """One decayed centroid update per arriving batch.

        Crash safety (ISSUE 4): ``checkpoint_manager`` +
        ``checkpoint_interval`` snapshot the carry (centroids, decayed
        weights, model version) every N consumed batches; ``resume=True``
        continues bit-exactly from the newest valid snapshot (corrupt
        ones are verified and skipped). ``stream_resume='replay'`` skips
        the already-consumed prefix of a restartable source;
        ``'continue'`` consumes a live stream from the front. See
        ``docs/development/fault_tolerance.md``.

        Self-healing (ISSUE 9): ``sentinel`` /``recovery`` thread the
        numerics sentinel and the rollback-and-quarantine policy of
        :mod:`flinkml_tpu.recovery` through the loop — a NaN'd batch is
        quarantined and the fit converges to the model the same stream
        without that batch produces (see the OnlineLogisticRegression
        docstring and ``fault_tolerance.md``, "Self-healing").

        Multi-process (round 4): each process feeds its OWN arriving
        stream partition; every update is one psum'd global assignment
        pass in SPMD lockstep (``stream_sync.synced_stream``), initial
        centroids pool across the ranks' first batches, and exhausted
        ranks contribute zero-weight dummies until every stream ends.
        The fitted centroids are identical on every rank."""
        k = self.get(self.K)
        decay = self.get(self.DECAY_FACTOR)
        features_col = self.get(self.FEATURES_COL)
        rng = np.random.default_rng(self.get_seed())
        if jax.process_count() > 1:
            if (checkpoint_manager is not None or resume
                    or sentinel is not None or recovery is not None):
                raise NotImplementedError(
                    "checkpoint/resume and sentinel/recovery for the "
                    "multi-process online stream path are not wired yet; "
                    "run the checkpointing/self-healing fit single-process"
                )
            return self._fit_stream_multiprocess(
                batches, k, decay, features_col, rng
            )

        from flinkml_tpu.iteration.checkpoint import begin_resume
        from flinkml_tpu.models._streaming import feed_world_size

        # The rescale guard pins the FEED's world (Dataset shard count /
        # ElasticFeed world; 1 for plain iterables); the centroid carry
        # is replicated, so a rescale="reshard" manager resumes it at
        # any world bit-exactly.
        restore_epoch = begin_resume(checkpoint_manager, resume,
                                     world_size=feed_world_size(batches))

        # Peek the first batch: initial centroids draw from it (when no
        # initial model data was given) and it fixes the carry structure
        # for checkpointing; it is then re-presented as epoch 0's data
        # (a flinkml_tpu.data.Dataset re-presents it by restarting — and
        # iterate() then owns its cursor checkpoint/resume).
        from flinkml_tpu.models._streaming import peek_stream

        first, stream = peek_stream(batches)
        if first is None:
            empty = self._model_from_empty_stream(
                checkpoint_manager, restore_epoch
            )
            if empty is not None:
                return empty
            raise ValueError("training stream is empty")
        x0 = features_matrix(first, features_col).astype(np.float64)
        if restore_epoch is not None:
            # A committed snapshot will overwrite the init state: skip the
            # draw (and its rows >= k validation — a resumed live stream's
            # first batch is NOT the draw batch); only the pytree
            # structure of the placeholder matters for restore.
            centroids0 = jnp.zeros((k, x0.shape[1]))
        elif self._initial_centroids is not None:
            centroids0 = jnp.asarray(self._initial_centroids)
        else:
            if x0.shape[0] < k:
                raise ValueError(
                    f"first batch has {x0.shape[0]} rows < k={k}; "
                    "increase globalBatchSize or provide initial model data"
                )
            idx = rng.choice(x0.shape[0], size=k, replace=False)
            centroids0 = jnp.asarray(x0[idx])
        state = {
            "centroids": centroids0,
            "weights": jnp.zeros(k, dtype=jnp.result_type(float)),
            "version": 0,
        }

        def step(carry, batch_table, epoch):
            x = features_matrix(batch_table, features_col).astype(np.float64)
            sums, counts = _batch_stats(jnp.asarray(x), carry["centroids"])
            old_w = carry["weights"] * decay
            new_w = old_w + counts
            safe = jnp.maximum(new_w, 1e-12)[:, None]
            updated = (old_w[:, None] * carry["centroids"] + sums) / safe
            carry["centroids"] = jnp.where(
                new_w[:, None] > 0, updated, carry["centroids"]
            )
            carry["weights"] = new_w
            carry["version"] = int(carry["version"]) + 1
            return carry, None

        result = iterate(
            step, state, stream,
            IterationConfig(
                TerminateOnMaxIter(2**31 - 1),
                checkpoint_interval=checkpoint_interval,
                checkpoint_manager=checkpoint_manager,
                stream_resume=stream_resume,
                sentinel=sentinel,
                recovery=recovery,
            ),
            resume=resume,
        )
        final = result.state
        model = OnlineKMeansModel()
        model.copy_params_from(self)
        model._centroids = np.asarray(final["centroids"])
        model._model_version = int(final["version"])
        # Self-healing record of the fit (None without a recovery policy).
        model.recovery_summary = result.recovery
        return model

    def _model_from_empty_stream(
        self, manager, restore_epoch
    ) -> Optional["OnlineKMeansModel"]:
        """The zero-batch cases that are NOT errors: a resumed run whose
        stream is already exhausted returns the checkpointed model
        (resume-as-noop on a fully consumed 'continue' tail), and a
        warm-started run returns the initial model data at version 0
        (the pre-ISSUE-4 contract). Returns None when the empty stream is
        a genuine error."""
        if restore_epoch is not None and manager is not None:
            # Leaf VALUES in `like` are irrelevant — only the structure.
            state, _ = manager.restore_latest(
                like={"centroids": 0, "weights": 0, "version": 0}
            )
            model = OnlineKMeansModel()
            model.copy_params_from(self)
            model._centroids = np.asarray(state["centroids"])
            model._model_version = int(state["version"])
            return model
        if self._initial_centroids is not None:
            model = OnlineKMeansModel()
            model.copy_params_from(self)
            model._centroids = np.asarray(self._initial_centroids)
            model._model_version = 0
            return model
        return None

    def _fit_stream_multiprocess(
        self, batches, k, decay, features_col, rng
    ) -> "OnlineKMeansModel":
        """The multi-host unbounded mode (see :meth:`fit_stream`)."""
        import itertools

        from flinkml_tpu.iteration.stream_sync import (
            agree_first_item_dim,
            pooled_sample,
            synced_padded_stream,
        )
        from flinkml_tpu.parallel import DeviceMesh
        from flinkml_tpu.parallel.dispatch import DispatchGuard

        mesh = self.mesh or DeviceMesh()
        row_tile = (mesh.axis_size() // jax.process_count()) * 8

        def extract(t):
            return features_matrix(t, features_col).astype(np.float32)

        d_seen = [None]

        def check(x):
            if x.ndim != 2 or x.shape[0] == 0:
                raise ValueError(
                    f"stream batches must be non-empty [n, d], got {x.shape}"
                )
            if d_seen[0] is None:
                d_seen[0] = x.shape[1]
            elif x.shape[1] != d_seen[0]:
                raise ValueError(
                    f"batch feature dim {x.shape[1]} != first batch's "
                    f"{d_seen[0]}"
                )

        first, it, dim = agree_first_item_dim(
            (extract(t) for t in batches), check,
            lambda x: x.shape[1], mesh,
        )
        d_seen[0] = dim

        if self._initial_centroids is not None:
            centroids = jnp.asarray(self._initial_centroids, jnp.float32)
        else:
            # Pool initial centroids across every rank's FIRST batch (the
            # single-process path draws k random rows of the first batch;
            # here "the first batch" is the union of the ranks' first
            # batches — identical selection on every host).
            if first is None:
                local = np.zeros((0, dim), np.float32)
                local_rows = 0
            else:
                take = min(k, first.shape[0])
                local = first[
                    rng.choice(first.shape[0], size=take, replace=False)
                ]
                local_rows = first.shape[0]
            pooled = pooled_sample(
                local, local_rows, k, self.get_seed(), mesh
            )
            if pooled.shape[0] < k:
                raise ValueError(
                    f"first batches hold {pooled.shape[0]} rows < k={k}; "
                    "increase globalBatchSize or provide initial model data"
                )
            centroids = jnp.asarray(pooled, jnp.float32)
        weights = jnp.zeros(k, jnp.float32)

        step_fn = _batch_stats_sharded(mesh.mesh, DeviceMesh.DATA_AXIS)
        guard = DispatchGuard()  # sustained dispatch needs backpressure
        stream = itertools.chain([first] if first is not None else [], it)
        version = 0
        for (x_pad,), wl, _h in synced_padded_stream(
            ((x,) for x in stream), mesh,
            check=lambda item: check(item[0]),
            row_tile=row_tile, dummy_cols=((dim,),),
        ):
            sums, counts = step_fn(
                mesh.global_batch(x_pad), mesh.global_batch(wl), centroids
            )
            old_w = weights * decay
            new_w = old_w + counts
            safe = jnp.maximum(new_w, 1e-12)[:, None]
            updated = (old_w[:, None] * centroids + sums) / safe
            centroids = jnp.where(new_w[:, None] > 0, updated, centroids)
            weights = new_w
            version += 1
            guard.after_dispatch(centroids)
        guard.flush(centroids)

        model = OnlineKMeansModel()
        model.copy_params_from(self)
        model._centroids = np.asarray(centroids, np.float64)
        model._model_version = version
        return model


class OnlineKMeansModel(_OnlineKMeansParams, Model):
    """Nearest-centroid prediction; tracks the model-data version like the
    online LR model (one version per consumed batch)."""

    def __init__(self):
        super().__init__()
        self._centroids: Optional[np.ndarray] = None
        self._model_version = 0

    @property
    def centroids(self) -> np.ndarray:
        self._require()
        return self._centroids

    @property
    def model_version(self) -> int:
        return self._model_version

    def set_model_data(self, *inputs: Table) -> "OnlineKMeansModel":
        (table,) = inputs
        c = np.asarray(table.column("centroids"), dtype=np.float64)
        self._centroids = c.reshape(c.shape[-2], c.shape[-1])
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"centroids": self._centroids[None, :, :]})]

    def _require(self) -> None:
        if self._centroids is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.FEATURES_COL))
        measure = DistanceMeasure.get_instance("euclidean")
        assign = np.asarray(
            measure.nearest(jnp.asarray(x), jnp.asarray(self._centroids))
        )
        return (table.with_column(self.get(self.PREDICTION_COL), assign),)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"centroids": self._centroids},
            extra={"modelVersion": self._model_version},
        )

    @classmethod
    def load(cls, path: str) -> "OnlineKMeansModel":
        model, arrays, extra = cls._load_with_arrays(path)
        model._centroids = arrays["centroids"]
        model._model_version = int(extra.get("modelVersion", 0))
        return model
