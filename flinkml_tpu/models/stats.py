"""Correlation — Pearson / Spearman correlation matrix of a features
column (the Spark/Flink ``Correlation`` stat operator).

Pearson runs on the mesh: the correlation matrix is the normalized
centered gram, and the gram pass is the same sharded MXU reduction PCA
uses (per-device ``centered_xᵀ @ centered_x`` + one ``psum``). Spearman
is Pearson over per-column average ranks; ranking is a host sort (ties
get average ranks, the scipy convention).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.common_params import HasFeaturesCol
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.models.pca import _mean_and_gram_fn
from flinkml_tpu.models.scalers import _shard_with_mask
from flinkml_tpu.params import ParamValidators, StringParam
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table

PEARSON = "pearson"
SPEARMAN = "spearman"


def _average_ranks(col: np.ndarray) -> np.ndarray:
    """1-based average ranks with ties averaged (scipy ``rankdata``)."""
    order = np.argsort(col, kind="stable")
    sorted_col = col[order]
    # Rank span of each tie group -> average rank per group.
    boundaries = np.concatenate(
        [[True], sorted_col[1:] != sorted_col[:-1]]
    )
    group = np.cumsum(boundaries) - 1
    start = np.nonzero(boundaries)[0]
    stop = np.append(start[1:], len(col))
    avg = (start + stop - 1) / 2.0 + 1.0
    ranks = np.empty(len(col))
    ranks[order] = avg[group]
    return ranks


def correlation_matrix(
    x: np.ndarray, method: str = PEARSON, mesh: DeviceMesh = None
) -> np.ndarray:
    x = np.asarray(x, dtype=np.float64)
    if method == SPEARMAN:
        x = np.stack([_average_ranks(x[:, j]) for j in range(x.shape[1])],
                     axis=1)
    mesh = mesh or DeviceMesh()
    xd, wd = _shard_with_mask(x, mesh)
    shift = np.asarray(x[0], dtype=np.float32)
    cnt, s, g = _mean_and_gram_fn(mesh.mesh, DeviceMesh.DATA_AXIS)(
        xd, wd, jnp.asarray(shift)
    )
    cnt = float(cnt)
    mean_c = np.asarray(s, np.float64) / cnt
    cov = np.asarray(g, np.float64) / cnt - np.outer(mean_c, mean_c)
    std = np.sqrt(np.maximum(np.diag(cov), 0.0))
    safe = np.where(std > 0, std, 1.0)
    corr = cov / np.outer(safe, safe)
    # Constant columns correlate NaN with everything but 1 with themselves
    # (the numpy/scipy convention).
    const = std == 0
    corr[const, :] = np.nan
    corr[:, const] = np.nan
    np.fill_diagonal(corr, 1.0)
    return np.clip(corr, -1.0, 1.0, out=corr)


class Correlation(HasFeaturesCol, AlgoOperator):
    METHOD = StringParam(
        "method", "Correlation method.", PEARSON,
        ParamValidators.in_array([PEARSON, SPEARMAN]),
    )

    def __init__(self, mesh: DeviceMesh = None):
        super().__init__()
        self.mesh = mesh

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        x = features_matrix(table, self.get(self.FEATURES_COL))
        corr = correlation_matrix(x, self.get(self.METHOD), self.mesh)
        return (Table({"corr": corr[None, :, :]}),)
