"""ALS — alternating least squares matrix factorization (explicit +
implicit feedback).

Beyond the reference snapshot but a flagship member of the wider Flink ML
family (recommendation). The TPU-native formulation avoids the
reference-style per-user sequential solves entirely:

  - Each half-step builds every user's normal equations AT ONCE from the
    ratings COO: gather the fixed side's factors (``y = Y[item_idx]``),
    form per-rating outer products, and ``segment_sum`` them into
    ``A [n, k, k]`` / ``b [n, k]`` — one fused scatter per half-step,
    the same keyed-aggregation pattern as NaiveBayes
    (SURVEY.md §2.5 "keyed sharding").
  - The per-rating work is chunked (``lax``-friendly fixed-size blocks)
    so peak memory is ``chunk × k²`` instead of ``nnz × k²``.
  - All user systems solve as ONE batched Cholesky
    (``jax.scipy.linalg.cho_factor/cho_solve`` over ``[n, k, k]``) —
    batched dense linear algebra is exactly what the MXU wants.
  - Multi-device: the COO is sharded over the data axis; per-device
    partial ``A``/``b`` combine with one ``psum`` (inside
    ``keyed_aggregate``), factors are replicated.

Regularization follows ALS-WR (the Spark/Flink convention): λ is scaled
by each user's rating count (``A_u += λ·n_u·I``); users with no ratings
get a pure-λ system and factor 0. Implicit mode is Hu/Koren/Volinsky:
confidence ``c = 1 + α·r``, preference 1 for observed pairs,
``A_u = YᵀY + Σ (c-1) y yᵀ + λ·n_u·I``, ``b_u = Σ c·y``.
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu import kernels
from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import HasMaxIter, HasPredictionCol, HasSeed
from flinkml_tpu.params import (
    BoolParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


class _ALSParams(HasMaxIter, HasPredictionCol, HasSeed):
    USER_COL = StringParam("userCol", "User id column.", "user")
    ITEM_COL = StringParam("itemCol", "Item id column.", "item")
    RATING_COL = StringParam("ratingCol", "Rating column.", "rating")
    RANK = IntParam("rank", "Factor dimensionality.", 10, ParamValidators.gt(0))
    REG_PARAM = FloatParam(
        "regParam", "ALS-WR regularization (scaled by rating count).", 0.1,
        ParamValidators.gt_eq(0.0),
    )
    IMPLICIT_PREFS = BoolParam(
        "implicitPrefs", "Implicit-feedback (confidence-weighted) mode.", False
    )
    ALPHA = FloatParam(
        "alpha", "Implicit-mode confidence slope (c = 1 + alpha * r).", 1.0,
        ParamValidators.gt_eq(0.0),
    )


def _als_layout() -> str:
    """Measured-default gate for the normal-equation reduction.

    ``segment`` (default): per-chunk ``segment_sum`` of the ``[rows, k,
    k]`` outer products — XLA's sort-based lowering drags the 4 KB
    per-row payload through a sort every chunk of every half-step
    (measured 1.4% of the streaming bound, BASELINE.md "rooflines").
    ``cumsum``: the rating→target assignment is STATIC across
    iterations, so the in-RAM fit sorts the COO by target once at pack
    time and each chunk reduces at precomputed run boundaries with
    :func:`~flinkml_tpu.ops.sparse.chunked_run_totals` — streaming
    passes plus a runs-sized sorted scatter. ``FLINKML_TPU_ALS_REDUCTION``
    selects; the device A/B decides the default. The streamed fit always
    uses ``segment`` (its chunks come from cache replay, unsorted)."""
    layout = os.environ.get("FLINKML_TPU_ALS_REDUCTION")
    if layout is None:
        # Measured default for this mesh (autotune tuning table), else
        # the historical "segment".
        from flinkml_tpu.autotune import tuned_default

        return tuned_default("als_reduction", "segment",
                             allowed=("segment", "cumsum"))
    if layout not in ("segment", "cumsum"):
        raise ValueError(
            f"FLINKML_TPU_ALS_REDUCTION={layout!r}: expected "
            "'segment' or 'cumsum'"
        )
    return layout


def als_run_tables(seg_padded: np.ndarray, p_size: int, chunk: int):
    """Per-(chunk, device) run boundaries for the ``cumsum`` reduction:
    ``(ends, cols)``, each ``[n_chunks, p·max_runs]``, over a COO that
    is PRE-SORTED by segment id (padding ids sort last by construction).
    One :func:`~flinkml_tpu.ops.sparse.run_boundary_tables` call over
    the COO reshaped to one row per (chunk, device) slice."""
    from flinkml_tpu.ops.sparse import run_boundary_tables

    chunk_g = p_size * chunk
    n_chunks = seg_padded.shape[0] // chunk_g
    if n_chunks == 0:  # empty table: zero chunks, zero table rows
        empty = np.zeros((0, 1), np.int32)
        return empty, empty
    ends, cols = run_boundary_tables(
        seg_padded[: n_chunks * chunk_g].reshape(n_chunks * p_size, chunk)
    )
    return (
        ends.reshape(n_chunks, -1),
        cols.reshape(n_chunks, -1),
    )


@functools.lru_cache(maxsize=32)
def _normal_eq_chunk_fn(mesh, axis: str, n_segments: int, implicit: bool,
                        layout: str = "segment",
                        segsum_backend: str = "xla"):
    """Accumulate one COO chunk into the normal equations.

    Chunk inputs are sharded over the data axis; the returned partial
    ``A``/``b`` are replicated (local reduction + one psum). Padded
    entries carry segment id ``n_segments`` and fall into a dummy row.
    ``layout="cumsum"`` takes two extra sharded args (per-device run
    ``ends``/``cols`` from :func:`als_run_tables`) and reduces without
    the per-chunk sort (see :func:`_als_layout`). The ``segment``
    layout's three scatters route through the kernel-backend gate
    (:mod:`flinkml_tpu.kernels`, site ``segment_sum``; ``segsum_backend``
    is lru-key material) — identical numerics under the default
    ``"xla"``, multi-block Pallas capable when the gate selects it.
    """
    from flinkml_tpu import kernels

    def weights(r, alpha):
        if implicit:
            conf_minus_1 = alpha * r
            return conf_minus_1, 1.0 + conf_minus_1  # Σ(c-1)yyᵀ / Σc·y
        return jnp.ones_like(r), r                   # Σyyᵀ / Σr·y

    def local(seg, idx, r, fixed, alpha):
        y = fixed[idx]                  # per-device gather of the fixed side
        a_w, b_w = weights(r, alpha)
        # Padded entries carry seg == n_segments and a_w/b_w of 0 (their
        # rating is 0; explicit a_w=1 is harmless in the dummy row).
        k = y.shape[1]
        outer = (y[:, :, None] * y[:, None, :]) * a_w[:, None, None]
        # Rank-2 operands keep the gated kernel eligible ([cells, k] is
        # its 2-D contract); reshape back after the scatter.
        a = kernels.segment_sum(
            outer.reshape(-1, k * k), seg, n_segments + 1,
            backend=segsum_backend,
        ).reshape(n_segments + 1, k, k)
        b = kernels.segment_sum(b_w[:, None] * y, seg, n_segments + 1,
                                backend=segsum_backend)
        cnt = kernels.segment_sum(jnp.ones_like(r), seg, n_segments + 1,
                                  backend=segsum_backend)
        return (
            jax.lax.psum(a[:-1], axis),
            jax.lax.psum(b[:-1], axis),
            jax.lax.psum(cnt[:-1], axis),
        )

    def local_cumsum(seg, idx, r, fixed, alpha, ends, cols):
        from flinkml_tpu.ops.sparse import chunked_run_totals

        k = fixed.shape[1]
        rows = seg.shape[0]
        y = fixed[idx]
        a_w, b_w = weights(r, alpha)
        outer = ((y[:, :, None] * y[:, None, :])
                 * a_w[:, None, None]).reshape(rows, k * k)
        payload = jnp.concatenate(
            [outer, b_w[:, None] * y, jnp.ones((rows, 1), y.dtype)], axis=1
        )
        runs = chunked_run_totals(payload, ends)     # [max_runs, k²+k+1]
        a = jnp.zeros((n_segments + 1, k * k), y.dtype).at[cols].add(
            runs[:, : k * k], indices_are_sorted=True
        )
        b = jnp.zeros((n_segments + 1, k), y.dtype).at[cols].add(
            runs[:, k * k: k * k + k], indices_are_sorted=True
        )
        cnt = jnp.zeros((n_segments + 1,), y.dtype).at[cols].add(
            runs[:, -1], indices_are_sorted=True
        )
        return (
            jax.lax.psum(a[:-1].reshape(n_segments, k, k), axis),
            jax.lax.psum(b[:-1], axis),
            jax.lax.psum(cnt[:-1], axis),
        )

    return jax.jit(
        jax.shard_map(
            local_cumsum if layout == "cumsum" else local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(), P())
            + ((P(axis), P(axis)) if layout == "cumsum" else ()),
            out_specs=(P(), P(), P()),
        )
    )


@jax.jit
def _solve_factors(a, b, gram, reg, counts):
    """Batched solve of every target's system: (A + gram + λ·max(n,1)·I) x = b.

    λ is floored at 1e-4: with regParam=0 an under-determined row (rating
    count < rank) has a singular system and cho_factor NaN-poisons
    silently; the floor keeps every system SPD within f32 Cholesky
    tolerance (1e-6 still produced NaNs) at negligible bias.
    """
    k = b.shape[1]
    lam = jnp.maximum(reg * jnp.maximum(counts, 1.0), 1e-4)
    eye = jnp.eye(k, dtype=a.dtype)
    systems = a + gram[None, :, :] + lam[:, None, None] * eye[None, :, :]
    cho = jax.scipy.linalg.cho_factor(systems)
    return jax.scipy.linalg.cho_solve(cho, b[:, :, None])[:, :, 0]


def _agree_id_vocab(local_ids: np.ndarray, mesh: DeviceMesh) -> np.ndarray:
    """Union the per-process sorted unique id arrays through the device
    fabric (multi-process streamed fit): each rank's ids ride the
    f64-exact hi/lo transport of
    :func:`~flinkml_tpu.iteration.stream_sync.gather_vectors` (exact for
    integer |id| < 2**47), NaN-padded to the agreed max length; every
    host computes the identical union. Returns int64 when every id is
    integral, float64 otherwise. An empty local vocabulary is legal
    (that rank feeds only dummy chunks)."""
    from flinkml_tpu.iteration.stream_sync import agree_max, gather_vectors

    h = agree_max(int(local_ids.shape[0]), mesh)
    if h == 0:
        raise ValueError("training stream is empty on every process")
    pad = np.full(h, np.nan)
    pad[: local_ids.shape[0]] = np.asarray(local_ids, np.float64)
    rows = gather_vectors(pad, mesh)
    ids = np.unique(rows[np.isfinite(rows)])
    as_int = ids.astype(np.int64)
    if np.array_equal(as_int.astype(np.float64), ids):
        return as_int
    return ids


def _pad_coo(seg: np.ndarray, idx: np.ndarray, r: np.ndarray,
             n_dummy: int, multiple: int):
    """Pad the COO to ``multiple``; padded entries get segment id
    ``n_dummy`` (the dropped dummy row), fixed-side index 0, rating 0 —
    contributing nothing in either mode."""
    pad = (-seg.shape[0]) % multiple
    return (
        np.concatenate([seg, np.full(pad, n_dummy)]).astype(np.int32),
        np.concatenate([idx, np.zeros(pad, idx.dtype)]).astype(np.int32),
        np.concatenate([r, np.zeros(pad, r.dtype)]).astype(np.float32),
    )


def _half_step(
    mesh: DeviceMesh,
    seg: np.ndarray, idx: np.ndarray, r: np.ndarray,   # padded COO (host)
    fixed: jnp.ndarray,            # [m, k] replicated factors of fixed side
    n_target: int,
    reg: float,
    implicit: bool,
    alpha: float,
    chunk: int,
    run_tables=None,
) -> jnp.ndarray:
    """One ALS half-step: solve all n_target factors given the fixed side.

    Chunks of ``devices × chunk`` COO rows stream through the
    normal-equation kernel, bounding the [rows, k, k] intermediate to
    ``chunk × k²`` per device. ``run_tables`` (a list of per-chunk
    device-resident ``(ends, cols)`` pairs from :func:`als_run_tables`,
    over a target-sorted COO) switches the reduction to the sort-free
    ``cumsum`` layout.
    """
    k = fixed.shape[1]
    chunk_g = mesh.axis_size() * chunk
    layout = "segment" if run_tables is None else "cumsum"
    fn = _normal_eq_chunk_fn(
        mesh.mesh, DeviceMesh.DATA_AXIS, n_target, implicit, layout,
        kernels.segsum_backend(),
    )
    a = jnp.zeros((n_target, k, k), jnp.float32)
    b = jnp.zeros((n_target, k), jnp.float32)
    cnt = jnp.zeros((n_target,), jnp.float32)
    alpha_j = jnp.asarray(alpha, jnp.float32)
    for c in range(seg.shape[0] // chunk_g):
        sl = slice(c * chunk_g, (c + 1) * chunk_g)
        # run_tables entries are per-chunk DEVICE-resident pairs, placed
        # once at fit time (they are iteration-invariant).
        extra = () if run_tables is None else run_tables[c]
        pa, pb, pc = fn(
            mesh.shard_batch(seg[sl]), mesh.shard_batch(idx[sl]),
            mesh.shard_batch(r[sl]), fixed, alpha_j, *extra,
        )
        a, b, cnt = a + pa, b + pb, cnt + pc
    if implicit:
        gram = fixed.T @ fixed
    else:
        gram = jnp.zeros((k, k), jnp.float32)
    return _solve_factors(a, b, gram, jnp.asarray(reg, jnp.float32), cnt)


class ALS(StreamingEstimatorMixin, _ALSParams, Estimator):
    """Alternating least squares over (user, item, rating) tables.

    ``fit`` accepts, besides a single in-RAM :class:`Table`:

      - an **iterable of batch Tables** — the out-of-core path: the COO
        stream is cached once (spilling to ``cache_dir`` beyond
        ``cache_memory_budget_bytes``) while the id vocabularies
        accumulate; every half-step then replays the cache, building the
        target side's normal equations batch-by-batch with bounded HBM
        residency (reference: ``ReplayOperator.java:62-250`` — every
        bounded iteration trains from replayed cached partitions);
      - a sealed :class:`~flinkml_tpu.iteration.datacache.DataCache`
        whose batches carry this estimator's user/item/rating columns.

    ``checkpoint_manager`` + ``checkpoint_interval`` snapshot
    ``(user_factors, item_factors)`` every N outer iterations of the
    streamed fit; ``resume=True`` restores and continues bit-exactly.
    """

    # Per-device rows handed to one normal-equation dispatch; bounds the
    # nnz×k² intermediate to chunk×k² per device.
    CHUNK = 1 << 16

    #: The knob is ACCEPTED at construction so the fit-time refusal can
    #: explain WHY the embedding-sharded primitive does not apply to
    #: ALS training (see :meth:`_refuse_sharded_fit`), instead of the
    #: mixin's generic constructor refusal.
    _SHARDING_PLAN_AWARE = True

    def _refuse_sharded_fit(self) -> None:
        """ALS's wall is NOT factor storage — it is the half-step's
        normal-equation buffers: every user half-step materializes
        ``A [n_users, k, k]`` / ``b [n_users, k]`` before the batched
        Cholesky, a vocab-sized working set that row-sharding the
        factor tables alone cannot cap (the sparse lookup/exchange
        primitive moves factor ROWS; it has nothing to say about A/b).
        Refuse loudly — the honest wiring — and point at what DOES
        exist: :meth:`ALSModel.factor_tables` serves fitted factors
        sharded, and the streamed fit bounds the COO (not A/b)."""
        if self.sharding_plan is not None:
            raise ValueError(
                "ALS.fit does not thread a sharding_plan: the per-half-"
                "step normal-equation buffers (A [n, k, k] / b [n, k]) "
                "are vocab-sized regardless of how the factor tables "
                "shard, so an embedding-sharded plan would not cap the "
                "working set it promises to cap. Partition the id space "
                "upstream (or shrink rank) to fit the half-step; fitted "
                "factors CAN be served sharded — see "
                "ALSModel.factor_tables and docs/development/"
                "embeddings.md."
            )

    def fit(self, *inputs) -> "ALSModel":
        self._refuse_sharded_fit()
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        self._reject_in_ram_checkpointing()
        users_raw = np.asarray(table.column(self.get(self.USER_COL)))
        items_raw = np.asarray(table.column(self.get(self.ITEM_COL)))
        ratings = np.asarray(
            table.column(self.get(self.RATING_COL)), dtype=np.float32
        )
        implicit = self.get(self.IMPLICIT_PREFS)
        if implicit and (ratings < 0).any():
            raise ValueError("implicitPrefs requires non-negative ratings")
        user_ids, u_idx = np.unique(users_raw, return_inverse=True)
        item_ids, i_idx = np.unique(items_raw, return_inverse=True)
        n_users, n_items = len(user_ids), len(item_ids)
        rank = self.get(self.RANK)
        reg = self.get(self.REG_PARAM)
        alpha = self.get(self.ALPHA)
        mesh = self.mesh or DeviceMesh()
        chunk = min(
            self.CHUNK,
            max(256, -(-len(ratings) // mesh.axis_size())),
        )

        rng = np.random.default_rng(self.get_seed())
        # Signed Gaussian init at scale 1/sqrt(rank); the first half-step
        # solves user factors from these, so no user init is needed
        # (maxIter is validated > 0).
        item_f = jnp.asarray(
            rng.normal(scale=1.0 / np.sqrt(rank), size=(n_items, rank))
            .astype(np.float32)
        )

        chunk_g = mesh.axis_size() * chunk
        user_tabs = item_tabs = None
        if _als_layout() == "cumsum":
            # Sort each side by target ONCE (the assignment is static
            # across iterations); padding ids (n_targets) sort last by
            # construction, so _pad_coo keeps the order.
            ou = np.argsort(u_idx, kind="stable")
            oi = np.argsort(i_idx, kind="stable")
            by_user = _pad_coo(
                u_idx[ou], i_idx[ou], ratings[ou], n_users, chunk_g
            )
            by_item = _pad_coo(
                i_idx[oi], u_idx[oi], ratings[oi], n_items, chunk_g
            )
            p = mesh.axis_size()

            def place_tabs(tabs):
                # Device-place the iteration-invariant tables ONCE, as
                # per-chunk sharded pairs.
                ends, cols = tabs
                return [
                    (mesh.shard_batch(e), mesh.shard_batch(c))
                    for e, c in zip(ends, cols)
                ]

            user_tabs = place_tabs(als_run_tables(by_user[0], p, chunk))
            item_tabs = place_tabs(als_run_tables(by_item[0], p, chunk))
        else:
            by_user = _pad_coo(u_idx, i_idx, ratings, n_users, chunk_g)
            by_item = _pad_coo(i_idx, u_idx, ratings, n_items, chunk_g)
        for _ in range(self.get(self.MAX_ITER)):
            user_f = _half_step(
                mesh, *by_user, item_f, n_users, reg, implicit, alpha,
                chunk, run_tables=user_tabs,
            )
            item_f = _half_step(
                mesh, *by_item, user_f, n_items, reg, implicit, alpha,
                chunk, run_tables=item_tabs,
            )
        model = ALSModel()
        model.copy_params_from(self)
        model._set_factors(
            user_ids, np.asarray(user_f), item_ids, np.asarray(item_f)
        )
        return model

    def _fit_stream(self, source) -> "ALSModel":
        """Out-of-core ALS (see class docstring): one caching pass
        accumulates the sorted id vocabularies; each half-step replays
        the cache, padding every batch to the row tile and accumulating
        the psum'd normal-equation partials on device. Only one batch
        (plus prefetch depth) of the COO is device-resident at a time.

        Multi-process (round 4): each process feeds its own ratings
        partition; the id vocabularies are unioned through the device
        fabric (numeric ids, |id| < 2**47 — :func:`_agree_id_vocab`),
        the per-half-step chunk schedule is agreed (drained ranks
        dispatch all-sentinel dummy chunks — exact no-ops, every row
        lands in the dropped segment), ingest failures ride the
        held-error rendezvous, dispatches are bounded, and the
        replicated factor pair checkpoints rank-0-write + barrier."""
        from flinkml_tpu.iteration.checkpoint import (
            begin_resume,
            should_snapshot,
        )
        from flinkml_tpu.iteration.datacache import (
            DataCache,
            DataCacheWriter,
            PrefetchingDeviceFeed,
        )
        from flinkml_tpu.iteration.stream_sync import (
            DeferredValidation,
            checked_ingest,
        )

        multi = jax.process_count() > 1
        if self.resume and not isinstance(source, DataCache):
            raise ValueError(
                "resume=True requires a durable DataCache input: a one-shot "
                "stream cannot be replayed from the start after a failure"
            )
        user_col = self.get(self.USER_COL)
        item_col = self.get(self.ITEM_COL)
        rating_col = self.get(self.RATING_COL)
        implicit = self.get(self.IMPLICIT_PREFS)
        rank = self.get(self.RANK)
        reg = self.get(self.REG_PARAM)
        alpha = self.get(self.ALPHA)
        mesh = self.mesh or DeviceMesh()
        resume_epoch = begin_resume(
            self.checkpoint_manager, self.resume, mesh.mesh.size
        )

        # -- pass 0: cache + per-batch uniques (one global sort at the end:
        # union1d per batch would re-sort the whole vocabulary B times) ----
        user_parts = []
        item_parts = []
        nnz = 0

        def ingest(u, i, r):
            nonlocal nnz
            if not (u.shape[0] == i.shape[0] == r.shape[0]):
                raise ValueError(
                    "user/item/rating columns must have equal length, got "
                    f"{u.shape[0]}/{i.shape[0]}/{r.shape[0]}"
                )
            if implicit and (r < 0).any():
                raise ValueError(
                    "implicitPrefs requires non-negative ratings"
                )
            if multi:
                for arr, what in ((u, "user"), (i, "item")):
                    ok = np.issubdtype(arr.dtype, np.number)
                    if ok:
                        a64 = np.asarray(arr, np.float64)
                        ok = bool(
                            np.all(np.isfinite(a64))
                            and (a64.size == 0
                                 or np.abs(a64).max() < 2.0 ** 47)
                        )
                    if not ok:
                        raise ValueError(
                            "multi-process ALS streamed fit requires "
                            f"finite numeric {what} ids with |id| < 2**47 "
                            "(they are unioned exactly through the "
                            "device fabric's f64 hi/lo transport)"
                        )
            user_parts.append(np.unique(u))
            item_parts.append(np.unique(i))
            nnz += r.shape[0]

        def batch_arrays(b):
            if isinstance(b, Table):
                return (
                    np.asarray(b.column(user_col)),
                    np.asarray(b.column(item_col)),
                    np.asarray(b.column(rating_col), np.float32),
                )
            return (
                np.asarray(b[user_col]),
                np.asarray(b[item_col]),
                np.asarray(b[rating_col], np.float32),
            )

        dv = DeferredValidation()

        def checked_add(b):
            # Extraction + validation are one checked step; multi-process
            # failures (and iterator raises) are held for the rendezvous.
            ingest(*batch_arrays(b))

        if isinstance(source, DataCache):
            cache = source
            for _ in checked_ingest(cache.reader(), dv, checked_add, multi):
                pass
        else:
            writer = DataCacheWriter(
                self.cache_dir, self.cache_memory_budget_bytes
            )

            def add_append(b):
                u, i, r = batch_arrays(b)
                ingest(u, i, r)
                # The append is part of the checked step too (a rank-local
                # spill failure must ride the rendezvous).
                writer.append({user_col: np.array(u), item_col: np.array(i),
                               rating_col: np.array(r)})

            for _ in checked_ingest(source, dv, add_append, multi):
                pass
            cache = writer.finish()

        def local_unique(parts):
            return (
                np.unique(np.concatenate(parts)) if parts else np.empty(0)
            )

        if multi:
            from flinkml_tpu.iteration.stream_sync import gather_vectors

            # Rendezvous BEFORE any agreement: a held ingest error must
            # surface as itself, not as "stream is empty".
            dv.rendezvous(mesh, "stream ingest validation")
            nnz = int(round(gather_vectors(
                np.asarray([float(nnz)]), mesh
            ).sum()))
            if nnz == 0:
                raise ValueError("training stream is empty on every process")
            user_ids = _agree_id_vocab(local_unique(user_parts), mesh)
            item_ids = _agree_id_vocab(local_unique(item_parts), mesh)
        else:
            if nnz == 0:
                raise ValueError("training stream is empty")
            user_ids = local_unique(user_parts)
            item_ids = local_unique(item_parts)
        n_users, n_items = len(user_ids), len(item_ids)

        # Replayed batches dispatch in FIXED chunk_local-row slices (this
        # process's share of one dispatch) — the same CHUNK bound the
        # in-RAM path uses to cap the [rows, k, k] normal-equation
        # intermediate at chunk×k² per device, and a single compiled
        # shape per target side regardless of how the cache happens to
        # be batched. Under multi-process, nnz is the GLOBAL count
        # (agreed above), so every rank compiles the same chunk shape.
        chunk = min(self.CHUNK, max(256, -(-nnz // mesh.axis_size())))
        chunk_local = (mesh.axis_size() // jax.process_count()) * chunk

        steps_half = None
        if multi:
            from flinkml_tpu.iteration.stream_sync import (
                agree_max,
                entry_rows,
            )

            # Agreed chunk schedule per half-step: every rank dispatches
            # the same number of chunk calls; drained ranks fill with
            # all-sentinel dummy chunks (exact no-ops — every padded row
            # lands in the dropped dummy segment).
            local_total = sum(
                -(-entry_rows(e) // chunk_local) for e in cache.entries
            )
            steps_half = agree_max(local_total, mesh)

        chunk_fns = {
            True: _normal_eq_chunk_fn(
                mesh.mesh, DeviceMesh.DATA_AXIS, n_users, implicit,
                "segment", kernels.segsum_backend(),
            ),
            False: _normal_eq_chunk_fn(
                mesh.mesh, DeviceMesh.DATA_AXIS, n_items, implicit,
                "segment", kernels.segsum_backend(),
            ),
        }
        alpha_j = jnp.asarray(alpha, jnp.float32)

        from flinkml_tpu.parallel.dispatch import DispatchGuard

        def replay_half(fixed, by_user: bool):
            """One half-step's accumulation over the replayed cache."""
            n_target = n_users if by_user else n_items
            k = fixed.shape[1]
            a = jnp.zeros((n_target, k, k), jnp.float32)
            bvec = jnp.zeros((n_target, k), jnp.float32)
            cnt = jnp.zeros((n_target,), jnp.float32)
            fn = chunk_fns[by_user]
            guard = DispatchGuard()  # multi-process backpressure

            def place(batch):
                u, i, r = batch_arrays(batch)
                u_idx = np.searchsorted(user_ids, u).astype(np.int32)
                i_idx = np.searchsorted(item_ids, i).astype(np.int32)
                seg, idx = (u_idx, i_idx) if by_user else (i_idx, u_idx)
                seg, idx, r = _pad_coo(seg, idx, r, n_target, chunk_local)
                return [
                    (
                        mesh.global_batch(seg[sl]), mesh.global_batch(idx[sl]),
                        mesh.global_batch(r[sl]),
                    )
                    for sl in (
                        slice(c * chunk_local, (c + 1) * chunk_local)
                        for c in range(seg.shape[0] // chunk_local)
                    )
                ]

            dispatched = 0
            feed = PrefetchingDeviceFeed(cache.reader(), place=place, depth=2)
            try:
                for chunks in feed:
                    for seg, idx, r in chunks:
                        if steps_half is not None and dispatched >= steps_half:
                            raise RuntimeError(
                                "local cache yielded more chunks than the "
                                "agreed schedule — caches must be sealed "
                                "before planning"
                            )
                        pa, pb, pc = fn(seg, idx, r, fixed, alpha_j)
                        a, bvec, cnt = a + pa, bvec + pb, cnt + pc
                        dispatched += 1
                        guard.after_dispatch(cnt)
            finally:
                feed.close()
            if steps_half is not None and dispatched < steps_half:
                # Drained before the agreed schedule: dummy chunks keep
                # the SPMD dispatch count aligned across ranks.
                dseg = mesh.global_batch(
                    np.full(chunk_local, n_target, np.int32)
                )
                didx = mesh.global_batch(np.zeros(chunk_local, np.int32))
                dr = mesh.global_batch(np.zeros(chunk_local, np.float32))
                while dispatched < steps_half:
                    pa, pb, pc = fn(dseg, didx, dr, fixed, alpha_j)
                    a, bvec, cnt = a + pa, bvec + pb, cnt + pc
                    dispatched += 1
                    guard.after_dispatch(cnt)
            guard.flush(cnt)
            if implicit:
                gram = fixed.T @ fixed
            else:
                gram = jnp.zeros((k, k), jnp.float32)
            return _solve_factors(
                a, bvec, gram, jnp.asarray(reg, jnp.float32), cnt
            )

        user_f = jnp.zeros((n_users, rank), jnp.float32)
        start_epoch = 0
        if resume_epoch is None:
            rng = np.random.default_rng(self.get_seed())
            item_f = jnp.asarray(
                rng.normal(scale=1.0 / np.sqrt(rank), size=(n_items, rank))
                .astype(np.float32)
            )
        else:
            item_f = jnp.zeros((n_items, rank), jnp.float32)  # restored below
            like = (np.zeros((n_users, rank), np.float32),
                    np.zeros((n_items, rank), np.float32))
            from flinkml_tpu.iteration.stream_sync import agreed_restore

            (user_h, item_h), start_epoch = agreed_restore(
                self.checkpoint_manager, resume_epoch, like, mesh
            )
            user_f = jnp.asarray(user_h)
            item_f = jnp.asarray(item_h)

        max_iter = self.get(self.MAX_ITER)
        for epoch in range(start_epoch, max_iter):
            user_f = replay_half(item_f, by_user=True)
            item_f = replay_half(user_f, by_user=False)
            if should_snapshot(self.checkpoint_manager,
                               self.checkpoint_interval, epoch + 1, max_iter):
                state = (np.asarray(user_f), np.asarray(item_f))
                if multi:
                    from flinkml_tpu.iteration.checkpoint import (
                        save_replicated,
                    )

                    save_replicated(
                        self.checkpoint_manager, state, epoch + 1, mesh
                    )
                else:
                    self.checkpoint_manager.save(state, epoch + 1)

        model = ALSModel()
        model.copy_params_from(self)
        model._set_factors(
            user_ids, np.asarray(user_f), item_ids, np.asarray(item_f)
        )
        return model


class ALSModel(_ALSParams, Model):
    def __init__(self):
        super().__init__()
        self._user_ids: Optional[np.ndarray] = None
        self._item_ids: Optional[np.ndarray] = None
        self._user_factors: Optional[np.ndarray] = None
        self._item_factors: Optional[np.ndarray] = None

    def _set_factors(self, user_ids, user_factors, item_ids, item_factors):
        self._user_ids = np.asarray(user_ids)
        self._item_ids = np.asarray(item_ids)
        self._user_factors = np.asarray(user_factors, np.float64)
        self._item_factors = np.asarray(item_factors, np.float64)

    @property
    def user_factors(self) -> np.ndarray:
        self._require()
        return self._user_factors

    @property
    def item_factors(self) -> np.ndarray:
        self._require()
        return self._item_factors

    def set_model_data(self, *inputs: Table) -> "ALSModel":
        user_t, item_t = inputs
        self._set_factors(
            user_t.column("id"), user_t.column("factors"),
            item_t.column("id"), item_t.column("factors"),
        )
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [
            Table({"id": self._user_ids, "factors": self._user_factors}),
            Table({"id": self._item_ids, "factors": self._item_factors}),
        ]

    def _require(self) -> None:
        if self._user_factors is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def _positions(self, raw: np.ndarray, ids: np.ndarray):
        order = np.argsort(ids, kind="stable")
        sorted_ids = ids[order]
        pos = np.searchsorted(sorted_ids, raw)
        pos_c = np.minimum(pos, len(ids) - 1)
        found = sorted_ids[pos_c] == raw
        return order[pos_c], found

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        """Predict ratings for (user, item) rows; unseen ids → NaN (the
        upstream 'nan' cold-start strategy)."""
        (table,) = inputs
        self._require()
        users = np.asarray(table.column(self.get(self.USER_COL)))
        items = np.asarray(table.column(self.get(self.ITEM_COL)))
        u_pos, u_ok = self._positions(users, self._user_ids)
        i_pos, i_ok = self._positions(items, self._item_ids)
        pred = np.einsum(
            "nk,nk->n", self._user_factors[u_pos], self._item_factors[i_pos]
        )
        pred = np.where(u_ok & i_ok, pred, np.nan)
        return (table.with_column(self.get(self.PREDICTION_COL), pred),)

    def factor_tables(self, mesh=None, plan=None,
                      hbm_budget_bytes=None):
        """The fitted factors as row-sharded
        :class:`~flinkml_tpu.embeddings.EmbeddingTable`\\ s
        ``(user_table, item_table)`` — the serving-scale export: a
        100M-user factor matrix that cannot replicate onto one chip
        serves sharded (``table.lookup`` is bitwise stable at every
        world size, and an
        :class:`~flinkml_tpu.embeddings.serving.EmbeddingLookupModel`
        built from ``model.item_factors`` rides the ReplicaPool's slice
        meshes). Plan/budget resolution is EmbeddingTable's (explicit
        plan > ``infer_plan`` under a budget > replicated)."""
        from flinkml_tpu.embeddings import EmbeddingTable

        self._require()
        kw = dict(mesh=mesh, plan=plan, hbm_budget_bytes=hbm_budget_bytes)
        return (
            EmbeddingTable(
                "als/user", *self._user_factors.shape,
                rows=self._user_factors.astype(np.float32), **kw,
            ),
            EmbeddingTable(
                "als/item", *self._item_factors.shape,
                rows=self._item_factors.astype(np.float32), **kw,
            ),
        )

    def recommend_for_all_users(self, num_items: int):
        """Top ``num_items`` items per user: one [users, k] @ [k, items]
        matmul + top_k on device (the MXU path). Returns
        (item_id_matrix [n_users, num_items], score_matrix)."""
        self._require()
        scores = jnp.asarray(self._user_factors, jnp.float32) @ jnp.asarray(
            self._item_factors, jnp.float32
        ).T
        vals, idx = jax.lax.top_k(scores, min(num_items, len(self._item_ids)))
        return self._item_ids[np.asarray(idx)], np.asarray(vals)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {
            "userIds": self._user_ids,
            "userFactors": self._user_factors,
            "itemIds": self._item_ids,
            "itemFactors": self._item_factors,
        })

    @classmethod
    def load(cls, path: str) -> "ALSModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._set_factors(
            arrays["userIds"], arrays["userFactors"],
            arrays["itemIds"], arrays["itemFactors"],
        )
        return model
