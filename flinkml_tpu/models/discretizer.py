"""KBinsDiscretizer — fit bin edges per feature, transform to bin ids.

Member of the wider Flink ML family (upstream ``KBinsDiscretizer``).
Strategies:

  - ``uniform``: equal-width bins between each feature's min and max;
  - ``quantile``: per-feature quantile edges (duplicates collapse, so a
    feature with few distinct values just gets fewer bins);
  - ``kmeans``: 1-D Lloyd per feature (sorted-quantile init, edges at
    midpoints between adjacent centroids — the sklearn convention).

The fitted model transforms like a Bucketizer whose splits were learned:
``bin = #{edges < x}`` per feature, values clipped into
``[0, numBins-1]`` (out-of-range data goes to the edge bins, matching
the upstream/sklearn clip behavior). Fit statistics are vectorized host
passes — quantiles and 1-D k-means over host-resident columns don't
benefit from a device round-trip; the GBT trainer shares this binning
layout on its hot path (``gbt.quantile_bin_edges``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import HasInputCol, HasOutputCol
from flinkml_tpu.models._data import features_matrix
from flinkml_tpu.params import IntParam, ParamValidators, StringParam

from flinkml_tpu.table import Table

UNIFORM = "uniform"
QUANTILE = "quantile"
KMEANS = "kmeans"


class _KBinsParams(HasInputCol, HasOutputCol):
    NUM_BINS = IntParam(
        "numBins", "Number of bins per feature.", 5, ParamValidators.gt(1)
    )
    STRATEGY = StringParam(
        "strategy", "How to place the bin edges.", QUANTILE,
        ParamValidators.in_array([UNIFORM, QUANTILE, KMEANS]),
    )


def _kmeans_1d_edges(col: np.ndarray, num_bins: int) -> np.ndarray:
    """1-D Lloyd: init from quantiles of the DISTINCT values (so ties in
    skewed data can never collapse the seed below k — Lloyd can only
    shrink the center count, never grow it), exact assignment via sorted
    midpoints."""
    uniq = np.unique(col)
    k = min(num_bins, len(uniq))
    if k < 2:
        return np.full(0, np.inf)
    centers = np.quantile(uniq, np.linspace(0, 1, 2 * k + 1)[1::2])
    centers = np.unique(centers)
    for _ in range(20):
        mids = (centers[:-1] + centers[1:]) / 2.0
        assign = np.searchsorted(mids, col)
        sums = np.bincount(assign, weights=col, minlength=len(centers))
        counts = np.bincount(assign, minlength=len(centers))
        new = np.where(counts > 0, sums / np.maximum(counts, 1), centers)
        if np.allclose(new, centers):
            centers = new
            break
        centers = np.unique(new)
    return (centers[:-1] + centers[1:]) / 2.0


class KBinsDiscretizer(_KBinsParams, Estimator):
    def fit(self, *inputs: Table) -> "KBinsDiscretizerModel":
        (table,) = inputs
        x = features_matrix(table, self.get(self.INPUT_COL))
        num_bins = self.get(self.NUM_BINS)
        strategy = self.get(self.STRATEGY)
        d = x.shape[1]
        if strategy == QUANTILE:
            # Same binning contract as the GBT trainer's hot path.
            from flinkml_tpu.models.gbt import quantile_bin_edges

            edges = quantile_bin_edges(x, num_bins)
        else:
            edges = np.full((d, num_bins - 1), np.inf)
            for j in range(d):
                col = x[:, j]
                if strategy == UNIFORM:
                    lo, hi = float(col.min()), float(col.max())
                    if hi > lo:
                        e = np.linspace(lo, hi, num_bins + 1)[1:-1]
                    else:
                        e = np.full(0, np.inf)
                else:
                    e = _kmeans_1d_edges(col, num_bins)
                edges[j, : len(e)] = e
        model = KBinsDiscretizerModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"binEdges": edges[None, :, :]}))
        return model


class KBinsDiscretizerModel(_KBinsParams, Model):
    def __init__(self):
        super().__init__()
        self._edges: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "KBinsDiscretizerModel":
        (table,) = inputs
        self._edges = np.asarray(table.column("binEdges"), np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"binEdges": self._edges[None, :, :]})]

    @property
    def bin_edges(self) -> np.ndarray:
        self._require()
        return self._edges

    def _require(self) -> None:
        if self._edges is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        x = features_matrix(table, self.get(self.INPUT_COL))
        if x.shape[1] != self._edges.shape[0]:
            raise ValueError(
                f"model was fit on {self._edges.shape[0]} features, "
                f"got {x.shape[1]}"
            )
        from flinkml_tpu.models.gbt import bin_features

        out = bin_features(x, self._edges).astype(np.float64)
        return (table.with_column(self.get(self.OUTPUT_COL), out),)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"binEdges": self._edges})

    @classmethod
    def load(cls, path: str) -> "KBinsDiscretizerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._edges = arrays["binEdges"]
        return model
