"""PowerIterationClustering — clustering from pairwise affinities (the
Spark/Flink family member; an AlgoOperator like the upstream).

Lin & Cohen's PIC: power-iterate ``v ← D⁻¹ A v`` (the row-normalized
affinity matrix) from a degree-seeded start; the pseudo-eigenvector's
entries separate by cluster long before convergence, and a 1-D k-means
over them yields the assignment.

Device mapping: each iteration is ONE jitted sparse matvec — the edge
list stays in COO form and ``segment_sum(values · v[dst], src)`` is the
``D⁻¹ A v`` product (the same keyed-aggregation primitive as NaiveBayes
and ALS use), so no dense [n, n] affinity is ever materialized. The
final 1-D k-means runs on the host (k centers over n scalars).

Input: a table of ``srcCol``/``dstCol``/``weightCol`` edges
(undirected: each edge is symmetrized). Output: one row per distinct
vertex id with its cluster assignment.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import AlgoOperator
from flinkml_tpu.common_params import HasMaxIter, HasPredictionCol, HasSeed
from flinkml_tpu.params import IntParam, ParamValidators, StringParam
from flinkml_tpu.table import Table


class _PICParams(HasMaxIter, HasPredictionCol, HasSeed):
    SRC_COL = StringParam("srcCol", "Edge source vertex id column.", "src")
    DST_COL = StringParam("dstCol", "Edge destination vertex id column.", "dst")
    WEIGHT_COL = StringParam(
        "weightCol", "Edge affinity column (empty = 1.0).", None
    )
    K = IntParam("k", "Number of clusters.", 2, ParamValidators.gt(1))


@functools.lru_cache(maxsize=8)
def _power_iteration_fn(n_vertices: int):
    @jax.jit
    def run(src, dst, w_norm, v0, n_iter):
        def body(_, v):
            v = jax.ops.segment_sum(
                w_norm * v[dst], src, num_segments=n_vertices
            )
            # PIC normalizes by the L1 norm each step.
            return v / jnp.maximum(jnp.sum(jnp.abs(v)), 1e-30)

        return jax.lax.fori_loop(0, n_iter, body, v0)

    return run


def _kmeans_1d(values: np.ndarray, k: int, rng: np.random.Generator,
               iters: int = 50) -> np.ndarray:
    """Tiny exact-assignment 1-D Lloyd (quantile-seeded)."""
    lo, hi = float(values.min()), float(values.max())
    if hi - lo <= 1e-30:
        # Constant embedding (e.g. a fully-symmetric complete graph):
        # there is nothing to separate; everything is one cluster.
        return np.zeros(len(values), dtype=np.int64)
    centers = np.unique(np.quantile(values, np.linspace(0, 1, 2 * k + 1)[1::2]))
    while len(centers) < k:
        centers = np.unique(np.append(centers, rng.uniform(lo, hi)))
    for _ in range(iters):
        mids = (centers[:-1] + centers[1:]) / 2.0
        assign = np.searchsorted(mids, values)
        sums = np.bincount(assign, weights=values, minlength=len(centers))
        counts = np.bincount(assign, minlength=len(centers))
        new = np.where(counts > 0, sums / np.maximum(counts, 1), centers)
        if np.allclose(new, centers):
            break
        centers = np.sort(new)
    mids = (centers[:-1] + centers[1:]) / 2.0
    return np.searchsorted(mids, values)


class PowerIterationClustering(_PICParams, AlgoOperator):
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        src_raw = np.asarray(table.column(self.get(self.SRC_COL)))
        dst_raw = np.asarray(table.column(self.get(self.DST_COL)))
        weight_col = self.get(self.WEIGHT_COL)
        w = (
            np.asarray(table.column(weight_col), np.float64)
            if weight_col else np.ones(len(src_raw))
        )
        if (w < 0).any():
            raise ValueError("affinities must be non-negative")
        vertex_ids, idx = np.unique(
            np.concatenate([src_raw, dst_raw]), return_inverse=True
        )
        n = len(vertex_ids)
        k = self.get(self.K)
        if n < k:
            raise ValueError(f"{n} vertices < k={k}")
        src = idx[: len(src_raw)].astype(np.int32)
        dst = idx[len(src_raw):].astype(np.int32)
        # Symmetrize (undirected affinities, the upstream convention).
        src_s = np.concatenate([src, dst])
        dst_s = np.concatenate([dst, src])
        w_s = np.concatenate([w, w]).astype(np.float64)
        degree = np.zeros(n)
        np.add.at(degree, src_s, w_s)
        if (degree <= 0).any():
            raise ValueError("every vertex needs positive total affinity")
        w_norm = (w_s / degree[src_s]).astype(np.float32)
        rng = np.random.default_rng(self.get_seed())
        # Degree-seeded start plus seeded jitter: exactly symmetric
        # components (e.g. two identical triangles) give identical
        # pseudo-eigenvector entries under a pure degree init, which the
        # 1-D k-means can never separate — the perturbation breaks ties
        # while the degree term keeps the fast mixing PIC relies on.
        v0 = degree / degree.sum()
        v0 = (v0 * (1.0 + 0.01 * rng.standard_normal(n))).astype(np.float32)
        v = np.asarray(_power_iteration_fn(n)(
            jnp.asarray(src_s), jnp.asarray(dst_s), jnp.asarray(w_norm),
            jnp.asarray(v0), jnp.asarray(self.get(self.MAX_ITER), jnp.int32),
        ), dtype=np.float64)
        labels = _kmeans_1d(v, k, rng)
        # First-appearance relabeling for determinism (vectorized: a
        # k-sized LUT instead of a Python loop over all n vertices).
        _, first = np.unique(labels, return_index=True)
        lut = np.empty(labels.max() + 1, dtype=np.int64)
        for rank, i in enumerate(np.sort(first)):
            lut[labels[i]] = rank
        labels = lut[labels].astype(np.float64)
        return (
            Table({
                "id": vertex_ids,
                self.get(self.PREDICTION_COL): labels,
            }),
        )
