from flinkml_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from flinkml_tpu.models.kmeans import KMeans, KMeansModel
from flinkml_tpu.models.knn import Knn, KnnModel
from flinkml_tpu.models.naive_bayes import NaiveBayes, NaiveBayesModel
from flinkml_tpu.models.one_hot_encoder import OneHotEncoder, OneHotEncoderModel
from flinkml_tpu.models.linear_svc import LinearSVC, LinearSVCModel
from flinkml_tpu.models.linear_regression import (
    LinearRegression,
    LinearRegressionModel,
)
from flinkml_tpu.models.one_vs_rest import OneVsRest, OneVsRestModel
from flinkml_tpu.models.pic import PowerIterationClustering
from flinkml_tpu.models.prefixspan import PrefixSpan
from flinkml_tpu.models.online_kmeans import OnlineKMeans, OnlineKMeansModel
from flinkml_tpu.models.online_logistic_regression import (
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
)
from flinkml_tpu.models.scalers import (
    MaxAbsScaler,
    MaxAbsScalerModel,
    MinMaxScaler,
    MinMaxScalerModel,
    RobustScaler,
    RobustScalerModel,
    StandardScaler,
    StandardScalerModel,
)
from flinkml_tpu.models.feature_transforms import (
    Binarizer,
    Bucketizer,
    ElementwiseProduct,
    Normalizer,
    PolynomialExpansion,
    VectorSlicer,
)
from flinkml_tpu.models.gbt import (
    GBTClassifier,
    GBTClassifierModel,
    GBTRegressor,
    GBTRegressorModel,
    RandomForestClassifier,
    RandomForestClassifierModel,
    RandomForestRegressor,
    RandomForestRegressorModel,
)
from flinkml_tpu.models.discretizer import (
    KBinsDiscretizer,
    KBinsDiscretizerModel,
)
from flinkml_tpu.models.fm import (
    FMClassifier,
    FMClassifierModel,
    FMRegressor,
    FMRegressorModel,
)
from flinkml_tpu.models.bisecting_kmeans import (
    BisectingKMeans,
    BisectingKMeansModel,
)
from flinkml_tpu.models.fpgrowth import FPGrowth, FPGrowthModel
from flinkml_tpu.models.gmm import GaussianMixture, GaussianMixtureModel
from flinkml_tpu.models.survival import (
    AFTSurvivalRegression,
    AFTSurvivalRegressionModel,
)
from flinkml_tpu.models.imputer import Imputer, ImputerModel
from flinkml_tpu.models.isotonic import (
    IsotonicRegression,
    IsotonicRegressionModel,
)
from flinkml_tpu.models.lda import LDA, LDAModel
from flinkml_tpu.models.lsh import MinHashLSH, MinHashLSHModel
from flinkml_tpu.models.mlp import (
    MLPClassifier,
    MLPClassifierModel,
    MLPRegressor,
    MLPRegressorModel,
)
from flinkml_tpu.models.ngram import NGram
from flinkml_tpu.models.word2vec import Word2Vec, Word2VecModel
from flinkml_tpu.models.vector_indexer import (
    VectorIndexer,
    VectorIndexerModel,
)
from flinkml_tpu.models.online_scaler import (
    OnlineStandardScaler,
    OnlineStandardScalerModel,
)
from flinkml_tpu.models.stats import Correlation
from flinkml_tpu.models.agglomerative import AgglomerativeClustering
from flinkml_tpu.models.als import ALS, ALSModel
from flinkml_tpu.models.swing import Swing
from flinkml_tpu.models.pca import PCA, PCAModel
from flinkml_tpu.models.misc_transforms import (
    DCT,
    FeatureHasher,
    Interaction,
    RandomSplitter,
    StopWordsRemover,
)
from flinkml_tpu.models.selectors import (
    ANOVATest,
    ChiSqTest,
    FValueTest,
    UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
)
from flinkml_tpu.models.text import (
    CountVectorizer,
    CountVectorizerModel,
    HashingTF,
    IDF,
    IDFModel,
    RegexTokenizer,
    Tokenizer,
)
from flinkml_tpu.models.string_indexer import (
    IndexToStringModel,
    StringIndexer,
    StringIndexerModel,
)
from flinkml_tpu.models.sql_transformer import SQLTransformer
from flinkml_tpu.models.vector_assembler import VectorAssembler
from flinkml_tpu.models.evaluation import BinaryClassificationEvaluator
from flinkml_tpu.models.evaluation_multi import (
    ClusteringEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)

__all__ = [
    "LogisticRegression",
    "LogisticRegressionModel",
    "KMeans",
    "KMeansModel",
    "Knn",
    "KnnModel",
    "NaiveBayes",
    "NaiveBayesModel",
    "OneHotEncoder",
    "OneHotEncoderModel",
    "LinearSVC",
    "LinearSVCModel",
    "LinearRegression",
    "LinearRegressionModel",
    "OnlineKMeans",
    "OnlineKMeansModel",
    "OnlineLogisticRegression",
    "OnlineLogisticRegressionModel",
    "StandardScaler",
    "StandardScalerModel",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "MaxAbsScaler",
    "MaxAbsScalerModel",
    "RobustScaler",
    "RobustScalerModel",
    "Normalizer",
    "ElementwiseProduct",
    "VectorSlicer",
    "PolynomialExpansion",
    "Binarizer",
    "Bucketizer",
    "Imputer",
    "ImputerModel",
    "KBinsDiscretizer",
    "KBinsDiscretizerModel",
    "OnlineStandardScaler",
    "OnlineStandardScalerModel",
    "Correlation",
    "ALS",
    "ALSModel",
    "AgglomerativeClustering",
    "BisectingKMeans",
    "BisectingKMeansModel",
    "PowerIterationClustering",
    "GaussianMixture",
    "GaussianMixtureModel",
    "Swing",
    "GBTClassifier",
    "GBTClassifierModel",
    "GBTRegressor",
    "GBTRegressorModel",
    "RandomForestClassifier",
    "RandomForestClassifierModel",
    "RandomForestRegressor",
    "RandomForestRegressorModel",
    "MLPClassifier",
    "MLPClassifierModel",
    "MLPRegressor",
    "MLPRegressorModel",
    "OneVsRest",
    "OneVsRestModel",
    "FMClassifier",
    "FMClassifierModel",
    "FMRegressor",
    "FMRegressorModel",
    "IsotonicRegression",
    "IsotonicRegressionModel",
    "AFTSurvivalRegression",
    "AFTSurvivalRegressionModel",
    "FPGrowth",
    "FPGrowthModel",
    "PrefixSpan",
    "PCA",
    "PCAModel",
    "Tokenizer",
    "RegexTokenizer",
    "HashingTF",
    "CountVectorizer",
    "CountVectorizerModel",
    "IDF",
    "IDFModel",
    "StringIndexer",
    "StringIndexerModel",
    "IndexToStringModel",
    "SQLTransformer",
    "VectorAssembler",
    "BinaryClassificationEvaluator",
    "FeatureHasher",
    "Interaction",
    "DCT",
    "StopWordsRemover",
    "RandomSplitter",
    "NGram",
    "Word2Vec",
    "Word2VecModel",
    "LDA",
    "LDAModel",
    "VectorIndexer",
    "VectorIndexerModel",
    "MinHashLSH",
    "MinHashLSHModel",
    "ChiSqTest",
    "ANOVATest",
    "FValueTest",
    "VarianceThresholdSelector",
    "VarianceThresholdSelectorModel",
    "UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel",
    "MulticlassClassificationEvaluator",
    "RegressionEvaluator",
    "ClusteringEvaluator",
]
