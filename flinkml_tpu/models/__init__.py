from flinkml_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)

__all__ = [
    "LogisticRegression",
    "LogisticRegressionModel",
]
