"""The shared constructor/knob surface of every streamed-capable
estimator (round 4): one definition of the cache + checkpoint knobs, so
adding or renaming a streaming knob is a one-site change instead of a
per-estimator copy-paste.

Estimators inherit this FIRST (``class KMeans(StreamingEstimatorMixin,
_KMeansParams, Estimator)``); the mixin's ``__init__`` stores the knobs
and chains ``super().__init__()`` into the params machinery. Estimators
with extra knobs (GBT's ``stream_reservoir_capacity``) override
``__init__`` and delegate here.
"""

from __future__ import annotations

from typing import Optional


class StreamingEstimatorMixin:
    """Cache + checkpoint knobs shared by every streamed-capable
    estimator; see ``docs/development/iteration.md`` ("Out-of-core
    training") for the capacity model and the checkpoint protocol."""

    def __init__(
        self,
        mesh=None,
        cache_dir: Optional[str] = None,
        cache_memory_budget_bytes: Optional[int] = None,
        checkpoint_manager=None,
        checkpoint_interval: int = 0,
        resume: bool = False,
    ):
        super().__init__()
        self.mesh = mesh
        self.cache_dir = cache_dir
        self.cache_memory_budget_bytes = cache_memory_budget_bytes
        self.checkpoint_manager = checkpoint_manager
        self.checkpoint_interval = checkpoint_interval
        self.resume = resume

    def _checkpoint_kwargs(self) -> dict:
        return dict(
            checkpoint_manager=self.checkpoint_manager,
            checkpoint_interval=self.checkpoint_interval,
            resume=self.resume,
        )

    def _reject_in_ram_checkpointing(self, detail: str = "") -> None:
        """In-RAM fits that cannot checkpoint raise loudly instead of
        silently dropping the knobs."""
        if self.checkpoint_manager is not None or self.resume:
            raise ValueError(
                "checkpointing is supported for streamed fits only "
                "(pass an iterable of batch Tables or a DataCache)"
                + (f"; {detail}" if detail else "")
            )
