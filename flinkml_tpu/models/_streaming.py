"""The shared constructor/knob surface of every streamed-capable
estimator (round 4): one definition of the cache + checkpoint knobs, so
adding or renaming a streaming knob is a one-site change instead of a
per-estimator copy-paste.

Estimators inherit this FIRST (``class KMeans(StreamingEstimatorMixin,
_KMeansParams, Estimator)``); the mixin's ``__init__`` stores the knobs
and chains ``super().__init__()`` into the params machinery. Estimators
with extra knobs (GBT's ``stream_reservoir_capacity``) override
``__init__`` and delegate here.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


def peek_stream(batches) -> Tuple[Optional[Any], Any]:
    """Peek the first batch of a training stream without losing it.

    Returns ``(first_batch_or_None, stream_for_iterate)``. The online
    trainers peek to fix the carry's array shapes before the loop; HOW
    the peeked batch is re-presented depends on the stream kind:

    - a :class:`flinkml_tpu.data.Dataset` is restartable and
      cursor-tracked: it is peeked with a throwaway prefetch-free
      iterator and handed to :func:`~flinkml_tpu.iteration.iterate`
      WHOLE, so the runtime owns the skip/cursor machinery (chaining a
      consumed iterator would hide the Dataset and break cursor
      checkpoint/resume);
    - an :class:`flinkml_tpu.data.ElasticFeed` (world-parallel
      global-order feed) follows the Dataset contract — peeked with a
      throwaway iteration, handed to ``iterate`` whole so its GLOBAL
      cursor (and the elastic reshard on resume) belongs to the runtime;
    - a LIST of batches is peeked in place and handed to ``iterate`` AS
      the list — ``iterate`` re-iterates it from the start (its replay
      fast-forward handles positioning), which is also what lets the
      self-healing recovery loop re-open it after a rollback (a chained
      one-shot iterator could never be rewound). Lists only: the
      runtime's stream detection treats a tuple as a static pytree, so
      a tuple feed must keep the chained-iterator path;
    - any other iterable is peeked destructively and re-chained.
    """
    try:
        from flinkml_tpu.data import Dataset, ElasticFeed
    except ImportError:  # pragma: no cover — data subsystem always ships
        Dataset = ElasticFeed = None
    if Dataset is not None and isinstance(batches, (Dataset, ElasticFeed)):
        return batches.peek(), batches
    if isinstance(batches, list):
        if not batches:
            return None, iter(())
        return batches[0], batches
    import itertools

    it = iter(batches)
    try:
        first = next(it)
    except StopIteration:
        return None, iter(())
    return first, itertools.chain([first], it)


def feed_world_size(batches) -> int:
    """The world size the checkpoint rescale guard should pin for a
    training feed: a :class:`~flinkml_tpu.data.Dataset`'s shard count or
    an :class:`~flinkml_tpu.data.ElasticFeed`'s world (both expose
    ``num_shards``); 1 for plain iterables (a single-controller feed has
    no data-plane parallelism to guard). This is what lifts the online
    trainers' old ``world_size=1`` pin to mesh-aware resume: snapshots
    record the feed's TRUE world, and a manager with
    ``rescale="reshard"`` restores them at any other."""
    world = getattr(batches, "num_shards", None)
    try:
        return max(1, int(world)) if world is not None else 1
    except (TypeError, ValueError):
        return 1


class StreamingEstimatorMixin:
    """Cache + checkpoint knobs shared by every streamed-capable
    estimator; see ``docs/development/iteration.md`` ("Out-of-core
    training") for the capacity model and the checkpoint protocol."""

    #: Subclasses whose trainers thread a ShardingPlan set this True;
    #: everyone else gets a constructor-time refusal of the knob.
    _SHARDING_PLAN_AWARE = False

    #: Subclasses whose trainers thread a PrecisionPolicy (the FML6xx
    #: policy-gated mixed-precision path) set this True; everyone else
    #: gets a constructor-time refusal of the knob.
    _PRECISION_AWARE = False

    def __init__(
        self,
        mesh=None,
        cache_dir: Optional[str] = None,
        cache_memory_budget_bytes: Optional[int] = None,
        checkpoint_manager=None,
        checkpoint_interval: int = 0,
        resume: bool = False,
        sharding_plan=None,
        precision=None,
    ):
        super().__init__()
        self.mesh = mesh
        self.cache_dir = cache_dir
        self.cache_memory_budget_bytes = cache_memory_budget_bytes
        self.checkpoint_manager = checkpoint_manager
        self.checkpoint_interval = checkpoint_interval
        self.resume = resume
        if sharding_plan is not None and not type(self)._SHARDING_PLAN_AWARE:
            # Constructor-time loud refusal: a silently-ignored plan on
            # a plan-unaware estimator would train replicated — exactly
            # the OOM the user configured the plan to avoid.
            raise ValueError(
                f"{type(self).__name__} does not support sharding_plan "
                "yet (plan-aware estimators: the linear family's dense "
                "paths — LogisticRegression, LinearSVC, LinearRegression)"
            )
        if precision is not None and not type(self)._PRECISION_AWARE:
            # Same loud-refusal contract as the plan knob: a silently
            # ignored policy would "train in bf16" at full f32 cost —
            # the measurement the policy was declared to change.
            raise ValueError(
                f"{type(self).__name__} does not support precision yet "
                "(policy-aware estimators: the linear family's dense "
                "paths — LogisticRegression, LinearSVC, LinearRegression)"
            )
        from flinkml_tpu.precision import resolve_policy

        #: Optional :class:`~flinkml_tpu.precision.PrecisionPolicy` (or
        #: preset name / JSON dict, resolved here so a bad spelling
        #: fails at construction) — policy-aware estimators validate
        #: their step's jaxpr against it BEFORE any compile (FML6xx)
        #: and run compute at ``policy.compute``; see
        #: ``docs/development/precision.md``.
        self.precision = resolve_policy(precision)
        #: Optional :class:`~flinkml_tpu.sharding.plan.ShardingPlan` —
        #: plan-aware estimators (``_SHARDING_PLAN_AWARE = True``; the
        #: linear family's dense paths) shard parameters + optimizer
        #: state per the plan; every other estimator refuses the knob at
        #: construction, and the aware ones refuse it loudly on their
        #: plan-unaware branches (sparse features, streamed fits).
        self.sharding_plan = sharding_plan

    def _checkpoint_kwargs(self) -> dict:
        return dict(
            checkpoint_manager=self.checkpoint_manager,
            checkpoint_interval=self.checkpoint_interval,
            resume=self.resume,
        )

    def _reject_in_ram_checkpointing(self, detail: str = "") -> None:
        """In-RAM fits that cannot checkpoint raise loudly instead of
        silently dropping the knobs."""
        if self.checkpoint_manager is not None or self.resume:
            raise ValueError(
                "checkpointing is supported for streamed fits only "
                "(pass an iterable of batch Tables or a DataCache)"
                + (f"; {detail}" if detail else "")
            )
