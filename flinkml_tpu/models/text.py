"""Text feature family: Tokenizer, RegexTokenizer, HashingTF,
CountVectorizer, IDF.

Beyond the reference snapshot but standard members of the wider Flink ML
operator family, and the natural producers for this framework's sparse
training path: HashingTF / CountVectorizerModel emit ``SparseVector``
columns that ``sparse_features`` dispatches straight into the
nnz-bucketed ELL trainers (documents → bag-of-words → sparse LR without
ever densifying).

TPU stance: strings and hashing are host work (XLA has no string type);
what belongs on the device is the *training* over the resulting sparse
matrices, which is exactly where the column hand-off happens. Hashing
uses crc32 (deterministic across runs and processes — Python's builtin
``hash`` is salted), memoized per token.
"""

from __future__ import annotations

import re
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from flinkml_tpu.api import Estimator, Model, Transformer
from flinkml_tpu.common_params import HasInputCol, HasOutputCol
from flinkml_tpu.linalg import SparseVector
from flinkml_tpu.params import (
    BoolParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringParam,
)
from flinkml_tpu.table import Table


class _HasInOutCol(HasInputCol, HasOutputCol):
    pass


def _string_column(table: Table, col: str) -> np.ndarray:
    values = table.column(col)
    if values.ndim != 1:
        raise ValueError(f"Column {col!r} must be 1-D strings, got {values.shape}")
    return values


def _token_column(table: Table, col: str) -> np.ndarray:
    """A column of token sequences (object array of lists/arrays of str)."""
    values = table.column(col)
    if values.dtype != object:
        raise ValueError(
            f"Column {col!r} must be a token-list column (object dtype), "
            f"got {values.dtype} — run a Tokenizer first"
        )
    return values


def _object_column(values: List) -> np.ndarray:
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


class Tokenizer(_HasInOutCol, Transformer):
    """Lowercase + whitespace split (the simple tokenizer)."""

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        values = _string_column(table, self.get(self.INPUT_COL))
        tokens = _object_column([str(v).lower().split() for v in values])
        return (table.with_column(self.get(self.OUTPUT_COL), tokens),)


class RegexTokenizer(_HasInOutCol, Transformer):
    """Regex tokenization: ``gaps=True`` splits on the pattern,
    ``gaps=False`` extracts pattern matches as tokens; tokens shorter
    than ``minTokenLength`` are dropped."""

    PATTERN = StringParam("pattern", "The regex pattern.", r"\s+")
    GAPS = BoolParam(
        "gaps", "Whether the pattern matches gaps (split) or tokens (findall).",
        True,
    )
    MIN_TOKEN_LENGTH = IntParam(
        "minTokenLength", "Minimum token length to keep.", 1,
        ParamValidators.gt_eq(0),
    )
    TO_LOWERCASE = BoolParam(
        "toLowercase", "Lowercase before tokenizing.", True
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        values = _string_column(table, self.get(self.INPUT_COL))
        pattern = re.compile(self.get(self.PATTERN))
        gaps = self.get(self.GAPS)
        min_len = self.get(self.MIN_TOKEN_LENGTH)
        lower = self.get(self.TO_LOWERCASE)
        out = []
        for v in values:
            s = str(v).lower() if lower else str(v)
            toks = pattern.split(s) if gaps else pattern.findall(s)
            out.append([t for t in toks if len(t) >= min_len])
        return (
            table.with_column(self.get(self.OUTPUT_COL), _object_column(out)),
        )


class HashingTF(_HasInOutCol, Transformer):
    """Hashing-trick term frequencies: token list → SparseVector of
    ``numFeatures`` (crc32 bucket per distinct token, memoized)."""

    NUM_FEATURES = IntParam(
        "numFeatures", "Hash-space dimensionality.", 1 << 18,
        ParamValidators.gt(0),
    )
    BINARY = BoolParam(
        "binary", "Presence (1.0) instead of counts.", False
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        tokens_col = _token_column(table, self.get(self.INPUT_COL))
        n = self.get(self.NUM_FEATURES)
        binary = self.get(self.BINARY)
        # Memoized per call, NOT per instance: buckets depend on the
        # current numFeatures, and a param change between calls must not
        # reuse stale moduli.
        cache: Dict[str, int] = {}
        rows = []
        for tokens in tokens_col:
            counts: Dict[int, float] = {}
            for tok in tokens:
                tok = str(tok)
                b = cache.get(tok)
                if b is None:
                    b = zlib.crc32(tok.encode("utf-8")) % n
                    cache[tok] = b
                counts[b] = 1.0 if binary else counts.get(b, 0.0) + 1.0
            idx = np.fromiter(counts.keys(), dtype=np.int64, count=len(counts))
            val = np.fromiter(counts.values(), dtype=np.float64, count=len(counts))
            order = np.argsort(idx)
            rows.append(
                SparseVector._from_sorted(n, idx[order], val[order])
            )
        return (
            table.with_column(self.get(self.OUTPUT_COL), _object_column(rows)),
        )


class _CountVectorizerParams(_HasInOutCol):
    VOCABULARY_SIZE = IntParam(
        "vocabularySize", "Max vocabulary size (top terms by corpus count).",
        1 << 18, ParamValidators.gt(0),
    )
    MIN_DF = FloatParam(
        "minDF",
        "Minimum number (>=1) or fraction (<1) of documents a term must "
        "appear in.",
        1.0, ParamValidators.gt_eq(0.0),
    )
    MAX_DF = FloatParam(
        "maxDF",
        "Maximum number (>=1) or fraction (<1) of documents a term may "
        "appear in.",
        float(2**63), ParamValidators.gt_eq(0.0),
    )
    MIN_TF = FloatParam(
        "minTF",
        "Per-document filter at transform time: minimum count (>=1) or "
        "fraction of the document's tokens (<1).",
        1.0, ParamValidators.gt_eq(0.0),
    )
    BINARY = BoolParam("binary", "Presence (1.0) instead of counts.", False)


class CountVectorizer(_CountVectorizerParams, Estimator):
    """Fit a vocabulary from token lists, ordered by corpus term count
    descending (ties by term ascending — deterministic)."""

    def fit(self, *inputs: Table) -> "CountVectorizerModel":
        (table,) = inputs
        tokens_col = _token_column(table, self.get(self.INPUT_COL))
        n_docs = len(tokens_col)
        term_count: Dict[str, int] = {}
        doc_freq: Dict[str, int] = {}
        for tokens in tokens_col:
            seen = set()
            for tok in tokens:
                tok = str(tok)
                term_count[tok] = term_count.get(tok, 0) + 1
                if tok not in seen:
                    seen.add(tok)
                    doc_freq[tok] = doc_freq.get(tok, 0) + 1
        min_df = self.get(self.MIN_DF)
        max_df = self.get(self.MAX_DF)
        min_docs = min_df * n_docs if min_df < 1.0 else min_df
        max_docs = max_df * n_docs if max_df < 1.0 else max_df
        kept = [
            t for t, df in doc_freq.items() if min_docs <= df <= max_docs
        ]
        kept.sort(key=lambda t: (-term_count[t], t))
        vocab = kept[: self.get(self.VOCABULARY_SIZE)]
        model = CountVectorizerModel()
        model.copy_params_from(self)
        model._set_vocab(np.asarray(vocab, dtype=str))
        return model


class CountVectorizerModel(_CountVectorizerParams, Model):
    def __init__(self):
        super().__init__()
        self._vocab: Optional[np.ndarray] = None
        self._index: Dict[str, int] = {}

    def _set_vocab(self, vocab: np.ndarray) -> None:
        self._vocab = vocab
        self._index = {str(t): i for i, t in enumerate(vocab)}

    @property
    def vocabulary(self) -> np.ndarray:
        self._require()
        return self._vocab

    def set_model_data(self, *inputs: Table) -> "CountVectorizerModel":
        (table,) = inputs
        self._set_vocab(np.asarray(table.column("term"), dtype=str))
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"term": self._vocab})]

    def _require(self) -> None:
        if self._vocab is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        tokens_col = _token_column(table, self.get(self.INPUT_COL))
        size = len(self._vocab)
        binary = self.get(self.BINARY)
        min_tf = self.get(self.MIN_TF)
        rows = []
        for tokens in tokens_col:
            counts: Dict[int, float] = {}
            for tok in tokens:
                i = self._index.get(str(tok))
                if i is not None:
                    counts[i] = counts.get(i, 0.0) + 1.0
            threshold = min_tf * len(tokens) if min_tf < 1.0 else min_tf
            items = [(i, c) for i, c in counts.items() if c >= threshold]
            items.sort()
            idx = np.asarray([i for i, _ in items], dtype=np.int64)
            val = (
                np.ones(len(items), dtype=np.float64)
                if binary
                else np.asarray([c for _, c in items], dtype=np.float64)
            )
            rows.append(SparseVector._from_sorted(size, idx, val))
        return (
            table.with_column(self.get(self.OUTPUT_COL), _object_column(rows)),
        )

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"term": self._vocab})

    @classmethod
    def load(cls, path: str) -> "CountVectorizerModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._set_vocab(arrays["term"].astype(str))
        return model


class IDF(_HasInOutCol, Estimator):
    """Inverse document frequency: fit document-frequency counts over TF
    vectors (sparse or dense), ``idf = log((n_docs + 1) / (df + 1))``;
    terms with ``df < minDocFreq`` get idf 0."""

    MIN_DOC_FREQ = IntParam(
        "minDocFreq", "Terms in fewer documents get idf 0.", 0,
        ParamValidators.gt_eq(0),
    )

    def fit(self, *inputs: Table) -> "IDFModel":
        (table,) = inputs
        col = table.column(self.get(self.INPUT_COL))
        if col.dtype == object:
            sizes = {v.size() for v in col}
            if len(sizes) != 1:
                raise ValueError(
                    f"TF vectors disagree on dimensionality: {sorted(sizes)}"
                )
            (dim,) = sizes
            df = np.zeros(dim, dtype=np.float64)
            for v in col:
                if isinstance(v, SparseVector):
                    df[v.indices[v.values != 0]] += 1.0
                else:
                    df += v.to_array() != 0
            n_docs = len(col)
        else:
            x = np.asarray(col, dtype=np.float64)
            if x.ndim != 2:
                raise ValueError(
                    f"TF column must be [n, d] or SparseVectors, got {x.shape}"
                )
            df = (x != 0).sum(axis=0).astype(np.float64)
            n_docs = x.shape[0]
        idf = np.log((n_docs + 1.0) / (df + 1.0))
        idf[df < self.get(self.MIN_DOC_FREQ)] = 0.0
        model = IDFModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"idf": idf[None, :], "docFreq": df[None, :]}))
        return model


class IDFModel(_HasInOutCol, Model):
    MIN_DOC_FREQ = IDF.MIN_DOC_FREQ

    def __init__(self):
        super().__init__()
        self._idf: Optional[np.ndarray] = None
        self._doc_freq: Optional[np.ndarray] = None

    def set_model_data(self, *inputs: Table) -> "IDFModel":
        (table,) = inputs
        self._idf = np.asarray(table.column("idf"), np.float64)[0]
        self._doc_freq = np.asarray(table.column("docFreq"), np.float64)[0]
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({
            "idf": self._idf[None, :], "docFreq": self._doc_freq[None, :],
        })]

    @property
    def idf(self) -> np.ndarray:
        self._require()
        return self._idf

    def _require(self) -> None:
        if self._idf is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        col = table.column(self.get(self.INPUT_COL))
        if col.dtype == object:
            rows = []
            for v in col:
                if v.size() != self._idf.shape[0]:
                    raise ValueError(
                        f"TF vector has size {v.size()}, model has "
                        f"{self._idf.shape[0]}"
                    )
                if isinstance(v, SparseVector):
                    rows.append(SparseVector._from_sorted(
                        v.size(), v.indices, v.values * self._idf[v.indices]
                    ))
                else:
                    rows.append(type(v)(v.to_array() * self._idf))
            out_col = _object_column(rows)
        else:
            x = np.asarray(col, dtype=np.float64)
            if x.ndim != 2 or x.shape[1] != self._idf.shape[0]:
                raise ValueError(
                    f"TF column shape {x.shape} does not match idf dim "
                    f"{self._idf.shape[0]}"
                )
            out_col = x * self._idf
        return (table.with_column(self.get(self.OUTPUT_COL), out_col),)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(
            path, {"idf": self._idf, "docFreq": self._doc_freq}
        )

    @classmethod
    def load(cls, path: str) -> "IDFModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._idf = arrays["idf"]
        model._doc_freq = arrays["docFreq"]
        return model
