"""NaiveBayes — multinomial NB over categorical (indexed) features.

Capability parity with
``flink-ml-lib/.../classification/naivebayes/NaiveBayes.java:55-348`` and
``NaiveBayesModel.java``, rebuilt TPU-first:

  - The reference's 3-stage keyed mapPartition aggregation — (label,
    featureIdx, value) → per-key weight maps → per-label map arrays → model
    at parallelism 1 — becomes ONE distributed ``keyed_aggregate``: each
    (label, feature, category) triple is a flat segment id, counts come from
    a single segment-sum + psum, and the smoothed log-theta tensor is
    computed densely on host.
  - Smoothing matches ``GenerateModelFunction`` (NaiveBayes.java:278-347):
    ``theta[l][j][c] = log(count + smoothing) - log(docCount_l +
    smoothing * numCategories_j)`` over the categories seen under ANY
    label; ``pi[l] = log(docCount_l * F + smoothing) - log(totalDocs * F +
    L * smoothing)``.
  - Prediction (``NaiveBayesModel.java:174-183``): argmax over
    ``pi[l] + Σ_j theta[l][j][x_j]``, computed as a batched gather + sum;
    a value never seen in training raises (parity with the reference's
    NullPointerException on ``theta.get(value)`` — but with a real error
    message).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasSmoothing,
)
from flinkml_tpu.models._data import features_matrix, labeled_data
from flinkml_tpu.parallel import DeviceMesh, keyed_aggregate, pad_to_multiple
from flinkml_tpu.table import Table


class _NaiveBayesParams(HasFeaturesCol, HasLabelCol, HasPredictionCol, HasSmoothing):
    pass


class NaiveBayes(_NaiveBayesParams, Estimator):
    def __init__(self, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self.mesh = mesh

    def fit(self, *inputs: Table) -> "NaiveBayesModel":
        (table,) = inputs
        x, y, _ = labeled_data(
            table,
            self.get(_NaiveBayesParams.FEATURES_COL),
            self.get(_NaiveBayesParams.LABEL_COL),
        )
        if not np.all(y == np.round(y)):
            raise ValueError("Label value should be indexed number.")
        smoothing = self.get(_NaiveBayesParams.SMOOTHING)
        n, num_features = x.shape

        # Host-side vocabularies: distinct labels; distinct categories per
        # feature (over all labels, as the reference's categoryNumbers set).
        labels, label_idx = np.unique(y, return_inverse=True)
        num_labels = len(labels)
        cat_values: List[np.ndarray] = []
        cat_idx = np.empty_like(x, dtype=np.int64)
        for j in range(num_features):
            vals, idx = np.unique(x[:, j], return_inverse=True)
            cat_values.append(vals)
            cat_idx[:, j] = idx
        max_cats = max(len(v) for v in cat_values)

        # Distributed count aggregation: flat segment id per
        # (label, feature, category) occurrence.
        mesh = self.mesh or DeviceMesh()
        num_segments = num_labels * num_features * max_cats
        flat = (
            label_idx[:, None] * (num_features * max_cats)
            + np.arange(num_features)[None, :] * max_cats
            + cat_idx
        ).reshape(-1)
        ones = np.ones(flat.shape[0], dtype=np.float64)
        flat_pad, n_valid = pad_to_multiple(flat, mesh.axis_size())
        ones_pad, _ = pad_to_multiple(ones, mesh.axis_size())  # pads with 0
        counts = np.asarray(
            keyed_aggregate(mesh, ones_pad, flat_pad, num_segments)
        ).reshape(num_labels, num_features, max_cats)

        doc_count = np.bincount(label_idx, minlength=num_labels).astype(np.float64)
        num_cats = np.array([len(v) for v in cat_values], dtype=np.float64)

        # Smoothed log-likelihoods (NaiveBayes.java:322-339).
        theta_log = np.log(doc_count[:, None] + smoothing * num_cats[None, :])
        theta = np.log(counts + smoothing) - theta_log[:, :, None]
        # Mask out padding categories (beyond each feature's vocab).
        for j in range(num_features):
            theta[:, j, len(cat_values[j]) :] = -np.inf

        total = doc_count.sum() * num_features
        pi = np.log(doc_count * num_features + smoothing) - np.log(
            total + num_labels * smoothing
        )

        model = NaiveBayesModel()
        model.copy_params_from(self)
        model._set_fitted(theta, pi, labels, cat_values)
        return model


class NaiveBayesModel(_NaiveBayesParams, Model):
    def __init__(self):
        super().__init__()
        self._theta: Optional[np.ndarray] = None  # [L, F, C] log-likelihood
        self._pi: Optional[np.ndarray] = None  # [L] log prior
        self._labels: Optional[np.ndarray] = None  # [L] label values
        self._cat_values: Optional[List[np.ndarray]] = None  # per-feature vocab

    def _set_fitted(self, theta, pi, labels, cat_values) -> "NaiveBayesModel":
        self._theta, self._pi, self._labels = theta, pi, labels
        self._cat_values = list(cat_values)
        return self

    # -- model data --------------------------------------------------------
    def set_model_data(self, *inputs: Table) -> "NaiveBayesModel":
        (table,) = inputs
        theta = np.asarray(table.column("theta"), dtype=np.float64)[0]
        pi = np.asarray(table.column("piArray"), dtype=np.float64)[0]
        labels = np.asarray(table.column("labels"), dtype=np.float64)[0]
        cats = table.column("categoryValues")[0]
        self._set_fitted(theta, pi, labels, [np.asarray(c) for c in cats])
        return self

    def get_model_data(self) -> List[Table]:
        self._require_model()
        cats = np.empty(1, dtype=object)
        cats[0] = [np.asarray(c) for c in self._cat_values]
        return [
            Table(
                {
                    "theta": self._theta[None],
                    "piArray": self._pi[None],
                    "labels": self._labels[None],
                    "categoryValues": cats,
                }
            )
        ]

    def _require_model(self) -> None:
        if self._theta is None:
            raise ValueError("Model data is not set; call set_model_data or fit first")

    # -- inference ---------------------------------------------------------
    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        x = features_matrix(table, self.get(_NaiveBayesParams.FEATURES_COL))
        n, num_features = x.shape
        if num_features != self._theta.shape[1]:
            raise ValueError(
                f"input has {num_features} features, model was fit on "
                f"{self._theta.shape[1]}"
            )
        # Map raw values to category ids; unseen values raise (parity with
        # the reference's NPE on theta.get, but with a real message).
        idx = np.empty((n, num_features), dtype=np.int64)
        for j in range(num_features):
            vocab = self._cat_values[j]
            pos = np.searchsorted(vocab, x[:, j])
            pos_clipped = np.clip(pos, 0, len(vocab) - 1)
            bad = vocab[pos_clipped] != x[:, j]
            if bad.any():
                raise ValueError(
                    f"feature {j} contains values never seen in training: "
                    f"{np.unique(x[bad.nonzero()[0], j])[:5]}"
                )
            idx[:, j] = pos_clipped

        # probs[n, L] = pi[l] + sum_j theta[l, j, idx[n, j]]
        theta = jnp.asarray(self._theta)  # [L, F, C]
        gathered = jnp.take_along_axis(
            theta[None, :, :, :],
            jnp.asarray(idx)[:, None, :, None],
            axis=3,
        )[..., 0]
        probs = jnp.asarray(self._pi)[None, :] + jnp.sum(gathered, axis=2)
        pred_idx = np.asarray(jnp.argmax(probs, axis=1))
        pred = self._labels[pred_idx]
        return (table.with_column(self.get(_NaiveBayesParams.PREDICTION_COL), pred),)

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        self._require_model()
        arrays = {
            "theta": self._theta,
            "piArray": self._pi,
            "labels": self._labels,
        }
        for j, v in enumerate(self._cat_values):
            arrays[f"catValues_{j}"] = v
        self._save_with_arrays(
            path, arrays, extra={"numFeatures": int(self._theta.shape[1])}
        )

    @classmethod
    def load(cls, path: str) -> "NaiveBayesModel":
        model, arrays, meta = cls._load_with_arrays(path)
        cats = [arrays[f"catValues_{j}"] for j in range(int(meta["numFeatures"]))]
        model._set_fitted(arrays["theta"], arrays["piArray"], arrays["labels"], cats)
        return model
