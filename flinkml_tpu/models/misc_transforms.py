"""Misc transformers: FeatureHasher, Interaction, DCT,
StopWordsRemover, RandomSplitter.

Members of the wider Flink ML operator family (the reference snapshot
has none of these). All host-side row transforms (see the TPU stance in
``feature_transforms.py``); DCT runs through scipy's C FFT path.
"""

from __future__ import annotations

import zlib
from functools import lru_cache as _lru_cache
from typing import Dict, List, Tuple

import numpy as np

from flinkml_tpu.api import AlgoOperator, Transformer
from flinkml_tpu.common_params import (
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    HasOutputCols,
    HasSeed,
)
from flinkml_tpu.linalg import SparseVector
from flinkml_tpu.models.text import _object_column, _token_column
from flinkml_tpu.params import (
    BoolParam,
    FloatArrayParam,
    IntParam,
    ParamValidators,
    StringArrayParam,
)
from flinkml_tpu.table import Table

# The classic English stop-word list (Snowball).
ENGLISH_STOP_WORDS = (
    "i me my myself we our ours ourselves you your yours yourself "
    "yourselves he him his himself she her hers herself it its itself "
    "they them their theirs themselves what which who whom this that "
    "these those am is are was were be been being have has had having "
    "do does did doing a an the and but if or because as until while "
    "of at by for with about against between into through during "
    "before after above below to from up down in out on off over under "
    "again further then once here there when where why how all any "
    "both each few more most other some such no nor not only own same "
    "so than too very s t can will just don should now"
).split()


class FeatureHasher(HasInputCols, HasOutputCol, Transformer):
    """Hash a mixed set of columns into one SparseVector feature space:
    numeric scalar columns contribute their value at the bucket of the
    column name; string/categorical columns contribute 1.0 at the bucket
    of ``"col=value"`` (the hashing-trick analog of one-hot). Collisions
    add (crc32, deterministic)."""

    NUM_FEATURES = IntParam(
        "numFeatures", "Hash-space dimensionality.", 1 << 18,
        ParamValidators.gt(0),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        if not input_cols:
            raise ValueError("inputCols must be set")
        n_feat = self.get(self.NUM_FEATURES)
        n_rows = table.num_rows

        def bucket(key: str) -> int:
            return zlib.crc32(key.encode("utf-8")) % n_feat

        # Numeric columns hash once per column; categorical per value.
        contribs: List[Tuple[np.ndarray, np.ndarray]] = []  # (bucket[n], value[n])
        for col in input_cols:
            values = table.column(col)
            if values.ndim != 1:
                raise ValueError(
                    f"FeatureHasher needs scalar columns; {col!r} has shape "
                    f"{values.shape} (use VectorAssembler for vectors)"
                )
            if values.dtype.kind in "fiub":
                b = bucket(col)
                contribs.append((
                    np.full(n_rows, b, dtype=np.int64),
                    np.asarray(values, dtype=np.float64),
                ))
            else:
                uniq, inv = np.unique(values.astype(str), return_inverse=True)
                buckets = np.asarray(
                    [bucket(f"{col}={v}") for v in uniq], dtype=np.int64
                )
                contribs.append((buckets[inv], np.ones(n_rows)))
        all_buckets = np.stack([c[0] for c in contribs], axis=1)  # [n, cols]
        all_values = np.stack([c[1] for c in contribs], axis=1)
        rows = []
        for i in range(n_rows):
            b, v = all_buckets[i], all_values[i]
            order = np.argsort(b, kind="stable")
            b, v = b[order], v[order]
            # Merge duplicate buckets (collisions add).
            uniq_b, start = np.unique(b, return_index=True)
            sums = np.add.reduceat(v, start)
            rows.append(SparseVector._from_sorted(n_feat, uniq_b, sums))
        return (
            table.with_column(self.get(self.OUTPUT_COL), _object_column(rows)),
        )


class Interaction(HasInputCols, HasOutputCol, Transformer):
    """Row-wise interaction: the flattened outer product of the input
    columns (scalars treated as 1-vectors) — dim = Π dims."""

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        if not input_cols or len(input_cols) < 2:
            raise ValueError("Interaction needs at least 2 inputCols")
        mats = []
        for col in input_cols:
            v = np.asarray(table.column(col), dtype=np.float64)
            mats.append(v[:, None] if v.ndim == 1 else v)
        out = mats[0]
        for m in mats[1:]:
            out = (out[:, :, None] * m[:, None, :]).reshape(out.shape[0], -1)
        return (table.with_column(self.get(self.OUTPUT_COL), out),)


class DCT(HasInputCol, HasOutputCol, Transformer):
    """Orthonormal DCT-II per row (``inverse=True`` applies DCT-III).

    Computed as one [n, d] @ [d, d] cosine-matrix matmul — no scipy
    dependency (the package's runtime deps are jax + numpy only), and
    the matmul form is what a device placement would want anyway.
    """

    INVERSE = BoolParam("inverse", "Apply the inverse DCT.", False)

    @staticmethod
    @_lru_cache(maxsize=16)
    def _basis(d: int) -> np.ndarray:
        """Orthonormal DCT-II matrix C: C[k, m] = s_k cos(π(m+½)k/d)."""
        k = np.arange(d)[:, None]
        m = np.arange(d)[None, :]
        c = np.cos(np.pi * (m + 0.5) * k / d)
        c[0] *= np.sqrt(1.0 / d)
        c[1:] *= np.sqrt(2.0 / d)
        return c

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        x = np.asarray(table.column(self.get(self.INPUT_COL)), dtype=np.float64)
        if x.ndim != 2:
            raise ValueError(f"DCT input must be [n, d], got {x.shape}")
        c = self._basis(x.shape[1])
        # DCT-II: y = x Cᵀ; DCT-III (the inverse, C orthonormal): x = y C.
        out = x @ c if self.get(self.INVERSE) else x @ c.T
        return (table.with_column(self.get(self.OUTPUT_COL), out),)


class StopWordsRemover(HasInputCols, HasOutputCols, Transformer):
    """Drop stop words from token-list columns (default: the English
    Snowball list; case-insensitive unless ``caseSensitive``)."""

    STOP_WORDS = StringArrayParam(
        "stopWords", "The words to filter out.", list(ENGLISH_STOP_WORDS),
    )
    CASE_SENSITIVE = BoolParam(
        "caseSensitive", "Case-sensitive filtering.", False
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        input_cols = self.get(self.INPUT_COLS)
        output_cols = self.get(self.OUTPUT_COLS)
        if not input_cols or not output_cols:
            raise ValueError("inputCols and outputCols must be set")
        if len(input_cols) != len(output_cols):
            raise ValueError(
                f"{len(input_cols)} input columns vs {len(output_cols)} output columns"
            )
        case = self.get(self.CASE_SENSITIVE)
        stop = set(self.get(self.STOP_WORDS))
        if not case:
            stop = {w.lower() for w in stop}
        out = table
        for col, out_col in zip(input_cols, output_cols):
            tokens_col = _token_column(table, col)
            filtered = [
                [t for t in toks
                 if (t if case else str(t).lower()) not in stop]
                for toks in tokens_col
            ]
            out = out.with_column(out_col, _object_column(filtered))
        return (out,)


class RandomSplitter(HasSeed, AlgoOperator):
    """Split one table into N disjoint tables by row, with probabilities
    proportional to ``weights`` (the upstream train/test splitter)."""

    WEIGHTS = FloatArrayParam(
        "weights", "Relative sizes of the output splits.", [0.8, 0.2],
        ParamValidators.non_empty_array(),
    )

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        weights = np.asarray(self.get(self.WEIGHTS), dtype=np.float64)
        if (weights <= 0).any():
            raise ValueError("weights must be positive")
        probs = weights / weights.sum()
        rng = np.random.default_rng(self.get_seed())
        assignment = rng.choice(len(probs), size=table.num_rows, p=probs)
        return tuple(
            table.take(np.nonzero(assignment == s)[0])
            for s in range(len(probs))
        )
