"""NGram — token lists → space-joined n-grams (the upstream operator).

Rows with fewer than ``n`` tokens produce an empty list (upstream
convention).
"""

from __future__ import annotations

from typing import Tuple

from flinkml_tpu.api import Transformer
from flinkml_tpu.common_params import HasInputCol, HasOutputCol
from flinkml_tpu.models.text import _object_column, _token_column
from flinkml_tpu.params import IntParam, ParamValidators
from flinkml_tpu.table import Table


class NGram(HasInputCol, HasOutputCol, Transformer):
    N = IntParam("n", "Number of tokens per n-gram.", 2, ParamValidators.gt(0))

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        tokens_col = _token_column(table, self.get(self.INPUT_COL))
        n = self.get(self.N)
        out = [
            [" ".join(map(str, toks[i: i + n]))
             for i in range(len(toks) - n + 1)]
            for toks in tokens_col
        ]
        return (
            table.with_column(self.get(self.OUTPUT_COL), _object_column(out)),
        )
