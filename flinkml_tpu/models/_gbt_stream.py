"""Out-of-core histogram GBT: level-wise boosting over a replayed cache.

The in-RAM builder (``gbt._forest_builder``) holds the whole binned
dataset in HBM and builds the forest in one device program. This module
is the bounded-residency variant (round-3: VERDICT "generalize streamed
out-of-core fit beyond linear models"): the dataset lives in a
:class:`~flinkml_tpu.iteration.datacache.DataCache` (host RAM + disk
segments) and only one batch (plus prefetch depth) is device-resident at
a time.

Reference parity: every bounded iteration in the reference trains from
cached partitions with bounded memory (``ReplayOperator.java:62-250``
disk-backed epoch replay; ``LogisticRegression.java:410-452``
ListState-cached train data). Here each *tree level* is an "epoch": one
replay pass accumulates all (node, feature, bin) gradient/hessian
histograms batch-by-batch (``psum``-combined on device, identical split
decisions everywhere), the host picks every node's best split from the
small [n_leaves, d, bins] tensor, and the next pass advances each row's
node id. Per-row state (prediction margin, node id, subsample mask) is
host-resident — O(13 bytes/row), two orders below the binned features
the cache holds — so "larger than HBM" holds for the dominant term.

Streamed-mode scope: boosting only (random forests need per-tree feature
subsets whose bagged trees are independent — use the in-RAM path), no
``validationFraction`` early stopping (a holdout split needs a second
materialized stream). Bin edges come from a seeded
:class:`~flinkml_tpu.utils.sampling.RowReservoir` uniform row sample
(default 64k rows) — the standard streaming-quantile approximation; with
``reservoir_capacity >= n`` the edges are exact and the streamed forest
matches the in-RAM forest's splits.
"""

from __future__ import annotations

import functools
import os
import shutil
import tempfile
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.parallel import DeviceMesh

_LAM_FLOOR = 1e-12


@functools.lru_cache(maxsize=16)
def _stream_fns(mesh, axis: str, n_feat: int, n_bins: int, n_leaves: int,
                logistic: bool):
    """Per-batch device programs for one (mesh, forest-shape) config.

    All row inputs arrive sharded over ``axis``; histogram/leaf outputs
    are psum'd to replicated. g/h are recomputed from the margin on the
    fly (cheaper than materializing two more per-row host arrays)."""
    seg = n_leaves * n_feat * n_bins

    def grad_hess(pred, y, w_eff):
        if logistic:
            p = jax.nn.sigmoid(pred)
            return (p - y) * w_eff, jnp.maximum(p * (1 - p), 1e-6) * w_eff
        return (pred - y) * w_eff, w_eff

    def _advance(binned, node, feat_l, bin_l):
        sample_bin = jnp.take_along_axis(
            binned.astype(jnp.int32), feat_l[node][:, None], axis=1
        )[:, 0]
        return node * 2 + (sample_bin > bin_l[node]).astype(jnp.int32)

    def _hists(binned, g, h, node):
        feat_ids = jnp.arange(n_feat, dtype=jnp.int32)[None, :]
        ids = ((node[:, None] * n_feat + feat_ids) * n_bins
               + binned.astype(jnp.int32)).reshape(-1)
        hg = jax.lax.psum(jax.ops.segment_sum(
            jnp.repeat(g, n_feat), ids, num_segments=seg), axis)
        hh = jax.lax.psum(jax.ops.segment_sum(
            jnp.repeat(h, n_feat), ids, num_segments=seg), axis)
        return hg, hh

    def hist_local(binned, y, w_eff, pred, node):
        g, h = grad_hess(pred, y, w_eff)
        return _hists(binned, g, h, node)

    def hist_adv_local(binned, y, w_eff, pred, node, feat_p, bin_p):
        # Fused: advance nodes through the PREVIOUS level's split, then
        # histogram the new level — one cache replay per level instead of
        # two (the advance-only pass re-read the whole spilled dataset).
        node = _advance(binned, node, feat_p, bin_p)
        g, h = grad_hess(pred, y, w_eff)
        hg, hh = _hists(binned, g, h, node)
        return hg, hh, node

    def leaf_adv_local(binned, y, w_eff, pred, node, feat_p, bin_p):
        node = _advance(binned, node, feat_p, bin_p)
        g, h = grad_hess(pred, y, w_eff)
        lg = jax.lax.psum(jax.ops.segment_sum(
            g, node, num_segments=n_leaves), axis)
        lh = jax.lax.psum(jax.ops.segment_sum(
            h, node, num_segments=n_leaves), axis)
        return lg, lh, node

    sm = functools.partial(jax.shard_map, mesh=mesh)
    a, r = P(axis), P()
    return (
        jax.jit(sm(hist_local, in_specs=(a, a, a, a, a), out_specs=(r, r))),
        jax.jit(sm(hist_adv_local, in_specs=(a, a, a, a, a, r, r),
                   out_specs=(r, r, a))),
        jax.jit(sm(leaf_adv_local, in_specs=(a, a, a, a, a, r, r),
                   out_specs=(r, r, a))),
    )


def _best_level_splits(hg, hh, lam, n_leaves, n_feat, n_bins):
    """Host mirror of the in-RAM builder's split selection
    (``gbt._forest_builder`` level body): cumulative histograms, XGBoost
    gain, empty-side/last-bin guards, per-node argmax."""
    hg = np.asarray(hg, np.float64).reshape(n_leaves, n_feat, n_bins)
    hh = np.asarray(hh, np.float64).reshape(n_leaves, n_feat, n_bins)
    gl = np.cumsum(hg, axis=2)
    hl = np.cumsum(hh, axis=2)
    gt, ht = gl[:, :, -1:], hl[:, :, -1:]
    gr, hr = gt - gl, ht - hl
    with np.errstate(divide="ignore", invalid="ignore"):
        gain = (
            gl * gl / (hl + lam) + gr * gr / (hr + lam)
            - gt * gt / (ht + lam)
        )
    gain = np.where((hl > 0) & (hr > 0), gain, 0.0)
    gain[:, :, -1] = 0.0
    flat = gain.reshape(n_leaves, n_feat * n_bins)
    best = np.argmax(flat, axis=1)
    best_gain = np.maximum(flat[np.arange(n_leaves), best], 0.0)
    return (
        (best // n_bins).astype(np.int32),
        (best % n_bins).astype(np.int32),
        best_gain.astype(np.float32),
    )


def train_gbt_stream(
    cache,
    *,
    mesh: DeviceMesh,
    logistic: bool,
    num_trees: int,
    depth: int,
    max_bins: int,
    learning_rate: float,
    reg_lambda: float,
    subsample: float,
    seed: int,
    columns: Tuple[str, str, Optional[str]] = ("x", "y", "w"),
    reservoir_capacity: int = 65_536,
    prefetch_depth: int = 2,
    label_check: Optional[Callable[[np.ndarray], None]] = None,
    checkpoint_manager=None,
    checkpoint_interval: int = 0,
    resume: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, float]:
    """Build a boosted forest from a sealed raw-feature ``DataCache``.

    Returns ``(feats[T, n_inner], bins[T, n_inner], gains[T, n_inner],
    leaves[T, n_leaves], base, edges[d, max_bins-1])`` — see the module
    docstring for the pass structure.

    Fault tolerance (``Checkpoints.java:43-211``; the reference checkpoints
    every bounded iteration's cached state): ``checkpoint_manager`` +
    ``checkpoint_interval`` snapshot the between-tree state — per-row
    margins, the partial forest, trees-built — every N trees (the unit of
    recovery: a crash replays at most the in-flight tree's
    ``depth + 1`` cache passes). ``resume=True`` restores the latest
    snapshot and continues bit-exactly: passes A/B (edges + binned cache)
    re-run deterministically from the same seed/cache, and the subsample
    RNG is fast-forwarded one draw per completed tree.
    """
    from flinkml_tpu.models.gbt import bin_features, quantile_bin_edges
    from flinkml_tpu.utils.sampling import RowReservoir

    # Multi-process (round 4): each process holds its OWN partition of
    # the dataset as its local cache; per-row state (margins, node ids,
    # subsample masks) stays on the rank that owns the rows, histograms
    # psum globally, split decisions replicate. Agreements (bin edges
    # from a pooled reservoir, base score from gathered sums, replay
    # schedule) come from iteration/stream_sync.py; checkpoints are
    # rank-scoped (per-row state) with an agreed commit.
    multi = jax.process_count() > 1

    x_key, y_key, w_key = columns
    rng = np.random.default_rng(seed)

    # -- pass A: reservoir for bin edges + base-score sums -----------------
    from flinkml_tpu.iteration.stream_sync import DeferredValidation

    dv = DeferredValidation()
    reservoir = RowReservoir(reservoir_capacity, seed=seed)
    wy_sum = w_sum = wneg_sum = 0.0
    n_feat = None

    def check_batch(x, y):
        nonlocal n_feat
        if x.ndim != 2:
            raise ValueError(f"stream batches must be [n, d], got {x.shape}")
        if x.shape[0] == 0:
            raise ValueError("stream batch has zero rows; drop empty batches")
        if n_feat is None:
            n_feat = x.shape[1]
        elif x.shape[1] != n_feat:
            raise ValueError(
                f"batch feature dim {x.shape[1]} != first batch's {n_feat}"
            )
        if label_check is not None:
            # Folded into this pass so a sealed out-of-core cache is not
            # read a whole extra time just for validation.
            label_check(y)

    def ingest(batch):
        # Extraction is part of the checked step: a missing column or a
        # ragged value raises HERE, not in the accumulation below.
        x = np.asarray(batch[x_key], np.float32)
        y = np.asarray(batch[y_key], np.float32)
        w = (
            np.asarray(batch[w_key], np.float32)
            if w_key is not None and w_key in batch
            else np.ones(x.shape[0], np.float32)
        )
        check_batch(x, y)
        return x, y, w

    from flinkml_tpu.iteration.stream_sync import checked_ingest

    # Multi-process, iterator and ingest failures are held for the
    # rendezvous below (a rank-local raise would strand the peers in the
    # first agreement collective), and held failures skip the
    # accumulation — adding a ragged batch to the fixed-width reservoir
    # would itself raise rank-locally.
    for x, y, w in checked_ingest(cache.reader(), dv, ingest, multi):
        reservoir.add(x)
        wy_sum += float(np.sum(w * y))
        w_sum += float(np.sum(w))
        wneg_sum += float(np.sum(w * (1 - y)))

    if multi:
        from flinkml_tpu.iteration.stream_sync import (
            agree_feature_dim,
            gather_vectors,
            pooled_sample,
        )

        dv.rendezvous(mesh, "stream ingest validation")
        dim = agree_feature_dim(
            cache, x_key, mesh, local_dim=0 if n_feat is None else n_feat
        )
        if dim == 0:
            raise ValueError("training stream is empty on every process")
        n_feat = dim
        sums = gather_vectors(
            np.asarray([wy_sum, w_sum, wneg_sum, float(cache.num_rows)]),
            mesh,
        ).sum(axis=0)
        wy_sum, w_sum, wneg_sum = sums[0], sums[1], sums[2]
        sample = reservoir.sample()
        if sample.size == 0:
            sample = np.zeros((0, dim), np.float32)
        sample = pooled_sample(
            sample.astype(np.float32), cache.num_rows,
            reservoir_capacity, seed, mesh,
        )
    else:
        if n_feat is None or cache.num_rows == 0:
            raise ValueError("training stream is empty")
        sample = reservoir.sample()
    n = cache.num_rows  # LOCAL rows: per-row state is rank-resident
    if logistic:
        base = float(np.log(max(wy_sum, 1e-12) / max(wneg_sum, 1e-12)))
    else:
        base = float(wy_sum / w_sum)
    edges = quantile_bin_edges(sample, max_bins)

    # -- pass B: binned cache (uint8 bins: max_bins <= 256) ----------------
    # Re-binning per replay would cost d searchsorteds per batch per level;
    # binning once into a second cache trades one extra dataset copy
    # (1 byte/feature) for O(T * depth) replay passes at memcpy speed. A
    # raw cache that spills gets a PRIVATE temp spill dir for the binned
    # copy (unique per fit; deleted after the build — concurrent fits must
    # never share segment files), removed in the ``finally`` below.
    from flinkml_tpu.iteration.datacache import DataCacheWriter

    spill_dir = None
    budget = None
    if cache.segments:
        # Spill NEXT TO the raw cache's segments: the user chose that
        # filesystem because the dataset fits there — a default-TMPDIR
        # copy could fill a small tmpfs with a dataset-sized file set.
        spill_dir = tempfile.mkdtemp(
            prefix="flinkml-gbt-binned-",
            dir=os.path.dirname(cache.segments[0].path),
        )
        budget = 0  # raw cache already spills: keep the binned copy on disk
    try:
        writer = DataCacheWriter(spill_dir, budget)
        ranges = []  # (start_row, rows) aligned with binned-cache batch order
        r0 = 0
        for batch in cache.reader():
            x = np.asarray(batch[x_key], np.float32)
            y = np.asarray(batch[y_key], np.float32)
            w = (
                np.asarray(batch[w_key], np.float32)
                if w_key is not None and w_key in batch
                else np.ones(x.shape[0], np.float32)
            )
            writer.append({
                "b": bin_features(x, edges).astype(np.uint8),
                "y": y, "w": w,
            })
            ranges.append((r0, x.shape[0]))
            r0 += x.shape[0]
        binned_cache = writer.finish()
        return _build_forest(
            binned_cache, ranges, mesh=mesh, logistic=logistic,
            num_trees=num_trees, depth=depth, max_bins=max_bins, n_feat=n_feat,
            n=n, base=base, edges=edges, learning_rate=learning_rate,
            reg_lambda=reg_lambda, subsample=subsample, rng=rng,
            prefetch_depth=prefetch_depth,
            checkpoint_manager=checkpoint_manager,
            checkpoint_interval=checkpoint_interval, resume=resume,
        )
    finally:
        if spill_dir is not None:
            shutil.rmtree(spill_dir, ignore_errors=True)

def _build_forest(
    binned_cache, ranges, *, mesh, logistic, num_trees, depth, max_bins,
    n_feat, n, base, edges, learning_rate, reg_lambda, subsample, rng,
    prefetch_depth, checkpoint_manager=None, checkpoint_interval=0,
    resume=False,
):
    """The level-wise replay build over a sealed binned cache (see module
    docstring); split out of :func:`train_gbt_stream` so the binned spill
    directory's lifetime wraps it exactly."""
    from flinkml_tpu.iteration.datacache import PrefetchingDeviceFeed
    from flinkml_tpu.parallel import pad_to_multiple

    n_leaves = 1 << depth
    n_inner = n_leaves - 1
    p_size = mesh.axis_size()
    row_tile = p_size * 8
    axis = DeviceMesh.DATA_AXIS
    multi = jax.process_count() > 1
    hist_fn, hist_adv_fn, leaf_adv_fn = _stream_fns(
        mesh.mesh, axis, n_feat, max_bins, n_leaves, logistic
    )

    # Host-resident per-row state: margin, node id, subsample mask —
    # rank-local (each rank owns its partition's rows).
    pred = np.full(n, base, np.float32)
    node = np.zeros(n, np.int32)
    mask = np.ones(n, np.float32)

    plan = None
    if multi:
        from flinkml_tpu.iteration.stream_sync import (
            SyncedReplayPlan,
            pad_rows_to,
        )

        plan = SyncedReplayPlan.create(binned_cache, mesh, row_tile)
        height = plan.local_height

        def shard_padded(arr):
            """Fixed agreed height + global placement: every rank
            contributes exactly ``height`` rows per step (zero-weight
            padding / dummies are exact no-ops downstream)."""
            return mesh.global_batch(pad_rows_to(arr, height))

    else:

        def shard_padded(arr):
            """Zero-pad rows to the mesh row tile and shard (padded rows
            carry w=0 downstream, so they are exact no-ops)."""
            return mesh.shard_batch(pad_to_multiple(arr, row_tile)[0])

    def place(item):
        if item is None:  # dummy step on a drained rank (multi only)
            zb = np.zeros((plan.local_height, n_feat), np.uint8)
            zf = np.zeros(plan.local_height, np.float32)
            return (
                0, 0,
                mesh.global_batch(zb),
                mesh.global_batch(zf),
                mesh.global_batch(zf),
            )
        start, rows, batch = item
        return (
            start, rows,
            shard_padded(batch["b"]),
            shard_padded(batch["y"]),
            shard_padded(batch["w"]),
        )

    def feed():
        src = (
            (ranges[i][0], ranges[i][1], b)
            for i, b in enumerate(binned_cache.reader())
        )
        if multi:
            src = plan.epoch_batches(src, lambda: None)
        return PrefetchingDeviceFeed(src, place=place, depth=prefetch_depth)

    def shard_state(arr, start, rows):
        return shard_padded(arr[start:start + rows])

    feats_out = np.zeros((num_trees, n_inner), np.int32)
    bins_out = np.zeros((num_trees, n_inner), np.int32)
    gains_out = np.zeros((num_trees, n_inner), np.float32)
    leaves_out = np.zeros((num_trees, n_leaves), np.float32)

    # -- checkpoint/resume: unit of recovery = one completed tree ----------
    from flinkml_tpu.iteration.checkpoint import (
        begin_resume,
        rank_scoped,
        should_snapshot,
    )

    if multi and checkpoint_manager is not None:
        # Per-row state (pred/node) is rank-local, so every rank saves
        # its own shard under <dir>/rank-<i> (no shared-dir collisions).
        checkpoint_manager = rank_scoped(checkpoint_manager)
    resume_tree = begin_resume(checkpoint_manager, resume, mesh.mesh.size)
    if multi and resume:
        from flinkml_tpu.iteration.stream_sync import agree_max

        # All ranks must resume from the SAME tree, and it must be one
        # EVERY rank still holds on disk: a crash between one rank's save
        # of tree t+1 (whose pruning may drop its tree t) and the agreed
        # commit on the others can leave ranks one tree apart, so "min of
        # latest" alone could pick an epoch the ahead rank already
        # pruned. Walk down instead — agree the min over ranks of each
        # rank's newest epoch <= cand until every rank holds cand (the
        # newest COMMON epoch); if the intersection is empty, all ranks
        # restart from scratch together. Every rank executes the same
        # agreed iterates, so the collective count stays aligned.
        local = set(checkpoint_manager.all_epochs())

        def newest_at_most(c):
            return max((e for e in local if e <= c), default=-1)

        cand = -agree_max(-newest_at_most(1 << 30), mesh)
        while cand >= 0:
            nxt = -agree_max(-newest_at_most(cand), mesh)
            if nxt == cand:
                break
            cand = nxt
        resume_tree = None if cand < 0 else cand
    start_tree = 0
    if resume_tree is not None:
        from flinkml_tpu.iteration.stream_sync import agreed_restore

        like = (pred, feats_out, bins_out, gains_out, leaves_out)
        # The per-rank restore can still fail rank-locally (corrupt or
        # missing shard) — the agreed restore aborts every rank together
        # instead of stranding the peers in the training collectives.
        state, start_tree = agreed_restore(
            checkpoint_manager, resume_tree, like, mesh,
            f"checkpoint restore (tree {resume_tree})",
        )
        # np.array: these are mutated in place below; the restore must
        # own its buffers.
        pred, feats_out, bins_out, gains_out, leaves_out = (
            np.array(a) for a in state
        )
        if subsample < 1.0:
            # Fast-forward the subsample RNG one draw per completed tree
            # so resumed trees see exactly the masks the uninterrupted
            # run would have drawn (no generator-state serialization).
            for _ in range(start_tree):
                rng.random(n)

    from flinkml_tpu.parallel.dispatch import DispatchGuard

    guard = DispatchGuard()  # multi-process backpressure (no-op single)
    lam = np.float64(reg_lambda)
    for t in range(start_tree, num_trees):
        if subsample < 1.0:
            mask = (rng.random(n) < subsample).astype(np.float32)
        node[:] = 0
        prev_split = None  # (feat_dev, bin_dev) of the level just decided
        for level in range(depth):
            hg_acc = hh_acc = None
            f = feed()
            try:
                for start, rows, bb, yb, wb in f:
                    weff = shard_state(mask, start, rows)
                    args = (
                        bb, yb, wb * weff,
                        shard_state(pred, start, rows),
                        shard_state(node, start, rows),
                    )
                    if prev_split is None:
                        hg, hh = hist_fn(*args)
                    else:
                        # Fused advance-then-histogram: one replay per
                        # level (the separate advance pass would re-read
                        # the whole spilled dataset).
                        hg, hh, new_node = hist_adv_fn(*args, *prev_split)
                        node[start:start + rows] = mesh.local_rows(new_node)[:rows]
                    hg_acc = hg if hg_acc is None else hg_acc + hg
                    hh_acc = hh if hh_acc is None else hh_acc + hh
                    guard.after_dispatch(hh_acc)
            finally:
                f.close()
            guard.flush(hh_acc)
            bf, bbin, bgain = _best_level_splits(
                hg_acc, hh_acc, lam, n_leaves, n_feat, max_bins
            )
            width = 1 << level
            start_i = width - 1
            feats_out[t, start_i:start_i + width] = bf[:width]
            bins_out[t, start_i:start_i + width] = bbin[:width]
            gains_out[t, start_i:start_i + width] = bgain[:width]
            prev_split = (jnp.asarray(bf), jnp.asarray(bbin))
        # -- final advance + leaf sums (fused, one replay) -----------------
        lg_acc = lh_acc = None
        f = feed()
        try:
            for start, rows, bb, yb, wb in f:
                weff = shard_state(mask, start, rows)
                lg, lh, new_node = leaf_adv_fn(
                    bb, yb, wb * weff,
                    shard_state(pred, start, rows),
                    shard_state(node, start, rows),
                    *prev_split,
                )
                node[start:start + rows] = mesh.local_rows(new_node)[:rows]
                lg_acc = lg if lg_acc is None else lg_acc + lg
                lh_acc = lh if lh_acc is None else lh_acc + lh
                guard.after_dispatch(lh_acc)
        finally:
            f.close()
        guard.flush(lh_acc)
        lg_np = np.asarray(lg_acc, np.float64)
        lh_np = np.asarray(lh_acc, np.float64)
        leaf = (-lg_np / np.maximum(lh_np + lam, _LAM_FLOOR)).astype(
            np.float32
        )
        leaves_out[t] = leaf
        # Margin update is pure host work: node and pred are already
        # host-resident and leaf is [n_leaves] — no cache replay needed.
        pred += learning_rate * leaf[node]
        if should_snapshot(checkpoint_manager, checkpoint_interval,
                           t + 1, num_trees):
            from flinkml_tpu.iteration.checkpoint import save_agreed

            # Rank-local state (pred): every rank writes its rank-scoped
            # shard; the agreement is the commit barrier. The layout
            # tags make the snapshot reshard-aware: per-row margins are
            # rank-entangled (a world change must refuse or reassemble
            # them via reshard_rank_state), the tree arrays replicate.
            save_agreed(
                checkpoint_manager,
                (pred, feats_out, bins_out, gains_out, leaves_out),
                t + 1, mesh, per_rank=True,
                layouts=("per_rank", "replicated", "replicated",
                         "replicated", "replicated"),
            )
    return feats_out, bins_out, gains_out, leaves_out, base, edges
