"""LDA — latent Dirichlet allocation via batch variational Bayes (the
Spark/Flink family member).

The VB updates (Blei/Hoffman, the sklearn formulation) are pure dense
linear algebra — exactly what the MXU wants:

  - E-step (per document, vectorized over ALL docs at once): iterate
    ``γ = α + expE[log θ] ⊙ ((counts / (expE[log θ]·expE[log β])) ·
    expE[log β]ᵀ)`` — two [n, V]×[V, k] matmuls per inner iteration;
  - M-step: ``λ = η + expE[log β] ⊙ (expE[log θ]ᵀ · (counts / φ))`` —
    one more matmul, with the sufficient statistic ``psum``-combined
    over the document-sharded mesh.

One outer iteration is ONE device program (jitted E-step inner loop +
sstats); the host loop carries the tiny [k, V] topic matrix and stops
on its L1 change. ``transform`` emits the normalized doc-topic mixture;
``describe_topics`` returns each topic's top terms.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasFeaturesCol,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
    HasTol,
)
from flinkml_tpu.linalg import SparseVector
from flinkml_tpu.params import FloatParam, IntParam, ParamValidators, StringParam
from flinkml_tpu.parallel import DeviceMesh, pad_to_multiple
from flinkml_tpu.table import Table

_E_STEPS = 40   # inner E-step iterations per outer pass


class _LDAParams(
    HasFeaturesCol, HasPredictionCol, HasMaxIter, HasTol, HasSeed,
):
    K = IntParam("k", "Number of topics.", 10, ParamValidators.gt(1))
    DOC_CONCENTRATION = FloatParam(
        "docConcentration",
        "Dirichlet prior on doc-topic mixtures (alpha; None = 1/k).", None,
        lambda v: v is None or v > 0,
    )
    TOPIC_CONCENTRATION = FloatParam(
        "topicConcentration",
        "Dirichlet prior on topic-word distributions (eta; None = 1/k).",
        None, lambda v: v is None or v > 0,
    )
    TOPIC_DISTRIBUTION_COL = StringParam(
        "topicDistributionCol", "Output doc-topic mixture column.",
        "topicDistribution",
    )


def _counts_matrix(table: Table, col: str) -> np.ndarray:
    c = table.column(col)
    if c.dtype == object:
        sizes = {v.size() for v in c}
        if len(sizes) != 1:
            raise ValueError(f"TF vectors disagree on vocab size: {sorted(sizes)}")
        out = np.zeros((len(c), sizes.pop()))
        for i, v in enumerate(c):
            if isinstance(v, SparseVector):
                out[i, v.indices] = v.values
            else:
                out[i] = v.to_array()
        return out
    x = np.asarray(c, dtype=np.float64)
    if x.ndim != 2:
        raise ValueError(f"counts column must be [n, V], got {x.shape}")
    return x


def _exp_dirichlet_expectation(a):
    """exp(E[log p]) for rows of a Dirichlet parameter matrix."""
    return jnp.exp(
        jax.scipy.special.digamma(a)
        - jax.scipy.special.digamma(jnp.sum(a, axis=-1, keepdims=True))
    )


@jax.jit
def _gamma_fixed_point(counts, lam, alpha):
    """The vectorized E-step fixed point as ONE device program — shared
    by fit (inside the sharded pass) and transform (single-device)."""
    exp_elog_beta = _exp_dirichlet_expectation(lam)
    k = lam.shape[0]
    gamma0 = jnp.full(
        (counts.shape[0], k),
        alpha + jnp.sum(counts, axis=1, keepdims=True) / k,
    )

    def body(_, gamma):
        exp_elog_theta = _exp_dirichlet_expectation(gamma)
        phi_norm = exp_elog_theta @ exp_elog_beta + 1e-30
        return alpha + exp_elog_theta * (
            (counts / phi_norm) @ exp_elog_beta.T
        )

    return jax.lax.fori_loop(0, _E_STEPS, body, gamma0)


@functools.lru_cache(maxsize=8)
def _vb_pass_fn(mesh, axis: str, k: int):
    """One outer VB pass: full E-step (fixed-point loop) + sstats."""

    def local(counts, rows_w, lam, alpha, key):
        exp_elog_beta = _exp_dirichlet_expectation(lam)       # [k, V]
        n_local = counts.shape[0]
        # Add a zero term from a SHARDED input so the carry is marked
        # varying over the mesh axis (a replicated-key random draw alone
        # is unvarying and shard_map rejects the fori carry).
        gamma0 = (
            jax.random.gamma(key, 100.0, (n_local, k)).astype(jnp.float32)
            * 0.01
            + 0.0 * rows_w[:, None]
        )

        def body(_, gamma):
            exp_elog_theta = _exp_dirichlet_expectation(gamma)
            phi_norm = exp_elog_theta @ exp_elog_beta + 1e-30   # [n, V]
            return alpha + exp_elog_theta * (
                (counts / phi_norm) @ exp_elog_beta.T
            )

        gamma = jax.lax.fori_loop(0, _E_STEPS, body, gamma0)
        exp_elog_theta = _exp_dirichlet_expectation(gamma)
        phi_norm = exp_elog_theta @ exp_elog_beta + 1e-30
        # sstats[k, V] = expElogThetaᵀ · (counts/φ), masked for padding.
        sstats = jax.lax.psum(
            (exp_elog_theta * rows_w[:, None]).T @ (counts / phi_norm),
            axis,
        )
        # Per-token log-likelihood bound proxy for the stop criterion —
        # returned UNNORMALIZED (sum + token count) so streamed callers
        # can combine batch partials before dividing.
        ll = jax.lax.psum(
            jnp.sum(counts * jnp.log(phi_norm) * rows_w[:, None]), axis
        )
        tokens = jax.lax.psum(jnp.sum(counts * rows_w[:, None]), axis)
        return sstats, gamma, ll, tokens

    return jax.jit(
        jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(axis), P(axis), P(), P(), P()),
            out_specs=(P(), P(axis), P(), P()),
        )
    )


class LDA(StreamingEstimatorMixin, _LDAParams, Estimator):
    """``fit`` accepts, besides a single in-RAM :class:`Table`, an
    iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the
    out-of-core path: each outer VB pass replays the cached corpus,
    accumulating the psum'd topic sufficient statistics batch-by-batch
    with bounded HBM residency (reference:
    ``ReplayOperator.java:62-250``). ``checkpoint_manager`` +
    ``checkpoint_interval`` snapshot ``(lambda, prev_ll)`` every N outer
    passes of the streamed fit; ``resume=True`` continues bit-exactly."""


    def fit(self, *inputs) -> "LDAModel":
        (table,) = inputs
        if not isinstance(table, Table):
            return self._fit_stream(table)
        self._reject_in_ram_checkpointing()
        counts = _counts_matrix(table, self.get(self.FEATURES_COL))
        if (counts < 0).any():
            raise ValueError("token counts must be non-negative")
        n, vocab = counts.shape
        k = self.get(self.K)
        alpha = self.get(self.DOC_CONCENTRATION)
        alpha = 1.0 / k if alpha is None else alpha
        eta = self.get(self.TOPIC_CONCENTRATION)
        eta = 1.0 / k if eta is None else eta
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        c_pad, n_valid = pad_to_multiple(counts.astype(np.float32), p)
        rows_w = np.zeros(c_pad.shape[0], np.float32)
        rows_w[:n_valid] = 1.0
        key = jax.random.PRNGKey(self.get_seed())
        lam = np.asarray(
            jax.random.gamma(key, 100.0, (k, vocab)) * 0.01, np.float64
        )
        step = _vb_pass_fn(mesh.mesh, DeviceMesh.DATA_AXIS, k)
        prev_ll = -np.inf
        for it in range(self.get(self.MAX_ITER)):
            sstats, _, ll_sum, tokens = step(
                mesh.shard_batch(c_pad), mesh.shard_batch(rows_w),
                jnp.asarray(lam, jnp.float32),
                jnp.asarray(alpha, jnp.float32),
                jax.random.fold_in(key, it),
            )
            exp_elog_beta = np.asarray(_exp_dirichlet_expectation(
                jnp.asarray(lam, jnp.float32)
            ), np.float64)
            lam = eta + exp_elog_beta * np.asarray(sstats, np.float64)
            ll = float(ll_sum) / max(float(tokens), 1e-30)
            if abs(ll - prev_ll) <= self.get(self.TOL):
                prev_ll = ll
                break
            prev_ll = ll
        model = LDAModel()
        model.copy_params_from(self)
        model._set(lam)
        return model

    def _fit_stream(self, source) -> "LDAModel":
        """Out-of-core VB (see class docstring): pass 0 caches the
        corpus; each outer pass replays it, accumulating sstats / ll /
        token partials per batch. Per-batch E-step gamma inits draw from
        ``fold_in(fold_in(key, it), batch_index)`` so the trajectory is
        deterministic (and independent of the RAM/spill split).

        Multi-process (round 4): each process feeds its own corpus
        partition; the agreed SPMD replay schedule (fixed height +
        zero-weight dummy steps — exact no-ops in the masked sstats/ll/
        token sums), vocab agreement, held-failure rendezvous, bounded
        dispatch, and rank-0-write replicated checkpoints follow the
        GMM streamed pattern (`iteration/stream_sync.py`). The fitted
        topics are identical on every rank; exact equality with a
        single-process run requires the same global device count (the
        per-device gamma init draws device-count-shaped blocks)."""
        from flinkml_tpu.iteration.checkpoint import (
            begin_resume,
            should_snapshot,
        )
        from flinkml_tpu.iteration.datacache import (
            DataCache,
            DataCacheWriter,
            PrefetchingDeviceFeed,
        )
        from flinkml_tpu.iteration.stream_sync import (
            DeferredValidation,
            checked_ingest,
        )

        multi = jax.process_count() > 1
        if self.resume and not isinstance(source, DataCache):
            raise ValueError(
                "resume=True requires a durable DataCache input: a one-shot "
                "stream cannot be replayed from the start after a failure"
            )
        features_col = self.get(self.FEATURES_COL)
        k = self.get(self.K)
        alpha = self.get(self.DOC_CONCENTRATION)
        alpha = 1.0 / k if alpha is None else alpha
        eta = self.get(self.TOPIC_CONCENTRATION)
        eta = 1.0 / k if eta is None else eta
        mesh = self.mesh or DeviceMesh()
        p = mesh.axis_size()
        resume_epoch = begin_resume(
            self.checkpoint_manager, self.resume, mesh.mesh.size
        )
        column = features_col if isinstance(source, DataCache) else "x"

        vocab = [None]

        def to_counts(batch) -> np.ndarray:
            if isinstance(batch, Table):
                c = _counts_matrix(batch, features_col)
            else:
                c = np.asarray(batch[column], np.float64)
            if c.ndim != 2 or c.shape[0] == 0:
                raise ValueError(
                    f"stream batches must be non-empty [n, V], got {c.shape}"
                )
            if (c < 0).any():
                raise ValueError("token counts must be non-negative")
            if vocab[0] is None:
                vocab[0] = c.shape[1]
            elif c.shape[1] != vocab[0]:
                raise ValueError(
                    f"batch vocab size {c.shape[1]} != first batch's "
                    f"{vocab[0]}"
                )
            return c

        dv = DeferredValidation()
        plan = None
        if isinstance(source, DataCache):
            cache = source
            if not multi and cache.num_rows == 0:
                raise ValueError("training stream is empty")
            if multi:
                # Validate EVERY cached batch before the rendezvous (the
                # GMM pattern): a bad batch first seen at replay time
                # would raise rank-locally on the feed thread while the
                # peers sit in the psum collective.
                for _ in checked_ingest(cache.reader(), dv, to_counts,
                                        multi):
                    pass
            elif cache.num_batches:
                reader = cache.reader()
                to_counts(next(iter(reader)))  # vocab from the first batch
                if hasattr(reader, "close"):
                    reader.close()
        else:
            writer = DataCacheWriter(
                self.cache_dir, self.cache_memory_budget_bytes
            )

            def ingest_append(t):
                # Extraction, validation, AND the append are one checked
                # step (see stream_sync.checked_ingest).
                writer.append({column: to_counts(t).astype(np.float32)})

            for _ in checked_ingest(source, dv, ingest_append, multi):
                pass
            cache = writer.finish()
            if not multi and vocab[0] is None:
                raise ValueError("training stream is empty")

        if multi:
            from flinkml_tpu.iteration.stream_sync import (
                SyncedReplayPlan,
                agree_feature_dim,
            )

            # Rendezvous BEFORE planning: a held ingest error must
            # surface as itself, not as plan.create's "stream is empty
            # on every process".
            dv.rendezvous(mesh, "stream ingest validation")
            plan = SyncedReplayPlan.create(cache, mesh, p * 8)
            vocab[0] = agree_feature_dim(
                cache, column, mesh,
                local_dim=0 if vocab[0] is None else vocab[0],
            )
            if vocab[0] == 0:
                raise ValueError("training stream is empty on every process")

        key = jax.random.PRNGKey(self.get_seed())
        if resume_epoch is None:
            lam = np.asarray(
                jax.random.gamma(key, 100.0, (k, vocab[0])) * 0.01,
                np.float64,
            )
        else:
            lam = np.zeros((k, vocab[0]))  # placeholder; restored below
        step = _vb_pass_fn(mesh.mesh, DeviceMesh.DATA_AXIS, k)

        prev_ll = -np.inf
        start_epoch = 0
        terminated = False
        if resume_epoch is not None:
            like = (lam, np.float64(0.0), np.asarray(False))
            from flinkml_tpu.iteration.stream_sync import agreed_restore

            (lam, prev_ll, term), start_epoch = agreed_restore(
                self.checkpoint_manager, resume_epoch, like, mesh
            )
            prev_ll = float(prev_ll)
            terminated = bool(term)

        def place_for(it):
            counter = [0]

            def step_key():
                b = counter[0]
                counter[0] += 1
                return jax.random.fold_in(jax.random.fold_in(key, it), b)

            if multi:
                from flinkml_tpu.iteration.stream_sync import pad_rows_to

                height = plan.local_height

                def place(batch):
                    kb = step_key()
                    if batch is None:  # dummy step on a drained rank
                        # Zero rows_w masks the gamma draw, sstats, ll,
                        # and token sums — an exact no-op step.
                        return (
                            mesh.global_batch(
                                np.zeros((height, vocab[0]), np.float32)
                            ),
                            mesh.global_batch(np.zeros(height, np.float32)),
                            kb,
                        )
                    c = to_counts(batch).astype(np.float32)
                    c_pad = pad_rows_to(c, height)
                    rows_w = pad_rows_to(
                        np.ones(c.shape[0], np.float32), height
                    )
                    return (
                        mesh.global_batch(c_pad),
                        mesh.global_batch(rows_w),
                        kb,
                    )

                return place

            def place(batch):
                c = to_counts(batch).astype(np.float32)
                # 8p row tile bounds the set of padded shapes -> compiles
                # (same bucketing as the linear stream path).
                c_pad, n_valid = pad_to_multiple(c, p * 8)
                rows_w = np.zeros(c_pad.shape[0], np.float32)
                rows_w[:n_valid] = 1.0
                return mesh.shard_batch(c_pad), mesh.shard_batch(rows_w), step_key()

            return place

        from flinkml_tpu.parallel.dispatch import DispatchGuard

        guard = DispatchGuard()  # multi-process backpressure (no-op single)
        max_iter = self.get(self.MAX_ITER)
        for it in range(start_epoch, max_iter):
            if terminated:
                break  # restored from a tol-terminated run: no-op resume
            lam_dev = jnp.asarray(lam, jnp.float32)
            alpha_dev = jnp.asarray(alpha, jnp.float32)
            sstats = ll_sum = tok_sum = None
            src = (
                plan.epoch_batches(cache.reader(), lambda: None)
                if multi else cache.reader()
            )
            feed = PrefetchingDeviceFeed(src, place=place_for(it), depth=2)
            try:
                for cb, wb, kb in feed:
                    s, _, ll_b, tok_b = step(cb, wb, lam_dev, alpha_dev, kb)
                    sstats = s if sstats is None else sstats + s
                    ll_sum = ll_b if ll_sum is None else ll_sum + ll_b
                    tok_sum = tok_b if tok_sum is None else tok_sum + tok_b
                    guard.after_dispatch(tok_sum)
            finally:
                feed.close()
            guard.flush(tok_sum)
            exp_elog_beta = np.asarray(
                _exp_dirichlet_expectation(lam_dev), np.float64
            )
            lam = eta + exp_elog_beta * np.asarray(sstats, np.float64)
            ll = float(ll_sum) / max(float(tok_sum), 1e-30)
            terminated = abs(ll - prev_ll) <= self.get(self.TOL)
            prev_ll = ll
            mgr = self.checkpoint_manager
            if should_snapshot(mgr, self.checkpoint_interval, it + 1,
                               max_iter, terminal=terminated):
                state = (lam, np.float64(prev_ll), np.asarray(terminated))
                if multi:
                    from flinkml_tpu.iteration.checkpoint import (
                        save_replicated,
                    )

                    save_replicated(mgr, state, it + 1, mesh)
                else:
                    mgr.save(state, it + 1)
            if terminated:
                break

        model = LDAModel()
        model.copy_params_from(self)
        model._set(lam)
        return model


class LDAModel(_LDAParams, Model):
    def __init__(self):
        super().__init__()
        self._lambda: Optional[np.ndarray] = None

    def _set(self, lam: np.ndarray) -> None:
        self._lambda = np.asarray(lam, np.float64)

    @property
    def topics_matrix(self) -> np.ndarray:
        """[k, V] topic-word distributions (rows sum to 1)."""
        self._require()
        return self._lambda / self._lambda.sum(axis=1, keepdims=True)

    def describe_topics(self, max_terms: int = 10) -> Table:
        """Per topic: top term indices and their weights."""
        self._require()
        tm = self.topics_matrix
        order = np.argsort(-tm, axis=1)[:, :max_terms]
        weights = np.take_along_axis(tm, order, axis=1)
        return Table({
            "topic": np.arange(tm.shape[0]),
            "termIndices": order,
            "termWeights": weights,
        })

    def set_model_data(self, *inputs: Table) -> "LDAModel":
        (table,) = inputs
        self._set(np.asarray(table.column("lambda"), np.float64)[0])
        return self

    def get_model_data(self) -> List[Table]:
        self._require()
        return [Table({"lambda": self._lambda[None, :, :]})]

    def _require(self) -> None:
        if self._lambda is None:
            raise ValueError("Model data is not set; fit or set_model_data first")

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require()
        counts = _counts_matrix(table, self.get(self.FEATURES_COL))
        if counts.shape[1] != self._lambda.shape[1]:
            raise ValueError(
                f"vocab size {counts.shape[1]} != model's "
                f"{self._lambda.shape[1]}"
            )
        k = self._lambda.shape[0]
        alpha = self.get(self.DOC_CONCENTRATION)
        alpha = 1.0 / k if alpha is None else alpha
        gamma = np.asarray(_gamma_fixed_point(
            jnp.asarray(counts, jnp.float32),
            jnp.asarray(self._lambda, jnp.float32),
            jnp.asarray(alpha, jnp.float32),
        ), np.float64)
        theta = gamma / gamma.sum(axis=1, keepdims=True)
        out = table.with_column(
            self.get(self.TOPIC_DISTRIBUTION_COL), theta
        )
        out = out.with_column(
            self.get(self.PREDICTION_COL),
            np.argmax(theta, axis=1).astype(np.float64),
        )
        return (out,)

    def save(self, path: str) -> None:
        self._require()
        self._save_with_arrays(path, {"lambda": self._lambda})

    @classmethod
    def load(cls, path: str) -> "LDAModel":
        model, arrays, _ = cls._load_with_arrays(path)
        model._set(arrays["lambda"])
        return model
