"""LinearSVC — linear support vector classifier via proximal SGD.

Capability target: BASELINE.json config #3 ("LinearSVC + LinearRegression
with L1/L2 — proximal SGD step on TPU"). The reference snapshot does not
ship LinearSVC (flink-ml 2.1's library is 5 algorithms, SURVEY.md §2.3);
the API mirrors how the reference's later versions shape it (params:
featuresCol/labelCol/weightCol/maxIter/reg/elasticNet/learningRate/
globalBatchSize/tol/seed; predict: label = 1[dot >= threshold], raw = dot).

Training shares ``flinkml_tpu.models._linear_sgd`` with LogisticRegression:
hinge margin gradient, L2 in the gradient, L1 via proximal soft-threshold —
the whole loop one XLA program on device.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flinkml_tpu.api import Estimator, Model
from flinkml_tpu.models._streaming import StreamingEstimatorMixin
from flinkml_tpu.common_params import (
    HasElasticNet,
    HasFeaturesCol,
    HasGlobalBatchSize,
    HasLabelCol,
    HasLearningRate,
    HasMaxIter,
    HasPredictionCol,
    HasRawPredictionCol,
    HasReg,
    HasSeed,
    HasTol,
    HasWeightCol,
)
from flinkml_tpu.models import _linear_sgd
from flinkml_tpu.models._coefficient import CoefficientModelMixin
from flinkml_tpu.models._data import (
    check_binary_labels,
    features_matrix,
    sparse_features,
)
from flinkml_tpu.params import FloatParam
from flinkml_tpu.parallel import DeviceMesh
from flinkml_tpu.table import Table


class _LinearSVCParams(
    HasFeaturesCol,
    HasLabelCol,
    HasWeightCol,
    HasMaxIter,
    HasReg,
    HasElasticNet,
    HasLearningRate,
    HasGlobalBatchSize,
    HasTol,
    HasSeed,
    HasPredictionCol,
    HasRawPredictionCol,
):
    THRESHOLD = FloatParam(
        "threshold", "Decision threshold on the raw prediction.", 0.0
    )


class LinearSVC(StreamingEstimatorMixin, _LinearSVCParams, Estimator):
    """``fit`` also accepts an iterable of batch Tables or a sealed
    :class:`~flinkml_tpu.iteration.datacache.DataCache` — the streamed
    out-of-core path (hinge loss through the shared linear stream
    trainer; ``ReplayOperator.java:62-250`` parity), checkpointable via
    ``checkpoint_manager``/``checkpoint_interval``/``resume``."""

    _SHARDING_PLAN_AWARE = True  # dense path threads a ShardingPlan
    _PRECISION_AWARE = True  # ... and the FML6xx-gated precision policy

    def _make_model(self, coef) -> "LinearSVCModel":
        model = LinearSVCModel()
        model.copy_params_from(self)
        model.set_model_data(Table({"coefficient": coef[None, :]}))
        return model

    def fit(self, *inputs) -> "LinearSVCModel":
        (table,) = inputs
        features_col = self.get(_LinearSVCParams.FEATURES_COL)
        if not isinstance(table, Table):
            if self.sharding_plan is not None:
                raise ValueError(
                    "sharding_plan supports in-RAM Table fits only; "
                    "streamed fits keep their replicated carry"
                )
            if self.precision is not None:
                raise ValueError(
                    "precision supports in-RAM Table fits only; the "
                    "streamed trainer is not yet policy-gated"
                )
            coef = _linear_sgd.streamed_linear_fit(
                table,
                features_col=features_col,
                label_col=self.get(_LinearSVCParams.LABEL_COL),
                weight_col=self.get(_LinearSVCParams.WEIGHT_COL),
                label_check=lambda y: check_binary_labels(y, "LinearSVC"),
                loss="hinge",
                mesh=self.mesh or DeviceMesh(),
                max_iter=self.get(_LinearSVCParams.MAX_ITER),
                learning_rate=self.get(_LinearSVCParams.LEARNING_RATE),
                reg=self.get(_LinearSVCParams.REG),
                elastic_net=self.get(_LinearSVCParams.ELASTIC_NET),
                tol=self.get(_LinearSVCParams.TOL),
                cache_dir=self.cache_dir,
                memory_budget_bytes=self.cache_memory_budget_bytes,
                **self._checkpoint_kwargs(),
            )
            return self._make_model(coef)
        hyper = dict(
            **self._checkpoint_kwargs(),
            loss="hinge",
            mesh=self.mesh or DeviceMesh(),
            max_iter=self.get(_LinearSVCParams.MAX_ITER),
            learning_rate=self.get(_LinearSVCParams.LEARNING_RATE),
            global_batch_size=self.get(_LinearSVCParams.GLOBAL_BATCH_SIZE),
            reg=self.get(_LinearSVCParams.REG),
            elastic_net=self.get(_LinearSVCParams.ELASTIC_NET),
            tol=self.get(_LinearSVCParams.TOL),
            seed=self.get_seed(),
        )
        coef = _linear_sgd.train_linear_model_from_table(
            table, features_col,
            self.get(_LinearSVCParams.LABEL_COL),
            self.get(_LinearSVCParams.WEIGHT_COL),
            label_check=lambda y: check_binary_labels(y, "LinearSVC"),
            sharding_plan=self.sharding_plan,
            precision=self.precision,
            **hyper,
        )
        return self._make_model(coef)


class LinearSVCModel(CoefficientModelMixin, _LinearSVCParams, Model):
    def __init__(self):
        super().__init__()
        self._coefficient: Optional[np.ndarray] = None

    def transform(self, *inputs: Table) -> Tuple[Table, ...]:
        (table,) = inputs
        self._require_model()
        features_col = self.get(_LinearSVCParams.FEATURES_COL)
        sparse_col = sparse_features(table, features_col)
        if sparse_col is not None:
            from flinkml_tpu.ops.sparse import sparse_margins

            dot = sparse_margins(sparse_col, self._coefficient).astype(
                np.float64
            )
        else:
            x = features_matrix(table, features_col)
            dot = np.asarray(jnp.asarray(x) @ jnp.asarray(self._coefficient))
        threshold = self.get(_LinearSVCParams.THRESHOLD)
        pred = (dot >= threshold).astype(np.float64)
        out = table.with_column(
            self.get(_LinearSVCParams.PREDICTION_COL), pred
        ).with_column(self.get(_LinearSVCParams.RAW_PREDICTION_COL), dot)
        return (out,)
